#!/bin/sh
# Port-forward Prometheus to localhost:9090.
kubectl -n monitoring port-forward svc/prometheus-k8s 9090:9090
