#!/bin/sh
# Port-forward the dashboard to localhost:8080.
kubectl -n foremast port-forward svc/foremast-ui 8080:8080
