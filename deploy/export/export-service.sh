#!/bin/sh
# Port-forward the job gateway to localhost:8099.
kubectl -n foremast port-forward svc/foremast-service 8099:8099
