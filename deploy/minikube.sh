#!/bin/sh
# Local demo cluster (reference deploy/minikube.sh footprint: 4 CPU / 6 GB).
minikube start --cpus 4 --memory 6144
minikube addons enable ingress
