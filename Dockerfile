# foremast-tpu runtime image (the IMAGE the deploy/ manifests reference).
#
# One image serves every role — the container args select it:
#   foremast serve | worker | watch-plane | ui    (see deploy/foremast/)
#   python -m foremast_tpu.demo                    (examples/demo/)
#
# The TPU engine pods additionally need the TPU-enabled jax wheel for the
# target accelerator; swap the base/pip line per your fleet (e.g.
# `pip install 'jax[tpu]' -f https://storage.googleapis.com/jax-releases/libtpu_releases.html`).

FROM python:3.12-slim

# native toolchain for the C++ data loader (built at image build time so
# worker startup never compiles)
RUN apt-get update && apt-get install -y --no-install-recommends \
        g++ make && \
    rm -rf /var/lib/apt/lists/*

WORKDIR /app
COPY pyproject.toml README.md ./
COPY foremast_tpu ./foremast_tpu
COPY native ./native
COPY bin ./bin
COPY tests/data ./tests/data

RUN pip install --no-cache-dir . && \
    make -C native && \
    ln -s /app/bin/kubectl-watch /usr/local/bin/kubectl-watch && \
    ln -s /app/bin/kubectl-unwatch /usr/local/bin/kubectl-unwatch

# service :8099, ui :8080, gauges :8000
EXPOSE 8099 8080 8000

ENTRYPOINT ["foremast"]
CMD ["serve"]
