"""Watch-plane loop tests: list+diff informer semantics + scheduler step."""

from foremast_tpu.watch.kubeapi import InMemoryKube
from foremast_tpu.watch.plane import (
    DEPLOY_RESYNC_SECONDS,
    DeploymentInformer,
    WatchPlane,
)


def _dep(ns, name, image="app:v1", rv="1", labels=None):
    return {
        "metadata": {
            "namespace": ns,
            "name": name,
            "resourceVersion": rv,
            "labels": labels if labels is not None else {"app": name},
            "uid": f"uid-{ns}-{name}",
        },
        "spec": {
            "template": {"spec": {"containers": [{"name": "c", "image": image}]}}
        },
    }


def test_informer_emits_add_update_delete():
    kube = InMemoryKube()
    events = []
    inf = DeploymentInformer(kube, lambda e, d, old: events.append((e, d, old)))

    kube.deployments[("ns", "a")] = _dep("ns", "a")
    inf.resync()
    assert [e for e, *_ in events] == ["add"]

    # unchanged resourceVersion -> no event
    inf.resync()
    assert len(events) == 1

    # image change bumps resourceVersion -> update with the old object
    kube.deployments[("ns", "a")] = _dep("ns", "a", image="app:v2", rv="2")
    inf.resync()
    assert events[-1][0] == "update"
    assert events[-1][2]["metadata"]["resourceVersion"] == "1"

    del kube.deployments[("ns", "a")]
    inf.resync()
    assert events[-1][0] == "delete"


def test_informer_handler_errors_do_not_stop_resync():
    kube = InMemoryKube()
    kube.deployments[("ns", "a")] = _dep("ns", "a")
    kube.deployments[("ns", "b")] = _dep("ns", "b")
    seen = []

    def handler(e, d, old):
        seen.append(d["metadata"]["name"])
        raise RuntimeError("boom")

    DeploymentInformer(kube, handler).resync()
    assert sorted(seen) == ["a", "b"]


def test_watchplane_step_resync_schedule():
    kube = InMemoryKube()
    now = [1000.0]
    plane = WatchPlane(kube, clock=lambda: now[0], sleep=lambda s: None)
    resyncs = []
    plane.informer.resync = lambda: resyncs.append(now[0])  # type: ignore[method-assign]

    last = plane.step(last_resync=0.0)
    assert resyncs == [1000.0] and last == 1000.0
    # within the resync period: monitor tick only
    now[0] += 10
    assert plane.step(last_resync=last) == last
    assert len(resyncs) == 1
    # past the period: resync again
    now[0] += DEPLOY_RESYNC_SECONDS
    last2 = plane.step(last_resync=last)
    assert len(resyncs) == 2 and last2 == now[0]


def test_watchplane_creates_monitor_for_existing_deployment():
    """First resync primes with add events -> Barrelman ensures a monitor
    CR exists for every labeled Deployment (AddFunc semantics)."""
    kube = InMemoryKube()
    kube.deployments[("prod", "shop")] = _dep("prod", "shop")
    plane = WatchPlane(kube, clock=lambda: 0.0, sleep=lambda s: None)
    plane.step(last_resync=0.0)
    assert ("prod", "shop") in kube.monitors


def test_watchplane_debug_state():
    """The controller's /debug/state payload (served by watch-plane's
    scrape port) carries identity, informer size, and tracer state."""
    from prometheus_client import CollectorRegistry

    from foremast_tpu.observe.spans import Tracer

    kube = InMemoryKube()
    kube.deployments[("prod", "shop")] = _dep("prod", "shop")
    now = [100.0]
    reg = CollectorRegistry()
    plane = WatchPlane(
        kube,
        clock=lambda: now[0],
        sleep=lambda s: None,
        tracer=Tracer(service="controller", registry=reg),
        registry=reg,
    )
    now[0] += 7
    plane.step(last_resync=0.0)
    state = plane.debug_state()
    assert state["component"] == "controller" and state["version"]
    assert state["uptime_seconds"] == 7.0
    assert state["deployments_cached"] == 1
    assert "trace" in state
