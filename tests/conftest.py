"""Test configuration: force an 8-device virtual CPU mesh BEFORE jax import.

Multi-chip hardware is unavailable in CI; sharding correctness is validated
on `--xla_force_host_platform_device_count=8` CPU devices standing in for a
v5e-8 (SURVEY.md section 4 implication). Benchmarks (bench.py) run on the
real chip and do NOT import this file.
"""

import os

# Hard override: the image's sitecustomize registers the `axon` TPU-tunnel
# backend and exports JAX_PLATFORMS=axon; tests must never dial the tunnel
# (single real chip, and CI has none), so force the CPU backend outright.
os.environ["JAX_PLATFORMS"] = "cpu"
# XLA's own variable, not a foremast knob — the registry enumerates
# OUR config surface, not the toolchain's
_flags = os.environ.get("XLA_FLAGS", "")  # foremast: ignore[env-contract]
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# The sitecustomize already imported jax and called axon's register(), which
# programmatically forces jax_platforms="axon,cpu" (overriding the env var).
# Re-override the config BEFORE any backend initialization so tests never
# dial the TPU tunnel.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np
import pytest


@pytest.fixture(scope="session")
def demo_traces():
    """The reference demo's golden canary traces as (times, values) arrays.

    data1: normal trace (~0.1-0.6); data2: same shape of traffic with
    injected 40.134 / 40.466 spikes (reference
    `examples/spring-boot-demo/src/main/resources/data{1,2}.txt`,
    replayed by `FileErrorGenerator.java:27-37`).
    """
    here = os.path.dirname(__file__)
    from datetime import datetime, timezone

    def load(name):
        ts, vs = [], []
        with open(os.path.join(here, "data", name)) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                t, v = line.split(",")
                dt = datetime.strptime(t, "%Y-%m-%d %H:%M:%S").replace(
                    tzinfo=timezone.utc
                )
                ts.append(int(dt.timestamp()))
                vs.append(float(v))
        return np.asarray(ts, dtype=np.int64), np.asarray(vs, dtype=np.float32)

    return {"normal": load("demo_canary_normal.csv"), "spike": load("demo_canary_spike.csv")}
