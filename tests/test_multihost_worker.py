"""The sharded worker AS A WORKER (VERDICT r4 #1): full
claim -> fetch -> judge -> write ticks executed across real process
boundaries, in both deployment modes the operations guide documents:

  * POD MODE — one logical worker spanning a 2-process jax.distributed
    cluster: process 0 claims from the store and fetches metrics, the
    claim set / series / clock are broadcast, the judgment runs SPMD
    through ShardedJudge over the global 8-device mesh (with the state
    arena REPLICATED over it — the deliberate placement decision), and
    only the leader persists verdicts.
  * SHARED-NOTHING MODE — the reference's scaling model
    (`docs/guides/design.md:35-43`): two independent worker processes,
    each sharding its judgment over its own local mesh, contending for
    the same documents through a REAL HTTP Elasticsearch wire (the fake
    ES cluster served over a socket), with CAS claims guaranteeing no
    double-scoring.

Both assert verdict parity with a plain single-process worker on the
identical (seeded) fleet.
"""

from __future__ import annotations

import json
import os
import socket
import subprocess
import sys
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
NOW = 1_760_000_000.0
SERVICES = 8
HIST_LEN = 256
CUR_LEN = 30


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _spike(source):
    """Push app3's latency current window far outside the band —
    identical mutation applied by every process / the reference run."""
    url = next(
        u for u in source.data if "cur" in u and "latency:app3" in u
    )
    ct, cv = source.data[url]
    spiked = cv.copy()
    spiked[-3:] = 40.0
    source.data[url] = (ct, spiked)


def _reference_statuses(now2: float):
    """Single-process ground truth on the identical seeded fleet."""
    from benchmarks.worker_bench import build_fleet
    from foremast_tpu.config import BrainConfig
    from foremast_tpu.jobs.worker import BrainWorker

    store, source = build_fleet(SERVICES, HIST_LEN, CUR_LEN, NOW)
    cfg = BrainConfig(algorithm="moving_average_all")
    w = BrainWorker(
        store, source, config=cfg, claim_limit=SERVICES, worker_id="ref"
    )
    assert w.tick(now=NOW + 150) == SERVICES
    _spike(source)
    assert w.tick(now=now2) == SERVICES
    return {
        d.id: (d.status, json.dumps(d.anomaly_info, sort_keys=True))
        for d in store._docs.values()
    }


# ---------------------------------------------------------------------------
# POD MODE
# ---------------------------------------------------------------------------

_POD_CHILD = """
import os, sys, json
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
import jax
jax.config.update("jax_platforms", "cpu")
try:  # CPU multi-process collectives (older jax needs explicit gloo)
    jax.config.update("jax_cpu_collectives_implementation", "gloo")
except Exception:
    pass
addr, pid = sys.argv[1], int(sys.argv[2])
jax.distributed.initialize(addr, 2, pid)

sys.path.insert(0, {repo!r})
from benchmarks.worker_bench import build_fleet
from foremast_tpu.config import BrainConfig
from foremast_tpu.engine.multivariate import MultivariateJudge
from foremast_tpu.parallel import (
    LeaderSource, LeaderStore, PodWorker, ShardedJudge, make_global_mesh,
)

NOW = {now!r}
leader = pid == 0
if leader:
    store_in, source_in = build_fleet({services}, {hist_len}, {cur_len}, NOW)
else:
    store_in = source_in = None
store = LeaderStore(store_in)
source = LeaderSource(source_in)
cfg = BrainConfig(algorithm="moving_average_all")
sharded = ShardedJudge(cfg, mesh=make_global_mesh())
judge = MultivariateJudge(cfg, univariate=sharded)
worker = PodWorker(
    store, source, config=cfg, judge=judge,
    claim_limit={services}, worker_id=f"pod-{{pid}}",
)
assert worker.tick(now=NOW + 150) == {services}
if leader:
    # identical spike on the leader's source; followers see it via the
    # broadcast fetch
    url = next(u for u in source_in.data
               if "cur" in u and "latency:app3" in u)
    ct, cv = source_in.data[url]
    cv = cv.copy(); cv[-3:] = 40.0
    source_in.data[url] = (ct, cv)
assert worker.tick(now=NOW + 200) == {services}
# the warm tick must have taken the columnar fast path SPMD: the
# univariate judge's arena lives replicated over the global mesh
counters = sharded.device_state_counters()
assert counters["hits"] > 0, counters
(arena,) = sharded._arenas.values()
ns = arena.state[0].sharding
assert len(ns.device_set) == 8, ns  # replicated over ALL devices
if leader:
    statuses = {{
        d.id: (d.status, json.dumps(d.anomaly_info, sort_keys=True))
        for d in store_in._docs.values()
    }}
    print("STATUSES " + json.dumps(statuses, sort_keys=True), flush=True)
print(f"proc {{pid}} ok", flush=True)
"""


# gloo's TCP transport occasionally corrupts a frame header on loaded
# single-CPU CI hosts and dies with this invariant — an environment
# flake inside the collective library, not a worker bug
_GLOO_FLAKE = "op.preamble.length"


def _launch_pod_children(child) -> tuple[list, list[str]]:
    addr = f"127.0.0.1:{_free_port()}"
    env = {k: v for k, v in os.environ.items() if not k.startswith("JAX_")}
    procs = [
        subprocess.Popen(
            [sys.executable, str(child), addr, str(pid)],
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
            env=env,
        )
        for pid in (0, 1)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=240)
            outs.append(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    return procs, outs


def test_pod_mode_two_process_worker_tick(tmp_path):
    """2-process jax.distributed cluster running FULL worker ticks SPMD;
    leader statuses must equal the single-process reference bit for bit.

    Retries once on gloo's `op.preamble.length` TCP frame flake (a new
    cluster on a fresh port), then skips with the flake named — every
    other failure still fails loudly."""
    child = tmp_path / "pod_child.py"
    child.write_text(
        _POD_CHILD.format(
            repo=REPO,
            now=NOW,
            services=SERVICES,
            hist_len=HIST_LEN,
            cur_len=CUR_LEN,
        )
    )
    procs, outs = _launch_pod_children(child)
    if any(p.returncode != 0 for p in procs) and any(
        _GLOO_FLAKE in out for out in outs
    ):
        procs, outs = _launch_pod_children(child)
        if any(p.returncode != 0 for p in procs) and any(
            _GLOO_FLAKE in out for out in outs
        ):
            pytest.skip(
                "gloo TCP transport flake (op.preamble.length) twice in "
                "a row — collective-library environment issue, not a "
                "worker regression"
            )
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"proc {pid} failed:\n{out}"
        assert f"proc {pid} ok" in out
    got = json.loads(
        next(
            line for line in outs[0].splitlines()
            if line.startswith("STATUSES ")
        )[len("STATUSES "):]
    )
    want = {k: list(v) for k, v in _reference_statuses(NOW + 200).items()}
    assert got == want
    # one doc unhealthy with anomaly pairs, the rest re-checking
    assert got["job-3"][0] == "completed_unhealth"


# ---------------------------------------------------------------------------
# SHARED-NOTHING MODE (real HTTP ES wire)
# ---------------------------------------------------------------------------


def _serve_fake_es():
    """The in-repo fake ES cluster behind a REAL HTTP socket."""
    from test_es_store import FakeES

    fake = FakeES()
    lock = threading.Lock()

    class Handler(BaseHTTPRequestHandler):
        def log_message(self, *a):  # quiet
            pass

        def _dispatch(self, method):
            n = int(self.headers.get("Content-Length") or 0)
            raw = self.rfile.read(n) if n else b""
            body = data = None
            if raw:
                if "x-ndjson" in (self.headers.get("Content-Type") or ""):
                    data = raw.decode()
                else:
                    body = json.loads(raw)
            with lock:
                if method == "GET":
                    resp = fake.get(self.path)
                elif method == "PUT":
                    resp = fake.put(self.path, json=body)
                else:
                    resp = fake.post(
                        self.path, json=body, data=data,
                        headers=dict(self.headers),
                    )
            payload = json.dumps(resp.json()).encode()
            self.send_response(resp.status_code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(payload)))
            self.end_headers()
            self.wfile.write(payload)

        def do_GET(self):
            self._dispatch("GET")

        def do_PUT(self):
            self._dispatch("PUT")

        def do_POST(self):
            self._dispatch("POST")

    srv = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    return srv, fake


_SN_CHILD = """
import os, sys, json, time
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
import jax
jax.config.update("jax_platforms", "cpu")
# NO gloo collectives here: shared-nothing workers run WITHOUT
# jax.distributed, and the gloo CPU client requires a distributed
# runtime handle (it is only configured in the pod-mode children)
sys.path.insert(0, {repo!r})
url, wid, sync = sys.argv[1], sys.argv[2], sys.argv[3]

from benchmarks.worker_bench import build_fleet
from foremast_tpu.config import BrainConfig
from foremast_tpu.engine.multivariate import MultivariateJudge
from foremast_tpu.jobs.store import ElasticsearchStore
from foremast_tpu.jobs.worker import BrainWorker
from foremast_tpu.parallel import ShardedJudge, make_mesh

NOW = {now!r}
# same seed => identical series; docs live ONLY in the shared ES
_, source = build_fleet({services}, {hist_len}, {cur_len}, NOW)
url_spike = next(u for u in source.data
                 if "cur" in u and "latency:app3" in u)
ct, cv = source.data[url_spike]
cv = cv.copy(); cv[-3:] = 40.0
source.data[url_spike] = (ct, cv)

store = ElasticsearchStore(url)
cfg = BrainConfig(algorithm="moving_average_all")
judge = MultivariateJudge(cfg, univariate=ShardedJudge(cfg, mesh=make_mesh()))
worker = BrainWorker(
    store, source, config=cfg, judge=judge,
    claim_limit={services} // 2, worker_id=wid,
)
# past endTime: every doc finalizes on its first judgment, so each is
# scored EXACTLY once across both workers (double-claiming would
# inflate the processed total)

def barrier(tag):
    # lockstep rounds: process startup/compile skew must not let one
    # worker drain the whole fleet before the other's first claim —
    # the point is CONCURRENT claim contention
    open(os.path.join(sync, wid + "." + tag), "w").close()
    want = {{"worker-a." + tag, "worker-b." + tag}}
    while not want <= set(os.listdir(sync)):
        time.sleep(0.02)

total = 0
for r in range(6):
    barrier(f"r{{r}}")
    total += worker.tick(now=NOW + 7200)
print(f"PROCESSED {{wid}} {{total}}", flush=True)
"""


def test_shared_nothing_two_workers_real_http_es(tmp_path):
    """Two independent worker PROCESSES against one fake-ES cluster over
    real HTTP: CAS claims must partition the fleet (no double-scoring),
    and final statuses must match the single-process reference."""
    from benchmarks.worker_bench import build_fleet
    from foremast_tpu.config import BrainConfig
    from foremast_tpu.jobs.worker import BrainWorker

    srv, fake = _serve_fake_es()
    try:
        url = f"http://127.0.0.1:{srv.server_address[1]}"
        # the parent owns document creation (the service's role)
        from foremast_tpu.jobs.store import ElasticsearchStore

        parent_store = ElasticsearchStore(url)
        parent_store.ensure_index()
        fleet_store, _ = build_fleet(SERVICES, HIST_LEN, CUR_LEN, NOW)
        for doc in fleet_store._docs.values():
            parent_store.create(doc)

        child = tmp_path / "sn_child.py"
        child.write_text(
            _SN_CHILD.format(
                repo=REPO,
                now=NOW,
                services=SERVICES,
                hist_len=HIST_LEN,
                cur_len=CUR_LEN,
            )
        )
        env = {
            k: v for k, v in os.environ.items() if not k.startswith("JAX_")
        }
        env["PYTHONPATH"] = os.path.dirname(os.path.abspath(__file__))
        sync = tmp_path / "sync"
        sync.mkdir()
        procs = [
            subprocess.Popen(
                [sys.executable, str(child), url, wid, str(sync)],
                stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT,
                text=True,
                env=env,
            )
            for wid in ("worker-a", "worker-b")
        ]
        outs = []
        try:
            for p in procs:
                out, _ = p.communicate(timeout=240)
                outs.append(out)
        finally:
            for p in procs:
                if p.poll() is None:
                    p.kill()
        totals = {}
        for (p, out), wid in zip(zip(procs, outs), ("worker-a", "worker-b")):
            assert p.returncode == 0, f"{wid} failed:\n{out}"
            for line in out.splitlines():
                if line.startswith("PROCESSED "):
                    _, w, n = line.split()
                    totals[w] = int(n)
        # every doc scored exactly once across the two workers
        assert sum(totals.values()) == SERVICES, totals

        # single-process reference on the identical fleet, same clock
        ref_store, ref_source = build_fleet(SERVICES, HIST_LEN, CUR_LEN, NOW)
        _spike(ref_source)
        ref_worker = BrainWorker(
            ref_store,
            ref_source,
            config=BrainConfig(algorithm="moving_average_all"),
            claim_limit=SERVICES,
            worker_id="ref",
        )
        assert ref_worker.tick(now=NOW + 7200) == SERVICES
        want = {
            d.id: (d.status, json.dumps(d.anomaly_info, sort_keys=True))
            for d in ref_store._docs.values()
        }
        claimers = set()
        for doc_id, (status, anom) in want.items():
            rec = fake.docs[doc_id]["_source"]
            assert rec["status"] == status, (doc_id, rec["status"], status)
            got_anom = json.dumps(
                rec.get("anomalyInfo") or rec.get("anomaly_info"),
                sort_keys=True,
            )
            if status == "completed_unhealth":
                assert got_anom == anom, doc_id
            claimers.add(rec["processingContent"])
        assert want["job-3"][0] == "completed_unhealth"
        # both workers actually participated (claim_limit forces a split)
        assert claimers == {"worker-a", "worker-b"}, claimers
    finally:
        srv.shutdown()
