"""scipy golden-value tests for the batched masked rank statistics."""

import numpy as np
import pytest
import scipy.stats as sps

import jax.numpy as jnp

from foremast_tpu.ops import (
    kruskal_wallis,
    mann_whitney_u,
    masked_ranks,
    wilcoxon_signed_rank,
)


def _pad(arr, n):
    v = np.zeros(n, dtype=np.float32)
    m = np.zeros(n, dtype=bool)
    v[: len(arr)] = arr
    m[: len(arr)] = True
    return v, m


def _batch(pairs, n=48):
    xs, xms, ys, yms = [], [], [], []
    for x, y in pairs:
        xv, xm = _pad(x, n)
        yv, ym = _pad(y, n)
        xs.append(xv)
        xms.append(xm)
        ys.append(yv)
        yms.append(ym)
    return (
        jnp.asarray(np.stack(xs)),
        jnp.asarray(np.stack(xms)),
        jnp.asarray(np.stack(ys)),
        jnp.asarray(np.stack(yms)),
    )


RNG = np.random.default_rng(42)

CASES = [
    (RNG.normal(0, 1, 25).astype(np.float32), RNG.normal(0, 1, 30).astype(np.float32)),
    (RNG.normal(0, 1, 25).astype(np.float32), RNG.normal(2, 1, 25).astype(np.float32)),
    # heavy ties (rounded values)
    (
        np.round(RNG.normal(0, 1, 32)).astype(np.float32),
        np.round(RNG.normal(0.5, 1, 28)).astype(np.float32),
    ),
    (RNG.exponential(1, 40).astype(np.float32), RNG.exponential(3, 22).astype(np.float32)),
]


def test_masked_ranks_match_scipy_rankdata():
    x = np.array([3.0, 1.0, 2.0, 2.0, 5.0, 2.0], dtype=np.float32)
    v, m = _pad(x, 10)
    ranks, tie = masked_ranks(jnp.asarray(v)[None], jnp.asarray(m)[None])
    expected = sps.rankdata(x)
    np.testing.assert_allclose(np.asarray(ranks)[0, : len(x)], expected, rtol=1e-6)
    # tie groups: {2.0: t=3} -> t^3 - t = 24
    assert float(tie[0]) == pytest.approx(24.0)
    # masked tail must be zero-ranked
    assert np.all(np.asarray(ranks)[0, len(x):] == 0.0)


def test_mann_whitney_matches_scipy():
    x, xm, y, ym = _batch(CASES)
    u, p, ok = mann_whitney_u(x, xm, y, ym, min_points=5)
    for i, (cx, cy) in enumerate(CASES):
        ref = sps.mannwhitneyu(cx, cy, method="asymptotic", use_continuity=True)
        assert float(u[i]) == pytest.approx(ref.statistic, rel=1e-5), f"case {i}"
        assert float(p[i]) == pytest.approx(ref.pvalue, rel=1e-4, abs=1e-8), f"case {i}"
        assert bool(ok[i])


def test_wilcoxon_matches_scipy():
    pairs = [
        (RNG.normal(0, 1, 30).astype(np.float32), RNG.normal(0, 1, 30).astype(np.float32)),
        (RNG.normal(0, 1, 26).astype(np.float32), RNG.normal(1, 1, 26).astype(np.float32)),
        # tie-heavy case: quarter increments are binary-exact, so the tie
        # groups of |d| agree between our float32 path and scipy's float64
        (
            (np.round(RNG.normal(0, 2, 36) * 4) / 4).astype(np.float32),
            (np.round(RNG.normal(0.4, 2, 36) * 4) / 4).astype(np.float32),
        ),
    ]
    x, xm, y, ym = _batch(pairs)
    w, p, ok = wilcoxon_signed_rank(x, xm, y, ym, min_points=5)
    for i, (cx, cy) in enumerate(pairs):
        ref = sps.wilcoxon(
            cx.astype(np.float64),
            cy.astype(np.float64),
            zero_method="wilcox",
            correction=False,
            method="approx",
        )
        d = cx - cy
        d = d[d != 0]
        w_plus = np.sum(sps.rankdata(np.abs(d))[d > 0])
        assert float(w[i]) == pytest.approx(w_plus, rel=1e-5), f"case {i}"
        assert float(p[i]) == pytest.approx(ref.pvalue, rel=1e-3, abs=1e-8), f"case {i}"
        assert bool(ok[i])


def test_kruskal_matches_scipy():
    x, xm, y, ym = _batch(CASES)
    h, p, ok = kruskal_wallis(x, xm, y, ym, min_points=5)
    for i, (cx, cy) in enumerate(CASES):
        ref = sps.kruskal(cx, cy)
        assert float(h[i]) == pytest.approx(ref.statistic, rel=1e-4), f"case {i}"
        assert float(p[i]) == pytest.approx(ref.pvalue, rel=1e-3, abs=1e-8), f"case {i}"
        assert bool(ok[i])


def test_min_points_gate_forces_inconclusive():
    x, xm, y, ym = _batch([(np.arange(8, dtype=np.float32), np.arange(8, dtype=np.float32) + 5)])
    _, p, ok = mann_whitney_u(x, xm, y, ym, min_points=20)
    assert not bool(ok[0])
    assert float(p[0]) == 1.0
    _, p, ok = wilcoxon_signed_rank(x, xm, y, ym, min_points=20)
    assert not bool(ok[0])
    assert float(p[0]) == 1.0
    _, p, ok = kruskal_wallis(x, xm, y, ym, min_points=20)
    assert not bool(ok[0])
    assert float(p[0]) == 1.0


def test_golden_trace_pairwise_detects_spike(demo_traces):
    """Baseline(normal) vs current(spike) must register as different
    distributions; normal vs normal must not."""
    _, normal = demo_traces["normal"]
    _, spike = demo_traces["spike"]
    pairs = [(spike, normal), (normal, normal.copy())]
    x, xm, y, ym = _batch(pairs, n=48)
    _, p_mw, ok = mann_whitney_u(x, xm, y, ym, min_points=20)
    assert bool(ok[0]) and bool(ok[1])
    # identical distributions -> p near 1; spike trace is mostly identical
    # traffic so MW (median-ish) may not fire, but identical must pass
    assert float(p_mw[1]) > 0.4


def _friedman_k2_reference(x, y):
    """scipy's friedmanchisquare formula applied at k=2 with scipy
    primitives (the public function refuses k < 3): per-block rankdata,
    tie correction c = 1 - sum(t^3 - t)/(n k (k^2-1)), chi2(k-1) sf."""
    n, k = len(x), 2
    ranks = np.stack([sps.rankdata([xi, yi]) for xi, yi in zip(x, y)])
    ssbn = np.sum(ranks.sum(axis=0) ** 2)
    ties = sum(
        np.sum(np.asarray([(ranks[i] == r).sum() for r in set(ranks[i])]) ** 3
               - np.asarray([(ranks[i] == r).sum() for r in set(ranks[i])]))
        for i in range(n)
    )
    c = 1.0 - ties / (n * k * (k * k - 1))
    stat = (12.0 / (n * k * (k + 1)) * ssbn - 3.0 * n * (k + 1)) / c
    return stat, sps.distributions.chi2.sf(stat, k - 1)


def test_friedman_matches_scipy_formula_at_k2():
    from foremast_tpu.ops import friedman_chi_square

    pairs = [
        (CASES[0][0][:25], CASES[0][1][:25]),  # same distribution
        (CASES[1][0][:25], CASES[1][1][:25]),  # shifted: must reject
        # heavy within-pair ties (rounded)
        (np.round(RNG.normal(0, 1, 30)).astype(np.float32),
         np.round(RNG.normal(0, 1, 30)).astype(np.float32)),
    ]
    x, xm, y, ym = _batch(pairs)
    stat, p, ok = friedman_chi_square(x, xm, y, ym, min_points=20)
    for i, (a, b) in enumerate(pairs):
        want_stat, want_p = _friedman_k2_reference(a, b)
        assert bool(ok[i])
        assert float(stat[i]) == pytest.approx(want_stat, abs=1e-3)
        assert float(p[i]) == pytest.approx(want_p, abs=1e-4)
    # and the no-tie identity: chi2 == (n+ - n-)^2 / n (sign-test form)
    a, b = pairs[1]
    npl = int((a > b).sum()); nmi = int((a < b).sum())
    assert float(stat[1]) == pytest.approx((npl - nmi) ** 2 / (npl + nmi), abs=1e-3)


def test_friedman_gates_and_all_ties():
    from foremast_tpu.ops import friedman_chi_square

    # all pairs tied: c = 0 -> inconclusive, not NaN
    x = np.ones(24, np.float32)
    pairs = [(x, x.copy()), (x[:8], x[:8].copy())]  # second: under min gate
    xv, xm, yv, ym = _batch(pairs)
    stat, p, ok = friedman_chi_square(xv, xm, yv, ym, min_points=20)
    assert not bool(ok[0]) and float(p[0]) == 1.0
    assert not bool(ok[1]) and float(p[1]) == 1.0
    assert np.isfinite(np.asarray(stat)).all()


# -- two-sample kernel vs concat masked_ranks (ISSUE 14 rewrite) ------------


def test_two_sample_rank_stats_matches_concat_ranks():
    """The two-sample kernels' (r1, tie) and the r1+r2 identity are
    BIT-identical to ranking the concatenation with masked_ranks — the
    exactness argument the kernel rewrite rests on (every count is an
    exact small integer; rank sums are multiples of 0.5 far below
    2^23)."""
    import jax.numpy as jnp

    from foremast_tpu.ops.ranks import _two_sample_rank_stats, masked_ranks

    rng = np.random.default_rng(7)
    for trial in range(4):
        b, nx, ny = 64, 17, 23
        if trial == 0:
            x = rng.choice([0.0, 0.25, 0.5, 1.0], (b, nx))
            y = rng.choice([0.0, 0.25, 0.5, 1.0], (b, ny))
        elif trial == 1:
            x = rng.normal(1, 0.1, (b, nx))
            y = rng.normal(1, 0.1, (b, ny))
        elif trial == 2:
            x = np.ones((b, nx))
            y = np.ones((b, ny))  # total cross-sample tie
        else:
            x = rng.normal(1, 0.1, (b, nx))
            y = rng.normal(9, 0.1, (b, ny))  # disjoint supports
        x = x.astype(np.float32)
        y = y.astype(np.float32)
        xm = rng.random((b, nx)) > 0.3
        ym = rng.random((b, ny)) > 0.3
        xm[:3] = False  # all-masked sample rows
        ym[3:6] = False
        ranks, tie_ref = masked_ranks(
            jnp.concatenate([jnp.asarray(x), jnp.asarray(y)], axis=-1),
            jnp.concatenate([jnp.asarray(xm), jnp.asarray(ym)], axis=-1),
        )
        r1_ref = np.asarray(jnp.sum(ranks[..., :nx] * xm, axis=-1))
        r2_ref = np.asarray(jnp.sum(ranks[..., nx:] * ym, axis=-1))
        r1, tie, n_x, n_y = _two_sample_rank_stats(
            jnp.asarray(x), jnp.asarray(xm), jnp.asarray(y), jnp.asarray(ym)
        )
        n = np.asarray(n_x) + np.asarray(n_y)
        np.testing.assert_array_equal(np.asarray(r1), r1_ref)
        np.testing.assert_array_equal(np.asarray(tie), np.asarray(tie_ref))
        np.testing.assert_array_equal(
            n * (n + 1.0) * 0.5 - np.asarray(r1), r2_ref
        )
