"""Service REST facade tests: wire parity + full create->score->poll loop."""

import asyncio

import numpy as np
import pytest
from aiohttp.test_utils import TestClient, TestServer

from foremast_tpu.config import BrainConfig
from foremast_tpu.jobs import BrainWorker, InMemoryStore
from foremast_tpu.metrics import ReplaySource
from foremast_tpu.service import make_app


def _run(coro):
    return asyncio.get_event_loop_policy().new_event_loop().run_until_complete(coro)


CREATE_BODY = {
    "appName": "demo",
    "startTime": "2026-07-29T00:00:00Z",
    "endTime": "2026-07-29T00:10:00Z",
    "strategy": "rollingUpdate",
    "metrics": {
        "current": {
            "error4xx": {
                "dataSourceType": "prometheus",
                "parameters": {
                    "endpoint": "http://replay/cur/",
                    "query": "spiketrace",
                    "start": 1,
                    "end": 600,
                    "step": 60,
                },
            }
        },
        "historical": {
            "error4xx": {
                "dataSourceType": "prometheus",
                "parameters": {
                    "endpoint": "http://replay/hist/",
                    "query": "histtrace",
                    "start": 1,
                    "end": 600,
                    "step": 60,
                },
            }
        },
    },
}


def test_create_and_poll_lifecycle(demo_traces):
    async def main():
        store = InMemoryStore()
        app = make_app(store=store)
        client = TestClient(TestServer(app))
        await client.start_server()
        try:
            # create
            r = await client.post("/v1/healthcheck/create", json=CREATE_BODY)
            assert r.status == 200
            body = await r.json()
            job_id = body["jobId"]
            assert body["status"] == "new" and body["statusCode"] == 201

            # idempotent re-create returns the same job
            r2 = await client.post("/v1/healthcheck/create", json=CREATE_BODY)
            body2 = await r2.json()
            assert body2["jobId"] == job_id and body2["statusCode"] == 208

            # poll: new
            r3 = await client.get(f"/v1/healthcheck/id/{job_id}")
            assert (await r3.json())["status"] == "new"

            # score out-of-band (the worker loop)
            nt, nv = demo_traces["normal"]
            st, sv = demo_traces["spike"]
            hist = np.tile(nv, 6).astype(np.float32)
            ht = 1700000000 + 60 * np.arange(len(hist), dtype=np.int64)
            src = ReplaySource()
            src.register("histtrace", (ht, hist))
            src.register("spiketrace", (st, sv))
            BrainWorker(store, src, BrainConfig()).tick(now=1e12)

            # poll: anomaly with flat wire pairs
            r4 = await client.get(f"/v1/healthcheck/id/{job_id}")
            out = await r4.json()
            assert out["status"] == "anomaly"
            vals = out["anomalyInfo"]["values"]["error4xx"]
            assert any(v > 30 for v in vals[1::2])
        finally:
            await client.close()

    _run(main())


def test_create_validation_errors():
    async def main():
        client = TestClient(TestServer(make_app(store=InMemoryStore())))
        await client.start_server()
        try:
            r = await client.post("/v1/healthcheck/create", json={"appName": ""})
            assert r.status == 400
            r = await client.post(
                "/v1/healthcheck/create", data=b"not json",
                headers={"Content-Type": "application/json"},
            )
            assert r.status == 400
            r = await client.get("/v1/healthcheck/id/nope")
            assert r.status == 404
            r = await client.get("/healthz")
            assert r.status == 200
        finally:
            await client.close()

    _run(main())


def test_query_proxy_cors_and_gating():
    async def main():
        client = TestClient(TestServer(make_app(store=InMemoryStore(), query_endpoint="")))
        await client.start_server()
        try:
            r = await client.get("/api/v1/query_range", params={"query": "up"})
            assert r.status == 502  # no upstream configured
            assert r.headers["Access-Control-Allow-Origin"] == "*"
        finally:
            await client.close()

    _run(main())


def test_observability_surface():
    """ISSUE 1: the gateway exposes /metrics + enriched /healthz +
    /debug/state, counts requests by route pattern, and mints a trace ID
    on every created document so worker/controller telemetry can join
    back to the originating request."""

    async def main():
        from prometheus_client import CollectorRegistry

        from foremast_tpu.observe.spans import Tracer

        store = InMemoryStore()
        reg = CollectorRegistry()
        app = make_app(
            store=store,
            tracer=Tracer(service="svc", registry=reg),
            registry=reg,
        )
        client = TestClient(TestServer(app))
        await client.start_server()
        try:
            r = await client.post("/v1/healthcheck/create", json=CREATE_BODY)
            assert r.status == 200
            jid = (await r.json())["jobId"]
            # correlation ID minted at create rides on the stored doc
            # (and round-trips the wire format as traceId); read off
            # the loop the way the app itself would (async-blocking)
            doc = await asyncio.to_thread(store.get, jid)
            assert doc.trace_id
            assert doc.to_json()["traceId"] == doc.trace_id

            r = await client.get("/healthz")
            health = await r.json()
            assert health["ok"] is True and health["store_ok"] is True
            assert health["version"] and health["store_depth"] == 1

            r = await client.get("/debug/state")
            state = await r.json()
            assert state["component"] == "service"
            assert state["queue_depth"] == 1
            assert state["store"] == "InMemoryStore"
            assert state["trace"]["service"] == "svc"

            r = await client.get("/metrics")
            assert r.status == 200
            text = await r.text()
            # route label is the PATTERN, not the raw path (cardinality)
            assert (
                'foremast_service_requests_total{code="200",'
                'route="/v1/healthcheck/create"} 1.0' in text
            )
            assert 'route="/healthz"' in text
        finally:
            await client.close()

    _run(main())
