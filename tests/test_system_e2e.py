"""Full-system test: the deployed topology end-to-end.

WatchPlane (list+diff informer + monitor poller + remediation) over the
kube fake, LocalAnalyst standing in for the REST hop into the job store,
BrainWorker scoring the golden spike trace — the demo runbook
(deploy v1 -> roll v2 with errors -> Unhealthy -> auto-rollback) driven
purely through the plane's own loop, never by calling Barrelman directly.
"""

from __future__ import annotations

import numpy as np

from foremast_tpu.config import BrainConfig
from foremast_tpu.jobs.models import STATUS_COMPLETED_UNHEALTH
from foremast_tpu.jobs.store import InMemoryStore
from foremast_tpu.jobs.worker import BrainWorker
from foremast_tpu.metrics.source import ReplaySource
from foremast_tpu.watch.analyst import LocalAnalyst
from foremast_tpu.watch.crds import (
    DeploymentMetadata,
    MonitoredMetric,
    MonitorPhase,
    Remediation,
    RemediationOption,
)
from foremast_tpu.watch.kubeapi import InMemoryKube
from foremast_tpu.watch.plane import WatchPlane

from tests.test_watch import FakeClock, make_deployment, seed_pods


def test_plane_driven_demo_runbook(demo_traces):
    kube = InMemoryKube()
    kube.add_namespace("demo")
    kube.add_metadata(
        DeploymentMetadata(
            name="demo",
            namespace="demo",
            analyst_endpoint="local://",
            metrics_endpoint="http://prom:9090/",
            monitoring=[
                MonitoredMetric(
                    "error5xx", metric_type="error5xx", metric_alias="error5xx"
                )
            ],
        )
    )
    seed_pods(kube)

    store = InMemoryStore()
    clock = FakeClock()
    plane = WatchPlane(
        kube,
        clock=clock,
        sleep=lambda s: None,
        analyst_factory=lambda ep: LocalAnalyst(store),
    )

    # ---- v1 deployed; first resync primes + creates the monitor CR
    v1 = make_deployment(image="demo:v1", revision=1)
    v1["metadata"]["resourceVersion"] = "1"
    kube.deployments[("demo", "demo")] = v1
    last = plane.step(last_resync=0.0)
    mon = kube.get_monitor("demo", "demo")
    assert mon is not None
    mon.remediation = Remediation(option=RemediationOption.AUTO_ROLLBACK)
    kube.upsert_monitor(mon)

    # ---- v2 rolls out (image change seen by the NEXT resync diff)
    v2 = make_deployment(image="demo:v2", revision=2)
    v2["metadata"]["resourceVersion"] = "2"
    kube.deployments[("demo", "demo")] = v2
    clock.t += 30
    last = plane.step(last_resync=last)
    mon = kube.get_monitor("demo", "demo")
    assert mon.status.phase == MonitorPhase.RUNNING
    assert mon.status.job_id

    # ---- the engine scores: current (new pods) replays the spike trace
    ht, hv = demo_traces["normal"]
    st, sv = demo_traces["spike"]
    source = ReplaySource()
    source.register("demo-new-1", (st, sv))
    source.register("demo-old-1", (ht, hv))
    source.register("namespace_app_per_pod:error5xx", (ht, hv))
    worker = BrainWorker(store, source, BrainConfig())
    assert worker.tick(now=clock.t) >= 1
    assert store.get(mon.status.job_id).status == STATUS_COMPLETED_UNHEALTH

    # ---- next plane tick polls the job, flips Unhealthy, auto-rolls back
    clock.t += 10
    plane.step(last_resync=last)
    mon = kube.get_monitor("demo", "demo")
    assert mon.status.phase == MonitorPhase.UNHEALTHY
    assert mon.status.remediation_taken
    pairs = mon.status.anomaly.get("error5xx", {}).get("values")
    assert pairs and any(v > 10 for v in [p["value"] for p in pairs])
    dep = kube.get_deployment("demo", "demo")
    assert dep["spec"]["template"]["spec"]["containers"][0]["image"] == "demo:v1"
    reasons = {e["reason"] for e in kube.events}
    assert {"MonitoringStarted", "Unhealthy", "AutoRollback"} <= reasons
