"""Pallas kernel tests (interpret mode on CPU): parity with the XLA path."""

import numpy as np
import jax.numpy as jnp
import pytest

from foremast_tpu.engine import scoring
from foremast_tpu.ops.anomaly import BOUND_BOTH, BOUND_LOWER, BOUND_UPPER
from foremast_tpu.ops.kernels import ma_judgment, masked_stats, use_pallas
from foremast_tpu.ops.windows import MetricWindows, masked_mean, masked_std


def _rand_batch(rng, b=5, t=300):
    vals = rng.normal(2.0, 1.5, size=(b, t)).astype(np.float32)
    mask = rng.random((b, t)) > 0.2
    mask[0] = False  # one fully-masked series
    mask[1, 5:] = False  # one nearly-empty series
    return jnp.asarray(vals), jnp.asarray(mask)


def test_masked_stats_matches_windows_ops():
    rng = np.random.default_rng(0)
    vals, mask = _rand_batch(rng)
    cnt, mean, std = masked_stats(vals, mask, interpret=True)
    np.testing.assert_allclose(cnt, mask.sum(axis=-1), rtol=0)
    np.testing.assert_allclose(mean, masked_mean(vals, mask), rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(
        std, masked_std(vals, mask, ddof=0), rtol=1e-4, atol=1e-5
    )


def test_masked_stats_unaligned_shapes():
    """B and T deliberately not multiples of the tile sizes."""
    rng = np.random.default_rng(1)
    vals, mask = _rand_batch(rng, b=3, t=131)
    cnt, mean, std = masked_stats(vals, mask, interpret=True)
    assert cnt.shape == (3,)
    np.testing.assert_allclose(mean, masked_mean(vals, mask), rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("bound", [BOUND_UPPER, BOUND_LOWER, BOUND_BOTH])
def test_ma_judgment_matches_xla_score(bound, monkeypatch):
    """The fused kernel must reproduce the XLA score() verdicts, flags,
    and bounds for algorithm=moving_average_all."""
    monkeypatch.setenv("FOREMAST_PALLAS", "0")  # XLA reference path
    rng = np.random.default_rng(2)
    b = 6
    hist_v, hist_m = _rand_batch(rng, b=b, t=400)
    cur_v = rng.normal(2.0, 1.5, size=(b, 30)).astype(np.float32)
    cur_v[2, 7] = 50.0  # guaranteed upper breach
    cur_v[3, 3] = -50.0  # guaranteed lower breach
    cur_m = np.ones((b, 30), bool)
    cur_m[4, :] = False  # no current data -> unknown
    cur_v, cur_m = jnp.asarray(cur_v), jnp.asarray(cur_m)

    batch = scoring.ScoreBatch(
        historical=MetricWindows(values=hist_v, mask=hist_m, times=jnp.zeros(hist_v.shape, jnp.int32)),
        current=MetricWindows(values=cur_v, mask=cur_m, times=jnp.zeros(cur_v.shape, jnp.int32)),
        baseline=MetricWindows(
            values=jnp.zeros_like(cur_v), mask=jnp.zeros_like(cur_m),
            times=jnp.zeros(cur_v.shape, jnp.int32),
        ),
        threshold=jnp.full((b,), 2.0, jnp.float32),
        bound=jnp.full((b,), bound, jnp.int32),
        min_lower_bound=jnp.zeros((b,), jnp.float32),
        min_points=jnp.full((b,), 10.0, jnp.float32),
    )
    ref = scoring.score(batch)

    verdict, anomalies, upper, lower = ma_judgment(
        hist_v,
        hist_m,
        cur_v,
        cur_m,
        batch.threshold,
        batch.bound,
        batch.min_lower_bound,
        batch.min_points,
        interpret=True,
    )
    np.testing.assert_array_equal(verdict, ref.verdict)
    np.testing.assert_array_equal(anomalies, ref.anomalies)
    np.testing.assert_allclose(upper, ref.upper, rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(lower, ref.lower, rtol=1e-4, atol=1e-4)


def test_ma_judgment_bf16_delta_matches_xla_score_bf16_delta():
    """The bf16-delta kernel (VERDICT r5 #5) must reproduce the shipped
    XLA bf16-delta program on the same anchor/delta/lens upload —
    verdicts and flags exactly, bands at f32 tolerance."""
    from foremast_tpu.engine.judge import _pack_hist_bf16_host
    from foremast_tpu.ops.kernels import ma_judgment_bf16_delta

    rng = np.random.default_rng(3)
    b, th, tc = 6, 300, 30
    lens = np.array([th, th, 150, 40, 5, 0])
    series = []
    for i in range(b):
        t = np.arange(lens[i], dtype=np.int64)
        v = rng.normal(2.0, 0.5, lens[i]).astype(np.float32)
        series.append((t, v))
    anchor, delta, lens_arr = _pack_hist_bf16_host(series, th)
    hist_mask = np.arange(th)[None, :] < lens_arr[:, None]

    cur_vals = rng.normal(2.0, 0.5, size=(b, tc)).astype(np.float32)
    cur_vals[0, -2:] = 40.0  # clear anomaly on a full row
    cur_mask = np.ones((b, tc), bool)

    thr = np.full(b, 2.5, np.float32)
    bound = np.array([BOUND_UPPER, BOUND_BOTH, BOUND_LOWER,
                      BOUND_UPPER, BOUND_UPPER, BOUND_UPPER], np.int32)
    mlb = np.zeros(b, np.float32)
    min_points = np.full(b, 10, np.int32)

    batch = scoring.ScoreBatch(
        historical=MetricWindows(
            values=jnp.zeros((b, 0), jnp.float32),
            mask=jnp.asarray(hist_mask),
            times=None,
        ),
        current=MetricWindows(
            values=jnp.asarray(cur_vals), mask=jnp.asarray(cur_mask), times=None
        ),
        baseline=MetricWindows(
            values=jnp.zeros((b, tc), jnp.float32),
            mask=jnp.zeros((b, tc), bool),
            times=None,
        ),
        threshold=jnp.asarray(thr),
        bound=jnp.asarray(bound),
        min_lower_bound=jnp.asarray(mlb),
        min_points=jnp.asarray(min_points),
    )
    want = scoring.score_bf16_delta(
        batch, jnp.asarray(anchor), jnp.asarray(delta)
    )
    verdict, anoms, upper, lower = ma_judgment_bf16_delta(
        jnp.asarray(anchor),
        jnp.asarray(delta),
        jnp.asarray(lens_arr),
        jnp.asarray(cur_vals),
        jnp.asarray(cur_mask),
        jnp.asarray(thr),
        jnp.asarray(bound),
        jnp.asarray(mlb),
        jnp.asarray(min_points),
        interpret=True,
    )
    np.testing.assert_array_equal(verdict, want.verdict)
    np.testing.assert_array_equal(anoms, want.anomalies)
    np.testing.assert_allclose(upper, want.upper, rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(lower, want.lower, rtol=1e-5, atol=1e-5)


def test_score_dispatches_to_pallas_path(monkeypatch):
    """FOREMAST_PALLAS=1 routes score() through the kernel (interpret mode
    off-TPU) and still produces the XLA-path results."""
    rng = np.random.default_rng(3)
    b = 4
    hist_v, hist_m = _rand_batch(rng, b=b, t=256)
    cur_v = jnp.asarray(rng.normal(2.0, 1.5, size=(b, 20)).astype(np.float32))
    cur_m = jnp.ones((b, 20), bool)
    batch = scoring.ScoreBatch(
        historical=MetricWindows(values=hist_v, mask=hist_m, times=jnp.zeros(hist_v.shape, jnp.int32)),
        current=MetricWindows(values=cur_v, mask=cur_m, times=jnp.zeros(cur_v.shape, jnp.int32)),
        baseline=MetricWindows(
            values=jnp.zeros_like(cur_v), mask=jnp.zeros_like(cur_m),
            times=jnp.zeros(cur_v.shape, jnp.int32),
        ),
        threshold=jnp.full((b,), 2.0, jnp.float32),
        bound=jnp.full((b,), 1, jnp.int32),
        min_lower_bound=jnp.zeros((b,), jnp.float32),
        min_points=jnp.full((b,), 10.0, jnp.float32),
    )

    monkeypatch.setenv("FOREMAST_PALLAS", "0")
    assert not use_pallas()
    ref = scoring.score(batch)

    monkeypatch.setenv("FOREMAST_PALLAS", "1")
    assert use_pallas()
    # score() dispatches at call time, so the env flip takes effect
    # without any cache clearing
    out = scoring.score(batch)

    np.testing.assert_array_equal(out.verdict, ref.verdict)
    np.testing.assert_array_equal(out.anomalies, ref.anomalies)
    np.testing.assert_allclose(out.upper, ref.upper, rtol=1e-4, atol=1e-4)
