"""Property tests: rank statistics vs scipy on random masked inputs.

The golden tests in test_ranks.py pin fixed vectors; these drive the
masked, batched TPU implementations across hypothesis-generated data —
ties, constant runs, tiny samples — against scipy's asymptotic paths.
"""

from __future__ import annotations

import numpy as np
import pytest
import scipy.stats as ss

# property tests are optional-extra coverage: environments without
# hypothesis (the baked CI image) skip instead of erroring collection
pytest.importorskip("hypothesis")
from hypothesis import given, settings, strategies as st

from foremast_tpu.ops.ranks import (
    kruskal_wallis,
    mann_whitney_u,
    wilcoxon_signed_rank,
)

# values drawn from a small grid to force ties (the hard case for the
# tie-correction terms); sizes straddle the min-points gates
_vals = st.lists(
    st.sampled_from([0.0, 0.25, 0.5, 1.0, 2.0, 3.5]), min_size=21, max_size=40
)


def _call(fn, x, y, **kw):
    # unequal lengths: pad into one fixed shape with masks (the TPU form)
    n = max(len(x), len(y))
    xa = np.zeros((1, n), np.float32)
    ya = np.zeros((1, n), np.float32)
    xa[0, : len(x)] = x
    ya[0, : len(y)] = y
    xm = np.zeros((1, n), bool)
    ym = np.zeros((1, n), bool)
    xm[0, : len(x)] = True
    ym[0, : len(y)] = True
    stat, p, ok = fn(xa, xm, ya, ym, **kw)
    return float(stat[0]), float(p[0]), bool(ok[0])


@settings(max_examples=60, deadline=None)
@given(x=_vals, y=_vals)
def test_mann_whitney_matches_scipy(x, y):
    stat, p, ok = _call(mann_whitney_u, x, y, min_points=20)
    ref = ss.mannwhitneyu(x, y, method="asymptotic", use_continuity=True)
    if not ok:
        assert p == 1.0  # degenerate (zero variance): gated out
        return
    np.testing.assert_allclose(stat, ref.statistic, rtol=1e-5)
    np.testing.assert_allclose(p, ref.pvalue, rtol=2e-4, atol=2e-6)


@settings(max_examples=60, deadline=None)
@given(x=_vals, y=_vals)
def test_kruskal_matches_scipy(x, y):
    stat, p, ok = _call(kruskal_wallis, x, y, min_points=5)
    if not ok:
        assert p == 1.0
        return
    ref = ss.kruskal(x, y)
    if np.isnan(ref.statistic) or ref.statistic < 1e-2:
        # degenerate pools: scipy returns nan when every value ties
        # (unequal constant samples), and near H=0 the chi2 survival
        # function's slope is unbounded, so float32's ~1e-4 cancellation
        # noise in H moves p arbitrarily. The decision-level property
        # still holds: no rejection either way.
        assert p > 0.9
        return
    # H is a difference of ~1e2-magnitude terms: float32 cancellation
    # leaves ~1e-4 absolute error, so atol dominates for small H
    np.testing.assert_allclose(stat, ref.statistic, rtol=1e-3, atol=5e-3)
    np.testing.assert_allclose(p, ref.pvalue, rtol=1e-3, atol=5e-4)


@settings(max_examples=60, deadline=None)
@given(
    pairs=st.lists(
        st.tuples(
            st.sampled_from([0.0, 0.25, 0.5, 1.0, 2.0]),
            st.sampled_from([0.0, 0.25, 0.5, 1.0, 2.0]),
        ),
        min_size=21,
        max_size=40,
    )
)
def test_wilcoxon_matches_scipy(pairs):
    x = [a for a, _ in pairs]
    y = [b for _, b in pairs]
    stat, p, ok = _call(wilcoxon_signed_rank, x, y, min_points=20)
    d = np.asarray(x) - np.asarray(y)
    if not ok:
        # all-zero differences or sub-minimum sample: gated out
        assert p == 1.0
        return
    ref = ss.wilcoxon(
        x, y, zero_method="wilcox", correction=False, mode="approx"
    )
    # ours returns W+; scipy's two-sided statistic is min(W+, W-)
    nz = int(np.count_nonzero(d))
    w_min = min(stat, nz * (nz + 1) / 2.0 - stat)
    np.testing.assert_allclose(w_min, ref.statistic, rtol=1e-5, atol=1e-3)
    np.testing.assert_allclose(p, ref.pvalue, rtol=1e-3, atol=5e-4)
