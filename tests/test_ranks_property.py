"""Property tests: rank statistics vs scipy on random masked inputs.

The golden tests in test_ranks.py pin fixed vectors; these drive the
masked, batched TPU implementations across hypothesis-generated data —
ties, constant runs, tiny samples — against scipy's asymptotic paths.
"""

from __future__ import annotations

import numpy as np
import pytest
import scipy.stats as ss

# property tests are optional-extra coverage: environments without
# hypothesis (the baked CI image) skip instead of erroring collection
pytest.importorskip("hypothesis")
from hypothesis import given, settings, strategies as st

from foremast_tpu.ops.ranks import (
    kruskal_wallis,
    mann_whitney_u,
    wilcoxon_signed_rank,
)

# values drawn from a small grid to force ties (the hard case for the
# tie-correction terms); sizes straddle the min-points gates
_vals = st.lists(
    st.sampled_from([0.0, 0.25, 0.5, 1.0, 2.0, 3.5]), min_size=21, max_size=40
)


def _call(fn, x, y, **kw):
    # unequal lengths: pad into one fixed shape with masks (the TPU form)
    n = max(len(x), len(y))
    xa = np.zeros((1, n), np.float32)
    ya = np.zeros((1, n), np.float32)
    xa[0, : len(x)] = x
    ya[0, : len(y)] = y
    xm = np.zeros((1, n), bool)
    ym = np.zeros((1, n), bool)
    xm[0, : len(x)] = True
    ym[0, : len(y)] = True
    stat, p, ok = fn(xa, xm, ya, ym, **kw)
    return float(stat[0]), float(p[0]), bool(ok[0])


@settings(max_examples=60, deadline=None)
@given(x=_vals, y=_vals)
def test_mann_whitney_matches_scipy(x, y):
    stat, p, ok = _call(mann_whitney_u, x, y, min_points=20)
    ref = ss.mannwhitneyu(x, y, method="asymptotic", use_continuity=True)
    if not ok:
        assert p == 1.0  # degenerate (zero variance): gated out
        return
    np.testing.assert_allclose(stat, ref.statistic, rtol=1e-5)
    np.testing.assert_allclose(p, ref.pvalue, rtol=2e-4, atol=2e-6)


@settings(max_examples=60, deadline=None)
@given(x=_vals, y=_vals)
def test_kruskal_matches_scipy(x, y):
    stat, p, ok = _call(kruskal_wallis, x, y, min_points=5)
    if not ok:
        assert p == 1.0
        return
    ref = ss.kruskal(x, y)
    if np.isnan(ref.statistic) or ref.statistic < 1e-2:
        # degenerate pools: scipy returns nan when every value ties
        # (unequal constant samples), and near H=0 the chi2 survival
        # function's slope is unbounded, so float32's ~1e-4 cancellation
        # noise in H moves p arbitrarily. The decision-level property
        # still holds: no rejection either way.
        assert p > 0.9
        return
    # H is a difference of ~1e2-magnitude terms: float32 cancellation
    # leaves ~1e-4 absolute error, so atol dominates for small H
    np.testing.assert_allclose(stat, ref.statistic, rtol=1e-3, atol=5e-3)
    np.testing.assert_allclose(p, ref.pvalue, rtol=1e-3, atol=5e-4)


@settings(max_examples=60, deadline=None)
@given(
    pairs=st.lists(
        st.tuples(
            st.sampled_from([0.0, 0.25, 0.5, 1.0, 2.0]),
            st.sampled_from([0.0, 0.25, 0.5, 1.0, 2.0]),
        ),
        min_size=21,
        max_size=40,
    )
)
def test_wilcoxon_matches_scipy(pairs):
    x = [a for a, _ in pairs]
    y = [b for _, b in pairs]
    stat, p, ok = _call(wilcoxon_signed_rank, x, y, min_points=20)
    d = np.asarray(x) - np.asarray(y)
    if not ok:
        # all-zero differences or sub-minimum sample: gated out
        assert p == 1.0
        return
    ref = ss.wilcoxon(
        x, y, zero_method="wilcox", correction=False, mode="approx"
    )
    # ours returns W+; scipy's two-sided statistic is min(W+, W-)
    nz = int(np.count_nonzero(d))
    w_min = min(stat, nz * (nz + 1) / 2.0 - stat)
    np.testing.assert_allclose(w_min, ref.statistic, rtol=1e-5, atol=1e-3)
    np.testing.assert_allclose(p, ref.pvalue, rtol=1e-3, atol=5e-4)


# -- batched columnar kernels vs the scalar reference (ISSUE 14) ------------
#
# The canary columnar bucket judges its pairwise tests as ONE batched
# program over [B, tc] buffers (and the two-sample kernels compute union
# ranks from [B, Nx, Ny] blocks + the r1+r2 identity instead of ranking
# the concatenation). These properties pin that the batched forms are
# POINTWISE identical to running each row alone — lengths, ties, masks,
# below-min-points gating, and the all-masked-baseline (p=1, False)
# hardwired outcome included.

from foremast_tpu.config import PAIRWISE_ALL
from foremast_tpu.engine.scoring import pairwise
from foremast_tpu.ops.windows import MetricWindows

_grid = st.sampled_from([0.0, 0.25, 0.5, 1.0, 2.0, 3.5])
_row = st.tuples(
    st.lists(_grid, min_size=0, max_size=40),           # current values
    st.lists(_grid, min_size=0, max_size=40),           # baseline values
    st.integers(min_value=0, max_value=7),              # mask pattern seed
)


def _pack_rows(rows, tc):
    b = len(rows)
    cur = np.zeros((b, tc), np.float32)
    curm = np.zeros((b, tc), bool)
    base = np.zeros((b, tc), np.float32)
    basem = np.zeros((b, tc), bool)
    for i, (cv, bv, mseed) in enumerate(rows):
        rng = np.random.default_rng(mseed)
        nc = min(len(cv), tc)
        nb = min(len(bv), tc)
        cur[i, :nc] = cv[:nc]
        base[i, :nb] = bv[:nb]
        # masks with random holes (invalid samples INSIDE the window)
        curm[i, :nc] = rng.random(nc) > 0.15 if nc else False
        basem[i, :nb] = rng.random(nb) > 0.15 if nb else False
    return cur, curm, base, basem


def _decide(cur, curm, base, basem):
    def win(v, m):
        return MetricWindows(
            values=np.asarray(v, np.float32),
            mask=np.asarray(m, bool),
            times=None,
        )

    p, differs = pairwise(
        win(cur, curm),
        win(base, basem),
        PAIRWISE_ALL,
        0.05,
        20,
        20,
        5,
        20,
    )
    return np.asarray(p), np.asarray(differs)


@settings(max_examples=40, deadline=None)
@given(rows=st.lists(_row, min_size=1, max_size=9))
def test_batched_pairwise_decision_matches_per_row(rows):
    """pairwise_decision over a [B, tc] batch == each row judged alone
    (B=1): batching is never allowed to leak across rows, whatever the
    mix of lengths, ties, masks, and gate outcomes in the batch."""
    tc = 40
    cur, curm, base, basem = _pack_rows(rows, tc)
    p_b, d_b = _decide(cur, curm, base, basem)
    for i in range(len(rows)):
        p_1, d_1 = _decide(
            cur[i : i + 1], curm[i : i + 1],
            base[i : i + 1], basem[i : i + 1],
        )
        assert p_b[i] == p_1[0], (i, p_b[i], p_1[0])
        assert d_b[i] == d_1[0], (i, rows[i])


@settings(max_examples=30, deadline=None)
@given(
    cv=st.lists(_grid, min_size=21, max_size=40),
    mseed=st.integers(min_value=0, max_value=100),
)
def test_all_masked_baseline_is_hardwired_constant(cv, mseed):
    """An all-masked (absent) baseline gates every rank test off: the
    decision is EXACTLY (p=1.0, differs=False) — the invariant that
    makes the baseline-less PAIRWISE_NONE program byte-equivalent."""
    tc = 40
    rng = np.random.default_rng(mseed)
    cur = np.zeros((1, tc), np.float32)
    cur[0, : len(cv)] = cv
    curm = np.zeros((1, tc), bool)
    curm[0, : len(cv)] = rng.random(len(cv)) > 0.1
    base = rng.normal(1.0, 0.3, (1, tc)).astype(np.float32)
    basem = np.zeros((1, tc), bool)  # values present, mask says absent
    p, differs = _decide(cur, curm, base, basem)
    assert p[0] == 1.0 and not differs[0]


@settings(max_examples=30, deadline=None)
@given(
    n_cur=st.integers(min_value=0, max_value=25),
    n_base=st.integers(min_value=0, max_value=25),
)
def test_below_min_points_gates_to_inconclusive(n_cur, n_base):
    """Below every test's min-points gate the decision must be the
    inconclusive constant — including the asymmetric cases (one side
    rich, the other sparse)."""
    if n_cur >= 20 or n_base >= 5:
        # kruskal's gate is 5/side; stay strictly under every gate on
        # at least one side so NO test can be applicable
        n_base = min(n_base, 4)
    tc = 32
    rng = np.random.default_rng(n_cur * 31 + n_base)
    cur = np.zeros((1, tc), np.float32)
    base = np.zeros((1, tc), np.float32)
    curm = np.zeros((1, tc), bool)
    basem = np.zeros((1, tc), bool)
    cur[0, :n_cur] = rng.normal(1.0, 0.3, n_cur)
    base[0, :n_base] = rng.normal(5.0, 0.3, n_base)  # wildly different
    curm[0, :n_cur] = True
    basem[0, :n_base] = True
    p, differs = _decide(cur, curm, base, basem)
    assert p[0] == 1.0 and not differs[0]
