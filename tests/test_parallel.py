"""Parallel-plane tests on the 8-device virtual CPU mesh (conftest forces
`xla_force_host_platform_device_count=8` standing in for a v5e-8)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from foremast_tpu.config import BrainConfig
from foremast_tpu.engine import HEALTHY, UNHEALTHY, MetricTask, scoring
from foremast_tpu.ops.forecasters import ewma_levels
from foremast_tpu.parallel import (
    ShardedJudge,
    make_mesh,
    pad_batch,
    shard_batch,
    sharded_ewma,
    sharded_linear_scan,
    sharded_masked_moments,
    throughput_batch,
)


@pytest.fixture(scope="module")
def mesh8():
    assert jax.device_count() >= 8, "conftest must provide 8 virtual devices"
    return make_mesh(n_data=8)


@pytest.fixture(scope="module")
def mesh_2d():
    return make_mesh(n_data=4, n_model=2)


def test_mesh_shapes(mesh8, mesh_2d):
    assert mesh8.shape == {"data": 8, "model": 1}
    assert mesh_2d.shape == {"data": 4, "model": 2}


def test_sharded_scoring_matches_single_device(mesh8):
    batch = throughput_batch(64, 128, 16)
    res_single = scoring.score(batch)
    sharded = shard_batch(pad_batch(batch, 8), mesh8)
    res_shard = scoring.score(sharded)
    np.testing.assert_array_equal(
        np.asarray(res_single.verdict), np.asarray(res_shard.verdict)[:64]
    )
    np.testing.assert_allclose(
        np.asarray(res_single.upper), np.asarray(res_shard.upper)[:64], rtol=1e-5
    )


def test_sharded_judge_end_to_end(mesh8):
    rng = np.random.default_rng(0)
    judge = ShardedJudge(BrainConfig(), mesh=mesh8)
    tasks = []
    for i in range(13):  # deliberately not a multiple of 8
        hist = 0.5 + 0.05 * rng.standard_normal(200)
        cur = 0.5 + 0.05 * rng.standard_normal(10)
        if i == 7:
            cur[3] = 50.0
        t = 1700000000 + 60 * np.arange(max(len(hist), len(cur)), dtype=np.int64)
        tasks.append(
            MetricTask(
                job_id=f"j{i}",
                alias="m",
                metric_type="latency",
                hist_times=t[: len(hist)],
                hist_values=hist.astype(np.float32),
                cur_times=t[: len(cur)],
                cur_values=cur.astype(np.float32),
            )
        )
    vs = judge.judge(tasks)
    assert len(vs) == 13
    assert vs[7].verdict == UNHEALTHY
    assert all(v.verdict == HEALTHY for i, v in enumerate(vs) if i != 7)


def test_sharded_judge_actually_shards(mesh8):
    """Regression: _place must spread the batch over the data axis."""
    batch = pad_batch(throughput_batch(16, 64, 8), 8)
    judge = ShardedJudge(BrainConfig(), mesh=mesh8)
    placed = judge._place(batch)
    sh = placed.current.values.sharding
    assert sh.spec[0] == "data"
    assert len(placed.current.values.devices()) == 8


def test_sharded_linear_scan_matches_local(mesh_2d):
    rng = np.random.default_rng(1)
    a = jnp.asarray(rng.uniform(0.5, 1.0, (8, 64)), jnp.float32)
    b = jnp.asarray(rng.standard_normal((8, 64)), jnp.float32)
    got = sharded_linear_scan(a, b, mesh_2d)

    def ref(a, b):
        out = np.zeros_like(np.asarray(b))
        l = np.zeros(a.shape[0], np.float32)
        for t in range(a.shape[1]):
            l = np.asarray(a)[:, t] * l + np.asarray(b)[:, t]
            out[:, t] = l
        return out

    np.testing.assert_allclose(np.asarray(got), ref(a, b), rtol=2e-4, atol=2e-4)


def test_sharded_ewma_matches_reference_op(mesh_2d):
    rng = np.random.default_rng(2)
    v = jnp.asarray(rng.standard_normal((8, 64)), jnp.float32)
    mask = jnp.asarray(rng.uniform(size=(8, 64)) > 0.2)
    got = sharded_ewma(v, mask, 0.3, mesh_2d)
    want = ewma_levels(v, mask, 0.3)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-4, atol=2e-4)


def test_sharded_moments(mesh_2d):
    rng = np.random.default_rng(3)
    v = jnp.asarray(rng.standard_normal((8, 64)) * 2 + 1, jnp.float32)
    mask = jnp.asarray(rng.uniform(size=(8, 64)) > 0.3)
    mean, var = sharded_masked_moments(v, mask, mesh_2d)
    mnp = np.asarray(mask)
    vnp = np.asarray(v)
    for i in range(8):
        sel = vnp[i][mnp[i]]
        np.testing.assert_allclose(np.asarray(mean)[i], sel.mean(), rtol=1e-4)
        np.testing.assert_allclose(
            np.asarray(var)[i], sel.var(), rtol=1e-3, atol=1e-4
        )


def test_lstm_ae_train_step_sharded(mesh_2d):
    """The dryrun_multichip path: stacked per-service params + windows
    sharded over data; gate axis over model."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from foremast_tpu.models import LSTMAEConfig, lstm_ae_shardings
    from foremast_tpu.models.lstm_ae import init_many, make_optimizer, train_step_many

    cfg = LSTMAEConfig(features=3, hidden=8)
    s, b, t = 8, 4, 12
    params = init_many(jax.random.key(0), s, cfg)
    opt_state = jax.vmap(make_optimizer(cfg).init)(params)
    rng = np.random.default_rng(4)
    x = jnp.asarray(rng.standard_normal((s, b, t, 3)), jnp.float32)
    mask = jnp.ones((s, b, t), bool)

    pspec, ospec = lstm_ae_shardings(mesh_2d, params, opt_state)
    params = jax.tree.map(jax.device_put, params, pspec)
    opt_state = jax.tree.map(jax.device_put, opt_state, ospec)
    x = jax.device_put(x, NamedSharding(mesh_2d, P("data", None, None, None)))
    mask = jax.device_put(mask, NamedSharding(mesh_2d, P("data", None, None)))

    p2, o2, loss = train_step_many(params, opt_state, x, mask, cfg)
    assert np.isfinite(np.asarray(loss)).all()
    # params actually updated
    diff = jax.tree.map(lambda a, b_: float(jnp.abs(a - b_).max()), params, p2)
    assert max(jax.tree.leaves(diff)) > 0


def test_make_global_mesh_single_host():
    from foremast_tpu.parallel.mesh import make_global_mesh

    mesh = make_global_mesh()
    assert mesh.shape["data"] == jax.device_count()
    assert mesh.shape["model"] == 1
    mesh2 = make_global_mesh(n_model=2)
    assert mesh2.shape["model"] == 2
    assert mesh2.shape["data"] == jax.device_count() // 2


def test_make_global_mesh_model_axis_exceeds_host_fails(monkeypatch):
    from foremast_tpu.parallel.mesh import make_global_mesh

    with pytest.raises(ValueError, match="single host"):
        make_global_mesh(n_model=jax.device_count() * 2)


def test_init_distributed_single_host_noop(monkeypatch):
    from foremast_tpu.parallel.mesh import init_distributed

    for var in ("JAX_COORDINATOR_ADDRESS", "JAX_NUM_PROCESSES", "JAX_PROCESS_ID"):
        monkeypatch.delenv(var, raising=False)
    assert init_distributed() is False


def test_score_time_sharded_matches_xla(mesh_2d):
    """Context parallelism: history time axis sharded over `model` must
    reproduce the single-program judgment."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from foremast_tpu.parallel import score_time_sharded

    batch = throughput_batch(32, 256, 16)
    ref = scoring.score(batch)

    def place(leaf, spec):
        return jax.device_put(leaf, NamedSharding(mesh_2d, spec))

    placed = scoring.ScoreBatch(
        historical=jax.tree.map(
            lambda a: place(a, P("data", "model")), batch.historical
        ),
        current=jax.tree.map(lambda a: place(a, P("data")), batch.current),
        baseline=jax.tree.map(lambda a: place(a, P("data")), batch.baseline),
        threshold=place(batch.threshold, P("data")),
        bound=place(batch.bound, P("data")),
        min_lower_bound=place(batch.min_lower_bound, P("data")),
        min_points=place(batch.min_points, P("data")),
    )
    res = score_time_sharded(placed, mesh_2d)
    np.testing.assert_array_equal(np.asarray(ref.verdict), np.asarray(res.verdict))
    np.testing.assert_array_equal(np.asarray(ref.anomalies), np.asarray(res.anomalies))
    np.testing.assert_allclose(np.asarray(ref.upper), np.asarray(res.upper), rtol=1e-4)
    np.testing.assert_allclose(np.asarray(ref.p_value), np.asarray(res.p_value), rtol=1e-5)


def test_sharded_judge_composes_with_fit_cache():
    """ShardedJudge + HealthJudge.fit_cache on the virtual mesh: identical
    verdicts cold vs warm, and a warm tick runs NO fit at all — including
    for the mesh-padding rows (constant '__pad__' cache key)."""
    import numpy as np

    from foremast_tpu.engine import scoring
    from foremast_tpu.engine.judge import MetricTask
    from foremast_tpu.models.cache import ModelCache
    from foremast_tpu.parallel.batch import ShardedJudge

    rng = np.random.default_rng(0)
    t = np.arange(24 * 10, dtype=np.float32)

    def task(i, spike=False):
        hist = (5 + 2 * np.sin(2 * np.pi * t / 24)
                + rng.normal(0, 0.1, len(t))).astype(np.float32)
        cur = (5 + 2 * np.sin(2 * np.pi * (len(t) + np.arange(10)) / 24)
               ).astype(np.float32)
        if spike:
            cur = cur.copy()
            cur[4] = 40.0
        ht = 1_700_000_000 + 60 * np.arange(len(t), dtype=np.int64)
        ct = ht[-1] + 60 + 60 * np.arange(10, dtype=np.int64)
        return MetricTask(
            job_id=f"j{i}", alias="m", metric_type=None,
            hist_times=ht, hist_values=hist, cur_times=ct, cur_values=cur,
            fit_key=f"a{i}|m|u{i}",
        )

    # season_steps matches the 24-step cycle this test synthesizes (the
    # deployed default is the daily 1440)
    judge = ShardedJudge(BrainConfig(algorithm="holt_winters", season_steps=24))
    judge.fit_cache = ModelCache(64)
    tasks = [task(i, spike=(i == 3)) for i in range(12)]  # 12 % 8 != 0: pads
    v1 = judge.judge(tasks)
    orig = scoring.fit_forecast

    def boom(*a, **k):  # pragma: no cover - failure path
        raise AssertionError("fit ran on a warm sharded tick")

    scoring.fit_forecast = boom
    try:
        v2 = judge.judge(tasks)
    finally:
        scoring.fit_forecast = orig
    assert [v.verdict for v in v1] == [v.verdict for v in v2]
    assert v1[3].verdict == scoring.UNHEALTHY
    assert all(v.verdict == scoring.HEALTHY for i, v in enumerate(v1) if i != 3)


def test_sharded_daily_auto_screen_matches_single_device(mesh8):
    """The long-season auto screen (phase-means reductions + Fourier
    Gram solve + significance gate) must partition over the data axis
    exactly like the mean model does — daily-season scoring at cluster
    scale is the round-3 workload shape. Small m=96 keeps CPU time sane
    while exercising the same rolled/pooled code path (m > 64)."""
    m = 96
    batch = throughput_batch(48, 4 * m, 16)
    kw = dict(algorithm="auto_univariate", season_length=m)
    res_single = scoring.score(batch, **kw)
    res_shard = scoring.score(shard_batch(pad_batch(batch, 8), mesh8), **kw)
    np.testing.assert_array_equal(
        np.asarray(res_single.verdict), np.asarray(res_shard.verdict)[:48]
    )
    np.testing.assert_allclose(
        np.asarray(res_single.upper),
        np.asarray(res_shard.upper)[:48],
        rtol=2e-5,
        atol=2e-5,
    )


def test_sharded_judge_phase_means_seasonal_detection(mesh8):
    """End-to-end over the mesh: a sharp per-phase burst history judged
    with ML_ALGORITHM=phase_means — clean re-occurrence of the burst in
    the current window stays healthy; an off-burst spike flags."""
    rng = np.random.default_rng(6)
    m, n, tc = 96, 480, 12
    t = np.arange(n)
    hist = (5 + 3.0 * ((t % m) < 4) + rng.normal(0, 0.1, (12, n))).astype(np.float32)
    ht = 1_700_000_000 + 60 * np.arange(n, dtype=np.int64)
    ct = ht[-1] + 60 + 60 * np.arange(tc, dtype=np.int64)
    tcur = n + np.arange(tc)
    base_cur = (5 + 3.0 * ((tcur % m) < 4)).astype(np.float32)

    tasks = []
    for i in range(12):
        cur = base_cur + rng.normal(0, 0.05, tc).astype(np.float32)
        if i == 5:
            cur[8] += 2.0  # 20-sigma spike OUTSIDE the burst phases
        tasks.append(
            MetricTask(
                job_id=f"j{i}", alias="m", metric_type=None,
                hist_times=ht, hist_values=hist[i],
                cur_times=ct, cur_values=cur,
            )
        )
    judge = ShardedJudge(
        BrainConfig(algorithm="phase_means", season_steps=m), mesh=mesh8
    )
    verdicts = judge.judge(tasks)
    assert verdicts[5].verdict == UNHEALTHY
    assert all(
        v.verdict == HEALTHY for i, v in enumerate(verdicts) if i != 5
    ), [v.verdict for v in verdicts]


def test_sharded_phase_means_matches_local_fit(mesh_2d):
    """Context parallelism for the daily model: the time-sharded
    phase-means fit must reproduce the single-device fit's terminal
    state (season buffer, level, trend, LOO scale) to float tolerance,
    including interior gaps and a ragged (masked) tail."""
    from foremast_tpu.ops.forecasters import fit_phase_means
    from foremast_tpu.parallel import sharded_phase_means

    rng = np.random.default_rng(8)
    b, m, n = 8, 24, 24 * 16  # 16 cycles; t_loc = 192 = 8 cycles per shard
    t = np.arange(n)
    v = (5 + 2.5 * ((t % m) < 3) + 0.004 * t
         + rng.normal(0, 0.1, (b, n))).astype(np.float32)
    mk = np.ones((b, n), bool)
    mk[2, 100:130] = False  # interior gap
    mk[5, 300:] = False  # ragged tail
    mk[6, m + 10 :] = False  # < 2 cycles valid: identifiability select

    ref = fit_phase_means(jnp.asarray(v), jnp.asarray(mk), m)

    from jax.sharding import NamedSharding, PartitionSpec as P

    vs = jax.device_put(jnp.asarray(v), NamedSharding(mesh_2d, P("data", "model")))
    ms = jax.device_put(jnp.asarray(mk), NamedSharding(mesh_2d, P("data", "model")))
    season, level, trend, scale, phase, n_hist = sharded_phase_means(
        vs, ms, m, mesh_2d
    )

    np.testing.assert_allclose(np.asarray(season), np.asarray(ref.season), rtol=2e-4, atol=2e-4)
    np.testing.assert_allclose(np.asarray(level), np.asarray(ref.level), rtol=2e-4, atol=2e-4)
    np.testing.assert_allclose(np.asarray(trend), np.asarray(ref.trend), rtol=2e-3, atol=1e-6)
    np.testing.assert_allclose(np.asarray(scale), np.asarray(ref.scale), rtol=2e-3, atol=2e-4)
    # full terminal state for horizon/score_from_state
    np.testing.assert_array_equal(np.asarray(phase), np.asarray(ref.season_phase))
    np.testing.assert_array_equal(np.asarray(n_hist), np.asarray(mk).sum(axis=1))
    # the under-observed series kept the global-mean model on BOTH paths
    assert float(np.abs(np.asarray(season)[6]).max()) == 0.0
    assert float(np.asarray(trend)[6]) == 0.0
    sel = v[6][np.asarray(mk)[6]]
    np.testing.assert_allclose(np.asarray(level)[6], sel.mean(), rtol=1e-4)
    np.testing.assert_allclose(np.asarray(scale)[6], sel.std(), rtol=1e-3)


def test_score_time_sharded_phase_means_matches_single_chip(mesh_2d):
    """End-to-end context-parallel DAILY judgment: the time-sharded
    phase-means fit + the shared score_from_state tail must reproduce
    scoring.score(algorithm='phase_means') verdict-for-verdict on a
    burst-seasonal fleet with injected off-burst spikes."""
    import dataclasses

    from jax.sharding import NamedSharding, PartitionSpec as P

    from foremast_tpu.parallel import score_time_sharded

    rng = np.random.default_rng(10)
    b, m, th, tc = 16, 24, 24 * 16, 12
    t = np.arange(th)
    hv = (5 + 2.0 * ((t % m) < 3) + rng.normal(0, 0.1, (b, th))).astype(np.float32)
    tcur = th + np.arange(tc)
    cv = (5 + 2.0 * ((tcur % m) < 3)
          + rng.normal(0, 0.05, (b, tc))).astype(np.float32)
    cv[3, 7] += 2.0  # off-burst spike
    batch = throughput_batch(b, th, tc)
    batch = dataclasses.replace(
        batch,
        historical=dataclasses.replace(
            batch.historical, values=jnp.asarray(hv)
        ),
        current=dataclasses.replace(batch.current, values=jnp.asarray(cv)),
        threshold=jnp.full((b,), 4.0, jnp.float32),
    )

    cfg = BrainConfig(season_steps=m)
    ref = scoring.score(batch, algorithm="phase_means", season_length=m)

    def place(leaf, spec):
        return jax.device_put(leaf, NamedSharding(mesh_2d, spec))

    placed = scoring.ScoreBatch(
        historical=jax.tree.map(
            lambda a: place(a, P("data", "model")), batch.historical
        ),
        current=jax.tree.map(lambda a: place(a, P("data")), batch.current),
        baseline=jax.tree.map(lambda a: place(a, P("data")), batch.baseline),
        threshold=place(batch.threshold, P("data")),
        bound=place(batch.bound, P("data")),
        min_lower_bound=place(batch.min_lower_bound, P("data")),
        min_points=place(batch.min_points, P("data")),
    )
    res = score_time_sharded(placed, mesh_2d, cfg, algorithm="phase_means")
    np.testing.assert_array_equal(np.asarray(ref.verdict), np.asarray(res.verdict))
    np.testing.assert_array_equal(
        np.asarray(ref.anomalies), np.asarray(res.anomalies)
    )
    np.testing.assert_allclose(
        np.asarray(ref.upper), np.asarray(res.upper), rtol=2e-4, atol=2e-4
    )
    assert int(np.asarray(res.verdict)[3]) == UNHEALTHY
    assert (np.asarray(res.verdict) == HEALTHY).sum() == b - 1


def test_score_time_sharded_phase_means_advances_gap(mesh_2d):
    """A drifted re-check window (gap % m != 0) must be judged at the
    advanced phase on the context-parallel path too (code-review r3:
    the stale-phase bug the fit-cache path fixed)."""
    import dataclasses

    from jax.sharding import NamedSharding, PartitionSpec as P

    from foremast_tpu.parallel import score_time_sharded

    rng = np.random.default_rng(12)
    # gap=18 puts the data's burst at window positions 6-8 where the
    # stale (un-advanced) model predicts base level — an UPWARD breach
    # the default upper bound sees; the advanced model predicts the
    # burst exactly there and stays quiet
    b, m, th, tc, gap = 8, 24, 24 * 16, 12, 18
    t = np.arange(th)
    hv = (5 + 2.0 * ((t % m) < 3) + rng.normal(0, 0.1, (b, th))).astype(np.float32)
    # current values are the TRUE continuation gap steps later
    tcur = th + gap + np.arange(tc)
    cv = (5 + 2.0 * ((tcur % m) < 3)
          + rng.normal(0, 0.05, (b, tc))).astype(np.float32)
    batch = throughput_batch(b, th, tc)
    batch = dataclasses.replace(
        batch,
        historical=dataclasses.replace(batch.historical, values=jnp.asarray(hv)),
        current=dataclasses.replace(batch.current, values=jnp.asarray(cv)),
        # no baseline (rollingUpdate shape): the throughput_batch noise
        # baseline vs the burst current would trip the canary
        # threshold-lowering and halve the band under test
        baseline=dataclasses.replace(
            batch.baseline, mask=jnp.zeros((b, tc), bool)
        ),
        threshold=jnp.full((b,), 4.0, jnp.float32),
    )

    def place(leaf, spec):
        return jax.device_put(leaf, NamedSharding(mesh_2d, spec))

    placed = scoring.ScoreBatch(
        historical=jax.tree.map(
            lambda a: place(a, P("data", "model")), batch.historical
        ),
        current=jax.tree.map(lambda a: place(a, P("data")), batch.current),
        baseline=jax.tree.map(lambda a: place(a, P("data")), batch.baseline),
        threshold=place(batch.threshold, P("data")),
        bound=place(batch.bound, P("data")),
        min_lower_bound=place(batch.min_lower_bound, P("data")),
        min_points=place(batch.min_points, P("data")),
    )
    cfg = BrainConfig(season_steps=m)
    with_gap = score_time_sharded(
        placed, mesh_2d, cfg, algorithm="phase_means",
        gap_steps=jnp.full((b,), gap, jnp.int32),
    )
    stale = score_time_sharded(placed, mesh_2d, cfg, algorithm="phase_means")
    assert (np.asarray(with_gap.verdict) == HEALTHY).all()
    assert (np.asarray(stale.verdict) == UNHEALTHY).all()  # phase off by 6


# ---------------------------------------------------------------------------
# device-mesh worker knob + columnar sharding (ISSUE 13)
# ---------------------------------------------------------------------------


def test_device_mesh_spec_parsing():
    from foremast_tpu.parallel.mesh import device_mesh_spec

    assert device_mesh_spec({}) == (None, 1)
    assert device_mesh_spec({"FOREMAST_DEVICE_MESH": "auto"}) == (None, 1)
    assert device_mesh_spec({"FOREMAST_DEVICE_MESH": "0"}) is None
    assert device_mesh_spec({"FOREMAST_DEVICE_MESH": "off"}) is None
    assert device_mesh_spec({"FOREMAST_DEVICE_MESH": "4"}) == (4, 1)
    assert device_mesh_spec({"FOREMAST_DEVICE_MESH": "4x2"}) == (4, 2)
    # zero on either grid axis means OFF (matches the bare "0"):
    # a templated "{data}x{model}" with data=0 must disable, not
    # clamp up to a 1-wide axis (review fix)
    assert device_mesh_spec({"FOREMAST_DEVICE_MESH": "0x2"}) is None
    assert device_mesh_spec({"FOREMAST_DEVICE_MESH": "4x0"}) is None
    assert device_mesh_spec(
        {"FOREMAST_DEVICE_MESH": "auto", "FOREMAST_DEVICE_MESH_MODEL": "2"}
    ) == (None, 2)
    # malformed values warn and fall back to auto — never kill startup
    assert device_mesh_spec({"FOREMAST_DEVICE_MESH": "garbage"}) == (None, 1)
    assert device_mesh_spec(
        {"FOREMAST_DEVICE_MESH": "4", "FOREMAST_DEVICE_MESH_MODEL": "bad"}
    ) == (4, 1)


def test_worker_device_mesh_resolution(monkeypatch):
    """auto spans all local devices; 1-device resolutions collapse to
    None (the identity — no ShardedJudge wrapper for stock hosts)."""
    from foremast_tpu.parallel.mesh import worker_device_mesh

    mesh = worker_device_mesh({})
    assert mesh is not None and mesh.shape["data"] == jax.device_count()
    assert worker_device_mesh({"FOREMAST_DEVICE_MESH": "off"}) is None
    assert worker_device_mesh({"FOREMAST_DEVICE_MESH": "1"}) is None
    # the explicit 1x1 grid means SINGLE-DEVICE, not auto (review fix:
    # it used to alias to auto and shard over every device)
    assert worker_device_mesh({"FOREMAST_DEVICE_MESH": "1x1"}) is None
    m2 = worker_device_mesh({"FOREMAST_DEVICE_MESH": "4x2"})
    assert dict(m2.shape) == {"data": 4, "model": 2}
    # infeasible grids warn and fall back to the all-local auto mesh
    # instead of killing worker startup (review fix: make_mesh used to
    # raise through BrainWorker.__init__)
    big = worker_device_mesh({"FOREMAST_DEVICE_MESH": "1024"})
    assert dict(big.shape) == {"data": jax.device_count(), "model": 1}
    bigm = worker_device_mesh(
        {"FOREMAST_DEVICE_MESH": "auto",
         "FOREMAST_DEVICE_MESH_MODEL": str(4 * jax.device_count())}
    )
    assert dict(bigm.shape) == {"data": jax.device_count(), "model": 1}


def test_sharded_judge_columnar_pads_to_data_axis(mesh8):
    """judge_columnar on a ShardedJudge rounds B up to a data-axis
    multiple, partitions the batch (the in-run assert inside _place
    fires otherwise), and returns byte-identical results vs a plain
    single-device judge on the same rows."""
    from foremast_tpu.engine.judge import HealthJudge
    from foremast_tpu.models.cache import ModelCache

    rng = np.random.default_rng(0)
    cfg = BrainConfig()
    b0, tc = 13, 10  # 13: not a multiple of 8
    values = (0.5 + 0.05 * rng.standard_normal((b0, tc))).astype(np.float32)
    values[7, 3] = 50.0
    mask = np.ones((b0, tc), bool)
    keys = [(cfg.algorithm, cfg.season_steps, f"k{i}") for i in range(b0)]
    entries = [(0.5, 0.0, np.zeros(1, np.float32), 0, 0.05, 200)] * b0
    nidx = np.full(b0, tc - 1, np.int32)
    thr = np.full(b0, 3.0, np.float32)
    bound = np.ones(b0, np.int32)
    mlb = np.zeros(b0, np.float32)

    def run(judge):
        judge.fit_cache = ModelCache(256)
        return judge.judge_columnar(
            values.copy(), mask.copy(), list(keys), list(entries),
            nidx, thr, bound, mlb,
        )

    sharded = ShardedJudge(cfg, mesh=mesh8)
    sv, sa, su, sl, sp, sd = run(sharded)
    pv, pa, pu, pl, pp, pd = run(HealthJudge(cfg))
    assert sp is None and pp is None  # baseline-less: constants host-side
    assert sharded.batch_rows_total % 8 == 0
    assert sharded.pad_rows_total == sharded.batch_rows_total - b0
    # exactly 2 placements: the batch buffers (ONE fused host->sharded
    # device_put — the round-15 double-place regression pin) plus the
    # sharded arena's local-row index vector (ISSUE 19; rides the same
    # hook so the roofline H2D leg counts its bytes)
    assert sharded.mesh_stats["place_calls"] == 2
    np.testing.assert_array_equal(sv, pv)
    np.testing.assert_array_equal(sa, pa)
    assert su.tobytes() == pu.tobytes() and sl.tobytes() == pl.tobytes()
    assert int(sv[7]) == UNHEALTHY


def test_pad_fit_keys_never_journal():
    """ISSUE 13 satellite: ShardedJudge batch padding writes its
    constant '__pad__' fit into the in-memory cache (warm ticks stay
    fit-free) but the PR-7 write-through journal, its compaction snap,
    and the PR-10 RefineBook must never record it."""
    import os
    import tempfile

    from foremast_tpu.jobs.refine import RefineBook
    from foremast_tpu.models.cache import (
        FitJournal,
        ModelCache,
        is_pad_fit_key,
    )

    assert is_pad_fit_key("__pad__")
    assert is_pad_fit_key(("moving_average_all", 24, "__pad__"))
    assert is_pad_fit_key("__pad__col__")
    assert is_pad_fit_key(("uni", ("ma", 24, "__pad__")))  # refine bkey
    assert not is_pad_fit_key(("moving_average_all", 24, "app|m|url"))

    with tempfile.TemporaryDirectory() as d:
        journal = FitJournal(os.path.join(d, "fit-uni"))
        cache = ModelCache(64)
        journal.attach(cache)
        judge = ShardedJudge(BrainConfig(), mesh=make_mesh(n_data=8))
        judge.fit_cache = cache
        rng = np.random.default_rng(0)
        hist = (0.5 + 0.05 * rng.standard_normal(200)).astype(np.float32)
        cur = (0.5 + 0.05 * rng.standard_normal(10)).astype(np.float32)
        t = 1_700_000_000 + 60 * np.arange(200, dtype=np.int64)
        tasks = [
            MetricTask(
                job_id=f"j{i}", alias="m", metric_type="latency",
                hist_times=t, hist_values=hist,
                cur_times=t[:10], cur_values=cur,
                fit_key=f"fit{i}",
            )
            for i in range(3)  # pads to 8: five '__pad__' rows fit too
        ]
        assert len(judge.judge(tasks)) == 3
        # the pad fit IS cached (warm ticks stay fit-free)...
        assert any(is_pad_fit_key(k) for k in cache._d)
        # ...but never journaled, and compaction keeps it off disk too
        restored = FitJournal(os.path.join(d, "fit-uni")).restore()
        assert restored and not any(is_pad_fit_key(k) for k in restored)
        journal.compact()
        restored = FitJournal(os.path.join(d, "fit-uni")).restore()
        assert restored and not any(is_pad_fit_key(k) for k in restored)
        journal.close()

    # RefineBook guard: a pad key cannot become a provisional record
    book = RefineBook()
    book.note_uni(("ma", 24, "__pad__"), "__pad__", "u", 5)
    assert len(book._recs) == 0
    book.note_uni(("ma", 24, "real"), "gap", "u", 5)
    assert len(book._recs) == 1


def test_leader_store_claim_filter_passthrough():
    """Mesh-of-pods seam (ISSUE 13): LeaderStore.claim forwards the
    leader's worker-mesh claim filter to the real store, so the
    partition-filtered claim set is what broadcasts to followers."""
    from foremast_tpu.jobs.models import Document
    from foremast_tpu.jobs.store import InMemoryStore
    from foremast_tpu.parallel import LeaderStore

    inner = InMemoryStore()
    for i in range(4):
        inner.create(
            Document(
                id=f"j{i}", app_name=f"app{i}",
                end_time="2999-01-01T00:00:00Z",
                current_config="m== http://x", historical_config="",
                strategy="continuous",
            )
        )
    store = LeaderStore(inner)
    got = store.claim(
        "w0", 90.0, limit=16,
        claim_filter=lambda d: d.app_name in ("app1", "app3"),
    )
    assert sorted(d.id for d in got) == ["j1", "j3"]
    # and the un-filtered spelling still claims the rest
    rest = store.claim("w0", 90.0, limit=16)
    assert sorted(d.id for d in rest) == ["j0", "j2"]
