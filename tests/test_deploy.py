"""Deploy-manifest generator tests: checked-in tree freshness + shape."""

import os

import yaml

from foremast_tpu.config import _DEFAULT_RULES
from foremast_tpu.deploy import render_file, tree
from foremast_tpu.watch.crds import GROUP

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_checked_in_tree_is_current():
    """deploy/ must match the generator (re-run `python -m
    foremast_tpu.deploy deploy/` after editing manifests.py)."""
    for rel, content in tree().items():
        path = os.path.join(REPO, "deploy", rel)
        assert os.path.exists(path), f"missing {rel}"
        with open(path) as f:
            assert f.read() == render_file(content), f"stale {rel}"


def test_crds_match_runtime_types():
    t = tree()
    for rel, plural, kind in [
        ("foremast/1_crds/deploymentmetadata.yaml", "deploymentmetadatas", "DeploymentMetadata"),
        ("foremast/1_crds/deploymentmonitor.yaml", "deploymentmonitors", "DeploymentMonitor"),
    ]:
        (crd,) = t[rel]
        assert crd["metadata"]["name"] == f"{plural}.{GROUP}"
        assert crd["spec"]["names"]["kind"] == kind
        assert crd["spec"]["versions"][0]["name"] == "v1alpha1"


def test_monitor_crd_enums():
    (crd,) = tree()["foremast/1_crds/deploymentmonitor.yaml"]
    props = crd["spec"]["versions"][0]["schema"]["openAPIV3Schema"]["properties"]
    phases = props["status"]["properties"]["phase"]["enum"]
    assert {"Healthy", "Running", "Unhealthy", "Expired", "Abort"} <= set(phases)
    opts = props["spec"]["properties"]["remediation"]["properties"]["option"]["enum"]
    assert opts == ["None", "AutoRollback", "AutoPause", "Auto"]


def test_engine_env_matrix_roundtrips_through_brainconfig():
    """The engine Deployment's env block must reproduce BrainConfig when
    parsed by BrainConfig.from_env — the no-drift guarantee."""
    from foremast_tpu.config import BrainConfig

    docs = tree()["foremast/3_engine/foremast-engine.yaml"]
    dep = next(d for d in docs if d["kind"] == "Deployment")
    env = {
        e["name"]: e["value"]
        for e in dep["spec"]["template"]["spec"]["containers"][0]["env"]
        if "value" in e
    }
    cfg = BrainConfig.from_env(env)
    assert cfg.algorithm == "moving_average_all"
    assert cfg.anomaly.rules == _DEFAULT_RULES
    assert cfg.pairwise.min_mann_white_points == 20
    assert cfg.max_stuck_seconds == 90.0


def test_rendered_yaml_parses_and_has_no_aliases():
    for rel, content in tree().items():
        text = render_file(content)
        if rel.endswith((".yaml", ".yml")):
            docs = list(yaml.safe_load_all(text))
            assert docs, rel
            assert "&id" not in text, f"yaml anchors leaked into {rel}"


def test_rbac_covers_rollback_and_crds():
    docs = tree()["foremast/2_watch/foremast-watch-rbac.yaml"]
    role = next(d for d in docs if d["kind"] == "ClusterRole")
    resources = {r for rule in role["rules"] for r in rule["resources"]}
    assert {"deployments", "deployments/rollback", "replicasets", "pods",
            "deploymentmonitors", "deploymentmetadatas"} <= resources


def test_monitoring_stack_is_self_contained():
    """VERDICT r1 item 9: deploy/prometheus/ must bootstrap monitoring on
    an EMPTY cluster — Prometheus (scrape job + recording rules as native
    rule files), kube-state-metrics (the rules' kube_pod_labels join), and
    Grafana wired to the prometheus-k8s service every foremast component
    points at."""
    t = tree()
    cfg_docs = t["prometheus/2_stack/prometheus-config.yaml"]
    data = cfg_docs[0]["data"]
    prom_cfg = yaml.safe_load(data["prometheus.yml"])
    jobs = {j["job_name"] for j in prom_cfg["scrape_configs"]}
    assert jobs == {"kube-state-metrics", "kubernetes-pods-scrape"}
    assert prom_cfg["rule_files"] == ["/etc/prometheus/rules.yml"]
    rules = yaml.safe_load(data["rules.yml"])
    records = [
        r["record"] for g in rules["groups"] for r in g["rules"] if "record" in r
    ]
    assert "namespace_pod:http_server_requests_error_5xx" in records
    assert any(r.startswith("foremastbrain:") for r in records)

    # the Service is named prometheus-k8s:9090 — the endpoint baked into
    # DeploymentMetadata, the engine env, and the service proxy
    svc = next(
        d for d in t["prometheus/2_stack/prometheus.yaml"] if d["kind"] == "Service"
    )
    assert svc["metadata"]["name"] == "prometheus-k8s"
    assert svc["spec"]["ports"][0]["port"] == 9090

    ksm = t["prometheus/2_stack/kube-state-metrics.yaml"]
    assert {d["kind"] for d in ksm} == {
        "ServiceAccount", "ClusterRole", "ClusterRoleBinding",
        "Deployment", "Service",
    }
    # pod app-labels must be exported for the label_replace join
    dep = next(d for d in ksm if d["kind"] == "Deployment")
    args = dep["spec"]["template"]["spec"]["containers"][0]["args"]
    assert any("metric-labels-allowlist" in a for a in args)

    graf = t["prometheus/2_stack/grafana.yaml"]
    cms = {d["metadata"]["name"]: d for d in graf if d["kind"] == "ConfigMap"}
    assert "prometheus-k8s.monitoring.svc:9090" in (
        cms["grafana-datasources"]["data"]["datasources.yaml"]
    )

    # the provisioned dashboard is generated from the UI's own panel spec
    import json as _json

    from foremast_tpu.ui.metrics import DEFAULT_PANELS

    dash = _json.loads(
        cms["grafana-dashboard-foremast"]["data"]["foremast.json"]
    )
    assert len(dash["panels"]) == len(DEFAULT_PANELS)
    for p, spec in zip(dash["panels"], DEFAULT_PANELS):
        exprs = [tgt["expr"] for tgt in p["targets"]]
        assert len(exprs) == 4  # base/upper/lower/anomaly
        assert any(spec.metric in e for e in exprs)
        assert all('$namespace' in e and '$app' in e for e in exprs)
    # the dashboard lands in the provider's path via the pod volumes
    dep = next(d for d in graf if d["kind"] == "Deployment")
    mounts = {
        m["mountPath"]
        for m in dep["spec"]["template"]["spec"]["containers"][0]["volumeMounts"]
    }
    assert "/var/lib/grafana/dashboards" in mounts
    assert "/etc/grafana/provisioning/dashboards" in mounts

    # alert rules ride the same native rule file Prometheus loads
    assert any(
        r.get("alert") == "ForemastEngineDown"
        for g in rules["groups"]
        for r in g["rules"]
    )


def test_alertmanager_and_node_exporter_complete_the_stack():
    """VERDICT r2 item 3: the alert rules must have somewhere to GO. The
    stack ships Alertmanager (reference alertmanager-*.yaml bundle) wired
    into Prometheus's `alerting:` stanza, and node-exporter (reference
    node-exporter-*.yaml) feeding the cpu/memory metric types."""
    t = tree()
    am = t["prometheus/2_stack/alertmanager.yaml"]
    assert [d["kind"] for d in am] == ["ConfigMap", "Deployment", "Service"]
    svc = next(d for d in am if d["kind"] == "Service")
    assert svc["metadata"]["name"] == "alertmanager-main"  # reference name
    assert svc["spec"]["ports"][0]["port"] == 9093
    am_cfg = yaml.safe_load(
        next(d for d in am if d["kind"] == "ConfigMap")["data"]["alertmanager.yml"]
    )
    # the route's receiver must exist (alertmanager refuses to start
    # otherwise) and carry the reference Secret's grouping cadence
    assert am_cfg["route"]["receiver"] in {r["name"] for r in am_cfg["receivers"]}
    assert am_cfg["route"]["group_wait"] == "30s"
    assert am_cfg["route"]["repeat_interval"] == "12h"

    # Prometheus routes evaluated alerts at the alertmanager Service
    prom_cfg = yaml.safe_load(
        t["prometheus/2_stack/prometheus-config.yaml"][0]["data"]["prometheus.yml"]
    )
    targets = prom_cfg["alerting"]["alertmanagers"][0]["static_configs"][0]["targets"]
    assert targets == ["alertmanager-main.monitoring.svc:9093"]

    ne = t["prometheus/2_stack/node-exporter.yaml"]
    ds = next(d for d in ne if d["kind"] == "DaemonSet")
    tmpl = ds["spec"]["template"]
    # collected by the stack's existing pod-annotation scrape job
    assert tmpl["metadata"]["annotations"]["prometheus.io/scrape"] == "true"
    assert tmpl["spec"]["hostPID"] is True
    args = tmpl["spec"]["containers"][0]["args"]
    assert any("--path.procfs=/host/proc" in a for a in args)


def test_firing_foremast_alert_reaches_alertmanager_api():
    """End-to-end over real HTTP: a ForemastAnomaly_* alert — in the v2
    wire shape Prometheus's notifier POSTs for a firing rule, built from
    the GENERATED rule (name/labels/rendered annotation) — must land in
    Alertmanager's /api/v2/alerts and be acknowledged. A stdlib fake
    stands in for Alertmanager (no real AM binary in the image); the
    payload shape is the real contract."""
    import json
    import threading
    import urllib.request
    from http.server import BaseHTTPRequestHandler, HTTPServer

    from foremast_tpu.metrics.rules import alert_rules

    received = []

    class FakeAM(BaseHTTPRequestHandler):
        def do_POST(self):
            assert self.path == "/api/v2/alerts"
            body = self.rfile.read(int(self.headers["Content-Length"]))
            received.extend(json.loads(body))
            self.send_response(200)
            self.end_headers()

        def log_message(self, *a):  # quiet
            pass

    srv = HTTPServer(("127.0.0.1", 0), FakeAM)
    thread = threading.Thread(target=srv.serve_forever, daemon=True)
    thread.start()
    try:
        rule = next(
            r for r in alert_rules()
            if r["alert"].startswith("ForemastAnomaly_")
            and "error_5xx" in r["alert"]
        )
        labels = dict(rule["labels"])
        labels.update(
            alertname=rule["alert"],
            app="demo", exported_namespace="foremast-examples",
        )
        summary = (
            rule["annotations"]["summary"]
            .replace("{{ $labels.app }}", "demo")
            .replace("{{ $labels.exported_namespace }}", "foremast-examples")
        )
        payload = [  # Prometheus notifier v2 POST shape
            {
                "labels": labels,
                "annotations": {"summary": summary},
                "startsAt": "2026-07-30T00:00:00Z",
                "generatorURL": "http://prometheus-k8s:9090/graph",
            }
        ]
        req = urllib.request.Request(
            f"http://127.0.0.1:{srv.server_port}/api/v2/alerts",
            data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=5) as resp:
            assert resp.status == 200
    finally:
        srv.shutdown()
        thread.join(timeout=5)

    (alert,) = received
    assert alert["labels"]["alertname"].startswith("ForemastAnomaly_")
    assert alert["labels"]["severity"] == "warning"
    assert "demo" in alert["annotations"]["summary"]
