"""Deploy-manifest generator tests: checked-in tree freshness + shape."""

import os

import yaml

from foremast_tpu.config import _DEFAULT_RULES
from foremast_tpu.deploy import render_file, tree
from foremast_tpu.watch.crds import GROUP

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_checked_in_tree_is_current():
    """deploy/ must match the generator (re-run `python -m
    foremast_tpu.deploy deploy/` after editing manifests.py)."""
    for rel, content in tree().items():
        path = os.path.join(REPO, "deploy", rel)
        assert os.path.exists(path), f"missing {rel}"
        with open(path) as f:
            assert f.read() == render_file(content), f"stale {rel}"


def test_crds_match_runtime_types():
    t = tree()
    for rel, plural, kind in [
        ("foremast/1_crds/deploymentmetadata.yaml", "deploymentmetadatas", "DeploymentMetadata"),
        ("foremast/1_crds/deploymentmonitor.yaml", "deploymentmonitors", "DeploymentMonitor"),
    ]:
        (crd,) = t[rel]
        assert crd["metadata"]["name"] == f"{plural}.{GROUP}"
        assert crd["spec"]["names"]["kind"] == kind
        assert crd["spec"]["versions"][0]["name"] == "v1alpha1"


def test_monitor_crd_enums():
    (crd,) = tree()["foremast/1_crds/deploymentmonitor.yaml"]
    props = crd["spec"]["versions"][0]["schema"]["openAPIV3Schema"]["properties"]
    phases = props["status"]["properties"]["phase"]["enum"]
    assert {"Healthy", "Running", "Unhealthy", "Expired", "Abort"} <= set(phases)
    opts = props["spec"]["properties"]["remediation"]["properties"]["option"]["enum"]
    assert opts == ["None", "AutoRollback", "AutoPause", "Auto"]


def test_engine_env_matrix_roundtrips_through_brainconfig():
    """The engine Deployment's env block must reproduce BrainConfig when
    parsed by BrainConfig.from_env — the no-drift guarantee."""
    from foremast_tpu.config import BrainConfig

    docs = tree()["foremast/3_engine/foremast-engine.yaml"]
    dep = next(d for d in docs if d["kind"] == "Deployment")
    env = {
        e["name"]: e["value"]
        for e in dep["spec"]["template"]["spec"]["containers"][0]["env"]
        if "value" in e
    }
    cfg = BrainConfig.from_env(env)
    assert cfg.algorithm == "moving_average_all"
    assert cfg.anomaly.rules == _DEFAULT_RULES
    assert cfg.pairwise.min_mann_white_points == 20
    assert cfg.max_stuck_seconds == 90.0


def test_rendered_yaml_parses_and_has_no_aliases():
    for rel, content in tree().items():
        text = render_file(content)
        if rel.endswith((".yaml", ".yml")):
            docs = list(yaml.safe_load_all(text))
            assert docs, rel
            assert "&id" not in text, f"yaml anchors leaked into {rel}"


def test_rbac_covers_rollback_and_crds():
    docs = tree()["foremast/2_watch/foremast-watch-rbac.yaml"]
    role = next(d for d in docs if d["kind"] == "ClusterRole")
    resources = {r for rule in role["rules"] for r in rule["resources"]}
    assert {"deployments", "deployments/rollback", "replicasets", "pods",
            "deploymentmonitors", "deploymentmetadatas"} <= resources
