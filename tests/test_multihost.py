"""True multi-process distributed test: two Python processes form one
jax.distributed cluster, build the global (data, model) mesh, and run a
cross-process reduction.

This is the only place the multi-host claims are exercised with real
process boundaries (everything else uses virtual devices in one process).
The child initializes jax.distributed FIRST because this test image's
import shims touch the backend during deep imports; on real TPU pods the
runtime auto-initializes, which init_distributed treats as idempotent
(the regression this test caught).
"""

import os
import socket
import subprocess
import sys

_CHILD = """
import os, sys
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
import jax
jax.config.update("jax_platforms", "cpu")
try:  # CPU multi-process collectives (older jax needs explicit gloo)
    jax.config.update("jax_cpu_collectives_implementation", "gloo")
except Exception:
    pass
addr, pid = sys.argv[1], int(sys.argv[2])
jax.distributed.initialize(addr, 2, pid)

sys.path.insert(0, {repo!r})
import numpy as np
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P
from foremast_tpu.parallel import init_distributed, make_global_mesh

os.environ["JAX_COORDINATOR_ADDRESS"] = addr
os.environ["JAX_NUM_PROCESSES"] = "2"
os.environ["JAX_PROCESS_ID"] = str(pid)
assert init_distributed() is True  # idempotent over the prior initialize

mesh = make_global_mesh()
assert jax.device_count() == 8, jax.device_count()
assert mesh.shape == {{"data": 8, "model": 1}}, mesh.shape

x = jax.make_array_from_process_local_data(
    NamedSharding(mesh, P("data")), np.full(4, 1.0 + pid, np.float32), (8,)
)
assert float(jax.jit(jnp.sum)(x)) == 12.0  # 4x1 (proc0) + 4x2 (proc1)

assert make_global_mesh(n_model=2).shape == {{"data": 4, "model": 2}}
print(f"proc {{pid}} ok", flush=True)
"""


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_two_process_global_mesh(tmp_path):
    # bounded by the 150 s communicate() timeout below
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    child = tmp_path / "child.py"
    child.write_text(_CHILD.format(repo=repo))
    addr = f"127.0.0.1:{_free_port()}"
    env = {k: v for k, v in os.environ.items() if not k.startswith("JAX_")}
    procs = [
        subprocess.Popen(
            [sys.executable, str(child), addr, str(pid)],
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
            env=env,
        )
        for pid in (0, 1)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=150)
            outs.append(out)
    finally:
        # a child hung at the init barrier (peer crashed) must not leak
        for p in procs:
            if p.poll() is None:
                p.kill()
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"proc {pid} failed:\n{out}"
        assert f"proc {pid} ok" in out
