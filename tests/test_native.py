"""Native runtime library tests: build, ABI, parity with the Python paths."""

import numpy as np
import pytest

from foremast_tpu import native
from foremast_tpu.ops.windows import MetricWindows

pytestmark = pytest.mark.skipif(
    not native.ensure_built(),  # builds once at collection; load() never compiles
    reason="native library unavailable (no C++ toolchain)",
)


def _series(rng, n):
    t = (1_700_000_000 + 60 * np.arange(n)).astype(np.int64)
    v = rng.normal(size=n).astype(np.float32)
    return t, v


def test_pack_windows_matches_python_path(monkeypatch):
    rng = np.random.default_rng(0)
    series = [_series(rng, n) for n in (0, 1, 7, 48, 100)]
    length = 48  # forces both padding and truncation

    values, times, mask = native.pack_windows(series, length)

    monkeypatch.setenv("FOREMAST_NATIVE", "0")
    ref = MetricWindows.from_ragged(series, length)
    np.testing.assert_array_equal(values, np.asarray(ref.values))
    np.testing.assert_array_equal(times, np.asarray(ref.times))
    np.testing.assert_array_equal(mask, np.asarray(ref.mask))


def test_from_ragged_uses_native_and_matches():
    """from_ragged with the native path on must equal the pure path."""
    rng = np.random.default_rng(1)
    series = [_series(rng, n) for n in (5, 30, 12)]
    w = MetricWindows.from_ragged(series, 30)
    assert w.values.shape == (3, 30)
    assert int(w.count()[0]) == 5
    assert int(w.count()[1]) == 30
    np.testing.assert_allclose(np.asarray(w.values)[0, :5], series[0][1][:5])
    assert not np.asarray(w.mask)[0, 5:].any()


def test_pack_windows_large_batch_parallel_path():
    """Cross the kParallelThreshold so the threaded path runs."""
    rng = np.random.default_rng(2)
    series = [_series(rng, 16) for _ in range(2048)]
    values, times, mask = native.pack_windows(series, 16)
    assert values.shape == (2048, 16)
    assert mask.all()
    i = 1234
    np.testing.assert_array_equal(values[i], series[i][1])


def test_anomaly_pairs_wire_format():
    t = np.array([10, 20, 30, 40], np.int64)
    v = np.array([1.0, 2.0, 3.0, 4.0], np.float32)
    flags = np.array([0, 1, 0, 1], np.uint8)
    pairs = native.anomaly_pairs(flags, t, v)
    assert pairs == [20.0, 2.0, 40.0, 4.0]


def test_abi_version():
    lib = native.load()
    assert lib.fp_abi_version() == native.ABI_VERSION


def test_pack_windows_rejects_length_mismatch():
    t = np.arange(3, dtype=np.int64)
    v = np.zeros(5, np.float32)
    with pytest.raises(ValueError, match="3 timestamps for 5 values"):
        native.pack_windows([(t, v)], 8)


def test_anomaly_pairs_rejects_length_mismatch():
    with pytest.raises(ValueError, match="length mismatch"):
        native.anomaly_pairs(
            np.ones(4, np.uint8), np.arange(3, dtype=np.int64), np.zeros(4, np.float32)
        )
