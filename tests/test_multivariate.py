"""Multivariate dispatch tests: metric-count rule, bivariate + LSTM joints."""

import dataclasses

import numpy as np
import pytest

from foremast_tpu.config import BrainConfig
from foremast_tpu.engine import scoring
from foremast_tpu.engine.judge import MetricTask
from foremast_tpu.engine.multivariate import (
    ALGO_AUTO,
    ALGO_BIVARIATE,
    ALGO_LSTM,
    MultivariateJudge,
    select_mode,
)


def _task(job, alias, hist_v, cur_v, base_v=None, t0=1_700_000_000, step=60):
    hist_t = t0 + step * np.arange(len(hist_v), dtype=np.int64)
    cur_t = t0 + step * (len(hist_v) + np.arange(len(cur_v), dtype=np.int64))
    base = {}
    if base_v is not None:
        base = dict(
            base_times=t0 - step * np.arange(len(base_v), 0, -1, dtype=np.int64),
            base_values=np.asarray(base_v, np.float32),
        )
    return MetricTask(
        job_id=job,
        alias=alias,
        metric_type=None,
        hist_times=hist_t,
        hist_values=np.asarray(hist_v, np.float32),
        cur_times=cur_t,
        cur_values=np.asarray(cur_v, np.float32),
        **base,
    )


def test_select_mode_rule():
    assert select_mode(ALGO_AUTO, 1) == "univariate"
    assert select_mode(ALGO_AUTO, 2) == "bivariate"
    assert select_mode(ALGO_AUTO, 3) == "lstm"
    assert select_mode(ALGO_AUTO, 4) == "lstm"
    assert select_mode(ALGO_BIVARIATE, 2) == "bivariate"
    assert select_mode(ALGO_BIVARIATE, 3) == "univariate"
    assert select_mode(ALGO_LSTM, 2) == "lstm"
    assert select_mode(ALGO_LSTM, 1) == "univariate"
    assert select_mode("moving_average_all", 5) == "univariate"


def _correlated(rng, n, rho=0.9):
    x = rng.normal(1.0, 0.2, n)
    y = rho * x + np.sqrt(1 - rho**2) * rng.normal(0.0, 0.2, n) + 1.0
    return x.astype(np.float32), y.astype(np.float32)


def test_bivariate_joint_detects_correlation_break():
    """A point normal in each marginal but off the correlation ridge must
    flag jointly — the capability univariate scoring cannot provide."""
    rng = np.random.default_rng(0)
    hx, hy = _correlated(rng, 400)
    cx, cy = _correlated(rng, 20)
    # break the ridge at one point: both values in-range marginally
    cx[10], cy[10] = float(np.max(hx)) * 0.95, float(np.min(hy)) * 1.05

    cfg = BrainConfig(algorithm=ALGO_BIVARIATE)
    judge = MultivariateJudge(cfg)
    t1 = _task("j1", "latency", hx, cx)
    t2 = _task("j1", "tps", hy, cy)
    verdicts = judge.judge([t1, t2])
    assert len(verdicts) == 2
    assert all(v.verdict == scoring.UNHEALTHY for v in verdicts)
    # both aliases carry the SAME flagged timestamp with their own values
    ts1 = verdicts[0].anomaly_pairs[0::2]
    ts2 = verdicts[1].anomaly_pairs[0::2]
    assert ts1 == ts2
    assert float(t1.cur_times[10]) in ts1


def test_bivariate_healthy_on_ridge():
    rng = np.random.default_rng(1)
    hx, hy = _correlated(rng, 400)
    cx, cy = _correlated(rng, 20)
    cfg = BrainConfig(algorithm=ALGO_BIVARIATE)
    # threshold high enough to ignore sampling noise
    cfg = dataclasses.replace(
        cfg, anomaly=dataclasses.replace(cfg.anomaly, threshold=6.0, rules=())
    )
    verdicts = MultivariateJudge(cfg).judge(
        [_task("j1", "a", hx, cx), _task("j1", "b", hy, cy)]
    )
    assert all(v.verdict == scoring.HEALTHY for v in verdicts)


def test_bivariate_insufficient_history_unknown():
    cfg = BrainConfig(algorithm=ALGO_BIVARIATE)
    verdicts = MultivariateJudge(cfg).judge(
        [_task("j1", "a", [1.0, 2.0], [1.0]), _task("j1", "b", [1.0, 2.0], [1.0])]
    )
    assert all(v.verdict == scoring.UNKNOWN for v in verdicts)


def test_auto_mixes_modes_per_job():
    """auto: a 1-metric job goes univariate, a 2-metric job bivariate."""
    rng = np.random.default_rng(2)
    hx, hy = _correlated(rng, 300)
    cfg = BrainConfig(algorithm=ALGO_AUTO)
    judge = MultivariateJudge(cfg)
    tasks = [
        _task("solo", "latency", hx, hx[:10]),
        _task("pair", "a", hx, hx[:10]),
        _task("pair", "b", hy, hy[:10]),
    ]
    verdicts = judge.judge(tasks)
    assert {v.job_id for v in verdicts} == {"solo", "pair"}
    assert len(verdicts) == 3


def test_lstm_joint_flags_spike_and_caches():
    rng = np.random.default_rng(3)
    f = 3
    hist = rng.normal(0.5, 0.05, size=(f, 240)).astype(np.float32)
    cur = rng.normal(0.5, 0.05, size=(f, 12)).astype(np.float32)
    cur_spiked = cur.copy()
    cur_spiked[:, 6] = 10.0  # joint spike across all metrics

    cfg = BrainConfig(algorithm=ALGO_LSTM)
    judge = MultivariateJudge(cfg)
    judge.lstm_steps = 30  # keep the test fast

    tasks = [_task("jl", f"m{i}", hist[i], cur_spiked[i]) for i in range(f)]
    verdicts = judge.judge(tasks)
    assert len(verdicts) == f
    assert all(v.verdict == scoring.UNHEALTHY for v in verdicts)
    spike_t = float(tasks[0].cur_times[6])
    for v in verdicts:
        assert spike_t in v.anomaly_pairs[0::2]

    assert len(judge.cache) == 1  # model cached by (aliases, F, bucket)

    # clean window scores healthy against the CACHED model (no retrain)
    judge.lstm_steps = 10**9  # would hang if training ran again
    tasks2 = [_task("jl2", f"m{i}", hist[i], cur[i]) for i in range(f)]
    verdicts2 = judge.judge(tasks2)
    assert all(v.verdict == scoring.HEALTHY for v in verdicts2)


def test_lstm_cache_is_per_app():
    """Two SERVICES with the identical standard alias set must not share
    a model (the starter gives every app the same metric names)."""
    rng = np.random.default_rng(4)
    hist = rng.normal(0.5, 0.05, size=(3, 240)).astype(np.float32)
    cur = rng.normal(0.5, 0.05, size=(3, 12)).astype(np.float32)
    cfg = BrainConfig(algorithm=ALGO_LSTM)
    judge = MultivariateJudge(cfg)
    judge.lstm_steps = 5

    def tasks(job, app):
        return [
            dataclasses.replace(_task(job, f"m{i}", hist[i], cur[i]), app=app)
            for i in range(3)
        ]

    judge.judge(tasks("ja", "app-a"))
    judge.judge(tasks("jb", "app-b"))
    assert len(judge.cache) == 2


def test_lstm_short_history_job_not_poisoned_by_long_group_peer():
    """A short-history job batched with a long-current job must not train
    on all-masked windows (mu=sd=0 would flag every clean point)."""
    rng = np.random.default_rng(5)
    short_h = rng.normal(0.5, 0.05, size=(3, 30)).astype(np.float32)
    short_c = rng.normal(0.5, 0.05, size=(3, 12)).astype(np.float32)
    long_h = rng.normal(0.5, 0.05, size=(3, 600)).astype(np.float32)
    long_c = rng.normal(0.5, 0.05, size=(3, 100)).astype(np.float32)

    cfg = BrainConfig(algorithm=ALGO_LSTM)
    judge = MultivariateJudge(cfg)
    judge.lstm_steps = 30
    tasks = [_task("short", f"m{i}", short_h[i], short_c[i]) for i in range(3)]
    tasks += [
        dataclasses.replace(_task("long", f"m{i}", long_h[i], long_c[i]), app="other")
        for i in range(3)
    ]
    verdicts = judge.judge(tasks)
    short_vs = [v for v in verdicts if v.job_id == "short"]
    assert short_vs and all(v.verdict != scoring.UNHEALTHY for v in short_vs)


def test_lstm_short_history_gates_to_unknown():
    """Explicit min-history gate (ISSUE 7 satellite): a history shorter
    than TWO training windows of the job's own bucket cannot calibrate
    the AE's mu/sd cutoff — clean in-band noise was measured flagging
    UNHEALTHY off the degenerate single-window fit. Such jobs must
    degrade to UNKNOWN ("insufficient data"), while a job just past the
    2-window floor still gets a real verdict."""
    rng = np.random.default_rng(11)
    cur = rng.normal(0.5, 0.05, size=(3, 12)).astype(np.float32)  # tc=16
    # the joint detectors' calibrated threshold (benchmarks/quality.py
    # runs them at 4 sigma; the deployed 2.0 default is the univariate
    # tuning) — this test pins the GATE boundary, not 2-sigma noise odds
    cfg = BrainConfig(algorithm=ALGO_LSTM)
    cfg = dataclasses.replace(
        cfg, anomaly=dataclasses.replace(cfg.anomaly, threshold=4.0)
    )
    judge = MultivariateJudge(cfg)
    judge.lstm_steps = 30

    # 30 pts < 2 * 16: gated, every alias UNKNOWN, nothing cached
    short_h = rng.normal(0.5, 0.05, size=(3, 30)).astype(np.float32)
    vs = judge.judge([_task("s", f"m{i}", short_h[i], cur[i]) for i in range(3)])
    assert len(vs) == 3
    assert all(v.verdict == scoring.UNKNOWN for v in vs)
    assert len(judge.cache) == 0

    # 64 pts >= 2 * 16: fits and judges (clean noise stays non-unhealthy)
    ok_h = rng.normal(0.5, 0.05, size=(3, 64)).astype(np.float32)
    vs2 = judge.judge([_task("k", f"m{i}", ok_h[i], cur[i]) for i in range(3)])
    assert all(v.verdict != scoring.UNKNOWN for v in vs2)
    assert all(v.verdict != scoring.UNHEALTHY for v in vs2)


def test_lstm_mid_batch_cache_eviction_does_not_crash():
    """More distinct alias sets than max_cache_size in ONE batch must not
    lose entries before scoring."""
    rng = np.random.default_rng(6)
    cfg = dataclasses.replace(BrainConfig(algorithm=ALGO_LSTM), max_cache_size=1)
    judge = MultivariateJudge(cfg)
    judge.lstm_steps = 5
    tasks = []
    for job in ("j1", "j2"):
        hist = rng.normal(0.5, 0.05, size=(3, 240)).astype(np.float32)
        cur = rng.normal(0.5, 0.05, size=(3, 12)).astype(np.float32)
        tasks += [
            dataclasses.replace(_task(job, f"m{i}", hist[i], cur[i]), app=job)
            for i in range(3)
        ]
    verdicts = judge.judge(tasks)  # must not raise
    assert len(verdicts) == 6


def test_lstm_cache_warm_restart_via_checkpoint(tmp_path):
    """save -> load in a fresh judge must score WITHOUT retraining, even
    though orbax restores NamedTuples as dicts."""
    import ast

    from foremast_tpu.models.cache import ModelCache

    rng = np.random.default_rng(7)
    hist = rng.normal(0.5, 0.05, size=(3, 240)).astype(np.float32)
    cur = rng.normal(0.5, 0.05, size=(3, 12)).astype(np.float32)
    cfg = BrainConfig(algorithm=ALGO_LSTM)

    judge = MultivariateJudge(cfg)
    judge.lstm_steps = 20
    tasks = [_task("j1", f"m{i}", hist[i], cur[i]) for i in range(3)]
    ref = judge.judge(tasks)  # trains + scores with the in-memory model
    path = str(tmp_path / "ck")
    judge.cache.save(path)

    cache2 = ModelCache()
    assert cache2.load(path, key_parser=ast.literal_eval) == 1
    judge2 = MultivariateJudge(cfg, cache=cache2)
    judge2.lstm_steps = 10**9  # would hang if training ran
    verdicts = judge2.judge(
        [_task("j2", f"m{i}", hist[i], cur[i]) for i in range(3)]
    )
    assert len(verdicts) == 3
    # the restored model must reproduce the in-memory model's judgment
    # (same data, same params round-tripped through orbax)
    for a, b in zip(ref, verdicts):
        assert a.verdict == b.verdict
        assert a.anomaly_pairs == b.anomaly_pairs


def _indep_pair(rng, n):
    """Two independent metrics so joint Mahalanobis ~ zx^2 + zy^2."""
    x = rng.normal(1.0, 0.2, n).astype(np.float32)
    y = rng.normal(2.0, 0.3, n).astype(np.float32)
    return x, y


def test_bivariate_canary_shifted_baseline_lowers_threshold_and_flags():
    """The reference's canary flow (design.md:31-33) on a 2-metric job: a
    current window ~1 sigma off-center is healthy at the global threshold
    (2.0), but a baseline that proves the distributions shifted lowers the
    joint threshold and the same window flags."""
    rng = np.random.default_rng(10)
    hx, hy = _indep_pair(rng, 400)
    # current: both metrics pinned ~1 sigma above their historical means
    # -> d^2 ~ 2: inside the 2.0-sigma ellipse, outside the lowered 1.0
    cx = np.full(24, 1.0 + 0.2, np.float32) + rng.normal(0, 0.01, 24).astype(
        np.float32
    )
    cy = np.full(24, 2.0 + 0.3, np.float32) + rng.normal(0, 0.01, 24).astype(
        np.float32
    )
    # baseline drawn from the historical distribution: clearly different
    # from the pinned current -> Mann-Whitney rejects
    bx, by = _indep_pair(rng, 24)

    cfg = BrainConfig(algorithm=ALGO_BIVARIATE)
    judge = MultivariateJudge(cfg)

    # without a baseline: healthy at threshold 2.0
    plain = judge.judge(
        [_task("j1", "a", hx, cx), _task("j1", "b", hy, cy)]
    )
    assert all(v.verdict == scoring.HEALTHY for v in plain)
    assert all(v.p_value == 1.0 and not v.dist_differs for v in plain)

    # with a shifted baseline: threshold lowered -> unhealthy, and the
    # verdicts carry real per-alias pairwise evidence
    canary = judge.judge(
        [_task("j2", "a", hx, cx, base_v=bx), _task("j2", "b", hy, cy, base_v=by)]
    )
    assert all(v.verdict == scoring.UNHEALTHY for v in canary)
    assert all(v.dist_differs for v in canary)
    assert all(v.p_value < 0.05 for v in canary)
    assert all(len(v.anomaly_pairs) > 0 for v in canary)


def test_bivariate_same_distribution_baseline_keeps_threshold():
    """A baseline matching the current distribution must NOT lower the
    threshold (no false canary sensitivity)."""
    rng = np.random.default_rng(11)
    hx, hy = _indep_pair(rng, 400)
    cx, cy = _indep_pair(rng, 24)
    bx, by = _indep_pair(rng, 24)
    cfg = BrainConfig(algorithm=ALGO_BIVARIATE)
    # threshold above sampling noise: chi^2(2) puts ~13.5% of clean points
    # outside the 2-sigma ellipse, so 24 draws almost surely breach it
    cfg = dataclasses.replace(
        cfg, anomaly=dataclasses.replace(cfg.anomaly, threshold=6.0, rules=())
    )
    verdicts = MultivariateJudge(cfg).judge(
        [_task("j1", "a", hx, cx, base_v=bx), _task("j1", "b", hy, cy, base_v=by)]
    )
    assert all(not v.dist_differs for v in verdicts)
    assert all(v.verdict == scoring.HEALTHY for v in verdicts)


def test_lstm_canary_reports_pairwise_evidence_per_alias():
    """3-metric LSTM job: per-alias p/differs ride the verdicts, and a
    shifted baseline lowers the joint recon threshold."""
    rng = np.random.default_rng(12)
    f = 3
    hist = rng.normal(0.5, 0.05, size=(f, 240)).astype(np.float32)
    cur = rng.normal(0.5, 0.05, size=(f, 24)).astype(np.float32)
    # baseline far from current on metric 0 only
    base = rng.normal(0.5, 0.05, size=(f, 24)).astype(np.float32)
    base[0] += 5.0

    cfg = BrainConfig(algorithm=ALGO_LSTM)
    judge = MultivariateJudge(cfg)
    judge.lstm_steps = 20
    tasks = [
        _task("jl", f"m{i}", hist[i], cur[i], base_v=base[i]) for i in range(f)
    ]
    verdicts = judge.judge(tasks)
    assert len(verdicts) == f
    by_alias = {v.alias: v for v in verdicts}
    assert by_alias["m0"].dist_differs and by_alias["m0"].p_value < 0.05
    assert not by_alias["m1"].dist_differs
    assert not by_alias["m2"].dist_differs


def test_worker_uses_multivariate_judge_by_default():
    from foremast_tpu.jobs.store import InMemoryStore
    from foremast_tpu.jobs.worker import BrainWorker
    from foremast_tpu.metrics.source import ReplaySource
    from foremast_tpu.engine.multivariate import MultivariateJudge

    w = BrainWorker(InMemoryStore(), ReplaySource(), BrainConfig())
    assert isinstance(w.judge, MultivariateJudge)


def test_lstm_mvn_refits_for_new_deployment_history():
    """The cached residual-MVN state is time-anchored: a later deployment
    of the same app (new history, phase-shifted vs the cached fit) must
    refit instead of replaying a stale seasonal phase — otherwise every
    clean point flags at anti-phase."""
    from benchmarks.quality import draw_comoving

    rng = np.random.default_rng(21)
    f, th, tc = 3, 240, 24
    cfg = BrainConfig(algorithm=ALGO_LSTM)
    cfg = dataclasses.replace(
        cfg, anomaly=dataclasses.replace(cfg.anomaly, threshold=4.0, rules=())
    )
    judge = MultivariateJudge(cfg)
    judge.lstm_steps = 20

    def tasks(job, t0_steps, seed):
        r = np.random.default_rng(seed)
        hist = draw_comoving(r, 1, f, th, t0_steps)[0]
        cur = draw_comoving(r, 1, f, tc, t0_steps + th)[0]
        t0 = 1_700_000_000 + 60 * t0_steps
        ht = t0 + 60 * np.arange(th, dtype=np.int64)
        ct = t0 + 60 * (th + np.arange(tc, dtype=np.int64))
        return [
            MetricTask(
                job_id=job, alias=f"m{i}", metric_type=None,
                hist_times=ht, hist_values=hist[i],
                cur_times=ct, cur_values=cur[i], app="svc",
            )
            for i in range(f)
        ]

    first = judge.judge(tasks("d1", 0, seed=5))
    assert all(v.verdict == scoring.HEALTHY for v in first)
    # redeploy 12 steps later: anti-phase vs the cached fit's anchor
    second = judge.judge(tasks("d2", 12, seed=6))
    assert all(v.verdict == scoring.HEALTHY for v in second), (
        "stale time-anchored MVN state replayed against a phase-shifted "
        "deployment"
    )


def test_auto_univariate_branch_uses_structure_screen():
    """`auto`'s univariate branch routes through the structure screen
    (flat -> mean model, seasonal/trend -> fitted HW), not the blind
    deployed default; explicitly-configured multivariate algorithms keep
    the reference default for their misfit jobs."""
    judge = MultivariateJudge(BrainConfig(algorithm=ALGO_AUTO))
    assert judge.univariate.config.algorithm == "auto_univariate"
    judge_bi = MultivariateJudge(BrainConfig(algorithm=ALGO_BIVARIATE))
    assert judge_bi.univariate.config.algorithm == "moving_average_all"
