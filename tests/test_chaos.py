"""Chaos plane + graceful degradation (ISSUE 9): fault-plan
determinism/scheduling, circuit-breaker state machine, injection seams
in the dependency clients, write-behind store degradation with
exactly-once replay, partial-tick release semantics, and receiver
overload shedding."""

import threading
import time
import urllib.error
import urllib.request

import pytest

from foremast_tpu.chaos import (
    BreakerOpen,
    ChaosCollector,
    CircuitBreaker,
    Degradation,
    FaultPlan,
    GuardedSession,
    InjectedFault,
    chaos_from_env,
    is_transient_error,
)
from foremast_tpu.chaos.degrade import (
    REASON_BUFFERED,
    REASON_DEADLINE,
    REASON_DROPPED_AGE,
    REASON_FETCH,
    REASON_REPLAYED,
    WriteBehindBuffer,
)

NOW = 1_760_000_000.0


# ---------------------------------------------------------------------------
# FaultPlan
# ---------------------------------------------------------------------------


def test_fault_plan_deterministic_across_replays():
    """Same seed + same call order => identical injection decisions;
    a different seed diverges (the whole point of seeding)."""

    def run(seed):
        plan = FaultPlan(
            rules=({"edge": "prometheus", "error_rate": 0.5},), seed=seed
        ).activate()
        edge = plan.edge("prometheus")
        hits = []
        for i in range(64):
            try:
                edge.perturb(f"http://p/{i}")
                hits.append(0)
            except InjectedFault:
                hits.append(1)
        return hits

    a, b, c = run(7), run(7), run(8)
    assert a == b
    assert a != c
    assert 0 < sum(a) < 64  # actually probabilistic, not all-or-nothing


def test_fault_plan_schedule_windows_and_edges():
    """Rules fire only inside their [after, after+duration) window on
    their own edge, measured on the injectable plan clock."""
    t = [100.0]
    plan = FaultPlan(
        rules=(
            {"edge": "store", "after": 5.0, "duration": 10.0,
             "error_rate": 1.0},
        ),
        clock=lambda: t[0],
    ).activate()
    edge = plan.edge("store")
    other = plan.edge("prometheus")
    edge.perturb("op")  # t=0: before the window, no fault
    t[0] = 106.0
    with pytest.raises(InjectedFault):
        edge.perturb("op")
    other.perturb("op")  # other edges untouched
    t[0] = 116.0
    edge.perturb("op")  # window over
    assert plan.injections_snapshot() == {("store", "connection"): 1}


def test_fault_plan_latency_blackhole_and_status():
    t = [0.0]
    plan = FaultPlan(
        rules=(
            {"edge": "a", "latency_seconds": 0.02},
            {"edge": "b", "blackhole": True},
            {"edge": "c", "error_rate": 1.0, "kind": "status",
             "status": 503},
        ),
        clock=lambda: t[0],
    ).activate()
    t0 = time.perf_counter()
    plan.edge("a").perturb("x")
    assert time.perf_counter() - t0 >= 0.02
    with pytest.raises(TimeoutError):  # blackhole = injected timeout
        plan.edge("b").perturb("x")
    fault = plan.edge("c").perturb("x", raise_faults=False)
    assert fault is not None and fault.status == 503
    assert is_transient_error(fault)  # faults classify transient


def test_fault_plan_op_substring_scoping():
    plan = FaultPlan(
        rules=({"edge": "store", "op": "_bulk", "error_rate": 1.0},)
    ).activate()
    edge = plan.edge("store")
    edge.perturb("http://es/documents/_search")  # unscoped op: clean
    with pytest.raises(InjectedFault):
        edge.perturb("http://es/documents/_bulk")


def test_chaos_from_env_inline_file_and_unset(tmp_path, monkeypatch):
    monkeypatch.delenv("FOREMAST_CHAOS_PLAN", raising=False)
    assert chaos_from_env() is None
    monkeypatch.setenv(
        "FOREMAST_CHAOS_PLAN",
        '{"seed": 3, "rules": [{"edge": "store", "error_rate": 1.0}]}',
    )
    plan = chaos_from_env()
    assert plan is not None and plan.seed == 3 and len(plan.rules) == 1
    p = tmp_path / "plan.json"
    p.write_text('{"rules": [{"edge": "kube"}]}')
    monkeypatch.setenv("FOREMAST_CHAOS_PLAN", f"@{p}")
    assert chaos_from_env().rules[0].edge == "kube"
    monkeypatch.setenv("FOREMAST_CHAOS_PLAN", '{"rules": [{"bad": 1}]}')
    with pytest.raises((ValueError, TypeError)):
        chaos_from_env()  # a chaos run that tests nothing must not start


def test_clock_skew_edge():
    t = [50.0]
    plan = FaultPlan(
        rules=({"edge": "clock", "after": 10.0, "skew_seconds": 7.5},),
        clock=lambda: t[0],
    ).activate()
    clock = plan.edge("clock").clock(base=lambda: 1000.0)
    assert clock() == 1000.0  # before the window: no skew
    t[0] = 65.0
    assert clock() == 1007.5


# ---------------------------------------------------------------------------
# CircuitBreaker
# ---------------------------------------------------------------------------


def test_breaker_opens_after_threshold_and_recovers_half_open():
    t = [0.0]
    br = CircuitBreaker(
        "es", failure_threshold=3, open_seconds=10.0, clock=lambda: t[0]
    )
    for _ in range(2):
        br.allow()
        br.record_failure()
    assert br.state == "closed"
    br.allow()
    br.record_failure()
    assert br.state == "open"
    with pytest.raises(BreakerOpen) as ei:
        br.allow()
    assert isinstance(ei.value, ConnectionError)  # existing nets catch it
    assert br.short_circuits == 1
    t[0] = 10.5  # cooldown elapsed: ONE probe allowed
    br.allow()
    with pytest.raises(BreakerOpen):
        br.allow()  # second concurrent probe short-circuits
    br.record_success()
    assert br.state == "closed"
    br.allow()  # closed again: calls flow


def test_breaker_failed_probe_reopens_with_fresh_cooldown():
    t = [0.0]
    br = CircuitBreaker(
        "es", failure_threshold=1, open_seconds=5.0, clock=lambda: t[0]
    )
    br.allow()
    br.record_failure()
    t[0] = 6.0
    br.allow()  # half-open probe
    br.record_failure()
    assert br.state == "open"
    with pytest.raises(BreakerOpen):
        br.allow()
    t[0] = 10.0  # 4s into the FRESH cooldown: still open
    with pytest.raises(BreakerOpen):
        br.allow()
    t[0] = 11.5
    br.allow()
    br.record_success()
    assert br.state == "closed"
    assert br.transitions["open"] == 2 and br.transitions["closed"] == 1


def test_breaker_success_resets_consecutive_failures():
    br = CircuitBreaker("p", failure_threshold=2)
    for _ in range(5):
        br.allow()
        br.record_failure()
        br.allow()
        br.record_success()
    assert br.state == "closed"


# ---------------------------------------------------------------------------
# client seams
# ---------------------------------------------------------------------------


class _Resp:
    def __init__(self, status=200):
        self.status_code = status

    def raise_for_status(self):
        if self.status_code >= 400:
            raise RuntimeError(f"http {self.status_code}")

    def json(self):
        return {
            "status": "success",
            "data": {"result": [{"values": [[100, "1.0"]]}]},
        }


class _OkSession:
    def __init__(self):
        self.calls = 0

    def get(self, url, timeout=None):
        self.calls += 1
        return _Resp(200)


def test_prometheus_source_chaos_injection_exhausts_retries():
    from foremast_tpu.metrics.source import PrometheusSource

    plan = FaultPlan(
        rules=({"edge": "prometheus", "error_rate": 1.0},)
    ).activate()
    sess = _OkSession()
    src = PrometheusSource(
        session=sess, retries=2, backoff_seconds=0.001,
        chaos=plan.edge("prometheus"),
    )
    with pytest.raises(InjectedFault):
        src.fetch("http://p/q")
    assert sess.calls == 0  # faults injected BEFORE the wire
    assert plan.injections_snapshot()[("prometheus", "connection")] == 3


def test_prometheus_source_breaker_opens_and_fails_fast():
    from foremast_tpu.metrics.source import PrometheusSource

    class _DeadSession:
        def __init__(self):
            self.calls = 0

        def get(self, url, timeout=None):
            self.calls += 1
            raise ConnectionError("refused")

    t = [0.0]
    br = CircuitBreaker(
        "prometheus", failure_threshold=2, open_seconds=30.0,
        clock=lambda: t[0],
    )
    sess = _DeadSession()
    src = PrometheusSource(
        session=sess, retries=0, backoff_seconds=0.001, breaker=br
    )
    for _ in range(2):
        with pytest.raises(ConnectionError):
            src.fetch("http://p/q")
    assert br.state == "open"
    wire_calls = sess.calls
    with pytest.raises(BreakerOpen):
        src.fetch("http://p/q")
    assert sess.calls == wire_calls  # short-circuited, no wire attempt
    # endpoint heals; cooldown elapses; the probe re-closes the breaker
    sess.get = lambda url, timeout=None: _Resp(200)
    t[0] = 31.0
    ts, vs = src.fetch("http://p/q")
    assert br.state == "closed"
    assert ts.tolist() == [100]


def test_guarded_session_wraps_chaos_and_breaker():
    plan = FaultPlan(rules=({"edge": "store", "error_rate": 1.0},)).activate()
    br = CircuitBreaker("store", failure_threshold=2, open_seconds=60.0)
    inner = _OkSession()
    gs = GuardedSession(inner, chaos=plan.edge("store"), breaker=br)
    for _ in range(2):
        with pytest.raises(InjectedFault):
            gs.get("http://es/")
    with pytest.raises(BreakerOpen):
        gs.get("http://es/")
    assert inner.calls == 0
    # non-verb attributes delegate (ES store reads .headers etc.)
    inner.headers = {"x": "y"}
    assert gs.headers == {"x": "y"}


def test_guarded_session_counts_5xx_as_breaker_failure():
    class _FiveHundred:
        def post(self, url, **kw):
            return _Resp(503)

    br = CircuitBreaker("store", failure_threshold=2)
    gs = GuardedSession(_FiveHundred(), breaker=br)
    gs.post("http://es/_bulk")
    gs.post("http://es/_bulk")
    assert br.state == "open"


# ---------------------------------------------------------------------------
# write-behind buffer
# ---------------------------------------------------------------------------


def test_write_behind_caps_and_ages_out():
    t = [0.0]
    buf = WriteBehindBuffer(max_docs=3, max_age_seconds=10.0, clock=lambda: t[0])
    buf.add(["d1", "d2", "d3", "d4"])  # cap 3: d1 drops (oldest)
    assert len(buf) == 3
    snap = buf.stats.docs_snapshot()
    assert snap[REASON_BUFFERED] == 4
    assert snap["write_dropped_cap"] == 1
    t[0] = 11.0  # everything aged past the stuck window
    assert buf.drain() == []
    assert buf.stats.docs_snapshot()[REASON_DROPPED_AGE] == 3
    assert len(buf) == 0


def test_write_behind_requeue_preserves_age():
    t = [0.0]
    buf = WriteBehindBuffer(max_docs=8, max_age_seconds=10.0, clock=lambda: t[0])
    buf.add(["d1"])
    t[0] = 6.0
    entries = buf.drain()
    assert [d for _, d in entries] == ["d1"]
    buf.requeue(entries)  # replay failed: back with the ORIGINAL stamp
    t[0] = 11.0
    assert buf.drain() == []  # aged from first buffering, not requeue
    assert buf.stats.docs_snapshot()[REASON_DROPPED_AGE] == 1


# ---------------------------------------------------------------------------
# worker degradation (the ISSUE 9 acceptance pins)
# ---------------------------------------------------------------------------


class _OutageStore:
    """Delegating store whose write path (or claim) can be browned out
    with transient errors — the ES-outage stand-in."""

    def __init__(self, inner):
        self.inner = inner
        self.fail_writes = False
        self.fail_claims = False
        self.write_log = []  # (doc_id, status) per landed write

    def __getattr__(self, name):
        return getattr(self.inner, name)

    def claim(self, *a, **kw):
        if self.fail_claims:
            raise ConnectionError("store down (claim)")
        return self.inner.claim(*a, **kw)

    def update(self, doc):
        if self.fail_writes:
            raise ConnectionError("store down (write)")
        self.write_log.append((doc.id, doc.status))
        return self.inner.update(doc)

    def update_many(self, docs):
        if self.fail_writes:
            raise ConnectionError("store down (write)")
        self.write_log.extend((d.id, d.status) for d in docs)
        return self.inner.update_many(docs)


def _mk_worker(services=3, **worker_kw):
    from benchmarks.worker_bench import build_fleet
    from foremast_tpu.config import BrainConfig
    from foremast_tpu.jobs import BrainWorker

    store, source = build_fleet(services, 256, 30, NOW, seed=0)
    outage = _OutageStore(store)
    cfg = BrainConfig(
        algorithm="moving_average_all", season_steps=24,
        max_cache_size=4 * services + 64,
    )
    worker = BrainWorker(
        outage, source, config=cfg, claim_limit=2 * services,
        worker_id="chaos-w", **worker_kw,
    )
    return worker, outage, store, source


def test_es_outage_mid_warm_tick_degrades_then_replays_exactly_once():
    """THE acceptance pin: a full store outage during a warm tick
    buffers write-back (degraded-mode counters) instead of failing the
    tick; replay after recovery lands each doc's verdict exactly once."""
    worker, outage, store, _source = _mk_worker(3)
    assert worker.tick(now=NOW + 150) == 3  # tick 1: warm the fits
    # every doc is a re-check doc (endTime in the future): healthy
    # ticks leave them preprocess_completed
    sts = {d.id: d.status for d in store._docs.values()}
    assert set(sts.values()) == {"preprocess_completed"}

    outage.fail_writes = True
    outage.write_log.clear()
    n = worker.tick(now=NOW + 210)  # warm tick THROUGH the outage
    assert n == 3  # the tick did not fail wholesale
    assert outage.write_log == []  # nothing reached the store...
    snap = worker._degrade.stats.docs_snapshot()
    assert snap[REASON_BUFFERED] == 3  # ...everything buffered
    assert len(worker._degrade.write_behind) == 3
    state = worker.debug_state()["degradation"]
    assert state["write_behind"]["buffered_docs"] == 3
    # (no store-status assertion here: InMemoryStore shares Document
    # OBJECTS with the worker, so in-place status mutations are visible
    # even though no update() landed — write_log above is the honest
    # record of what reached the store's write path)

    outage.fail_writes = False
    n = worker.tick(now=NOW + 270)  # heals: replay THEN a normal tick
    assert n == 3
    assert worker._degrade.stats.docs_snapshot()[REASON_REPLAYED] == 3
    assert len(worker._degrade.write_behind) == 0
    # exactly-once: each doc got ONE replayed write of the buffered
    # status, then one write from this tick's own judgment
    per_doc = {}
    for doc_id, status in outage.write_log:
        per_doc.setdefault(doc_id, []).append(status)
    assert all(
        v == ["preprocess_completed", "preprocess_completed"]
        for v in per_doc.values()
    ), per_doc
    worker.close()


def test_claim_outage_degrades_to_empty_tick_not_a_crash():
    worker, outage, _store, _source = _mk_worker(2)
    outage.fail_claims = True
    assert worker.tick(now=NOW + 150) == 0  # no exception
    events = worker._degrade.stats.events_snapshot()
    assert events[("store", "claim_error")] == 1
    outage.fail_claims = False
    assert worker.tick(now=NOW + 160) == 2  # worker still usable
    worker.close()


def test_transient_fetch_failure_releases_doc_not_terminal():
    """A doc whose fetch fails TRANSIENTLY (dependency down / breaker
    open) is released un-judged — claimable next tick — while a
    permanent fetch error keeps the reference's preprocess_failed."""
    worker, outage, store, source = _mk_worker(3)
    worker._fast_tick = lambda docs, now: (0, docs)  # force slow path
    source.concurrent_fetch = True
    orig_fetch = source.fetch

    def fetch(url):
        if "app0" in url:
            raise ConnectionError("prometheus down")  # transient
        if "app1" in url:
            raise RuntimeError("bad query")  # permanent
        return orig_fetch(url)

    source.fetch = fetch
    assert worker.tick(now=NOW + 150) == 3
    sts = {d.id: d.status for d in store._docs.values()}
    assert sts["job-0"] == "preprocess_completed"  # released, no verdict
    assert sts["job-1"] == "preprocess_failed"  # permanent: terminal
    assert sts["job-2"] == "preprocess_completed"  # judged normally
    assert worker._degrade.stats.docs_snapshot()[REASON_FETCH] == 1
    worker.close()


def test_tick_budget_releases_unfetched_chunks():
    """Partial-tick semantics: chunks whose turn comes after the tick
    budget release their docs un-judged instead of wedging the tick
    behind a slow dependency."""
    degrade = Degradation(tick_budget_seconds=0.15)
    worker, outage, store, source = _mk_worker(6, degrade=degrade)
    worker._fast_tick = lambda docs, now: (0, docs)
    worker.cold_chunk_docs = 2
    worker.pipeline_depth = 1
    source.concurrent_fetch = True
    orig_fetch = source.fetch

    def slow_fetch(url):
        time.sleep(0.02)  # ~0.12s per 2-doc chunk (3 urls per doc)
        return orig_fetch(url)

    source.fetch = slow_fetch
    assert worker.tick(now=NOW + 150) == 6
    sts = {d.id: d.status for d in store._docs.values()}
    # every doc is accounted for: judged or released, none in-progress
    assert set(sts.values()) == {"preprocess_completed"}
    released = worker._degrade.stats.docs_snapshot().get(REASON_DEADLINE, 0)
    assert released > 0  # the budget actually bit
    assert worker._last_tick["docs"] == 6
    worker.close()


def test_degradation_debug_state_sections():
    worker, _outage, _store, _source = _mk_worker(1)
    state = worker.debug_state()
    deg = state["degradation"]
    assert "write_behind" in deg and "breakers" in deg
    assert deg["chaos"] is None  # no plan: production shape
    assert state["store_connect"] is None  # in-memory store
    worker.close()


# ---------------------------------------------------------------------------
# receiver overload shedding
# ---------------------------------------------------------------------------


def _push(addr, payload=b'{"timeseries": []}'):
    req = urllib.request.Request(
        f"http://{addr}/api/v1/write", data=payload, method="POST",
        headers={"Content-Type": "application/json"},
    )
    return urllib.request.urlopen(req, timeout=5)


def test_receiver_sheds_with_429_retry_after_under_overload():
    from foremast_tpu.chaos.degrade import DegradeStats
    from foremast_tpu.ingest import RingStore, stop_ingest_server
    from foremast_tpu.ingest.receiver import start_ingest_server

    # one slow handler (chaos latency) + max_inflight=1 => concurrent
    # pushes shed deterministically
    plan = FaultPlan(
        rules=({"edge": "receiver", "latency_seconds": 0.4},)
    ).activate()
    stats = DegradeStats()
    srv, _ = start_ingest_server(
        0, RingStore(budget_bytes=1 << 20, shards=1), host="127.0.0.1",
        max_inflight=1, chaos=plan.edge("receiver"), degrade_stats=stats,
    )
    addr = "127.0.0.1:%d" % srv.server_address[1]
    try:
        results = {}

        def slow_push():
            results["slow"] = _push(addr).status

        t = threading.Thread(target=slow_push)
        t.start()
        time.sleep(0.1)  # the slow handler is now inside its latency
        with pytest.raises(urllib.error.HTTPError) as ei:
            _push(addr)
        assert ei.value.code == 429
        assert ei.value.headers["Retry-After"] == "1"
        ei.value.close()
        t.join()
        assert results["slow"] == 200  # the in-flight push completed
        assert stats.events_snapshot()[("receiver", "shed")] >= 1
        # RoutingPusher classifies 429 as transient (retry-then-buffer)
        from foremast_tpu.metrics.source import RETRY_STATUSES

        assert 429 in RETRY_STATUSES
    finally:
        stop_ingest_server(srv)


def test_receiver_chaos_fault_answers_status():
    from foremast_tpu.ingest import RingStore, stop_ingest_server
    from foremast_tpu.ingest.receiver import start_ingest_server

    plan = FaultPlan(
        rules=(
            {"edge": "receiver", "error_rate": 1.0, "kind": "status",
             "status": 503},
        )
    ).activate()
    srv, _ = start_ingest_server(
        0, RingStore(budget_bytes=1 << 20, shards=1), host="127.0.0.1",
        chaos=plan.edge("receiver"),
    )
    addr = "127.0.0.1:%d" % srv.server_address[1]
    try:
        with pytest.raises(urllib.error.HTTPError) as ei:
            _push(addr)
        assert ei.value.code == 503  # answered, not a dropped thread
        ei.value.close()
    finally:
        stop_ingest_server(srv)


# ---------------------------------------------------------------------------
# ChaosCollector exposition
# ---------------------------------------------------------------------------


def test_chaos_collector_families_lint_clean():
    from prometheus_client import CollectorRegistry

    from foremast_tpu.observe.metrics_lint import lint_registry

    plan = FaultPlan(rules=({"edge": "x", "error_rate": 1.0},)).activate()
    with pytest.raises(InjectedFault):
        plan.edge("x").perturb("op")
    degrade = Degradation(chaos_plan=plan)
    br = degrade.breakers.get("x")
    br.allow()
    br.record_failure()
    degrade.stats.count_docs(REASON_DEADLINE)
    degrade.stats.count_event("receiver", "shed")
    registry = CollectorRegistry()
    registry.register(ChaosCollector(degrade))
    assert lint_registry(registry) == []
    families = {f.name for f in registry.collect()}
    assert families == {
        "foremast_chaos_injections",
        "foremast_breaker_state",
        "foremast_breaker_transitions",
        "foremast_breaker_short_circuits",
        "foremast_degraded_docs",
        "foremast_degraded_events",
    }


# ---------------------------------------------------------------------------
# ElasticsearchStore: guarded session + bounded connect retry
# ---------------------------------------------------------------------------


def test_es_store_chaos_seam_wraps_session():
    from foremast_tpu.jobs.store import ElasticsearchStore

    plan = FaultPlan(rules=({"edge": "store", "error_rate": 1.0},)).activate()
    store = ElasticsearchStore(
        "http://es:9200", session=_OkSession(), chaos=plan.edge("store")
    )
    with pytest.raises(InjectedFault):
        store.get("doc-1")
    assert plan.injections_snapshot()[("store", "connection")] == 1


def test_es_store_wait_ready_deadline_and_stop_and_state():
    from foremast_tpu.jobs.store import ElasticsearchStore

    class _DownSession:
        def get(self, url, timeout=None):
            raise ConnectionError("refused")

    store = ElasticsearchStore("http://es:9200", session=_DownSession())
    t0 = time.monotonic()
    assert store.wait_ready(retry_seconds=0.05, max_wait=0.2) is False
    assert time.monotonic() - t0 < 5.0  # bounded, not forever
    state = store.connect_state
    assert state["connected"] is False
    assert state["attempts"] >= 2
    assert "ConnectionError" in state["last_error"]
    # clean shutdown: a stop request is honored between retries
    t0 = time.monotonic()
    assert (
        store.wait_ready(retry_seconds=30.0, stop=lambda: True) is False
    )
    assert time.monotonic() - t0 < 5.0


def test_breaker_abandoned_probe_reservation_self_heals():
    """A half-open probe whose caller died without recording an outcome
    (an unclassified exception between allow() and record_*) must not
    short-circuit the edge forever: past one cooldown the reservation
    is considered abandoned and a new probe may take over."""
    t = [0.0]
    br = CircuitBreaker(
        "es", failure_threshold=1, open_seconds=5.0, clock=lambda: t[0]
    )
    br.allow()
    br.record_failure()  # open
    t[0] = 6.0
    br.allow()  # probe reserved... and its caller dies silently
    with pytest.raises(BreakerOpen):
        br.allow()  # reservation held within the cooldown
    t[0] = 12.0  # a full cooldown later: reservation abandoned
    br.allow()  # a NEW probe takes over instead of BreakerOpen forever
    br.record_success()
    assert br.state == "closed"


def test_write_behind_claim_time_stamping_closes_takeover_window():
    """The worker stamps write-behind entries at the CLAIM instant: an
    entry buffered late in a slow tick still expires max_age after the
    CLAIM, so the replay can never land after a peer's stuck-claim
    takeover (the exactly-once net)."""
    t = [0.0]
    buf = WriteBehindBuffer(
        max_docs=8, max_age_seconds=10.0, clock=lambda: t[0]
    )
    claim_at = 0.0
    t[0] = 9.0  # the write failed 9s into the tick (slow fetch/judge)
    buf.add(["doc"], now=claim_at)  # stamped at claim, not at failure
    t[0] = 11.0  # 11s after the CLAIM: takeover owns the doc now
    assert buf.drain() == []  # dropped, never replayed


# ---------------------------------------------------------------------------
# the peer→peer `transfer` edge (ISSUE 11): planned handoff under chaos
# ---------------------------------------------------------------------------


def test_blackholed_transfer_degrades_to_cold_refit_not_deadlock():
    """A blackholed/faulted transfer edge must abandon the handoff
    (counted) and let the fenced joiner activate at its deadline — the
    moved partition cold-refits through the PR-6 rebalance path. The
    one forbidden outcome is a wedge: a sender tick stuck behind the
    transfer, or a joiner parked forever."""
    from foremast_tpu.mesh import HandoffManager
    from foremast_tpu.mesh.membership import MemberRecord

    plan = FaultPlan(
        rules=({"edge": "transfer", "error_rate": 1.0, "kind": "timeout"},),
        seed=77,
    ).activate(now=0.0)
    degrade = Degradation(chaos_plan=plan)
    t = [1000.0]
    slept = []
    h = HandoffManager(
        deadline_seconds=30.0, retries=1, backoff_seconds=0.1,
        chaos=plan.edge("transfer"),
        breaker=degrade.breakers.get("transfer"),
        clock=lambda: t[0], sleep=slept.append,
    )

    class _OneFit:
        def persistable_snapshot(self):
            return {("ma", 0, "appA|m0|http://x"): {"mu": 1.0}}

    class _Router:
        def transfer_target(self, route_key):
            return "w-j"

    h.register_caches({"fits": _OneFit()})
    ok = h.send_to(
        MemberRecord(worker_id="w-j", ingest_address="127.0.0.1:1"),
        _Router(), "w-s",
    )
    assert ok is False  # abandoned, not wedged
    c = h.counters_snapshot()
    assert c["send"]["failed"] == 1 and c["send"]["ok"] == 0
    assert plan.injections_snapshot().get(("transfer", "timeout"), 0) >= 1
    assert slept  # jittered backoff between the injected faults
    # the joiner side: fenced on this sender, activates at the deadline
    h2 = HandoffManager(deadline_seconds=30.0, clock=lambda: t[0])
    h2.begin_join({"w-s"})
    assert h2.join_ready({"w-s"}) is False
    t[0] = 1031.0
    assert h2.join_ready({"w-s"}) is True
    # ChaosCollector carries the new edge with no registration needed
    from prometheus_client import CollectorRegistry

    from foremast_tpu.observe.metrics_lint import lint_registry

    reg = CollectorRegistry()
    reg.register(ChaosCollector(degrade))
    assert lint_registry(reg) == []
    assert reg.get_sample_value(
        "foremast_chaos_injections_total",
        {"edge": "transfer", "kind": "timeout"},
    ) >= 1.0


def test_transfer_breaker_fails_fast_once_open():
    """Repeated transfer failures open the per-edge breaker: later
    sends short-circuit instead of burning the full timeout × retries
    on every joiner — and a later successful probe re-closes it."""
    from foremast_tpu.mesh import HandoffManager
    from foremast_tpu.mesh.membership import MemberRecord

    br = CircuitBreaker("transfer", failure_threshold=2, open_seconds=60.0)
    h = HandoffManager(
        deadline_seconds=5.0, retries=0, backoff_seconds=0.0,
        breaker=br, sleep=lambda s: None,
    )

    class _OneFit:
        def persistable_snapshot(self):
            return {("ma", 0, "appA|m0|http://x"): {"mu": 1.0}}

    class _Router:
        def transfer_target(self, route_key):
            return "w-j"

    h.register_caches({"fits": _OneFit()})
    rec = MemberRecord(worker_id="w-j", ingest_address="127.0.0.1:1")
    calls = [0]

    def refused(address, body):
        br.allow()
        calls[0] += 1
        try:
            raise ConnectionRefusedError("no receiver")
        except Exception:
            br.record_failure()
            raise

    h._post = refused
    assert h.send_to(rec, _Router(), "w-s") is False
    assert h.send_to(rec, _Router(), "w-s") is False
    assert br.state == "open"
    before = calls[0]
    # breaker open: the next send never reaches the wire
    assert h.send_to(rec, _Router(), "w-s") is False
    assert calls[0] == before
    assert h.counters_snapshot()["send"]["failed"] == 3


def test_transient_classification_unwraps_urlerror():
    """urllib wraps socket-level transport failures (connection
    refused/reset, DNS, timeouts) in URLError — a real unreachable
    handoff peer must classify TRANSIENT (retry, then degrade) rather
    than crash the sender's tick loop; HTTPError keeps its status
    semantics and a non-socket URLError stays a permanent error."""
    import socket
    import urllib.error

    from foremast_tpu.chaos.degrade import is_transient_error

    assert is_transient_error(
        urllib.error.URLError(ConnectionRefusedError(111, "refused"))
    )
    assert is_transient_error(
        urllib.error.URLError(socket.gaierror(-2, "unknown name"))
    )
    assert is_transient_error(
        urllib.error.HTTPError("http://x", 503, "unavailable", {}, None)
    )
    assert not is_transient_error(
        urllib.error.HTTPError("http://x", 400, "bad request", {}, None)
    )
    assert not is_transient_error(urllib.error.URLError("not an OSError"))
