"""HttpKube against a real HTTP fake API server (VERDICT r1 item 7).

Every method of the REST client — list/get/patch on builtin workloads,
event posting, CRD CRUD — exercised over the wire against
`tests/fake_kube_server.py`, including the error paths (404 -> NotFound,
409 Conflict, merge-patch content types) the in-memory substrate never
produces. The `foremast watch`/`unwatch` CLI and a WatchPlane step run
against the same server, so the plane's one real-cluster dependency has
real-socket coverage.
"""

import urllib.error

import pytest

from foremast_tpu.watch.crds import (
    API_VERSION,
    DeploymentMonitor,
    MonitorStatus,
)
from foremast_tpu.watch.kubeapi import HttpKube, NotFound
from tests.fake_kube_server import FakeKubeServer


@pytest.fixture()
def srv():
    with FakeKubeServer() as s:
        yield s


@pytest.fixture()
def kube(srv):
    return HttpKube(base_url=srv.url, token="test-token")


def _deployment(ns, name, image="app:v1"):
    return {
        "apiVersion": "apps/v1",
        "kind": "Deployment",
        "metadata": {"name": name, "namespace": ns, "labels": {"app": name}},
        "spec": {
            "template": {"spec": {"containers": [{"name": "c", "image": image}]}}
        },
    }


def test_builtin_workload_reads(srv, kube):
    st = srv.state
    st.put("namespaces", "", {"metadata": {"name": "prod"}})
    st.put("namespaces", "", {"metadata": {"name": "dev"}})
    st.put("deployments", "prod", _deployment("prod", "shop"))
    st.put("deployments", "dev", _deployment("dev", "cart"))
    st.put("replicasets", "prod", {"metadata": {"name": "shop-abc"}})
    st.put("pods", "prod", {"metadata": {"name": "shop-abc-1"}})

    assert {n["metadata"]["name"] for n in kube.list_namespaces()} == {
        "prod",
        "dev",
    }
    assert kube.get_namespace("prod")["metadata"]["name"] == "prod"
    assert len(kube.list_deployments()) == 2  # all namespaces
    assert [d["metadata"]["name"] for d in kube.list_deployments("prod")] == [
        "shop"
    ]
    assert kube.get_deployment("prod", "shop")["metadata"]["labels"] == {
        "app": "shop"
    }
    assert kube.list_replicasets("prod")[0]["metadata"]["name"] == "shop-abc"
    assert kube.list_pods("prod")[0]["metadata"]["name"] == "shop-abc-1"


def test_get_missing_raises_notfound(kube):
    with pytest.raises(NotFound):
        kube.get_deployment("prod", "ghost")
    with pytest.raises(NotFound):
        kube.get_namespace("ghost")
    with pytest.raises(NotFound):
        kube.get_monitor("prod", "ghost")
    with pytest.raises(NotFound):
        kube.get_metadata("prod", "ghost")


def test_patch_deployment_strategic_merge(srv, kube):
    srv.state.put("deployments", "prod", _deployment("prod", "shop"))
    out = kube.patch_deployment(
        "prod", "shop", {"spec": {"paused": True, "template": None}}
    )
    assert out["spec"]["paused"] is True
    assert "template" not in out["spec"]  # null deletes the key
    # the server only accepted it because the right content type was sent
    patches = [
        h for m, p, h in srv.state.requests if m == "PATCH" and "shop" in p
    ]
    assert patches[0]["Content-Type"] == "application/strategic-merge-patch+json"


def test_patch_missing_deployment_raises_notfound(kube):
    with pytest.raises(NotFound):
        kube.patch_deployment("prod", "ghost", {"spec": {"paused": True}})


def test_create_event(srv, kube):
    kube.create_event(
        "prod", {"metadata": {"name": "ev1"}, "reason": "Unhealthy"}
    )
    assert ("prod", "ev1") in srv.state.objects["events"]


def test_bearer_token_sent(srv, kube):
    srv.state.put("namespaces", "", {"metadata": {"name": "prod"}})
    kube.get_namespace("prod")
    assert all(
        h.get("Authorization") == "Bearer test-token"
        for _, _, h in srv.state.requests
    )


def _monitor(ns, name, continuous=False):
    return DeploymentMonitor(
        namespace=ns,
        name=name,
        continuous=continuous,
        status=MonitorStatus(job_id="job-1", phase="Running"),
    )


def test_monitor_crud_roundtrip(srv, kube):
    created = kube.upsert_monitor(_monitor("prod", "shop"))  # POST path
    assert created.name == "shop"
    assert kube.get_monitor("prod", "shop").status.job_id == "job-1"
    assert [m.name for m in kube.list_monitors("prod")] == ["shop"]
    assert [m.name for m in kube.list_monitors()] == ["shop"]

    updated = kube.upsert_monitor(_monitor("prod", "shop", continuous=True))
    assert updated.continuous is True  # PUT path with fresh rv

    patched = kube.patch_monitor(
        "prod", "shop", {"spec": {"continuous": False}}
    )
    assert patched.continuous is False
    assert patched.status.job_id == "job-1"  # merge-patch left status alone

    kube.delete_monitor("prod", "shop")
    with pytest.raises(NotFound):
        kube.get_monitor("prod", "shop")
    kube.delete_monitor("prod", "shop")  # idempotent: swallowed 404


def test_upsert_conflict_surfaces_409(srv, kube):
    kube.upsert_monitor(_monitor("prod", "shop"))
    # sabotage: the server's object advances between GET and PUT
    orig = srv.state.objects["deploymentmonitors"][("prod", "shop")]
    done = {}

    class RacingKube(HttpKube):
        def _req(self, method, path, body=None, content_type="application/json"):
            out = super()._req(method, path, body, content_type)
            if method == "GET" and not done:
                done["raced"] = True
                with srv.state.lock:
                    orig["metadata"]["resourceVersion"] = srv.state.next_rv()
            return out

    racing = RacingKube(base_url=srv.url)
    with pytest.raises(urllib.error.HTTPError) as ei:
        racing.upsert_monitor(_monitor("prod", "shop", continuous=True))
    assert ei.value.code == 409


def test_metadata_read(srv, kube):
    srv.state.put(
        "deploymentmetadatas",
        "prod",
        {
            "apiVersion": API_VERSION,
            "kind": "DeploymentMetadata",
            "metadata": {"name": "shop", "namespace": "prod"},
            "spec": {
                "analyst": {"endpoint": "http://svc:8099/v1/healthcheck/"},
                "metrics": {
                    "dataSourceType": "prometheus",
                    "endpoint": "http://prom:9090/",
                    "monitoring": [
                        {
                            "metricName": "namespace_pod:http_server_requests_error_5xx",
                            "metricType": "error5xx",
                            "metricAlias": "error5xx",
                        }
                    ],
                },
            },
        },
    )
    md = kube.get_metadata("prod", "shop")
    assert md.analyst_endpoint == "http://svc:8099/v1/healthcheck/"
    assert md.monitoring[0].metric_type == "error5xx"


def test_cli_watch_unwatch_against_real_server(srv, capsys):
    """`foremast watch/unwatch` (kubectl-watch parity) over a real socket."""
    from foremast_tpu.cli import main

    with FakeKubeServer() as s:
        HttpKube(base_url=s.url).upsert_monitor(_monitor("prod", "shop"))
        rc = main(
            ["watch", "shop", "--namespace", "prod", "--api-server", s.url]
        )
        assert rc == 0
        assert "watching application shop" in capsys.readouterr().out
        mon = HttpKube(base_url=s.url).get_monitor("prod", "shop")
        assert mon.continuous is True

        rc = main(
            ["unwatch", "shop", "--namespace", "prod", "--api-server", s.url]
        )
        assert rc == 0
        assert not HttpKube(base_url=s.url).get_monitor("prod", "shop").continuous

        rc = main(
            ["watch", "ghost", "--namespace", "prod", "--api-server", s.url]
        )
        assert rc == 1  # NotFound -> exit code 1


def test_watch_plane_step_against_real_server(srv):
    """One WatchPlane step over HttpKube: a labeled deployment in a watched
    namespace gets its DeploymentMonitor created through the real REST
    path (informer resync -> upsert)."""
    from foremast_tpu.watch.plane import WatchPlane

    st = srv.state
    st.put("namespaces", "", {"metadata": {"name": "prod"}})
    st.put(
        "deploymentmetadatas",
        "prod",
        {
            "apiVersion": API_VERSION,
            "kind": "DeploymentMetadata",
            "metadata": {"name": "shop", "namespace": "prod"},
            "spec": {
                "analyst": {"endpoint": "http://svc:8099/v1/healthcheck/"},
                "metrics": {
                    "dataSourceType": "prometheus",
                    "endpoint": "http://prom:9090/",
                    "monitoring": [
                        {
                            "metricName": "namespace_pod:http_server_requests_error_5xx",
                            "metricType": "error5xx",
                            "metricAlias": "error5xx",
                        }
                    ],
                },
            },
        },
    )
    st.put("deployments", "prod", _deployment("prod", "shop"))

    kube = HttpKube(base_url=srv.url)
    plane = WatchPlane(kube, own_namespace="foremast")
    plane.step(now=1_700_000_000.0)
    monitors = kube.list_monitors("prod")
    assert [m.name for m in monitors] == ["shop"]


# ---------------------------------------------------------------------------
# transient-retry policy + timeouts (ISSUE 9 satellite) — driven through
# the REAL server's fault hooks, not monkeypatched clients
# ---------------------------------------------------------------------------


def test_httpkube_retries_transient_5xx_then_succeeds(srv):
    srv.state.put("namespaces", "", {"metadata": {"name": "prod"}})
    srv.state.add_fault(path="/api/v1/namespaces", status=503, times=2)
    kube = HttpKube(base_url=srv.url, retries=2, backoff_seconds=0.001)
    names = [n["metadata"]["name"] for n in kube.list_namespaces()]
    assert names == ["prod"]
    # 2 faulted attempts + 1 clean one reached the server
    assert len([r for r in srv.state.requests if "namespaces" in r[1]]) == 3


def test_httpkube_retries_429_and_exhausts_budget(srv):
    srv.state.add_fault(path="/api/v1/namespaces", status=429)  # forever
    kube = HttpKube(base_url=srv.url, retries=1, backoff_seconds=0.001)
    with pytest.raises(urllib.error.HTTPError) as ei:
        kube.list_namespaces()
    assert ei.value.code == 429
    assert len([r for r in srv.state.requests if "namespaces" in r[1]]) == 2


def test_httpkube_hard_4xx_fails_fast_no_retry(srv):
    srv.state.add_fault(path="/api/v1/namespaces", status=403)
    kube = HttpKube(base_url=srv.url, retries=3, backoff_seconds=0.001)
    with pytest.raises(urllib.error.HTTPError) as ei:
        kube.list_namespaces()
    assert ei.value.code == 403
    assert len([r for r in srv.state.requests if "namespaces" in r[1]]) == 1


def test_httpkube_404_stays_notfound_after_faulted_retry(srv):
    srv.state.add_fault(path="/deployments/", status=502, times=1)
    kube = HttpKube(base_url=srv.url, retries=2, backoff_seconds=0.001)
    with pytest.raises(NotFound):
        kube.get_deployment("prod", "ghost")


def test_httpkube_explicit_timeout_and_knobs(monkeypatch):
    monkeypatch.setenv("FOREMAST_KUBE_TIMEOUT_SECONDS", "7.5")
    monkeypatch.setenv("FOREMAST_FETCH_RETRIES", "4")
    kube = HttpKube(base_url="http://unused:1")
    assert kube.timeout == 7.5
    assert kube.retries == 4
    monkeypatch.delenv("FOREMAST_KUBE_TIMEOUT_SECONDS")
    monkeypatch.delenv("FOREMAST_FETCH_RETRIES")
    kube = HttpKube(base_url="http://unused:1", timeout=3.0, retries=0)
    assert kube.timeout == 3.0 and kube.retries == 0


def test_httpkube_breaker_opens_on_connection_refused():
    """A dead API server opens the kube breaker; further calls fail in
    microseconds instead of paying connect timeouts."""
    from foremast_tpu.chaos import BreakerOpen, CircuitBreaker

    br = CircuitBreaker("kube", failure_threshold=2, open_seconds=60.0)
    # 127.0.0.1:1 refuses connections immediately
    kube = HttpKube(
        base_url="http://127.0.0.1:1", retries=0,
        backoff_seconds=0.001, timeout=0.2, breaker=br,
    )
    for _ in range(2):
        with pytest.raises(OSError):
            kube.list_namespaces()
    with pytest.raises(BreakerOpen):
        kube.list_namespaces()


def test_httpkube_latency_fault_hook_respects_timeout(srv):
    """The fake server's latency hook + the client's explicit timeout:
    a hung API server surfaces as a timeout error, not a forever-wait."""
    srv.state.add_fault(path="/api/v1/namespaces", latency=1.5)
    kube = HttpKube(base_url=srv.url, timeout=0.2, retries=0)
    with pytest.raises(OSError):
        kube.list_namespaces()
