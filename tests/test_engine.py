"""Engine tests: batched judgment semantics + golden-trace parity.

The de-facto integration test of the reference is the demo runbook: roll a
v2 with injected errors and assert the monitor goes Unhealthy
(`docs/guides/installation.md:84-143`), driven by the deterministic CSV
traces data1.txt (normal) / data2.txt (spike) — SURVEY.md section 4. Here the
same traces drive the batched judge: the spike trace must be flagged
unhealthy with the spike points in the anomaly payload, the normal trace
must pass.
"""

import numpy as np
import pytest

from foremast_tpu.config import BrainConfig, PairwiseConfig
from foremast_tpu.engine import (
    HEALTHY,
    UNHEALTHY,
    UNKNOWN,
    HealthJudge,
    MetricTask,
    combine_verdicts,
)


def _task(job, alias, hist, cur, base=None, mtype=None):
    def tv(arr):
        arr = np.asarray(arr, np.float32)
        t = 1700000000 + 60 * np.arange(len(arr), dtype=np.int64)
        return t, arr

    ht, hv = tv(hist)
    ct, cv = tv(cur)
    kw = {}
    if base is not None:
        bt, bv = tv(base)
        kw = dict(base_times=bt, base_values=bv)
    return MetricTask(
        job_id=job,
        alias=alias,
        metric_type=mtype,
        hist_times=ht,
        hist_values=hv,
        cur_times=ct,
        cur_values=cv,
        **kw,
    )


@pytest.fixture(scope="module")
def judge():
    return HealthJudge(BrainConfig())


def test_healthy_flat_series(judge):
    rng = np.random.default_rng(0)
    hist = 0.5 + 0.05 * rng.standard_normal(200)
    cur = 0.5 + 0.05 * rng.standard_normal(10)
    [v] = judge.judge([_task("j1", "latency", hist, cur)])
    assert v.verdict == HEALTHY
    assert v.anomaly_pairs == []


def test_spike_flags_unhealthy_with_pairs(judge):
    rng = np.random.default_rng(1)
    hist = 0.5 + 0.05 * rng.standard_normal(200)
    cur = 0.5 + 0.05 * rng.standard_normal(10)
    cur[4] = 40.0  # the demo's 40.134-style spike
    [v] = judge.judge([_task("j2", "error5xx", hist, cur)])
    assert v.verdict == UNHEALTHY
    # flat [t, v, t, v...] pairs, reference Barrelman.go:605-615
    assert len(v.anomaly_pairs) % 2 == 0 and v.anomaly_pairs
    flagged = v.anomaly_pairs[1::2]
    assert pytest.approx(40.0) in flagged
    # pair times line up with the current window's timestamps
    assert all(t >= 1700000000 for t in v.anomaly_pairs[0::2])


def test_too_little_history_is_unknown(judge):
    [v] = judge.judge([_task("j3", "m", [0.5] * 3, [0.5] * 5)])
    assert v.verdict == UNKNOWN


def test_golden_traces(demo_traces):
    """Reference demo parity: data2 spike trace unhealthy, data1 healthy.

    Scored at the error4xx threshold (t=3, foremast-brain.yaml:44-49): the
    normal trace's own 0.666 max sits just past 2 sigma of its mean, so the
    deployed t=2 error5xx row would flag it; at t=3 separation is exact.
    """
    nt, nv = demo_traces["normal"]
    st, sv = demo_traces["spike"]
    # history = the normal trace tiled (stable ~0.1-0.6 signal)
    hist = np.tile(nv, 6)
    tasks = [
        _task("g1", "error4xx", hist, nv, mtype="error4xx"),
        _task("g2", "error4xx", hist, sv, mtype="error4xx"),
    ]
    judge = HealthJudge(BrainConfig())
    v_norm, v_spike = judge.judge(tasks)
    assert v_norm.verdict == HEALTHY
    assert v_spike.verdict == UNHEALTHY
    flagged_values = v_spike.anomaly_pairs[1::2]
    assert any(val > 30 for val in flagged_values)  # the 40.134 spike caught
    # F1 parity on this trace: exactly the spike points flagged, no false
    # positives on the normal trace => precision = recall = 1.0
    assert v_norm.anomaly_pairs == []


def test_pairwise_lowers_threshold():
    """A shifted canary distribution tightens bounds (design.md:33)."""
    rng = np.random.default_rng(2)
    hist = 1.0 + 0.1 * rng.standard_normal(500)
    base = 1.0 + 0.1 * rng.standard_normal(30)
    # current shifted up but below the nominal threshold*std band
    cur = 1.18 + 0.1 * rng.standard_normal(30)
    cfg = BrainConfig()
    judge = HealthJudge(cfg)
    with_base = judge.judge([_task("p1", "m", hist, cur, base=base)])[0]
    without = judge.judge([_task("p2", "m", hist, cur)])[0]
    assert with_base.dist_differs
    assert not without.dist_differs
    # tightened band => upper bound strictly inside the nominal one
    assert np.all(with_base.upper <= without.upper + 1e-6)
    assert with_base.upper.mean() < without.upper.mean()


def test_batch_mixed_lengths_buckets():
    judge = HealthJudge(BrainConfig())
    rng = np.random.default_rng(3)
    tasks = []
    for i, (hl, cl) in enumerate([(50, 10), (200, 10), (50, 40), (1000, 30)]):
        hist = 0.5 + 0.05 * rng.standard_normal(hl)
        cur = 0.5 + 0.05 * rng.standard_normal(cl)
        tasks.append(_task(f"b{i}", "m", hist, cur, mtype="latency"))
    vs = judge.judge(tasks)
    assert len(vs) == 4
    assert [v.job_id for v in vs] == ["b0", "b1", "b2", "b3"]
    assert all(v.verdict == HEALTHY for v in vs)


def test_combine_verdicts_fail_fast():
    class V:
        def __init__(self, v):
            self.verdict = v

    assert combine_verdicts([V(HEALTHY), V(UNHEALTHY)]) == UNHEALTHY
    assert combine_verdicts([V(HEALTHY), V(UNKNOWN)]) == HEALTHY
    assert combine_verdicts([V(UNKNOWN), V(UNKNOWN)]) == UNKNOWN
    assert combine_verdicts([]) == UNKNOWN


def test_per_metric_type_threshold_applies():
    """latency rows use t=10/bound=both; cpu rows t=5/upper."""
    rng = np.random.default_rng(4)
    hist = 1.0 + 0.1 * rng.standard_normal(300)
    cur = np.full(10, 1.65, np.float32)  # +6.5 sigma
    judge = HealthJudge(BrainConfig())
    v_lat, v_cpu = judge.judge(
        [
            _task("t1", "m", hist, cur, mtype="latency"),
            _task("t2", "m", hist, cur, mtype="cpu"),
        ]
    )
    assert v_lat.verdict == HEALTHY  # within 10 sigma
    assert v_cpu.verdict == UNHEALTHY  # beyond 5 sigma


def test_lower_bound_detection():
    """bound=both also catches drops (e.g. tps collapse)."""
    rng = np.random.default_rng(5)
    hist = 10.0 + 0.2 * rng.standard_normal(300)
    cur = np.full(10, 10.0, np.float32)
    cur[5] = 0.5  # traffic collapse
    from foremast_tpu.config import AnomalyConfig, MetricTypeRule
    from foremast_tpu.ops.anomaly import BOUND_BOTH

    cfg = BrainConfig(
        anomaly=AnomalyConfig(rules=(MetricTypeRule("tps", 5.0, BOUND_BOTH, 0.0),))
    )
    [v] = HealthJudge(cfg).judge([_task("lb", "m", hist, cur, mtype="tps")])
    assert v.verdict == UNHEALTHY
    assert v.anomaly_pairs[1] == pytest.approx(0.5)


def test_bucketing_bounds_compiles_for_ragged_tasks():
    """SURVEY 'hard part' (b): heterogeneous window lengths must compile a
    handful of programs, not one per job. 60 random-length tasks may
    produce at most ~log2 distinct (hist, cur) buckets."""
    import numpy as np

    from foremast_tpu.engine.judge import HealthJudge, MetricTask, bucket_length

    rng = np.random.default_rng(0)
    tasks = []
    buckets = set()
    for i in range(60):
        nh = int(rng.integers(3, 700))
        nc = int(rng.integers(1, 40))
        ht = 1_700_000_000 + 60 * np.arange(nh, dtype=np.int64)
        ct = ht[-1] + 60 * np.arange(1, nc + 1, dtype=np.int64)
        tasks.append(
            MetricTask(
                job_id=f"j{i}",
                alias="m",
                metric_type=None,
                hist_times=ht,
                hist_values=rng.normal(1.0, 0.1, nh).astype(np.float32),
                cur_times=ct,
                cur_values=rng.normal(1.0, 0.1, nc).astype(np.float32),
            )
        )
        buckets.add((bucket_length(nh), bucket_length(nc)))

    assert len(buckets) <= 24  # powers of two: ~7 hist x ~3 cur at most
    verdicts = HealthJudge().judge(tasks)
    assert len(verdicts) == 60
    assert {v.job_id for v in verdicts} == {t.job_id for t in tasks}


# -- univariate fit cache ----------------------------------------------------


def _hw_task(job, rng, spike=False, fit_key=None):
    import dataclasses

    t = np.arange(24 * 12, dtype=np.float32)
    hist = (5 + 2 * np.sin(2 * np.pi * t / 24) + rng.normal(0, 0.1, len(t))).astype(
        np.float32
    )
    cur = (5 + 2 * np.sin(2 * np.pi * (len(t) + np.arange(10)) / 24)).astype(
        np.float32
    )
    if spike:
        cur = cur.copy()
        cur[4] = 40.0
    task = _task(job, "latency", hist, cur)
    return dataclasses.replace(task, fit_key=fit_key)


def test_fit_cache_reuses_fit_and_matches_fresh_results():
    """Two judgments with the same fit_key: the second must not re-fit,
    and cached verdicts must equal fresh-fit verdicts exactly."""
    from foremast_tpu.engine import scoring
    from foremast_tpu.models.cache import ModelCache

    rng = np.random.default_rng(0)
    cfg = BrainConfig(algorithm="holt_winters", season_steps=24)
    plain = HealthJudge(cfg)
    cached = HealthJudge(cfg)
    cached.fit_cache = ModelCache(8)

    tasks = [
        _hw_task("j1", rng, fit_key="app|latency|u1"),
        _hw_task("j2", rng, spike=True, fit_key="app2|latency|u2"),
    ]
    ref = plain.judge(tasks)
    got1 = cached.judge(tasks)
    # two real fits + the single constant batch-padding entry
    real = [k for k in cached.fit_cache._d if k[-1] != "__pad__"]
    assert len(real) == 2 and len(cached.fit_cache) == 3

    # second tick: same histories, new job ids -> no fitting at all
    import dataclasses

    tasks2 = [dataclasses.replace(t, job_id=t.job_id + "b") for t in tasks]
    orig = scoring.fit_forecast
    orig16 = scoring.fit_forecast_bf16_delta

    def boom(*a, **k):  # pragma: no cover - failure path
        raise AssertionError("fit ran despite warm cache")

    scoring.fit_forecast = boom
    scoring.fit_forecast_bf16_delta = boom  # bf16-delta fit path too
    try:
        got2 = cached.judge(tasks2)
    finally:
        scoring.fit_forecast = orig
        scoring.fit_forecast_bf16_delta = orig16

    for a, b in zip(ref, got1):
        assert a.verdict == b.verdict
        assert a.anomaly_pairs == b.anomaly_pairs
        # rtol covers the bf16-delta cold-fit upload (default on):
        # deviations carry ~3 significant digits and HW's sequential
        # scan compounds the rounding slightly (measured ~6e-4 rel);
        # verdicts/pairs stay exact, band geometry is gated at 2%
        np.testing.assert_allclose(a.upper, b.upper, rtol=5e-3)
        assert a.p_value == pytest.approx(b.p_value)
    for a, b in zip(got1, got2):
        assert a.verdict == b.verdict
        assert a.anomaly_pairs == b.anomaly_pairs


def test_fit_cache_mixed_keyed_and_unkeyed_batch():
    """Tasks without fit_key ride the same batch (fitted fresh each time)
    and never pollute the cache."""
    from foremast_tpu.models.cache import ModelCache

    rng = np.random.default_rng(1)
    cfg = BrainConfig(algorithm="holt_winters", season_steps=24)
    judge = HealthJudge(cfg)
    judge.fit_cache = ModelCache(8)
    tasks = [
        _hw_task("k", rng, fit_key="app|latency|u1"),
        _hw_task("n", rng, spike=True),  # no key
    ]
    ref = HealthJudge(cfg).judge(tasks)
    got = judge.judge(tasks)
    real = [k for k in judge.fit_cache._d if k[-1] != "__pad__"]
    assert len(real) == 1  # the unkeyed task never entered the cache
    for a, b in zip(ref, got):
        assert a.verdict == b.verdict
        assert a.anomaly_pairs == b.anomaly_pairs


def test_fit_cache_caches_cheap_fits_too():
    """The deployed default (moving_average_all) caches terminal state
    like every other algorithm: the fit FLOPs are trivial, but a cached
    fit is what lets a warm re-check tick skip packing and uploading the
    [B, 10080] history (the dominant warm-tick cost on the shipped
    path). Cached verdicts must equal fresh-fit verdicts exactly."""
    from foremast_tpu.models.cache import ModelCache

    rng = np.random.default_rng(2)
    judge = HealthJudge(BrainConfig())  # default moving_average_all
    judge.fit_cache = ModelCache(8)
    task = _hw_task("j", rng, spike=True, fit_key="app|latency|u1")
    ref = HealthJudge(BrainConfig()).judge([task])
    got1 = judge.judge([task])
    real = [k for k in judge.fit_cache._d if k[-1] != "__pad__"]
    assert len(real) == 1  # + the constant batch-padding entry
    got2 = judge.judge([task])  # warm: arena replay path
    for a, b in zip(ref, got1):
        assert a.verdict == b.verdict
        assert a.anomaly_pairs == b.anomaly_pairs
        # rtol covers the bf16-delta cold-fit upload (default on)
        np.testing.assert_allclose(a.upper, b.upper, rtol=1e-4)
    for a, b in zip(got1, got2):
        assert a.verdict == b.verdict
        assert a.anomaly_pairs == b.anomaly_pairs


def test_worker_sets_fit_key_only_for_settled_histories():
    """The worker keys fits by (app, alias, URL) only when the historical
    range's end is safely in the past (same admission as the history
    cache) — mutable ranges must be re-fit every tick."""
    from foremast_tpu.jobs.store import InMemoryStore
    from foremast_tpu.jobs.worker import BrainWorker
    from foremast_tpu.metrics.source import ReplaySource
    from foremast_tpu.jobs.models import Document

    now = 1_700_000_000.0
    src = ReplaySource()
    t = np.arange(64, dtype=np.int64) * 60 + int(now) - 864000
    v = np.ones(64, np.float32)
    src.register("q", (t, v))
    w = BrainWorker(InMemoryStore(), src, BrainConfig(algorithm="holt_winters", season_steps=24))
    doc = Document(
        id="d1", app_name="demo", status="initial",
        current_config="m== http://p/q?query=x&start=1&end=2&step=60",
        historical_config=(
            f"m== http://p/q?query=x&start=1&end={int(now)-86400}&step=60"
        ),
    )
    tasks = w._fetch_tasks(doc, now)
    assert tasks[0].fit_key == (
        f"demo|m|http://p/q?query=x&start=1&end={int(now)-86400}&step=60"
    )
    # future-ending history: no fit key
    doc2 = Document(
        id="d2", app_name="demo", status="initial",
        current_config="m== http://p/q?query=x&start=1&end=2&step=60",
        historical_config=(
            f"m== http://p/q?query=x&start=1&end={int(now)+600}&step=60"
        ),
    )
    tasks2 = w._fetch_tasks(doc2, now)
    assert tasks2[0].fit_key is None
    # the worker attaches its fit cache to the univariate judge
    assert w.judge.univariate.fit_cache is w._fit_cache


def test_seasonal_phase_advances_across_hist_cur_gap():
    """A re-check tick whose current window starts LATER than one step
    after the history's end must be judged at the advanced seasonal
    phase (ADVICE r2: score_from_state used to replay the stale phase).
    Both the fresh path and the warm fit-cache path must agree."""
    from foremast_tpu.models.cache import ModelCache

    rng = np.random.default_rng(4)
    n, m, tc, gap = 24 * 12, 24, 10, 6  # quarter-cycle drift
    t = np.arange(n, dtype=np.float64)
    hist = (5 + 2 * np.sin(2 * np.pi * t / m)
            + rng.normal(0, 0.05, n)).astype(np.float32)
    ht = 1_700_000_000 + 60 * np.arange(n, dtype=np.int64)

    def task(job, start_idx, cur_start_ts):
        tcur = start_idx + np.arange(tc, dtype=np.float64)
        cur = (5 + 2 * np.sin(2 * np.pi * tcur / m)).astype(np.float32)
        ct = cur_start_ts + 60 * np.arange(tc, dtype=np.int64)
        return MetricTask(
            job_id=job, alias="latency", metric_type="latency",
            hist_times=ht, hist_values=hist,
            cur_times=ct, cur_values=cur,
            fit_key="app|latency|u1",
        )

    late_ts = ht[-1] + 60 * (gap + 1)
    aligned = task("ok", n + gap, late_ts)  # true values at the true time
    stale = task("bad", n, late_ts)  # values from the pre-gap phase

    cfg = BrainConfig(algorithm="holt_winters", season_steps=m)
    fresh = HealthJudge(cfg).judge([aligned, stale])
    assert fresh[0].verdict == HEALTHY
    assert fresh[1].verdict == UNHEALTHY

    cached = HealthJudge(cfg)
    cached.fit_cache = ModelCache(8)
    warm_fill = cached.judge([aligned])  # fills the cache
    assert warm_fill[0].verdict == HEALTHY
    warm = cached.judge([aligned, stale])  # warm: score_from_state path
    assert [v.verdict for v in warm] == [v.verdict for v in fresh]


def test_pairwise_friedman_selector_and_combiners():
    """FRIEDMAN as a first-class ML_PAIRWISE_ALGORITHM choice: a clean
    level shift (every pair moves the same way) is exactly Friedman's
    strength; ANY/ALL include it (design.md:90-93 lists all four)."""
    import jax.numpy as jnp

    from foremast_tpu.config import PAIRWISE_FRIEDMAN
    from foremast_tpu.engine import scoring
    from foremast_tpu.ops.windows import MetricWindows

    rng = np.random.default_rng(5)
    n = 32
    base = rng.normal(1.0, 0.1, (2, n)).astype(np.float32)
    cur = base.copy()
    cur[1] = base[1] + 0.25  # shifted row: every pair increases

    def win(v):
        return MetricWindows(
            values=jnp.asarray(v),
            mask=jnp.ones(v.shape, bool),
            times=jnp.zeros(v.shape, jnp.int32),
        )

    p, differs = scoring.pairwise(
        win(cur), win(base),
        algorithm=PAIRWISE_FRIEDMAN, p_threshold=0.05,
        min_mw=20, min_wilcoxon=20, min_kruskal=5, min_friedman=20,
    )
    assert not bool(differs[0]) and float(p[0]) > 0.05
    assert bool(differs[1]) and float(p[1]) < 0.05
    # combiners include the fourth test
    for combo in ("ANY", "ALL"):
        p2, d2 = scoring.pairwise(
            win(cur), win(base),
            algorithm=combo, p_threshold=0.05,
            min_mw=20, min_wilcoxon=20, min_kruskal=5, min_friedman=20,
        )
        assert bool(d2[1]), combo
        assert not bool(d2[0]), combo


def test_judge_buckets_batch_axis_to_bound_compiles():
    """Production claim sizes vary tick to tick; the judge must pad the
    BATCH axis to its power-of-two bucket so XLA compiles one program
    per (B, Th, Tc) bucket triple, not one per claim size (a fresh
    compile is 20-40 s on a TPU). Verdicts for the real rows must be
    unaffected and pad rows never surface."""
    from foremast_tpu.engine import scoring as scoring_mod

    rng = np.random.default_rng(14)

    def mk(n):
        return [
            _task(
                f"j{i}",
                "m",
                rng.normal(1.0, 0.1, 120).astype(np.float32),
                rng.normal(1.0, 0.1, 10).astype(np.float32),
                mtype="latency",  # threshold 10: noise never flags
            )
            for i in range(n)
        ]

    judge = HealthJudge(BrainConfig())
    seen_batch_sizes = []
    orig = scoring_mod.score

    def spy(batch, **kw):
        seen_batch_sizes.append(batch.current.values.shape[0])
        return orig(batch, **kw)

    scoring_mod.score = spy
    try:
        for n in (5, 6, 7, 8):
            vs = judge.judge(mk(n))
            assert len(vs) == n
            assert all(v.verdict == HEALTHY for v in vs)
            assert not any(v.job_id == "__pad__" for v in vs)
    finally:
        scoring_mod.score = orig
    # every claim size landed in the same compiled-shape bucket
    assert seen_batch_sizes == [8, 8, 8, 8]


def test_fit_cache_arena_reuse_and_invalidation():
    """Warm ticks gather device-resident arena rows (zero state upload);
    any fit-cache miss — e.g. an evicted entry — must refit that row and
    force-scatter it over the stale device row, producing identical
    verdicts."""
    from foremast_tpu.models.cache import ModelCache

    rng = np.random.default_rng(9)
    cfg = BrainConfig(algorithm="holt_winters", season_steps=24)
    judge = HealthJudge(cfg)
    judge.fit_cache = ModelCache(16)
    tasks = [
        _hw_task(f"j{i}", rng, spike=(i == 2), fit_key=f"a{i}|m|u{i}")
        for i in range(4)
    ]
    ref = [v.verdict for v in judge.judge(tasks)]  # cold: fit + scatter
    (arena,) = judge._arenas.values()
    rows_after_cold = dict(arena.rows)
    scattered_cold = arena.misses
    warm = [v.verdict for v in judge.judge(tasks)]  # pure gather
    assert arena.misses == scattered_cold  # nothing re-scattered
    assert arena.rows == rows_after_cold  # stable row assignment
    again = [v.verdict for v in judge.judge(tasks)]
    assert ref == warm == again
    assert ref[2] == UNHEALTHY and ref[0] == HEALTHY
    hits_before = arena.hits

    # evict one entry: the next tick MUST refit that row and overwrite
    # the stale device row (a silent gather of it would be wrong if the
    # refit differed), while the other rows stay warm gathers
    judge.fit_cache.pop((cfg.algorithm, cfg.season_steps, "a1|m|u1"))
    after = [v.verdict for v in judge.judge(tasks)]
    assert after == ref
    assert arena.misses == scattered_cold + 1  # exactly the evicted row
    assert arena.hits > hits_before  # the rest were gathers


def test_arena_churn_rescatters_only_changed_rows():
    """VERDICT r3 item 3: a churned claim set (jobs finishing/arriving,
    claim-order jitter) must re-upload only the CHANGED rows — round 3's
    ordered-tuple stack key silently re-paid the full restack on any
    churn. Also pins verdict correctness under rotation + reordering."""
    from foremast_tpu.models.cache import ModelCache

    rng = np.random.default_rng(11)
    cfg = BrainConfig(algorithm="holt_winters", season_steps=24)
    judge = HealthJudge(cfg)
    judge.fit_cache = ModelCache(64)
    tasks = [
        _hw_task(f"j{i}", rng, spike=(i == 2), fit_key=f"a{i}|m|u{i}")
        for i in range(10)
    ]
    ref = {v.job_id: v.verdict for v in judge.judge(tasks)}
    (arena,) = judge._arenas.values()
    base_misses = arena.misses

    # 10% churn: one job leaves, one arrives, order shuffles
    newcomer = _hw_task("j10", rng, fit_key="a10|m|u10")
    churned = tasks[1:] + [newcomer]
    rng.shuffle(churned)
    got = {v.job_id: v.verdict for v in judge.judge(churned)}
    # ONLY the newcomer's row was scattered (plus nothing for survivors)
    assert arena.misses == base_misses + 1
    for t in tasks[1:]:
        assert got[t.job_id] == ref[t.job_id]
    assert got["j10"] == HEALTHY

    # the departed job's row still exists until evicted by pressure;
    # re-claiming it later is a pure gather, not a refit
    before = arena.misses
    got2 = {v.job_id: v.verdict for v in judge.judge(tasks)}
    assert arena.misses == before
    assert got2 == ref


def test_arena_auto_grows_past_soft_budget(monkeypatch):
    """VERDICT r4 #3 (the daily-season cliff): a batch larger than the
    soft byte budget must GROW the arena toward the hard cap instead of
    silently falling back to a per-tick full restack — an LRU arena
    smaller than the working set thrashes (every access misses)."""
    from foremast_tpu.engine.arena import StateArena, _row_bytes

    monkeypatch.setenv("FOREMAST_ARENA_BYTES", str(8 * _row_bytes(24)))
    monkeypatch.setenv(
        "FOREMAST_ARENA_MAX_BYTES", str(32 * _row_bytes(24))
    )
    a = StateArena(24)
    assert a.max_rows == 8 and a.hard_rows == 32
    got = a.assign([f"k{i}" for i in range(16)], range(16))
    assert got is not None, "must auto-grow, not refuse"
    assert a.max_rows == 16
    # past the hard cap: refuse up front (counted by the judge), with no
    # partial row mutation
    rows_before = dict(a.rows)
    assert a.assign([f"x{i}" for i in range(64)], range(64)) is None
    assert a.rows == rows_before


def test_arena_fallback_is_counted_and_verdicts_survive(monkeypatch):
    """When a batch exceeds even the hard cap, the judge falls back to a
    one-off stacked score: verdicts must be unchanged and the fallback
    must be COUNTED (VERDICT r4: the silent-fallback cliff)."""
    from foremast_tpu.models.cache import ModelCache

    rng = np.random.default_rng(13)
    cfg = BrainConfig(algorithm="holt_winters", season_steps=24)
    ref_judge = HealthJudge(cfg)
    ref_judge.fit_cache = ModelCache(64)
    tasks = [
        _hw_task(f"j{i}", rng, spike=(i == 2), fit_key=f"a{i}|m|u{i}")
        for i in range(12)
    ]
    ref = [v.verdict for v in ref_judge.judge(tasks)]

    from foremast_tpu.engine.arena import _row_bytes

    monkeypatch.setenv("FOREMAST_ARENA_BYTES", str(8 * _row_bytes(24)))
    monkeypatch.setenv("FOREMAST_ARENA_MAX_BYTES", str(8 * _row_bytes(24)))
    judge = HealthJudge(cfg)
    judge.fit_cache = ModelCache(64)
    got = [v.verdict for v in judge.judge(tasks)]  # 12 -> 16-row bucket
    assert got == ref
    c = judge.device_state_counters()
    assert c["fallbacks"] >= 1
    got2 = [v.verdict for v in judge.judge(tasks)]
    assert got2 == ref
    assert judge.device_state_counters()["fallbacks"] > c["fallbacks"]


def test_device_state_counters_monotone_across_rebuilds():
    """ADVICE r4: clear_device_state / widen rebuilds must not move the
    cumulative counters backwards — retired arenas fold into a base so
    the gauge exporter can export plain deltas."""
    from foremast_tpu.models.cache import ModelCache

    rng = np.random.default_rng(17)
    cfg = BrainConfig(algorithm="holt_winters", season_steps=24)
    judge = HealthJudge(cfg)
    judge.fit_cache = ModelCache(64)
    tasks = [
        _hw_task(f"j{i}", rng, fit_key=f"a{i}|m|u{i}") for i in range(4)
    ]
    judge.judge(tasks)
    judge.judge(tasks)  # warm: hits accumulate
    before = judge.device_state_counters()
    assert before["hits"] > 0 and before["misses"] > 0

    judge.clear_device_state()
    after_clear = judge.device_state_counters()
    for k in ("hits", "misses", "evictions"):
        assert after_clear[k] == before[k]  # nothing lost
    assert after_clear["rows_live"] == 0

    judge.judge(tasks)  # rebuilt arena: counters keep rising
    final = judge.device_state_counters()
    assert final["misses"] > after_clear["misses"]
    assert final["rows_live"] > 0


def test_bf16_delta_scorer_matches_f32_and_keeps_low_cv_bands():
    """FOREMAST_BF16_DELTA variant (BENCHMARKS.md roofline): the
    anchor-shifted bf16-delta moving_average_all scorer must reproduce
    f32 verdicts/flags on realistic data, and — the round-3 refusal
    case — keep band geometry on LOW-CV series (value 100 +- 0.1, where
    RAW bf16 storage had ulp 0.5 and destroyed the band)."""
    import dataclasses

    import jax.numpy as jnp

    from foremast_tpu.engine import scoring
    from foremast_tpu.ops.windows import MetricWindows
    from foremast_tpu.parallel.batch import throughput_batch

    b, th = 64, 512
    batch = throughput_batch(b, th, 30, seed=3)
    ref = scoring.score(batch, algorithm="moving_average_all")
    slim, anchor, delta = scoring.make_bf16_delta_batch(batch)
    got = scoring.score_bf16_delta(slim, anchor, delta)
    assert (np.asarray(got.verdict) == np.asarray(ref.verdict)).all()
    assert (np.asarray(got.anomalies) == np.asarray(ref.anomalies)).all()

    # low-CV: 100 +- 0.1 noise; the fitted scale must stay within 2% of
    # the f32 scale (raw bf16 storage would quantize values to +-0.5 and
    # inflate/deflate it wildly), and band edges within 0.5% of level
    rng = np.random.default_rng(0)
    hist = (100.0 + 0.1 * rng.standard_normal((b, th))).astype(np.float32)
    low = dataclasses.replace(
        batch,
        historical=MetricWindows(
            values=jnp.asarray(hist),
            mask=jnp.ones((b, th), bool),
            times=None,
        ),
        current=MetricWindows(
            values=jnp.asarray(
                (100.0 + 0.1 * rng.standard_normal((b, 30))).astype(
                    np.float32
                )
            ),
            mask=jnp.ones((b, 30), bool),
            times=None,
        ),
    )
    ref_low = scoring.score(low, algorithm="moving_average_all")
    slim_low, a2, d2 = scoring.make_bf16_delta_batch(low)
    got_low = scoring.score_bf16_delta(slim_low, a2, d2)
    ref_scale = np.asarray(ref_low.upper - ref_low.lower)
    got_scale = np.asarray(got_low.upper - got_low.lower)
    assert np.all(np.abs(got_scale - ref_scale) <= 0.02 * ref_scale + 1e-6)
    assert np.allclose(
        np.asarray(got_low.upper), np.asarray(ref_low.upper), rtol=5e-5,
        atol=5e-3,
    )
    assert (np.asarray(got_low.verdict) == np.asarray(ref_low.verdict)).all()


def test_bf16_delta_fit_path_daily_seasonal_quality():
    """Generalized bf16-delta cold-fit upload (any algorithm): the
    auto_univariate daily fit from reconstructed bf16 deltas must land
    the same terminal state (within bf16 deviation tolerance) and the
    SAME anomaly flags as the f32 fit on the m=1440 workload shape."""
    import jax.numpy as jnp

    from benchmarks.quality import gen, make_batch
    from foremast_tpu.engine import scoring
    from foremast_tpu.engine.judge import _pack_hist_bf16_host

    b, th, tc, m = 8, 10_080, 30, 1440
    hist, cur, truth = gen("seasonal", b, th, tc, period=m)
    t = np.arange(th, dtype=np.int64)
    ragged = [(t, hist[i]) for i in range(b)]
    anchor, delta, lens = _pack_hist_bf16_host(ragged, th)
    fc16 = scoring.fit_forecast_bf16_delta(
        jnp.asarray(anchor),
        jnp.asarray(delta),
        jnp.asarray(lens),
        algorithm="auto_univariate",
        season_length=m,
    )
    fc32 = scoring.fit_forecast(
        jnp.asarray(hist),
        jnp.ones((b, th), bool),
        algorithm="auto_univariate",
        season_length=m,
    )
    assert np.allclose(
        np.asarray(fc16.level), np.asarray(fc32.level), atol=2e-3
    )
    s16, s32 = np.asarray(fc16.scale), np.asarray(fc32.scale)
    assert np.all(np.abs(s16 - s32) <= 0.02 * s32 + 1e-6)
    assert np.allclose(
        np.asarray(fc16.season), np.asarray(fc32.season), atol=1e-2
    )

    batch = make_batch(hist, cur)
    n_hist = jnp.asarray(lens)

    def judge(fc):
        return scoring.score_from_state(
            batch,
            fc.level,
            fc.trend,
            fc.season,
            fc.season_phase,
            fc.scale,
            n_hist,
        )

    r16, r32 = judge(fc16), judge(fc32)
    assert (np.asarray(r16.anomalies) == np.asarray(r32.anomalies)).all()
    assert (np.asarray(r16.verdict) == np.asarray(r32.verdict)).all()
    # and the flags actually catch the injected spikes (not vacuous)
    flags = np.asarray(r16.anomalies)
    assert (flags & truth).sum() >= 0.98 * truth.sum()


def test_arena_budget_setter_overrides_env(monkeypatch):
    """Pod-mode knob adoption (parallel/distributed.PodWorker) goes
    through explicit setters, not post-startup os.environ writes (the
    lock-discipline rule those writes violated): the override wins over
    the env, and clearing it restores env/default behavior."""
    from foremast_tpu.engine.arena import (
        _arena_bytes,
        _arena_max_bytes,
        set_arena_budget,
    )

    monkeypatch.setenv("FOREMAST_ARENA_BYTES", "123")
    monkeypatch.setenv("FOREMAST_ARENA_MAX_BYTES", "456")
    set_arena_budget(1024, 2048)
    try:
        assert _arena_bytes() == 1024
        assert _arena_max_bytes() == 2048
    finally:
        set_arena_budget(None, None)
    assert _arena_bytes() == 123
    assert _arena_max_bytes() == 456


def test_bf16_delta_setter_overrides_env(monkeypatch):
    from foremast_tpu.engine import scoring

    monkeypatch.setenv("FOREMAST_BF16_DELTA", "0")
    assert not scoring.bf16_delta_enabled()
    scoring.set_bf16_delta(True)
    try:
        assert scoring.bf16_delta_enabled()
    finally:
        scoring.set_bf16_delta(None)
    assert not scoring.bf16_delta_enabled()


def test_arena_grows_for_cross_bucket_working_set():
    """ISSUE 14: a warm tick split across sibling bucket calls (the
    baseline-less and canary columnar buckets share the univariate
    arena) must GROW the arena to the cross-call working set, not evict
    the sibling's rows every call (LRU thrash: the whole fleet state
    would re-scatter each tick)."""
    from foremast_tpu.engine.arena import StateArena, _row_bytes

    a = StateArena(1, max_bytes=4096 * _row_bytes(1))
    bucket_a = [f"a{i}" for i in range(32)]
    bucket_b = [f"b{i}" for i in range(32)]
    # cold pass: both buckets scatter once
    ra, sa = a.assign(bucket_a, range(32))
    rb, sb = a.assign(bucket_b, range(32))
    assert len(sa) == 32 and len(sb) == 32
    # warm passes: every row must HIT — zero evictions, zero scatters —
    # for several alternating cycles (capacity grew to hold both)
    for _ in range(3):
        for bucket in (bucket_a, bucket_b):
            rows, scatter = a.assign(bucket, ())
            assert scatter == [], scatter
    assert a.evictions == 0
    assert a.cap >= 64


def test_arena_grows_for_many_bucket_working_set():
    """The in-loop backstop (code review round): with 3+ assigns per
    tick cycle — uni + canary + several slow-path buckets — a row used
    a few calls ago is still working set; only rows idle for 8+ calls
    may be recycled instead of growing."""
    from foremast_tpu.engine.arena import StateArena, _row_bytes

    a = StateArena(1, max_bytes=4096 * _row_bytes(1))
    buckets = [
        [f"{c}{i}" for i in range(16)] for c in "abcde"
    ]  # 5 buckets x 16 rows = 80-row working set
    for bucket in buckets:
        a.assign(bucket, range(16))
    for _ in range(3):
        for bucket in buckets:
            rows, scatter = a.assign(bucket, ())
            assert scatter == [], scatter
    assert a.evictions == 0
    assert a.cap >= 80
