"""Benchmark-suite smoke: the F1 quality gate must hold (CPU, tiny)."""

import json

import benchmarks.suite as suite


def test_golden_trace_f1_is_perfect(capsys):
    suite.main(["--small", "--config", "f1"])
    line = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert line["config"] == "f1-golden-trace"
    assert line["value"] == 1.0
    assert line["precision"] == 1.0 and line["recall"] == 1.0


def test_suite_config1_runs_small(capsys):
    suite.main(["--small", "--config", "1"])
    line = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert line["metric"] == "windows_per_sec"
    assert line["value"] > 0
