"""Benchmark-suite smoke: the F1 quality gate must hold (CPU, tiny)."""

import json

import benchmarks.suite as suite


def test_golden_trace_f1_is_perfect(capsys):
    suite.main(["--small", "--config", "f1"])
    line = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert line["config"] == "f1-golden-trace"
    assert line["value"] == 1.0
    assert line["precision"] == 1.0 and line["recall"] == 1.0


def test_suite_config1_runs_small(capsys):
    suite.main(["--small", "--config", "1"])
    line = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert line["metric"] == "windows_per_sec"
    assert line["value"] > 0


def test_quality_benchmark_structured_beats_flat_on_seasonal(capsys):
    """Smoke the quality harness: fitted HW must dominate the global-mean
    default on the seasonal scenario."""
    import benchmarks.quality as quality

    quality.main(["--small"])
    rows = [
        json.loads(line) for line in capsys.readouterr().out.strip().splitlines()
    ]
    by = {(r["scenario"], r["algorithm"]): r["f1"] for r in rows}
    assert by[("seasonal", "holt_winters")] > 0.9
    assert by[("seasonal", "moving_average_all")] < 0.5
    assert by[("flat", "moving_average_all")] > 0.9
