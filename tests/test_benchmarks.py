"""Benchmark-suite smoke: the F1 quality gate must hold (CPU, tiny)."""

import json

import pytest

import benchmarks.suite as suite


def test_golden_trace_f1_is_perfect(capsys):
    suite.main(["--small", "--config", "f1"])
    line = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert line["config"] == "f1-golden-trace"
    assert line["value"] == 1.0
    assert line["precision"] == 1.0 and line["recall"] == 1.0


def test_suite_config1_runs_small(capsys):
    suite.main(["--small", "--config", "1"])
    line = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert line["metric"] == "windows_per_sec"
    assert line["value"] > 0


def test_quality_benchmark_structured_beats_flat_on_seasonal(capsys):
    """Smoke the quality harness: fitted HW must dominate the global-mean
    default on the seasonal scenario, and the joint detectors must hold
    F1 >= 0.9 on their scenarios (VERDICT r1 item 5):

      * joint-bivariate   — off-ridge points, marginally in-range
      * joint-lstm        — all-metric spikes incl. seasonal troughs
                            (contextual: near the marginal mean there)
      * joint-lstm-break  — one metric deviating from the co-moving pack
    """
    import benchmarks.quality as quality

    quality.main(["--small"])
    rows = [
        json.loads(line) for line in capsys.readouterr().out.strip().splitlines()
    ]
    by = {(r["scenario"], r["algorithm"]): r for r in rows}
    f1 = lambda k: by[k]["f1"]
    assert f1(("seasonal", "holt_winters")) > 0.9
    assert f1(("seasonal", "moving_average_all")) < 0.5
    assert f1(("flat", "moving_average_all")) > 0.9
    assert f1(("joint-bivariate", "bivariate_normal")) >= 0.9
    # hybrid joint detector (VERDICT r2 item 4): precision >= 0.95 at
    # recall >= 0.98 — fail-fast + AutoRollback semantics price every
    # false point as a potential rollback
    for k in ("joint-lstm", "joint-lstm-break"):
        row = by[(k, "lstm_autoencoder")]
        assert row["precision"] >= 0.95, row
        assert row["recall"] >= 0.98, row
    # and CLEAN windows must not page at all (job-level false alarms)
    assert by[("joint-clean-windows", "lstm_autoencoder")]["job_false_alarms"] == 0
    # auto_univariate (VERDICT r1 item 6): structure screen routes
    # seasonal/trend series to the fitted model without regressing flat
    assert f1(("seasonal", "auto_univariate")) >= 0.95
    assert f1(("trend", "auto_univariate")) >= 0.95
    assert f1(("flat", "auto_univariate")) >= 0.95
    # level-shift scenario (VERDICT r2 item 7): the changepoint trend
    # (models/seasonal.py hinges) keeps the band centered through a
    # redeploy-style step; a global-band model drowns
    assert f1(("shift", "seasonal_p24")) >= 0.99
    assert f1(("shift", "auto_univariate")) >= 0.99
    assert f1(("shift", "moving_average_all")) < 0.5
    # the reference's REAL workload shape (VERDICT r2 item 1): daily
    # m=1440 cycle over the 7-day 10,080-pt history — the auto screen
    # must route it to a structured model and hold F1 >= 0.99, while the
    # global-mean default's band swallows the cycle
    assert f1(("daily-1440", "auto_univariate")) >= 0.99
    assert f1(("daily-1440", "seasonal")) >= 0.99
    assert f1(("daily-1440", "moving_average_all")) < 0.5
    # ONE mixed batch of every shape — auto must route per series inside
    # a single compiled program (the production condition)
    mix = by[("fleet-mix", "auto_univariate")]
    assert mix["f1"] >= 0.97, mix
    assert all(v >= 0.95 for v in mix["per_kind_f1"].values()), mix
    # sparse sharp cycle features (cron-style bursts): only the pooled
    # phase-means fit represents the shape, and the auto screen's
    # phase-significance gate must route to it (the SSE-ratio gate alone
    # is blind to features covering <1% of samples)
    assert f1(("daily-1440-sharp", "phase_means")) >= 0.99
    assert f1(("daily-1440-sharp", "auto_univariate")) >= 0.99
    assert f1(("daily-1440-sharp", "seasonal")) < 0.7  # Fourier can't
    assert f1(("daily-1440-sharp", "moving_average_all")) < 0.7


def test_worker_bench_churn_mode_small():
    """Churn machinery (VERDICT r4 #4): each warm tick retires and
    admits 10% of services; every tick must still process the full
    fleet, the columnar fast path must keep serving the warm majority
    (per-key admission revalidation — no wholesale re-walk), and no
    arena fallbacks may fire."""
    from benchmarks.worker_bench import run

    out = run(
        services=20,
        ticks=3,
        algorithm="moving_average_all",
        season=24,
        hist_len=256,
        cur_len=30,
        churn=0.1,
    )
    assert out["churn_per_tick"] == 2
    assert out["arena_fallbacks"] == 0
    assert out["warm_windows_per_sec"] > 0
    assert out["cold_first_verdict_seconds"] <= out["cold_tick_seconds"]


def test_pipeline_bench_small_smoke(capsys):
    """Shipped-tick pipeline benchmark, one iteration at CI shapes: the
    serial and pipelined cold ticks must both run, produce identical
    store writes (asserted inside run()), and report occupancy stats."""
    import benchmarks.pipeline_bench as pipeline_bench

    pipeline_bench.main(["--small"])
    line = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert line["config"] == "p-pipelined-cold-tick"
    assert line["metric"] == "cold_tick_speedup"
    assert line["equivalent"] is True
    assert line["value"] and line["value"] > 0
    assert line["chunks"] == 3
    assert line["serial_cold_tick_seconds"] > 0
    assert line["pipelined_cold_tick_seconds"] > 0
    assert 0.0 <= line["overlap_ratio"] < 1.0


def test_worker_bench_mixed_fleet_small():
    """`make bench-mixed --small` smoke (ISSUE 4): a mixed fleet (15%
    joint docs) must run cold + warm, with the JOINT docs scored on the
    columnar path during the warm ticks (per-kind counters > 0 is the
    acceptance signal) and zero joint-arena fallbacks."""
    from benchmarks.worker_bench import run

    out = run(
        services=40,
        ticks=2,
        algorithm="auto",
        season=24,
        hist_len=256,
        cur_len=30,
        joint_frac=0.15,
    )
    assert out["joint_services"] == 6
    fast = out["fast_path_docs"]
    assert fast["bivariate"] > 0 and fast["lstm"] > 0, fast
    assert fast["univariate"] > 0, fast
    assert out["joint_arena"]["fallbacks"] == 0
    assert out["joint_arena"]["rows_live"] > 0
    assert out["warm_windows_per_sec"] > 0


def test_ingest_bench_small_smoke(capsys):
    """`make bench-ingest --small` smoke (ISSUE 5): warm RingSource vs
    PrometheusSource-over-localhost on the same fleet — judgments must
    be byte-identical (asserted inside run()), the push worker's ticks
    must issue ZERO Prometheus HTTP requests, and the fetch stage must
    get faster (the >= 5x acceptance bar is checked at full benchmark
    shapes, not CI smoke shapes)."""
    import benchmarks.ingest_bench as ingest_bench

    ingest_bench.main(["--small"])
    lines = capsys.readouterr().out.strip().splitlines()
    line = json.loads(lines[-1])
    assert line["config"] == "i-ingest-warm-fetch"
    assert line["equivalent"] is True
    assert line["zero_http_warm_tick"] is True
    assert line["ring_hit_ratio"] == 1.0
    assert line["series_resident"] == line["windows"]
    assert line["value"] and line["value"] > 1.0
    # ISSUE 18 cross-codec parity on the fixed fleet fixture: the
    # receiver answered byte-identical responses for JSON and binary
    # warming, and the judged statuses matched (both asserted inside
    # run(); the flags witness the asserts ran)
    assert line["codec_responses_identical"] is True
    assert line["codec_statuses_identical"] is True
    # wire-protocol phase prints its own line before the warm-fetch one
    wire = json.loads(lines[-2])
    assert wire["config"] == "i-ingest-wire-codec"
    assert (
        wire["codecs"]["json"]["samples"]
        == wire["codecs"]["binary"]["samples"]
        == wire["codecs"]["binary_snappy"]["samples"]
        == wire["total_samples"]
    )
    assert wire["value"] and wire["value"] > 0
    assert wire["dirty_slo"]["items_closed"] > 0
    # perf bars (>= 5M samples/s/worker, >= 6x JSON at equal CPU, SLO
    # p99 <= 0.5 s) are asserted in-run at FULL shapes only, not CI smoke


def test_cold_bench_small_smoke(capsys):
    """`make bench-cold --small` smoke (ISSUE 10): ring-resident cold
    fits (zero HTTP, byte-identical statuses vs the pull path — both
    asserted inside run()), a zero-HTTP churn tick, short-history
    newcomer admission (no UNKNOWNs), and refinement draining to
    band-parity with from-scratch fits."""
    import benchmarks.cold_bench as cold_bench

    cold_bench.main(["--small"])
    line = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert line["config"] == "c-cold-ring-tick"
    assert line["zero_http_cold"] is True
    assert line["zero_http_churn"] is True
    assert line["newcomer_unknown"] == 0
    assert line["band_parity"] is True
    assert line["refine_counts"]["pending"] == 0
    assert line["cold_speedup"] > 1.0


def test_scaleout_bench_small_smoke(capsys):
    """`make bench-scaleout --small` smoke (ISSUE 6): 1 then 2 REAL
    worker processes over the HTTP store — exactly-once judgment and
    the kill/rebalance ≤2-tick bar are asserted inside run(); routed
    pushes must converge by the second cycle (the ≥3x throughput bar is
    checked at full benchmark shapes, not CI smoke shapes)."""
    import benchmarks.scaleout_bench as scaleout_bench

    scaleout_bench.main(["--small"])
    line = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert line["config"] == "s-mesh-scaleout"
    assert line["worker_counts"] == [1, 2]
    assert line["no_double_judgment"] is True
    assert line["routed_push_converged"] is True
    assert line["rebalance"] is not None
    assert line["rebalance"]["worst_ticks_after_heal"] <= 2
    assert line["rebalance"]["orphan_docs"] > 0
    assert all(
        v > 0 for v in line["fleet_warm_windows_per_sec"].values()
    )


def test_scaleout_bench_sharded_judge_small_smoke(capsys):
    """`make bench-scaleout` sharded-judge variant smoke (ISSUE 13):
    one REAL worker process whose judge partitions over a forced
    2-virtual-device mesh — exactly-once judgment asserts run inside
    run(), the in-run partition assert runs inside ShardedJudge._place,
    and the summary must carry the roofline account (H2D place / device
    dispatch / host gather / decode) plus the padded-row fraction."""
    import benchmarks.scaleout_bench as scaleout_bench

    scaleout_bench.main(
        ["--small", "--workers", "1", "--no-kill", "--device-mesh", "2"]
    )
    line = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert line["config"] == "s-mesh-scaleout-sharded"
    assert line["device_mesh"] == 2
    rl = line["roofline"]
    assert rl is not None
    assert rl["devices_per_worker"] == 2
    assert rl["h2d_seconds"] >= 0 and rl["gather_seconds"] > 0
    assert rl["padded_row_fraction"] is not None
    # per-device bytes x devices: the SHARD-SUM under the default
    # sharded layout (ISSUE 19) — same arithmetic the replicated
    # layout used for its replication tax
    assert rl["arena_layout"] == "sharded"
    assert rl["arena_capacity_rows"] > 0
    assert rl["arena_total_device_bytes"] == 2 * rl["arena_replica_bytes"]
    # the ISSUE 19 capacity claims ran in-run (run_arena_check asserts
    # them before the fleet starts; the summary echoes the verdict)
    cap = line["arena_capacity"]
    assert cap["oom_replicated"] and cap["fits_sharded"], cap
    assert cap["linear_scaling"], cap
    assert cap["warm_gather_collectives"] == [], cap
    assert line["no_double_judgment"] is True
    assert all(
        v > 0 for v in line["fleet_warm_windows_per_sec"].values()
    )


def test_restart_bench_small_smoke(capsys):
    """`make bench-restart --small` smoke (ISSUE 7): one REAL worker
    SIGKILLed mid-tick (claim persisted, no verdict) and restarted
    against the same snapshot directory, single-worker and 3-worker
    mesh variants. The acceptance bar is asserted inside run() —
    recovery tick ≥ 90% fast-path, ZERO fallback fetches, exactly-once
    judgment across the kill — and echoed in the output line."""
    import benchmarks.restart_bench as restart_bench

    restart_bench.main(["--small"])
    lines = [
        json.loads(line)
        for line in capsys.readouterr().out.strip().splitlines()
    ]
    assert [ln["variant"] for ln in lines] == ["single", "mesh-3"]
    for ln in lines:
        assert ln["config"] == "r-restart-recovery"
        assert ln["recovery_fast_fraction"] >= 0.9
        assert ln["recovery_fallback_fetches"] == 0
        assert ln["exactly_once"] is True
        assert ln["restored_series"] > 0 and ln["restored_fits"] > 0
        assert ln["parked_docs_at_kill"] > 0


def test_chaos_bench_small_smoke(capsys):
    """`make bench-chaos --small` smoke (ISSUE 9): the 3-worker chaos
    soak — store brownout, prometheus blackhole, pusher flood, skewed
    clocks, worker crash — with every acceptance assert in-run (the
    bench FAILS on a lost/duplicated verdict, a breaker that never
    re-closes, recovery > 2 busy ticks, a lock-witness miss, or an
    unbounded buffer). The summary line echoes the bars; `make ci`
    runs this via test-fast."""
    import benchmarks.chaos_bench as chaos_bench

    chaos_bench.main(["--small"])
    lines = [
        json.loads(line)
        for line in capsys.readouterr().out.strip().splitlines()
    ]
    summary = lines[-1]
    assert summary["config"] == "c-chaos-soak"
    assert summary["phases"] == [
        "baseline", "brownout", "blackhole", "flood", "skew", "crash",
    ]
    assert summary["no_lost_or_duplicated_verdicts"] is True
    assert summary["breakers_reclosed"] is True
    assert summary["recovery_within_2_ticks"] is True
    assert summary["lock_witness_clean"] is True
    assert summary["memory_bounded"] is True
    by_phase = {ln["phase"]: ln for ln in lines}
    # mid-write asserts gated on observed overlap: on a loaded 1-CPU
    # host the judge pass can outlast even the bench's extended
    # brownout window, in which case no write could have buffered —
    # the bench records that honestly instead of flaking
    if by_phase["brownout"]["overlap_observed"]:
        assert by_phase["brownout"]["buffered"] > 0
        assert by_phase["brownout"]["replayed"] > 0
    assert by_phase["blackhole"]["released"] > 0
    assert by_phase["flood"]["sheds"] > 0
    assert by_phase["crash"]["parked_at_wedge"] > 0


def test_latency_bench_small_smoke(capsys):
    """`make bench-latency --small` smoke (ISSUE 12): the reactive
    plane end to end at CI shapes — a deployment PATCHed into the fake
    kube server produces a verdict through the real watch stream +
    micro-tick chain, anomaly injections through the real receiver all
    land (the bench FAILS on a timed-out injection, a missing deploy
    verdict, or a micro-vs-full tick-path parity break). The <= 1 s /
    p99 <= 2 s bars are asserted at the full 16k shape, not CI smoke
    shapes."""
    import benchmarks.latency_bench as latency_bench

    latency_bench.main(["--small"])
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["bench"] == "latency"
    assert out["injections_timed_out"] == 0
    assert out["deploy_to_first_verdict_seconds"] is not None
    assert out["anomaly_latency_p99_seconds"] is not None
    assert out["parity"] == "byte-identical (asserted)"
    # ISSUE 15: the sliced-vs-monolithic parity arm ran (the sharded
    # child arm is full-run only), and the warm-throughput phase
    # actually exercised the sliced warm pipeline (slices > 1)
    assert out["sliced_parity"].startswith("byte-identical")
    assert out["warm_throughput"]["slices"] > 1
    assert out["warm_throughput"]["warm_windows_per_sec"] > 0


def test_noisy_bench_small_smoke(capsys):
    """`make bench-noisy --small` smoke (ISSUE 20): the noisy-neighbor
    fleet at CI shapes — a whale tenant at 10x share floods the real
    receiver while quiet tenants' anomaly injections are measured
    against a solo-tenant control. The bench FAILS in-run on a shed
    landing anywhere but the whale, a quiet-tenant F1 change, an
    evicted quiet series, a missing /debug/state tenants section, or a
    zero-vs-one-tenant parity break; the p99-vs-control bar asserts at
    the full shape only."""
    import benchmarks.noisy_bench as noisy_bench

    noisy_bench.main(["--small"])
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["bench"] == "noisy"
    assert out["quiet_push_codes"] == {"200": out["inject"]}
    assert out["whale_flood_codes"].get("429", 0) > 0
    assert out["treatment"]["f1"] == out["control"]["f1"]
    assert out["treatment"]["timeouts"] == 0
    assert out["accounting"]["t0"]["shed"] > 0
    assert out["debug_state_tenants"] is True
    assert out["parity"].startswith("zero-vs-one-tenant byte-identical")


def test_elastic_bench_small_smoke(capsys):
    """`make bench-elastic --small` smoke (ISSUE 11): 2 -> 4 -> 2
    workers under continuous load with every acceptance assert in-run
    (the bench FAILS on a lost/duplicated verdict, an UNKNOWN
    regression, a handoff past 2 ticks, a cold refit or fallback fetch
    on a planned move, or a blackholed transfer that wedges instead of
    degrading to cold refit). The summary line echoes the bars; `make
    ci` runs this via test-fast."""
    import benchmarks.elastic_bench as elastic_bench

    elastic_bench.main(["--small"])
    lines = [
        json.loads(line)
        for line in capsys.readouterr().out.strip().splitlines()
    ]
    summary = lines[-1]
    assert summary["config"] == "c-elastic"
    assert summary["phases"] == [
        "load", "scale_up", "scale_down", "fault",
    ]
    assert summary["no_lost_or_duplicated_verdicts"] is True
    assert summary["no_unknown_regression"] is True
    assert summary["planned_moves_zero_cold_refits"] is True
    assert summary["planned_moves_zero_fallback_fetches"] is True
    assert summary["handoff_within_2_ticks"] is True
    assert summary["fault_degraded_to_cold_refit"] is True
    assert summary["lock_witness_clean"] is True
    by_phase = {ln["phase"]: ln for ln in lines}
    assert by_phase["scale_up"]["moved_series"] > 0
    assert by_phase["scale_up"]["moved_fits"] > 0
    assert by_phase["scale_up"]["joiner_docs"] > 0
    assert by_phase["scale_down"]["survivor_cold_refits"] == 0
    assert by_phase["fault"]["failed_sends"] >= 1
    assert by_phase["fault"]["w5_cold_refits"] > 0


def test_plane_bench_small_smoke():
    """Watch-plane scale benchmark (VERDICT r5 #7) at CI shapes: the
    informer resync and the controller poll tick must run and stay
    inside the ~1 s budget (at 10k monitors the measured full-scale
    numbers are ~12/48 ms — BENCHMARKS.md)."""
    from benchmarks.plane_bench import run

    out = run(monitors=64, ticks=2)
    assert out["events_handled"] == 64
    assert out["within_budget"] is True
    assert out["poll_tick_seconds"] >= 0


def test_fleet_mix_f1_pinned():
    """Regression pin for the fleet-mix quality scenario (ISSUE 4: the
    joint columnar path must not move univariate routing quality): at
    the CI shape, `auto_univariate` over one batch mixing all five
    shapes holds the round-5 floors."""
    from benchmarks.quality import fleet_mix

    f1, precision, recall, by_kind = fleet_mix(32, 240, 30)
    assert f1 >= 0.97, (f1, by_kind)
    assert precision >= 0.99, (precision, by_kind)
    assert all(v >= 0.95 for v in by_kind.values()), by_kind


def test_mixed_univariate_joint_worker_tick():
    """VERDICT r4 #5: ONE worker claim set mixing all five univariate
    shapes with bivariate + LSTM-hybrid joint jobs under the `auto`
    selector; tick 1 warms every model clean, tick 2 judges the spiked
    fleet warm (univariate docs on the columnar fast path, joint docs on
    the slow path — in the same tick). Small CI shapes; at benchmark
    size (per_uni=24, per_joint=4) every kind measures F1 = 1.0 with 0
    false alarms (BENCHMARKS.md mixed-tick row)."""
    from benchmarks.quality import mixed_fleet_tick

    by_kind, false_alarms = mixed_fleet_tick(4, 2, 240, 30)
    assert false_alarms == 0  # clean docs stay healthy: no contamination
    for kind, (f1, points) in by_kind.items():
        floor = 1.0 if kind in ("bivariate", "lstm") else 0.93
        assert f1 >= floor, (kind, f1, points)


def test_mixed_bench_canary_small():
    """`make bench-mixed` canary phase smoke (ISSUE 14): a canary-heavy
    fleet judged through the columnar canary bucket vs the knob-off and
    full-object arms — byte parity between ALL arms is asserted inside
    run_canary() at every shape; the >= 3x / >= 12.5k w/s bars are
    asserted at full benchmark shapes, not CI smoke shapes."""
    from benchmarks.mixed_bench import run_canary

    out = run_canary(24, 2, 256, 30, assert_bars=False)
    assert out["config"] == "w-canary-fleet-tick"
    assert out["equivalent"] is True
    assert out["canary_services"] == 12
    fast = out["fast_path_docs"]
    assert fast["baseline"] > 0 and fast["univariate"] > 0, fast
    assert out["columnar"]["warm_windows_per_sec"] > 0
    assert out["object_path"]["warm_windows_per_sec"] > 0
    assert out["value"] > 0


def test_mixed_bench_scenario_matrix_small():
    """Scenario-matrix smoke (ISSUE 14): every strategy x regime cell
    runs at CI shape and holds its F1 floor (in-run assert inside
    run_scenarios); canary cells must report the pairwise false-reject
    rate and never score materially WORSE than their baseline-less
    siblings on the same regime (the rank tests must not hurt clean
    detection)."""
    from benchmarks.mixed_bench import run_scenarios
    from benchmarks.scenarios import REGIMES, STRATEGIES

    rows = run_scenarios(16, 240, 30, assert_floors=True)
    assert len(rows) == len(STRATEGIES) * len(REGIMES)
    by = {(r["strategy"], r["regime"]): r for r in rows}
    for regime in REGIMES:
        canary = by[("canary", regime)]
        assert "pairwise_differs_rate" in canary
        for other in ("rolling", "continuous"):
            assert canary["f1"] >= by[(other, regime)]["f1"] - 0.1, (
                canary, by[(other, regime)],
            )


def test_mixed_bench_label_shape_routing_small():
    """Label-shape routing cells (ISSUE 15 satellite / ROADMAP item
    4's generator gap): multi-cluster and multi-tenant label shapes
    must leave doc↔series co-location AND ownership spread invariant —
    the mesh routes by the `app` label value alone, so extra
    cluster/tenant labels can never move a series off its document's
    worker (asserted inside the cell)."""
    from benchmarks.scenarios import LABEL_SHAPES, label_shape_routing_cell

    rows = [
        label_shape_routing_cell(shape, services=64, workers=4)
        for shape in LABEL_SHAPES
    ]
    assert [r["label_shape"] for r in rows] == list(LABEL_SHAPES)
    for row in rows:
        assert row["co_located"] is True
        assert sum(row["owners"].values()) == 64
    # ownership is a function of the ROUTE KEY alone: identical
    # distributions across shapes is the invariance made visible
    assert rows[0]["owners"] == rows[1]["owners"] == rows[2]["owners"]


def test_bench_report_round_and_merge(tmp_path, monkeypatch):
    """BENCH_rNN.json emission (ISSUE 15 satellite): summaries merge
    per bench under one round file, --small runs never write, and the
    round resolves from BENCHMARKS.md's highest pinned round + 1."""
    from benchmarks import report

    # the env override must not leak into the resolution assertions
    monkeypatch.delenv("FOREMAST_BENCH_ROUND", raising=False)

    path = str(tmp_path / "BENCH_r99.json")
    assert report.write_summary("latency", {"p99": 0.4}, small=True) is None
    out = report.write_summary("latency", {"p99": 0.4}, path=path)
    assert out == path
    report.write_summary("mixed", {"wps": 1.0}, path=path)
    with open(path) as f:
        doc = json.load(f)
    assert set(doc["results"]) == {"latency", "mixed"}
    assert doc["results"]["latency"]["asserts_passed"] is True
    assert doc["results"]["latency"]["p99"] == 0.4
    # round resolution: highest pinned round + 1, in BOTH heading
    # spellings ("## Round N" and "## <title> (round N, ...)")
    md = tmp_path / "BENCHMARKS.md"
    md.write_text(
        "## Round 3\n\nstuff\n\n"
        "## Columnar canary: fast path (round 12, `make bench-mixed`)\n"
    )
    assert report.current_round(str(tmp_path)) == 13
    # the REAL BENCHMARKS.md resolves to a round past every pinned one
    assert report.current_round() >= 17
    # a foreign-schema artifact (e.g. the driver's own BENCH_rNN.json)
    # is never clobbered — loud failure, not silent overwrite
    foreign = tmp_path / "BENCH_r01.json"
    foreign.write_text('{"n": 1, "cmd": "x"}')
    with pytest.raises(ValueError):
        report.write_summary("latency", {"p99": 1}, path=str(foreign))
    assert json.loads(foreign.read_text())["n"] == 1


def test_mixed_bench_fanin_small():
    """Pusher fan-in smoke (ISSUE 14): the canary fleet pushed through
    the REAL receiver by 1 vs 8 concurrent pushers, judged pure-push
    from the ring — statuses identical across fan-in shapes (asserted
    inside run_fanin) and the canary bucket engaged on the warm tick."""
    from benchmarks.mixed_bench import run_fanin

    rows = run_fanin(8, 128, 30, (1, 4))
    assert [r["fan_in"] for r in rows] == [1, 4]
    for row in rows:
        assert row["pure_push"] is True
        assert row["equivalent_across_shapes"] is True
        assert row["push_samples_per_sec"] > 0
