"""A minimal fake Kubernetes API server for exercising HttpKube over real
HTTP.

Speaks just enough of the K8s REST surface for the watch plane: GET/PATCH
on apps/v1 deployments (strategic-merge semantics), GET namespaces /
replicasets / pods, POST events, and full CRUD on the two foremast CRDs
(merge-patch, resourceVersion bumping, 404/409/415 error paths). The
object store is plain dicts keyed (namespace, name); the merge logic is
implemented here independently of `watch.kubeapi` so the client's
expectations are validated against a second implementation, not against
itself.
"""

from __future__ import annotations

import json
import re
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

GROUP = "deployment.foremast.ai"
VERSION = "v1alpha1"

_MERGE_TYPES = {
    "application/strategic-merge-patch+json",
    "application/merge-patch+json",
}


def _merge(dst: dict, patch: dict) -> None:
    for k, v in patch.items():
        if v is None:
            dst.pop(k, None)
        elif isinstance(v, dict) and isinstance(dst.get(k), dict):
            _merge(dst[k], v)
        else:
            dst[k] = v


class FakeKubeState:
    """Shared object store; pre-populate via the typed helpers."""

    def __init__(self):
        self.lock = threading.Lock()
        self.rv = 0
        # kind -> {(namespace or "", name): obj}
        self.objects: dict[str, dict[tuple[str, str], dict]] = {
            "namespaces": {},
            "deployments": {},
            "replicasets": {},
            "pods": {},
            "events": {},
            "deploymentmonitors": {},
            "deploymentmetadatas": {},
        }
        self.requests: list[tuple[str, str, dict]] = []  # (method, path, headers)
        # fault hooks (ISSUE 9 satellite): chaos tests drive a REAL
        # server answering real statuses, not monkeypatched clients.
        # Each fault: {"path": substr, "method": "GET"|None, "status":
        # int(0=none), "latency": seconds, "times": remaining fires
        # (None = forever)} — consumed in registration order.
        self.faults: list[dict] = []

    def add_fault(
        self,
        path: str = "",
        method: str | None = None,
        status: int = 0,
        latency: float = 0.0,
        times: int | None = None,
    ) -> None:
        with self.lock:
            self.faults.append(
                {
                    "path": path,
                    "method": method,
                    "status": status,
                    "latency": latency,
                    "times": times,
                }
            )

    def take_fault(self, method: str, path: str) -> dict | None:
        """Pop (decrement) the first matching armed fault, or None."""
        with self.lock:
            for f in self.faults:
                if f["method"] not in (None, method):
                    continue
                if f["path"] and f["path"] not in path:
                    continue
                if f["times"] is not None:
                    if f["times"] <= 0:
                        continue
                    f["times"] -= 1
                return dict(f)
        return None

    def next_rv(self) -> str:
        self.rv += 1
        return str(self.rv)

    def put(self, kind: str, namespace: str, obj: dict) -> dict:
        name = obj["metadata"]["name"]
        obj["metadata"].setdefault("namespace", namespace)
        obj["metadata"]["resourceVersion"] = self.next_rv()
        self.objects[kind][(namespace, name)] = obj
        return obj


# URL patterns -> (kind, namespaced collection)
_ROUTES = [
    (re.compile(r"^/api/v1/namespaces$"), "namespaces", None),
    (re.compile(r"^/api/v1/namespaces/(?P<name>[^/]+)$"), "namespaces", "item"),
    (
        re.compile(r"^/apis/apps/v1(/namespaces/(?P<ns>[^/]+))?/deployments$"),
        "deployments",
        None,
    ),
    (
        re.compile(r"^/apis/apps/v1/namespaces/(?P<ns>[^/]+)/deployments/(?P<name>[^/]+)$"),
        "deployments",
        "item",
    ),
    (
        re.compile(r"^/apis/apps/v1/namespaces/(?P<ns>[^/]+)/replicasets$"),
        "replicasets",
        None,
    ),
    (re.compile(r"^/api/v1/namespaces/(?P<ns>[^/]+)/pods$"), "pods", None),
    (re.compile(r"^/api/v1/namespaces/(?P<ns>[^/]+)/events$"), "events", None),
    (
        re.compile(
            rf"^/apis/{GROUP}/{VERSION}(/namespaces/(?P<ns>[^/]+))?"
            r"/(?P<kind>deploymentmonitors|deploymentmetadatas)$"
        ),
        None,
        None,
    ),
    (
        re.compile(
            rf"^/apis/{GROUP}/{VERSION}/namespaces/(?P<ns>[^/]+)"
            r"/(?P<kind>deploymentmonitors|deploymentmetadatas)/(?P<name>[^/]+)$"
        ),
        None,
        "item",
    ),
]


def _handler(state: FakeKubeState):
    class Handler(BaseHTTPRequestHandler):
        def log_message(self, *a):  # keep test output clean
            pass

        def _send(self, code: int, obj: dict | None = None):
            body = json.dumps(obj or {}).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def _body(self) -> dict:
            n = int(self.headers.get("Content-Length", 0))
            return json.loads(self.rfile.read(n) or b"{}")

        def _route(self):
            from urllib.parse import unquote, urlparse

            path = unquote(urlparse(self.path).path)
            for rx, kind, mode in _ROUTES:
                m = rx.match(path)
                if m:
                    gd = m.groupdict()
                    kind = kind or gd.get("kind")
                    ns = gd.get("ns") or ""
                    name = gd.get("name")
                    return kind, ns, name, mode
            return None, None, None, None

        def _record(self):
            state.requests.append(
                (self.command, self.path, dict(self.headers.items()))
            )

        def _fault(self) -> bool:
            """Apply an armed fault hook; True = request already
            answered (the caller returns immediately)."""
            f = state.take_fault(self.command, self.path)
            if f is None:
                return False
            if f["latency"]:
                import time

                time.sleep(f["latency"])
            if f["status"]:
                self._send(f["status"], {"reason": "injected fault"})
                return True
            return False  # latency-only fault: continue normally

        def do_GET(self):
            self._record()
            if self._fault():
                return
            kind, ns, name, mode = self._route()
            if kind is None:
                return self._send(404, {"reason": "NotFound"})
            with state.lock:
                store = state.objects[kind]
                if mode == "item" or (kind == "namespaces" and name):
                    key = (ns, name) if kind != "namespaces" else ("", name)
                    if key not in store:
                        return self._send(404, {"reason": "NotFound"})
                    return self._send(200, store[key])
                items = [
                    o
                    for (o_ns, _), o in sorted(store.items())
                    if not ns or o_ns == ns
                ]
                return self._send(200, {"items": items})

        def do_POST(self):
            self._record()
            if self._fault():
                return
            kind, ns, name, mode = self._route()
            if kind is None or mode == "item":
                return self._send(404, {"reason": "NotFound"})
            obj = self._body()
            with state.lock:
                oname = obj.get("metadata", {}).get("name") or f"gen-{state.rv}"
                obj.setdefault("metadata", {})["name"] = oname
                key = (ns, oname)
                if key in state.objects[kind] and kind != "events":
                    return self._send(409, {"reason": "AlreadyExists"})
                obj["metadata"]["namespace"] = ns
                obj["metadata"]["resourceVersion"] = state.next_rv()
                state.objects[kind][key] = obj
                return self._send(201, obj)

        def do_PUT(self):
            self._record()
            if self._fault():
                return
            kind, ns, name, mode = self._route()
            if kind is None or mode != "item":
                return self._send(404, {"reason": "NotFound"})
            obj = self._body()
            with state.lock:
                key = (ns, name)
                store = state.objects[kind]
                if key not in store:
                    return self._send(404, {"reason": "NotFound"})
                current = store[key]
                # optimistic concurrency: stale resourceVersion conflicts
                sent_rv = obj.get("metadata", {}).get("resourceVersion")
                if sent_rv and sent_rv != current["metadata"]["resourceVersion"]:
                    return self._send(409, {"reason": "Conflict"})
                obj.setdefault("metadata", {})["namespace"] = ns
                obj["metadata"]["name"] = name
                obj["metadata"]["resourceVersion"] = state.next_rv()
                store[key] = obj
                return self._send(200, obj)

        def do_PATCH(self):
            self._record()
            if self._fault():
                return
            kind, ns, name, mode = self._route()
            if kind is None or mode != "item":
                return self._send(404, {"reason": "NotFound"})
            ctype = self.headers.get("Content-Type", "")
            if ctype not in _MERGE_TYPES:
                return self._send(415, {"reason": "UnsupportedMediaType"})
            patch = self._body()
            with state.lock:
                key = (ns, name) if kind != "namespaces" else ("", name)
                store = state.objects[kind]
                if key not in store:
                    return self._send(404, {"reason": "NotFound"})
                _merge(store[key], patch)
                store[key]["metadata"]["resourceVersion"] = state.next_rv()
                return self._send(200, store[key])

        def do_DELETE(self):
            self._record()
            if self._fault():
                return
            kind, ns, name, mode = self._route()
            if kind is None or mode != "item":
                return self._send(404, {"reason": "NotFound"})
            with state.lock:
                key = (ns, name)
                if key not in state.objects[kind]:
                    return self._send(404, {"reason": "NotFound"})
                del state.objects[kind][key]
                return self._send(200, {"status": "Success"})

    return Handler


class FakeKubeServer:
    """Context manager: spins up the server on an ephemeral localhost port."""

    def __init__(self):
        self.state = FakeKubeState()
        self._srv = ThreadingHTTPServer(("127.0.0.1", 0), _handler(self.state))
        self._thread = threading.Thread(target=self._srv.serve_forever, daemon=True)

    @property
    def url(self) -> str:
        host, port = self._srv.server_address
        return f"http://{host}:{port}"

    def __enter__(self):
        self._thread.start()
        return self

    def __exit__(self, *exc):
        self._srv.shutdown()
        self._srv.server_close()
        return False
