"""A minimal fake Kubernetes API server for exercising HttpKube over real
HTTP.

Speaks just enough of the K8s REST surface for the watch plane: GET/PATCH
on apps/v1 deployments (strategic-merge semantics), GET namespaces /
replicasets / pods, POST events, and full CRUD on the two foremast CRDs
(merge-patch, resourceVersion bumping, 404/409/415 error paths). The
object store is plain dicts keyed (namespace, name); the merge logic is
implemented here independently of `watch.kubeapi` so the client's
expectations are validated against a second implementation, not against
itself.

Streaming watch (ISSUE 12 satellite): every mutation logs an rv-ordered
event, and ``GET ...?watch=true&resourceVersion=N&timeoutSeconds=S``
streams the suffix as JSON lines then long-polls until the window ends
— real apiserver semantics including the 410-Gone floor when a resume
point falls behind the compacted event log. `add_watch_fault` injects
stream stalls, mid-JSON-line disconnects, and 410 answers, so watch
tests drive a REAL server misbehaving in real ways, not stubs.
"""

from __future__ import annotations

import copy
import json
import re
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlparse

GROUP = "deployment.foremast.ai"
VERSION = "v1alpha1"

_MERGE_TYPES = {
    "application/strategic-merge-patch+json",
    "application/merge-patch+json",
}


def _merge(dst: dict, patch: dict) -> None:
    for k, v in patch.items():
        if v is None:
            dst.pop(k, None)
        elif isinstance(v, dict) and isinstance(dst.get(k), dict):
            _merge(dst[k], v)
        else:
            dst[k] = v


class FakeKubeState:
    """Shared object store; pre-populate via the typed helpers."""

    def __init__(self):
        self.lock = threading.Lock()
        self.rv = 0
        # kind -> {(namespace or "", name): obj}
        self.objects: dict[str, dict[tuple[str, str], dict]] = {
            "namespaces": {},
            "deployments": {},
            "replicasets": {},
            "pods": {},
            "events": {},
            "deploymentmonitors": {},
            "deploymentmetadatas": {},
        }
        self.requests: list[tuple[str, str, dict]] = []  # (method, path, headers)
        # fault hooks (ISSUE 9 satellite): chaos tests drive a REAL
        # server answering real statuses, not monkeypatched clients.
        # Each fault: {"path": substr, "method": "GET"|None, "status":
        # int(0=none), "latency": seconds, "times": remaining fires
        # (None = forever)} — consumed in registration order.
        self.faults: list[dict] = []
        # streaming watch (ISSUE 12 satellite): every object mutation
        # appends an rv-ordered event here; watch requests stream the
        # suffix past their resourceVersion and then long-poll on the
        # condition until timeoutSeconds. Bounded: compaction past
        # `watch_cap` raises the 410 floor (real apiserver semantics —
        # a resume point older than the window gets Gone).
        self.watch_log: list[dict] = []
        self.watch_cap = 1024
        self.watch_compacted_to = 0  # resume rv below this => 410
        self.watch_cond = threading.Condition(self.lock)
        # injectable stream faults, one consumed per watch REQUEST:
        # {"gone": bool, "after_events": int, "stall_seconds": float,
        #  "disconnect": bool, "times": remaining}
        self.watch_faults: list[dict] = []

    def log_event(self, kind: str, ns: str, etype: str, obj: dict) -> None:
        """Append one watch event (caller holds `self.lock`)."""
        self.watch_log.append(
            {
                "rv": int(obj["metadata"]["resourceVersion"]),
                "kind": kind,
                "ns": ns,
                "type": etype,
                "object": copy.deepcopy(obj),
            }
        )
        if len(self.watch_log) > self.watch_cap:
            drop = len(self.watch_log) - self.watch_cap
            self.watch_compacted_to = self.watch_log[drop - 1]["rv"]
            del self.watch_log[:drop]
        self.watch_cond.notify_all()

    def add_watch_fault(
        self,
        gone: bool = False,
        after_events: int = 0,
        stall_seconds: float = 0.0,
        disconnect: bool = False,
        error_code: int = 0,
        times: int = 1,
    ) -> None:
        """Arm one watch-stream fault: `gone` answers the request 410;
        `disconnect` tears the connection mid-JSON-line after
        `after_events` streamed events; `stall_seconds` holds the
        stream open without writing (the client's stall margin should
        fire) after `after_events`, then resumes normally;
        `error_code` opens the stream 200 then immediately writes a
        ``{"type": "ERROR", "object": {"code": N}}`` event (the real
        apiserver's mid-stream failure shape — 410 = expired resume
        point, anything else = server-side watch failure)."""
        with self.lock:
            self.watch_faults.append(
                {
                    "gone": gone,
                    "after_events": int(after_events),
                    "stall_seconds": float(stall_seconds),
                    "disconnect": disconnect,
                    "error_code": int(error_code),
                    "times": int(times),
                }
            )

    def take_watch_fault(self) -> dict | None:
        with self.lock:
            for f in self.watch_faults:
                if f["times"] > 0:
                    f["times"] -= 1
                    return dict(f)
        return None

    def add_fault(
        self,
        path: str = "",
        method: str | None = None,
        status: int = 0,
        latency: float = 0.0,
        times: int | None = None,
    ) -> None:
        with self.lock:
            self.faults.append(
                {
                    "path": path,
                    "method": method,
                    "status": status,
                    "latency": latency,
                    "times": times,
                }
            )

    def take_fault(self, method: str, path: str) -> dict | None:
        """Pop (decrement) the first matching armed fault, or None."""
        with self.lock:
            for f in self.faults:
                if f["method"] not in (None, method):
                    continue
                if f["path"] and f["path"] not in path:
                    continue
                if f["times"] is not None:
                    if f["times"] <= 0:
                        continue
                    f["times"] -= 1
                return dict(f)
        return None

    def next_rv(self) -> str:
        self.rv += 1
        return str(self.rv)

    def put(self, kind: str, namespace: str, obj: dict) -> dict:
        with self.lock:
            name = obj["metadata"]["name"]
            obj["metadata"].setdefault("namespace", namespace)
            obj["metadata"]["resourceVersion"] = self.next_rv()
            existed = (namespace, name) in self.objects[kind]
            self.objects[kind][(namespace, name)] = obj
            self.log_event(
                kind, namespace, "MODIFIED" if existed else "ADDED", obj
            )
            return obj


# URL patterns -> (kind, namespaced collection)
_ROUTES = [
    (re.compile(r"^/api/v1/namespaces$"), "namespaces", None),
    (re.compile(r"^/api/v1/namespaces/(?P<name>[^/]+)$"), "namespaces", "item"),
    (
        re.compile(r"^/apis/apps/v1(/namespaces/(?P<ns>[^/]+))?/deployments$"),
        "deployments",
        None,
    ),
    (
        re.compile(r"^/apis/apps/v1/namespaces/(?P<ns>[^/]+)/deployments/(?P<name>[^/]+)$"),
        "deployments",
        "item",
    ),
    (
        re.compile(r"^/apis/apps/v1/namespaces/(?P<ns>[^/]+)/replicasets$"),
        "replicasets",
        None,
    ),
    (re.compile(r"^/api/v1/namespaces/(?P<ns>[^/]+)/pods$"), "pods", None),
    (re.compile(r"^/api/v1/namespaces/(?P<ns>[^/]+)/events$"), "events", None),
    (
        re.compile(
            rf"^/apis/{GROUP}/{VERSION}(/namespaces/(?P<ns>[^/]+))?"
            r"/(?P<kind>deploymentmonitors|deploymentmetadatas)$"
        ),
        None,
        None,
    ),
    (
        re.compile(
            rf"^/apis/{GROUP}/{VERSION}/namespaces/(?P<ns>[^/]+)"
            r"/(?P<kind>deploymentmonitors|deploymentmetadatas)/(?P<name>[^/]+)$"
        ),
        None,
        "item",
    ),
]


def _handler(state: FakeKubeState):
    class Handler(BaseHTTPRequestHandler):
        def log_message(self, *a):  # keep test output clean
            pass

        def _send(self, code: int, obj: dict | None = None):
            body = json.dumps(obj or {}).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def _body(self) -> dict:
            n = int(self.headers.get("Content-Length", 0))
            return json.loads(self.rfile.read(n) or b"{}")

        def _route(self):
            from urllib.parse import unquote, urlparse

            path = unquote(urlparse(self.path).path)
            for rx, kind, mode in _ROUTES:
                m = rx.match(path)
                if m:
                    gd = m.groupdict()
                    kind = kind or gd.get("kind")
                    ns = gd.get("ns") or ""
                    name = gd.get("name")
                    return kind, ns, name, mode
            return None, None, None, None

        def _record(self):
            state.requests.append(
                (self.command, self.path, dict(self.headers.items()))
            )

        def _fault(self) -> bool:
            """Apply an armed fault hook; True = request already
            answered (the caller returns immediately)."""
            f = state.take_fault(self.command, self.path)
            if f is None:
                return False
            if f["latency"]:
                import time

                time.sleep(f["latency"])
            if f["status"]:
                self._send(f["status"], {"reason": "injected fault"})
                return True
            return False  # latency-only fault: continue normally

        def do_GET(self):
            self._record()
            if self._fault():
                return
            kind, ns, name, mode = self._route()
            if kind is None:
                return self._send(404, {"reason": "NotFound"})
            qs = parse_qs(urlparse(self.path).query)
            if mode != "item" and qs.get("watch", ["false"])[0] in (
                "true", "1",
            ):
                return self._watch(kind, ns, qs)
            with state.lock:
                store = state.objects[kind]
                if mode == "item" or (kind == "namespaces" and name):
                    key = (ns, name) if kind != "namespaces" else ("", name)
                    if key not in store:
                        return self._send(404, {"reason": "NotFound"})
                    return self._send(200, store[key])
                items = [
                    o
                    for (o_ns, _), o in sorted(store.items())
                    if not ns or o_ns == ns
                ]
                # lists carry the store's resourceVersion (the watch
                # resume point, exactly the real apiserver contract)
                return self._send(
                    200,
                    {
                        "items": items,
                        "metadata": {"resourceVersion": str(state.rv)},
                    },
                )

        def _watch(self, kind, ns, qs):
            """Streaming watch: send every logged event past the
            resume rv as one JSON line each, then long-poll for new
            ones until timeoutSeconds — with injectable 410s, stream
            stalls and torn-line disconnects (take_watch_fault)."""
            try:
                rv = int(qs.get("resourceVersion", ["0"])[0] or 0)
            except ValueError:
                rv = 0
            try:
                timeout_s = float(qs.get("timeoutSeconds", ["30"])[0])
            except ValueError:
                timeout_s = 30.0
            fault = state.take_watch_fault() or {}
            if fault.get("gone"):
                return self._send(410, {"reason": "Expired", "code": 410})
            with state.lock:
                if rv < state.watch_compacted_to:
                    # the resume point (rv=0 "from the start" included)
                    # fell out of the retained window — streaming only
                    # the surviving suffix would silently lose events
                    return self._send(
                        410, {"reason": "Expired", "code": 410}
                    )
            self.send_response(200)
            self.send_header("Content-Type", "application/json")
            self.end_headers()  # no Content-Length: close-delimited stream
            if fault.get("error_code"):
                try:
                    self.wfile.write(
                        json.dumps(
                            {
                                "type": "ERROR",
                                "object": {
                                    "kind": "Status",
                                    "code": fault["error_code"],
                                },
                            }
                        ).encode()
                        + b"\n"
                    )
                    self.wfile.flush()
                except OSError:
                    pass
                return
            deadline = time.monotonic() + timeout_s
            sent = 0
            stalled = False
            try:
                while True:
                    with state.lock:
                        pending = [
                            e
                            for e in state.watch_log
                            if e["rv"] > rv
                            and e["kind"] == kind
                            and (not ns or e["ns"] == ns)
                        ]
                    for e in pending:
                        line = json.dumps(
                            {"type": e["type"], "object": e["object"]}
                        ).encode() + b"\n"
                        if (
                            fault.get("disconnect")
                            and sent >= fault.get("after_events", 0)
                        ):
                            # torn tail: half a JSON line, then the
                            # connection dies (client must resume from
                            # the last APPLIED rv, not the torn one)
                            self.wfile.write(line[: max(3, len(line) // 2)])
                            self.wfile.flush()
                            self.close_connection = True
                            return
                        if (
                            fault.get("stall_seconds", 0.0) > 0
                            and sent >= fault.get("after_events", 0)
                            and not stalled
                        ):
                            # hold the stream open without writing:
                            # the client's stall margin should fire
                            time.sleep(fault["stall_seconds"])
                            stalled = True
                        self.wfile.write(line)
                        self.wfile.flush()
                        rv = e["rv"]
                        sent += 1
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        return  # clean window end: client reconnects
                    with state.watch_cond:
                        state.watch_cond.wait(min(0.05, remaining))
            except OSError:
                return  # client went away mid-stream

        def do_POST(self):
            self._record()
            if self._fault():
                return
            kind, ns, name, mode = self._route()
            if kind is None or mode == "item":
                return self._send(404, {"reason": "NotFound"})
            obj = self._body()
            with state.lock:
                oname = obj.get("metadata", {}).get("name") or f"gen-{state.rv}"
                obj.setdefault("metadata", {})["name"] = oname
                key = (ns, oname)
                if key in state.objects[kind] and kind != "events":
                    return self._send(409, {"reason": "AlreadyExists"})
                obj["metadata"]["namespace"] = ns
                obj["metadata"]["resourceVersion"] = state.next_rv()
                state.objects[kind][key] = obj
                state.log_event(kind, ns, "ADDED", obj)
                return self._send(201, obj)

        def do_PUT(self):
            self._record()
            if self._fault():
                return
            kind, ns, name, mode = self._route()
            if kind is None or mode != "item":
                return self._send(404, {"reason": "NotFound"})
            obj = self._body()
            with state.lock:
                key = (ns, name)
                store = state.objects[kind]
                if key not in store:
                    return self._send(404, {"reason": "NotFound"})
                current = store[key]
                # optimistic concurrency: stale resourceVersion conflicts
                sent_rv = obj.get("metadata", {}).get("resourceVersion")
                if sent_rv and sent_rv != current["metadata"]["resourceVersion"]:
                    return self._send(409, {"reason": "Conflict"})
                obj.setdefault("metadata", {})["namespace"] = ns
                obj["metadata"]["name"] = name
                obj["metadata"]["resourceVersion"] = state.next_rv()
                store[key] = obj
                state.log_event(kind, ns, "MODIFIED", obj)
                return self._send(200, obj)

        def do_PATCH(self):
            self._record()
            if self._fault():
                return
            kind, ns, name, mode = self._route()
            if kind is None or mode != "item":
                return self._send(404, {"reason": "NotFound"})
            ctype = self.headers.get("Content-Type", "")
            if ctype not in _MERGE_TYPES:
                return self._send(415, {"reason": "UnsupportedMediaType"})
            patch = self._body()
            with state.lock:
                key = (ns, name) if kind != "namespaces" else ("", name)
                store = state.objects[kind]
                if key not in store:
                    return self._send(404, {"reason": "NotFound"})
                _merge(store[key], patch)
                store[key]["metadata"]["resourceVersion"] = state.next_rv()
                state.log_event(kind, ns, "MODIFIED", store[key])
                return self._send(200, store[key])

        def do_DELETE(self):
            self._record()
            if self._fault():
                return
            kind, ns, name, mode = self._route()
            if kind is None or mode != "item":
                return self._send(404, {"reason": "NotFound"})
            with state.lock:
                key = (ns, name)
                if key not in state.objects[kind]:
                    return self._send(404, {"reason": "NotFound"})
                gone = state.objects[kind].pop(key)
                gone["metadata"]["resourceVersion"] = state.next_rv()
                state.log_event(kind, ns, "DELETED", gone)
                return self._send(200, {"status": "Success"})

    return Handler


class FakeKubeServer:
    """Context manager: spins up the server on an ephemeral localhost port."""

    def __init__(self):
        self.state = FakeKubeState()
        self._srv = ThreadingHTTPServer(("127.0.0.1", 0), _handler(self.state))
        self._thread = threading.Thread(target=self._srv.serve_forever, daemon=True)

    @property
    def url(self) -> str:
        host, port = self._srv.server_address
        return f"http://{host}:{port}"

    def __enter__(self):
        self._thread.start()
        return self

    def __exit__(self, *exc):
        self._srv.shutdown()
        self._srv.server_close()
        return False
