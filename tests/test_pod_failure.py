"""Pod-mode failure semantics (VERDICT r5 #6): the LEADER dies mid-tick.

Pod mode concentrates the reference's worker-death risk: one logical
worker spans every process, the leader alone talks to ES/Prometheus,
and every fetch is a broadcast collective. This test kills the leader
process mid-tick — AFTER the claim is persisted (documents sit in
`preprocess_inprogress` on the real store) but BEFORE any verdict — and
asserts the two halves of the recovery story documented in
docs/operations.md:

  1. FOLLOWERS FAIL FAST: the surviving process's next collective
     errors out and the process EXITS (nonzero) within the test budget —
     no silent hang waiting on a dead coordinator.
  2. NOTHING IS LOST OR DOUBLE-SCORED: the in-flight claims age out
     after MAX_STUCK_IN_SECONDS and a restarted worker takes them over
     via the store's CAS claim (the reference's work-stealing,
     design.md:39); every document lands exactly one verdict, identical
     to a single-process run of the same fleet.

The store is the parent's fake-ES cluster behind a real HTTP socket, so
it survives the pod like production ES would.
"""

from __future__ import annotations

import json
import os
import socket
import subprocess
import sys
import threading
import time

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
NOW = 1_760_000_000.0
SERVICES = 4
HIST_LEN = 64
CUR_LEN = 16


@pytest.fixture(scope="module", autouse=True)
def lock_witness():
    """ISSUE 8: the runtime lock witness rides this module — the mesh
    crash/restart tests drive the InMemory claim path through the mesh
    partition filter (store lock -> router lock) and the restart tests
    replay the snapshot plane, all on real threads. At teardown every
    OBSERVED acquisition edge must exist in the committed static lock
    graph (the subprocess workers are outside this process's witness;
    their lock topology is the same code the in-process tests cover)."""
    from foremast_tpu.analysis import witness

    wit = witness.install()
    yield wit
    graph = witness.load_graph()
    witness.uninstall()
    assert graph is not None, "analysis_lockgraph.json missing from repo root"
    missing = wit.unobserved_edges(graph)
    assert not missing, (
        "runtime lock-acquisition edges missing from the static graph "
        f"(run `make lockgraph` and review): {missing}"
    )


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _serve_fake_es():
    from test_multihost_worker import _serve_fake_es as serve

    return serve()


_CHILD = """
import os, sys
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
# tight collective watchdog: the follower must abandon a dead leader's
# broadcast well inside the test's 180 s hang budget (60 s, not the
# production 300 s default — but wide enough for process-startup skew
# on a loaded CI host, where one interpreter can trail the other by
# tens of seconds before the first collective)
os.environ["FOREMAST_POD_TIMEOUT_SECONDS"] = "60"
import jax
jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_cpu_collectives_implementation", "gloo")
except Exception:
    pass
addr, pid, es_url = sys.argv[1], int(sys.argv[2]), sys.argv[3]
jax.distributed.initialize(addr, 2, pid)

sys.path.insert(0, {repo!r})
from benchmarks.worker_bench import build_fleet
from foremast_tpu.config import BrainConfig
from foremast_tpu.jobs.store import ElasticsearchStore
from foremast_tpu.parallel import LeaderSource, LeaderStore, PodWorker

NOW = {now!r}
leader = pid == 0
if leader:
    _, source_in = build_fleet({services}, {hist_len}, {cur_len}, NOW)

    class DyingSource:
        # the real source, but the LEADER PROCESS DIES on the 3rd fetch
        # of the tick — after the claim was persisted to ES, before any
        # verdict. os._exit: a crash, not an exception (no cleanup, no
        # broadcast of an error object — the pod's worst case).
        concurrent_fetch = False
        def __init__(self, inner):
            self.inner = inner
            self.calls = 0
        def fetch(self, url):
            self.calls += 1
            if self.calls >= 3:
                os._exit(17)
            return self.inner.fetch(url)

    store_in = ElasticsearchStore(es_url)
    source = LeaderSource(DyingSource(source_in))
else:
    store_in = None
    source = LeaderSource(None)
store = LeaderStore(store_in)
cfg = BrainConfig(algorithm="moving_average_all", max_stuck_seconds=90.0)
worker = PodWorker(
    store, source, config=cfg, claim_limit={services},
    worker_id=f"pod-{{pid}}",
)
print(f"proc {{pid}} ticking", flush=True)
worker.tick(now=NOW + 150)  # leader dies inside; follower must ERROR
print(f"proc {{pid}} SURVIVED", flush=True)  # only reachable on a bug
"""


def test_leader_death_mid_tick_fails_fast_and_recovers(tmp_path):
    from benchmarks.worker_bench import build_fleet
    from foremast_tpu.config import BrainConfig
    from foremast_tpu.jobs.models import (
        STATUS_PREPROCESS_INPROGRESS,
        TERMINAL_STATUSES,
    )
    from foremast_tpu.jobs.store import ElasticsearchStore
    from foremast_tpu.jobs.worker import BrainWorker

    srv, fake = _serve_fake_es()
    try:
        url = f"http://127.0.0.1:{srv.server_address[1]}"
        parent_store = ElasticsearchStore(url)
        parent_store.ensure_index()
        fleet_store, _ = build_fleet(SERVICES, HIST_LEN, CUR_LEN, NOW)
        for doc in fleet_store._docs.values():
            parent_store.create(doc)

        child = tmp_path / "pod_child.py"
        child.write_text(
            _CHILD.format(
                repo=REPO,
                now=NOW,
                services=SERVICES,
                hist_len=HIST_LEN,
                cur_len=CUR_LEN,
            )
        )
        addr = f"127.0.0.1:{_free_port()}"
        env = {
            k: v for k, v in os.environ.items() if not k.startswith("JAX_")
        }
        t0 = time.monotonic()
        procs = [
            subprocess.Popen(
                [sys.executable, str(child), addr, str(pid), url],
                stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT,
                text=True,
                env=env,
            )
            for pid in (0, 1)
        ]
        outs = []
        try:
            for p in procs:
                out, _ = p.communicate(timeout=180)
                outs.append(out)
        finally:
            for p in procs:
                if p.poll() is None:
                    p.kill()
        elapsed = time.monotonic() - t0

        # the leader crashed with its marker code; the follower FAILED
        # FAST — nonzero exit, no hang (the 180 s communicate timeout is
        # the hang detector), and it never completed the tick
        assert procs[0].returncode == 17, outs[0]
        assert procs[1].returncode not in (0, None), outs[1]
        assert "SURVIVED" not in outs[1], outs[1]
        assert elapsed < 175, f"follower hung for {elapsed:.0f}s"

        # the claim is parked on the store: in-progress, owned by the
        # dead pod — exactly what MAX_STUCK_IN_SECONDS exists for
        stuck = [
            d["_source"]
            for d in fake.docs.values()
            if d["_source"]["status"] == STATUS_PREPROCESS_INPROGRESS
        ]
        assert stuck, "leader died before persisting any claim"

        # restarted pod (single process suffices — the store contract is
        # identical): past the stuck window, CAS takeover re-claims and
        # every document lands exactly one verdict. The stuck clock is
        # the store's WALL clock (modified_at), so the test shrinks
        # MAX_STUCK_IN_SECONDS instead of sleeping the production 90 s.
        _, source = build_fleet(SERVICES, HIST_LEN, CUR_LEN, NOW)
        takeover = BrainWorker(
            ElasticsearchStore(url),
            source,
            config=BrainConfig(
                algorithm="moving_average_all", max_stuck_seconds=2.0
            ),
            claim_limit=SERVICES,
            worker_id="takeover",
        )
        # age the dead pod's claims past the window, then tick until the
        # takeover lands (modified_at has second granularity and the
        # claim clock is wall time, so a fixed sleep is load-flaky);
        # `now` past endTime so every doc finalizes on this judgment
        total = 0
        deadline = time.monotonic() + 60
        while total < SERVICES and time.monotonic() < deadline:
            time.sleep(1.0)
            total += takeover.tick(now=NOW + 7200)
        assert total == SERVICES, f"takeover claimed {total} != {SERVICES}"

        # no lost docs, no duplicates: every document terminal, judged
        # by the takeover worker, matching the single-process reference
        ref_store, ref_source = build_fleet(SERVICES, HIST_LEN, CUR_LEN, NOW)
        ref = BrainWorker(
            ref_store,
            ref_source,
            config=BrainConfig(algorithm="moving_average_all"),
            claim_limit=SERVICES,
            worker_id="ref",
        )
        assert ref.tick(now=NOW + 7200) == SERVICES
        want = {
            d.id: (d.status, json.dumps(d.anomaly_info, sort_keys=True))
            for d in ref_store._docs.values()
        }
        assert len(fake.docs) == SERVICES
        for doc_id, (status, anom) in want.items():
            rec = fake.docs[doc_id]["_source"]
            assert rec["status"] == status, (doc_id, rec["status"], status)
            assert rec["status"] in TERMINAL_STATUSES
            assert rec["processingContent"] == "takeover"
        # a second tick finds nothing claimable: no verdict re-issued
        assert takeover.tick(now=NOW + 7300) == 0
    finally:
        srv.shutdown()


# ---------------------------------------------------------------------------
# ISSUE 6: a 3-worker MESH loses one worker mid-tick
# ---------------------------------------------------------------------------


class _Die(BaseException):
    """Raised from the victim's source mid-tick: a BaseException, so no
    worker-level Exception handler can soften the crash — the claim is
    persisted, no verdict is written, exactly the pod test's worst
    case at mesh scale."""


def test_mesh_worker_death_rebalances_within_two_ticks():
    """Three mesh workers share one store and partition a 12-service
    fleet by consistent hash. Worker w2 dies mid-tick (after its claim
    persisted, before any verdict). Asserts:

      1. the steady state judges every document exactly once per round,
         each by its one owner;
      2. after w2's lease expires, the ring heals and the SURVIVORS
         re-judge every orphaned document within 2 ticks — exactly
         once, via the existing stuck-claim takeover;
      3. ownership converges: each orphan's new judge is the healed
         ring's owner for it.

    Clocks are injected (membership leases never sleep); only the
    stuck-claim aging crosses a real ~1 s wall-clock second, because
    the store stamps modified_at with wall time."""
    from benchmarks.worker_bench import build_fleet
    from foremast_tpu.config import BrainConfig
    from foremast_tpu.jobs.models import STATUS_PREPROCESS_INPROGRESS
    from foremast_tpu.jobs.worker import BrainWorker
    from foremast_tpu.mesh import MESH_APP, Membership, MeshNode, MeshRouter

    SERVICES_M = 12
    store, source = build_fleet(SERVICES_M, HIST_LEN, CUR_LEN, NOW)

    clock = [1000.0]
    judged: list[tuple[str, str]] = []  # (doc_id, worker) per judgment

    orig_update, orig_many = store.update, store.update_many

    def _rec(doc, worker):
        # membership heartbeats ride the same store — not judgments
        if doc.app_name == MESH_APP:
            return
        if doc.status != STATUS_PREPROCESS_INPROGRESS:
            judged.append((doc.id, worker))

    class _DyingSource:
        concurrent_fetch = False

        def __init__(self, inner):
            self.inner = inner
            self.armed = False
            self.calls = 0

        def fetch(self, url):
            if self.armed:
                self.calls += 1
                if self.calls >= 3:
                    raise _Die()
            return self.inner.fetch(url)

    workers = {}
    nodes = {}
    dying = None
    for wid in ("w0", "w1", "w2"):
        mem = Membership(
            store, wid, lease_seconds=10.0, clock=lambda: clock[0]
        )
        router = MeshRouter(
            mem, refresh_seconds=0.0, clock=lambda: clock[0]
        )
        node = MeshNode(mem, router, clock=lambda: clock[0])
        node.start()
        nodes[wid] = node
        src = source
        if wid == "w2":
            dying = _DyingSource(source)
            src = dying
        w = BrainWorker(
            store,
            src,
            config=BrainConfig(
                algorithm="moving_average_all", max_stuck_seconds=0.0
            ),
            claim_limit=SERVICES_M,
            worker_id=wid,
            mesh=node,
        )
        workers[wid] = w
    for node in nodes.values():
        node.router.refresh(force=True)  # everyone sees all three

    current_worker = [""]

    def _u(doc):
        _rec(doc, current_worker[0])
        return orig_update(doc)

    def _um(docs):
        for d in docs:
            _rec(d, current_worker[0])
        return orig_many(docs)

    store.update, store.update_many = _u, _um

    def tick_all(now, who=("w0", "w1", "w2")):
        total = 0
        for wid in who:
            current_worker[0] = wid
            total += workers[wid].tick(now=now)
        return total

    # round 1 (cold) + round 2 (warm): every doc judged exactly once per
    # round, partitions disjoint and total
    assert tick_all(NOW + 150) == SERVICES_M
    owner_of = {
        doc_id: wid
        for doc_id, wid in judged
    }
    assert len(owner_of) == SERVICES_M
    assert len(judged) == SERVICES_M  # nothing judged twice
    judged.clear()
    clock[0] += 4.0
    assert tick_all(NOW + 160) == SERVICES_M
    assert {d: w for d, w in judged} == owner_of  # stable ownership
    assert len(judged) == SERVICES_M
    orphans = {d for d, w in owner_of.items() if w == "w2"}
    assert orphans, "w2 owned nothing — hash ring degenerate?"
    judged.clear()

    # round 3: w2 dies MID-TICK — claim persisted, then the source
    # blows up before any write-back; w0/w1 finish their ticks clean
    clock[0] += 4.0
    assert tick_all(NOW + 170, who=("w0", "w1")) == SERVICES_M - len(orphans)
    dying.armed = True
    current_worker[0] = "w2"
    import pytest as _pytest

    with _pytest.raises(_Die):
        workers["w2"].tick(now=NOW + 170)
    parked = {
        d.id
        for d in store._docs.values()
        if d.status == STATUS_PREPROCESS_INPROGRESS
    }
    assert parked == orphans  # the whole partition is stuck in-progress
    judged.clear()

    # w2's lease expires (fake clock); the store's stuck window is
    # max_stuck_seconds=0 but modified_at has 1 s granularity — cross it.
    # The survivors renew first: a live worker heartbeats every lease/3,
    # so the artificial clock jump must not expire THEIR leases too.
    clock[0] += 11.0
    nodes["w0"].membership.renew(force=True)
    nodes["w1"].membership.renew(force=True)
    time.sleep(1.1)

    # rounds 4..5: survivors only. The ≤2-tick bar: every orphan judged
    # (exactly once, by a survivor) within two survivor rounds.
    ticks_needed = 0
    for _ in range(2):
        ticks_needed += 1
        tick_all(NOW + 180, who=("w0", "w1"))
        if {d for d, _ in judged} >= orphans:
            break
        time.sleep(1.1)  # stuck-stamp granularity between rounds
    post = {}
    for d, w in judged:
        assert d not in post or post[d] == w, f"{d} judged twice"
        post.setdefault(d, w)
    assert {d for d in post} == set(owner_of)  # every doc judged again
    assert ticks_needed <= 2
    counts = {}
    for d, _w in judged:
        counts[d] = counts.get(d, 0) + 1
    assert all(n == 1 for n in counts.values()), counts

    # ownership converged onto the healed ring: each orphan's judge is
    # the ring's post-death owner, and w2 judged nothing
    for d in orphans:
        assert post[d] in ("w0", "w1")
        doc = store._docs[d]
        assert post[d] == nodes["w0"].router.owner_of_doc(doc)
    store.update, store.update_many = orig_update, orig_many


# ---------------------------------------------------------------------------
# ISSUE 7: crash-injection harness — kill a DURABLE worker mid-tick,
# restart it, and prove the restart is warm (≥ 90% fast-path, ZERO
# fallback fetches, no lost or duplicated verdicts). The `make
# bench-restart` harness does the same with a real SIGKILLed
# subprocess; these pin the contract in tier-1.
# ---------------------------------------------------------------------------


class _CountingSource:
    """Wraps the would-be pull path (Prometheus in production) and
    counts every fetch that reaches it — the "zero fallback HTTP
    fetches" meter."""

    concurrent_fetch = False

    def __init__(self, inner):
        self.inner = inner
        self.calls = 0

    def fetch(self, url):
        self.calls += 1
        return self.inner.fetch(url)


class _DyingRing:
    """Wraps a RingSource; once armed, the worker's Nth fetch raises a
    BaseException — mid-tick, AFTER the claim persisted, BEFORE any
    verdict (worker-level Exception handlers must not soften it, same
    shape as the mesh kill test). The files on disk are whatever the
    journals flushed: exactly the SIGKILL situation."""

    concurrent_fetch = False

    def __init__(self, inner, die_at=3):
        self.inner = inner
        self.armed = False
        self.calls = 0
        self.die_at = die_at

    def fetch(self, url):
        if self.armed:
            self.calls += 1
            if self.calls >= self.die_at:
                raise _Die()
        return self.inner.fetch(url)

    # the ring-first cold path is part of the wrapped surface (a
    # production worker sees RingSource directly)
    def hist_columns(self, url, now=None):
        return self.inner.hist_columns(url, now)

    def hist_coverage(self, url, now=None):
        return self.inner.hist_coverage(url, now)

    def ingest_debug_state(self):
        return self.inner.ingest_debug_state()


def _durable_worker(store, snap_dir, worker_id, data_now, fallback, *,
                    mesh=None, max_stuck=0.0):
    """One worker with the full durable data plane mounted: RingSource
    over a fresh RingStore, snapshot restore + journal attach, fit
    journals restored lazily. Returns (worker, snapshotter, dying)."""
    from foremast_tpu.config import BrainConfig
    from foremast_tpu.ingest import RingSnapshotter, RingSource, RingStore
    from foremast_tpu.jobs.worker import BrainWorker

    ring = RingStore(shards=2)
    snap = RingSnapshotter(ring, snap_dir, clock=lambda: data_now[0])
    snap.restore()
    snap.attach()
    src = RingSource(ring, fallback=fallback, clock=lambda: data_now[0])
    dying = _DyingRing(src)
    worker = BrainWorker(
        store,
        dying,
        config=BrainConfig(
            algorithm="moving_average_all",
            max_stuck_seconds=max_stuck,
            max_cache_size=256,
        ),
        claim_limit=64,
        worker_id=worker_id,
        mesh=mesh,
    )
    worker.enable_fit_persistence(snap_dir)
    worker.attach_ring_snapshotter(snap)
    return worker, snap, dying


def test_worker_crash_mid_tick_restarts_warm(tmp_path):
    """Single-worker crash harness: kill mid-tick after two healthy
    ticks, restart against the same snapshot dir, and assert the next
    tick is 100% fast-path with ZERO fallback fetches and every parked
    document re-judged exactly once (statuses identical to a worker
    that never crashed)."""
    from benchmarks.scaleout_bench import SynthSource, build_fleet
    from foremast_tpu.config import BrainConfig
    from foremast_tpu.jobs.models import (
        STATUS_PREPROCESS_COMPLETED,
        STATUS_PREPROCESS_INPROGRESS,
    )
    from foremast_tpu.jobs.store import InMemoryStore
    from foremast_tpu.jobs.worker import BrainWorker

    SERVICES_D = 8
    snap_dir = str(tmp_path / "durable")
    store = InMemoryStore()
    build_fleet(store, SERVICES_D, 2, HIST_LEN, CUR_LEN, int(NOW))

    data_now = [NOW + 150.0]
    fb1 = _CountingSource(SynthSource())
    w1, snap1, dying1 = _durable_worker(
        store, snap_dir, "w-dur", data_now, fb1
    )
    assert w1.tick(now=data_now[0]) == SERVICES_D  # cold: fits + backfill
    cold_fallback = fb1.calls
    assert cold_fallback > 0
    data_now[0] = NOW + 160
    assert w1.tick(now=data_now[0]) == SERVICES_D
    assert w1._last_tick["fast"] == SERVICES_D  # warm before the crash
    assert fb1.calls == cold_fallback  # warm tick: zero fallback already
    snap1.snapshot()  # a mid-life snapshot pass (logs cover the rest)

    # CRASH mid-tick: claim persisted, fetch #3 explodes, no verdict
    dying1.armed = True
    data_now[0] = NOW + 170
    import pytest as _pytest

    with _pytest.raises(_Die):
        w1.tick(now=data_now[0])
    parked = [
        d for d in store._docs.values()
        if d.status == STATUS_PREPROCESS_INPROGRESS
    ]
    assert parked, "crash landed before any claim persisted"
    # the dead process's file handles just vanish — no close(), no
    # final snapshot; restore must work from whatever was flushed

    # RESTART: fresh ring, fresh caches, same directory
    judged: list[str] = []
    orig_update, orig_many = store.update, store.update_many

    def _u(doc):
        if doc.status != STATUS_PREPROCESS_INPROGRESS:
            judged.append(doc.id)
        return orig_update(doc)

    def _um(docs):
        for d in docs:
            if d.status != STATUS_PREPROCESS_INPROGRESS:
                judged.append(d.id)
        return orig_many(docs)

    store.update, store.update_many = _u, _um
    try:
        data_now2 = [NOW + 400.0]
        fb2 = _CountingSource(SynthSource())
        w2, snap2, _ = _durable_worker(
            store, snap_dir, "w-dur", data_now2, fb2
        )
        restored = w2.debug_state()["durability"]
        assert restored["ring"]["restored_series"] > 0
        time.sleep(1.1)  # stuck-claim stamp granularity (wall clock)
        n = w2.tick(now=data_now2[0])
        assert n == SERVICES_D
        # THE acceptance bar: ≥ 90% fast path, zero fallback fetches
        assert w2._last_tick["fast"] >= 0.9 * SERVICES_D
        assert fb2.calls == 0, (
            f"restarted worker fell back {fb2.calls} times"
        )
        # no lost, no duplicated verdicts; statuses match the no-crash
        # steady state (open docs keep re-checking)
        assert sorted(judged) == sorted(d.id for d in store._docs.values())
        assert all(
            d.status == STATUS_PREPROCESS_COMPLETED
            for d in store._docs.values()
        )
    finally:
        store.update, store.update_many = orig_update, orig_many
        w1.close()
        w2.close()
        snap1.close()
        snap2.close()


def test_restored_ring_serves_recovery_cold_fits_zero_fallback(tmp_path):
    """Durability × cold-start interplay (ISSUE 10 satellite): even
    when the fit journals are LOST across a SIGKILL (only the ring
    snapshot/log survives), the restarted worker's recovery tick
    re-fits every document COLD — and those cold fits read the
    restored ring's resident columns, zero fallback HTTP fetches."""
    import os as _os

    from benchmarks.scaleout_bench import SynthSource, build_fleet
    from foremast_tpu.jobs.models import STATUS_PREPROCESS_COMPLETED
    from foremast_tpu.jobs.store import InMemoryStore

    SERVICES_D = 6
    snap_dir = str(tmp_path / "durable-cold")
    store = InMemoryStore()
    build_fleet(store, SERVICES_D, 2, HIST_LEN, CUR_LEN, int(NOW))

    data_now = [NOW + 150.0]
    fb1 = _CountingSource(SynthSource())
    w1, snap1, dying1 = _durable_worker(
        store, snap_dir, "w-coldfit", data_now, fb1
    )
    assert w1.tick(now=data_now[0]) == SERVICES_D  # cold: backfills ring
    snap1.snapshot()
    # CRASH mid-tick (claim persisted, no verdict)
    dying1.armed = True
    data_now[0] = NOW + 160
    import pytest as _pytest

    with _pytest.raises(_Die):
        w1.tick(now=data_now[0])

    # the fit journals are LOST (disk swap, operator wipe, version
    # bump): only the ring state survives
    for name in _os.listdir(snap_dir):
        if name.startswith("fit-"):
            _os.unlink(_os.path.join(snap_dir, name))

    data_now2 = [NOW + 400.0]
    fb2 = _CountingSource(SynthSource())
    w2, snap2, _ = _durable_worker(
        store, snap_dir, "w-coldfit", data_now2, fb2
    )
    try:
        dur = w2.debug_state()["durability"]
        assert dur["ring"]["restored_series"] > 0
        assert all(
            j["restored_entries"] == 0
            for j in dur["fit_journals"].values()
        )
        time.sleep(1.1)  # stuck-claim stamp granularity (wall clock)
        n = w2.tick(now=data_now2[0])
        assert n == SERVICES_D
        # every doc re-fit COLD (no fits survived) ...
        assert w2._last_tick["fast"] == 0
        # ... and every cold fit read the restored ring: zero fallback
        assert fb2.calls == 0, (
            f"recovery cold fits fell back {fb2.calls} times"
        )
        reads = w2.debug_state()["cold_start"]["hist_reads"]
        assert reads["ring_full"] >= SERVICES_D
        assert reads["http"] == 0 and reads["cache"] == 0
        assert all(
            d.status == STATUS_PREPROCESS_COMPLETED
            for d in store._docs.values()
        )
    finally:
        w1.close()
        w2.close()
        snap1.close()
        snap2.close()


def test_mesh_worker_crash_restart_reclaims_partition_warm(tmp_path):
    """3-worker mesh crash harness: w2 (durable) dies mid-tick, then
    RESTARTS under the same worker id + snapshot dir BEFORE its lease
    expires. The ring never moves: the restarted worker re-takes its
    seat, reclaims exactly its own parked partition, and judges it
    ≥ 90% fast-path with zero fallback fetches — while the survivors'
    partitions are untouched (no double judgment anywhere)."""
    from benchmarks.scaleout_bench import SynthSource, build_fleet
    from foremast_tpu.config import BrainConfig
    from foremast_tpu.jobs.models import STATUS_PREPROCESS_INPROGRESS
    from foremast_tpu.jobs.worker import BrainWorker
    from foremast_tpu.mesh import MESH_APP, Membership, MeshNode, MeshRouter
    from foremast_tpu.jobs.store import InMemoryStore

    SERVICES_M = 12
    store = InMemoryStore()
    build_fleet(store, SERVICES_M, 2, HIST_LEN, CUR_LEN, int(NOW))
    clock = [1000.0]
    data_now = [NOW + 150.0]
    judged: list[tuple[str, str]] = []
    current_worker = [""]
    orig_update, orig_many = store.update, store.update_many

    def _rec(doc):
        if doc.app_name == MESH_APP:
            return
        if doc.status != STATUS_PREPROCESS_INPROGRESS:
            judged.append((doc.id, current_worker[0]))

    def _u(doc):
        _rec(doc)
        return orig_update(doc)

    def _um(docs):
        for d in docs:
            _rec(d)
        return orig_many(docs)

    store.update, store.update_many = _u, _um

    def mesh_node(wid):
        mem = Membership(
            store, wid, lease_seconds=60.0, clock=lambda: clock[0]
        )
        router = MeshRouter(mem, refresh_seconds=0.0, clock=lambda: clock[0])
        node = MeshNode(mem, router, clock=lambda: clock[0])
        node.start()
        return node

    workers = {}
    snaps = {}
    fallbacks = {}
    nodes = {}
    dying = None
    try:
        for wid in ("w0", "w1", "w2"):
            nodes[wid] = mesh_node(wid)
            fallbacks[wid] = _CountingSource(SynthSource())
            w, snap, d = _durable_worker(
                store, str(tmp_path / wid), wid, data_now, fallbacks[wid],
                mesh=nodes[wid],
            )
            workers[wid] = w
            snaps[wid] = snap
            if wid == "w2":
                dying = d
        for node in nodes.values():
            node.router.refresh(force=True)

        def tick_all(now, who=("w0", "w1", "w2")):
            total = 0
            for wid in who:
                current_worker[0] = wid
                total += workers[wid].tick(now=now)
            return total

        # rounds 1 (cold) + 2 (warm): disjoint total partitions
        assert tick_all(NOW + 150) == SERVICES_M
        owner_of = dict(judged)
        assert len(judged) == SERVICES_M
        judged.clear()
        clock[0] += 4.0
        data_now[0] = NOW + 160
        assert tick_all(NOW + 160) == SERVICES_M
        assert {d: w for d, w in judged} == owner_of
        orphans = {d for d, w in owner_of.items() if w == "w2"}
        assert orphans, "w2 owned nothing — ring degenerate?"
        judged.clear()

        # round 3: w2 dies mid-tick; its partition parks in-progress
        clock[0] += 4.0
        data_now[0] = NOW + 170
        tick_all(NOW + 170, who=("w0", "w1"))
        dying.armed = True
        current_worker[0] = "w2"
        import pytest as _pytest

        with _pytest.raises(_Die):
            workers["w2"].tick(now=NOW + 170)
        parked = {
            d.id
            for d in store._docs.values()
            if d.status == STATUS_PREPROCESS_INPROGRESS
        }
        assert parked == orphans
        judged.clear()

        # RESTART w2 (same id, same dir) BEFORE the lease expires: the
        # ring does not move, so nothing rebalances away from it
        fb2 = _CountingSource(SynthSource())
        fallbacks["w2-restarted"] = fb2
        nodes["w2r"] = mesh_node("w2")
        data_now[0] = NOW + 400
        w2r, snap2r, _ = _durable_worker(
            store, str(tmp_path / "w2"), "w2", data_now, fb2,
            mesh=nodes["w2r"],
        )
        workers["w2r"] = w2r
        snaps["w2r"] = snap2r
        assert len(nodes["w2r"].router.members()) == 3  # re-joined seat
        time.sleep(1.1)  # stuck-claim stamp granularity
        clock[0] += 4.0
        current_worker[0] = "w2"
        n = w2r.tick(now=NOW + 400)
        # reclaimed EXACTLY its partition, warm, zero fallback
        assert n == len(orphans)
        assert {d for d, _ in judged} == orphans
        assert len(judged) == len(orphans)  # exactly once each
        assert w2r._last_tick["fast"] >= 0.9 * len(orphans)
        assert fb2.calls == 0
        # survivors' partitions were never touched by the restart
        assert all(w == "w2" for _, w in judged)
    finally:
        store.update, store.update_many = orig_update, orig_many
        for w in workers.values():
            w.close()
        for s in snaps.values():
            s.close()
