"""Pod-mode failure semantics (VERDICT r5 #6): the LEADER dies mid-tick.

Pod mode concentrates the reference's worker-death risk: one logical
worker spans every process, the leader alone talks to ES/Prometheus,
and every fetch is a broadcast collective. This test kills the leader
process mid-tick — AFTER the claim is persisted (documents sit in
`preprocess_inprogress` on the real store) but BEFORE any verdict — and
asserts the two halves of the recovery story documented in
docs/operations.md:

  1. FOLLOWERS FAIL FAST: the surviving process's next collective
     errors out and the process EXITS (nonzero) within the test budget —
     no silent hang waiting on a dead coordinator.
  2. NOTHING IS LOST OR DOUBLE-SCORED: the in-flight claims age out
     after MAX_STUCK_IN_SECONDS and a restarted worker takes them over
     via the store's CAS claim (the reference's work-stealing,
     design.md:39); every document lands exactly one verdict, identical
     to a single-process run of the same fleet.

The store is the parent's fake-ES cluster behind a real HTTP socket, so
it survives the pod like production ES would.
"""

from __future__ import annotations

import json
import os
import socket
import subprocess
import sys
import threading
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
NOW = 1_760_000_000.0
SERVICES = 4
HIST_LEN = 64
CUR_LEN = 16


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _serve_fake_es():
    from test_multihost_worker import _serve_fake_es as serve

    return serve()


_CHILD = """
import os, sys
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
# tight collective watchdog: the follower must abandon a dead leader's
# broadcast well inside the test's 180 s hang budget (60 s, not the
# production 300 s default — but wide enough for process-startup skew
# on a loaded CI host, where one interpreter can trail the other by
# tens of seconds before the first collective)
os.environ["FOREMAST_POD_TIMEOUT_SECONDS"] = "60"
import jax
jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_cpu_collectives_implementation", "gloo")
except Exception:
    pass
addr, pid, es_url = sys.argv[1], int(sys.argv[2]), sys.argv[3]
jax.distributed.initialize(addr, 2, pid)

sys.path.insert(0, {repo!r})
from benchmarks.worker_bench import build_fleet
from foremast_tpu.config import BrainConfig
from foremast_tpu.jobs.store import ElasticsearchStore
from foremast_tpu.parallel import LeaderSource, LeaderStore, PodWorker

NOW = {now!r}
leader = pid == 0
if leader:
    _, source_in = build_fleet({services}, {hist_len}, {cur_len}, NOW)

    class DyingSource:
        # the real source, but the LEADER PROCESS DIES on the 3rd fetch
        # of the tick — after the claim was persisted to ES, before any
        # verdict. os._exit: a crash, not an exception (no cleanup, no
        # broadcast of an error object — the pod's worst case).
        concurrent_fetch = False
        def __init__(self, inner):
            self.inner = inner
            self.calls = 0
        def fetch(self, url):
            self.calls += 1
            if self.calls >= 3:
                os._exit(17)
            return self.inner.fetch(url)

    store_in = ElasticsearchStore(es_url)
    source = LeaderSource(DyingSource(source_in))
else:
    store_in = None
    source = LeaderSource(None)
store = LeaderStore(store_in)
cfg = BrainConfig(algorithm="moving_average_all", max_stuck_seconds=90.0)
worker = PodWorker(
    store, source, config=cfg, claim_limit={services},
    worker_id=f"pod-{{pid}}",
)
print(f"proc {{pid}} ticking", flush=True)
worker.tick(now=NOW + 150)  # leader dies inside; follower must ERROR
print(f"proc {{pid}} SURVIVED", flush=True)  # only reachable on a bug
"""


def test_leader_death_mid_tick_fails_fast_and_recovers(tmp_path):
    from benchmarks.worker_bench import build_fleet
    from foremast_tpu.config import BrainConfig
    from foremast_tpu.jobs.models import (
        STATUS_PREPROCESS_INPROGRESS,
        TERMINAL_STATUSES,
    )
    from foremast_tpu.jobs.store import ElasticsearchStore
    from foremast_tpu.jobs.worker import BrainWorker

    srv, fake = _serve_fake_es()
    try:
        url = f"http://127.0.0.1:{srv.server_address[1]}"
        parent_store = ElasticsearchStore(url)
        parent_store.ensure_index()
        fleet_store, _ = build_fleet(SERVICES, HIST_LEN, CUR_LEN, NOW)
        for doc in fleet_store._docs.values():
            parent_store.create(doc)

        child = tmp_path / "pod_child.py"
        child.write_text(
            _CHILD.format(
                repo=REPO,
                now=NOW,
                services=SERVICES,
                hist_len=HIST_LEN,
                cur_len=CUR_LEN,
            )
        )
        addr = f"127.0.0.1:{_free_port()}"
        env = {
            k: v for k, v in os.environ.items() if not k.startswith("JAX_")
        }
        t0 = time.monotonic()
        procs = [
            subprocess.Popen(
                [sys.executable, str(child), addr, str(pid), url],
                stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT,
                text=True,
                env=env,
            )
            for pid in (0, 1)
        ]
        outs = []
        try:
            for p in procs:
                out, _ = p.communicate(timeout=180)
                outs.append(out)
        finally:
            for p in procs:
                if p.poll() is None:
                    p.kill()
        elapsed = time.monotonic() - t0

        # the leader crashed with its marker code; the follower FAILED
        # FAST — nonzero exit, no hang (the 180 s communicate timeout is
        # the hang detector), and it never completed the tick
        assert procs[0].returncode == 17, outs[0]
        assert procs[1].returncode not in (0, None), outs[1]
        assert "SURVIVED" not in outs[1], outs[1]
        assert elapsed < 175, f"follower hung for {elapsed:.0f}s"

        # the claim is parked on the store: in-progress, owned by the
        # dead pod — exactly what MAX_STUCK_IN_SECONDS exists for
        stuck = [
            d["_source"]
            for d in fake.docs.values()
            if d["_source"]["status"] == STATUS_PREPROCESS_INPROGRESS
        ]
        assert stuck, "leader died before persisting any claim"

        # restarted pod (single process suffices — the store contract is
        # identical): past the stuck window, CAS takeover re-claims and
        # every document lands exactly one verdict. The stuck clock is
        # the store's WALL clock (modified_at), so the test shrinks
        # MAX_STUCK_IN_SECONDS instead of sleeping the production 90 s.
        _, source = build_fleet(SERVICES, HIST_LEN, CUR_LEN, NOW)
        takeover = BrainWorker(
            ElasticsearchStore(url),
            source,
            config=BrainConfig(
                algorithm="moving_average_all", max_stuck_seconds=2.0
            ),
            claim_limit=SERVICES,
            worker_id="takeover",
        )
        # age the dead pod's claims past the window, then tick until the
        # takeover lands (modified_at has second granularity and the
        # claim clock is wall time, so a fixed sleep is load-flaky);
        # `now` past endTime so every doc finalizes on this judgment
        total = 0
        deadline = time.monotonic() + 60
        while total < SERVICES and time.monotonic() < deadline:
            time.sleep(1.0)
            total += takeover.tick(now=NOW + 7200)
        assert total == SERVICES, f"takeover claimed {total} != {SERVICES}"

        # no lost docs, no duplicates: every document terminal, judged
        # by the takeover worker, matching the single-process reference
        ref_store, ref_source = build_fleet(SERVICES, HIST_LEN, CUR_LEN, NOW)
        ref = BrainWorker(
            ref_store,
            ref_source,
            config=BrainConfig(algorithm="moving_average_all"),
            claim_limit=SERVICES,
            worker_id="ref",
        )
        assert ref.tick(now=NOW + 7200) == SERVICES
        want = {
            d.id: (d.status, json.dumps(d.anomaly_info, sort_keys=True))
            for d in ref_store._docs.values()
        }
        assert len(fake.docs) == SERVICES
        for doc_id, (status, anom) in want.items():
            rec = fake.docs[doc_id]["_source"]
            assert rec["status"] == status, (doc_id, rec["status"], status)
            assert rec["status"] in TERMINAL_STATUSES
            assert rec["processingContent"] == "takeover"
        # a second tick finds nothing claimable: no verdict re-issued
        assert takeover.tick(now=NOW + 7300) == 0
    finally:
        srv.shutdown()
