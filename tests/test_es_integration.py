"""Gated REAL-Elasticsearch integration tests (VERDICT r4 #6).

Everything else in the suite exercises `ElasticsearchStore` against the
in-repo fake; these run against a LIVE cluster to catch version skew in
the semantics the fake merely models — index-template creation, CAS
claim behavior under real refresh/visibility rules, bulk update
conflicts, and mapping-divergence detection on a pre-existing index.

Gate: set `FOREMAST_ES_URL` (e.g. http://localhost:9200). Skipped
otherwise — the build image has no ES and zero egress; CI runs these in
the `es-integration` job against a service container
(`.github/workflows/ci.yml`). Reference seam:
`foremast-service/pkg/search/elasticsearchstore.go:22-62`.
"""

from __future__ import annotations

import os
import threading
import time
import uuid

import pytest

# test-suite-only opt-in gate (points tier-2 at a LIVE Elasticsearch);
# deliberately not in ENV_KNOBS — it configures this test run, not the
# product, and registering it would put it in the operator docs
ES_URL = os.environ.get("FOREMAST_ES_URL")  # foremast: ignore[env-contract]

pytestmark = pytest.mark.skipif(
    not ES_URL, reason="FOREMAST_ES_URL not set (no live Elasticsearch)"
)


def _store(index: str):
    from foremast_tpu.jobs.store import ElasticsearchStore

    store = ElasticsearchStore(ES_URL)
    # unique index per test: a shared dev cluster must not leak state
    # between runs
    store.INDEX = index
    return store


def _doc(i: int, end_epoch: int):
    from foremast_tpu.jobs.models import Document

    return Document(
        id=f"it-{uuid.uuid4().hex[:6]}-{i}",
        app_name=f"app{i}",
        end_time=str(end_epoch),
        current_config=f"latency== http://prom/cur?q=l:app{i}&step=60",
        historical_config=(
            f"latency== http://prom/hist?q=l:app{i}&end=1700000000&step=60"
        ),
        strategy="continuous",
    )


@pytest.fixture()
def index():
    name = f"foremast-it-{uuid.uuid4().hex[:8]}"
    yield name
    import requests

    requests.delete(f"{ES_URL.rstrip('/')}/{name}", timeout=10)


def test_wait_ready_creates_index_with_template(index):
    store = _store(index)
    assert store.wait_ready(max_wait=30) is True
    import requests

    r = requests.get(f"{ES_URL.rstrip('/')}/{index}/_mapping", timeout=10)
    r.raise_for_status()
    mappings = next(iter(r.json().values()))["mappings"]
    props = mappings.get("properties", mappings)
    # the claim query's load-bearing field types (store.INDEX_MAPPINGS):
    # terms over keyword, range+sort over date
    if "properties" in props:
        props = props["properties"]
    assert props["status"]["type"] == "keyword"
    assert props["processingContent"]["type"] == "keyword"
    assert props["modifiedAt"]["type"] == "date"


def test_create_idempotent_and_roundtrip(index):
    store = _store(index)
    store.wait_ready(max_wait=30)
    doc = _doc(0, int(time.time()) + 3600)
    created, fresh = store.create(doc)
    assert fresh is True
    again, fresh2 = store.create(doc)
    assert fresh2 is False  # op_type=create conflict -> existing doc
    got = store.get(doc.id)
    assert got is not None
    assert got.app_name == doc.app_name
    assert got.status == doc.status


def test_two_claimers_no_double_claim_under_real_refresh(index):
    """The CAS seam the fake cannot prove: real ES refresh intervals and
    seq_no semantics. Two threads claim concurrently; every doc must be
    claimed by exactly one of them."""
    store_a = _store(index)
    store_b = _store(index)
    store_a.wait_ready(max_wait=30)
    n = 8
    ids = []
    for i in range(n):
        doc = _doc(i, int(time.time()) + 3600)
        store_a.create(doc)
        ids.append(doc.id)
    # claims search with the store's own visibility handling; give the
    # cluster one refresh interval for the creates
    time.sleep(1.5)

    results = {}

    def claim(store, wid):
        got = results.setdefault(wid, [])  # shared: peers see progress
        for _ in range(6):
            docs = store.claim(wid, max_stuck_seconds=300, limit=3)
            got.extend(d.id for d in docs)
            if len(results.get("a", [])) + len(results.get("b", [])) >= n:
                break
            time.sleep(0.5)

    ta = threading.Thread(target=claim, args=(store_a, "a"))
    tb = threading.Thread(target=claim, args=(store_b, "b"))
    ta.start(), tb.start()
    ta.join(30), tb.join(30)
    all_claimed = results["a"] + results["b"]
    assert sorted(all_claimed) == sorted(ids), results
    assert len(set(all_claimed)) == len(all_claimed), "double claim!"


def test_bulk_update_many_roundtrip(index):
    store = _store(index)
    store.wait_ready(max_wait=30)
    docs = []
    for i in range(4):
        d = _doc(i, int(time.time()) + 3600)
        store.create(d)
        docs.append(d)
    for d in docs:
        d.status = "preprocess_completed"
    store.update_many(docs)
    time.sleep(1.5)
    for d in docs:
        assert store.get(d.id).status == "preprocess_completed"


def test_mapping_divergence_detected_on_wrong_index(index):
    """A pre-existing index whose critical fields were dynamic-mapped as
    text must be REFUSED (MappingDivergence), not silently used — claim
    terms queries would hit analyzer behavior."""
    import requests

    from foremast_tpu.jobs.store import MappingDivergence

    requests.put(
        f"{ES_URL.rstrip('/')}/{index}",
        json={
            "mappings": {
                "properties": {
                    "status": {"type": "text"},
                    "processingContent": {"type": "text"},
                    "modifiedAt": {"type": "text"},
                }
            }
        },
        timeout=10,
    ).raise_for_status()
    store = _store(index)
    with pytest.raises(MappingDivergence):
        store.ensure_index()
