"""Sliced, preemptible sweeps (ISSUE 15): parity, preemption triage,
and the preemption × degradation interactions.

The contracts pinned here:

  * a SLICED sweep's statuses are byte-identical to a monolithic
    sweep's on identical fleets (the acceptance parity arm);
  * slice-boundary preemption triages arrivals correctly — pooled
    docs PROMOTE into the next slice, arrivals for docs outside the
    sweep's claim run a NESTED micro-tick between slices, in-flight
    collisions requeue at the front with their original stamps;
  * a micro-tick preempting a slice composes with tick-budget
    release: the nested cycle restores the sweep's deadline, the
    expired remainder releases in one bulk write, and every claimed
    doc is judged exactly once OR released — never both, never twice;
  * write-behind entries buffered by slice writes are stamped at the
    SWEEP's claim instant (not the write failure, not a nested
    micro's claim) and replay exactly once across a store brownout
    that begins mid-sweep.

Plus the ChunkPipeline extensions the sweep rides on: lazy chunk
iterators with the END sentinel, the boundary hook, and on_drained.
"""

from __future__ import annotations

import threading
import time

import numpy as np
import pytest

from benchmarks.latency_bench import _statuses, build_fleet, mk_worker
from foremast_tpu.chaos.degrade import (
    REASON_DEADLINE,
    REASON_FETCH,
)
from foremast_tpu.jobs import pipeline as pl
from foremast_tpu.jobs.models import (
    STATUS_COMPLETED_UNHEALTH,
    STATUS_PREPROCESS_COMPLETED,
    TERMINAL_STATUSES,
)
from foremast_tpu.reactive import DirtySet

NOW = int(time.time())


class _CountingStore:
    """Wraps a store: counts per-doc writes, optional per-call claim
    hook (fires AFTER the claim — the deterministic way to land dirty
    marks mid-sweep, past the catch-all take_all), and an injectable
    transient write fault."""

    def __init__(self, inner):
        self.inner = inner
        self.writes: dict[str, int] = {}
        self.on_claim = None
        self.fail_writes = False
        self.write_attempts = 0
        self._lock = threading.Lock()

    def __getattr__(self, name):
        return getattr(self.inner, name)

    def claim(self, *a, **kw):
        docs = self.inner.claim(*a, **kw)
        hook, self.on_claim = self.on_claim, None
        if hook is not None:
            hook(docs)
        return docs

    def _count(self, docs):
        with self._lock:
            for d in docs:
                self.writes[d.id] = self.writes.get(d.id, 0) + 1

    def update(self, doc):
        with self._lock:
            self.write_attempts += 1
        if self.fail_writes:
            raise ConnectionError("injected store brownout")
        doc = self.inner.update(doc)
        self._count([doc])
        return doc

    def update_many(self, docs):
        with self._lock:
            self.write_attempts += 1
        if self.fail_writes:
            raise ConnectionError("injected store brownout")
        self.inner.update_many(docs)
        self._count(docs)


def _sliced_worker(services, slice_docs=8, dirty=None, claim_limit=None):
    store, ring, keys, ht, ct = build_fleet(services, NOW)
    wrapped = _CountingStore(store)
    w = mk_worker(wrapped, ring, services, dirty=dirty)
    if claim_limit is not None:
        w.claim_limit = claim_limit
    w.sweep_slice_docs = slice_docs
    return w, wrapped, store, ring, keys, ct


# -- parity ----------------------------------------------------------------


def test_sliced_vs_monolithic_byte_parity():
    """Cold + warm + spiked sweeps: statuses byte-identical between
    the monolithic arm and the sliced arm (the pack/dispatch/decode
    helpers are shared, so parity is by construction — this pins it)."""
    wa, _, sa, ring_a, keys_a, ct = _sliced_worker(24, slice_docs=0)
    wb, _, sb, ring_b, keys_b, _ = _sliced_worker(24, slice_docs=8)
    assert not wa._sweep_sliceable() and wb._sweep_sliceable()
    now = float(NOW)
    assert wa.tick(now=now) == 24
    assert wb.tick(now=now) == 24
    assert _statuses(sa) == _statuses(sb)
    spike = np.full(3, 40.0, np.float32)
    for ring, keys in ((ring_a, keys_a), (ring_b, keys_b)):
        ring.push(keys[3], ct[-3:], spike, now=now)
    assert wa.tick(now=now + 60) == 24
    assert wb.tick(now=now + 60) == 24
    a, b = _statuses(sa), _statuses(sb)
    assert a == b
    assert a["job-3"][0] == STATUS_COMPLETED_UNHEALTH
    assert wb._last_sweep["slices"] == 3
    wa.close()
    wb.close()


# -- preemption triage -----------------------------------------------------


def test_boundary_promotes_pooled_doc():
    """An arrival for a claimed-but-unfetched doc promotes its slice
    to the front: the sweep itself delivers the verdict, the arrival
    is attributed through the sweep ledger, and the dirty set counts
    the promotion."""
    dirty = DirtySet(max_keys=64)
    w, cs, store, ring, keys, ct = _sliced_worker(32, 8, dirty=dirty)
    now = float(NOW)
    assert w.tick(now=now) == 32  # cold: fits cached

    # spike the LAST pool doc's series, and mark it dirty AFTER the
    # sweep's claim (mid-sweep arrival, past the catch-all drain)
    def on_claim(_docs):
        ring.push(keys[31], ct[-3:], np.full(3, 40.0, np.float32), now=now)
        dirty.mark_series(keys[31], now=now)

    cs.on_claim = on_claim
    assert w.tick(now=now + 60) == 32
    sweep = w._last_sweep
    assert sweep["promoted"] >= 1, sweep
    assert sweep["preempt_microticks"] == 0, sweep
    assert dirty.counts()["promoted"] >= 1
    assert store._docs["job-31"].status == STATUS_COMPLETED_UNHEALTH
    assert len(dirty) == 0  # consumed, not requeued
    w.close()


def test_boundary_microtick_judges_unclaimed_doc():
    """An arrival for a doc OUTSIDE the sweep's claim (bounded
    claim_limit) runs a nested micro-tick between slices — the doc is
    judged DURING the sweep, not after it."""
    dirty = DirtySet(max_keys=64)
    w, cs, store, ring, keys, ct = _sliced_worker(
        32, 8, dirty=dirty, claim_limit=24
    )
    now = float(NOW)
    # the 24-doc claim cap leaves the insertion-order tail (job-24..31)
    # permanently outside the sweep's claim — exactly the docs only a
    # micro-tick can reach mid-sweep
    assert w.tick(now=now) == 24

    judged_mid_sweep = {}

    def on_claim(docs):
        claimed = {d.id for d in docs}
        # job-31 re-checks but was NOT claimed by this sweep iff the
        # claim cap bit it; pick any unclaimed doc deterministically
        victim = next(
            f"job-{s}" for s in range(31, -1, -1)
            if f"job-{s}" not in claimed
        )
        s = int(victim.split("-")[1])
        ring.push(keys[s], ct[-3:], np.full(3, 40.0, np.float32), now=now)
        dirty.mark_series(keys[s], now=now)
        judged_mid_sweep["id"] = victim

    cs.on_claim = on_claim
    assert w.tick(now=now + 60) > 0
    sweep = w._last_sweep
    assert sweep["preempt_microticks"] >= 1, sweep
    assert sweep["preempt_docs"] >= 1, sweep
    assert (
        store._docs[judged_mid_sweep["id"]].status
        == STATUS_COMPLETED_UNHEALTH
    )
    w.close()


# -- preemption x degradation ---------------------------------------------


def test_microtick_preempts_then_budget_release():
    """A sweep whose budget expires after the first boundary: the
    nested micro-tick runs (and restores the sweep's deadline), the
    pooled remainder releases in ONE bulk write with
    reason=deadline_released, and every claimed doc is judged exactly
    once or released — never both."""
    dirty = DirtySet(max_keys=64)
    w, cs, store, ring, keys, ct = _sliced_worker(
        32, 8, dirty=dirty, claim_limit=24
    )
    now = float(NOW)
    assert w.tick(now=now) == 24

    # burn the budget inside slice 1's prepare: the fetch hook sleeps
    # past the budget, so every LATER slice's prepare sees an expired
    # deadline and drains the pool as one release bundle
    w._degrade.tick_budget_seconds = 0.05
    orig_fetch = w.source.fetch
    slept = []

    def slow_fetch(url):
        if not slept:
            slept.append(1)
            time.sleep(0.12)
        return orig_fetch(url)

    w.source.fetch = slow_fetch

    def on_claim(docs):
        claimed = {d.id for d in docs}
        victim = next(
            f"job-{s}" for s in range(31, -1, -1)
            if f"job-{s}" not in claimed
        )
        s = int(victim.split("-")[1])
        dirty.mark_series(keys[s], now=now)

    cs.on_claim = on_claim
    before = w._degrade.stats.docs_snapshot().get(REASON_DEADLINE, 0)
    n = w.tick(now=now + 60)
    sweep = w._last_sweep
    released = (
        w._degrade.stats.docs_snapshot().get(REASON_DEADLINE, 0) - before
    )
    # the nested micro ran, the sweep's own deadline survived it, and
    # the remainder released; judged + released covers the claim with
    # no overlap (exactly-once)
    assert sweep["preempt_microticks"] >= 1, sweep
    assert released > 0, (sweep, released)
    # every claimed doc is accounted exactly once: judged slices +
    # the one bulk deadline release cover the whole 24-doc claim
    # (n counts both; the released remainder is 24 - judged)
    assert n == 24, (n, released, sweep)
    open_docs = [
        d for d in store._docs.values()
        if d.status == STATUS_PREPROCESS_COMPLETED
    ]
    assert len(open_docs) >= released  # released docs stay claimable
    w.close()


def test_write_behind_replay_across_slice_boundary():
    """A store brownout beginning mid-sweep: slice writes buffer into
    write-behind — stamped at the SWEEP's claim instant — and replay
    exactly once when the store heals, original stamps preserved."""
    w, cs, store, ring, keys, ct = _sliced_worker(32, 8)
    now = float(NOW)
    assert w.tick(now=now) == 32  # cold, store healthy

    claim_stamp = []

    def on_claim(_docs):
        cs.fail_writes = True  # brownout begins AFTER the claim
        claim_stamp.append(w._tick_claim_mono)

    cs.on_claim = on_claim
    writes_before = dict(cs.writes)
    assert w.tick(now=now + 60) == 32
    buf = w._degrade.write_behind
    assert len(buf) == 32, len(buf)
    # every buffered entry is stamped at the sweep's claim instant —
    # NOT the (later) write-failure instant; the exactly-once age
    # window measures from the claim
    with buf._lock:
        stamps = [at for at, _ in buf._entries]
    assert all(at == claim_stamp[0] for at in stamps), stamps
    assert cs.writes == writes_before  # nothing landed during brownout

    cs.fail_writes = False  # store heals; next tick replays FIRST
    assert w.tick(now=now + 120) == 32
    assert len(buf) == 0
    # each doc's buffered verdict landed exactly once (one replay
    # bulk write) plus the healed tick's own judgment write
    assert all(
        cs.writes[d] - writes_before.get(d, 0) == 2
        for d in (f"job-{s}" for s in range(32))
    ), cs.writes
    replayed = w._degrade.stats.docs_snapshot().get("write_replayed", 0)
    assert replayed == 32, replayed
    w.close()


def test_chaos_store_brownout_mid_sweep_exactly_once():
    """Brownout that begins between slices (first slice lands, the
    rest buffer): the ledger stays exactly-once — every doc's verdict
    is written exactly once for that sweep, split between direct
    writes and the replay."""
    w, cs, store, ring, keys, ct = _sliced_worker(32, 8)
    now = float(NOW)
    assert w.tick(now=now) == 32

    flipped = []
    orig_update_many = cs.inner.update_many

    def tripwire(docs):
        # heal-side counter: flip the fault after the FIRST slice's
        # bulk write lands
        orig_update_many(docs)
        if not flipped:
            flipped.append(1)
            cs.fail_writes = True

    cs.inner.update_many = tripwire
    writes_before = dict(cs.writes)
    assert w.tick(now=now + 60) == 32
    cs.inner.update_many = orig_update_many
    buf = w._degrade.write_behind
    assert 0 < len(buf) < 32  # some landed, some buffered
    buffered = len(buf)
    cs.fail_writes = False
    assert w.tick(now=now + 120) == 32
    assert len(buf) == 0
    for s in range(32):
        doc_id = f"job-{s}"
        delta = cs.writes[doc_id] - writes_before.get(doc_id, 0)
        # 1 write for the brownout sweep (direct or replayed) + 1 for
        # the healed sweep — never a double write
        assert delta == 2, (doc_id, delta, buffered)
    w.close()


# -- ChunkPipeline extensions ---------------------------------------------


def _pool():
    from concurrent.futures import ThreadPoolExecutor

    return ThreadPoolExecutor(max_workers=1)


def test_pipeline_lazy_iterator_end_sentinel():
    """run() over an unbounded iterator stops at the first END payload
    from fetch, in both serial and pipelined modes, and counts only
    the real chunks."""
    import itertools

    for pool in (None, _pool()):
        seen = []
        budget = [4]

        def fetch(i):
            if budget[0] <= 0:
                return pl.END
            budget[0] -= 1
            return f"payload-{i}"

        pipe = pl.ChunkPipeline(
            fetch,
            lambda i, p: (i, p),
            lambda i, r: seen.append(r),
            depth=2,
            prefetch_pool=pool,
        )
        stats = pipe.run(itertools.count())
        assert len(seen) == 4, seen
        assert stats.chunks == 4
        assert stats.completed
        budget[0] = 4
        if pool is not None:
            pool.shutdown()


def test_pipeline_real_payload_queued_behind_end_still_judged():
    """Depth >= 3 runs 2+ concurrent prefetch workers: a fully
    prepared chunk can be QUEUED BEHIND the END that raced it for the
    pool's last items. END must stop SUBMISSION, not abandon already-
    prepared work to the abort drain (that would silently release a
    healthy sweep's claimed slice every sweep)."""
    import itertools
    from concurrent.futures import ThreadPoolExecutor

    judged = []
    drained = []
    payloads = {0: pl.END, 1: "prep-1"}
    pool = ThreadPoolExecutor(max_workers=2)
    pipe = pl.ChunkPipeline(
        lambda i: payloads.get(i, pl.END),
        lambda i, p: p,
        lambda i, r: judged.append(r),
        depth=3,
        prefetch_pool=pool,
        on_drained=lambda i, p: drained.append(p),
    )
    stats = pipe.run(itertools.count())
    assert judged == ["prep-1"], (judged, drained)
    assert drained == []
    assert stats.completed
    pool.shutdown()


def test_pipeline_boundary_hook_runs_between_chunks():
    boundaries = []
    for pool in (None, _pool()):
        boundaries.clear()
        order = []
        pipe = pl.ChunkPipeline(
            lambda c: c,
            lambda c, p: order.append(("judge", c)) or c,
            lambda c, r: None,
            depth=2,
            prefetch_pool=pool,
            boundary=lambda: boundaries.append(len(order)),
        )
        pipe.run([1, 2, 3])
        assert boundaries == [1, 2, 3]  # after each chunk's judgment
        if pool is not None:
            pool.shutdown()


def test_pipeline_on_drained_gets_unjudged_prefetches():
    """A judge abort drains completed-but-unjudged prefetches through
    on_drained so a side-effecting fetch stage can give work back."""
    drained = []

    def judge(c, p):
        if c == 1:
            raise RuntimeError("boom")
        return p

    pool = _pool()
    pipe = pl.ChunkPipeline(
        lambda c: f"prep-{c}",
        judge,
        lambda c, r: None,
        depth=3,
        prefetch_pool=pool,
        on_drained=lambda c, p: drained.append((c, p)),
    )
    with pytest.raises(RuntimeError):
        pipe.run([1, 2, 3])
    # chunk 1 aborted the run; at depth 3 chunk 2 (and possibly 3) was
    # already prefetched and must drain through on_drained
    assert (2, "prep-2") in drained, drained
    pool.shutdown()


def test_sweep_abort_releases_pooled_and_prepared_docs():
    """A judge-stage death mid-sweep: prepared-but-unjudged slices and
    the un-sliced pool remainder release un-judged (claimable again),
    never parked behind the stuck-takeover window."""
    w, cs, store, ring, keys, ct = _sliced_worker(32, 8)
    now = float(NOW)
    assert w.tick(now=now) == 32  # warm the fits

    calls = []
    orig = w._uni.judge_columnar_async

    def dying(*a, **kw):
        calls.append(1)
        if len(calls) == 2:
            raise RuntimeError("device died")
        return orig(*a, **kw)

    w._uni.judge_columnar_async = dying
    with pytest.raises(RuntimeError):
        w.tick(now=now + 60)
    w._uni.judge_columnar_async = orig
    # nothing may be left in preprocess_inprogress: slice 1 judged,
    # slice 2 released via the StageError partial, prepared slice 3 +
    # the pool remainder released via on_drained / the sweep finally
    stuck = [
        d.id for d in store._docs.values()
        if d.status not in TERMINAL_STATUSES
        and d.status != STATUS_PREPROCESS_COMPLETED
    ]
    assert stuck == [], stuck
    # and the next sweep judges everything again, cleanly
    assert w.tick(now=now + 120) == 32
    w.close()
