"""Job-plane tests: wire parity, idempotent ids, store semantics, and the
batched worker end-to-end on the golden traces."""

import os
import time

import numpy as np
import pytest

from foremast_tpu.config import BrainConfig
from foremast_tpu.jobs import (
    AnalyzeRequest,
    BrainWorker,
    Document,
    InMemoryStore,
    MetricQuery,
    MetricsInfo,
    STATUS_COMPLETED_HEALTH,
    STATUS_COMPLETED_UNHEALTH,
    STATUS_COMPLETED_UNKNOWN,
    STATUS_INITIAL,
    STATUS_PREPROCESS_COMPLETED,
    STATUS_PREPROCESS_FAILED,
    STATUS_PREPROCESS_INPROGRESS,
    document_response,
    infer_metric_type,
    job_id,
    status_to_external,
)
from foremast_tpu.jobs.convert import InvalidRequest, request_to_document
from foremast_tpu.metrics import (
    ReplaySource,
    StaticSource,
    decode_config,
    encode_config,
    prometheus_url,
    wavefront_url,
)


# ---------------------------------------------------------------------------
# status machine / wire parity
# ---------------------------------------------------------------------------


def test_status_translation_matches_converter_go():
    # converter.go:13-26
    assert status_to_external("initial") == "new"
    assert status_to_external("preprocess_inprogress") == "inprogress"
    assert status_to_external("postprocess_inprogress") == "inprogress"
    assert status_to_external("preprocess_completed") == "inprogress"
    assert status_to_external("completed_health") == "success"
    assert status_to_external("completed_unhealth") == "anomaly"
    assert status_to_external("completed_unknown") == "abort"
    assert status_to_external("preprocess_failed") == "abort"
    assert status_to_external("weird") == "weird"  # default branch passthrough


def test_job_id_idempotent_and_distinct():
    a = job_id("app", "1", "2", ("c", "b", "h"), ("p", "p", "p"), "canary")
    b = job_id("app", "1", "2", ("c", "b", "h"), ("p", "p", "p"), "canary")
    c = job_id("app", "1", "2", ("c2", "b", "h"), ("p", "p", "p"), "canary")
    assert a == b != c
    assert len(a) == 64  # hex sha256


def test_config_string_codec_roundtrip():
    # main.go:28-31 separators: " ||" and "== "
    queries = {
        "latency": MetricQuery(
            "prometheus",
            {"endpoint": "http://p/api/v1/", "query": "up{a=\"b\"}", "start": 1, "end": 2, "step": 60},
        ),
        "error5xx": MetricQuery(
            "prometheus",
            {"endpoint": "http://p/api/v1/", "query": "err", "start": 1, "end": 2, "step": 60},
        ),
    }
    cfg, src = encode_config(queries)
    assert " ||" in cfg and "== " in cfg
    decoded = decode_config(cfg)
    assert set(decoded) == {"latency", "error5xx"}
    assert decoded["latency"].startswith("http://p/api/v1/query_range?query=up")
    assert src == "error5xx== prometheus ||latency== prometheus"


def test_prometheus_url_builder():
    # prometheushelper.go:12-27
    url = prometheus_url(
        {"endpoint": "http://prom/api/v1/", "query": 'up{pod=~"a|b"}', "start": 10, "end": 20, "step": 60}
    )
    assert url == (
        "http://prom/api/v1/query_range?query=up%7Bpod%3D~%22a%7Cb%22%7D"
        "&start=10&end=20&step=60"
    )


def test_wavefront_url_builder():
    # wavefronthelper.go:20-29
    assert wavefront_url({"query": "ts(x)", "start": 1, "end": 2, "step": 60}) == "ts(x)&&1&&m&&2"
    assert wavefront_url({"query": "q", "start": 1, "end": 2, "step": 3600}) == "q&&1&&h&&2"


def test_request_to_document_validation_and_id():
    req = AnalyzeRequest(
        app_name="demo",
        start_time="2026-07-29T00:00:00Z",
        end_time="2026-07-29T00:10:00Z",
        metrics=MetricsInfo(
            current={
                "error5xx": MetricQuery(
                    "prometheus",
                    {"endpoint": "http://p/", "query": "e", "start": 1, "end": 2, "step": 60},
                )
            }
        ),
        strategy="rollingUpdate",
    )
    doc = request_to_document(req)
    assert doc.status == STATUS_INITIAL
    assert doc.id == request_to_document(req).id  # idempotent
    assert "error5xx== " in doc.current_config
    assert doc.current_metric_store == "error5xx== prometheus"

    with pytest.raises(InvalidRequest):
        request_to_document(AnalyzeRequest("", "", "", MetricsInfo(), "x"))
    with pytest.raises(InvalidRequest):
        request_to_document(AnalyzeRequest("a", "", "", MetricsInfo(), "x"))


def test_document_response_shape():
    doc = Document(id="j1", app_name="demo", status="completed_unhealth")
    doc.anomaly_info = {"tags": "", "values": {"m": [1.0, 2.0]}}
    resp = document_response(doc)
    assert resp["jobId"] == "j1"
    assert resp["status"] == "anomaly"
    assert resp["anomalyInfo"]["values"]["m"] == [1.0, 2.0]


# ---------------------------------------------------------------------------
# store semantics
# ---------------------------------------------------------------------------


def test_inmemory_store_idempotent_create():
    s = InMemoryStore()
    d1, created1 = s.create(Document(id="a", app_name="x"))
    d2, created2 = s.create(Document(id="a", app_name="x"))
    assert created1 and not created2
    assert d1 is d2


def test_inmemory_claim_and_stuck_takeover():
    s = InMemoryStore()
    s.create(Document(id="a", app_name="x"))
    docs = s.claim("w1", max_stuck_seconds=90)
    assert [d.id for d in docs] == ["a"]
    # mark in-progress recently: not claimable again
    docs[0].status = STATUS_PREPROCESS_INPROGRESS
    s.update(docs[0])
    assert s.claim("w2", max_stuck_seconds=90) == []
    # simulate staleness: claimable again (work stealing, design.md:39)
    stale = s.get("a")
    stale.modified_at = "2020-01-01T00:00:00Z"
    s._docs["a"] = stale
    stolen = s.claim("w2", max_stuck_seconds=90)
    assert [d.id for d in stolen] == ["a"]
    # terminal docs never claimable
    stale.status = STATUS_COMPLETED_HEALTH
    s.update(stale)
    assert s.claim("w3", max_stuck_seconds=0) == []


# ---------------------------------------------------------------------------
# worker end-to-end on golden traces
# ---------------------------------------------------------------------------


def _mk_doc(app, alias, cur_key, end_time="0"):
    return Document(
        id=f"job-{app}-{alias}-{cur_key}",
        app_name=app,
        end_time=end_time,
        current_config=f"{alias}== http://replay/{cur_key}",
        baseline_config="",
        historical_config=f"{alias}== http://replay/hist",
        strategy="rollingUpdate",
    )


@pytest.fixture
def replay(demo_traces):
    nt, nv = demo_traces["normal"]
    st, sv = demo_traces["spike"]
    hist = np.tile(nv, 6)
    ht = 1700000000 + 60 * np.arange(len(hist), dtype=np.int64)
    src = ReplaySource()
    src.register("replay/hist", (ht, hist.astype(np.float32)))
    src.register("replay/normal", (nt, nv))
    src.register("replay/spike", (st, sv))
    return src


def test_worker_flags_spike_trace(replay):
    store = InMemoryStore()
    store.create(_mk_doc("demo", "error4xx", "spike"))
    worker = BrainWorker(store, replay, BrainConfig())
    n = worker.tick()
    assert n == 1
    doc = store.get("job-demo-error4xx-spike")
    assert doc.status == STATUS_COMPLETED_UNHEALTH
    vals = doc.anomaly_info["values"]["error4xx"]
    assert any(v > 30 for v in vals[1::2])  # the 40.134 spike in wire pairs


def test_worker_healthy_past_endtime(replay):
    store = InMemoryStore()
    store.create(_mk_doc("demo", "error4xx", "normal", end_time="100"))
    worker = BrainWorker(store, replay, BrainConfig())
    worker.tick(now=1e12)  # far past end_time
    doc = store.get("job-demo-error4xx-normal")
    assert doc.status == STATUS_COMPLETED_HEALTH


def test_worker_rechecks_until_endtime(replay):
    store = InMemoryStore()
    future = str(int(time.time()) + 3600)
    store.create(_mk_doc("demo", "error4xx", "normal", end_time=future))
    worker = BrainWorker(store, replay, BrainConfig())
    worker.tick()
    doc = store.get("job-demo-error4xx-normal")
    # healthy-so-far but window still open -> keep re-checking
    assert doc.status == STATUS_PREPROCESS_COMPLETED


def test_worker_preprocess_failure():
    class Boom:
        def fetch(self, url):
            raise RuntimeError("prometheus down")

    store = InMemoryStore()
    store.create(_mk_doc("demo", "m", "x"))
    worker = BrainWorker(store, Boom(), BrainConfig())
    worker.tick()
    assert store.get("job-demo-m-x").status == STATUS_PREPROCESS_FAILED


def test_worker_unknown_on_empty_data(replay):
    store = InMemoryStore()
    doc = _mk_doc("demo", "m", "missing", end_time="100")
    store.create(doc)
    worker = BrainWorker(store, replay, BrainConfig())
    worker.tick(now=1e12)
    assert store.get(doc.id).status == STATUS_COMPLETED_UNKNOWN


def test_worker_batches_multiple_jobs(replay):
    store = InMemoryStore()
    for i in range(5):
        store.create(_mk_doc(f"app{i}", "error4xx", "normal", end_time="100"))
    store.create(_mk_doc("bad", "error4xx", "spike"))
    worker = BrainWorker(store, replay, BrainConfig())
    n = worker.tick(now=1e12)
    assert n == 6
    statuses = {d.id: d.status for d in store._docs.values()}
    assert statuses["job-bad-error4xx-spike"] == STATUS_COMPLETED_UNHEALTH
    healthy = [s for s in statuses.values() if s == STATUS_COMPLETED_HEALTH]
    assert len(healthy) == 5


def test_infer_metric_type():
    cfg = BrainConfig()
    assert infer_metric_type("http_error5xx_rate", cfg) == "error5xx"
    assert infer_metric_type("p99Latency", cfg) == "latency"
    assert infer_metric_type("tps", cfg) is None


class _FakeResp:
    def __init__(self, body, status=200):
        self._body = body
        self.status_code = status

    def raise_for_status(self):
        if self.status_code >= 400:
            raise RuntimeError(f"http {self.status_code}")

    def json(self):
        return self._body


class _FakeSession:
    def __init__(self, body):
        self.body = body
        self.urls = []

    def get(self, url, timeout=None):
        self.urls.append(url)
        return _FakeResp(self.body)


def test_prometheus_source_parses_and_merges():
    from foremast_tpu.metrics.source import PrometheusSource

    body = {
        "status": "success",
        "data": {
            "result": [
                {"values": [[100, "1.5"], [160, "2.0"]]},
                {"values": [[100, "0.5"]]},  # second series sums per ts
            ]
        },
    }
    src = PrometheusSource(session=_FakeSession(body))
    ts, vs = src.fetch("http://prom/q")
    assert ts.tolist() == [100, 160]
    assert vs.tolist() == [2.0, 2.0]


def test_prometheus_source_drops_nan_and_inf():
    """Prometheus emits "NaN"/"+Inf" strings (0/0 recording rules);
    float() parses them, so they must be dropped explicitly."""
    from foremast_tpu.metrics.source import PrometheusSource

    body = {
        "status": "success",
        "data": {
            "result": [
                {"values": [[100, "NaN"], [160, "+Inf"], [220, "3.0"]]}
            ]
        },
    }
    ts, vs = PrometheusSource(session=_FakeSession(body)).fetch("http://p/q")
    assert ts.tolist() == [220]
    assert vs.tolist() == [3.0]


def test_prometheus_source_error_status_raises():
    from foremast_tpu.metrics.source import PrometheusSource

    body = {"status": "error", "error": "bad query"}
    try:
        PrometheusSource(session=_FakeSession(body)).fetch("http://p/q")
        raise AssertionError("should have raised")
    except RuntimeError as e:
        assert "bad query" in str(e)


def test_worker_concurrent_fetch_isolates_failures(replay):
    """Pool-based fetching: one doc whose metrics 404 fails alone; the
    rest of the claimed batch still scores."""

    class Flaky:
        def fetch(self, url):
            if "bad" in url:
                raise RuntimeError("404")
            return replay.fetch(url)

    store = InMemoryStore()
    for i in range(4):
        store.create(_mk_doc(f"ok{i}", "error4xx", "normal", end_time="100"))
    store.create(_mk_doc("bad", "error4xx", "bad"))
    worker = BrainWorker(store, Flaky(), BrainConfig())
    n = worker.tick(now=1e12)
    assert n == 5
    statuses = {d.id: d.status for d in store._docs.values()}
    assert statuses["job-bad-error4xx-bad"] == STATUS_PREPROCESS_FAILED
    assert sum(s == STATUS_COMPLETED_HEALTH for s in statuses.values()) == 4


def test_two_workers_contend_without_double_processing(replay):
    """Race coverage: two workers ticking concurrently over one store must
    process every job exactly once (claim flips status inside the lock)."""
    import threading

    store = InMemoryStore()
    n_jobs = 24
    for i in range(n_jobs):
        store.create(_mk_doc(f"app{i}", "error4xx", "normal", end_time="100"))

    processed: dict[str, list[int]] = {}
    lock = threading.Lock()

    class CountingWorker(BrainWorker):
        def _write_back(self, doc, verdicts, now):
            with lock:
                processed.setdefault(doc.id, []).append(1)
            return super()._write_back(doc, verdicts, now)

    workers = [
        CountingWorker(store, replay, BrainConfig(), worker_id=f"w{i}", claim_limit=8)
        for i in range(2)
    ]

    def run(w):
        for _ in range(6):
            w.tick(now=1e12)

    threads = [threading.Thread(target=run, args=(w,)) for w in workers]
    for t in threads:
        t.start()
    for t in threads:
        t.join()

    assert len(processed) == n_jobs
    assert all(len(v) == 1 for v in processed.values()), processed
    assert all(
        d.status == STATUS_COMPLETED_HEALTH for d in store._docs.values()
    )


def test_recheck_reuses_cached_history(replay):
    """Incremental re-check (SURVEY hard part (d)): the immutable 7-day
    history is fetched once per job, not once per tick."""

    class Counting:
        def __init__(self, inner):
            self.inner = inner
            self.urls = []

        def fetch(self, url):
            self.urls.append(url)
            return self.inner.fetch(url)

    src = Counting(replay)
    store = InMemoryStore()
    # endTime far in the future -> stays in the re-check loop; the hist
    # URL carries an `end` safely in the past, making the range provably
    # immutable (the cache-admission rule)
    doc = _mk_doc("demo", "error4xx", "normal", end_time=str(2**31))
    doc.historical_config = "error4xx== http://replay/hist?end=1700000000"
    store.create(doc)
    worker = BrainWorker(store, src, BrainConfig())

    # injected clock well past the range end + settle margin
    worker.tick(now=1700000000 + 300.0)
    worker.tick(now=1700000000 + 400.0)  # re-claim + re-check the same job
    hist_fetches = [u for u in src.urls if "hist" in u]
    cur_fetches = [u for u in src.urls if "normal" in u]
    assert len(hist_fetches) == 1  # cached after the first tick
    assert len(cur_fetches) == 2  # current window re-fetched each tick


def test_recheck_refetches_unsettled_history(replay):
    """A historical range without a provably-past `end` must NOT be
    cached: REST clients can submit arbitrary params, and freezing an
    in-progress range would judge against truncated data forever."""

    class Counting:
        def __init__(self, inner):
            self.inner = inner
            self.urls = []

        def fetch(self, url):
            self.urls.append(url)
            return self.inner.fetch(url)

    src = Counting(replay)
    store = InMemoryStore()
    # no `end` param on the hist URL -> not provably immutable
    doc = _mk_doc("demo", "error4xx", "normal", end_time=str(2**31))
    store.create(doc)
    worker = BrainWorker(store, src, BrainConfig())
    worker.tick(now=100.0)
    worker.tick(now=200.0)
    assert len([u for u in src.urls if "hist" in u]) == 2


def test_hist_end_epoch_parses_all_url_shapes():
    from foremast_tpu.jobs.worker import _hist_end_epoch

    assert _hist_end_epoch("http://p/api/v1/query_range?q=x&end=1700000000") == 1700000000.0
    # RFC3339 end (Prometheus accepts it)
    assert _hist_end_epoch(
        "http://p/api/v1/query_range?end=2023-11-14T22:13:20Z"
    ) == 1700000000.0
    # wavefront stub shape: <query>&&<start>&&<unit>&&<end>
    assert _hist_end_epoch("ts(x)&&1699990000&&m&&1700000000") == 1700000000.0
    assert _hist_end_epoch("http://p/api/v1/query_range?q=x") is None
    assert _hist_end_epoch("http://p/api/v1/query_range?end=garbage") is None


def test_worker_daily_recheck_warm_ticks_advance_phase():
    """The production daily loop through the SHIPPED worker path: a
    10,080-pt burst-seasonal history (default ML_SEASON_STEPS=1440) is
    fitted ONCE; later re-check ticks run from the cached fit (zero
    refits — fit_forecast is boobytrapped), judge drifted current
    windows at the ADVANCED seasonal phase (a clean window straddling
    the burst stays healthy), and an off-burst spike finalizes the job
    Unhealthy."""
    import dataclasses as _dc

    from foremast_tpu.engine import scoring as _scoring

    rng = np.random.default_rng(31)
    m, th, tc = 1440, 10_080, 20
    t0 = 1_700_000_000
    sig = lambda i: 5.0 + 4.0 * ((i % m) < 10) + rng.normal(0, 0.05, len(i))
    ht = t0 + 60 * np.arange(th, dtype=np.int64)
    hist_end = int(ht[-1])

    src = ReplaySource()
    src.register("replay/dhist", (ht, sig(np.arange(th)).astype(np.float32)))
    windows = {}  # key -> (times, values), re-registered per tick

    def cur_window(gap, spike_at=None):
        idx = th + gap + np.arange(tc)
        ct = t0 + 60 * idx
        cv = sig(idx).astype(np.float32)
        if spike_at is not None:
            cv[spike_at] += 1.0  # 20 sigmas, off-burst position
        return ct.astype(np.int64), cv

    src.register("replay/dcur", lambda: windows["cur"])

    store = InMemoryStore()
    now1 = hist_end + 3600.0
    doc = Document(
        id="daily-job", app_name="dapp", end_time=str(int(now1) + 60 * 3000),
        current_config="custom_rate== http://replay/dcur",
        historical_config=(
            f"custom_rate== http://replay/dhist?query=x&start={t0}"
            f"&end={hist_end}&step=60"
        ),
        strategy="rollingUpdate",
    )
    store.create(doc)
    cfg = BrainConfig(algorithm="auto_univariate")  # daily season default
    cfg = _dc.replace(
        cfg, anomaly=_dc.replace(cfg.anomaly, threshold=4.0, rules=())
    )
    worker = BrainWorker(store, src, cfg)

    # tick 1 (cold): clean continuation right after the history
    windows["cur"] = cur_window(gap=0)
    worker.tick(now=now1)
    assert store.get("daily-job").status == STATUS_PREPROCESS_COMPLETED

    # ticks 2+: warm — any refit explodes
    orig = _scoring.fit_forecast

    def boom(*a, **k):  # pragma: no cover - failure path
        raise AssertionError("refit on a warm daily re-check tick")

    _scoring.fit_forecast = boom
    try:
        # clean window STRADDLING the burst, 1430 steps after the
        # history: phases 1430..1439 then 0..9 — only the advanced
        # phase predicts the second half's burst
        windows["cur"] = cur_window(gap=1430)
        worker.tick(now=now1 + 60 * 1430)
        assert store.get("daily-job").status == STATUS_PREPROCESS_COMPLETED

        # off-burst spike -> fail-fast Unhealthy, terminal
        windows["cur"] = cur_window(gap=2000, spike_at=15)
        worker.tick(now=now1 + 60 * 2000)
    finally:
        _scoring.fit_forecast = orig
    final = store.get("daily-job")
    assert final.status == STATUS_COMPLETED_UNHEALTH
    vals = final.anomaly_info["values"]["custom_rate"]
    assert len(vals) == 2  # exactly the one spiked point, as [t, v]


def test_worker_warmup_precompiles_without_polluting_caches():
    """`worker --warmup` judges synthetic windows at the canonical
    shapes so the first real tick reuses compiled programs; warmup fits
    must not occupy fit-cache capacity, and a real tick afterwards
    still works."""
    store = InMemoryStore()
    src = ReplaySource()
    # ML_ALGORITHM=auto: the univariate judge rewrites to auto_univariate
    # (EXPENSIVE -> fit-cached) — the eviction must key off THAT
    worker = BrainWorker(
        store, src, BrainConfig(algorithm="auto", season_steps=24),
        claim_limit=20,
    )
    worker.warmup(hist_len=256, cur_len=10)  # CPU-sized shapes
    assert len(worker._fit_cache) == 0
    uni = worker.judge.univariate
    assert uni._arenas == {}  # device arena HBM released too
    assert store.list_open() == []  # nothing written anywhere

    # real work still flows after warmup
    nt = 1_700_000_000 + 60 * np.arange(64, dtype=np.int64)
    nv = np.ones(64, np.float32)
    src.register("replay/whist", (nt, nv))
    src.register("replay/wcur", (nt[:10], nv[:10]))
    store.create(
        Document(
            id="wjob", app_name="w", end_time="100",
            current_config="m== http://replay/wcur",
            historical_config="m== http://replay/whist",
            strategy="rollingUpdate",
        )
    )
    worker.tick(now=1e12)
    assert store.get("wjob").status == STATUS_COMPLETED_HEALTH
