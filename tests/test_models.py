"""Model-zoo tests: LSTM-AE, bivariate normal, seasonal, cache."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from foremast_tpu.models import (
    LSTMAEConfig,
    ModelCache,
    detect_bivariate,
    fit_bivariate,
    fit_many,
    fit_seasonal,
    mahalanobis2,
    score_many,
)
from foremast_tpu.ops.forecasters import horizon


# ---------------------------------------------------------------------------
# seasonal (Prophet substitute)
# ---------------------------------------------------------------------------


def _seasonal_series(b, t, period, noise=0.02, seed=0):
    rng = np.random.default_rng(seed)
    tt = np.arange(t)
    base = 1.0 + 0.001 * tt
    seas = 0.5 * np.sin(2 * np.pi * tt / period)
    y = base[None] + seas[None] + noise * rng.standard_normal((b, t))
    return jnp.asarray(y, jnp.float32)


def test_seasonal_recovers_cycle():
    period = 48
    y = _seasonal_series(3, 6 * period, period)
    mask = jnp.ones_like(y, bool)
    fc = fit_seasonal(y, mask, period=period, order=3)
    resid = np.asarray(y - fc.pred)
    assert np.abs(resid).mean() < 0.05
    assert float(fc.scale.mean()) < 0.05
    # extrapolation continues the cycle
    future = np.asarray(horizon(fc, period))
    tt = np.arange(6 * period, 7 * period)
    expected = 1.0 + 0.001 * tt + 0.5 * np.sin(2 * np.pi * tt / period)
    assert np.abs(future[0] - expected).mean() < 0.08


def test_seasonal_masked_fit():
    period = 24
    y = _seasonal_series(2, 4 * period, period)
    mask = np.ones(y.shape, bool)
    mask[:, 10:20] = False  # gap
    y = y.at[:, 10:20].set(999.0)  # garbage under the mask
    fc = fit_seasonal(y, jnp.asarray(mask), period=period, order=2)
    resid = np.asarray(y - fc.pred)[np.asarray(mask)]
    assert np.abs(resid).mean() < 0.1


def test_seasonal_registered_in_registry():
    from foremast_tpu.engine import AI_MODEL

    assert "seasonal" in AI_MODEL and "prophet" in AI_MODEL


# ---------------------------------------------------------------------------
# bivariate normal
# ---------------------------------------------------------------------------


def test_bivariate_flags_joint_outlier():
    rng = np.random.default_rng(1)
    n = 500
    # correlated history: y ~ 2x + noise
    x = 1.0 + 0.1 * rng.standard_normal((1, n))
    y = 2.0 * x + 0.02 * rng.standard_normal((1, n))
    mask = jnp.ones((1, n), bool)
    fit = fit_bivariate(jnp.asarray(x, jnp.float32), jnp.asarray(y, jnp.float32), mask)
    assert bool(fit.valid[0])
    # current: marginally normal in each axis but violating the correlation
    cx = jnp.asarray([[1.0, 1.1, 0.9]], jnp.float32)
    cy = jnp.asarray([[2.0, 1.8, 2.2]], jnp.float32)  # 1.8 vs expected 2.2
    cm = jnp.ones((1, 3), bool)
    d2 = np.asarray(mahalanobis2(fit, cx, cy))
    assert d2[0, 0] < 4.0  # on-manifold point is fine
    flags = np.asarray(detect_bivariate(fit, cx, cy, cm, threshold=3.0))
    assert not flags[0, 0]
    assert flags[0, 1] and flags[0, 2]  # correlation violations caught


def test_bivariate_insufficient_history_is_invalid():
    x = jnp.ones((1, 4), jnp.float32)
    y = jnp.ones((1, 4), jnp.float32)
    mask = jnp.ones((1, 4), bool)
    fit = fit_bivariate(x, y, mask, min_points=10)
    assert not bool(fit.valid[0])
    flags = detect_bivariate(fit, x, y, mask)
    assert not bool(jnp.any(flags))


# ---------------------------------------------------------------------------
# LSTM autoencoder
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def trained_ae():
    rng = np.random.default_rng(2)
    s, b, t, f = 2, 8, 24, 3
    tt = np.arange(t)
    pattern = np.stack(
        [np.sin(2 * np.pi * tt / 12), np.cos(2 * np.pi * tt / 12), 0.1 * tt / t],
        axis=-1,
    )  # [T, F]
    x = pattern[None, None] + 0.02 * rng.standard_normal((s, b, t, f))
    x = jnp.asarray(x, jnp.float32)
    mask = jnp.ones((s, b, t), bool)
    cfg = LSTMAEConfig(features=f, hidden=16, learning_rate=5e-3)
    params, err_mean, err_std, losses = fit_many(
        jax.random.key(0), x, mask, cfg, steps=200
    )
    return params, (err_mean, err_std), losses, x, mask, pattern, cfg


def test_lstm_ae_training_reduces_loss(trained_ae):
    _, _, losses, *_ = trained_ae
    losses = np.asarray(losses).mean(axis=-1)  # [steps, S] -> [steps]
    assert losses[-1] < losses[0] * 0.5


def test_lstm_ae_scores_anomalies(trained_ae):
    params, scale, _, x, mask, pattern, cfg = trained_ae
    rng = np.random.default_rng(3)
    t, f = pattern.shape
    clean = pattern[None, None] + 0.02 * rng.standard_normal((2, 1, t, f))
    broken = clean.copy()
    broken[:, :, 10:14, :] += 3.0  # injected fault
    em, es = scale
    flags_c, _ = score_many(params, jnp.asarray(clean, jnp.float32), mask[:, :1], em, es, 5.0)
    flags_b, _ = score_many(params, jnp.asarray(broken, jnp.float32), mask[:, :1], em, es, 5.0)
    assert not bool(jnp.any(flags_c))
    assert bool(jnp.all(flags_b[:, :, 10:14]))


def test_lstm_ae_masked_steps_ignored(trained_ae):
    params, (em, es), _, x, mask, _, cfg = trained_ae
    x_mod = x.at[:, :, 5, :].set(1e6)  # garbage at a masked slot
    m = mask.at[:, :, 5].set(False)
    flags, err = score_many(params, x_mod, m, em, es, 3.0)
    assert not bool(jnp.any(flags[:, :, 5]))
    assert float(err[0, 0, 5]) == 0.0


# ---------------------------------------------------------------------------
# cache
# ---------------------------------------------------------------------------


def test_model_cache_lru_eviction():
    c = ModelCache(max_size=2)
    c.put(("svc1", "latency"), {"w": jnp.ones(2)})
    c.put(("svc2", "latency"), {"w": jnp.ones(2)})
    c.get(("svc1", "latency"))  # refresh svc1
    c.put(("svc3", "latency"), {"w": jnp.ones(2)})
    assert c.get(("svc2", "latency")) is None  # LRU evicted
    assert c.get(("svc1", "latency")) is not None
    assert len(c) == 2


def test_model_cache_pop_where():
    """Predicate pop drops matching resident AND restored-overlay
    entries (journaled as deletions), leaves the rest, and reports the
    count — the refinement planner's app-scoped joint invalidation."""
    c = ModelCache(max_size=8)
    c.put(("lstm", "appx", ("a", "b"), 2), {"w": 1})
    c.put(("bivariate", "appx", ("a", "b"), ("h",)), {"w": 2})
    c.put(("lstm", "other", ("a",), 1), {"w": 3})
    c.restore_lazy({("lstm", "appx", ("c",), 1): {"w": 4}})
    deleted = []
    c.journal = lambda items, **kw: deleted.extend(k for k, _ in items)
    n = c.pop_where(
        lambda k: isinstance(k, tuple) and len(k) > 1 and k[1] == "appx"
    )
    assert n == 3
    assert c.peek(("lstm", "other", ("a",), 1)) is not None
    assert c.peek(("lstm", "appx", ("a", "b"), 2)) is None
    assert c.restored_pending() == 0
    assert len(deleted) == 3
    # no matches: no version bump, no journal traffic
    v = c.version
    assert c.pop_where(lambda k: False) == 0
    assert c.version == v and len(deleted) == 3


def test_model_cache_checkpoint_roundtrip(tmp_path):
    c = ModelCache()
    c.put("svc1/latency", {"w": jnp.arange(3, dtype=jnp.float32)})
    c.save(str(tmp_path / "ckpt"))
    c2 = ModelCache()
    n = c2.load(str(tmp_path / "ckpt"))
    assert n == 1
    np.testing.assert_allclose(c2.get("svc1/latency")["w"], [0.0, 1.0, 2.0])


# -- seasonal-residual multivariate Gaussian ---------------------------------


def _comoving(rng, b, f, th, tc, period=24):
    from benchmarks.quality import draw_comoving

    return (
        draw_comoving(rng, b, f, th, 0, period),
        draw_comoving(rng, b, f, tc, th, period),
    )


def test_residual_mvn_catches_trough_masked_spike():
    """An all-metric spike at a seasonal trough lands near the MARGINAL
    mean — only the causal seasonal residual makes it visible."""
    from foremast_tpu.models.residual_mvn import (
        chi2_quantile,
        fit_residual_mvn,
        score_residual_mvn,
    )

    rng = np.random.default_rng(0)
    b, f, th, tc = 8, 4, 240, 30
    hist, cur = _comoving(rng, b, f, th, tc)
    # spike at phase 18 of the 24-cycle (trough: sin = -1 region)
    pos = (18 - (th + 0) % 24) % 24
    cur[:, :, pos] += 0.6
    state = fit_residual_mvn(jnp.asarray(hist))
    cut = chi2_quantile(4.0, f)
    flags = np.asarray(score_residual_mvn(state, jnp.asarray(cur), cut))
    assert flags[:, pos].all(), "trough spike must flag on every job"
    fp = flags.sum() - flags[:, pos].sum()
    assert fp <= 2, f"too many false positives: {fp}"


def test_residual_mvn_catches_correlation_break():
    """One metric leaving the co-moving pack is invisible marginally but
    huge in Mahalanobis distance."""
    from foremast_tpu.models.residual_mvn import (
        chi2_quantile,
        fit_residual_mvn,
        score_residual_mvn,
    )

    rng = np.random.default_rng(1)
    b, f, th, tc = 8, 4, 240, 30
    hist, cur = _comoving(rng, b, f, th, tc)
    cur[:, 2, 11] -= 0.6  # metric 2 departs downward at t=11
    state = fit_residual_mvn(jnp.asarray(hist))
    cut = chi2_quantile(4.0, f)
    flags = np.asarray(score_residual_mvn(state, jnp.asarray(cur), cut))
    assert flags[:, 11].all()


def test_residual_mvn_short_history_degrades_to_holt_and_tiny_invalid():
    """Histories under two seasons fit with m=1 (Holt residuals — the
    2-cycle identifiability rule) instead of going dark: the MVN stays
    valid and still catches gross joint anomalies. Histories too short
    for even the Holt fit's warm region stay invalid and flag nothing."""
    from foremast_tpu.models.residual_mvn import (
        fit_residual_mvn,
        score_residual_mvn,
    )

    rng = np.random.default_rng(2)
    hist, cur = _comoving(rng, 2, 3, 26, 10)  # < 2*24: m=1 partition
    state = fit_residual_mvn(jnp.asarray(hist))
    assert state.hw.season.shape[-1] == 1
    assert np.asarray(state.valid).all()
    cur[:, :, 4] += 100.0
    flags = np.asarray(score_residual_mvn(state, jnp.asarray(cur), 10.0))
    assert flags[:, 4].all()

    tiny_hist, tiny_cur = _comoving(rng, 2, 3, 8, 10)  # 7 warm < min 10
    tiny = fit_residual_mvn(jnp.asarray(tiny_hist))
    assert not np.asarray(tiny.valid).any()
    tiny_cur[:, :, 4] += 100.0
    tflags = np.asarray(score_residual_mvn(tiny, jnp.asarray(tiny_cur), 10.0))
    assert not tflags.any()


def test_residual_mvn_prefix_mask_matches_exact_length():
    """Bucket-padded histories must fit the same model as exact-length
    ones (the judge packs joint histories into power-of-two buckets)."""
    from foremast_tpu.models.residual_mvn import fit_residual_mvn

    rng = np.random.default_rng(3)
    b, f, th, tc = 4, 3, 200, 10
    hist, _ = _comoving(rng, b, f, th, tc)
    exact = fit_residual_mvn(jnp.asarray(hist))
    padded_h = np.zeros((b, f, 256), np.float32)
    padded_h[:, :, :th] = hist
    mask = np.zeros((b, 256), bool)
    mask[:, :th] = True
    padded = fit_residual_mvn(jnp.asarray(padded_h), jnp.asarray(mask))
    np.testing.assert_allclose(
        np.asarray(exact.mu), np.asarray(padded.mu), rtol=1e-4, atol=1e-5
    )
    np.testing.assert_allclose(
        np.asarray(exact.cov), np.asarray(padded.cov), rtol=1e-3, atol=1e-6
    )
    np.testing.assert_allclose(
        np.asarray(exact.hw.level), np.asarray(padded.hw.level), rtol=1e-4
    )


def test_ae_cutoff_gamma_tail_above_gaussian_bound():
    """Reconstruction error is right-skewed; the gamma quantile cutoff
    must sit at or above mean + thr*std (never loosen precision) and
    reduce to the mean for degenerate zero-variance errors."""
    from scipy import stats

    from foremast_tpu.models.lstm_ae import ae_cutoff

    mean = np.array([0.02, 0.5], np.float32)
    std = np.array([0.02, 0.0], np.float32)  # row 1: cv=1 (exponential-like)
    cut = ae_cutoff(mean, std, 4.0)
    assert cut[0] >= mean[0] + 4.0 * std[0]
    # cv=1 => k=1 (exponential): quantile = -theta*ln(p_tail), well above
    p_tail = 2 * stats.norm.sf(4.0)
    assert cut[0] == pytest.approx(-0.02 * np.log(p_tail), rel=1e-3)
    assert cut[1] == pytest.approx(0.5)  # zero variance: mean fallback
    # per-job thresholds broadcast (canary lowering)
    cut2 = ae_cutoff(mean, std, np.array([4.0, 2.0], np.float32))
    assert cut2[0] == pytest.approx(cut[0], rel=1e-6)


def test_residual_mvn_robust_d2_suppresses_spike_echo():
    """The causal HW state absorbs an observed spike and contaminates the
    NEXT prediction (an echo). The two-pass robust d^2 must keep the
    spike's own score high while flattening the echo back to clean
    levels; the plain pass shows the echo."""
    from foremast_tpu.models.residual_mvn import (
        chi2_quantile,
        fit_residual_mvn,
        residual_mvn_d2,
        residual_mvn_d2_robust,
    )

    rng = np.random.default_rng(11)
    b, f, th, tc = 4, 3, 480, 30
    hist, cur = _comoving(rng, b, f, th, tc)
    spike_t = 12
    cur[:, :, spike_t] += 1.0  # huge joint spike
    cut = chi2_quantile(4.0, f)
    state = fit_residual_mvn(jnp.asarray(hist))
    plain = np.asarray(residual_mvn_d2(state, jnp.asarray(cur)))
    robust = np.asarray(
        residual_mvn_d2_robust(state, jnp.asarray(cur), cut)
    )
    assert (robust[:, spike_t] > cut).all()  # the spike still screams
    # echo at t+1: plain is inflated, robust returns to clean levels
    clean_ref = np.median(robust[:, spike_t + 3 :], axis=1)
    assert (robust[:, spike_t + 1] < plain[:, spike_t + 1]).all()
    assert (robust[:, spike_t + 1] < cut).all()
    assert (robust[:, spike_t + 1] < 10 * np.maximum(clean_ref, 1.0)).all()


def test_seasonal_changepoints_localize_level_shift():
    """A mid-history step (redeploy / traffic migration) must not bend
    the global trend: the hinge weights absorb it locally, the terminal
    trend reflects the (flat) post-shift regime, and the horizon stays
    centered (VERDICT r2 item 7). The changepoint-free fit shows the
    bogus slope this guards against."""
    rng = np.random.default_rng(5)
    b, th, period = 4, 1008, 24
    t = np.arange(th)
    sig = 1.0 + 0.5 * np.sin(2 * np.pi * t / period) + 0.5 * (t >= int(0.55 * th))
    v = jnp.asarray(sig[None] + rng.normal(0, 0.05, (b, th)), jnp.float32)
    mask = jnp.ones((b, th), bool)

    fc = fit_seasonal(v, mask, period=period, order=3)
    plain = fit_seasonal(v, mask, period=period, order=3, n_changepoints=0)
    tt = th + np.arange(30)
    expect = 1.5 + 0.5 * np.sin(2 * np.pi * tt / period)
    err_cp = np.abs(np.asarray(horizon(fc, 30)) - expect[None]).max()
    err_plain = np.abs(np.asarray(horizon(plain, 30)) - expect[None]).max()
    assert err_cp < 0.05
    assert err_plain > 2 * err_cp  # the global-slope fit mis-centers
    assert abs(float(fc.trend.mean())) < 2e-4  # post-shift regime is flat
    assert float(fc.scale.mean()) < 0.1  # band ~ noise, not the step


def test_bivariate_short_history_is_verdict_capable():
    """Short-history entry point (ISSUE 10 admission): a paired
    history clearing `min_points` — a newcomer's 1-2 pushed days, not
    7 — fits a VALID, verdict-capable Gaussian; below the floor the
    fit is invalid and flags nothing (UNKNOWN upstream)."""
    from foremast_tpu.models.bivariate import detect_bivariate, fit_bivariate

    rng = np.random.default_rng(5)
    t_short = 24  # two "days" at an hourly step — far under a 7-day fit
    x = rng.normal(1.0, 0.1, (2, t_short)).astype(np.float32)
    y = (x + rng.normal(0.0, 0.03, x.shape)).astype(np.float32)
    mask = np.ones_like(x, bool)
    fit = fit_bivariate(jnp.asarray(x), jnp.asarray(y), jnp.asarray(mask))
    assert np.asarray(fit.valid).all()
    cx = np.full((2, 6), 1.0, np.float32)
    cy = cx.copy()
    cy[:, 3] += 5.0  # gross joint break
    flags = np.asarray(
        detect_bivariate(fit, jnp.asarray(cx), jnp.asarray(cy),
                         jnp.asarray(np.ones_like(cx, bool)), 4.0)
    )
    assert flags[:, 3].all()

    # below min_points: invalid, nothing flagged
    tiny_mask = np.zeros_like(mask)
    tiny_mask[:, :6] = True
    tiny = fit_bivariate(
        jnp.asarray(x), jnp.asarray(y), jnp.asarray(tiny_mask)
    )
    assert not np.asarray(tiny.valid).any()
