"""Recording-rule generator: naming-family coverage + manifest validity.

The reference's rule manifest is `metrics-rules-default.yaml`; the query
builder consumes the recorded names (`metricsquery.go:53-78`). These tests
assert the generated rules expose the exact naming families the query layer
depends on, and that the YAML renderer emits a parseable PrometheusRule.
"""

import yaml

from foremast_tpu.metrics.rules import (
    ALL_METRICS,
    BRAIN_GAUGE_SUFFIXES,
    all_rules,
    brain_rules,
    core_rules,
    prometheus_rule_manifest,
    request_rules,
    rule_expr,
    to_yaml,
)


def test_every_metric_recorded_at_all_three_levels():
    names = {r.record for r in all_rules()}
    for metric in ALL_METRICS:
        assert f"namespace_pod:{metric}" in names
        assert f"namespace_app:{metric}" in names
        assert f"namespace_app_per_pod:{metric}" in names


def test_per_pod_is_quotient_of_app_and_pod_count():
    expr = rule_expr("namespace_app_per_pod:http_server_requests_latency")
    assert expr == (
        "namespace_app:http_server_requests_latency / namespace_app:pod_count"
    )
    assert rule_expr("namespace_app:pod_count") is not None


def test_status_class_selectors():
    assert 'status=~"5[0-9]+"' in rule_expr(
        "namespace_pod:http_server_requests_error_5xx"
    )
    assert 'status=~"[4-5][0-9]+"' in rule_expr(
        "namespace_pod:http_server_requests_errors"
    )
    # total count has no status selector
    assert "status" not in rule_expr("namespace_pod:http_server_requests_count")
    # latency is a sum/count ratio gated on 200s
    latency = rule_expr("namespace_app:http_server_requests_latency")
    assert "http_server_requests_seconds_sum" in latency
    assert 'status="200"' in latency


def test_resource_rules_join_app_label():
    expr = rule_expr("namespace_app:cpu_usage_seconds_total")
    assert "kube_pod_labels" in expr and "group_left(app)" in expr
    pod_expr = rule_expr("namespace_pod:memory_usage_bytes")
    assert "container_memory_usage_bytes" in pod_expr


def test_no_duplicate_records():
    records = [r.record for r in all_rules()]
    assert len(records) == len(set(records))
    assert len(core_rules()) + len(request_rules()) + len(brain_rules()) == len(
        records
    )


def test_manifest_yaml_roundtrip():
    text = to_yaml()
    parsed = yaml.safe_load(text)
    assert parsed == prometheus_rule_manifest()
    assert parsed["kind"] == "PrometheusRule"
    groups = {g["name"] for g in parsed["spec"]["groups"]}
    assert groups == {
        "core.metrics.aggregation.rules",
        "request.metrics.aggregation.rules",
        "foremastbrain.gauge.spelling.rules",
        "foremast.alert.rules",
    }


def test_alert_rules_cover_every_metric_and_engine_liveness():
    """The reference only DECLARES alerting intent (`types.go:190-191`);
    the generated rules deliver it: per metric an anomaly-event alert
    (changes() on the sticky gauge — same event semantics as the UI join)
    and an upper-band breach alert with the exported_namespace join, plus
    an engine-liveness alert."""
    from foremast_tpu.metrics.rules import alert_rules

    rules = alert_rules()
    by_name = {r["alert"]: r for r in rules}
    for m in ALL_METRICS:
        gauge = f"namespace_app_per_pod:{m}"  # what the engine publishes
        anom = by_name[f"ForemastAnomaly_{m}"]
        a = f"foremastbrain:{gauge}_anomaly"
        # value change OR first appearance both count as an anomaly event
        assert f"changes({a}[5m]) > 0" in anom["expr"]
        assert f"({a} unless {a} offset 5m)" in anom["expr"]
        # direction-aware breach: traffic/success metrics page on a
        # LOWER-band collapse, everything else on an upper-band breach
        low_is_bad = m in ("http_server_requests_2xx", "http_server_requests_count")
        side = "Lower" if low_is_bad else "Upper"
        breach = by_name[f"Foremast{side}Breach_{m}"]
        band = "lower" if low_is_bad else "upper"
        assert f"foremastbrain:{gauge}_{band}" in breach["expr"]
        assert (" < " if low_is_bad else " > ") in breach["expr"]
        assert 'label_replace' in breach["expr"]
        assert "exported_namespace" in breach["expr"]
        # engine replicas / restart staleness must not break the join
        assert f"{'min' if low_is_bad else 'max'} by (namespace, app)" in breach["expr"]
        assert breach["for"] == "2m"
    down = by_name["ForemastEngineDown"]
    assert down["labels"]["severity"] == "critical"
    assert "foremast_worker_tick_seconds_count" in down["expr"]
    assert len(rules) == 2 * len(ALL_METRICS) + 1


def test_brain_rules_pin_colon_spelling_for_every_published_metric():
    """The signature observability contract (`foremast-brain.yaml:109-122`,
    `metrics.js:15-23`): every metric the engine can publish gauges for
    must have a recording rule mapping the exported underscore name to the
    reference's exact colon name — INCLUDING the recorded-family prefix
    (`foremastbrain:namespace_app_per_pod:<metric>_<suffix>`, the literal
    series the reference browser queries) — for all three suffixes."""
    by_record = {r.record: r.expr for r in brain_rules()}
    for metric in ALL_METRICS:
        for suffix in BRAIN_GAUGE_SUFFIXES:
            colon = f"foremastbrain:namespace_app_per_pod:{metric}_{suffix}"
            assert by_record[colon] == (
                f"foremastbrain_namespace_app_per_pod_{metric}_{suffix}"
            )
    assert set(BRAIN_GAUGE_SUFFIXES) == {"upper", "lower", "anomaly"}
    # exact reference spelling spot-check (metrics.js:15)
    assert (
        "foremastbrain:namespace_app_per_pod:http_server_requests_error_5xx_upper"
        in by_record
    )
    # the exported (underscore) names are exactly what BrainGauges creates
    # when publishing under the series name the verdict hook derives
    from prometheus_client import CollectorRegistry

    from foremast_tpu.observe.gauges import BrainGauges

    reg = CollectorRegistry()
    g = BrainGauges(registry=reg)
    for metric in ALL_METRICS:
        g.publish(
            f"namespace_app_per_pod:{metric}",
            "ns",
            "app",
            upper=1.0,
            lower=0.0,
            anomaly_value=2.0,
        )
    exported = {m.name for m in reg.collect()}
    for r in brain_rules():
        assert r.expr in exported


def test_unknown_record_resolves_none():
    assert rule_expr("namespace_pod:nope") is None
