"""CLI surface: score (end-to-end slice), watch/unwatch, rules.

The score test is the SURVEY.md section 7.3 "minimum end-to-end slice": a
reference-wire-format request judged against the golden demo traces, with
the response in the reference's DocumentResponse shape.
"""

import io
import json
import os

import pytest

from foremast_tpu.cli import main

DATA = os.path.join(os.path.dirname(__file__), "data")
NORMAL = os.path.join(DATA, "demo_canary_normal.csv")
SPIKE = os.path.join(DATA, "demo_canary_spike.csv")


def make_request(tmp_path, aliases=("error4xx",)):
    def mq(query):
        return {
            "dataSourceType": "prometheus",
            "parameters": {
                "endpoint": "http://prometheus:9090/api/v1/",
                "query": query,
                "start": "1600000000",
                "end": "1600000600",
                "step": "60",
            },
        }

    req = {
        "appName": "demo-app",
        "startTime": "2020-09-13T12:26:40Z",
        "endTime": "2020-09-13T12:36:40Z",
        "strategy": "canary",
        "metrics": {
            "current": {a: mq(f"cur:{a}") for a in aliases},
            "baseline": {a: mq(f"base:{a}") for a in aliases},
            "historical": {a: mq(f"hist:{a}") for a in aliases},
        },
    }
    path = tmp_path / "request.json"
    path.write_text(json.dumps(req))
    return str(path)


def run_score(capsys, request_path, current, baseline, historical):
    argv = ["score", "--request", request_path]
    for alias, path in current.items():
        argv += ["--current", f"{alias}={path}"]
    for alias, path in baseline.items():
        argv += ["--baseline", f"{alias}={path}"]
    for alias, path in historical.items():
        argv += ["--historical", f"{alias}={path}"]
    rc = main(argv)
    out = capsys.readouterr().out
    return rc, json.loads(out)


def test_score_spike_trace_is_anomaly(tmp_path, capsys):
    req = make_request(tmp_path)
    rc, resp = run_score(
        capsys,
        req,
        current={"error4xx": SPIKE},
        baseline={"error4xx": NORMAL},
        historical={"error4xx": NORMAL},
    )
    assert rc == 0
    # external status enum (converter.go:11-30): unhealthy -> "anomaly"
    assert resp["status"] == "anomaly"
    assert resp["anomalyInfo"]["values"]["error4xx"], "flat [t,v,...] pairs"
    # flat pair encoding: even length, alternating time/value
    pairs = resp["anomalyInfo"]["values"]["error4xx"]
    assert len(pairs) % 2 == 0
    values = pairs[1::2]
    assert any(v > 30 for v in values), "the 40.134 spike should be flagged"


def test_score_normal_trace_is_healthy(tmp_path, capsys):
    req = make_request(tmp_path)
    rc, resp = run_score(
        capsys,
        req,
        current={"error4xx": NORMAL},
        baseline={"error4xx": NORMAL},
        historical={"error4xx": NORMAL},
    )
    assert rc == 0
    assert resp["status"] == "success"


def test_score_unknown_alias_rejected(tmp_path, capsys):
    req = make_request(tmp_path)
    with pytest.raises(SystemExit):
        main(["score", "--request", req, "--current", f"nope={NORMAL}"])


def test_score_reads_stdin(tmp_path, capsys, monkeypatch):
    req_path = make_request(tmp_path)
    monkeypatch.setattr(
        "sys.stdin", io.StringIO(open(req_path).read())
    )
    rc, resp = run_score(
        capsys,
        "-",
        current={"error4xx": NORMAL},
        baseline={"error4xx": NORMAL},
        historical={"error4xx": NORMAL},
    )
    assert rc == 0 and resp["status"] == "success"


def test_rules_prints_manifest(capsys):
    import yaml

    rc = main(["rules", "--namespace", "observ"])
    assert rc == 0
    parsed = yaml.safe_load(capsys.readouterr().out)
    assert parsed["kind"] == "PrometheusRule"
    assert parsed["metadata"]["namespace"] == "observ"


def test_watch_unwatch_toggle_continuous(monkeypatch, capsys):
    from foremast_tpu.watch.crds import DeploymentMonitor
    from foremast_tpu.watch.kubeapi import InMemoryKube

    from foremast_tpu.watch.crds import MonitorStatus

    kube = InMemoryKube()
    kube.upsert_monitor(
        DeploymentMonitor(
            name="demo", namespace="ns1", status=MonitorStatus(job_id="job-42")
        )
    )
    monkeypatch.setattr(
        "foremast_tpu.watch.kubeapi.HttpKube", lambda base_url=None: kube
    )
    rc = main(["watch", "demo", "-n", "ns1"])
    assert rc == 0
    assert kube.get_monitor("ns1", "demo").continuous is True
    rc = main(["unwatch", "demo", "-n", "ns1"])
    assert rc == 0
    assert kube.get_monitor("ns1", "demo").continuous is False
    # merge-patch semantics: untouched fields survive the toggle
    assert kube.get_monitor("ns1", "demo").status.job_id == "job-42"
    out = capsys.readouterr().out
    assert "watching application demo" in out
    assert "Job: job-42" in out


def test_watch_missing_monitor_fails(monkeypatch, capsys):
    from foremast_tpu.watch.kubeapi import InMemoryKube

    monkeypatch.setattr(
        "foremast_tpu.watch.kubeapi.HttpKube", lambda base_url=None: InMemoryKube()
    )
    assert main(["watch", "ghost", "-n", "ns1"]) == 1


def test_score_honors_env_config(tmp_path, capsys, monkeypatch):
    """cmd_score must build its worker from BrainConfig.from_env() — the
    reference brain is configured entirely through env vars
    (foremast-brain/README.md:20-38). A near-zero threshold must flip
    even the normal trace to anomaly; the indexed rule matrix would be
    silently ignored if score used BrainConfig() defaults."""
    monkeypatch.setenv("metric_type_threshold_count", "1")
    monkeypatch.setenv("metric_type0", "error4xx")
    monkeypatch.setenv("threshold0", "0.0001")
    req = make_request(tmp_path)
    rc, resp = run_score(
        capsys,
        req,
        current={"error4xx": NORMAL},
        baseline={"error4xx": NORMAL},
        historical={"error4xx": NORMAL},
    )
    assert resp["status"] == "anomaly"


def test_enable_compile_cache_sets_jax_config(tmp_path, monkeypatch):
    """FOREMAST_COMPILE_CACHE_DIR points JAX's persistent compilation
    cache at a durable dir (and creates it) so warmup compiles survive
    process restarts; unset, the knob must be a no-op."""
    import jax

    from foremast_tpu.cli import _enable_compile_cache

    flags = (
        "jax_compilation_cache_dir",
        "jax_persistent_cache_min_compile_time_secs",
        "jax_persistent_cache_min_entry_size_bytes",
    )
    prev = {f: getattr(jax.config, f) for f in flags if hasattr(jax.config, f)}
    target = tmp_path / "xla-cache"
    monkeypatch.setenv("FOREMAST_COMPILE_CACHE_DIR", str(target))
    try:
        _enable_compile_cache()
        assert jax.config.jax_compilation_cache_dir == str(target)
        assert target.is_dir()
    finally:
        # restore: a tmp_path-bound cache dir must not outlive the test
        for f, v in prev.items():
            jax.config.update(f, v)

    monkeypatch.delenv("FOREMAST_COMPILE_CACHE_DIR")
    _enable_compile_cache()  # unset: no-op, config untouched
    assert jax.config.jax_compilation_cache_dir == prev.get(
        "jax_compilation_cache_dir"
    )
