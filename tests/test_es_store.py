"""ElasticsearchStore tests against an in-process fake ES.

The fake implements exactly the REST surface the store uses (root ping,
_doc GET/PUT with op_type=create and if_seq_no/if_primary_term CAS,
_search with terms / bool-must_not queries), so the production-critical
semantics — idempotent creation, optimistic-concurrency claims, stuck-job
takeover — are covered without a live cluster.
"""

from __future__ import annotations

import re
import urllib.parse

from foremast_tpu.jobs.models import (
    Document,
    STATUS_COMPLETED_HEALTH,
    STATUS_PREPROCESS_INPROGRESS,
)
from foremast_tpu.jobs.store import ElasticsearchStore


class _Resp:
    def __init__(self, status: int, body: dict):
        self.status_code = status
        self._body = body
        self.ok = status < 400

    def json(self):
        return self._body

    def raise_for_status(self):
        if self.status_code >= 400:
            raise RuntimeError(f"http {self.status_code}: {self._body}")


class FakeES:
    """documents/_doc store with seq_no/primary_term versioning."""

    def __init__(self):
        self.docs: dict[str, dict] = {}  # id -> {"_source":…, "_seq_no":int}
        self._seq = 0

    # requests.Session surface -----------------------------------------

    def get(self, url, timeout=None, **kw):
        path = urllib.parse.urlparse(url).path
        if path in ("", "/"):
            return _Resp(200, {"cluster_name": "fake"})
        m = re.fullmatch(r"/documents/_doc/([^/]+)", path)
        if m:
            rec = self.docs.get(urllib.parse.unquote(m.group(1)))
            if rec is None:
                return _Resp(404, {"found": False})
            return _Resp(200, {"found": True, "_source": rec["_source"]})
        return _Resp(404, {})

    def put(self, url, json=None, timeout=None, **kw):
        u = urllib.parse.urlparse(url)
        q = urllib.parse.parse_qs(u.query)
        m = re.fullmatch(r"/documents/_doc/([^/]+)", u.path)
        assert m, u.path
        doc_id = urllib.parse.unquote(m.group(1))
        rec = self.docs.get(doc_id)
        if q.get("op_type") == ["create"] and rec is not None:
            return _Resp(409, {"error": "version_conflict_engine_exception"})
        if "if_seq_no" in q:
            if rec is None or rec["_seq_no"] != int(q["if_seq_no"][0]):
                return _Resp(409, {"error": "version_conflict_engine_exception"})
        self._seq += 1
        self.docs[doc_id] = {"_source": json, "_seq_no": self._seq}
        return _Resp(200, {"result": "updated"})

    def post(self, url, json=None, timeout=None, **kw):
        path = urllib.parse.urlparse(url).path
        assert path == "/documents/_search", path
        hits = []
        for doc_id, rec in self.docs.items():
            if self._matches(json.get("query", {}), rec["_source"]):
                hits.append(
                    {
                        "_id": doc_id,
                        "_source": rec["_source"],
                        "_seq_no": rec["_seq_no"],
                        "_primary_term": 1,
                    }
                )
        size = json.get("size", 10)
        return _Resp(200, {"hits": {"hits": hits[:size]}})

    @staticmethod
    def _matches(query: dict, source: dict) -> bool:
        if "terms" in query:
            (field, values), = query["terms"].items()
            return source.get(field) in values
        if "bool" in query and "must_not" in query["bool"]:
            return not FakeES._matches(query["bool"]["must_not"], source)
        return True


def _store(fake=None):
    fake = fake or FakeES()
    return ElasticsearchStore("http://fake:9200", session=fake), fake


def test_create_is_idempotent():
    store, fake = _store()
    d1, created1 = store.create(Document(id="j1", app_name="a"))
    d2, created2 = store.create(Document(id="j1", app_name="a"))
    assert created1 and not created2
    assert d2.id == "j1"
    assert len(fake.docs) == 1


def test_get_roundtrip_and_missing():
    store, _ = _store()
    store.create(Document(id="j1", app_name="a", strategy="canary"))
    doc = store.get("j1")
    assert doc is not None and doc.strategy == "canary"
    assert store.get("nope") is None


def test_claim_flips_status_and_is_exclusive():
    fake = FakeES()
    a, _ = _store(fake)
    b, _ = _store(fake)
    a.create(Document(id="j1", app_name="x"))
    got_a = a.claim("worker-a", max_stuck_seconds=90)
    got_b = b.claim("worker-b", max_stuck_seconds=90)
    assert [d.id for d in got_a] == ["j1"]
    assert got_b == []  # already in-progress, not claimable
    assert fake.docs["j1"]["_source"]["status"] == STATUS_PREPROCESS_INPROGRESS
    assert fake.docs["j1"]["_source"]["processingContent"] == "worker-a"


def test_claim_cas_race_single_winner():
    """Two workers fetch the same search hit; the CAS must let exactly one
    win (the reference gets this from ES versioned writes)."""
    fake = FakeES()
    a, _ = _store(fake)
    a.create(Document(id="j1", app_name="x"))

    hit_seq = fake.docs["j1"]["_seq_no"]
    # simulate B writing first with the same seq_no A saw
    fake.put(
        "http://fake:9200/documents/_doc/j1"
        f"?if_seq_no={hit_seq}&if_primary_term=1",
        json={**fake.docs["j1"]["_source"], "status": STATUS_PREPROCESS_INPROGRESS},
    )
    # A's claim now sees a stale seq_no on its own CAS write -> 409 -> skip
    got = a.claim("worker-a", max_stuck_seconds=90)
    assert got == []


def test_stuck_job_takeover():
    """A doc stuck in preprocess_inprogress past MAX_STUCK_IN_SECONDS is
    claimable again (work stealing, design.md:39)."""
    fake = FakeES()
    store, _ = _store(fake)
    store.create(Document(id="j1", app_name="x"))
    (claimed,) = store.claim("worker-a", max_stuck_seconds=90)
    # age the claim far past the stuck threshold
    src = fake.docs["j1"]["_source"]
    src["modifiedAt"] = "2000-01-01T00:00:00Z"
    got = store.claim("worker-b", max_stuck_seconds=90)
    assert [d.id for d in got] == ["j1"]
    assert fake.docs["j1"]["_source"]["processingContent"] == "worker-b"


def test_update_and_list_open():
    store, fake = _store()
    store.create(Document(id="j1", app_name="a"))
    store.create(Document(id="j2", app_name="b"))
    doc = store.get("j1")
    doc.status = STATUS_COMPLETED_HEALTH
    store.update(doc)
    open_ids = {d.id for d in store.list_open()}
    assert open_ids == {"j2"}


def test_wait_ready_returns_when_reachable():
    store, _ = _store()
    assert store.wait_ready(retry_seconds=0.01, max_wait=1.0)
