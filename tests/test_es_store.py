"""ElasticsearchStore tests against an in-process fake ES.

The fake implements exactly the REST surface the store uses (root ping,
_doc GET/PUT with op_type=create and if_seq_no/if_primary_term CAS,
_search with terms / bool should+must+range queries and modifiedAt sort,
_bulk with per-action CAS), so the production-critical semantics —
idempotent creation, optimistic-concurrency claims, stuck-job takeover,
starvation-free O(1)-round-trip claiming — are covered without a live
cluster.
"""

from __future__ import annotations

import re
import urllib.parse

from foremast_tpu.jobs.models import (
    Document,
    STATUS_COMPLETED_HEALTH,
    STATUS_PREPROCESS_INPROGRESS,
)
from foremast_tpu.jobs.store import ElasticsearchStore


class _Resp:
    def __init__(self, status: int, body: dict):
        self.status_code = status
        self._body = body
        self.ok = status < 400

    def json(self):
        return self._body

    def raise_for_status(self):
        if self.status_code >= 400:
            raise RuntimeError(f"http {self.status_code}: {self._body}")


class FakeES:
    """documents/_doc store with seq_no/primary_term versioning.

    Mapping-strict: `terms` queries require an explicitly-mapped keyword
    field, `range` filters and sorts require a date field, and searching
    before the index exists is a 404 — so the store's claim semantics are
    provably guaranteed by its INDEX_MAPPINGS template, never by
    dynamic-mapping luck (VERDICT r2 item 6).
    """

    def __init__(self):
        self.docs: dict[str, dict] = {}  # id -> {"_source":…, "_seq_no":int}
        self._seq = 0
        self.requests = 0  # HTTP round trips (claim must stay O(1))
        self.mappings: dict | None = None  # set by index-create PUT

    def _field_type(self, field: str) -> str | None:
        if not self.mappings:
            return None
        return (self.mappings.get("properties", {}).get(field) or {}).get("type")

    # requests.Session surface -----------------------------------------

    def get(self, url, timeout=None, **kw):
        self.requests += 1
        path = urllib.parse.urlparse(url).path
        if path in ("", "/"):
            return _Resp(200, {"cluster_name": "fake"})
        if path == "/documents/_mapping":
            if self.mappings is None:
                return _Resp(404, {"error": {"type": "index_not_found_exception"}})
            return _Resp(200, {"documents": {"mappings": self.mappings}})
        m = re.fullmatch(r"/documents/_doc/([^/]+)", path)
        if m:
            rec = self.docs.get(urllib.parse.unquote(m.group(1)))
            if rec is None:
                return _Resp(404, {"found": False})
            return _Resp(200, {"found": True, "_source": rec["_source"]})
        return _Resp(404, {})

    def put(self, url, json=None, timeout=None, **kw):
        self.requests += 1
        u = urllib.parse.urlparse(url)
        q = urllib.parse.parse_qs(u.query)
        if u.path == "/documents":  # index creation with mappings
            if self.mappings is not None:
                return _Resp(
                    400,
                    {"error": {"type": "resource_already_exists_exception",
                               "reason": "resource_already_exists_exception"}},
                )
            self.mappings = (json or {}).get("mappings", {})
            return _Resp(200, {"acknowledged": True})
        if u.path == "/documents/_mapping":  # additive field mapping
            if self.mappings is None:
                return _Resp(404, {"error": {"type": "index_not_found_exception"}})
            self.mappings.setdefault("properties", {}).update(
                (json or {}).get("properties", {})
            )
            return _Resp(200, {"acknowledged": True})
        m = re.fullmatch(r"/documents/_doc/([^/]+)", u.path)
        assert m, u.path
        doc_id = urllib.parse.unquote(m.group(1))
        rec = self.docs.get(doc_id)
        if q.get("op_type") == ["create"] and rec is not None:
            return _Resp(409, {"error": "version_conflict_engine_exception"})
        if "if_seq_no" in q:
            if rec is None or rec["_seq_no"] != int(q["if_seq_no"][0]):
                return _Resp(409, {"error": "version_conflict_engine_exception"})
        self._seq += 1
        self.docs[doc_id] = {"_source": json, "_seq_no": self._seq}
        return _Resp(200, {"result": "updated"})

    def post(self, url, json=None, data=None, headers=None, timeout=None, **kw):
        self.requests += 1
        path = urllib.parse.urlparse(url).path
        if path == "/documents/_bulk":
            return self._bulk(data, headers or {})
        assert path == "/documents/_search", path
        if self.mappings is None:
            return _Resp(404, {"error": {"type": "index_not_found_exception"}})
        err = self._validate_query(json.get("query", {}))
        if err is None:
            for spec in json.get("sort", []):
                ((field, _opts),) = spec.items()
                if self._field_type(field) != "date":
                    err = f"sort on non-date field {field!r}"
        if err is not None:
            return _Resp(400, {"error": {"type": "search_phase_execution_exception", "reason": err}})
        hits = []
        for doc_id, rec in self.docs.items():
            if self._matches(json.get("query", {}), rec["_source"]):
                hits.append(
                    {
                        "_id": doc_id,
                        "_source": rec["_source"],
                        "_seq_no": rec["_seq_no"],
                        "_primary_term": 1,
                    }
                )
        for spec in json.get("sort", []):
            ((field, opts),) = spec.items()
            hits.sort(
                key=lambda h: h["_source"].get(field, ""),
                reverse=opts.get("order") == "desc",
            )
        size = json.get("size", 10)
        return _Resp(200, {"hits": {"hits": hits[:size]}})

    def _bulk(self, data: str, headers: dict) -> _Resp:
        import json as _json

        assert headers.get("Content-Type") == "application/x-ndjson"
        lines = [ln for ln in data.split("\n") if ln.strip()]
        items = []
        for action_ln, doc_ln in zip(lines[0::2], lines[1::2]):
            action = _json.loads(action_ln)["index"]
            doc = _json.loads(doc_ln)
            doc_id = action["_id"]
            rec = self.docs.get(doc_id)
            if "if_seq_no" in action and (
                rec is None or rec["_seq_no"] != action["if_seq_no"]
            ):
                items.append({"index": {"_id": doc_id, "status": 409}})
                continue
            self._seq += 1
            self.docs[doc_id] = {"_source": doc, "_seq_no": self._seq}
            items.append({"index": {"_id": doc_id, "status": 200}})
        return _Resp(200, {"items": items, "errors": False})

    def _validate_query(self, query: dict) -> str | None:
        """Reject query shapes dynamic mapping would not support: exact
        `terms` need an explicit keyword field, `range` needs a date."""
        if "terms" in query:
            ((field, _values),) = query["terms"].items()
            if self._field_type(field) != "keyword":
                return f"terms on non-keyword field {field!r}"
        if "range" in query:
            ((field, _cond),) = query["range"].items()
            if self._field_type(field) != "date":
                return f"range on non-date field {field!r}"
        if "bool" in query:
            b = query["bool"]
            for key in ("must", "should"):
                for sub in b.get(key, []):
                    err = self._validate_query(sub)
                    if err:
                        return err
            if "must_not" in b:
                return self._validate_query(b["must_not"])
        return None

    @staticmethod
    def _matches(query: dict, source: dict) -> bool:
        if "terms" in query:
            (field, values), = query["terms"].items()
            return source.get(field) in values
        if "range" in query:
            ((field, cond),) = query["range"].items()
            value = source.get(field, "")
            ok = True
            if "lt" in cond:
                ok = ok and value < cond["lt"]
            if "gt" in cond:
                ok = ok and value > cond["gt"]
            return ok
        if "bool" in query:
            # real ES conjoins the clause kinds — a bool carrying both
            # `must` and `must_not` (the store's list_app query) must
            # apply BOTH, not whichever is checked first
            b = query["bool"]
            ok = True
            if "must" in b:
                ok = ok and all(FakeES._matches(q, source) for q in b["must"])
            if "should" in b:
                ok = ok and any(
                    FakeES._matches(q, source) for q in b["should"]
                )
            if "must_not" in b:
                ok = ok and not FakeES._matches(b["must_not"], source)
            return ok
        return True


def _store(fake=None):
    fake = fake or FakeES()
    store = ElasticsearchStore("http://fake:9200", session=fake)
    assert store.wait_ready(max_wait=0)  # ping + idempotent index create
    return store, fake


def test_create_is_idempotent():
    store, fake = _store()
    d1, created1 = store.create(Document(id="j1", app_name="a"))
    d2, created2 = store.create(Document(id="j1", app_name="a"))
    assert created1 and not created2
    assert d2.id == "j1"
    assert len(fake.docs) == 1


def test_get_roundtrip_and_missing():
    store, _ = _store()
    store.create(Document(id="j1", app_name="a", strategy="canary"))
    doc = store.get("j1")
    assert doc is not None and doc.strategy == "canary"
    assert store.get("nope") is None


def test_claim_flips_status_and_is_exclusive():
    fake = FakeES()
    a, _ = _store(fake)
    b, _ = _store(fake)
    a.create(Document(id="j1", app_name="x"))
    got_a = a.claim("worker-a", max_stuck_seconds=90)
    got_b = b.claim("worker-b", max_stuck_seconds=90)
    assert [d.id for d in got_a] == ["j1"]
    assert got_b == []  # already in-progress, not claimable
    assert fake.docs["j1"]["_source"]["status"] == STATUS_PREPROCESS_INPROGRESS
    assert fake.docs["j1"]["_source"]["processingContent"] == "worker-a"


def test_claim_cas_race_single_winner():
    """Two workers race on the same search hit; the bulk-action CAS must
    let exactly one win (the reference gets this from ES versioned
    writes)."""

    class RacingES(FakeES):
        """Bumps j1's version between A's search and A's bulk write —
        modelling worker B winning the CAS in that window."""

        def post(self, url, json=None, data=None, headers=None, **kw):
            resp = super().post(url, json=json, data=data, headers=headers, **kw)
            if url.endswith("/_search") and "j1" in self.docs:
                self._seq += 1
                self.docs["j1"]["_seq_no"] = self._seq
            return resp

    fake = RacingES()
    a, _ = _store(fake)
    a.create(Document(id="j1", app_name="x"))
    got = a.claim("worker-a", max_stuck_seconds=90)
    assert got == []  # stale seq_no -> per-item 409 -> skipped
    # and the loser's write did NOT clobber the winner's version
    assert fake.docs["j1"]["_source"]["status"] == "initial"


def test_claim_not_starved_by_inprogress_crowd_and_two_round_trips():
    """VERDICT r1 item 8: 64 fresh docs must be claimed even when 1,000
    non-stuck in-progress docs exist (server-side claimability + sort,
    not client-side filtering of an arbitrary page), in exactly two HTTP
    round trips (search + _bulk)."""
    fake = FakeES()
    store, _ = _store(fake)
    for i in range(1000):
        store.create(
            Document(
                id=f"busy{i}", app_name="x", status=STATUS_PREPROCESS_INPROGRESS
            )
        )
    for i in range(64):
        store.create(Document(id=f"fresh{i}", app_name="x"))

    fake.requests = 0
    got = store.claim("worker-a", max_stuck_seconds=90, limit=64)
    assert len(got) == 64
    assert {d.id for d in got} == {f"fresh{i}" for i in range(64)}
    assert fake.requests == 2  # one _search + one _bulk


def test_claim_oversampled_page_still_caps_at_limit():
    """Contention decorrelation (ISSUE 7): the claim searches a 2x page
    and shuffles fresh hits so concurrent workers CAS mostly-disjoint
    subsets — but it must never claim MORE than `limit` docs, and a
    stuck takeover must still outrank every shuffled fresh hit."""
    fake = FakeES()
    store, _ = _store(fake)
    for i in range(8):
        store.create(Document(id=f"f{i}", app_name="x"))
    store.create(Document(id="stuck", app_name="x"))
    fake.docs["stuck"]["_source"]["status"] = STATUS_PREPROCESS_INPROGRESS
    fake.docs["stuck"]["_source"]["modifiedAt"] = "2000-01-01T00:00:00Z"
    got = store.claim("worker-a", max_stuck_seconds=90, limit=3)
    assert len(got) == 3
    assert got[0].id == "stuck"  # strict takeover priority survives
    # the rest stay claimable for a peer
    got2 = store.claim("worker-b", max_stuck_seconds=90, limit=64)
    assert {d.id for d in got} | {d.id for d in got2} == (
        {f"f{i}" for i in range(8)} | {"stuck"}
    )


def test_claim_prefers_oldest_docs():
    """Oldest-modified first: a stuck doc aged far in the past outranks
    fresher claimables when the page is smaller than the backlog."""
    fake = FakeES()
    store, _ = _store(fake)
    store.create(Document(id="new1", app_name="x"))
    store.create(Document(id="stuck1", app_name="x"))
    fake.docs["stuck1"]["_source"]["status"] = STATUS_PREPROCESS_INPROGRESS
    fake.docs["stuck1"]["_source"]["modifiedAt"] = "2000-01-01T00:00:00Z"
    got = store.claim("worker-a", max_stuck_seconds=90, limit=1)
    assert [d.id for d in got] == ["stuck1"]


def test_stuck_job_takeover():
    """A doc stuck in preprocess_inprogress past MAX_STUCK_IN_SECONDS is
    claimable again (work stealing, design.md:39)."""
    fake = FakeES()
    store, _ = _store(fake)
    store.create(Document(id="j1", app_name="x"))
    (claimed,) = store.claim("worker-a", max_stuck_seconds=90)
    # age the claim far past the stuck threshold
    src = fake.docs["j1"]["_source"]
    src["modifiedAt"] = "2000-01-01T00:00:00Z"
    got = store.claim("worker-b", max_stuck_seconds=90)
    assert [d.id for d in got] == ["j1"]
    assert fake.docs["j1"]["_source"]["processingContent"] == "worker-b"


def test_update_and_list_open():
    store, fake = _store()
    store.create(Document(id="j1", app_name="a"))
    store.create(Document(id="j2", app_name="b"))
    doc = store.get("j1")
    doc.status = STATUS_COMPLETED_HEALTH
    store.update(doc)
    open_ids = {d.id for d in store.list_open()}
    assert open_ids == {"j2"}


def test_wait_ready_returns_when_reachable():
    store, _ = _store()
    assert store.wait_ready(retry_seconds=0.01, max_wait=1.0)


def test_ensure_index_idempotent_and_template_guarantees_claims():
    """wait_ready creates the index with INDEX_MAPPINGS once; a second
    call hits resource_already_exists and still reports ready. Skipping
    the template (fresh fake, no wait_ready) makes the claim query FAIL
    LOUDLY instead of silently depending on dynamic-mapping luck."""
    from foremast_tpu.jobs.store import INDEX_MAPPINGS

    store, fake = _store()
    assert fake.mappings == INDEX_MAPPINGS
    assert store.wait_ready(max_wait=0)  # second create: 400 handled
    store.create(Document(id="a", app_name="x", status="initial"))
    assert [d.id for d in store.claim("w", 90.0)] == ["a"]

    bare = ElasticsearchStore("http://fake:9200", session=FakeES())
    bare.create(Document(id="a", app_name="x", status="initial"))
    try:
        bare.claim("w", 90.0)
    except RuntimeError as e:
        assert "404" in str(e) or "400" in str(e)
    else:  # pragma: no cover
        raise AssertionError("claim without the index template must surface")


def test_index_mappings_cover_every_wire_field():
    """Every field Document serializes must have an explicit mapping —
    a new wire field silently falling back to dynamic mapping is exactly
    the drift this template exists to prevent."""
    from foremast_tpu.jobs.store import INDEX_MAPPINGS

    wire = set(Document(id="x", app_name="a", anomaly_info={"k": 1}).to_json())
    assert wire <= set(INDEX_MAPPINGS["properties"]), (
        wire - set(INDEX_MAPPINGS["properties"])
    )
    # and the claim-critical types are pinned
    p = INDEX_MAPPINGS["properties"]
    assert p["status"]["type"] == "keyword"
    assert p["processingContent"]["type"] == "keyword"
    assert p["modifiedAt"]["type"] == "date"


def test_ensure_index_rejects_divergent_preexisting_mapping():
    """An index that already exists with incompatible field types (e.g.
    dynamic-mapped text `status` from a write that raced ahead of
    wait_ready) must raise MappingDivergence — never silently run claim
    queries against it. A compatible pre-existing index passes."""
    import pytest

    from foremast_tpu.jobs.store import (
        INDEX_MAPPINGS,
        MappingDivergence,
    )

    fake = FakeES()
    fake.mappings = {
        "properties": {
            **INDEX_MAPPINGS["properties"],
            "status": {"type": "text"},  # dynamic-mapping shape
        }
    }
    store = ElasticsearchStore("http://fake:9200", session=fake)
    with pytest.raises(MappingDivergence, match="status"):
        store.ensure_index()
    with pytest.raises(MappingDivergence):
        store.wait_ready(max_wait=0)  # config error surfaces, no retry loop

    ok = FakeES()
    ok.mappings = INDEX_MAPPINGS  # pre-existing but compatible
    store2 = ElasticsearchStore("http://fake:9200", session=ok)
    assert store2.ensure_index()


def test_ensure_index_pins_fields_added_since_index_creation():
    """An index created by a previous version lacks template fields the
    template has since gained (traceId); ensure_index must add them in
    place so the first trace-stamped write doesn't fall to analyzed-text
    dynamic mapping."""
    from foremast_tpu.jobs.store import INDEX_MAPPINGS

    fake = FakeES()
    fake.mappings = {
        "properties": {
            k: v
            for k, v in INDEX_MAPPINGS["properties"].items()
            if k != "traceId"
        }
    }
    store = ElasticsearchStore("http://fake:9200", session=fake)
    assert store.ensure_index()
    assert (
        fake.mappings["properties"]["traceId"]
        == INDEX_MAPPINGS["properties"]["traceId"]
    )
