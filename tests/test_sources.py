"""Metric-source robustness (ISSUE 5 satellites): transient-failure
retries in PrometheusSource, and CSV-trace tolerance (empty files,
unsorted/duplicated timestamps)."""

import numpy as np
import pytest

from foremast_tpu.metrics.source import (
    PrometheusSource,
    ReplaySource,
    load_csv_trace,
)

_OK_BODY = {
    "status": "success",
    "data": {"result": [{"values": [[100, "1.0"], [160, "2.0"]]}]},
}


class _FlakySession:
    """Fails the first `failures` GETs (exception or status), then 200."""

    def __init__(self, failures, mode="conn"):
        self.failures = failures
        self.mode = mode
        self.calls = 0

    def get(self, url, timeout=None):
        self.calls += 1
        if self.calls <= self.failures:
            if self.mode == "conn":
                raise ConnectionError("refused")
            return _Resp(self.mode)
        return _Resp(200)


class _Resp:
    def __init__(self, status):
        self.status_code = status

    def raise_for_status(self):
        if self.status_code >= 400:
            raise RuntimeError(f"http {self.status_code}")

    def json(self):
        return _OK_BODY


@pytest.mark.parametrize("mode", ["conn", 503, 429])
def test_prometheus_source_retries_transient_failures(mode):
    sess = _FlakySession(2, mode=mode)
    src = PrometheusSource(session=sess, retries=2, backoff_seconds=0.001)
    ts, vs = src.fetch("http://p/q")
    assert sess.calls == 3
    assert ts.tolist() == [100, 160]


def test_prometheus_source_exhausted_retries_raise():
    sess = _FlakySession(10, mode="conn")
    src = PrometheusSource(session=sess, retries=2, backoff_seconds=0.001)
    with pytest.raises(ConnectionError):
        src.fetch("http://p/q")
    assert sess.calls == 3  # 1 try + 2 retries, bounded


def test_prometheus_source_does_not_retry_config_errors():
    """4xx (bad query) is not transient: fail on the first attempt."""
    sess = _FlakySession(10, mode=404)
    src = PrometheusSource(session=sess, retries=3, backoff_seconds=0.001)
    with pytest.raises(RuntimeError):
        src.fetch("http://p/q")
    assert sess.calls == 1


def test_prometheus_source_zero_retries_restores_fail_fast():
    sess = _FlakySession(1, mode="conn")
    src = PrometheusSource(session=sess, retries=0)
    with pytest.raises(ConnectionError):
        src.fetch("http://p/q")
    assert sess.calls == 1


def test_prometheus_source_reads_retry_knob(monkeypatch):
    monkeypatch.setenv("FOREMAST_FETCH_RETRIES", "5")
    assert PrometheusSource().retries == 5
    monkeypatch.delenv("FOREMAST_FETCH_RETRIES")
    assert PrometheusSource().retries == 2  # registry default


# ---------------------------------------------------------------------------
# CSV traces
# ---------------------------------------------------------------------------


def test_load_csv_trace_empty_file(tmp_path):
    p = tmp_path / "empty.csv"
    p.write_text("")
    ts, vs = load_csv_trace(str(p))
    assert len(ts) == 0 and len(vs) == 0
    assert ts.dtype == np.int64 and vs.dtype == np.float32


def test_load_csv_trace_sorts_stably_keeping_duplicates(tmp_path):
    p = tmp_path / "unsorted.csv"
    # out of order + duplicate timestamps: sorted, file order preserved
    # within a timestamp run, NO samples dropped (the demo replay traces
    # record several observations per coarse 5-min stamp — collapsing
    # them would starve the min-points gates)
    p.write_text("300,3.0\n100,1.0\n300,9.0\n200,2.0\n")
    ts, vs = load_csv_trace(str(p))
    assert ts.tolist() == [100, 200, 300, 300]
    assert vs.tolist() == [1.0, 2.0, 3.0, 9.0]


def test_load_csv_trace_sorted_input_unchanged(tmp_path):
    p = tmp_path / "sorted.csv"
    p.write_text("100,1.0\n200,2.0\n300,3.0\n")
    ts, vs = load_csv_trace(str(p))
    assert ts.tolist() == [100, 200, 300]
    assert vs.tolist() == [1.0, 2.0, 3.0]


def test_load_csv_trace_value_only_rows_keep_synthetic_timeline(tmp_path):
    p = tmp_path / "values.csv"
    p.write_text("1.0\n1.0\n2.0\n")  # repeated values must NOT be deduped
    ts, vs = load_csv_trace(str(p), step=60)
    assert ts.tolist() == [0, 60, 120]
    assert vs.tolist() == [1.0, 1.0, 2.0]


def test_replay_source_tolerates_empty_csv(tmp_path):
    p = tmp_path / "empty.csv"
    p.write_text("")
    src = ReplaySource().register_csv("q=latency", str(p))
    ts, vs = src.fetch("http://prom/api?q=latency")
    assert len(ts) == 0 and len(vs) == 0
