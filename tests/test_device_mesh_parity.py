"""Multi-device parity CI (ISSUE 13 acceptance): the device-mesh
sharded warm path is PLACEMENT, not semantics.

A child process re-execs under `XLA_FLAGS=
--xla_force_host_platform_device_count=8` (the parallel/mesh.py:15
mechanism — virtual CPU devices standing in for a v5e-8) and runs the
IDENTICAL seeded mixed fleet through two full workers:

  * sharded  — `BrainWorker(device_mesh=make_mesh(n_data=8))`: the
    univariate columnar fast tick AND the joint from-rows paths
    (bivariate + LSTM hybrid) partition their batch leading axis over
    the 8-device data axis, state-arena ROW SPACE block-sharded over
    the same axis (ISSUE 19 default);
  * replicated — the same mesh with `FOREMAST_ARENA_SHARDED=0`: the
    ISSUE-13 replicated-arena layout (global-index gathers against
    per-device replicas);
  * single   — `BrainWorker(device_mesh=None)`: the plain one-device
    judge.

The fleet is 13 services — deliberately NOT a multiple of 8, so every
dispatch pads — and all three workers run a cold tick (object path), a
spike, and a warm tick (columnar paths). The child pins BYTE-identical
statuses, anomaly payloads, hook bands, and fit-cache key sets (pad fit
keys excluded — the sharded arena's per-shard pad rows are deliberately
shard-qualified), verifies the in-run partition assert actually ran
(mesh place calls, pad accounting), and checks the per-device
arena-rows partition: every arena leaf block-shards its [capacity]
axis so each device holds exactly capacity/8 rows. The parent only
checks the child's verdict — process isolation keeps the forced device
count away from the rest of the suite's fixed conftest environment.
"""

from __future__ import annotations

import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_CHILD = """
import os
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
os.environ["JAX_PLATFORMS"] = "cpu"
import sys
sys.path.insert(0, {repo!r})

import dataclasses
import json

import jax
import numpy as np

from benchmarks.worker_bench import build_mixed_fleet
from foremast_tpu.config import BrainConfig
from foremast_tpu.jobs.worker import BrainWorker
from foremast_tpu.models.cache import is_pad_fit_key
from foremast_tpu.parallel.mesh import make_mesh

NOW = 1_760_000_000.0
SERVICES = 13  # not a multiple of 8: every sharded dispatch pads
HIST_LEN = 256
CUR_LEN = 30


def spike(source, sid, f):
    for m in range(f):
        url = f"http://prom/cur?q=m{{m}}:app{{sid}}&step=60"
        ct, cv = source.data[url]
        s = cv.copy()
        s[-3:] += 0.6
        source.data[url] = (ct, s)


def run(device_mesh, arena_sharded=True):
    os.environ["FOREMAST_ARENA_SHARDED"] = "1" if arena_sharded else "0"
    bands = []

    def hook(doc, verdicts):
        for v in verdicts:
            bands.append(
                (
                    doc.id,
                    v.alias,
                    int(v.verdict),
                    tuple(v.anomaly_pairs),
                    np.asarray(v.upper, np.float32).tobytes().hex(),
                    np.asarray(v.lower, np.float32).tobytes().hex(),
                )
            )

    store, source, _ = build_mixed_fleet(
        SERVICES, HIST_LEN, CUR_LEN, NOW, joint_frac=0.17
    )
    cfg = BrainConfig(
        algorithm="auto", season_steps=24, max_cache_size=4 * SERVICES + 64
    )
    cfg = dataclasses.replace(
        cfg, anomaly=dataclasses.replace(cfg.anomaly, threshold=4.0)
    )
    w = BrainWorker(
        store, source, config=cfg, claim_limit=2 * SERVICES,
        worker_id="w", on_verdict=hook, device_mesh=device_mesh,
    )
    w.judge.lstm_steps = 10  # CI speed; identical on both workers
    assert w.tick(now=NOW + 150) > 0
    # find a joint service id to spike (mixed fleet: joint docs carry
    # multiple aliases) + one univariate
    joint_sid = None
    for d in store._docs.values():
        n = d.current_config.count("==")
        if n >= 2 and joint_sid is None:
            joint_sid = (d.app_name.replace("app", ""), n)
    spike(source, joint_sid[0], joint_sid[1])
    uurl = next(
        u for u in source.data if "cur" in u and ":app0&" in u
    )
    ct, cv = source.data[uurl]
    s = cv.copy()
    s[-3:] = 40.0
    source.data[uurl] = (ct, s)
    assert w.tick(now=NOW + 210) > 0
    statuses = {{
        d.id: (d.status, json.dumps(d.anomaly_info, sort_keys=True))
        for d in store._docs.values()
    }}
    # pad fit keys excluded: the sharded arena pins one pad row PER
    # SHARD (shard-qualified "__pad__" keys) where the replicated/
    # single judges pin one — placement bookkeeping, never persisted
    # (is_pad_fit_key gates the journal) and never doc-visible
    fit_keys = sorted(
        repr(k) for k in w._fit_cache._d if not is_pad_fit_key(k)
    )
    joint_keys = sorted(
        repr(k) for k in w.judge.cache._d if not is_pad_fit_key(k)
    )
    return statuses, sorted(bands), fit_keys, joint_keys, w


sharded_mesh = make_mesh(n_data=8)
s_stat, s_bands, s_fit, s_joint, sw = run(sharded_mesh)
r_stat, r_bands, r_fit, r_joint, rw = run(sharded_mesh, arena_sharded=False)
p_stat, p_bands, p_fit, p_joint, pw = run(None)

# the sharded worker genuinely placed + partitioned (the in-run assert
# inside ShardedJudge._place/_place_cols raised already if any dispatch
# was not B_padded/8 rows per device); the 13-doc fleet forced padding
dm = sw._device_mesh_state()
assert dm is not None and dm["devices"] == 8, dm
assert dm["place_calls"] > 0, dm
assert dm["pad_rows_total"] > 0, dm
assert sw._fast_kinds["univariate"] > 0, sw._fast_kinds
assert sw._fast_kinds["bivariate"] + sw._fast_kinds["lstm"] > 0, (
    sw._fast_kinds
)
assert pw._device_mesh_state() is None

# ISSUE 19: the default mesh judge runs SHARDED arenas, the
# FOREMAST_ARENA_SHARDED=0 arm replicated — and the varz says which
assert dm["arena_layout"] == "sharded", dm
assert dm["arena_capacity_rows"] > 0, dm
assert rw._device_mesh_state()["arena_layout"] == "replicated"

# per-device arena-rows partition: every arena leaf block-shards its
# [capacity] axis over the 8 data-axis devices — each device holds
# exactly capacity/8 rows (a replicated leaf would hold all of them)
arenas = list(sw._uni._arenas.values()) + (
    list(sw._mvj._joint_arenas.values()) if sw._mvj is not None else []
)
assert arenas, "no arenas built on the sharded worker"
for a in arenas:
    assert a.shards == 8, a.shards
    assert a.cap == 8 * a.cap_s, (a.cap, a.cap_s)
    for leaf in jax.tree.leaves(a.state):
        shard_rows = sorted(
            s.data.shape[0] for s in leaf.addressable_shards
        )
        assert shard_rows == [a.cap_s] * 8, (leaf.shape, shard_rows)
rep = list(rw._uni._arenas.values())[0]
assert rep.shards == 1, "replicated arm must keep the plain layout"
for leaf in jax.tree.leaves(rep.state):
    assert all(
        s.data.shape[0] == rep.cap for s in leaf.addressable_shards
    ), "replicated arm leaf is not a full replica per device"

# byte parity: statuses, anomaly payloads, hook verdicts + bands,
# fit-cache key sets — univariate columnar AND joint from-rows paths,
# sharded-arena vs replicated-arena vs single-device
for nm, (o_stat, o_bands, o_fit, o_joint) in {{
    "replicated": (r_stat, r_bands, r_fit, r_joint),
    "single": (p_stat, p_bands, p_fit, p_joint),
}}.items():
    assert s_stat == o_stat, (
        nm,
        {{
            k: (s_stat[k], o_stat[k])
            for k in s_stat
            if s_stat[k] != o_stat[k]
        }},
    )
    assert s_bands == o_bands, nm + ": hook verdict/band mismatch"
    assert s_fit == o_fit, nm + ": univariate fit-cache key drift"
    assert s_joint == o_joint, nm + ": joint fit-cache key drift"
assert any(st == "completed_unhealth" for st, _ in s_stat.values()), s_stat
print("PARITY OK", len(s_stat), "docs,", dm["pad_rows_total"], "pad rows")
"""


def test_sharded_vs_single_device_byte_parity():
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env.pop("FOREMAST_DEVICE_MESH", None)
    out = subprocess.run(
        [sys.executable, "-c", _CHILD.format(repo=REPO)],
        cwd=REPO,
        env=env,
        capture_output=True,
        text=True,
        timeout=420,
    )
    assert out.returncode == 0, f"child failed:\n{out.stdout}\n{out.stderr}"
    assert "PARITY OK" in out.stdout, out.stdout


_CANARY_CHILD = """
import os
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
os.environ["JAX_PLATFORMS"] = "cpu"
import sys
sys.path.insert(0, {repo!r})

import json

import numpy as np

from benchmarks.worker_bench import build_mixed_fleet
from foremast_tpu.config import BrainConfig
from foremast_tpu.jobs.worker import BrainWorker
from foremast_tpu.parallel.mesh import make_mesh

NOW = 1_760_000_000.0
SERVICES = 13  # not a multiple of 8: every sharded dispatch pads
HIST_LEN = 256
CUR_LEN = 30


def run(device_mesh):
    bands = []

    def hook(doc, verdicts):
        for v in verdicts:
            bands.append(
                (
                    doc.id,
                    v.alias,
                    int(v.verdict),
                    tuple(v.anomaly_pairs),
                    round(float(v.p_value), 7),
                    bool(v.dist_differs),
                    np.asarray(v.upper, np.float32).tobytes().hex(),
                    np.asarray(v.lower, np.float32).tobytes().hex(),
                )
            )

    # canary-heavy fleet (ISSUE 14): over half the docs carry baseline
    # windows, so the warm tick runs the PAIRWISE-ACTIVE columnar
    # program — the variant this test pins across the mesh
    store, source, _ = build_mixed_fleet(
        SERVICES, HIST_LEN, CUR_LEN, NOW, baseline_frac=0.6
    )
    cfg = BrainConfig(
        algorithm="moving_average_all",
        season_steps=24,
        max_cache_size=4 * SERVICES + 64,
    )
    w = BrainWorker(
        store, source, config=cfg, claim_limit=2 * SERVICES,
        worker_id="w", on_verdict=hook, device_mesh=device_mesh,
    )
    assert w.tick(now=NOW + 150) > 0
    # spike one canary doc's current AND shift another canary doc's
    # baseline distribution (differs=True lowers the threshold
    # in-program — the pairwise outputs must survive the mesh bitwise)
    url = next(
        u for u in source.data
        if u.startswith("http://prom/cur") and "latency:app1&" in u
    )
    ct, cv = source.data[url]
    s = cv.copy()
    s[-3:] = 40.0
    source.data[url] = (ct, s)
    burl = next(
        u for u in source.data
        if u.startswith("http://prom/base") and "latency:app0&" in u
    )
    bt, bv = source.data[burl]
    source.data[burl] = (bt, (bv + 0.5).astype(np.float32))
    assert w.tick(now=NOW + 210) > 0
    statuses = {{
        d.id: (d.status, json.dumps(d.anomaly_info, sort_keys=True))
        for d in store._docs.values()
    }}
    return statuses, sorted(bands), w


s_stat, s_bands, sw = run(make_mesh(n_data=8))
p_stat, p_bands, pw = run(None)

dm = sw._device_mesh_state()
assert dm is not None and dm["devices"] == 8, dm
assert dm["place_calls"] > 0, dm
assert dm["pad_rows_total"] > 0, dm  # 13-doc fleet forces pad rows
assert dm["arena_layout"] == "sharded", dm  # ISSUE 19 default
assert sw._fast_kinds["baseline"] > 0, sw._fast_kinds
assert pw._device_mesh_state() is None

assert s_stat == p_stat, (
    {{k: (s_stat[k], p_stat[k]) for k in s_stat if s_stat[k] != p_stat[k]}}
)
assert any(st == "completed_unhealth" for st, _ in s_stat.values()), s_stat
assert s_bands == p_bands, "hook verdict/band/pairwise mismatch"
# the shifted-baseline doc's REAL pairwise rejection survived sharding
assert any(b[0] == "job-0" and b[5] for b in s_bands), s_bands
print("CANARY PARITY OK", len(s_stat), "docs,", dm["pad_rows_total"], "pad rows")
"""


def test_sharded_vs_single_device_canary_byte_parity():
    """ISSUE 14 satellite: the pairwise-active columnar program (canary
    bucket — baseline buffers ride the same mesh placement) is byte-
    identical sharded vs single-device, pad accounting included."""
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env.pop("FOREMAST_DEVICE_MESH", None)
    out = subprocess.run(
        [sys.executable, "-c", _CANARY_CHILD.format(repo=REPO)],
        cwd=REPO,
        env=env,
        capture_output=True,
        text=True,
        timeout=420,
    )
    assert out.returncode == 0, f"child failed:\n{out.stdout}\n{out.stderr}"
    assert "CANARY PARITY OK" in out.stdout, out.stdout
