"""Observability tests: gauges, worker hook, logs, profiler no-op."""

import json
import logging

import numpy as np
from prometheus_client import CollectorRegistry, generate_latest

from foremast_tpu.config import BrainConfig
from foremast_tpu.jobs import BrainWorker, Document, InMemoryStore
from foremast_tpu.metrics import ReplaySource
from foremast_tpu.observe import (
    BrainGauges,
    JsonFormatter,
    make_verdict_hook,
    setup_logging,
    trace_scoring,
)


def test_gauges_publish_triplet():
    reg = CollectorRegistry()
    g = BrainGauges(registry=reg)
    g.publish("error5xx", "ns1", "demo", upper=1.5, lower=0.0, anomaly_value=40.1)
    text = generate_latest(reg).decode()
    assert 'foremastbrain_error5xx_upper{app="demo",exported_namespace="ns1"} 1.5' in text
    assert "foremastbrain_error5xx_lower" in text
    assert 'foremastbrain_error5xx_anomaly{app="demo",exported_namespace="ns1"} 40.1' in text


def test_worker_publishes_gauges(demo_traces):
    nt, nv = demo_traces["normal"]
    st, sv = demo_traces["spike"]
    hist = np.tile(nv, 6).astype(np.float32)
    ht = 1700000000 + 60 * np.arange(len(hist), dtype=np.int64)
    src = ReplaySource()
    src.register("hist", (ht, hist))
    src.register("cur", (st, sv))
    store = InMemoryStore()
    store.create(
        Document(
            id="g1",
            app_name="demo",
            current_config=(
                "error4xx== http://x/cur?query=namespace_pod"
                "%3Ahttp_server_requests_error_4xx%7Bnamespace%3D%22ns%22%7D"
            ),
            historical_config=(
                "error4xx== http://x/hist?query=namespace_app_per_pod"
                "%3Ahttp_server_requests_error_4xx%7Bnamespace%3D%22ns%22%7D"
            ),
        )
    )
    reg = CollectorRegistry()
    gauges = BrainGauges(registry=reg)
    worker = BrainWorker(
        store, src, BrainConfig(), on_verdict=make_verdict_hook(gauges, "ns")
    )
    worker.tick(now=1e12)
    text = generate_latest(reg).decode()
    # gauge named after the HISTORICAL query's base series (the reference
    # browser contract, metrics.js:15-23), not the job's short alias
    g = "foremastbrain_namespace_app_per_pod_http_server_requests_error_4xx"
    assert f"{g}_upper" in text
    assert 'app="demo"' in text
    assert f"{g}_anomaly" in text  # spike published


def test_verdict_hook_derives_namespace_from_query():
    """exported_namespace comes from the job's PromQL selector so gauges
    land next to the base series they model (UI joins on it)."""
    reg = CollectorRegistry()
    gauges = BrainGauges(registry=reg)
    hook = make_verdict_hook(gauges, "fallback-ns")

    class V:
        alias = "latency"
        upper = [1.0]
        lower = [0.5]
        anomaly_pairs = []

    doc = Document(
        id="n1",
        app_name="shop",
        current_config=(
            "latency== http://prom/api/v1/query_range?query=namespace_pod"
            "%3Alatency%7Bnamespace%3D%22prod%22%2Cpod%3D~%22a%7Cb%22%7D"
        ),
    )
    hook(doc, [V()])
    text = generate_latest(reg).decode()
    assert 'exported_namespace="prod"' in text

    # no namespace selector in the query -> static fallback
    doc2 = Document(id="n2", app_name="shop", current_config="latency== http://x/q")
    hook(doc2, [V()])
    text = generate_latest(reg).decode()
    assert 'exported_namespace="fallback-ns"' in text


def test_json_logging(capsys):
    import io

    buf = io.StringIO()
    setup_logging(stream=buf)
    log = logging.getLogger("foremast_tpu.test")
    log.info("hello")
    rec = json.loads(buf.getvalue().strip())
    assert rec["msg"] == "hello" and rec["level"] == "info"


def test_trace_scoring_noop(monkeypatch):
    monkeypatch.delenv("FOREMAST_PROFILE", raising=False)
    with trace_scoring():
        pass  # must not start a trace or raise


def test_worker_metrics_counters(demo_traces):
    from foremast_tpu.observe.gauges import WorkerMetrics

    nt, nv = demo_traces["normal"]
    st, sv = demo_traces["spike"]
    hist = np.tile(nv, 6).astype(np.float32)
    ht = 1700000000 + 60 * np.arange(len(hist), dtype=np.int64)
    src = ReplaySource()
    src.register("hist", (ht, hist))
    src.register("cur", (st, sv))
    store = InMemoryStore()
    store.create(
        Document(
            id="wm1",
            app_name="demo",
            current_config=(
                "error4xx== http://x/cur?query=namespace_pod"
                "%3Ahttp_server_requests_error_4xx%7Bnamespace%3D%22ns%22%7D"
            ),
            historical_config=(
                "error4xx== http://x/hist?query=namespace_app_per_pod"
                "%3Ahttp_server_requests_error_4xx%7Bnamespace%3D%22ns%22%7D"
            ),
        )
    )
    reg = CollectorRegistry()
    metrics = WorkerMetrics(registry=reg)
    BrainWorker(store, src, BrainConfig(), metrics=metrics).tick(now=1e12)
    text = generate_latest(reg).decode()
    assert 'foremast_worker_jobs_total{status="completed_unhealth"} 1.0' in text
    assert "foremast_worker_windows_total 1.0" in text
    assert "foremast_worker_tick_seconds_count 1.0" in text


def test_series_names_rejects_wrapped_expressions():
    """Gauge naming falls back to the alias for non-bare-selector queries:
    `sum(rate(...))` must not name a gauge "sum" (two such aliases would
    collide into one family and overwrite each other)."""
    from foremast_tpu.observe.gauges import _series_names

    cfg = (
        "a== http://x?query=sum%28rate%28m1%5B5m%5D%29%29"
        " ||b== http://x?query=namespace_app_per_pod%3Alat%7Bapp%3D%22s%22%7D"
        " ||c== http://x?query=bare_series&start=1&end=2"
    )
    names = _series_names(cfg)
    assert "a" not in names  # wrapped expression: alias fallback
    assert names["b"] == "namespace_app_per_pod:lat"
    assert names["c"] == "bare_series"


def test_series_names_requires_query_param_boundary():
    """`subquery=foo` (or any param merely ending in "query") must not
    derive a gauge name (ADVICE r2): the match anchors to a real `query=`
    parameter at the URL's query-string boundary."""
    from foremast_tpu.observe.gauges import _series_names

    cfg = (
        "a== http://x?subquery=not_a_series&other=1"
        " ||b== http://x?start=1&query=real_series&end=2"
    )
    names = _series_names(cfg)
    assert "a" not in names  # no bare `query=`: alias fallback
    assert names["b"] == "real_series"


def test_series_names_drops_same_series_collisions():
    """Two aliases of one job resolving to the SAME base series must not
    share a gauge family (last verdict would silently win — ADVICE r2):
    both fall back to their alias-named gauges."""
    from foremast_tpu.observe.gauges import _series_names

    cfg = (
        "p50== http://x?query=latency_series%7Bq%3D%220.5%22%7D"
        " ||p99== http://x?query=latency_series%7Bq%3D%220.99%22%7D"
        " ||ok== http://x?query=other_series"
    )
    names = _series_names(cfg)
    assert "p50" not in names and "p99" not in names
    assert names["ok"] == "other_series"
