"""Observability tests: gauges, worker hook, logs, profiler no-op."""

import json
import logging
import time

import numpy as np
import pytest
from prometheus_client import CollectorRegistry, generate_latest

from foremast_tpu.config import BrainConfig
from foremast_tpu.jobs import BrainWorker, Document, InMemoryStore
from foremast_tpu.metrics import ReplaySource
from foremast_tpu.observe import (
    BrainGauges,
    JsonFormatter,
    make_verdict_hook,
    setup_logging,
    trace_scoring,
)


def test_gauges_publish_triplet():
    reg = CollectorRegistry()
    g = BrainGauges(registry=reg)
    g.publish("error5xx", "ns1", "demo", upper=1.5, lower=0.0, anomaly_value=40.1)
    text = generate_latest(reg).decode()
    assert 'foremastbrain_error5xx_upper{app="demo",exported_namespace="ns1"} 1.5' in text
    assert "foremastbrain_error5xx_lower" in text
    assert 'foremastbrain_error5xx_anomaly{app="demo",exported_namespace="ns1"} 40.1' in text


def test_worker_publishes_gauges(demo_traces):
    nt, nv = demo_traces["normal"]
    st, sv = demo_traces["spike"]
    hist = np.tile(nv, 6).astype(np.float32)
    ht = 1700000000 + 60 * np.arange(len(hist), dtype=np.int64)
    src = ReplaySource()
    src.register("hist", (ht, hist))
    src.register("cur", (st, sv))
    store = InMemoryStore()
    store.create(
        Document(
            id="g1",
            app_name="demo",
            current_config=(
                "error4xx== http://x/cur?query=namespace_pod"
                "%3Ahttp_server_requests_error_4xx%7Bnamespace%3D%22ns%22%7D"
            ),
            historical_config=(
                "error4xx== http://x/hist?query=namespace_app_per_pod"
                "%3Ahttp_server_requests_error_4xx%7Bnamespace%3D%22ns%22%7D"
            ),
        )
    )
    reg = CollectorRegistry()
    gauges = BrainGauges(registry=reg)
    worker = BrainWorker(
        store, src, BrainConfig(), on_verdict=make_verdict_hook(gauges, "ns")
    )
    worker.tick(now=1e12)
    text = generate_latest(reg).decode()
    # gauge named after the HISTORICAL query's base series (the reference
    # browser contract, metrics.js:15-23), not the job's short alias
    g = "foremastbrain_namespace_app_per_pod_http_server_requests_error_4xx"
    assert f"{g}_upper" in text
    assert 'app="demo"' in text
    assert f"{g}_anomaly" in text  # spike published


def test_verdict_hook_derives_namespace_from_query():
    """exported_namespace comes from the job's PromQL selector so gauges
    land next to the base series they model (UI joins on it)."""
    reg = CollectorRegistry()
    gauges = BrainGauges(registry=reg)
    hook = make_verdict_hook(gauges, "fallback-ns")

    class V:
        alias = "latency"
        upper = [1.0]
        lower = [0.5]
        anomaly_pairs = []

    doc = Document(
        id="n1",
        app_name="shop",
        current_config=(
            "latency== http://prom/api/v1/query_range?query=namespace_pod"
            "%3Alatency%7Bnamespace%3D%22prod%22%2Cpod%3D~%22a%7Cb%22%7D"
        ),
    )
    hook(doc, [V()])
    text = generate_latest(reg).decode()
    assert 'exported_namespace="prod"' in text

    # no namespace selector in the query -> static fallback
    doc2 = Document(id="n2", app_name="shop", current_config="latency== http://x/q")
    hook(doc2, [V()])
    text = generate_latest(reg).decode()
    assert 'exported_namespace="fallback-ns"' in text


def test_json_logging(capsys):
    import io

    buf = io.StringIO()
    setup_logging(stream=buf)
    log = logging.getLogger("foremast_tpu.test")
    log.info("hello")
    rec = json.loads(buf.getvalue().strip())
    assert rec["msg"] == "hello" and rec["level"] == "info"


def test_trace_scoring_noop(monkeypatch):
    monkeypatch.delenv("FOREMAST_PROFILE", raising=False)
    with trace_scoring():
        pass  # must not start a trace or raise


def test_worker_metrics_counters(demo_traces):
    from foremast_tpu.observe.gauges import WorkerMetrics

    nt, nv = demo_traces["normal"]
    st, sv = demo_traces["spike"]
    hist = np.tile(nv, 6).astype(np.float32)
    ht = 1700000000 + 60 * np.arange(len(hist), dtype=np.int64)
    src = ReplaySource()
    src.register("hist", (ht, hist))
    src.register("cur", (st, sv))
    store = InMemoryStore()
    store.create(
        Document(
            id="wm1",
            app_name="demo",
            current_config=(
                "error4xx== http://x/cur?query=namespace_pod"
                "%3Ahttp_server_requests_error_4xx%7Bnamespace%3D%22ns%22%7D"
            ),
            historical_config=(
                "error4xx== http://x/hist?query=namespace_app_per_pod"
                "%3Ahttp_server_requests_error_4xx%7Bnamespace%3D%22ns%22%7D"
            ),
        )
    )
    reg = CollectorRegistry()
    metrics = WorkerMetrics(registry=reg)
    BrainWorker(store, src, BrainConfig(), metrics=metrics).tick(now=1e12)
    text = generate_latest(reg).decode()
    assert 'foremast_worker_jobs_total{status="completed_unhealth"} 1.0' in text
    assert "foremast_worker_windows_total 1.0" in text
    assert "foremast_worker_tick_seconds_count 1.0" in text


def test_series_names_rejects_wrapped_expressions():
    """Gauge naming falls back to the alias for non-bare-selector queries:
    `sum(rate(...))` must not name a gauge "sum" (two such aliases would
    collide into one family and overwrite each other)."""
    from foremast_tpu.observe.gauges import _series_names

    cfg = (
        "a== http://x?query=sum%28rate%28m1%5B5m%5D%29%29"
        " ||b== http://x?query=namespace_app_per_pod%3Alat%7Bapp%3D%22s%22%7D"
        " ||c== http://x?query=bare_series&start=1&end=2"
    )
    names = _series_names(cfg)
    assert "a" not in names  # wrapped expression: alias fallback
    assert names["b"] == "namespace_app_per_pod:lat"
    assert names["c"] == "bare_series"


def test_series_names_requires_query_param_boundary():
    """`subquery=foo` (or any param merely ending in "query") must not
    derive a gauge name (ADVICE r2): the match anchors to a real `query=`
    parameter at the URL's query-string boundary."""
    from foremast_tpu.observe.gauges import _series_names

    cfg = (
        "a== http://x?subquery=not_a_series&other=1"
        " ||b== http://x?start=1&query=real_series&end=2"
    )
    names = _series_names(cfg)
    assert "a" not in names  # no bare `query=`: alias fallback
    assert names["b"] == "real_series"


def test_series_names_drops_same_series_collisions():
    """Two aliases of one job resolving to the SAME base series must not
    share a gauge family (last verdict would silently win — ADVICE r2):
    both fall back to their alias-named gauges."""
    from foremast_tpu.observe.gauges import _series_names

    cfg = (
        "p50== http://x?query=latency_series%7Bq%3D%220.5%22%7D"
        " ||p99== http://x?query=latency_series%7Bq%3D%220.99%22%7D"
        " ||ok== http://x?query=other_series"
    )
    names = _series_names(cfg)
    assert "p50" not in names and "p99" not in names
    assert names["ok"] == "other_series"


# ---------------------------------------------------------------------------
# span pipeline (observe/spans.py)
# ---------------------------------------------------------------------------


def _tracer(tmp_dir=None):
    from foremast_tpu.observe.spans import Tracer

    return Tracer(
        service="test",
        registry=CollectorRegistry(),
        trace_dir=str(tmp_dir) if tmp_dir is not None else None,
    )


def test_span_nesting_and_ambient_parenting():
    """Nested spans parent to the innermost open span and share its trace
    ID — including via the module-level ambient helper, which is how the
    engine/store instrument without a tracer reference."""
    from foremast_tpu.observe.spans import current_span, span

    tracer = _tracer()
    with tracer.span("root") as root:
        assert current_span() is root
        assert root.parent_id == ""
        with span("child", stage="fit") as child:
            assert child.trace_id == root.trace_id
            assert child.parent_id == root.span_id
            with span("grandchild") as g:
                assert g.trace_id == root.trace_id
                assert g.parent_id == child.span_id
        assert current_span() is root
    assert current_span() is None
    # stage spans feed the last-tick breakdown
    assert "fit" in tracer.last_stage_seconds
    # explicit trace_id adoption starts a fresh root under that ID
    with tracer.span("adopted", trace_id="req0000cafe") as s:
        assert s.trace_id == "req0000cafe" and s.parent_id == ""
    # separate roots mint separate trace IDs
    with tracer.span("other") as s2:
        pass
    assert s2.trace_id != root.trace_id
    # ...and each new root restarts the breakdown — /debug/state must
    # describe the latest tick only, never a mix of ticks
    assert "fit" not in tracer.last_stage_seconds
    # ambient helper with no open span: structured no-op
    with span("orphan") as none_span:
        assert none_span is None


def test_stage_breakdown_accumulates_repeated_stages():
    """A tick opens several spans per stage (chunked fetch/write-back,
    per-bucket score); the /debug/state breakdown must attribute the SUM
    of a stage's time, not just the final chunk's."""
    from foremast_tpu.observe.spans import span

    tracer = _tracer()
    with tracer.span("tick"):
        durations = []
        for _ in range(3):
            with span("chunk", stage="metric_fetch") as s:
                time.sleep(0.002)
            durations.append(s.duration)
    assert tracer.last_stage_seconds["metric_fetch"] == pytest.approx(
        sum(durations)
    )


def test_inherit_span_propagates_to_executor_threads():
    """Fetch-pool threads must see the tick's ambient span so their log
    records keep its trace_id (executor threads start context-empty)."""
    from concurrent.futures import ThreadPoolExecutor

    from foremast_tpu.observe.spans import current_span, inherit_span

    tracer = _tracer()

    def probe(_):
        sp = current_span()
        return sp.trace_id if sp is not None else None

    with tracer.span("tick") as root:
        with ThreadPoolExecutor(max_workers=4) as pool:
            ids = list(pool.map(inherit_span(probe), range(8)))
        assert ids == [root.trace_id] * 8
        # the submitting thread's context is untouched
        assert current_span() is root
    # without the wrapper the pool thread sees no span
    with tracer.span("tick2"):
        with ThreadPoolExecutor(max_workers=1) as pool:
            assert list(pool.map(probe, range(1))) == [None]


def test_span_ring_thread_safety():
    """Concurrent adds never lose the total count and never grow the
    buffer past capacity (newest spans win)."""
    import threading

    from foremast_tpu.observe.spans import SpanRing

    ring = SpanRing(capacity=128)

    def add_many(k):
        for i in range(500):
            ring.add({"name": f"t{k}-{i}"})

    threads = [
        threading.Thread(target=add_many, args=(k,)) for k in range(8)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert ring.total == 8 * 500
    assert len(ring) == 128
    snap = ring.snapshot()
    assert len(snap) == 128 and all(isinstance(e, dict) for e in snap)


def test_perfetto_dump_schema(tmp_path):
    """The JSONL dump is newline-delimited Chrome trace events —
    complete ("X") events with microsecond ts/dur and numeric pid/tid,
    the exact shape Perfetto's JSON importer accepts."""
    tracer = _tracer(tmp_path)
    with tracer.span("root"):
        with tracer.span("inner", stage="score", rows=4):
            pass
    path = tracer.flush()
    events = [json.loads(line) for line in open(path)]
    assert len(events) == 2
    for e in events:
        assert e["ph"] == "X" and e["cat"] == "foremast"
        assert isinstance(e["ts"], (int, float))
        assert isinstance(e["dur"], (int, float)) and e["dur"] >= 0
        assert isinstance(e["pid"], int) and isinstance(e["tid"], int)
        assert e["name"] and e["args"]["trace_id"] and e["args"]["span_id"]
    inner = next(e for e in events if e["name"] == "inner")
    assert inner["args"]["stage"] == "score" and inner["args"]["rows"] == 4


def test_json_formatter_exc_info_and_trace_correlation():
    """ctx_log/JsonFormatter records carry the active trace/span IDs and
    the full traceback on the exc_info path (ISSUE 1 satellite)."""
    import io

    buf = io.StringIO()
    setup_logging(stream=buf)
    log = logging.getLogger("foremast_tpu.test.exc")
    tracer = _tracer()
    with tracer.span("op") as sp:
        try:
            raise ValueError("boom")
        except ValueError:
            log.exception("failed")
    log.info("outside")
    exc_rec, out_rec = [
        json.loads(line) for line in buf.getvalue().splitlines()
    ]
    assert exc_rec["level"] == "error" and exc_rec["msg"] == "failed"
    assert "ValueError: boom" in exc_rec["exc"]
    assert "Traceback" in exc_rec["exc"]
    assert exc_rec["trace_id"] == sp.trace_id
    assert exc_rec["span_id"] == sp.span_id
    # outside any span the keys are absent, not empty
    assert "trace_id" not in out_rec and "span_id" not in out_rec


def test_gauge_family_cap_enforced():
    """BrainGauges really bounds its family set now (ISSUE 1 satellite):
    past the cap new metric names are dropped and counted while existing
    families keep updating."""
    reg = CollectorRegistry()
    g = BrainGauges(registry=reg, max_families=2)
    for m in ["m_a", "m_b", "m_c", "m_d"]:
        g.publish(m, "ns", "app", upper=1.0, lower=0.0)
    text = generate_latest(reg).decode()
    assert "foremastbrain_m_a_upper" in text
    assert "foremastbrain_m_b_upper" in text
    assert "foremastbrain_m_c_upper" not in text
    assert "foremastbrain_m_d_upper" not in text
    assert "foremastbrain_gauge_families_dropped_total 2.0" in text
    # the counter counts distinct FAMILIES, not publishes: republishing
    # a dropped name every tick must not inflate it
    g.publish("m_c", "ns", "app", upper=1.0, lower=0.0)
    text = generate_latest(reg).decode()
    assert "foremastbrain_gauge_families_dropped_total 2.0" in text
    # families created before the cap keep updating normally
    g.publish("m_a", "ns", "app", upper=9.0, lower=0.5)
    text = generate_latest(reg).decode()
    assert (
        'foremastbrain_m_a_upper{app="app",exported_namespace="ns"} 9.0'
        in text
    )
    # a second BrainGauges on the same registry shares the dropped
    # counter instead of exploding on duplicate registration
    g2 = BrainGauges(registry=reg, max_families=2)
    assert g2.dropped is g.dropped


def test_metrics_lint_default_registry_clean():
    """Tier-1 dashboard contract: every family the deployed
    worker+service+controller exports conforms to the naming convention
    and documented label sets (ISSUE 1 satellite)."""
    from foremast_tpu.observe.metrics_lint import (
        default_registry_families,
        lint_registry,
    )

    assert lint_registry(default_registry_families()) == []


def test_metrics_lint_flags_violations():
    from prometheus_client import Counter, Gauge

    from foremast_tpu.observe.metrics_lint import lint_registry

    reg = CollectorRegistry()
    Gauge("acme_rogue_metric", "wrong prefix", registry=reg)
    Counter(
        "foremast_worker_jobs", "undocumented extra label",
        ["status", "shard"], registry=reg,
    ).labels(status="done", shard="0").inc()
    problems = lint_registry(reg)
    assert any("acme_rogue_metric" in p for p in problems)
    assert any("shard" in p for p in problems)


def _demo_store_and_source(demo_traces, job_id="e2e"):
    nt, nv = demo_traces["normal"]
    st, sv = demo_traces["spike"]
    hist = np.tile(nv, 6).astype(np.float32)
    ht = 1700000000 + 60 * np.arange(len(hist), dtype=np.int64)
    src = ReplaySource()
    src.register("hist", (ht, hist))
    src.register("cur", (st, sv))
    store = InMemoryStore()
    store.create(
        Document(
            id=job_id,
            app_name="demo",
            # the correlation ID the service would have minted at create
            trace_id="svc00000cafe",
            current_config=(
                "error4xx== http://x/cur?query=namespace_pod"
                "%3Ahttp_server_requests_error_4xx%7Bnamespace%3D%22ns%22%7D"
            ),
            historical_config=(
                "error4xx== http://x/hist?query=namespace_app_per_pod"
                "%3Ahttp_server_requests_error_4xx%7Bnamespace%3D%22ns%22%7D"
            ),
        )
    )
    return store, src


def test_e2e_judgment_trace_pipeline(demo_traces, tmp_path):
    """ISSUE 1 acceptance: one demo judgment produces (1) stage
    histograms for >= 5 distinct stage labels, (2) a Perfetto-loadable
    JSONL dump whose spans share one trace ID, (3) JSON log lines
    carrying that same trace ID — then the controller leg (HttpKube over
    tests/fake_kube_server.py) lands its poll/transition/pause spans and
    transition counter in the same registry."""
    import io

    from prometheus_client import CollectorRegistry

    from foremast_tpu.observe.spans import Tracer
    from foremast_tpu.watch.analyst import LocalAnalyst
    from foremast_tpu.watch.controller import MonitorController
    from foremast_tpu.watch.crds import (
        DeploymentMonitor,
        MonitorPhase,
        MonitorStatus,
        Remediation,
        RemediationOption,
    )
    from foremast_tpu.watch.kubeapi import HttpKube
    from tests.fake_kube_server import FakeKubeServer

    store, src = _demo_store_and_source(demo_traces)
    buf = io.StringIO()
    setup_logging(stream=buf)
    reg = CollectorRegistry()
    tracer = Tracer(service="worker", registry=reg, trace_dir=str(tmp_path))
    worker = BrainWorker(store, src, BrainConfig(), tracer=tracer)
    worker.tick(now=1e12)

    # (1) stage histograms: >= 5 distinct stage labels on /metrics
    text = generate_latest(reg).decode()
    stages = {
        line.split('stage="')[1].split('"')[0]
        for line in text.splitlines()
        if line.startswith("foremast_tick_stage_seconds_count")
    }
    assert len(stages) >= 5, stages
    assert {"claim", "metric_fetch", "score", "decide"} <= stages

    # (2) Perfetto-loadable JSONL: valid events sharing ONE trace ID
    path = tracer.flush()
    events = [json.loads(line) for line in open(path)]
    assert len(events) >= 5
    trace_ids = {e["args"]["trace_id"] for e in events}
    assert len(trace_ids) == 1
    (tid,) = trace_ids
    by_id = {e["args"]["span_id"]: e for e in events}
    roots = [e for e in events if not e["args"]["parent_id"]]
    assert len(roots) == 1 and roots[0]["name"] == "worker.tick"
    for e in events:
        assert e["ph"] == "X"
        if e["args"]["parent_id"]:
            assert e["args"]["parent_id"] in by_id  # parents are real spans

    # (3) JSON log lines carry the same trace ID
    logs = [json.loads(line) for line in buf.getvalue().splitlines()]
    traced = [rec for rec in logs if "trace_id" in rec]
    assert traced and all(rec["trace_id"] == tid for rec in traced)
    assert any(rec["msg"] == "tick complete" for rec in traced)
    # per-doc judgment line joins the tick trace to the REQUEST trace
    # the service stamped on the document
    judged = [rec for rec in traced if rec["msg"] == "judgment"]
    assert len(judged) == 1
    assert judged[0]["job_trace_id"] == "svc00000cafe"
    assert judged[0]["job_id"] == "e2e"

    # worker varz: stage breakdown + cache/arena state for /debug/state
    state = worker.debug_state()
    assert state["last_tick"]["docs"] == 1
    assert state["model_cache"]["fit_entries"] >= 1
    assert state["trace"]["spans_total"] == len(events)
    assert set(state["trace"]["last_stage_seconds"]) == stages

    # controller leg over a real HTTP kube fake: the unhealthy verdict
    # drives poll -> transition -> pause, counted and spanned
    with FakeKubeServer() as srv:
        kube = HttpKube(base_url=srv.url)
        srv.state.put(
            "deployments",
            "demo",
            {"metadata": {"name": "demo"}, "spec": {}},
        )
        kube.upsert_monitor(
            DeploymentMonitor(
                name="demo",
                namespace="demo",
                remediation=Remediation(option=RemediationOption.AUTO_PAUSE),
                status=MonitorStatus(
                    job_id="e2e", phase=MonitorPhase.RUNNING
                ),
            )
        )
        ctl = MonitorController(
            kube,
            analyst_factory=lambda ep: LocalAnalyst(store),
            tracer=tracer,
            registry=reg,
        )
        ctl.tick()
        mon = kube.get_monitor("demo", "demo")
        assert mon.status.phase == MonitorPhase.UNHEALTHY
        assert srv.state.objects["deployments"][("demo", "demo")]["spec"][
            "paused"
        ]
        ctl.tick()  # re-poll of an unchanged phase is NOT a transition
    text = generate_latest(reg).decode()
    assert (
        'foremast_controller_transitions_total{phase="Unhealthy"} 1.0'
        in text
    )
    names = {e["name"] for e in tracer.ring.snapshot()}
    assert {
        "controller.poll",
        "controller.get_status",
        "controller.update",
        "controller.pause",
    } <= names


def test_observe_server_endpoints(demo_traces):
    """The worker scrape port serves /metrics, /healthz and /debug/state
    (the reference exposed /metrics only)."""
    import urllib.error
    import urllib.request

    from prometheus_client import CollectorRegistry

    from foremast_tpu.observe.spans import Tracer, start_observe_server

    store, src = _demo_store_and_source(demo_traces, job_id="varz")
    reg = CollectorRegistry()
    tracer = Tracer(service="worker", registry=reg)
    worker = BrainWorker(store, src, BrainConfig(), tracer=tracer)
    worker.tick(now=1e12)
    srv, _thread = start_observe_server(
        0, registry=reg, state_fn=worker.debug_state, host="127.0.0.1"
    )
    try:
        base = f"http://127.0.0.1:{srv.server_address[1]}"

        def get(path):
            with urllib.request.urlopen(base + path) as r:
                return r.status, r.read().decode()

        code, body = get("/metrics")
        assert code == 200
        assert "foremast_tick_stage_seconds_bucket" in body
        code, body = get("/healthz")
        health = json.loads(body)
        assert code == 200 and health["ok"] and health["version"]
        code, body = get("/debug/state")
        state = json.loads(body)
        assert code == 200
        assert state["queue_depth"] == 0  # the one job completed
        assert state["store_ok"] is True
        assert state["config_fingerprint"]
        assert state["last_tick"]["docs"] == 1
        assert set(state["trace"]["last_stage_seconds"]) >= {
            "claim",
            "score",
            "decide",
        }
        try:
            get("/nope")
            raise AssertionError("expected 404")
        except urllib.error.HTTPError as e:
            assert e.code == 404
    finally:
        srv.shutdown()
        srv.server_close()




def test_controller_counts_only_phase_changes():
    """foremast_controller_transitions_total counts phase CHANGES: a
    poll that re-asserts the current phase must not increment (a rate()
    over the counter would otherwise measure poll frequency)."""
    from prometheus_client import CollectorRegistry

    from foremast_tpu.watch.analyst import JobStatus
    from foremast_tpu.watch.controller import MonitorController
    from foremast_tpu.watch.crds import (
        DeploymentMonitor,
        MonitorPhase,
        MonitorStatus,
    )
    from foremast_tpu.watch.kubeapi import InMemoryKube

    class StubAnalyst:
        phase = MonitorPhase.RUNNING

        def get_status(self, job_id):
            return JobStatus(phase=self.phase)

    stub = StubAnalyst()
    kube = InMemoryKube()
    kube.upsert_monitor(
        DeploymentMonitor(
            name="demo",
            namespace="demo",
            status=MonitorStatus(job_id="j1", phase=MonitorPhase.RUNNING),
        )
    )
    reg = CollectorRegistry()
    ctl = MonitorController(
        kube, analyst_factory=lambda ep: stub, registry=reg
    )
    ctl.tick()
    ctl.tick()  # still Running: re-assertions, not transitions
    text = generate_latest(reg).decode()
    assert 'phase="Running"' not in text
    stub.phase = MonitorPhase.UNHEALTHY
    ctl.tick()
    text = generate_latest(reg).decode()
    assert (
        'foremast_controller_transitions_total{phase="Unhealthy"} 1.0'
        in text
    )
