"""Durable data plane (ISSUE 7): ring snapshot/restore, fit journals,
torn-state degradation, and the metric families that count the damage.

The contract under test: a SIGKILL can land between any two bytes of
the on-disk state, and restore must (a) never crash, (b) serve every
HEALTHY series/fit resident, and (c) count everything it discarded on
`foremast_snapshot_discards{reason}` so the operator can tell a clean
warm restart from a lossy one.
"""

from __future__ import annotations

import os
import pickle
import struct

import numpy as np
import pytest

from foremast_tpu.ingest import RingSnapshotter, RingStore, SnapshotCollector
from foremast_tpu.ingest.snapshot import (
    _LOG_HEADER,
    _LOG_MAGIC,
    append_record,
    read_records,
)
from foremast_tpu.models.cache import FitJournal, ModelCache

NOW = 1_760_000_000.0


@pytest.fixture(scope="module", autouse=True)
def lock_witness():
    """ISSUE 8: the runtime lock witness rides this whole module — the
    snapshot/journal suite exercises the ring's deepest lock nesting
    (shard lock -> journal log lock, pass mutex -> everything) on real
    threads, and at teardown every OBSERVED acquisition edge must
    already exist in the committed static lock graph. A failure here
    means the static model (analysis_lockgraph.json) has a hole: run
    `make lockgraph`, review the new edge, and commit it."""
    from foremast_tpu.analysis import witness

    wit = witness.install()
    yield wit
    graph = witness.load_graph()
    witness.uninstall()
    assert graph is not None, "analysis_lockgraph.json missing from repo root"
    missing = wit.unobserved_edges(graph)
    assert not missing, (
        "runtime lock-acquisition edges missing from the static graph "
        f"(run `make lockgraph` and review): {missing}"
    )


def _store(shards=4, stale=300.0):
    return RingStore(shards=shards, stale_seconds=stale)


def _fill(store, snap, n=10, now=NOW):
    t = np.arange(int(now) - 600, int(now), 60, np.int64)
    for i in range(n):
        store.push(
            f'm{{app="a{i}"}}',
            t,
            np.full(len(t), float(i), np.float32),
            start=float(t[0]),
            now=now,
        )
    return t


# ---------------------------------------------------------------------------
# round trips
# ---------------------------------------------------------------------------


def test_snapshot_restore_round_trip_serves_identical_windows(tmp_path):
    s1 = _store()
    snap1 = RingSnapshotter(s1, str(tmp_path), clock=lambda: NOW)
    snap1.restore()
    snap1.attach()
    t = _fill(s1, snap1)
    snap1.snapshot()
    # pushes AFTER the snapshot land in the fresh log and must replay
    t2 = np.arange(int(NOW), int(NOW) + 180, 60, np.int64)
    s1.push('m{app="a0"}', t2, np.full(len(t2), 42.0, np.float32), now=NOW)
    snap1.close()

    s2 = _store()
    snap2 = RingSnapshotter(s2, str(tmp_path), clock=lambda: NOW + 60)
    stats = snap2.restore()
    assert stats["restored_series"] == 10
    assert not any(stats["discards"].values())
    for i in range(10):
        key = f'm{{app="a{i}"}}'
        want = s1.query(key, float(t[0]), NOW + 180, NOW + 60)
        got = s2.query(key, float(t[0]), NOW + 180, NOW + 60)
        assert got[0] == want[0] == "hit"
        np.testing.assert_array_equal(got[1], want[1])
        np.testing.assert_array_equal(got[2], want[2])


def test_log_only_restore_without_any_snapshot(tmp_path):
    """A worker killed before its first snapshot pass restores from the
    append log alone — journaling starts at attach, not at snapshot."""
    s1 = _store()
    snap1 = RingSnapshotter(s1, str(tmp_path), clock=lambda: NOW)
    snap1.restore()
    snap1.attach()
    t = _fill(s1, snap1, n=4)
    snap1.close()  # no snapshot() ever ran

    s2 = _store()
    snap2 = RingSnapshotter(s2, str(tmp_path), clock=lambda: NOW + 30)
    stats = snap2.restore()
    assert stats["restored_samples"] == 40
    assert s2.query('m{app="a1"}', float(t[0]), float(t[-1]), NOW + 30)[0] == "hit"


def test_restore_replays_rotated_log_after_crash_mid_snapshot(tmp_path):
    """A crash between log rotation and snapshot rename leaves a
    ``.log.old.<N>`` generation behind; restore must replay it (before
    the live log) or the samples pushed since the previous snapshot are
    lost."""
    s1 = _store(shards=1)
    snap1 = RingSnapshotter(s1, str(tmp_path), clock=lambda: NOW)
    snap1.restore()
    snap1.attach()
    t = _fill(s1, snap1, n=3)
    # simulate the crash window: rotate the log the way snapshot() does,
    # then DIE before writing the snapshot file
    rotated = snap1._logs[0].rotate()
    assert rotated and os.path.exists(rotated)
    snap1.close()

    s2 = _store(shards=1)
    snap2 = RingSnapshotter(s2, str(tmp_path), clock=lambda: NOW + 30)
    stats = snap2.restore()
    assert stats["restored_samples"] == 30
    assert s2.query('m{app="a2"}', float(t[0]), float(t[-1]), NOW + 30)[0] == "hit"


def test_repeated_crash_mid_snapshot_never_clobbers_earlier_rotation(tmp_path):
    """Rotations RATCHET: a second crash-mid-snapshot (after a restart
    that replayed but deliberately did not re-journal) must not
    overwrite the first crash's rotated generation — both replay, in
    order, and only a COMPLETED snapshot pass deletes them."""
    from foremast_tpu.ingest.snapshot import rotated_logs

    t = np.arange(int(NOW) - 600, int(NOW), 60, np.int64)
    # run 1: journal one series, crash mid-snapshot (rotate, no snap)
    s1 = _store(shards=1)
    snap1 = RingSnapshotter(s1, str(tmp_path), clock=lambda: NOW)
    snap1.restore()
    snap1.attach()
    s1.push('m{app="first"}', t, np.ones(len(t), np.float32), now=NOW)
    snap1._logs[0].rotate()
    snap1.close()

    # run 2: restores run 1's samples (from .old.0), journals a NEW
    # series, then crashes mid-snapshot again
    s2 = _store(shards=1)
    snap2 = RingSnapshotter(s2, str(tmp_path), clock=lambda: NOW + 30)
    assert snap2.restore()["restored_series"] == 1
    snap2.attach()
    s2.push('m{app="second"}', t, np.ones(len(t), np.float32), now=NOW + 30)
    snap2._logs[0].rotate()
    snap2.close()
    base = os.path.join(str(tmp_path), "ring-0.log")
    assert len(rotated_logs(base)) == 2  # both generations on disk

    # run 3: BOTH series must restore — run 1's samples exist in no
    # snapshot, only in the oldest rotated generation
    s3 = _store(shards=1)
    snap3 = RingSnapshotter(s3, str(tmp_path), clock=lambda: NOW + 60)
    stats = snap3.restore()
    assert stats["restored_series"] == 2
    for app in ("first", "second"):
        q = s3.query(f'm{{app="{app}"}}', float(t[0]), float(t[-1]), NOW + 60)
        assert q[0] == "hit", app
    # a COMPLETED pass finally clears the backlog
    snap3.snapshot()
    assert rotated_logs(base) == []
    snap3.close()


# ---------------------------------------------------------------------------
# torn-state degradation (the ISSUE 7 satellite matrix)
# ---------------------------------------------------------------------------


def _snap_files(tmp_path):
    return sorted(
        str(p) for p in tmp_path.iterdir() if p.name.endswith(".snap.npz")
    )


def test_truncated_snapshot_file_degrades_that_shard_only(tmp_path):
    s1 = _store(shards=2)
    snap1 = RingSnapshotter(s1, str(tmp_path), clock=lambda: NOW)
    snap1.restore()
    snap1.attach()
    _fill(s1, snap1, n=12)
    snap1.snapshot()
    snap1.close()
    # truncate ONE shard's snapshot mid-file (the logs were rotated
    # away by snapshot(), so nothing can paper over the damage)
    files = _snap_files(tmp_path)
    raw = open(files[0], "rb").read()
    with open(files[0], "wb") as fh:
        fh.write(raw[: len(raw) // 2])

    s2 = _store(shards=2)
    snap2 = RingSnapshotter(s2, str(tmp_path), clock=lambda: NOW + 30)
    stats = snap2.restore()
    assert stats["discards"]["unreadable"] == 1
    # the OTHER shard's series all restored; no crash anywhere
    assert 0 < stats["restored_series"] < 12


def test_version_mismatched_snapshot_header_is_discarded(tmp_path):
    s1 = _store(shards=1)
    snap1 = RingSnapshotter(s1, str(tmp_path), clock=lambda: NOW)
    snap1.restore()
    snap1.attach()
    _fill(s1, snap1, n=3)
    snap1.snapshot()
    snap1.close()
    path = _snap_files(tmp_path)[0]
    with np.load(path, allow_pickle=False) as z:
        arrays = {name: z[name] for name in z.files}
    arrays["version"] = np.asarray([999], np.int64)
    np.savez(path.replace(".npz", ""), **arrays)

    s2 = _store(shards=1)
    snap2 = RingSnapshotter(s2, str(tmp_path), clock=lambda: NOW + 30)
    stats = snap2.restore()
    assert stats["discards"]["version"] == 1
    assert stats["restored_series"] == 0  # format unknown: trust nothing


def test_torn_append_log_tail_replays_healthy_prefix(tmp_path):
    s1 = _store(shards=1)
    snap1 = RingSnapshotter(s1, str(tmp_path), clock=lambda: NOW)
    snap1.restore()
    snap1.attach()
    t = _fill(s1, snap1, n=5)
    snap1.close()
    log_path = os.path.join(str(tmp_path), "ring-0.log")
    raw = open(log_path, "rb").read()
    with open(log_path, "wb") as fh:
        fh.write(raw[:-7])  # cut mid-record: the SIGKILL tail

    s2 = _store(shards=1)
    snap2 = RingSnapshotter(s2, str(tmp_path), clock=lambda: NOW + 30)
    stats = snap2.restore()
    assert stats["discards"]["torn_log"] == 1
    assert stats["restored_series"] == 4  # prefix intact, tail cold
    assert s2.query('m{app="a0"}', float(t[0]), float(t[-1]), NOW + 30)[0] == "hit"
    assert s2.query('m{app="a4"}', float(t[0]), float(t[-1]), NOW + 30)[0] == "miss"


def test_mid_record_garbage_does_not_resync_later_frames(tmp_path):
    """A corrupted length field would desync every later frame — the
    reader must stop at the first bad frame, not invent records."""
    path = os.path.join(str(tmp_path), "x.log")
    with open(path, "wb") as fh:
        append_record(fh, b"good-1")
        fh.write(_LOG_HEADER.pack(_LOG_MAGIC, 10_000_000, 0))
        fh.write(b"\x00" * 64)
        append_record(fh, b"good-2-unreachable")
    got = list(read_records(path))
    assert got[0] == (b"good-1", None)
    assert got[1] == (None, "torn_log")
    assert len(got) == 2


def test_snapshot_mid_eviction_broken_series_degrades_per_series(tmp_path):
    """A snapshot carrying one inconsistent series (the mid-eviction /
    external-corruption shape: arrays missing or length-mismatched)
    restores the healthy rest and counts exactly the broken one."""
    s1 = _store(shards=1)
    snap1 = RingSnapshotter(s1, str(tmp_path), clock=lambda: NOW)
    snap1.restore()
    snap1.attach()
    t = _fill(s1, snap1, n=4)
    snap1.snapshot()
    snap1.close()
    path = _snap_files(tmp_path)[0]
    with np.load(path, allow_pickle=False) as z:
        arrays = {name: z[name] for name in z.files}
    # series 1 loses its value column; series 2's columns disagree
    del arrays["v1"]
    arrays["t2"] = arrays["t2"][:-3]
    np.savez(path.replace(".npz", ""), **arrays)

    s2 = _store(shards=1)
    snap2 = RingSnapshotter(s2, str(tmp_path), clock=lambda: NOW + 30)
    stats = snap2.restore()
    assert stats["discards"]["series"] == 2
    assert stats["restored_series"] == 2
    assert s2.query('m{app="a0"}', float(t[0]), float(t[-1]), NOW + 30)[0] == "hit"
    assert s2.query('m{app="a1"}', float(t[0]), float(t[-1]), NOW + 30)[0] == "miss"


def test_log_replay_applies_the_age_cutoff_too(tmp_path):
    """A worker killed before its first snapshot pass restores from the
    log alone — the age cutoff must apply THERE as well, or week-old
    series resurrect through the log and LRU-evict fresh state (the
    exact shadowing the knob's contract forbids)."""
    s1 = _store(shards=1)
    snap1 = RingSnapshotter(s1, str(tmp_path), clock=lambda: NOW)
    snap1.restore()
    snap1.attach()
    old_t = np.arange(int(NOW) - 9 * 86_400, int(NOW) - 9 * 86_400 + 300,
                      60, np.int64)
    s1.push('m{app="ancient"}', old_t, np.ones(len(old_t), np.float32),
            now=NOW, record_lag=False)
    fresh_t = _fill(s1, snap1, n=1)
    snap1.close()  # no snapshot: log-only restore

    s2 = _store(shards=1)
    snap2 = RingSnapshotter(
        s2, str(tmp_path), max_age_seconds=86_400.0, clock=lambda: NOW + 60
    )
    stats = snap2.restore()
    assert stats["restored_series"] == 1
    assert stats["discards"]["stale"] == 1
    assert (
        s2.query('m{app="a0"}', float(fresh_t[0]), float(fresh_t[-1]),
                 NOW + 60)[0]
        == "hit"
    )
    assert s2.query('m{app="ancient"}', None, None, NOW + 60)[0] == "miss"


def test_snapshot_dir_exclusivity_flock(tmp_path):
    """Two LIVE processes must not share one snapshot directory (torn
    interleaved frames, one mesh identity). The advisory flock refuses
    the second holder and releases on close — the restart-after-SIGKILL
    case, where the kernel drops the dead process's lock."""
    from foremast_tpu.ingest import lock_snapshot_dir

    first = lock_snapshot_dir(str(tmp_path))
    assert first is not None
    # flock is per open-file-description: a second open conflicts even
    # in-process, standing in for the concurrent-worker case
    assert lock_snapshot_dir(str(tmp_path)) is None
    first.close()  # the holder died/exited: next worker acquires
    again = lock_snapshot_dir(str(tmp_path))
    assert again is not None
    again.close()


def test_restore_age_cutoff_discards_ancient_series(tmp_path):
    s1 = _store(shards=1)
    snap1 = RingSnapshotter(s1, str(tmp_path), clock=lambda: NOW)
    snap1.restore()
    snap1.attach()
    _fill(s1, snap1, n=2)
    snap1.snapshot()
    snap1.close()

    s2 = _store(shards=1)
    week_later = NOW + 7 * 86_400
    snap2 = RingSnapshotter(
        s2, str(tmp_path), max_age_seconds=86_400.0,
        clock=lambda: week_later,
    )
    stats = snap2.restore()
    assert stats["restored_series"] == 0
    assert stats["discards"]["stale"] == 2


def test_restore_across_a_shard_count_change(tmp_path):
    """Files written under FOREMAST_INGEST_SHARDS=4 must fully restore
    into a 2-shard store (and vice versa): replay re-hashes keys
    through the production push path, so restore walks every shard
    index present ON DISK, not just the current count — retuning
    shards across a restart must never silently drop durable state."""
    s1 = _store(shards=4)
    snap1 = RingSnapshotter(s1, str(tmp_path), clock=lambda: NOW)
    snap1.restore()
    snap1.attach()
    t = _fill(s1, snap1, n=12)
    snap1.snapshot()
    # post-snapshot pushes land in the 4 per-shard logs too
    t2 = np.arange(int(NOW), int(NOW) + 120, 60, np.int64)
    s1.push('m{app="a7"}', t2, np.full(len(t2), 9.0, np.float32), now=NOW)
    snap1.close()

    s2 = _store(shards=2)
    snap2 = RingSnapshotter(s2, str(tmp_path), clock=lambda: NOW + 30)
    stats = snap2.restore()
    assert stats["restored_series"] == 12
    assert not any(stats["discards"].values())
    st, tt, vv = s2.query('m{app="a7"}', float(t[0]), NOW + 120, NOW + 30)
    assert st == "hit" and vv[-1] == 9.0
    snap2.close()


def test_maybe_snapshot_cadence_interval_and_log_budget(tmp_path):
    s1 = _store(shards=1)
    clock = [NOW]
    snap1 = RingSnapshotter(
        s1, str(tmp_path), interval_seconds=60.0, log_max_bytes=200,
        clock=lambda: clock[0],
    )
    snap1.restore()
    snap1.attach()
    assert snap1.maybe_snapshot()  # first call: interval since epoch 0
    assert not snap1.maybe_snapshot()  # fresh, small log: not due
    clock[0] = NOW + 61
    assert snap1.maybe_snapshot()  # interval elapsed
    clock[0] = NOW + 62
    _fill(s1, snap1, n=4)  # blows the 200-byte log budget
    assert snap1.maybe_snapshot()
    assert snap1.counters["snapshots"] == 3
    snap1.close()


# ---------------------------------------------------------------------------
# fit journal + lazy rehydration
# ---------------------------------------------------------------------------


def test_fit_journal_write_through_restore_and_lazy_rehydrate(tmp_path):
    cache = ModelCache(max_size=8)
    j = FitJournal(str(tmp_path / "fit-uni"))
    cache.restore_lazy(j.restore())
    j.attach(cache)
    season = np.arange(5, dtype=np.float32)
    cache.put(("ma", 24, "k1"), (1.0, 0.0, season, 0, 0.1, 100))
    cache.put_many([(("ma", 24, f"k{i}"), (float(i), 0.0, season, 0, 0.1, 100))
                    for i in range(2, 5)])
    cache.pop(("ma", 24, "k2"))  # tombstone must survive restart
    j.close()

    cache2 = ModelCache(max_size=8)
    j2 = FitJournal(str(tmp_path / "fit-uni"))
    items = j2.restore()
    assert set(k[2] for k in items) == {"k1", "k3", "k4"}
    staged = cache2.restore_lazy(items)
    j2.attach(cache2)
    assert staged == 3
    assert len(cache2) == 0  # nothing resident until first lookup
    v0 = cache2.version
    # peek (the worker's admission path) rehydrates lazily + bumps the
    # version so admission tokens revalidate
    entry = cache2.peek(("ma", 24, "k1"))
    assert entry is not None and entry[0] == 1.0
    np.testing.assert_array_equal(entry[2], season)
    assert cache2.version > v0
    assert len(cache2) == 1 and cache2.restored_pending() == 2
    # identity stability: the rehydrated object IS the cached object
    assert cache2.peek(("ma", 24, "k1")) is entry
    assert cache2.get(("ma", 24, "k2")) is None  # tombstoned
    j2.close()


def test_fit_journal_torn_tail_and_unreadable_snap_degrade(tmp_path):
    cache = ModelCache(max_size=8)
    j = FitJournal(str(tmp_path / "fit-x"))
    j.attach(cache)
    cache.put("a", 1)
    cache.put("b", 2)
    j.compact()  # snap holds {a, b}; log fresh
    cache.put("c", 3)
    j.close()
    # tear the log tail: c is lost, a/b survive via the snap
    raw = open(j.log_path, "rb").read()
    with open(j.log_path, "wb") as fh:
        fh.write(raw[:-3])
    j2 = FitJournal(str(tmp_path / "fit-x"))
    items = j2.restore()
    assert items == {"a": 1, "b": 2}
    assert j2.counters["discards"]["fit_torn"] == 1
    # now corrupt the snap too: everything degrades to cold, no crash
    with open(j2.snap_path, "wb") as fh:
        fh.write(b"\x80\x04notpickle")
    j3 = FitJournal(str(tmp_path / "fit-x"))
    items3 = j3.restore()
    assert items3 == {}
    assert j3.counters["discards"]["fit_unreadable"] == 1


def test_fit_journal_compaction_preserves_unclaimed_restored_entries(tmp_path):
    """Compaction must persist the LAZY overlay too — an entry the
    restarted worker has not claimed yet is still warm state the NEXT
    restart deserves."""
    cache = ModelCache(max_size=8)
    j = FitJournal(str(tmp_path / "fit-y"))
    j.attach(cache)
    cache.put_many([("a", 1), ("b", 2)])
    j.close()

    cache2 = ModelCache(max_size=8)
    j2 = FitJournal(str(tmp_path / "fit-y"))
    cache2.restore_lazy(j2.restore())
    j2.attach(cache2)
    assert cache2.get("a") == 1  # claim a; b stays staged
    n = j2.compact()
    assert n == 2  # resident a AND staged b
    j2.close()
    j3 = FitJournal(str(tmp_path / "fit-y"))
    assert j3.restore() == {"a": 1, "b": 2}


def test_model_cache_lazy_overlay_respects_puts_and_capacity(tmp_path):
    cache = ModelCache(max_size=2)
    assert cache.restore_lazy({"a": 1, "b": 2, "c": 3, "d": 4}) == 4
    # a fresh fit shadows its restored version permanently
    cache.put("a", 99)
    assert cache.get("a") == 99
    # rehydration respects LRU capacity (never balloons past max_size)
    assert cache.get("b") == 2 and cache.get("c") == 3 and cache.get("d") == 4
    assert len(cache) == 2
    # get_many pulls from the overlay too
    cache2 = ModelCache(max_size=8)
    cache2.restore_lazy({"x": 7})
    assert cache2.get_many(["x", "y", None]) == [7, None, None]


# ---------------------------------------------------------------------------
# exposition
# ---------------------------------------------------------------------------


def test_snapshot_collector_families_and_lint(tmp_path):
    from prometheus_client import CollectorRegistry

    from foremast_tpu.observe.metrics_lint import lint_registry

    s1 = _store(shards=1)
    snap1 = RingSnapshotter(s1, str(tmp_path), clock=lambda: NOW)
    snap1.restore()
    snap1.attach()
    _fill(s1, snap1, n=2)
    snap1.snapshot()

    cache = ModelCache(max_size=8)
    j = FitJournal(str(tmp_path / "fit-z"))
    cache.restore_lazy(j.restore())
    j.attach(cache)
    cache.put("k", 1)

    reg = CollectorRegistry()
    reg.register(SnapshotCollector(snap1, journals=[j]))
    assert lint_registry(reg) == []
    assert reg.get_sample_value("foremast_snapshot_writes_total") == 1.0
    assert (
        reg.get_sample_value(
            "foremast_snapshot_discards_total", {"reason": "torn_log"}
        )
        == 0.0
    )
    assert reg.get_sample_value("foremast_snapshot_restored_series") == 0.0
    age = reg.get_sample_value("foremast_snapshot_age_seconds")
    assert age is not None and age >= 0.0
    snap1.close()
    j.close()


def test_worker_debug_state_durability_section(tmp_path):
    from foremast_tpu.config import BrainConfig
    from foremast_tpu.jobs.store import InMemoryStore
    from foremast_tpu.jobs.worker import BrainWorker
    from foremast_tpu.metrics.source import StaticSource

    worker = BrainWorker(
        InMemoryStore(),
        StaticSource({}),
        config=BrainConfig(algorithm="moving_average_all"),
        worker_id="dbg",
    )
    assert worker.debug_state()["durability"] is None
    worker.enable_fit_persistence(str(tmp_path))
    ring = _store(shards=1)
    snap = RingSnapshotter(ring, str(tmp_path), clock=lambda: NOW)
    worker.attach_ring_snapshotter(snap)
    state = worker.debug_state()["durability"]
    assert set(state["fit_journals"]) >= {"fits", "gaps"}
    assert state["ring"]["directory"] == str(tmp_path)
    worker.close()
    snap.close()


def test_restore_into_smaller_ring_clamps_older_spans(tmp_path):
    """Retuning the ring smaller (FOREMAST_INGEST_MAX_POINTS) across a
    restart must not leave restored older coverage spans claiming
    authority over ranges whose samples the smaller ring just dropped:
    the spans re-assert BEFORE the sample push so the overwrite clamp
    applies to them too, and a cold fit's hist read for the lost range
    degrades to the pull path instead of serving a silently truncated
    "full" history (ring.py: degrade, never a wrong answer)."""
    base = int(NOW)
    s1 = _store(shards=1)
    snap1 = RingSnapshotter(s1, str(tmp_path), clock=lambda: NOW)
    # an old historical-backfill span, disjoint from the live stream
    h0, h1 = base - 50_000, base - 48_200
    old_t = np.arange(h0, h1, 60, np.int64)  # 30 samples
    s1.push("m", old_t, np.ones(len(old_t), np.float32),
            start=float(h0), end=float(h1))
    live_t = np.arange(base - 64 * 60, base, 60, np.int64)  # 64 samples
    s1.push("m", live_t, np.ones(len(live_t), np.float32))
    assert len(s1._shards[0]._series["m"].intervals()) == 2
    snap1.snapshot()
    snap1.close()

    # restart into a ring whose max_points holds only the live stream:
    # the restore push drops every historical sample
    s2 = RingStore(shards=1, stale_seconds=300.0, max_points=64)
    snap2 = RingSnapshotter(s2, str(tmp_path), clock=lambda: NOW + 30)
    res = snap2.restore()
    assert res["restored_series"] == 1
    snap2.close()
    # the historical span may not survive its samples: a hist read for
    # that range must degrade (uncovered -> pull path), never serve
    # "full" off columns that no longer hold the samples
    state = s2.hist_query("m", float(h0), float(h1), now=NOW + 30)[0]
    assert state != "full", state
    # the live span still serves resident
    state = s2.query("m", float(base - 64 * 60), None, now=NOW + 30)[0]
    assert state == "hit", state


def test_read_record_stream_is_the_shared_frame_decoder():
    """ISSUE 11: the crc-framed record decoder is ONE definition shared
    by append-log replay and the mesh handoff transfer path — intact
    records stream, the first bad frame ends the stream with a single
    (None, "torn_log"), and nothing after it is trusted."""
    import io

    from foremast_tpu.ingest.snapshot import append_record, read_record_stream

    buf = io.BytesIO()
    for payload in (b"alpha", b"beta", b"gamma"):
        append_record(buf, payload)
    # clean stream
    out = list(read_record_stream(io.BytesIO(buf.getvalue())))
    assert out == [(b"alpha", None), (b"beta", None), (b"gamma", None)]
    # torn tail: the healthy prefix survives, the tear is reported once
    torn = list(read_record_stream(io.BytesIO(buf.getvalue()[:-3])))
    assert torn[:2] == [(b"alpha", None), (b"beta", None)]
    assert torn[-1] == (None, "torn_log")
    # mid-stream corruption desyncs everything after it: only the
    # prefix is served
    raw = bytearray(buf.getvalue())
    raw[len(raw) // 2] ^= 0xFF
    got = list(read_record_stream(io.BytesIO(bytes(raw))))
    assert (None, "torn_log") in got and len(got) <= 3
