"""Instrumentation starter + demo workload tests (SURVEY.md section 2.4/2.6
feature list)."""

import pytest

from foremast_tpu.demo import DemoClient, ErrorGenerator, FileErrorGenerator, make_demo_app
from foremast_tpu.instrument import HttpMetrics, K8sMetricsConfig, MetricsFilter
from foremast_tpu.instrument.starter import _parse_pairs


@pytest.fixture
def demo():
    app, metrics = make_demo_app()
    return DemoClient(app), metrics


def scrape(client) -> str:
    status, body = client.get("/metrics")
    assert status == 200
    return body.decode()


def test_routes_and_status_codes(demo):
    client, _ = demo
    assert client.get("/")[0] == 200
    assert client.get("/error4xx")[0] == 404
    assert client.get("/error5xx")[0] == 500


def test_metrics_alias_paths(demo):
    client, _ = demo
    s1, b1 = client.get("/metrics")
    s2, b2 = client.get("/actuator/prometheus")
    assert s1 == s2 == 200
    assert b"http_server_requests_seconds" in b1
    assert b"http_server_requests_seconds" in b2


def test_common_tags_present(demo):
    client, _ = demo
    client.get("/")
    text = scrape(client)
    assert 'app="spring-boot-demo"' in text


def test_zero_initialized_statuses(demo):
    client, _ = demo
    # before any error traffic the 404/500 counters exist at 0
    text = scrape(client)
    assert 'status="500"' in text
    assert 'status="404"' in text


def test_request_timing_recorded(demo):
    client, _ = demo
    client.get("/error5xx")
    client.get("/error5xx")
    text = scrape(client)
    line = next(
        l for l in text.splitlines()
        if l.startswith("http_server_requests_seconds_count")
        and 'uri="/error5xx"' in l and 'status="500"' in l
    )
    assert float(line.rsplit(" ", 1)[1]) == 2.0


def test_caller_tag_from_header():
    metrics = HttpMetrics(
        K8sMetricsConfig(common_tags={"app": "x"}, caller_header="X-Caller")
    )
    app, _ = make_demo_app(metrics)
    client = DemoClient(app)
    client.get("/", headers={"X-Caller": "checkout-svc"})
    assert 'caller="checkout-svc"' in scrape(client)


def test_runtime_disable_enable(demo):
    client, _ = demo
    client.get("/")
    assert "http_server_requests_seconds" in scrape(client)
    status, _ = client.get("/k8s-metrics/disable/http_server_requests_seconds")
    assert status == 200
    assert "http_server_requests_seconds" not in scrape(client)
    client.get("/k8s-metrics/enable/http_server_requests_seconds")
    assert "http_server_requests_seconds" in scrape(client)
    assert client.get("/k8s-metrics/bogus/x")[0] == 404


def test_filter_whitelist_blacklist_prefix():
    f = MetricsFilter(K8sMetricsConfig(common_tags={}, blacklist={"secret_metric"}))
    assert f.visible("anything")
    assert not f.visible("secret_metric")
    f.enable("secret_metric")
    assert f.visible("secret_metric")

    f2 = MetricsFilter(K8sMetricsConfig(common_tags={}, hide_prefix="jvm_"))
    assert not f2.visible("jvm_threads")
    assert f2.visible("http_server_requests_seconds")

    f3 = MetricsFilter(K8sMetricsConfig(common_tags={}, whitelist={"only_this"}))
    assert f3.visible("only_this")
    assert not f3.visible("other")


def test_tag_env_fallback(monkeypatch):
    monkeypatch.setenv("K8S_METRICS_COMMON_TAGS", "env:prod , team:sre")
    cfg = K8sMetricsConfig()
    assert cfg.common_tags == {"env": "prod", "team": "sre"}
    monkeypatch.delenv("K8S_METRICS_COMMON_TAGS")
    monkeypatch.setenv("APP_NAME", "demo-app")
    assert K8sMetricsConfig().common_tags == {"app": "demo-app"}
    assert _parse_pairs("a:1,bad,b:2") == {"a": "1", "b": "2"}


def test_error_generator_burst(demo):
    client, _ = demo
    ErrorGenerator(client, error_type="5xx", frequency=6).burst(6)
    text = scrape(client)
    line = next(
        l for l in text.splitlines()
        if l.startswith("http_server_requests_seconds_count")
        and 'uri="/error5xx"' in l and 'status="500"' in l
    )
    assert float(line.rsplit(" ", 1)[1]) == 6.0


def test_file_error_generator_replays_trace(demo, tmp_path):
    client, _ = demo
    trace = tmp_path / "trace.csv"
    trace.write_text(
        "2014-02-15 03:00:00,0.2\n2014-02-15 03:01:00,40.134\n2014-02-15 03:02:00,1.0\n"
    )
    gen = FileErrorGenerator(client, str(trace))
    assert gen.rates() == [0.2, 40.134, 1.0]
    total = gen.replay()
    assert total == 0 + 40 + 1
    text = scrape(client)
    line = next(
        l for l in text.splitlines()
        if l.startswith("http_server_requests_seconds_count")
        and 'uri="/error5xx"' in l
    )
    assert float(line.rsplit(" ", 1)[1]) == 41.0


def test_wsgi_streaming_app_records_real_status():
    """PEP 3333: apps may defer start_response until the body is iterated —
    the middleware must record the real status, not a default 500."""
    from foremast_tpu.instrument.starter import wsgi_middleware

    def streaming_app(environ, start_response):
        def gen():
            start_response("200 OK", [("Content-Type", "text/plain")])
            yield b"chunk1"
            yield b"chunk2"

        return gen()

    metrics = HttpMetrics(K8sMetricsConfig(common_tags={"app": "x"}))
    client = DemoClient(wsgi_middleware(streaming_app, metrics))
    status, body = client.get("/stream")
    assert status == 200 and body == b"chunk1chunk2"
    text = scrape(client)
    line = next(
        l for l in text.splitlines()
        if l.startswith("http_server_requests_seconds_count")
        and 'uri="/stream"' in l
    )
    assert 'status="200"' in line
    assert float(line.rsplit(" ", 1)[1]) == 1.0


def test_wsgi_exception_recorded_as_500():
    from foremast_tpu.instrument.starter import wsgi_middleware

    def crashing_app(environ, start_response):
        raise RuntimeError("boom")

    metrics = HttpMetrics(K8sMetricsConfig(common_tags={"app": "x"}))
    app = wsgi_middleware(crashing_app, metrics)
    client = DemoClient(app)
    with pytest.raises(RuntimeError):
        client.get("/crash")
    text = scrape(DemoClient(app))
    assert any(
        'uri="/crash"' in l and 'status="500"' in l
        for l in text.splitlines()
        if l.startswith("http_server_requests_seconds_count")
    )


def test_aiohttp_http_exception_status_not_500():
    """Raising web.HTTPNotFound is aiohttp's idiomatic 404, not a 5xx."""
    import asyncio

    from aiohttp import web
    from aiohttp.test_utils import TestClient, TestServer

    from foremast_tpu.instrument.starter import instrument_aiohttp

    async def run():
        async def missing(request):
            raise web.HTTPNotFound()

        app = web.Application()
        app.router.add_get("/gone", missing)
        metrics = HttpMetrics(K8sMetricsConfig(common_tags={"app": "x"}))
        instrument_aiohttp(app, metrics)
        client = TestClient(TestServer(app))
        await client.start_server()
        try:
            r = await client.get("/gone")
            assert r.status == 404
            m = await client.get("/metrics")
            assert m.headers["Content-Type"].startswith("text/plain; version=")
            text = await m.text()
        finally:
            await client.close()
        return text

    text = asyncio.get_event_loop_policy().new_event_loop().run_until_complete(run())
    lines = [
        l for l in text.splitlines()
        if l.startswith("http_server_requests_seconds_count") and 'uri="/gone"' in l
    ]
    assert lines and all('status="404"' in l for l in lines)
