"""Watch-plane tests: watcher, poller, remediation, and the full
deploy-event -> score -> rollback loop against the in-memory kube fake
(replacing the reference's generated fake clientsets, SURVEY.md section 4).
"""

import os

import numpy as np
import pytest

from foremast_tpu.config import BrainConfig
from foremast_tpu.jobs.models import STATUS_COMPLETED_UNHEALTH
from foremast_tpu.jobs.store import InMemoryStore
from foremast_tpu.jobs.worker import BrainWorker
from foremast_tpu.metrics.source import ReplaySource, StaticSource
from foremast_tpu.watch.analyst import LocalAnalyst, status_to_phase
from foremast_tpu.watch.barrelman import (
    Barrelman,
    containers_changed,
    env_equals,
)
from foremast_tpu.watch.controller import MonitorController, convert_to_anomaly
from foremast_tpu.watch.crds import (
    DeploymentMetadata,
    DeploymentMonitor,
    MonitoredMetric,
    MonitorPhase,
    Remediation,
    RemediationOption,
)
from foremast_tpu.watch.kubeapi import InMemoryKube


# ---------------------------------------------------------------------------
# fixtures
# ---------------------------------------------------------------------------


def make_deployment(
    name="demo", namespace="demo", image="demo:v1", revision=1, env=None, uid="dep-1"
):
    return {
        "metadata": {
            "name": name,
            "namespace": namespace,
            "uid": uid,
            "labels": {"app": name},
            "annotations": {"deployment.kubernetes.io/revision": str(revision)},
        },
        "spec": {
            "template": {
                "metadata": {"labels": {"app": name}},
                "spec": {
                    "containers": [
                        {"name": "main", "image": image, "env": env or []}
                    ]
                },
            }
        },
    }


def make_rs(name, namespace, dep_uid, revision, replicas=1, uid=None, image="demo:v1"):
    return {
        "metadata": {
            "name": name,
            "namespace": namespace,
            "uid": uid or f"rs-{name}",
            "annotations": {"deployment.kubernetes.io/revision": str(revision)},
            "ownerReferences": [{"uid": dep_uid}],
        },
        "spec": {
            "replicas": replicas,
            "template": {
                "metadata": {"labels": {"app": "demo", "pod-template-hash": name}},
                "spec": {"containers": [{"name": "main", "image": image}]},
            },
        },
        "status": {"replicas": replicas},
    }


def make_pod(name, namespace, rs_uid):
    return {
        "metadata": {
            "name": name,
            "namespace": namespace,
            "uid": f"pod-{name}",
            "ownerReferences": [{"uid": rs_uid}],
        }
    }


class FakeClock:
    def __init__(self, t=1_700_000_000.0):
        self.t = t

    def __call__(self):
        return self.t


@pytest.fixture
def world():
    """kube fake + job store + barrelman wired through LocalAnalyst."""
    kube = InMemoryKube()
    kube.add_namespace("demo")
    kube.add_metadata(
        DeploymentMetadata(
            name="demo",
            namespace="demo",
            analyst_endpoint="local://",
            metrics_endpoint="http://prom:9090/",
            monitoring=[
                MonitoredMetric("error5xx", metric_type="error5xx", metric_alias="error5xx")
            ],
        )
    )
    store = InMemoryStore()
    clock = FakeClock()
    bman = Barrelman(
        kube,
        analyst_factory=lambda ep: LocalAnalyst(store),
        clock=clock,
        sleep=lambda s: None,
    )
    kube.on_deployment(bman.handle_deployment)
    return kube, store, bman, clock


def seed_pods(kube, dep_uid="dep-1", old_rev=1, new_rev=2):
    kube.add_replicaset(make_rs("demo-old", "demo", dep_uid, old_rev, image="demo:v1"))
    kube.add_replicaset(make_rs("demo-new", "demo", dep_uid, new_rev, image="demo:v2"))
    kube.add_pod(make_pod("demo-old-1", "demo", "rs-demo-old"))
    kube.add_pod(make_pod("demo-new-1", "demo", "rs-demo-new"))


# ---------------------------------------------------------------------------
# unit: diffing + CRDs
# ---------------------------------------------------------------------------


def test_env_equals_order_insensitive():
    a = [{"name": "A", "value": "1"}, {"name": "B", "value": "2"}]
    b = [{"name": "B", "value": "2"}, {"name": "A", "value": "1"}]
    assert env_equals(a, b)
    assert not env_equals(a, [{"name": "A", "value": "9"}, {"name": "B", "value": "2"}])


def test_containers_changed_on_image_and_env():
    old = make_deployment(image="demo:v1")
    assert not containers_changed(old, make_deployment(image="demo:v1"))
    assert containers_changed(old, make_deployment(image="demo:v2"))
    assert containers_changed(
        old, make_deployment(image="demo:v1", env=[{"name": "X", "value": "1"}])
    )


def test_crd_roundtrip():
    m = DeploymentMonitor(
        name="demo",
        namespace="demo",
        selector={"app": "demo"},
        continuous=True,
        remediation=Remediation(option=RemediationOption.AUTO_ROLLBACK),
        rollback_revision=3,
    )
    m.status.phase = MonitorPhase.RUNNING
    m2 = DeploymentMonitor.from_json(m.to_json())
    assert m2 == m
    md = DeploymentMetadata(
        name="x", namespace="y", analyst_endpoint="http://a/",
        monitoring=[MonitoredMetric("m1", "latency", "lat")],
    )
    assert DeploymentMetadata.from_json(md.to_json()) == md
    assert md.metric_names() == {"lat": "m1"}


def test_convert_to_anomaly_flat_pairs():
    out = convert_to_anomaly(
        {"tags": "", "values": {"error5xx": [100.0, 40.1, 160.0, 41.0]}}
    )
    assert out["error5xx"]["values"] == [
        {"time": 100.0, "value": 40.1},
        {"time": 160.0, "value": 41.0},
    ]


def test_status_to_phase_map():
    assert status_to_phase("new") == MonitorPhase.RUNNING
    assert status_to_phase("inprogress") == MonitorPhase.RUNNING
    assert status_to_phase("success") == MonitorPhase.HEALTHY
    assert status_to_phase("anomaly") == MonitorPhase.UNHEALTHY
    assert status_to_phase("abort") == MonitorPhase.ABORT
    assert status_to_phase("garbage") == MonitorPhase.FAILED


# ---------------------------------------------------------------------------
# gating + metadata resolution
# ---------------------------------------------------------------------------


def test_namespace_blacklist_and_annotation(world):
    kube, store, bman, clock = world
    assert not bman.namespace_monitored("kube-system")
    assert not bman.namespace_monitored("monitoring")
    assert bman.namespace_monitored("demo")
    kube.add_namespace("optout", {"foremast.ai/monitoring": "false"})
    assert not bman.namespace_monitored("optout")
    # cached for 5 min: flipping the annotation is invisible until TTL
    kube.add_namespace("optout", {"foremast.ai/monitoring": "true"})
    assert not bman.namespace_monitored("optout")
    clock.t += 301
    assert bman.namespace_monitored("optout")


def test_metadata_fallback_chain(world):
    kube, store, bman, clock = world
    dep = make_deployment(name="other", namespace="demo")
    dep["metadata"]["labels"]["appType"] = "java-service"
    assert bman.get_metadata(dep) is None  # negative-cached now
    kube.add_metadata(
        DeploymentMetadata(name="java-service", namespace="foremast")
    )
    # every candidate key was negative-cached by the first lookup, so the
    # new CR stays invisible until the 1-min TTL lapses
    assert bman.get_metadata(dep) is None
    clock.t += 61
    md = bman.get_metadata(dep)
    assert md is not None and md.name == "java-service"


# ---------------------------------------------------------------------------
# watcher behavior
# ---------------------------------------------------------------------------


def test_add_creates_monitor(world):
    kube, store, bman, clock = world
    kube.apply_deployment(make_deployment())
    assert ("demo", "demo") in kube.monitors


def test_image_update_starts_job(world):
    kube, store, bman, clock = world
    seed_pods(kube)
    kube.apply_deployment(make_deployment(image="demo:v1", revision=1))
    kube.apply_deployment(make_deployment(image="demo:v2", revision=2))
    mon = kube.get_monitor("demo", "demo")
    assert mon.status.phase == MonitorPhase.RUNNING
    assert mon.status.job_id
    doc = store.get(mon.status.job_id)
    assert doc is not None
    assert "demo-new-1" in doc.current_config  # current pinned to new pods
    assert mon.rollback_revision == 1  # remembers pre-update revision


def test_no_metadata_no_job(world):
    kube, store, bman, clock = world
    kube.add_namespace("bare")
    dep = make_deployment(name="nomd", namespace="bare", uid="dep-9")
    kube.apply_deployment(dep)
    dep2 = make_deployment(name="nomd", namespace="bare", image="demo:v2", uid="dep-9")
    kube.apply_deployment(dep2)
    mon = kube.get_monitor("bare", "nomd")
    assert mon.status.job_id == ""  # ensure_monitor only; no job without metadata


def test_rollback_loop_suppression(world):
    kube, store, bman, clock = world
    seed_pods(kube)
    kube.apply_deployment(make_deployment(image="demo:v1", revision=1))
    mon = kube.get_monitor("demo", "demo")
    mon.rollback_revision = 3
    kube.upsert_monitor(mon)
    n_jobs = len(store._docs)
    # the "update" that lands on the suppressed revision starts no job
    kube.apply_deployment(make_deployment(image="demo:v1-rb", revision=3))
    assert len(store._docs) == n_jobs
    # annotation path
    dep = make_deployment(image="demo:v3", revision=4)
    dep["metadata"]["annotations"]["deprecated.deployment.rollback.to"] = "1"
    kube.apply_deployment(dep)
    assert len(store._docs) == n_jobs


def test_canary_suffix_maps_to_primary_monitor(world):
    kube, store, bman, clock = world
    kube.add_metadata(
        DeploymentMetadata(
            name="demo-foremast-canary",
            namespace="demo",
            analyst_endpoint="local://",
            metrics_endpoint="http://prom:9090/",
            monitoring=[MonitoredMetric("error5xx")],
        )
    )
    canary_uid = "dep-canary"
    kube.add_replicaset(make_rs("canary-rs", "demo", canary_uid, 1, image="demo:v2"))
    kube.add_pod(make_pod("canary-1", "demo", "rs-canary-rs"))
    # primary deployment with live pods: the canary's baseline population
    kube.apply_deployment(make_deployment(image="demo:v1", revision=1))
    seed_pods(kube, old_rev=1, new_rev=1)
    kube.apply_deployment(
        make_deployment(name="demo-foremast-canary", uid=canary_uid, image="demo:v2")
    )
    # monitor is created under the PRIMARY name
    mon = kube.get_monitor("demo", "demo")
    assert mon.status.phase == MonitorPhase.RUNNING
    # baseline query pinned to the primary's pods, not canary's own
    doc = store.get(mon.status.job_id)
    assert "canary-1" in doc.current_config
    assert "demo-new-1" in doc.baseline_config or "demo-old-1" in doc.baseline_config


# ---------------------------------------------------------------------------
# poller + remediation
# ---------------------------------------------------------------------------


def unhealthy_store_with_job(store, job_id_holder, world_kube, bman):
    seed_pods(world_kube)
    world_kube.apply_deployment(make_deployment(image="demo:v1", revision=1))
    world_kube.apply_deployment(make_deployment(image="demo:v2", revision=2))
    mon = world_kube.get_monitor("demo", "demo")
    doc = store.get(mon.status.job_id)
    doc.status = STATUS_COMPLETED_UNHEALTH
    doc.reason = "anomaly detected"
    doc.anomaly_info = {
        "tags": "",
        "values": {"error5xx": [100.0, 40.1]},
    }
    store.update(doc)
    return mon


def test_poll_unhealthy_triggers_rollback(world):
    kube, store, bman, clock = world
    mon = unhealthy_store_with_job(store, None, kube, bman)
    mon.remediation = Remediation(option=RemediationOption.AUTO_ROLLBACK)
    kube.upsert_monitor(mon)
    ctl = MonitorController(kube, bman, clock=clock)
    ctl.tick()
    mon = kube.get_monitor("demo", "demo")
    assert mon.status.phase == MonitorPhase.UNHEALTHY
    assert mon.status.remediation_taken
    assert mon.status.anomaly["error5xx"]["values"] == [
        {"time": 100.0, "value": 40.1}
    ]
    # deployment template patched back to the old RS image
    dep = kube.get_deployment("demo", "demo")
    img = dep["spec"]["template"]["spec"]["containers"][0]["image"]
    assert img == "demo:v1"
    # idempotent: second tick does not re-remediate
    patches = [a for a in kube.actions if a[0] == "patch"]
    ctl.tick()
    assert [a for a in kube.actions if a[0] == "patch"] == patches


def test_poll_unhealthy_pause(world):
    kube, store, bman, clock = world
    mon = unhealthy_store_with_job(store, None, kube, bman)
    mon.remediation = Remediation(option=RemediationOption.AUTO_PAUSE)
    kube.upsert_monitor(mon)
    MonitorController(kube, bman, clock=clock).tick()
    dep = kube.get_deployment("demo", "demo")
    assert dep["spec"]["paused"] is True


def test_wait_until_expiry_defaults_healthy(world):
    kube, store, bman, clock = world
    seed_pods(kube)
    kube.apply_deployment(make_deployment(image="demo:v1", revision=1))
    kube.apply_deployment(make_deployment(image="demo:v2", revision=2))
    ctl = MonitorController(kube, bman, clock=clock)
    ctl.tick()  # job still "initial" -> Running, nothing happens
    assert kube.get_monitor("demo", "demo").status.phase == MonitorPhase.RUNNING
    clock.t += 1801  # past waitUntil (30 min)
    ctl.tick()
    mon = kube.get_monitor("demo", "demo")
    assert mon.status.phase == MonitorPhase.HEALTHY
    assert mon.status.expired


def test_continuous_rearm_with_backoff(world):
    kube, store, bman, clock = world
    seed_pods(kube)
    kube.apply_deployment(make_deployment(image="demo:v1", revision=1))
    mon = kube.get_monitor("demo", "demo")
    mon.continuous = True
    mon.status.phase = MonitorPhase.UNHEALTHY
    kube.upsert_monitor(mon)
    ctl = MonitorController(kube, bman, clock=clock)
    ctl._unhealthy_since[("demo", "demo")] = clock.t
    ctl.tick()  # inside 60 s backoff: no re-arm
    assert kube.get_monitor("demo", "demo").status.phase == MonitorPhase.UNHEALTHY
    clock.t += 61
    ctl.tick()  # backoff over: re-armed as a continuous job
    mon = kube.get_monitor("demo", "demo")
    assert mon.status.phase == MonitorPhase.RUNNING
    assert mon.continuous
    doc = store.get(mon.status.job_id)
    assert "namespace_app_per_pod" in doc.current_config  # no pod pinning


def test_delete_deployment_deletes_monitor(world):
    kube, store, bman, clock = world
    kube.apply_deployment(make_deployment())
    assert ("demo", "demo") in kube.monitors
    kube.remove_deployment("demo", "demo")
    assert ("demo", "demo") not in kube.monitors


# ---------------------------------------------------------------------------
# end-to-end: deploy event -> brain scores spike trace -> rollback
# ---------------------------------------------------------------------------


def test_e2e_deploy_score_rollback(world, demo_traces):
    kube, store, bman, clock = world
    seed_pods(kube)
    kube.apply_deployment(make_deployment(image="demo:v1", revision=1))
    mon = kube.get_monitor("demo", "demo")
    mon.remediation = Remediation(option=RemediationOption.AUTO_ROLLBACK)
    kube.upsert_monitor(mon)

    kube.apply_deployment(make_deployment(image="demo:v2", revision=2))

    ht, hv = demo_traces["normal"]
    st, sv = demo_traces["spike"]
    source = ReplaySource()
    # current (pod-pinned to the new pods) replays the spike trace;
    # baseline (old pods) + historical (app-wide) replay the normal one.
    source.register("demo-new-1", (st, sv))
    source.register("demo-old-1", (ht, hv))
    source.register("namespace_app_per_pod:error5xx", (ht, hv))

    worker = BrainWorker(store, source, BrainConfig())
    assert worker.tick(now=clock.t) >= 1
    mon = kube.get_monitor("demo", "demo")
    doc = store.get(mon.status.job_id)
    assert doc.status == STATUS_COMPLETED_UNHEALTH

    MonitorController(kube, bman, clock=clock).tick()
    mon = kube.get_monitor("demo", "demo")
    assert mon.status.phase == MonitorPhase.UNHEALTHY
    assert mon.status.remediation_taken
    assert mon.status.anomaly.get("error5xx", {}).get("values")
    dep = kube.get_deployment("demo", "demo")
    assert dep["spec"]["template"]["spec"]["containers"][0]["image"] == "demo:v1"


def test_events_emitted_on_monitoring_and_remediation(world):
    """K8s Events parity (EventBroadcaster role): monitoring start emits
    Normal/MonitoringStarted; unhealthy emits Warning/Unhealthy."""
    kube, store, bman, clock = world
    seed_pods(kube)
    kube.apply_deployment(make_deployment(image="demo:v1", revision=1))
    kube.apply_deployment(make_deployment(image="demo:v2", revision=2))
    reasons = [e["reason"] for e in kube.events]
    assert "MonitoringStarted" in reasons

    mon = kube.get_monitor("demo", "demo")
    mon.remediation.option = "AutoRollback"
    mon.status.phase = MonitorPhase.UNHEALTHY
    MonitorController(kube, bman, clock=clock).handle_transition(mon)
    types = {e["reason"]: e["type"] for e in kube.events}
    assert types.get("Unhealthy") == "Warning"
    assert types["MonitoringStarted"] == "Normal"
