"""Smoke checks for ui/static/app.js without a JS engine (VERDICT r2
item 8; the image ships no node/browser/embeddable JS runtime).

Two layers:

  1. a tokenizer-level structural lint — comments, string/template
     literals (with nested ${...}), and typed bracket matching — which
     fails on the ship-a-typo class (stray brace, unclosed paren/string)
     anywhere in the file;
  2. executable Python PORTS of the pure helpers (extent, niceTicks),
     golden-tested here, with the corresponding JS source text PINNED —
     editing the JS helper fails the pin and forces re-validating the
     port, so helper behavior cannot silently drift.
"""

from __future__ import annotations

import math
import os
import re

APP_JS = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "foremast_tpu",
    "ui",
    "static",
    "app.js",
)

_OPEN = {"(": ")", "[": "]", "{": "}"}
_CLOSE = {v: k for k, v in _OPEN.items()}
# a `/` after any of these (last significant char) starts a regex literal
_REGEX_PRECEDER = set("([{=:,;!&|?+-*%<>~^")


def lint_js(src: str) -> list[str]:
    """Structural errors in a JS source: bracket mismatches and
    unterminated comments/strings/templates. Returns [] when clean."""
    errors: list[str] = []
    # (bracket, line, from_template): from_template marks the '{' opened
    # by a template's '${' — only ITS matching '}' pops back into the
    # template, so object/block braces inside ${...} nest correctly
    stack: list[tuple[str, int, bool]] = []
    mode: list[str] = ["code"]  # code | line | block | ' | " | ` | regex
    last_sig = ""  # last significant char seen in code mode
    line = 1
    i, n = 0, len(src)
    while i < n:
        c = src[i]
        nxt = src[i + 1] if i + 1 < n else ""
        if c == "\n":
            line += 1
        m = mode[-1]
        if m == "line":
            if c == "\n":
                mode.pop()
        elif m == "block":
            if c == "*" and nxt == "/":
                mode.pop()
                i += 1
        elif m in ("'", '"'):
            if c == "\\":
                i += 1
            elif c == m or c == "\n":
                if c == "\n":
                    errors.append(f"line {line - 1}: unterminated string")
                mode.pop()
        elif m == "`":
            if c == "\\":
                i += 1
            elif c == "$" and nxt == "{":
                mode.append("code")
                stack.append(("{", line, True))
                i += 1
            elif c == "`":
                mode.pop()
        elif m == "regex":
            if c == "\\":
                i += 1
            elif c == "/" or c == "\n":
                mode.pop()
        else:  # code
            if c == "/" and nxt == "/":
                mode.append("line")
                i += 1
            elif c == "/" and nxt == "*":
                mode.append("block")
                i += 1
            elif c == "/" and last_sig in _REGEX_PRECEDER:
                mode.append("regex")
            elif c in ("'", '"', "`"):
                mode.append(c)
            elif c in _OPEN:
                stack.append((c, line, False))
            elif c in _CLOSE:
                if not stack or stack[-1][0] != _CLOSE[c]:
                    errors.append(f"line {line}: unmatched '{c}'")
                else:
                    _opener, _, from_template = stack.pop()
                    if from_template:  # the '}' of '${': back into `...`
                        mode.pop()
            if not c.isspace():
                last_sig = c
        i += 1
    for b, ln, _ in stack:
        errors.append(f"line {ln}: unclosed '{b}'")
    if mode[-1] != "code":
        errors.append(f"EOF inside {mode[-1]!r}")
    return errors


def extract_function(src: str, name: str) -> str:
    """Source text of `function <name>(...) {...}` via brace matching."""
    m = re.search(rf"function {re.escape(name)}\s*\(", src)
    assert m, f"{name} not found in app.js"
    i = src.index("{", m.end() - 1)
    depth = 0
    for j in range(i, len(src)):
        if src[j] == "{":
            depth += 1
        elif src[j] == "}":
            depth -= 1
            if depth == 0:
                return src[m.start() : j + 1]
    raise AssertionError(f"unbalanced braces in {name}")


# -- Python ports of the pure helpers (validated against the pinned JS) --


def extent_py(series_list, pick):
    lo, hi = math.inf, -math.inf
    for s in series_list:
        for d in s:
            x = pick(d)
            if isinstance(x, (int, float)) and math.isfinite(x):
                lo, hi = min(lo, x), max(hi, x)
    return [lo, hi] if lo <= hi else None


def nice_ticks_py(lo, hi, n):
    span = (hi - lo) or 1
    step = 10.0 ** math.floor(math.log10(span / n))
    err = span / n / step
    mult = 10 if err >= 7.5 else 5 if err >= 3.5 else 2 if err >= 1.5 else 1
    s = step * mult
    ticks = []
    v = math.ceil(lo / s) * s
    while v <= hi + 1e-9:
        ticks.append(v)
        v += s
    return ticks


# The pinned JS sources. If these pins fail, the JS helper changed:
# update the pin AND mirror the change in the Python port above (its
# golden tests below are the executable spec both implementations share).
PINNED_EXTENT = """function extent(seriesList, pick) {
  let lo = Infinity, hi = -Infinity;
  for (const s of seriesList)
    for (const d of s) {
      const x = pick(d);
      if (Number.isFinite(x)) { if (x < lo) lo = x; if (x > hi) hi = x; }
    }
  return lo <= hi ? [lo, hi] : null;
}"""

PINNED_NICE_TICKS = """function niceTicks(lo, hi, n) {
  const span = hi - lo || 1;
  const step = Math.pow(10, Math.floor(Math.log10(span / n)));
  const err = span / n / step;
  const mult = err >= 7.5 ? 10 : err >= 3.5 ? 5 : err >= 1.5 ? 2 : 1;
  const s = step * mult;
  const ticks = [];
  for (let v = Math.ceil(lo / s) * s; v <= hi + 1e-9; v += s) ticks.push(v);
  return ticks;
}"""


def test_app_js_is_structurally_sound():
    src = open(APP_JS).read()
    assert lint_js(src) == []


def test_lint_catches_injected_typos():
    """The lint must actually detect the failure class it guards: a
    dropped brace, an extra paren, an unclosed string/template."""
    src = open(APP_JS).read()
    assert lint_js(src.replace("function extent", "function extent)", 1))
    broken = src.replace("return lo <= hi ? [lo, hi] : null;\n}", "", 1)
    assert lint_js(broken)
    assert lint_js(src + "\nconst s = 'unterminated;\n")
    assert lint_js(src + "\nconst t = `no close ${1 + 2};\n")
    # valid constructs that must NOT false-positive (code-review r3:
    # braces inside template interpolations)
    assert lint_js("const x = `${fmt({a: 1})}`;") == []
    assert lint_js("const y = `a${list.map((v) => `${v}`).join({}['k'])}b`;") == []
    assert lint_js("const r = /a[{(]b/.test(s) ? 1 : 2;") == []


def test_helper_sources_match_pins():
    src = open(APP_JS).read()
    assert extract_function(src, "extent") == PINNED_EXTENT
    assert extract_function(src, "niceTicks") == PINNED_NICE_TICKS


def test_python_ports_golden_behavior():
    # extent: finite values only, across multiple series; empty -> None
    series = [[{"t": 1, "v": 5.0}, {"t": 2, "v": float("nan")}],
              [{"t": 3, "v": -2.0}]]
    assert extent_py(series, lambda d: d["v"]) == [-2.0, 5.0]
    assert extent_py(series, lambda d: d["t"]) == [1, 3]
    assert extent_py([[]], lambda d: d) is None

    # niceTicks: round steps covering [lo, hi], first tick >= lo
    ticks = nice_ticks_py(0.13, 9.9, 5)
    assert ticks == [2, 4, 6, 8]
    ticks = nice_ticks_py(0.0, 1.0, 4)
    assert ticks[0] == 0.0 and ticks[-1] <= 1.0 + 1e-9
    # spacing is uniform up to float accumulation (the JS accumulates
    # v += s the same way)
    assert all(
        abs((b - a) - (ticks[1] - ticks[0])) < 1e-9
        for a, b in zip(ticks, ticks[1:])
    )
    # degenerate span (lo == hi) must not divide by zero
    assert nice_ticks_py(3.0, 3.0, 5) != []


# -- render-path ports (VERDICT r4 #9): geometry as executed Python ------

PAD = {"l": 44, "r": 10, "t": 8, "b": 18}


def js_num(x) -> str:
    """JS Number->String for the values these ports emit: integral
    doubles print without a decimal point, everything else as the
    shortest round-trip (Python repr matches for non-exotic floats)."""
    if isinstance(x, float) and math.isfinite(x) and x == int(x):
        return str(int(x))
    return repr(x)


def make_domain_py(base, upper, lower):
    t_ext = extent_py([base], lambda x: x["t"])
    v_ext = extent_py([base, upper, lower], lambda x: x["v"])
    if t_ext is None or v_ext is None:
        return None
    t0, t1 = t_ext
    v0, v1 = v_ext
    if v0 == v1:
        v0, v1 = v0 - 1, v1 + 1
    pad_v = (v1 - v0) * 0.08
    return {"t0": t0, "t1": t1, "v0": v0 - pad_v, "v1": v1 + pad_v}


def x_pix_py(t, dom, w):
    return PAD["l"] + ((t - dom["t0"]) / ((dom["t1"] - dom["t0"]) or 1)) * (
        w - PAD["l"] - PAD["r"]
    )


def y_pix_py(v, dom, h):
    return h - PAD["b"] - ((v - dom["v0"]) / (dom["v1"] - dom["v0"])) * (
        h - PAD["t"] - PAD["b"]
    )


def path_points_py(series, dom, w, h):
    return " ".join(
        f"{js_num(x_pix_py(x['t'], dom, w))},{js_num(y_pix_py(x['v'], dom, h))}"
        for x in series
    )


def band_polygon_py(upper, lower, dom, w, h):
    lo_by_t = {x["t"]: x["v"] for x in lower}
    pts = [x for x in upper if x["t"] in lo_by_t]
    if not pts:
        return None
    fwd = [
        f"{js_num(x_pix_py(x['t'], dom, w))},{js_num(y_pix_py(x['v'], dom, h))}"
        for x in pts
    ]
    back = [
        f"{js_num(x_pix_py(x['t'], dom, w))},"
        f"{js_num(y_pix_py(lo_by_t[x['t']], dom, h))}"
        for x in reversed(pts)
    ]
    return " ".join(fwd + back)


def anomaly_dots_py(anoms, dom, w, h):
    return [
        {"cx": x_pix_py(a["t"], dom, w), "cy": y_pix_py(a["v"], dom, h)}
        for a in anoms
    ]


def tick_layout_py(dom, w, h):
    y_ticks = [
        {"v": v, "y": y_pix_py(v, dom, h)}
        for v in nice_ticks_py(dom["v0"], dom["v1"], 4)
    ]
    n_t = max(2, math.floor(w / 140))
    x_ticks = [
        {"t": t, "x": x_pix_py(t, dom, w)}
        for t in nice_ticks_py(dom["t0"], dom["t1"], n_t)
    ]
    return {"yTicks": y_ticks, "xTicks": x_ticks}


def nearest_py(series, t):
    best, bd = None, math.inf
    for d in series:
        dd = abs(d["t"] - t)
        if dd < bd:
            bd, best = dd, d
    return best


PINNED_MAKE_DOMAIN = """function makeDomain(base, upper, lower) {
  // time domain from the measured curve; value domain over curve + band,
  // +-8% headroom; degenerate (flat) spans widen by 1 so Y never /0
  const tExt = extent([base], (x) => x.t);
  const vExt = extent([base, upper, lower], (x) => x.v);
  if (!tExt || !vExt) return null;
  const t0 = tExt[0], t1 = tExt[1];
  let v0 = vExt[0], v1 = vExt[1];
  if (v0 === v1) { v0 -= 1; v1 += 1; }
  const padV = (v1 - v0) * 0.08;
  return { t0, t1, v0: v0 - padV, v1: v1 + padV };
}"""

PINNED_X_PIX = """function xPix(t, dom, W) {
  return PAD.l + ((t - dom.t0) / (dom.t1 - dom.t0 || 1)) * (W - PAD.l - PAD.r);
}"""

PINNED_Y_PIX = """function yPix(v, dom, H) {
  return H - PAD.b - ((v - dom.v0) / (dom.v1 - dom.v0)) * (H - PAD.t - PAD.b);
}"""

PINNED_PATH_POINTS = """function pathPoints(series, dom, W, H) {
  return series.map((x) => `${xPix(x.t, dom, W)},${yPix(x.v, dom, H)}`).join(" ");
}"""

PINNED_BAND_POLYGON = """function bandPolygon(upper, lower, dom, W, H) {
  // fill between the band edges over their COMMON timestamps: forward
  // along upper, back along lower (reversed) closes the polygon
  const loByT = new Map(lower.map((x) => [x.t, x.v]));
  const pts = upper.filter((x) => loByT.has(x.t));
  if (!pts.length) return null;
  const fwd = pts.map((x) => `${xPix(x.t, dom, W)},${yPix(x.v, dom, H)}`);
  const back = pts.slice().reverse()
    .map((x) => `${xPix(x.t, dom, W)},${yPix(loByT.get(x.t), dom, H)}`);
  return fwd.concat(back).join(" ");
}"""

PINNED_ANOMALY_DOTS = """function anomalyDots(anoms, dom, W, H) {
  return anoms.map((a) => ({ cx: xPix(a.t, dom, W), cy: yPix(a.v, dom, H) }));
}"""

PINNED_TICK_LAYOUT = """function tickLayout(dom, W, H) {
  const yTicks = niceTicks(dom.v0, dom.v1, 4)
    .map((v) => ({ v, y: yPix(v, dom, H) }));
  const nT = Math.max(2, Math.floor(W / 140));
  const xTicks = niceTicks(dom.t0, dom.t1, nT)
    .map((t) => ({ t, x: xPix(t, dom, W) }));
  return { yTicks, xTicks };
}"""

PINNED_NEAREST = """function nearest(series, t) {
  let best = null, bd = Infinity;
  for (const d of series) {
    const dd = Math.abs(d.t - t);
    if (dd < bd) { bd = dd; best = d; }
  }
  return best;
}"""


def test_render_path_sources_match_pins():
    src = open(APP_JS).read()
    for name, pin in [
        ("makeDomain", PINNED_MAKE_DOMAIN),
        ("xPix", PINNED_X_PIX),
        ("yPix", PINNED_Y_PIX),
        ("pathPoints", PINNED_PATH_POINTS),
        ("bandPolygon", PINNED_BAND_POLYGON),
        ("anomalyDots", PINNED_ANOMALY_DOTS),
        ("tickLayout", PINNED_TICK_LAYOUT),
        ("nearest", PINNED_NEAREST),
    ]:
        assert extract_function(src, name) == pin, name


def _demo_panel():
    """A panel payload in the shape ui/join.py serves."""
    base = [{"t": 1000 + 60 * i, "v": 1.0 + 0.1 * i} for i in range(10)]
    upper = [{"t": 1000 + 60 * i, "v": 2.0 + 0.1 * i} for i in range(10)]
    # lower misses two timestamps: the polygon must drop them
    lower = [
        {"t": 1000 + 60 * i, "v": 0.5 + 0.1 * i} for i in range(10)
        if i not in (3, 7)
    ]
    anoms = [{"t": 1240, "v": 1.4}, {"t": 1480, "v": 1.8}]
    return base, upper, lower, anoms


def test_render_geometry_golden():
    base, upper, lower, anoms = _demo_panel()
    w, h = 440, 180
    dom = make_domain_py(base, upper, lower)
    # domain: time from base only, value across curve+band with 8% pad
    assert dom["t0"] == 1000 and dom["t1"] == 1540
    assert dom["v0"] < 0.5 and dom["v1"] > 2.9
    span = (2.9 - 0.5) * 0.08
    assert abs(dom["v0"] - (0.5 - span)) < 1e-12
    assert abs(dom["v1"] - (2.9 + span)) < 1e-12

    # pixel scales: corners map to the padded plot box exactly
    assert x_pix_py(dom["t0"], dom, w) == PAD["l"]
    assert x_pix_py(dom["t1"], dom, w) == w - PAD["r"]
    assert y_pix_py(dom["v0"], dom, h) == h - PAD["b"]
    assert abs(y_pix_py(dom["v1"], dom, h) - PAD["t"]) < 1e-12

    # path string: one "x,y" pair per point, in order, JS formatting
    pts = path_points_py(base, dom, w, h).split(" ")
    assert len(pts) == len(base)
    assert pts[0].split(",")[0] == "44"  # first point at the left pad

    # band polygon: common timestamps only, forward + reversed back edge
    poly = band_polygon_py(upper, lower, dom, w, h)
    coords = poly.split(" ")
    assert len(coords) == 2 * (len(upper) - 2)  # two missing lower pts
    first_x = coords[0].split(",")[0]
    last_x = coords[-1].split(",")[0]
    assert first_x == last_x  # back edge returns to the start column

    # anomaly dots ride the measured curve inside the plot box
    for dot, a in zip(anomaly_dots_py(anoms, dom, w, h), anoms):
        assert PAD["l"] <= dot["cx"] <= w - PAD["r"]
        assert PAD["t"] <= dot["cy"] <= h - PAD["b"]
        assert abs(dot["cx"] - x_pix_py(a["t"], dom, w)) < 1e-12

    # tick layout: gridlines inside the box, x-tick count tracks width
    ticks = tick_layout_py(dom, w, h)
    assert all(PAD["t"] <= g["y"] <= h - PAD["b"] for g in ticks["yTicks"])
    assert all(PAD["l"] <= g["x"] <= w - PAD["r"] for g in ticks["xTicks"])
    assert len(tick_layout_py(dom, 880, h)["xTicks"]) >= len(ticks["xTicks"])

    # degenerate and empty domains
    flat = [{"t": 0, "v": 5.0}, {"t": 60, "v": 5.0}]
    dflat = make_domain_py(flat, [], [])
    assert dflat["v1"] - dflat["v0"] > 1  # widened, no /0
    assert make_domain_py([], [], []) is None
    nan = [{"t": 0, "v": float("nan")}]
    assert make_domain_py(nan, [], []) is None  # all-NaN -> "no data"

    # crosshair nearest-point lookup
    assert nearest_py(base, 1239)["t"] == 1240
    assert nearest_py(base, -1e9)["t"] == 1000
    assert nearest_py([], 5) is None
