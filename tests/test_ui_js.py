"""Smoke checks for ui/static/app.js without a JS engine (VERDICT r2
item 8; the image ships no node/browser/embeddable JS runtime).

Two layers:

  1. a tokenizer-level structural lint — comments, string/template
     literals (with nested ${...}), and typed bracket matching — which
     fails on the ship-a-typo class (stray brace, unclosed paren/string)
     anywhere in the file;
  2. executable Python PORTS of the pure helpers (extent, niceTicks),
     golden-tested here, with the corresponding JS source text PINNED —
     editing the JS helper fails the pin and forces re-validating the
     port, so helper behavior cannot silently drift.
"""

from __future__ import annotations

import math
import os
import re

APP_JS = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "foremast_tpu",
    "ui",
    "static",
    "app.js",
)

_OPEN = {"(": ")", "[": "]", "{": "}"}
_CLOSE = {v: k for k, v in _OPEN.items()}
# a `/` after any of these (last significant char) starts a regex literal
_REGEX_PRECEDER = set("([{=:,;!&|?+-*%<>~^")


def lint_js(src: str) -> list[str]:
    """Structural errors in a JS source: bracket mismatches and
    unterminated comments/strings/templates. Returns [] when clean."""
    errors: list[str] = []
    # (bracket, line, from_template): from_template marks the '{' opened
    # by a template's '${' — only ITS matching '}' pops back into the
    # template, so object/block braces inside ${...} nest correctly
    stack: list[tuple[str, int, bool]] = []
    mode: list[str] = ["code"]  # code | line | block | ' | " | ` | regex
    last_sig = ""  # last significant char seen in code mode
    line = 1
    i, n = 0, len(src)
    while i < n:
        c = src[i]
        nxt = src[i + 1] if i + 1 < n else ""
        if c == "\n":
            line += 1
        m = mode[-1]
        if m == "line":
            if c == "\n":
                mode.pop()
        elif m == "block":
            if c == "*" and nxt == "/":
                mode.pop()
                i += 1
        elif m in ("'", '"'):
            if c == "\\":
                i += 1
            elif c == m or c == "\n":
                if c == "\n":
                    errors.append(f"line {line - 1}: unterminated string")
                mode.pop()
        elif m == "`":
            if c == "\\":
                i += 1
            elif c == "$" and nxt == "{":
                mode.append("code")
                stack.append(("{", line, True))
                i += 1
            elif c == "`":
                mode.pop()
        elif m == "regex":
            if c == "\\":
                i += 1
            elif c == "/" or c == "\n":
                mode.pop()
        else:  # code
            if c == "/" and nxt == "/":
                mode.append("line")
                i += 1
            elif c == "/" and nxt == "*":
                mode.append("block")
                i += 1
            elif c == "/" and last_sig in _REGEX_PRECEDER:
                mode.append("regex")
            elif c in ("'", '"', "`"):
                mode.append(c)
            elif c in _OPEN:
                stack.append((c, line, False))
            elif c in _CLOSE:
                if not stack or stack[-1][0] != _CLOSE[c]:
                    errors.append(f"line {line}: unmatched '{c}'")
                else:
                    _opener, _, from_template = stack.pop()
                    if from_template:  # the '}' of '${': back into `...`
                        mode.pop()
            if not c.isspace():
                last_sig = c
        i += 1
    for b, ln, _ in stack:
        errors.append(f"line {ln}: unclosed '{b}'")
    if mode[-1] != "code":
        errors.append(f"EOF inside {mode[-1]!r}")
    return errors


def extract_function(src: str, name: str) -> str:
    """Source text of `function <name>(...) {...}` via brace matching."""
    m = re.search(rf"function {re.escape(name)}\s*\(", src)
    assert m, f"{name} not found in app.js"
    i = src.index("{", m.end() - 1)
    depth = 0
    for j in range(i, len(src)):
        if src[j] == "{":
            depth += 1
        elif src[j] == "}":
            depth -= 1
            if depth == 0:
                return src[m.start() : j + 1]
    raise AssertionError(f"unbalanced braces in {name}")


# -- Python ports of the pure helpers (validated against the pinned JS) --


def extent_py(series_list, pick):
    lo, hi = math.inf, -math.inf
    for s in series_list:
        for d in s:
            x = pick(d)
            if isinstance(x, (int, float)) and math.isfinite(x):
                lo, hi = min(lo, x), max(hi, x)
    return [lo, hi] if lo <= hi else None


def nice_ticks_py(lo, hi, n):
    span = (hi - lo) or 1
    step = 10.0 ** math.floor(math.log10(span / n))
    err = span / n / step
    mult = 10 if err >= 7.5 else 5 if err >= 3.5 else 2 if err >= 1.5 else 1
    s = step * mult
    ticks = []
    v = math.ceil(lo / s) * s
    while v <= hi + 1e-9:
        ticks.append(v)
        v += s
    return ticks


# The pinned JS sources. If these pins fail, the JS helper changed:
# update the pin AND mirror the change in the Python port above (its
# golden tests below are the executable spec both implementations share).
PINNED_EXTENT = """function extent(seriesList, pick) {
  let lo = Infinity, hi = -Infinity;
  for (const s of seriesList)
    for (const d of s) {
      const x = pick(d);
      if (Number.isFinite(x)) { if (x < lo) lo = x; if (x > hi) hi = x; }
    }
  return lo <= hi ? [lo, hi] : null;
}"""

PINNED_NICE_TICKS = """function niceTicks(lo, hi, n) {
  const span = hi - lo || 1;
  const step = Math.pow(10, Math.floor(Math.log10(span / n)));
  const err = span / n / step;
  const mult = err >= 7.5 ? 10 : err >= 3.5 ? 5 : err >= 1.5 ? 2 : 1;
  const s = step * mult;
  const ticks = [];
  for (let v = Math.ceil(lo / s) * s; v <= hi + 1e-9; v += s) ticks.push(v);
  return ticks;
}"""


def test_app_js_is_structurally_sound():
    src = open(APP_JS).read()
    assert lint_js(src) == []


def test_lint_catches_injected_typos():
    """The lint must actually detect the failure class it guards: a
    dropped brace, an extra paren, an unclosed string/template."""
    src = open(APP_JS).read()
    assert lint_js(src.replace("function extent", "function extent)", 1))
    broken = src.replace("return lo <= hi ? [lo, hi] : null;\n}", "", 1)
    assert lint_js(broken)
    assert lint_js(src + "\nconst s = 'unterminated;\n")
    assert lint_js(src + "\nconst t = `no close ${1 + 2};\n")
    # valid constructs that must NOT false-positive (code-review r3:
    # braces inside template interpolations)
    assert lint_js("const x = `${fmt({a: 1})}`;") == []
    assert lint_js("const y = `a${list.map((v) => `${v}`).join({}['k'])}b`;") == []
    assert lint_js("const r = /a[{(]b/.test(s) ? 1 : 2;") == []


def test_helper_sources_match_pins():
    src = open(APP_JS).read()
    assert extract_function(src, "extent") == PINNED_EXTENT
    assert extract_function(src, "niceTicks") == PINNED_NICE_TICKS


def test_python_ports_golden_behavior():
    # extent: finite values only, across multiple series; empty -> None
    series = [[{"t": 1, "v": 5.0}, {"t": 2, "v": float("nan")}],
              [{"t": 3, "v": -2.0}]]
    assert extent_py(series, lambda d: d["v"]) == [-2.0, 5.0]
    assert extent_py(series, lambda d: d["t"]) == [1, 3]
    assert extent_py([[]], lambda d: d) is None

    # niceTicks: round steps covering [lo, hi], first tick >= lo
    ticks = nice_ticks_py(0.13, 9.9, 5)
    assert ticks == [2, 4, 6, 8]
    ticks = nice_ticks_py(0.0, 1.0, 4)
    assert ticks[0] == 0.0 and ticks[-1] <= 1.0 + 1e-9
    # spacing is uniform up to float accumulation (the JS accumulates
    # v += s the same way)
    assert all(
        abs((b - a) - (ticks[1] - ticks[0])) < 1e-9
        for a, b in zip(ticks, ticks[1:])
    )
    # degenerate span (lo == hi) must not divide by zero
    assert nice_ticks_py(3.0, 3.0, 5) != []
