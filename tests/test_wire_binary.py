"""ISSUE 18 — binary remote-write codec: frame/snappy negative paths,
codec negotiation on the shared POST route, the pre-read decoded-size
413 guard, striped batch appends, and the decode-pool half of the
shutdown drain (a push at shutdown is fully applied or cleanly 503'd,
never half-appended).

Every malformed payload here must come back as a clean 400/WireError —
a handler traceback (500) is a test failure, not a flavor of rejection.
"""

import json
import socket
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from foremast_tpu.ingest import (
    BINARY_CONTENT_TYPE,
    RingStore,
    WireError,
    decode_frame,
    encode_frame,
    parse_push,
    snappy_compress,
    snappy_decompress,
    start_ingest_server,
    stop_ingest_server,
)
from foremast_tpu.ingest.receiver import _DecodePool, _PoolClosed
from foremast_tpu.ingest.wire import snappy_uncompressed_len
from foremast_tpu.reactive import DirtySet

NOW = 1_760_000_000.0


def _entries(n_series=3, n_samples=4, base_ts=60):
    out = []
    for i in range(n_series):
        ts = np.arange(n_samples, dtype=np.int64) * 30 + base_ts + i
        vs = (np.arange(n_samples, dtype=np.float32) + i) * 0.5
        out.append((f'm{{app="a{i}"}}', ts, vs, 10.0 * i if i else None))
    return out


def _push(port, body, ctype="application/json", enc=None, path="/api/v1/write"):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}", data=body, method="POST"
    )
    req.add_header("Content-Type", ctype)
    if enc:
        req.add_header("Content-Encoding", enc)
    try:
        resp = urllib.request.urlopen(req, timeout=10)
        return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


# ---------------------------------------------------------------- frame codec


def test_frame_roundtrip_zero_copy():
    entries = _entries()
    buf = encode_frame(entries)
    out = decode_frame(buf)
    assert len(out) == len(entries)
    for (k0, t0, v0, s0), (k1, t1, v1, s1) in zip(entries, out):
        assert k1 == k0
        np.testing.assert_array_equal(t1, t0)
        np.testing.assert_array_equal(v1, v0.astype(np.float32))
        assert s1 == s0
        # zero-copy contract: the decoded arrays are views over the
        # frame buffer, not materialized copies
        assert t1.base is not None and v1.base is not None
    # empty frame is legal (a heartbeat push)
    assert decode_frame(encode_frame([])) == []


def test_frame_interning_and_canonicalization():
    # non-canonical spelling: label order + whitespace normalize once at
    # intern-miss, then every repeat frame hits the cache
    entries = [('m{ b="2", a="1" }', np.array([60], np.int64),
                np.array([1.0], np.float32), None)]
    cache: dict = {}
    out1 = decode_frame(encode_frame(entries), cache, canonicalize=True)
    out2 = decode_frame(encode_frame(entries), cache, canonicalize=True)
    assert out1[0][0] == 'm{a="1",b="2"}'
    assert out2[0][0] is out1[0][0]  # same interned str object
    assert len(cache) == 1


@pytest.mark.parametrize(
    "mangle, reason_match",
    [
        (lambda b: b[:20], "shorter than its 32-byte header"),
        (lambda b: b"XXXX" + b[4:], "bad frame magic"),
        (lambda b: b[:4] + b"\x09" + b[5:], "unsupported frame version"),
        (lambda b: b[:5] + b"\x01" + b[6:], "reserved"),
        (lambda b: b[:-3], "length mismatch"),
        (lambda b: b + b"\x00\x00", "length mismatch"),
    ],
)
def test_frame_truncations_and_header_damage(mangle, reason_match):
    buf = encode_frame(_entries())
    with pytest.raises(WireError, match=reason_match):
        decode_frame(mangle(buf))


def test_frame_internal_inconsistencies():
    buf = bytearray(encode_frame(_entries(n_series=2, n_samples=3)))
    # n_samples in the header no longer matches the counts section (the
    # frame_len check fires first — sections are sized from the header)
    bad = bytearray(buf)
    bad[12:20] = (7).to_bytes(8, "little")
    with pytest.raises(WireError):
        decode_frame(bytes(bad))
    # corrupt a counts entry so counts.sum() != n_samples
    n_samples = int.from_bytes(buf[12:20], "little")
    off = 32 + 8 * n_samples + 8 * 2 + 4 * n_samples  # counts offset
    bad = bytearray(buf)
    bad[off : off + 4] = (99).to_bytes(4, "little")
    with pytest.raises(WireError, match="counts do not sum"):
        decode_frame(bytes(bad))


def test_frame_rejects_nonfinite_values():
    for poison in (np.nan, np.inf, -np.inf):
        entries = [("m", np.array([60, 90], np.int64),
                    np.array([1.0, poison], np.float32), None)]
        with pytest.raises(WireError, match="non-finite"):
            decode_frame(encode_frame(entries))


def test_frame_rejects_out_of_order_timestamps():
    entries = [("m", np.array([120, 60], np.int64),
                np.array([1.0, 2.0], np.float32), None)]
    with pytest.raises(WireError, match="out-of-order"):
        decode_frame(encode_frame(entries))
    # duplicates are NOT out of order (last-write-wins merge path), and
    # time may reset between series (per-series order only)
    ok = [
        ("a", np.array([60, 60, 90], np.int64),
         np.array([1, 2, 3], np.float32), None),
        ("b", np.array([30], np.int64), np.array([4], np.float32), None),
    ]
    assert len(decode_frame(encode_frame(ok))) == 2


def test_frame_rejects_invalid_utf8_key():
    buf = bytearray(encode_frame([("mm", np.array([60], np.int64),
                                   np.array([1.0], np.float32), None)]))
    buf[-2:] = b"\xff\xfe"  # key blob is the final section
    with pytest.raises(WireError, match="not valid utf-8"):
        decode_frame(bytes(buf))


def test_json_parse_push_negatives_match_binary_contract():
    # non-finite values are rejected by BOTH codecs (cross-codec parity)
    with pytest.raises(WireError, match="non-finite"):
        parse_push({"timeseries": [{"labels": {"__name__": "m"},
                                    "samples": [[60, float("nan")]]}]})
    # ... but out-of-order timestamps stay legal JSON: the compat codec
    # keeps accepting what it always accepted
    out = parse_push({"timeseries": [{"labels": {"__name__": "m"},
                                      "samples": [[120, 2.0], [60, 1.0]]}]})
    assert len(out) == 1 and len(out[0][1]) == 2


# -------------------------------------------------------------------- snappy


def test_snappy_roundtrip_and_rle():
    for payload in (b"", b"x", b"abc" * 40000, bytes(range(256)) * 7):
        assert snappy_decompress(snappy_compress(payload)) == payload
    # overlapping-copy RLE stream (offset < length), hand-built:
    # literal "ab" then a copy-1 of length 6 at offset 2 -> "abababab"
    stream = bytes([8]) + bytes([(2 - 1) << 2]) + b"ab" + bytes(
        [0b01 | ((6 - 4) << 2), 2]
    )
    assert snappy_decompress(stream) == b"abababab"


@pytest.mark.parametrize(
    "stream",
    [
        b"",  # no preamble
        b"\xff" * 11,  # unterminated varint
        bytes([5]) + bytes([(10 - 1) << 2]) + b"ab",  # literal overruns input
        bytes([4]) + bytes([0b01, 9]),  # copy offset beyond output
        bytes([9]) + bytes([(2 - 1) << 2]) + b"ab",  # declared len mismatch
    ],
)
def test_snappy_malformed_streams(stream):
    with pytest.raises(WireError):
        snappy_decompress(stream)


def test_snappy_max_len_guard():
    comp = snappy_compress(b"z" * 4096)
    assert snappy_uncompressed_len(comp) == 4096
    with pytest.raises(WireError, match="cap"):
        snappy_decompress(comp, max_len=1024)


# ----------------------------------------------------- striped batch appends


def test_push_batch_matches_sequential_push():
    seq, bat = RingStore(shards=4), RingStore(shards=4)
    entries = _entries(n_series=8, n_samples=16)
    for key, ts, vs, start in entries:
        seq.push(key, ts, vs, start=start)
    counts = bat.push_batch(entries)
    assert counts == [16] * 8
    assert seq.stats()["samples"] == bat.stats()["samples"]
    for key, ts, _vs, _start in entries:
        a = seq.query(key, int(ts[0]), int(ts[-1]), now=float(ts[-1]))
        b = bat.query(key, int(ts[0]), int(ts[-1]), now=float(ts[-1]))
        assert a[0] == b[0] == "hit"
        for x, y in zip(a[1:], b[1:]):
            np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


def test_push_many_journal_fires_in_apply_order():
    store = RingStore(shards=1)
    shard = store._shards[0]
    items = [(k, t, v, s, None) for k, t, v, s in _entries(n_series=4)]
    journaled = []
    counts = shard.push_many(
        items, journal=lambda key, *rest: journaled.append(key)
    )
    assert counts == [4] * 4
    assert journaled == [k for k, *_ in items]  # replay order == apply order
    assert shard.push_many([]) == []


# --------------------------------------------------------- HTTP negotiation


def test_receiver_binary_codec_negotiation_and_parity():
    entries = _entries(n_series=2, n_samples=3, base_ts=60)
    frame = encode_frame(entries)
    js = json.dumps(
        {
            "timeseries": [
                {
                    "labels": {"__name__": "m", "app": f"a{i}"},
                    "samples": [[int(t), float(v)] for t, v in zip(ts, vs)],
                    **({"start": start} if start is not None else {}),
                }
                for i, (_k, ts, vs, start) in enumerate(entries)
            ]
        }
    ).encode()
    store = RingStore(shards=2)
    dirty = DirtySet(max_keys=1024)
    srv, _ = start_ingest_server(0, store, host="127.0.0.1", dirty=dirty)
    try:
        port = srv.server_address[1]
        code, out = _push(port, frame, ctype=BINARY_CONTENT_TYPE)
        assert (code, out["accepted_samples"], out["series"]) == (200, 6, 2)
        # snappy rides on either codec
        code, out2 = _push(
            port, snappy_compress(frame), ctype=BINARY_CONTENT_TYPE,
            enc="snappy",
        )
        assert (code, out2) == (200, out)
        code, out3 = _push(port, snappy_compress(js), enc="snappy")
        assert (code, out3["accepted_samples"]) == (200, 6)
        # dirty-set marks are codec-independent (route key = app label)
        marked = {k for k, _stamp in dirty.take_all()}
        assert {"a0", "a1"} <= marked
        # per-codec, per-stage wire stats surface in /debug/state
        state = json.loads(
            urllib.request.urlopen(
                f"http://127.0.0.1:{port}/debug/state", timeout=10
            ).read()
        )
        for codec in ("json", "binary"):
            w = state["wire"][codec]
            assert w["requests"] >= 1 and w["samples"] >= 6
            assert set(w["stage_seconds"]) == {
                "read", "decompress", "decode", "apply"
            }
        # unsupported Content-Encoding → 400 before any body parse
        code, out = _push(port, frame, ctype=BINARY_CONTENT_TYPE, enc="gzip")
        assert code == 400 and "Content-Encoding" in out["reason"]
    finally:
        stop_ingest_server(srv)


@pytest.mark.parametrize(
    "body_fn, enc",
    [
        (lambda f: f[:20], None),  # truncated header
        (lambda f: f[:-5], None),  # truncated sections
        (lambda f: b"XXXX" + f[4:], None),  # bad magic
        (lambda f: bytes([200]) + b"\x00garbage", "snappy"),  # bad snappy
        (lambda f: snappy_compress(f)[:-3], "snappy"),  # truncated snappy
    ],
)
def test_receiver_binary_negatives_are_clean_400(body_fn, enc):
    """Malformed binary payloads answer 400 with a reason — never a 500
    (which would mean a traceback escaped the codec's own checks)."""
    frame = encode_frame(_entries())
    store = RingStore(shards=1)
    srv, _ = start_ingest_server(0, store, host="127.0.0.1")
    try:
        port = srv.server_address[1]
        code, out = _push(
            port, body_fn(frame), ctype=BINARY_CONTENT_TYPE, enc=enc
        )
        assert code == 400 and out["reason"]
        assert store.stats()["samples"] == 0
        # out-of-order inside a binary frame: 400, with the JSON-codec
        # escape hatch named in the reason
        bad = encode_frame(
            [("m", np.array([120, 60], np.int64),
              np.array([1, 2], np.float32), None)]
        )
        code, out = _push(port, bad, ctype=BINARY_CONTENT_TYPE)
        assert code == 400 and "out-of-order" in out["reason"]
        # NaN via JSON: same 400 contract on the compat codec
        code, out = _push(
            port,
            b'{"timeseries": [{"labels": {"__name__": "m"},'
            b' "samples": [[60, NaN]]}]}',
        )
        assert code == 400
        # receiver still healthy afterwards
        good = encode_frame(_entries(n_series=1, n_samples=2))
        code, out = _push(port, good, ctype=BINARY_CONTENT_TYPE)
        assert (code, out["accepted_samples"]) == (200, 2)
    finally:
        stop_ingest_server(srv)


# ------------------------------------------------- pre-read 413 bomb guard


def _raw_post_expect(port, headers: dict, payload: bytes) -> int:
    """POST with a Content-Length larger than what we actually send —
    the status can only come back if the receiver answered BEFORE
    reading the full declared body (the no-buffering guard)."""
    with socket.create_connection(("127.0.0.1", port), timeout=10) as s:
        head = "POST /api/v1/write HTTP/1.1\r\nHost: x\r\n" + "".join(
            f"{k}: {v}\r\n" for k, v in headers.items()
        ) + "\r\n"
        s.sendall(head.encode() + payload)
        s.settimeout(10)
        status = s.recv(4096).split(b"\r\n", 1)[0]
        return int(status.split()[1])


def test_binary_413_from_frame_header_before_read():
    store = RingStore(shards=1)
    srv, _ = start_ingest_server(
        0, store, host="127.0.0.1", max_decoded_bytes=4096,
        max_body_bytes=8 << 20,
    )
    try:
        port = srv.server_address[1]
        # a frame header declaring 1 MiB decoded, but we transmit ONLY
        # the 32 header bytes of the claimed 1 MiB body: a 413 proves
        # the guard fired off the peek, without buffering the body
        declared = 1 << 20
        header = (
            b"FMW1" + bytes((1, 0, 0, 0))
            + (1).to_bytes(4, "little") + (100).to_bytes(8, "little")
            + (10).to_bytes(4, "little") + declared.to_bytes(8, "little")
        )
        code = _raw_post_expect(
            port,
            {"Content-Type": BINARY_CONTENT_TYPE,
             "Content-Length": str(declared)},
            header,
        )
        assert code == 413
        # snappy bomb: a TINY body whose varint preamble declares
        # 256 MiB decoded — 413 off the preamble, before decompressing
        bomb = bytes([0x80, 0x80, 0x80, 0x80, 0x01]) + b"\x00\x00"  # 2**28
        code, out = _push(port, bomb, enc="snappy")
        assert code == 413 and "declared decoded size" in out["reason"]
        assert store.stats()["samples"] == 0
        # an honest small frame still lands afterwards
        frame = encode_frame(_entries(n_series=1, n_samples=2))
        code, out = _push(port, frame, ctype=BINARY_CONTENT_TYPE)
        assert (code, out["accepted_samples"]) == (200, 2)
    finally:
        stop_ingest_server(srv)


# ------------------------------------------------------ shutdown drain


class _SlowApplyStore(RingStore):
    """RingStore whose batch apply stalls long enough for the test to
    land a shutdown mid-decode."""

    def __init__(self, *a, delay=0.4, **kw):
        super().__init__(*a, **kw)
        self._delay = delay
        self.apply_started = threading.Event()

    def push_batch(self, entries, **kw):
        self.apply_started.set()
        time.sleep(self._delay)
        return super().push_batch(entries, **kw)


def test_shutdown_drains_pooled_decode_never_half_applies():
    """ISSUE 18 satellite: a binary push that is mid-decode when
    stop_ingest_server runs is either FULLY applied (200, all samples
    queryable) or cleanly 503'd with nothing appended — the drain must
    wait for the pooled worker, not just the handler thread."""
    store = _SlowApplyStore(shards=2, delay=0.4)
    srv, _ = start_ingest_server(0, store, host="127.0.0.1",
                                 decode_workers=2)
    port = srv.server_address[1]
    frame = encode_frame(_entries(n_series=3, n_samples=5))
    result: dict = {}

    def pusher():
        result["resp"] = _push(port, frame, ctype=BINARY_CONTENT_TYPE)

    t = threading.Thread(target=pusher)
    t.start()
    assert store.apply_started.wait(5.0)  # decode worker is mid-apply
    clean = stop_ingest_server(srv, drain_seconds=5.0)
    t.join(timeout=10)
    assert not t.is_alive()
    code, out = result["resp"]
    assert clean is True
    if code == 200:
        assert out["accepted_samples"] == 15
        assert store.stats()["samples"] == 15
    else:  # cleanly shed: nothing half-appended
        assert code == 503
        assert store.stats()["samples"] == 0


def test_closed_pool_sheds_503_with_nothing_applied():
    store = RingStore(shards=1)
    srv, _ = start_ingest_server(0, store, host="127.0.0.1")
    try:
        port = srv.server_address[1]
        # simulate the drain window: pool already closed, socket still up
        srv._foremast_decode_pool.close(time.monotonic())
        code, out = _push(
            port, encode_frame(_entries()), ctype=BINARY_CONTENT_TYPE
        )
        assert code == 503 and "draining" in out["reason"]
        assert store.stats()["samples"] == 0
    finally:
        stop_ingest_server(srv)


def test_decode_pool_close_refuses_then_drains():
    pool = _DecodePool(workers=2)
    release = threading.Event()
    started = threading.Event()

    def job():
        started.set()
        release.wait(5.0)
        return "done"

    results = []
    t = threading.Thread(target=lambda: results.append(pool.run(job)))
    t.start()
    assert started.wait(5.0)
    closer = threading.Thread(
        target=lambda: results.append(
            ("clean", pool.close(time.monotonic() + 5.0))
        )
    )
    closer.start()
    # admission is refused the moment close begins ...
    with pytest.raises(_PoolClosed):
        pool.run(lambda: "late")
    # ... but the started job runs to completion and close reports clean
    release.set()
    t.join(timeout=5)
    closer.join(timeout=5)
    assert "done" in results and ("clean", True) in results
