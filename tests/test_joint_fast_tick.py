"""Joint (multi-alias) columnar fast-path coverage — ISSUE 4 tentpole.

Serial-vs-columnar equivalence for mixed univariate/joint claim sets:
once a joint doc's bivariate/LSTM-hybrid fit is cached, the warm tick
claims it onto the columnar path (`worker._judge_joint_fast` +
`MultivariateJudge.joint_columnar`, scoring from arena-resident state)
— and must produce the SAME statuses, anomaly payloads, store-write
set, fit-cache keys, and hook verdicts as the per-task object path on
identical claims.
"""

import dataclasses

import numpy as np
import pytest

from benchmarks.worker_bench import build_mixed_fleet
from foremast_tpu.config import BrainConfig
from foremast_tpu.jobs import (
    BrainWorker,
    STATUS_COMPLETED_UNHEALTH,
    STATUS_PREPROCESS_COMPLETED,
)

NOW = 1_760_000_000.0
HIST_LEN = 256
CUR_LEN = 30
SERVICES = 12  # 2 joint (1 bivariate + 1 lstm) + 10 single-alias


def _mk_worker(joint_fast: bool, hook=None, services: int = SERVICES,
               algorithm: str = "auto", joint_frac: float = 0.17):
    store, source, windows = build_mixed_fleet(
        services, HIST_LEN, CUR_LEN, NOW, joint_frac=joint_frac
    )
    cfg = BrainConfig(algorithm=algorithm, season_steps=24,
                      max_cache_size=4 * services + 64)
    # joint detectors read the base threshold; calibrate at 4 sigma like
    # the quality scenarios (2.0 would page on clean windows)
    cfg = dataclasses.replace(
        cfg, anomaly=dataclasses.replace(cfg.anomaly, threshold=4.0)
    )
    worker = BrainWorker(
        store, source, config=cfg, claim_limit=2 * services,
        worker_id="joint-w", on_verdict=hook,
    )
    worker.judge.lstm_steps = 10  # CI speed; identical on both workers
    if not joint_fast:
        worker._joint_fast = False
    return worker, store, source, windows


def _statuses(store):
    return {
        d.id: (d.status, d.reason, d.anomaly_info)
        for d in store._docs.values()
    }


def _record_writes(store):
    writes = []
    orig_update, orig_many = store.update, store.update_many

    def _u(doc):
        writes.append((doc.id, doc.status))
        return orig_update(doc)

    def _um(docs):
        writes.extend((d.id, d.status) for d in docs)
        return orig_many(docs)

    store.update, store.update_many = _u, _um
    return writes


def _spike_joint(source, sid: str, f: int):
    """Push every metric of a joint service up 0.6 (≈8 idio-sigmas) on
    the last 3 points — the quality scenarios' all-metric spike."""
    for m in range(f):
        url = f"http://prom/cur?q=m{m}:app{sid}&step=60"
        ct, cv = source.data[url]
        spiked = cv.copy()
        spiked[-3:] += 0.6
        source.data[url] = (ct, spiked)


def test_joint_fast_path_engages_and_matches_object_path():
    """Tick 1 is cold (object path fits + caches joint models); tick 2
    must claim the joint docs onto the columnar path and produce the
    same statuses, anomaly_info, write set, and fit-cache keys the
    object path would."""
    verdicts_a, verdicts_b = {}, {}
    hook_a = lambda doc, vs: verdicts_a.setdefault(doc.id, []).append(vs)
    hook_b = lambda doc, vs: verdicts_b.setdefault(doc.id, []).append(vs)
    a, a_store, a_src, windows = _mk_worker(True, hook=hook_a)
    b, b_store, b_src, _ = _mk_worker(False, hook=hook_b)

    assert a.tick(now=NOW + 150) == SERVICES
    assert b.tick(now=NOW + 150) == SERVICES
    assert _statuses(a_store) == _statuses(b_store)
    assert a._fast_kinds["bivariate"] == 0  # cold tick: slow path only
    assert a._fast_kinds["lstm"] == 0

    # spike the lstm joint doc (sid 1, f=4) so anomaly pairs cross the
    # columnar path; the bivariate doc (sid 0) stays clean
    for src in (a_src, b_src):
        _spike_joint(src, "1", 4)

    writes_a = _record_writes(a_store)
    writes_b = _record_writes(b_store)
    assert a.tick(now=NOW + 200) == SERVICES
    assert b.tick(now=NOW + 200) == SERVICES
    sa, sb = _statuses(a_store), _statuses(b_store)
    assert sa == sb
    assert sa["job-1"][0] == STATUS_COMPLETED_UNHEALTH
    assert set(sa["job-1"][2]["values"]) == {"m0", "m1", "m2", "m3"}
    assert sa["job-0"][0] == STATUS_PREPROCESS_COMPLETED

    # the columnar worker actually took the joint fast path; the object
    # worker never did
    assert a._fast_kinds["bivariate"] == 1 and a._fast_kinds["lstm"] == 1
    assert b._fast_kinds["bivariate"] == 0 and b._fast_kinds["lstm"] == 0
    ja = a._mvj.joint_state_counters()
    assert ja["misses"] == 2 and ja["rows_live"] == 2

    # same write SET (the columnar path batches its update_many, so the
    # order differs; the persisted outcomes may not)
    assert sorted(writes_a) == sorted(writes_b)
    # same joint fit-cache key population
    assert set(a._mvj.cache._d) == set(b._mvj.cache._d)
    assert set(a._mvj.joint_meta._d) == set(b._mvj.joint_meta._d)

    # hook verdict parity on the warm tick for the joint docs: same
    # verdicts, pairs, FULL marginal bands, and pairwise evidence
    for doc_id in ("job-0", "job-1"):
        va, vb = verdicts_a[doc_id][-1], verdicts_b[doc_id][-1]
        assert len(va) == len(vb)
        for x, y in zip(va, vb):
            assert (x.alias, x.verdict, x.anomaly_pairs) == (
                y.alias, y.verdict, y.anomaly_pairs
            )
            np.testing.assert_array_equal(x.upper, y.upper)
            np.testing.assert_array_equal(x.lower, y.lower)
            assert (x.p_value, x.dist_differs) == (y.p_value, y.dist_differs)


def test_joint_admission_revalidates_by_identity():
    """A joint-cache version bump (unrelated churn) must not evict the
    admission cache: entries revalidate by identity and stay admitted."""
    a, a_store, _, _ = _mk_worker(True)
    a.tick(now=NOW + 150)
    a.tick(now=NOW + 160)
    assert len(a._jadmit) == 2  # both joint docs admitted
    token0 = {k: v[2] for k, v in a._jadmit.items()}
    jinfo0 = {k: v[1] for k, v in a._jadmit.items()}

    # unrelated churn: bump both cache versions without touching the
    # admitted entries
    a._mvj.cache.put(("unrelated",), (1,))
    a._mvj.joint_meta.put(("unrelated-meta",), (1,))
    a.tick(now=NOW + 170)
    assert len(a._jadmit) == 2
    for k in a._jadmit:
        assert a._jadmit[k][2] != token0[k]  # restamped
        assert a._jadmit[k][1] is jinfo0[k]  # jinfo NOT rebuilt
    counters = a._mvj.joint_state_counters()
    assert counters["hits"] >= 2  # tick 3 gathered, not re-scattered


def test_joint_fast_matches_slow_under_explicit_bivariate_algorithm():
    """ML_ALGORITHM=bivariate_normal: 2-alias docs ride the joint
    columnar path; the 1-alias docs fall to the univariate fallback
    (still columnar, kind=univariate)."""
    a, a_store, a_src, _ = _mk_worker(True, algorithm="bivariate_normal")
    b, b_store, b_src, _ = _mk_worker(False, algorithm="bivariate_normal")
    assert a.tick(now=NOW + 150) == SERVICES
    assert b.tick(now=NOW + 150) == SERVICES
    # off-ridge spike on the bivariate doc (sid 0): x up, y down
    for src in (a_src, b_src):
        u0 = "http://prom/cur?q=m0:app0&step=60"
        u1 = "http://prom/cur?q=m1:app0&step=60"
        ct, cv = src.data[u0]
        s = cv.copy()
        s[-2:] += 1.0
        src.data[u0] = (ct, s)
        ct, cv = src.data[u1]
        s = cv.copy()
        s[-2:] -= 1.0
        src.data[u1] = (ct, s)
    assert a.tick(now=NOW + 200) == SERVICES
    assert b.tick(now=NOW + 200) == SERVICES
    assert _statuses(a_store) == _statuses(b_store)
    assert _statuses(a_store)["job-0"][0] == STATUS_COMPLETED_UNHEALTH
    assert a._fast_kinds["bivariate"] == 1
    assert a._fast_kinds["univariate"] > 0


def test_joint_window_bucket_drift_demotes_to_slow_path():
    """A joint doc whose current-window bucket drifts from the fitted
    one must be refit on the slow path, not scored through the wrong
    compiled program — and the verdict must match the object path's."""
    a, a_store, a_src, _ = _mk_worker(True)
    b, b_store, b_src, _ = _mk_worker(False)
    assert a.tick(now=NOW + 150) == SERVICES
    assert b.tick(now=NOW + 150) == SERVICES
    # grow the lstm doc's current windows past the 32-bucket (33 > 32)
    for src in (a_src, b_src):
        for m in range(4):
            url = f"http://prom/cur?q=m{m}:app1&step=60"
            ct, cv = src.data[url]
            ct2 = np.concatenate([ct, ct[-1:] + 60 * np.arange(1, 4)])
            cv2 = np.concatenate([cv, cv[-3:]]).astype(np.float32)
            src.data[url] = (ct2, cv2)
    from foremast_tpu.chaos.degrade import REASON_DEMOTED

    demoted_before = a._degrade.stats.docs_snapshot().get(REASON_DEMOTED, 0)
    assert a.tick(now=NOW + 200) == SERVICES
    assert b.tick(now=NOW + 200) == SERVICES
    assert _statuses(a_store) == _statuses(b_store)
    # the drifted doc went through the slow path, not the lstm bucket
    assert a._fast_kinds["lstm"] == 0
    # ... and the demotion was COUNTED on the degraded-docs counter
    # (ISSUE 14 satellite: it used to ride the slow leftovers silently)
    demoted_after = a._degrade.stats.docs_snapshot().get(REASON_DEMOTED, 0)
    assert demoted_after == demoted_before + 1, (
        demoted_before, demoted_after,
    )


def test_joint_fast_disabled_by_env(monkeypatch):
    """FOREMAST_JOINT_COLUMNAR=0 restores the object-path routing."""
    monkeypatch.setenv("FOREMAST_JOINT_COLUMNAR", "0")
    a, _, _, _ = _mk_worker(True)
    assert not a._joint_fast
    a.tick(now=NOW + 150)
    a.tick(now=NOW + 200)
    assert a._fast_kinds["bivariate"] == 0 and a._fast_kinds["lstm"] == 0


def test_debug_state_carries_joint_counters():
    a, _, _, _ = _mk_worker(True)
    a.tick(now=NOW + 150)
    a.tick(now=NOW + 200)
    state = a.debug_state()
    assert state["fast_path_docs"]["bivariate"] == 1
    assert state["fast_path_docs"]["lstm"] == 1
    assert state["joint_arena"]["rows_live"] == 2


def test_worker_metrics_fast_docs_counter():
    from prometheus_client import CollectorRegistry

    from foremast_tpu.observe.gauges import WorkerMetrics

    reg = CollectorRegistry()
    a, a_store, a_src, _ = _mk_worker(True)
    a.metrics = WorkerMetrics(registry=reg)
    a.tick(now=NOW + 150)
    a.tick(now=NOW + 200)
    got = {
        s.labels["kind"]: s.value
        for fam in reg.collect()
        if fam.name == "foremast_worker_fast_docs"
        for s in fam.samples
        if s.name.endswith("_total")
    }
    assert got.get("bivariate") == 1.0
    assert got.get("lstm") == 1.0
    assert got.get("univariate", 0) >= 1.0


def test_lstm_mixed_window_buckets_merge_into_one_dispatch():
    """VERDICT r5 #10 satellite: lstm docs fitted at DIFFERENT window
    buckets score in ONE merged dispatch (padded to the widest bucket)
    on the fast path — and the merged program's flags match the object
    path exactly, spikes included."""
    from benchmarks.quality import draw_comoving

    hist_len, long_cur = 1280, 300  # buckets 32 (CUR_LEN) and 512
    verdicts_a = {}
    a, a_store, a_src, _ = _mk_worker(
        True, hook=lambda d, vs: verdicts_a.setdefault(d.id, []).append(vs),
        joint_frac=0.34,
    )
    b, b_store, b_src, _ = _mk_worker(False, joint_frac=0.34)

    # regenerate lstm service 3 with a long history + long current so
    # its fitted bucket is 512 while service 1 stays at 32
    t_now = int(NOW)
    ht = t_now - 86_400 * 7 + 60 * np.arange(HIST_LEN, dtype=np.int64)
    ht3 = ht[-1] - 60 * np.arange(hist_len, dtype=np.int64)[::-1]
    ct3 = ht[-1] + 60 + 60 * np.arange(long_cur, dtype=np.int64)
    r = np.random.default_rng(99)
    hist3 = draw_comoving(r, 1, 4, hist_len, 0)[0]
    cur3 = draw_comoving(r, 1, 4, long_cur, hist_len)[0]
    for src in (a_src, b_src):
        for m in range(4):
            src.data[f"http://prom/cur?q=m{m}:app3&step=60"] = (
                ct3, cur3[m].copy()
            )
            src.data[
                f"http://prom/hist?q=m{m}:app3&end={ht[-1] + 60}&step=60"
            ] = (ht3, hist3[m].copy())

    assert a.tick(now=NOW + 150) == SERVICES
    assert b.tick(now=NOW + 150) == SERVICES
    assert _statuses(a_store) == _statuses(b_store)

    # spike the SHORT-bucket lstm doc: its flags must decode correctly
    # out of the merged (wider) dispatch
    for src in (a_src, b_src):
        _spike_joint(src, "1", 4)
    assert a.tick(now=NOW + 200) == SERVICES
    assert b.tick(now=NOW + 200) == SERVICES
    sa = _statuses(a_store)
    assert sa == _statuses(b_store)
    assert sa["job-1"][0] == STATUS_COMPLETED_UNHEALTH
    assert sa["job-3"][0] == STATUS_PREPROCESS_COMPLETED
    # both lstm docs rode the columnar path (merged dispatch)
    assert a._fast_kinds["lstm"] == 2
    assert b._fast_kinds["lstm"] == 0
