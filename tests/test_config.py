"""Config env-parity tests (reference `foremast-brain.yaml:21-81`)."""

import numpy as np

from foremast_tpu.config import (
    AnomalyConfig,
    BrainConfig,
    MetricTypeRule,
    PAIRWISE_ANY,
)
from foremast_tpu.ops.anomaly import BOUND_BOTH, BOUND_UPPER


def test_defaults_match_deployed_values():
    cfg = BrainConfig()
    assert cfg.algorithm == "moving_average_all"
    assert cfg.anomaly.threshold == 2.0
    assert cfg.anomaly.bound == BOUND_UPPER
    assert cfg.max_stuck_seconds == 90.0
    assert cfg.pairwise.min_mann_white_points == 20
    assert cfg.pairwise.min_wilcoxon_points == 20
    assert cfg.pairwise.min_kruskal_points == 5
    # deployed per-type matrix rows (foremast-brain.yaml:32-73)
    assert cfg.anomaly.rule_for("error5xx").threshold == 2.0
    assert cfg.anomaly.rule_for("error4xx").threshold == 3.0
    assert cfg.anomaly.rule_for("latency").threshold == 10.0
    assert cfg.anomaly.rule_for("cpu").threshold == 5.0
    assert cfg.anomaly.rule_for("memory").threshold == 5.0


def test_from_env_indexed_metric_type_family():
    env = {
        "ML_ALGORITHM": "ewma",
        "threshold": "2.5",
        "bound": "1",
        "min_lower_bound": "0",
        "metric_type_threshold_count": "2",
        "metric_type0": "error5xx",
        "threshold0": "2",
        "bound0": "upper",
        "metric_type1": "latency",
        "threshold1": "10",
        "bound1": "both",
        "min_lower_bound1": "0.5",
        "ML_PAIRWISE_ALGORITHM": "any",
        "MIN_MANN_WHITE_DATA_POINTS": "15",
        "MAX_STUCK_IN_SECONDS": "120",
        "ES_ENDPOINT": "http://es:9200",
    }
    cfg = BrainConfig.from_env(env)
    assert cfg.algorithm == "ewma"
    assert cfg.anomaly.threshold == 2.5
    assert len(cfg.anomaly.rules) == 2
    lat = cfg.anomaly.rule_for("latency")
    assert lat.threshold == 10.0 and lat.bound == BOUND_BOTH
    assert lat.min_lower_bound == 0.5
    # unknown type falls back to globals
    unk = cfg.anomaly.rule_for("tps")
    assert unk.threshold == 2.5
    assert cfg.pairwise.algorithm == PAIRWISE_ANY
    assert cfg.pairwise.min_mann_white_points == 15
    assert cfg.max_stuck_seconds == 120.0
    assert cfg.es_endpoint == "http://es:9200"


def test_gather_builds_dense_vectors():
    ac = AnomalyConfig(
        rules=(MetricTypeRule("latency", 10.0, BOUND_BOTH, 0.25),)
    )
    thr, bound, mlb = ac.gather(["latency", None, "cpu"])
    np.testing.assert_allclose(thr, [10.0, 2.0, 2.0])
    np.testing.assert_array_equal(bound, [BOUND_BOTH, BOUND_UPPER, BOUND_UPPER])
    np.testing.assert_allclose(mlb, [0.25, 0.0, 0.0])


def test_from_env_friedman_round_trip():
    """FRIEDMAN (design.md:90-93's fourth pairwise algorithm) selects and
    gates from env like the other three."""
    from foremast_tpu.config import PAIRWISE_FRIEDMAN

    cfg = BrainConfig.from_env(
        {
            "ML_PAIRWISE_ALGORITHM": "friedman",
            "MIN_FRIEDMAN_DATA_POINTS": "12",
            "ML_SEASON_STEPS": "288",
        }
    )
    assert cfg.pairwise.algorithm == PAIRWISE_FRIEDMAN
    assert cfg.pairwise.min_friedman_points == 12
    assert cfg.season_steps == 288
    # defaults: daily season, Wilcoxon-like Friedman gate
    d = BrainConfig.from_env({})
    assert d.season_steps == 1440
    assert d.pairwise.min_friedman_points == 20
