"""Dashboard-plane tests: panel config generation + server surface."""

import asyncio
import json

from aiohttp.test_utils import TestClient, TestServer

from foremast_tpu.observe.gauges import _san
from foremast_tpu.ui.app import make_app, render_index
from foremast_tpu.ui.metrics import DEFAULT_PANELS, Panel, dashboard_config


def test_panel_series_names_match_engine_gauges():
    """The dashboard must chart exactly the series names BrainGauges
    exports — derived through the same sanitizer."""
    p = Panel("namespace_app_per_pod:http_server_requests_latency", "Latency")
    series = p.series("ns1", "app1")
    types = [s["type"] for s in series]
    assert types == ["base", "upper", "lower", "anomaly"]
    g = "foremastbrain_" + _san(p.metric)
    assert series[1]["name"] == f"{g}_upper"
    assert series[2]["name"] == f"{g}_lower"
    assert series[3]["name"] == f"{g}_anomaly"
    # base selects namespace/app; gauges select exported_namespace/app
    assert 'namespace="ns1"' in series[0]["query"]
    assert 'exported_namespace="ns1"' in series[1]["query"]


def test_dashboard_config_shape():
    cfg = dashboard_config("http://svc:8099/", namespace="n", app="a")
    assert cfg["serviceEndpoint"] == "http://svc:8099"  # trailing / stripped
    assert cfg["pollSeconds"] == 15  # reference App.js:20,78
    assert cfg["stepSeconds"] == 15
    assert len(cfg["panels"]) == len(DEFAULT_PANELS)
    for panel in cfg["panels"]:
        assert {"metric", "commonName", "scale", "unit", "series"} <= set(panel)


def test_render_index_injects_config():
    cfg = dashboard_config("http://svc:8099")
    html = render_index(cfg)
    assert "__CONFIG__" not in html
    # the blob must be parseable JSON exactly as injected
    start = html.index("window.FOREMAST_CONFIG = ") + len("window.FOREMAST_CONFIG = ")
    end = html.index(";</script>", start)
    assert json.loads(html[start:end]) == cfg


def test_ui_server_serves_index_config_and_static():
    async def main():
        app = make_app(service_endpoint="http://svc:8099", namespace="n", app_name="a")
        async with TestClient(TestServer(app)) as c:
            r = await c.get("/")
            assert r.status == 200
            body = await r.text()
            assert "FOREMAST_CONFIG" in body
            assert '"serviceEndpoint": "http://svc:8099"' in body
            r = await c.get("/config")
            assert (await r.json())["app"] == "a"
            for path in ("/static/app.js", "/static/style.css"):
                r = await c.get(path)
                assert r.status == 200, path
            r = await c.get("/healthz")
            assert (await r.json()) == {"ok": True}

    asyncio.run(main())


def test_demo_mode_serves_synthetic_query_range():
    async def main():
        app = make_app(demo=True)
        async with TestClient(TestServer(app)) as c:
            r = await c.get("/config")
            assert (await r.json())["serviceEndpoint"] == ""  # same-origin
            r = await c.get(
                "/api/v1/query_range",
                params={"query": "namespace_app_per_pod:http_server_requests_latency"
                        '{namespace="n",app="a"}',
                        "start": "0", "end": "600", "step": "15"},
            )
            body = await r.json()
            assert body["status"] == "success"
            values = body["data"]["result"][0]["values"]
            assert len(values) > 30
            # anomaly series returns only spike timestamps (sparse)
            r = await c.get(
                "/api/v1/query_range",
                params={"query": "foremastbrain_x_anomaly", "start": "0",
                        "end": "3600", "step": "15"},
            )
            body = await r.json()
            res = body["data"]["result"]
            assert res and len(res[0]["values"]) < 10

    asyncio.run(main())
