"""Dashboard-plane tests: panel config generation + server surface."""

import asyncio
import json

from aiohttp.test_utils import TestClient, TestServer

from foremast_tpu.observe.gauges import _san
from foremast_tpu.ui.app import make_app, render_index
from foremast_tpu.ui.metrics import DEFAULT_PANELS, Panel, dashboard_config


def test_panel_series_names_match_engine_gauges():
    """The dashboard must chart exactly the series names BrainGauges
    exports — derived through the same sanitizer."""
    p = Panel("namespace_app_per_pod:http_server_requests_latency", "Latency")
    series = p.series("ns1", "app1")
    types = [s["type"] for s in series]
    assert types == ["base", "upper", "lower", "anomaly"]
    g = "foremastbrain_" + _san(p.metric)
    assert series[1]["name"] == f"{g}_upper"
    assert series[2]["name"] == f"{g}_lower"
    assert series[3]["name"] == f"{g}_anomaly"
    # base selects namespace/app; gauges select exported_namespace/app
    assert 'namespace="ns1"' in series[0]["query"]
    assert 'exported_namespace="ns1"' in series[1]["query"]


def test_dashboard_config_shape():
    cfg = dashboard_config("http://svc:8099/", namespace="n", app="a")
    assert cfg["serviceEndpoint"] == "http://svc:8099"  # trailing / stripped
    assert cfg["pollSeconds"] == 15  # reference App.js:20,78
    assert cfg["stepSeconds"] == 15
    assert len(cfg["panels"]) == len(DEFAULT_PANELS)
    for panel in cfg["panels"]:
        assert {"metric", "commonName", "scale", "unit", "series"} <= set(panel)


def test_render_index_injects_config():
    cfg = dashboard_config("http://svc:8099")
    html = render_index(cfg)
    assert "__CONFIG__" not in html
    # the blob must be parseable JSON exactly as injected
    start = html.index("window.FOREMAST_CONFIG = ") + len("window.FOREMAST_CONFIG = ")
    end = html.index(";</script>", start)
    assert json.loads(html[start:end]) == cfg


def test_ui_server_serves_index_config_and_static():
    async def main():
        app = make_app(service_endpoint="http://svc:8099", namespace="n", app_name="a")
        async with TestClient(TestServer(app)) as c:
            r = await c.get("/")
            assert r.status == 200
            body = await r.text()
            assert "FOREMAST_CONFIG" in body
            assert '"serviceEndpoint": "http://svc:8099"' in body
            r = await c.get("/config")
            assert (await r.json())["app"] == "a"
            for path in ("/static/app.js", "/static/style.css"):
                r = await c.get(path)
                assert r.status == 200, path
            r = await c.get("/healthz")
            assert (await r.json()) == {"ok": True}

    asyncio.run(main())


def test_demo_mode_serves_synthetic_query_range():
    async def main():
        app = make_app(demo=True)
        async with TestClient(TestServer(app)) as c:
            r = await c.get("/config")
            assert (await r.json())["serviceEndpoint"] == ""  # same-origin
            r = await c.get(
                "/api/v1/query_range",
                params={"query": "namespace_app_per_pod:http_server_requests_latency"
                        '{namespace="n",app="a"}',
                        "start": "0", "end": "600", "step": "15"},
            )
            body = await r.json()
            assert body["status"] == "success"
            values = body["data"]["result"][0]["values"]
            assert len(values) > 30
            # anomaly series models the engine's STICKY gauge: present at
            # every scrape, its value changing only when a new spike lands
            r = await c.get(
                "/api/v1/query_range",
                params={"query": "foremastbrain_x_anomaly", "start": "0",
                        "end": "3600", "step": "15"},
            )
            body = await r.json()
            res = body["data"]["result"]
            values = res[0]["values"]
            assert len(values) > 30  # dense (sticky), not event-sparse
            assert len({v for _, v in values}) <= 4  # few distinct spikes

    asyncio.run(main())


# -- anomaly join (VERDICT r1 item 10: the join logic, executed) -------------


def test_anomaly_join_golden_trace_dots_land_on_base_points():
    """Feed the golden spike trace through the join: the sticky anomaly
    gauge repeats 40.134 after the spike; exactly the event timestamps
    survive, plotted at the MEASURED base value."""
    import csv
    import os

    from foremast_tpu.ui.join import join_anomalies

    data = os.path.join(os.path.dirname(os.path.abspath(__file__)), "data")
    rows = []
    with open(os.path.join(data, "demo_canary_spike.csv")) as f:
        for i, row in enumerate(csv.reader(f)):
            if row:
                rows.append((1_700_000_000 + 15.0 * i, float(row[1])))
    base = rows
    start = base[0][0] - 15.0
    # sticky gauge: holds the last anomalous value from each spike onward
    spikes = [(t, v) for t, v in base if v > 10.0]
    anomaly = []
    last = None
    for t, v in base:
        for st, sv in spikes:
            if st <= t:
                last = sv
        if last is not None:
            anomaly.append((t, last))

    joined = join_anomalies(base, anomaly, start, 15.0)
    base_by_t = dict(base)
    assert [t for t, _ in joined] == [t for t, _ in spikes]
    for t, v in joined:
        assert v == base_by_t[t], "dot must land on the measured curve"


def test_anomaly_join_left_edge_and_missing_base():
    from foremast_tpu.ui.join import anomaly_events, join_anomalies

    # a series already present at the window's left edge is an old sticky
    # value — not an event
    assert anomaly_events([(100.0, 5.0), (115.0, 5.0)], 100.0, 15.0) == []
    # value change mid-window IS an event
    assert anomaly_events(
        [(100.0, 5.0), (115.0, 5.0), (130.0, 7.0)], 100.0, 15.0
    ) == [(130.0, 7.0)]
    # appearance mid-window IS an event
    assert anomaly_events([(160.0, 5.0)], 100.0, 15.0) == [(160.0, 5.0)]
    # events without a matching base timestamp are dropped
    assert join_anomalies([(100.0, 1.0)], [(160.0, 5.0)], 100.0, 15.0) == []


def test_panel_endpoint_demo_mode_joins_anomalies_onto_base():
    """GET /api/v1/panel end-to-end in demo mode: the payload carries all
    four series plus anomalyJoined, every joined dot lying on the base
    series."""

    async def main():
        app = make_app(demo=True)
        async with TestClient(TestServer(app)) as c:
            r = await c.get("/api/v1/panel", params={"i": "0", "end": "7200"})
            assert r.status == 200
            data = await r.json()
            assert {"base", "upper", "lower", "anomaly", "anomalyJoined"} <= set(
                data
            )
            assert data["base"], "demo base series must not be empty"
            assert data["anomalyJoined"], "demo spikes must join"
            base_by_t = {d["t"]: d["v"] for d in data["base"]}
            for d in data["anomalyJoined"]:
                assert d["t"] in base_by_t
                assert d["v"] == base_by_t[d["t"]]
            # bad panel index is a 400, not a 500
            r = await c.get("/api/v1/panel", params={"i": "999"})
            assert r.status == 400

    asyncio.run(main())


def test_panel_endpoint_honors_window_and_rejects_negative_index():
    async def main():
        app = make_app(demo=True)
        async with TestClient(TestServer(app)) as c:
            r1 = await c.get(
                "/api/v1/panel",
                params={"i": "0", "end": "7200", "window": "900", "step": "15"},
            )
            d1 = await r1.json()
            ts = [d["t"] for d in d1["base"]]
            assert min(ts) >= 7200 - 900 - 15  # the preset window applies
            r = await c.get("/api/v1/panel", params={"i": "-1"})
            assert r.status == 400

    asyncio.run(main())
