"""foremast-check (foremast_tpu/analysis): fixtures per checker, the
suppression and baseline machinery, the env registry/docs contract, and
the tier-1 gate asserting the tree itself is clean.

Fixture snippets are analyzed as source strings through the same
`analyze_source` path the runner uses, so a checker regression that
stops catching its violation class fails here before it silently
green-lights the tree.
"""

from __future__ import annotations

import os
import textwrap

import pytest

from foremast_tpu.analysis import all_checkers, analyze_source, repo_root
from foremast_tpu.analysis.async_blocking import AsyncBlockingChecker
from foremast_tpu.analysis.core import (
    Baseline,
    Finding,
    analyze_modules,
    collect_modules,
)
from foremast_tpu.analysis.env_contract import (
    EnvContractChecker,
    check_env_docs,
    render_env_table,
)
from foremast_tpu.analysis.jit_hygiene import JitHygieneChecker
from foremast_tpu.analysis.lock_discipline import LockDisciplineChecker


def src(text: str) -> str:
    return textwrap.dedent(text)


# ---------------------------------------------------------------------------
# jit-hygiene
# ---------------------------------------------------------------------------

JIT_PATH = "foremast_tpu/engine/fixture.py"

JIT_BAD = src(
    '''
    import jax
    import jax.numpy as jnp
    import numpy as np
    from functools import partial

    @partial(jax.jit, static_argnames=("mode",))
    def score(values, threshold, mode=[]):
        if threshold > 1.0:
            values = values * 2
        return _peak(values)

    def _peak(values):
        top = values.max()
        return float(top) + np.asarray(values).sum() + top.item()
    '''
)

JIT_CLEAN = src(
    '''
    import jax
    import jax.numpy as jnp
    from functools import partial

    @partial(jax.jit, static_argnames=("algorithm",))
    def score(values, mask, algorithm="ma"):
        b, t_len = values.shape
        if algorithm == "ma" or t_len < 2:
            return jnp.mean(values)
        if mask is None:
            return jnp.mean(values)
        return _helper(values, float(t_len))

    def _helper(values, scale):
        return values * scale + float(scale)
    '''
)


def test_jit_hygiene_catches_each_violation_class():
    findings = analyze_source(JIT_BAD, JIT_PATH, [JitHygieneChecker()])
    messages = "\n".join(f.message for f in findings)
    assert "branches in Python on traced value `threshold`" in messages
    assert "`float()` on traced value" in messages
    assert "`np.asarray` materializes traced value" in messages
    assert "`.item()` on traced value" in messages
    assert "static arg `mode`" in messages and "unhashable" in messages
    assert all(f.rule == "jit-hygiene" for f in findings)
    assert len(findings) == 5


def test_jit_hygiene_taint_is_interprocedural_not_blanket():
    """`_helper` is only flagged because its caller passes traced data;
    the same helper fed static scalars stays clean (the `_design`
    false-positive class)."""
    findings = analyze_source(JIT_CLEAN, JIT_PATH, [JitHygieneChecker()])
    assert findings == []


def test_jit_hygiene_scope_is_engine_models_ops():
    checker = JitHygieneChecker()
    assert checker.applies_to("foremast_tpu/engine/scoring.py")
    assert checker.applies_to("foremast_tpu/models/seasonal.py")
    assert checker.applies_to("foremast_tpu/ops/forecasters.py")
    assert not checker.applies_to("foremast_tpu/service/app.py")
    # host-side code may branch on numpy values freely
    assert analyze_source(JIT_BAD, "foremast_tpu/jobs/fixture.py", [JitHygieneChecker()]) == []


def test_jit_hygiene_shape_branching_is_static():
    source = src(
        """
        import jax
        import jax.numpy as jnp

        @jax.jit
        def fit(values, mask):
            b, t_len = values.shape
            if t_len == 0:
                return jnp.zeros((b,))
            if len(values) > 4 and values.ndim == 2:
                return jnp.mean(values)
            return jnp.sum(values)
        """
    )
    assert analyze_source(source, JIT_PATH, [JitHygieneChecker()]) == []


def test_jit_hygiene_assignment_form_roots():
    source = src(
        """
        import jax
        from functools import partial

        def _decide(x, algorithm):
            if algorithm == "any":
                return x.sum()
            return x.item()

        decide = partial(jax.jit, static_argnames=("algorithm",))(_decide)
        """
    )
    findings = analyze_source(source, JIT_PATH, [JitHygieneChecker()])
    assert len(findings) == 1
    assert "`.item()`" in findings[0].message


# ---------------------------------------------------------------------------
# async-blocking
# ---------------------------------------------------------------------------

ASYNC_PATH = "foremast_tpu/service/fixture.py"

ASYNC_BAD = src(
    """
    import time
    import requests

    async def handler(request, store):
        time.sleep(1)
        requests.get("http://upstream")
        store.update(request)
        return open("/etc/hostname").read()
    """
)

ASYNC_CLEAN = src(
    """
    import asyncio
    import time

    async def handler(request, store):
        await asyncio.sleep(1)
        doc = await asyncio.to_thread(store.get, "id")

        def executor_target():
            time.sleep(1)

        return doc
    """
)


def test_async_blocking_catches_each_violation_class():
    findings = analyze_source(ASYNC_BAD, ASYNC_PATH, [AsyncBlockingChecker()])
    messages = "\n".join(f.message for f in findings)
    assert "`time.sleep(...)`" in messages
    assert "`requests.get(...)`" in messages
    assert "`store.update(...)`" in messages
    assert "`open()`" in messages
    assert len(findings) == 4


def test_async_blocking_permits_to_thread_and_nested_sync_defs():
    assert analyze_source(ASYNC_CLEAN, ASYNC_PATH, [AsyncBlockingChecker()]) == []


def test_async_blocking_ignores_sync_functions():
    source = src(
        """
        import time

        def poll_loop():
            time.sleep(5)
        """
    )
    assert analyze_source(source, ASYNC_PATH, [AsyncBlockingChecker()]) == []


# ---------------------------------------------------------------------------
# lock-discipline
# ---------------------------------------------------------------------------

LOCK_PATH = "foremast_tpu/jobs/fixture.py"

LOCK_BAD = src(
    """
    import threading

    class Box:
        def __init__(self):
            self._lock = threading.Lock()
            self._items = {}
            self.count = 0

        def put(self, key, value):
            with self._lock:
                self._items[key] = value
                self.count += 1

        def racy_get(self, key):
            return self._items.get(key)

        def racy_reset(self):
            self.count = 0
    """
)

LOCK_CLEAN = src(
    """
    import threading

    class Box:
        def __init__(self):
            self._lock = threading.Lock()
            self._items = {}
            self.limit = 8  # read-only config: never guarded

        def put(self, key, value):
            with self._lock:
                if len(self._items) < self.limit:
                    self._items[key] = value

        def get(self, key):
            with self._lock:
                return self._items.get(key)

        def describe(self):
            return f"box(limit={self.limit})"
    """
)


def test_lock_discipline_flags_unlocked_access():
    findings = analyze_source(LOCK_BAD, LOCK_PATH, [LockDisciplineChecker()])
    messages = "\n".join(f.message for f in findings)
    assert "unlocked read of `self._items` in `Box.racy_get`" in messages
    assert "unlocked write to `self.count` in `Box.racy_reset`" in messages
    assert len(findings) == 2


def test_lock_discipline_clean_class_and_readonly_config():
    assert analyze_source(LOCK_CLEAN, LOCK_PATH, [LockDisciplineChecker()]) == []


def test_lock_discipline_module_level_globals():
    source = src(
        """
        import threading

        _lock = threading.Lock()
        _cache = None

        def load():
            global _cache
            with _lock:
                if _cache is None:
                    _cache = object()
                return _cache

        def racy_invalidate():
            global _cache
            _cache = None
        """
    )
    findings = analyze_source(source, LOCK_PATH, [LockDisciplineChecker()])
    assert len(findings) == 1
    assert "module global `_cache` in `racy_invalidate`" in findings[0].message


def test_lock_discipline_nested_def_does_not_inherit_lock():
    source = src(
        """
        import threading

        class Box:
            def __init__(self):
                self._lock = threading.Lock()
                self._flag = False

            def arm(self):
                with self._lock:
                    self._flag = True

                    def later():
                        self._flag = False

                    return later
        """
    )
    findings = analyze_source(source, LOCK_PATH, [LockDisciplineChecker()])
    assert len(findings) == 1
    assert "unlocked write to `self._flag`" in findings[0].message


def test_lock_discipline_flags_runtime_env_writes():
    source = src(
        """
        import os

        def adopt(knobs):
            os.environ["FOREMAST_ARENA_BYTES"] = str(knobs[0])
        """
    )
    findings = analyze_source(source, LOCK_PATH, [LockDisciplineChecker()])
    assert len(findings) == 1
    assert "mutates process env at runtime" in findings[0].message


def test_lock_discipline_wsgi_environ_dict_is_not_process_env():
    source = src(
        """
        def app(environ, start_response):
            environ["HTTP_X"] = "1"
            return environ.get("PATH_INFO", "/")
        """
    )
    assert analyze_source(source, LOCK_PATH, [LockDisciplineChecker()]) == []


# ---------------------------------------------------------------------------
# env-contract
# ---------------------------------------------------------------------------

ENV_PATH = "foremast_tpu/engine/fixture_env.py"


def env_checker() -> EnvContractChecker:
    return EnvContractChecker(names=frozenset({"GOOD_KNOB"}))


def test_env_contract_flags_unregistered_and_dynamic_reads():
    source = src(
        """
        import os

        def configure(name):
            a = os.environ.get("GOOD_KNOB")
            b = os.environ.get("BAD_KNOB", "1")
            c = os.environ["ALSO_BAD"]
            d = os.environ.get(name)
            return a, b, c, d
        """
    )
    findings = analyze_source(source, ENV_PATH, [env_checker()])
    messages = "\n".join(f.message for f in findings)
    assert "'BAD_KNOB'" in messages
    assert "'ALSO_BAD'" in messages
    assert "computed name" in messages
    assert "GOOD_KNOB" not in messages
    assert len(findings) == 3


def test_env_contract_exempts_config_and_wsgi_dicts():
    source = 'import os\nx = os.environ.get("ANYTHING")\n'
    assert analyze_source(source, "foremast_tpu/config.py", [env_checker()]) == []
    wsgi = src(
        """
        def app(environ, start_response):
            return environ.get("PATH_INFO")
        """
    )
    assert analyze_source(wsgi, ENV_PATH, [env_checker()]) == []


def test_env_contract_from_import_alias_counts():
    source = src(
        """
        from os import environ

        def f():
            return environ.get("BAD_KNOB"), environ["WORSE"]
        """
    )
    findings = analyze_source(source, ENV_PATH, [env_checker()])
    assert len(findings) == 2


def test_registry_names_unique_and_real():
    from foremast_tpu.config import ENV_KNOBS

    names = [k.name for k in ENV_KNOBS]
    assert len(names) == len(set(names))
    for knob in ENV_KNOBS:
        assert knob.description
        assert knob.group in ("engine", "framework", "deploy")


def test_env_overrides_enumerates_set_knobs(monkeypatch):
    from foremast_tpu.config import env_overrides

    monkeypatch.setenv("FOREMAST_ARENA_BYTES", "4096")
    monkeypatch.delenv("FOREMAST_BF16_DELTA", raising=False)
    over = env_overrides()
    assert over["FOREMAST_ARENA_BYTES"] == "4096"
    assert "FOREMAST_BF16_DELTA" not in over


def test_env_docs_block_in_sync_with_registry():
    assert check_env_docs(repo_root()) == []
    # and the renderer output actually lives in the committed file
    with open(os.path.join(repo_root(), "docs", "operations.md")) as f:
        assert render_env_table() in f.read()


# ---------------------------------------------------------------------------
# suppressions
# ---------------------------------------------------------------------------


def test_suppression_same_line_by_rule():
    source = src(
        """
        import time

        async def handler(request):
            time.sleep(1)  # foremast: ignore[async-blocking]
        """
    )
    assert analyze_source(source, ASYNC_PATH, [AsyncBlockingChecker()]) == []


def test_suppression_bare_and_comment_line_above():
    source = src(
        """
        import time

        async def handler(request):
            # foremast: ignore
            time.sleep(1)
        """
    )
    assert analyze_source(source, ASYNC_PATH, [AsyncBlockingChecker()]) == []


def test_suppression_wrong_rule_does_not_apply():
    source = src(
        """
        import time

        async def handler(request):
            time.sleep(1)  # foremast: ignore[jit-hygiene]
        """
    )
    findings = analyze_source(source, ASYNC_PATH, [AsyncBlockingChecker()])
    assert len(findings) == 1


def test_suppression_on_other_statement_does_not_leak_down():
    source = src(
        """
        import time

        async def handler(request):
            x = 1  # foremast: ignore[async-blocking]
            time.sleep(1)
        """
    )
    findings = analyze_source(source, ASYNC_PATH, [AsyncBlockingChecker()])
    assert len(findings) == 1


# ---------------------------------------------------------------------------
# baseline round-trip
# ---------------------------------------------------------------------------


def test_baseline_round_trip_and_staleness(tmp_path):
    findings = analyze_source(ASYNC_BAD, ASYNC_PATH, [AsyncBlockingChecker()])
    assert findings
    path = str(tmp_path / "baseline.json")
    Baseline.from_findings(findings).save(path)
    loaded = Baseline.load(path)
    new, grandfathered = loaded.split(findings)
    assert new == [] and len(grandfathered) == len(findings)
    assert loaded.stale(findings) == []
    # a paid-off finding shows as stale; a brand-new one is not masked
    subset = findings[1:]
    assert len(loaded.stale(subset)) == 1
    extra = Finding(
        rule="async-blocking", path=ASYNC_PATH, line=99, message="novel"
    )
    new, _ = loaded.split([*findings, extra])
    assert new == [extra]


def test_baseline_fingerprint_is_line_independent():
    a = Finding(rule="r", path="p.py", line=10, message="m")
    b = Finding(rule="r", path="p.py", line=99, message="m")
    assert a.fingerprint() == b.fingerprint()
    assert a.fingerprint() != Finding(
        rule="r", path="p.py", line=10, message="other"
    ).fingerprint()


def test_missing_baseline_means_empty():
    assert Baseline.load("/nonexistent/baseline.json").entries == []


# ---------------------------------------------------------------------------
# the gate: the tree itself is clean (tier-1)
# ---------------------------------------------------------------------------


def test_tree_clean_against_committed_baseline():
    """`python -m foremast_tpu.analysis` exits 0 on this tree: every
    AST checker over the whole package, the env-docs sync contract, and
    the committed (empty-or-shrinking) baseline."""
    root = repo_root()
    modules = collect_modules(root)
    findings = analyze_modules(modules, all_checkers())
    findings.extend(check_env_docs(root))
    baseline = Baseline.load(os.path.join(root, "analysis_baseline.json"))
    new, _ = baseline.split(findings)
    assert new == [], "\n" + "\n".join(f.render() for f in new)


def test_runner_exit_codes(tmp_path, capsys):
    from foremast_tpu.analysis.__main__ import main

    bad = tmp_path / "fixture_bad.py"
    bad.write_text(ASYNC_BAD)
    assert main([str(bad)]) == 1
    out = capsys.readouterr().out
    assert "async-blocking" in out and "new finding" in out

    clean = tmp_path / "fixture_clean.py"
    clean.write_text(ASYNC_CLEAN)
    assert main([str(clean)]) == 0
    assert "clean" in capsys.readouterr().out


def test_runner_folds_in_metrics_lint():
    from foremast_tpu.analysis.__main__ import metrics_lint_findings

    assert metrics_lint_findings() == []


@pytest.mark.slow
def test_runner_cli_subprocess_gate():
    """The exact command `make check` runs, end to end."""
    import subprocess
    import sys

    proc = subprocess.run(
        [sys.executable, "-m", "foremast_tpu.analysis"],
        capture_output=True,
        text=True,
        cwd=repo_root(),
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
        timeout=300,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
