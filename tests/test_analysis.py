"""foremast-check (foremast_tpu/analysis): fixtures per checker, the
suppression and baseline machinery, the env registry/docs contract, and
the tier-1 gate asserting the tree itself is clean.

Fixture snippets are analyzed as source strings through the same
`analyze_source` path the runner uses, so a checker regression that
stops catching its violation class fails here before it silently
green-lights the tree.
"""

from __future__ import annotations

import os
import textwrap

import pytest

from foremast_tpu.analysis import all_checkers, analyze_source, repo_root
from foremast_tpu.analysis.async_blocking import AsyncBlockingChecker
from foremast_tpu.analysis.core import (
    Baseline,
    Finding,
    analyze_modules,
    collect_modules,
)
from foremast_tpu.analysis.env_contract import (
    EnvContractChecker,
    check_env_docs,
    render_env_table,
)
from foremast_tpu.analysis.jit_hygiene import JitHygieneChecker
from foremast_tpu.analysis.lock_discipline import LockDisciplineChecker


def src(text: str) -> str:
    return textwrap.dedent(text)


# ---------------------------------------------------------------------------
# jit-hygiene
# ---------------------------------------------------------------------------

JIT_PATH = "foremast_tpu/engine/fixture.py"

JIT_BAD = src(
    '''
    import jax
    import jax.numpy as jnp
    import numpy as np
    from functools import partial

    @partial(jax.jit, static_argnames=("mode",))
    def score(values, threshold, mode=[]):
        if threshold > 1.0:
            values = values * 2
        return _peak(values)

    def _peak(values):
        top = values.max()
        return float(top) + np.asarray(values).sum() + top.item()
    '''
)

JIT_CLEAN = src(
    '''
    import jax
    import jax.numpy as jnp
    from functools import partial

    @partial(jax.jit, static_argnames=("algorithm",))
    def score(values, mask, algorithm="ma"):
        b, t_len = values.shape
        if algorithm == "ma" or t_len < 2:
            return jnp.mean(values)
        if mask is None:
            return jnp.mean(values)
        return _helper(values, float(t_len))

    def _helper(values, scale):
        return values * scale + float(scale)
    '''
)


def test_jit_hygiene_catches_each_violation_class():
    findings = analyze_source(JIT_BAD, JIT_PATH, [JitHygieneChecker()])
    messages = "\n".join(f.message for f in findings)
    assert "branches in Python on traced value `threshold`" in messages
    assert "`float()` on traced value" in messages
    assert "`np.asarray` materializes traced value" in messages
    assert "`.item()` on traced value" in messages
    assert "static arg `mode`" in messages and "unhashable" in messages
    assert all(f.rule == "jit-hygiene" for f in findings)
    assert len(findings) == 5


def test_jit_hygiene_taint_is_interprocedural_not_blanket():
    """`_helper` is only flagged because its caller passes traced data;
    the same helper fed static scalars stays clean (the `_design`
    false-positive class)."""
    findings = analyze_source(JIT_CLEAN, JIT_PATH, [JitHygieneChecker()])
    assert findings == []


def test_jit_hygiene_scope_is_engine_models_ops():
    checker = JitHygieneChecker()
    assert checker.applies_to("foremast_tpu/engine/scoring.py")
    assert checker.applies_to("foremast_tpu/models/seasonal.py")
    assert checker.applies_to("foremast_tpu/ops/forecasters.py")
    assert not checker.applies_to("foremast_tpu/service/app.py")
    # host-side code may branch on numpy values freely
    assert analyze_source(JIT_BAD, "foremast_tpu/jobs/fixture.py", [JitHygieneChecker()]) == []


def test_jit_hygiene_shape_branching_is_static():
    source = src(
        """
        import jax
        import jax.numpy as jnp

        @jax.jit
        def fit(values, mask):
            b, t_len = values.shape
            if t_len == 0:
                return jnp.zeros((b,))
            if len(values) > 4 and values.ndim == 2:
                return jnp.mean(values)
            return jnp.sum(values)
        """
    )
    assert analyze_source(source, JIT_PATH, [JitHygieneChecker()]) == []


def test_jit_hygiene_assignment_form_roots():
    source = src(
        """
        import jax
        from functools import partial

        def _decide(x, algorithm):
            if algorithm == "any":
                return x.sum()
            return x.item()

        decide = partial(jax.jit, static_argnames=("algorithm",))(_decide)
        """
    )
    findings = analyze_source(source, JIT_PATH, [JitHygieneChecker()])
    assert len(findings) == 1
    assert "`.item()`" in findings[0].message


# ---------------------------------------------------------------------------
# async-blocking
# ---------------------------------------------------------------------------

ASYNC_PATH = "foremast_tpu/service/fixture.py"

ASYNC_BAD = src(
    """
    import time
    import requests

    async def handler(request, store):
        time.sleep(1)
        requests.get("http://upstream")
        store.update(request)
        return open("/etc/hostname").read()
    """
)

ASYNC_CLEAN = src(
    """
    import asyncio
    import time

    async def handler(request, store):
        await asyncio.sleep(1)
        doc = await asyncio.to_thread(store.get, "id")

        def executor_target():
            time.sleep(1)

        return doc
    """
)


def test_async_blocking_catches_each_violation_class():
    findings = analyze_source(ASYNC_BAD, ASYNC_PATH, [AsyncBlockingChecker()])
    messages = "\n".join(f.message for f in findings)
    assert "`time.sleep(...)`" in messages
    assert "`requests.get(...)`" in messages
    assert "`store.update(...)`" in messages
    assert "`open()`" in messages
    assert len(findings) == 4


def test_async_blocking_permits_to_thread_and_nested_sync_defs():
    assert analyze_source(ASYNC_CLEAN, ASYNC_PATH, [AsyncBlockingChecker()]) == []


def test_async_blocking_ignores_sync_functions():
    source = src(
        """
        import time

        def poll_loop():
            time.sleep(5)
        """
    )
    assert analyze_source(source, ASYNC_PATH, [AsyncBlockingChecker()]) == []


# ---------------------------------------------------------------------------
# lock-discipline
# ---------------------------------------------------------------------------

LOCK_PATH = "foremast_tpu/jobs/fixture.py"

LOCK_BAD = src(
    """
    import threading

    class Box:
        def __init__(self):
            self._lock = threading.Lock()
            self._items = {}
            self.count = 0

        def put(self, key, value):
            with self._lock:
                self._items[key] = value
                self.count += 1

        def racy_get(self, key):
            return self._items.get(key)

        def racy_reset(self):
            self.count = 0
    """
)

LOCK_CLEAN = src(
    """
    import threading

    class Box:
        def __init__(self):
            self._lock = threading.Lock()
            self._items = {}
            self.limit = 8  # read-only config: never guarded

        def put(self, key, value):
            with self._lock:
                if len(self._items) < self.limit:
                    self._items[key] = value

        def get(self, key):
            with self._lock:
                return self._items.get(key)

        def describe(self):
            return f"box(limit={self.limit})"
    """
)


def test_lock_discipline_flags_unlocked_access():
    findings = analyze_source(LOCK_BAD, LOCK_PATH, [LockDisciplineChecker()])
    messages = "\n".join(f.message for f in findings)
    assert "unlocked read of `self._items` in `Box.racy_get`" in messages
    assert "unlocked write to `self.count` in `Box.racy_reset`" in messages
    assert len(findings) == 2


def test_lock_discipline_clean_class_and_readonly_config():
    assert analyze_source(LOCK_CLEAN, LOCK_PATH, [LockDisciplineChecker()]) == []


def test_lock_discipline_module_level_globals():
    source = src(
        """
        import threading

        _lock = threading.Lock()
        _cache = None

        def load():
            global _cache
            with _lock:
                if _cache is None:
                    _cache = object()
                return _cache

        def racy_invalidate():
            global _cache
            _cache = None
        """
    )
    findings = analyze_source(source, LOCK_PATH, [LockDisciplineChecker()])
    assert len(findings) == 1
    assert "module global `_cache` in `racy_invalidate`" in findings[0].message


def test_lock_discipline_nested_def_does_not_inherit_lock():
    source = src(
        """
        import threading

        class Box:
            def __init__(self):
                self._lock = threading.Lock()
                self._flag = False

            def arm(self):
                with self._lock:
                    self._flag = True

                    def later():
                        self._flag = False

                    return later
        """
    )
    findings = analyze_source(source, LOCK_PATH, [LockDisciplineChecker()])
    assert len(findings) == 1
    assert "unlocked write to `self._flag`" in findings[0].message


def test_lock_discipline_flags_runtime_env_writes():
    source = src(
        """
        import os

        def adopt(knobs):
            os.environ["FOREMAST_ARENA_BYTES"] = str(knobs[0])
        """
    )
    findings = analyze_source(source, LOCK_PATH, [LockDisciplineChecker()])
    assert len(findings) == 1
    assert "mutates process env at runtime" in findings[0].message


def test_lock_discipline_wsgi_environ_dict_is_not_process_env():
    source = src(
        """
        def app(environ, start_response):
            environ["HTTP_X"] = "1"
            return environ.get("PATH_INFO", "/")
        """
    )
    assert analyze_source(source, LOCK_PATH, [LockDisciplineChecker()]) == []


# ---------------------------------------------------------------------------
# env-contract
# ---------------------------------------------------------------------------

ENV_PATH = "foremast_tpu/engine/fixture_env.py"


def env_checker() -> EnvContractChecker:
    return EnvContractChecker(names=frozenset({"GOOD_KNOB"}))


def test_env_contract_flags_unregistered_and_dynamic_reads():
    source = src(
        """
        import os

        def configure(name):
            a = os.environ.get("GOOD_KNOB")
            b = os.environ.get("BAD_KNOB", "1")
            c = os.environ["ALSO_BAD"]
            d = os.environ.get(name)
            return a, b, c, d
        """
    )
    findings = analyze_source(source, ENV_PATH, [env_checker()])
    messages = "\n".join(f.message for f in findings)
    assert "'BAD_KNOB'" in messages
    assert "'ALSO_BAD'" in messages
    assert "computed name" in messages
    assert "GOOD_KNOB" not in messages
    assert len(findings) == 3


def test_env_contract_exempts_config_and_wsgi_dicts():
    source = 'import os\nx = os.environ.get("ANYTHING")\n'
    assert analyze_source(source, "foremast_tpu/config.py", [env_checker()]) == []
    wsgi = src(
        """
        def app(environ, start_response):
            return environ.get("PATH_INFO")
        """
    )
    assert analyze_source(wsgi, ENV_PATH, [env_checker()]) == []


def test_env_contract_from_import_alias_counts():
    source = src(
        """
        from os import environ

        def f():
            return environ.get("BAD_KNOB"), environ["WORSE"]
        """
    )
    findings = analyze_source(source, ENV_PATH, [env_checker()])
    assert len(findings) == 2


def test_registry_names_unique_and_real():
    from foremast_tpu.config import ENV_KNOBS

    names = [k.name for k in ENV_KNOBS]
    assert len(names) == len(set(names))
    for knob in ENV_KNOBS:
        assert knob.description
        assert knob.group in ("engine", "framework", "deploy")


def test_env_overrides_enumerates_set_knobs(monkeypatch):
    from foremast_tpu.config import env_overrides

    monkeypatch.setenv("FOREMAST_ARENA_BYTES", "4096")
    monkeypatch.delenv("FOREMAST_BF16_DELTA", raising=False)
    over = env_overrides()
    assert over["FOREMAST_ARENA_BYTES"] == "4096"
    assert "FOREMAST_BF16_DELTA" not in over


def test_env_docs_block_in_sync_with_registry():
    assert check_env_docs(repo_root()) == []
    # and the renderer output actually lives in the committed file
    with open(os.path.join(repo_root(), "docs", "operations.md")) as f:
        assert render_env_table() in f.read()


# ---------------------------------------------------------------------------
# suppressions
# ---------------------------------------------------------------------------


def _program(sources: dict):
    """Program over fixture sources (path -> source)."""
    from foremast_tpu.analysis.core import Module
    from foremast_tpu.analysis.interproc import Program

    return Program([Module(p, src(s)) for p, s in sources.items()])


# ---------------------------------------------------------------------------
# lock-order (ISSUE 8 tentpole)
# ---------------------------------------------------------------------------

LOCK_ORDER_NESTED = {
    "foremast_tpu/fix/a.py": """
        import threading

        from foremast_tpu.fix.b import Inner

        class Outer:
            def __init__(self, inner: Inner):
                self._lock = threading.Lock()
                self.inner = inner

            def work(self):
                with self._lock:
                    self.inner.poke()

            def hook_up(self, sink):
                sink.on_data = self.inner.poke
    """,
    "foremast_tpu/fix/b.py": """
        import threading

        class Inner:
            def __init__(self):
                self._lock = threading.Lock()
                self.n = 0

            def poke(self):
                with self._lock:
                    self.n += 1

        class Sink:
            def __init__(self):
                self._lock = threading.Lock()
                self.on_data = None

            def deliver(self):
                with self._lock:
                    on_data = self.on_data
                    on_data()
    """,
}


def test_lock_order_graph_interprocedural_edges():
    """Direct nesting through a TYPED attribute call, and a CALLBACK
    registered by attribute assignment in another module, both become
    static edges — the cross-module resolution PR-2 had no answer to."""
    from foremast_tpu.analysis.lock_order import build_graph

    g = build_graph(_program(LOCK_ORDER_NESTED))
    ids = {n["id"] for n in g["nodes"]}
    assert {"Outer._lock", "Inner._lock", "Sink._lock"} <= ids
    edges = {(e["from"], e["to"]) for e in g["edges"]}
    assert ("Outer._lock", "Inner._lock") in edges  # typed-attr call
    assert ("Sink._lock", "Inner._lock") in edges   # callback table


def test_lock_order_cycle_is_a_finding(tmp_path):
    from foremast_tpu.analysis.lock_order import (
        build_graph,
        check_lock_order,
        find_cycles,
        write_graph,
    )

    prog = _program(
        {
            "foremast_tpu/fix/cycle.py": """
                import threading

                class A:
                    def __init__(self, b: "B"):
                        self._lock = threading.Lock()
                        self.b = b

                    def fwd(self):
                        with self._lock:
                            self.b.take()

                    def take(self):
                        with self._lock:
                            pass

                class B:
                    def __init__(self, a: A):
                        self._lock = threading.Lock()
                        self.a = a

                    def take(self):
                        with self._lock:
                            pass

                    def back(self):
                        with self._lock:
                            self.a.take()
            """
        }
    )
    g = build_graph(prog)
    assert find_cycles(g), "A->B and B->A must form a cycle"
    write_graph(str(tmp_path), g)  # artifact in sync: only the cycle fires
    findings = check_lock_order(str(tmp_path), prog)
    assert len(findings) == 1
    assert "lock-order cycle" in findings[0].message
    assert "A._lock" in findings[0].message and "B._lock" in findings[0].message


def test_lock_order_nested_def_does_not_inherit_lock_context():
    """Code-review regression: a call inside a def DEFINED under a
    `with lock:` runs later (possibly on another thread, unlocked) —
    it must not fabricate an acquisition edge at the definition site."""
    from foremast_tpu.analysis.lock_order import build_graph

    prog = _program(
        {
            "foremast_tpu/fix/nested.py": """
                import threading

                class B:
                    def __init__(self):
                        self._lock = threading.Lock()

                    def poke(self):
                        with self._lock:
                            pass

                class A:
                    def __init__(self, b: B):
                        self._lock = threading.Lock()
                        self.b = b

                    def sched(self):
                        with self._lock:
                            def task():
                                self.b.poke()
                            return task
            """
        }
    )
    edges = {(e["from"], e["to"]) for e in build_graph(prog)["edges"]}
    assert ("A._lock", "B._lock") not in edges


def test_thread_escape_closure_under_lock_is_not_guard_evidence():
    """Code-review regression: a thread-target closure DEFINED inside a
    locked region runs unlocked — its mutation must not count as locked
    guard evidence (which would hide the race), and the unlocked
    mutation of genuinely-guarded state must still be flagged."""
    from foremast_tpu.analysis.thread_escape import check_thread_escape

    sources = dict(THREAD_ESCAPE_SRC)
    sources["foremast_tpu/fix/runner.py"] = """
        import threading

        from foremast_tpu.fix.guarded import Guarded

        class Runner:
            def __init__(self, g: Guarded):
                self.g = g

            def start(self):
                with self.g._lock:
                    def loop():
                        self.g.hits += 1
                    threading.Thread(target=loop, daemon=True).start()
    """
    findings = check_thread_escape(_program(sources))
    assert len(findings) == 1
    assert "Guarded.hits" in findings[0].message


def test_blocking_under_lock_nested_def_not_attributed_inline():
    findings = _blocking_findings(
        {
            "foremast_tpu/fix/blk4.py": """
                import threading
                import time

                class Poller:
                    def __init__(self):
                        self._lock = threading.Lock()

                    def sched(self):
                        with self._lock:
                            def later():
                                time.sleep(1)
                            return later
            """
        }
    )
    assert findings == []


def test_lock_order_rlock_reentrancy_is_not_a_cycle():
    from foremast_tpu.analysis.lock_order import build_graph, find_cycles

    prog = _program(
        {
            "foremast_tpu/fix/rl.py": """
                import threading

                class Cache:
                    def __init__(self):
                        self._lock = threading.RLock()

                    def get(self):
                        with self._lock:
                            return self._fill()

                    def _fill(self):
                        with self._lock:
                            return 1
            """
        }
    )
    g = build_graph(prog)
    assert find_cycles(g) == []
    assert [r["id"] for r in g["reentrant"]] == ["Cache._lock"]


def test_lock_order_plain_lock_self_deadlock_is_a_cycle():
    from foremast_tpu.analysis.lock_order import build_graph, find_cycles

    prog = _program(
        {
            "foremast_tpu/fix/dead.py": """
                import threading

                class Box:
                    def __init__(self):
                        self._lock = threading.Lock()

                    def outer(self):
                        with self._lock:
                            self.inner()

                    def inner(self):
                        with self._lock:
                            pass
            """
        }
    )
    assert find_cycles(build_graph(prog)) == [["Box._lock", "Box._lock"]]


def test_lockgraph_artifact_roundtrip_and_staleness(tmp_path):
    import json

    from foremast_tpu.analysis.lock_order import (
        GRAPH_NAME,
        build_graph,
        check_lock_order,
        load_graph,
        write_graph,
    )

    prog = _program(LOCK_ORDER_NESTED)
    g = build_graph(prog)
    root = str(tmp_path)
    # missing artifact is a finding
    missing = check_lock_order(root, prog)
    assert any("missing" in f.message for f in missing)
    # committed + in sync: clean
    write_graph(root, g)
    assert load_graph(root) == g
    assert check_lock_order(root, prog) == []
    # drift (an edge disappears from the committed file) is a finding
    stale = dict(g)
    stale["edges"] = g["edges"][1:]
    with open(tmp_path / GRAPH_NAME, "w") as f:
        json.dump(stale, f)
    findings = check_lock_order(root, prog)
    assert any("stale" in f.message for f in findings)


def test_tree_lockgraph_committed_in_sync_and_cycle_free():
    """Acceptance: analysis_lockgraph.json is committed, matches the
    computed graph, and is cycle-free."""
    from foremast_tpu.analysis.interproc import Program
    from foremast_tpu.analysis.lock_order import (
        build_graph,
        check_lock_order,
        find_cycles,
        load_graph,
    )

    root = repo_root()
    pkg = [
        m for m in collect_modules(root)
        if m.relpath.startswith("foremast_tpu/")
    ]
    program = Program(pkg)
    assert check_lock_order(root, program) == []
    graph = load_graph(root)
    assert graph is not None and find_cycles(graph) == []
    # the known deepest nesting is present (journal hook under the
    # shard lock — the PR-7 replay-order contract)
    edges = {(e["from"], e["to"]) for e in graph["edges"]}
    assert ("RingShard._lock", "_ShardLog._lock") in edges
    assert ("InMemoryStore._lock", "MeshRouter._lock") in edges


# ---------------------------------------------------------------------------
# thread-escape
# ---------------------------------------------------------------------------


def test_thread_escape_mixed_guard():
    from foremast_tpu.analysis.thread_escape import check_thread_escape

    prog = _program(
        {
            "foremast_tpu/fix/mix.py": """
                import threading

                class T:
                    def __init__(self):
                        self._a = threading.Lock()
                        self._b = threading.Lock()
                        self.stamp = 0.0

                    def sched(self):
                        with self._a:
                            self.stamp = 1.0

                    def flush(self):
                        with self._b:
                            self.stamp = 2.0
            """
        }
    )
    findings = check_thread_escape(prog)
    assert len(findings) == 1
    assert "T.stamp" in findings[0].message
    assert "DIFFERENT locks" in findings[0].message


def test_thread_escape_nested_locks_are_not_mixed_guard():
    """A mutation under BOTH locks shares a lock with a mutation under
    one of them — consistently guarded, not mixed (the false positive
    the intersection criterion exists for)."""
    from foremast_tpu.analysis.thread_escape import check_thread_escape

    prog = _program(
        {
            "foremast_tpu/fix/nest.py": """
                import threading

                class T:
                    def __init__(self):
                        self._pass = threading.Lock()
                        self._meta = threading.Lock()
                        self.count = 0

                    def heavy(self):
                        with self._pass:
                            with self._meta:
                                self.count += 1

                    def light(self):
                        with self._meta:
                            self.count += 1
            """
        }
    )
    assert check_thread_escape(prog) == []


THREAD_ESCAPE_SRC = {
    "foremast_tpu/fix/guarded.py": """
        import threading

        class Guarded:
            def __init__(self):
                self._lock = threading.Lock()
                self.hits = 0

            def bump(self):
                with self._lock:
                    self.hits += 1
    """,
    "foremast_tpu/fix/runner.py": """
        import threading

        from foremast_tpu.fix.guarded import Guarded

        class Runner:
            def __init__(self, g: Guarded):
                self.g = g

            def start(self):
                threading.Thread(target=self._loop, daemon=True).start()

            def _loop(self):
                self.g.hits += 1

            def safe_loop(self):
                with self.g._lock:
                    self.g.hits += 1
    """,
}


def test_thread_escape_cross_module_unlocked_mutation():
    from foremast_tpu.analysis.thread_escape import check_thread_escape

    findings = check_thread_escape(_program(THREAD_ESCAPE_SRC))
    assert len(findings) == 1
    f = findings[0]
    assert f.path == "foremast_tpu/fix/runner.py"
    assert "Guarded.hits" in f.message and "Runner._loop" in f.message
    # safe_loop holds the owner's lock through the typed receiver — clean


def test_thread_escape_needs_a_thread_root():
    """The same unlocked cross-class mutation with NO thread anywhere
    is not flagged — the rule is about state threads can reach."""
    from foremast_tpu.analysis.thread_escape import check_thread_escape

    sources = dict(THREAD_ESCAPE_SRC)
    sources["foremast_tpu/fix/runner.py"] = """
        from foremast_tpu.fix.guarded import Guarded

        class Runner:
            def __init__(self, g: Guarded):
                self.g = g

            def _loop(self):
                self.g.hits += 1
    """
    assert check_thread_escape(_program(sources)) == []


def test_thread_escape_roots_include_handlers_and_collectors():
    from foremast_tpu.analysis.thread_escape import thread_roots

    prog = _program(
        {
            "foremast_tpu/fix/surface.py": """
                from http.server import BaseHTTPRequestHandler

                class Handler(BaseHTTPRequestHandler):
                    def do_GET(self):
                        pass

                class StatsCollector:
                    def collect(self):
                        yield 1

                def wire(registry):
                    registry.register(StatsCollector())
            """
        }
    )
    names = {f.qualname for f in thread_roots(prog)}
    assert "Handler.do_GET" in names
    assert "StatsCollector.collect" in names


# ---------------------------------------------------------------------------
# blocking-under-lock
# ---------------------------------------------------------------------------


def _blocking_findings(sources):
    from foremast_tpu.analysis.blocking_under_lock import (
        apply_suppressions,
        check_blocking_under_lock,
    )

    prog = _program(sources)
    return apply_suppressions(
        check_blocking_under_lock(prog), prog.modules
    )


def test_blocking_under_lock_direct_and_clean():
    findings = _blocking_findings(
        {
            "foremast_tpu/fix/blk.py": """
                import threading
                import time

                class Poller:
                    def __init__(self):
                        self._lock = threading.Lock()

                    def bad(self):
                        with self._lock:
                            time.sleep(1)

                    def good(self):
                        with self._lock:
                            x = 1
                        time.sleep(1)
                        return x
            """
        }
    )
    assert len(findings) == 1
    assert "time.sleep" in findings[0].message
    assert "Poller.bad" in findings[0].message


def test_blocking_under_lock_interprocedural():
    findings = _blocking_findings(
        {
            "foremast_tpu/fix/blk2.py": """
                import threading
                import requests

                def _fetch(url):
                    return requests.get(url)

                class Client:
                    def __init__(self):
                        self._lock = threading.Lock()

                    def refresh(self):
                        with self._lock:
                            return _fetch("http://upstream")
            """
        }
    )
    msgs = "\n".join(f.message for f in findings)
    assert "_fetch" in msgs and "HTTP call" in msgs
    assert "Client.refresh" in msgs


def test_blocking_under_lock_suppression_in_place():
    findings = _blocking_findings(
        {
            "foremast_tpu/fix/blk3.py": """
                import threading
                import time

                class Poller:
                    def __init__(self):
                        self._lock = threading.Lock()

                    def deliberate(self):
                        with self._lock:
                            # the lock IS the serializer here (fixture)
                            time.sleep(0)  # foremast: ignore[blocking-under-lock]
            """
        }
    )
    assert findings == []


# ---------------------------------------------------------------------------
# metrics-contract
# ---------------------------------------------------------------------------


def _metrics_checker():
    from foremast_tpu.analysis.metrics_contract import MetricsContractChecker

    return MetricsContractChecker(
        registry={"foremast_known": frozenset()},
        docs={"foremast_known": "a known family"},
    )


def test_metrics_contract_flags_unregistered_and_undocumented():
    source = src(
        """
        from prometheus_client import Counter

        def build(reg):
            Counter("foremast_known_total", "fine", registry=reg)
            Counter("foremast_rogue_total", "not registered", registry=reg)
        """
    )
    findings = analyze_source(
        source, "foremast_tpu/observe/fixture.py", [_metrics_checker()]
    )
    assert len(findings) == 1
    assert "foremast_rogue_total" in findings[0].message
    assert "ALLOWED_LABELS" in findings[0].message


def test_metrics_contract_counts_metric_family_constructors():
    source = src(
        """
        from prometheus_client.core import GaugeMetricFamily

        def collect():
            yield GaugeMetricFamily("foremast_mystery", "nope")
        """
    )
    findings = analyze_source(
        source, "foremast_tpu/observe/fixture.py", [_metrics_checker()]
    )
    assert len(findings) == 1 and "foremast_mystery" in findings[0].message


def test_metrics_contract_checks_name_keyword_form():
    """Code-review regression: `Counter(name="foremast_x_total", ...)`
    is legal prometheus_client usage and must not escape the contract."""
    source = src(
        """
        from prometheus_client import Counter

        def build(reg):
            Counter(name="foremast_rogue_total", documentation="d", registry=reg)
        """
    )
    findings = analyze_source(
        source, "foremast_tpu/observe/fixture.py", [_metrics_checker()]
    )
    assert len(findings) == 1 and "foremast_rogue_total" in findings[0].message


def test_metrics_contract_ignores_dynamic_and_nonmetric_strings():
    source = src(
        """
        from prometheus_client import Counter

        def build(reg, ns):
            Counter(f"{ns}_dynamic_total", "f-string: not checked", registry=reg)
            print("foremast_not_a_constructor")
        """
    )
    assert analyze_source(
        source, "foremast_tpu/observe/fixture.py", [_metrics_checker()]
    ) == []


def test_metrics_registry_docs_and_table_in_sync():
    """Acceptance: ALLOWED_LABELS == FAMILY_DOCS keys, every registry
    entry is constructed (or declared dynamic), and the committed
    observability table matches the renderer."""
    import os as _os

    from foremast_tpu.analysis.metrics_contract import (
        check_metrics_docs,
        check_registry_coverage,
        render_family_table,
    )

    root = repo_root()
    assert check_metrics_docs(root) == []
    assert check_registry_coverage(collect_modules(root)) == []
    with open(_os.path.join(root, "docs", "observability.md")) as f:
        assert render_family_table() in f.read()


# ---------------------------------------------------------------------------
# runtime witness (analysis/witness.py)
# ---------------------------------------------------------------------------


def test_witness_observes_ordered_fixture_and_matches_graph(tmp_path):
    """A deliberately ordered fixture: the ring journal hook nests
    _ShardLog._lock under RingShard._lock on a REAL push. The witness
    must observe exactly that edge, and the committed static graph must
    contain it; a doctored graph missing the edge must be reported."""
    import numpy as np

    from foremast_tpu.analysis import witness
    from foremast_tpu.analysis.lock_order import load_graph
    from foremast_tpu.ingest import RingSnapshotter, RingStore

    wit = witness.install()
    try:
        store = RingStore(shards=1)
        snap = RingSnapshotter(store, str(tmp_path))
        snap.attach()
        t = np.arange(0, 300, 60, np.int64)
        store.push(
            'm{app="w"}', t, np.ones(len(t), np.float32), start=0.0, now=300.0
        )
        snap.close()
    finally:
        witness.uninstall()
    shard_site = "foremast_tpu/ingest/shards.py"
    log_site = "foremast_tpu/ingest/snapshot.py"
    observed = wit.edges()
    assert any(
        a.startswith(shard_site) and b.startswith(log_site)
        for a, b in observed
    ), observed
    graph = load_graph(repo_root())
    assert graph is not None
    assert wit.unobserved_edges(graph) == []
    # a graph missing the journal edge must be reported as a hole
    doctored = dict(graph)
    doctored["edges"] = [
        e
        for e in graph["edges"]
        if (e["from"], e["to"]) != ("RingShard._lock", "_ShardLog._lock")
    ]
    assert ("RingShard._lock", "_ShardLog._lock") in wit.unobserved_edges(
        doctored
    )


def test_witness_reentrant_rlock_records_no_self_edge():
    from foremast_tpu.analysis import witness
    from foremast_tpu.models.cache import ModelCache

    wit = witness.install()
    try:
        cache = ModelCache(max_size=4)
        cache.restore_lazy({("k", "m"): 1})
        assert cache.get(("k", "m")) == 1  # locked get -> locked rehydrate
    finally:
        witness.uninstall()
    cache_site = "foremast_tpu/models/cache.py"
    assert not any(
        a.startswith(cache_site) and b.startswith(cache_site)
        for a, b in wit.edges()
    )


def test_witness_ignores_non_package_locks():
    import threading

    from foremast_tpu.analysis import witness

    wit = witness.install()
    try:
        outer = threading.Lock()  # created HERE: a tests/ frame
        inner = threading.Lock()
        with outer:
            with inner:
                pass
        # created from a tests/ frame: raw locks, no edges recorded
        assert not hasattr(outer, "site")
        assert wit.edges() == set()
    finally:
        witness.uninstall()


# ---------------------------------------------------------------------------
# scan scopes (benchmarks/ + tests/ for the repo-scoped rules)
# ---------------------------------------------------------------------------


def test_scope_repo_rules_cover_tests_and_benchmarks():
    from foremast_tpu.analysis.lock_discipline import LockDisciplineChecker
    from foremast_tpu.analysis.metrics_contract import MetricsContractChecker

    assert AsyncBlockingChecker().applies_to("tests/test_x.py")
    assert env_checker().applies_to("benchmarks/bench_x.py")
    assert not LockDisciplineChecker().applies_to("tests/test_x.py")
    assert not JitHygieneChecker().applies_to("benchmarks/bench_x.py")
    assert not MetricsContractChecker().applies_to("tests/test_x.py")


def test_default_scan_includes_tests_and_benchmarks():
    relpaths = {m.relpath for m in collect_modules(repo_root())}
    assert any(p.startswith("tests/") for p in relpaths)
    assert any(p.startswith("benchmarks/") for p in relpaths)


def test_suppression_same_line_by_rule():
    source = src(
        """
        import time

        async def handler(request):
            time.sleep(1)  # foremast: ignore[async-blocking]
        """
    )
    assert analyze_source(source, ASYNC_PATH, [AsyncBlockingChecker()]) == []


def test_suppression_bare_and_comment_line_above():
    source = src(
        """
        import time

        async def handler(request):
            # foremast: ignore
            time.sleep(1)
        """
    )
    assert analyze_source(source, ASYNC_PATH, [AsyncBlockingChecker()]) == []


def test_suppression_wrong_rule_does_not_apply():
    source = src(
        """
        import time

        async def handler(request):
            time.sleep(1)  # foremast: ignore[jit-hygiene]
        """
    )
    findings = analyze_source(source, ASYNC_PATH, [AsyncBlockingChecker()])
    assert len(findings) == 1


def test_suppression_on_other_statement_does_not_leak_down():
    source = src(
        """
        import time

        async def handler(request):
            x = 1  # foremast: ignore[async-blocking]
            time.sleep(1)
        """
    )
    findings = analyze_source(source, ASYNC_PATH, [AsyncBlockingChecker()])
    assert len(findings) == 1


def test_suppression_multi_rule_on_one_line():
    """ISSUE 8 regression: `ignore[rule-a,rule-b]` must suppress each
    listed rule — and ONLY those (spaces around the commas allowed)."""
    source = src(
        """
        import time

        async def handler(request):
            time.sleep(1)  # foremast: ignore[async-blocking, jit-hygiene]
        """
    )
    assert analyze_source(source, ASYNC_PATH, [AsyncBlockingChecker()]) == []
    other = src(
        """
        import time

        async def handler(request):
            time.sleep(1)  # foremast: ignore[jit-hygiene,lock-discipline]
        """
    )
    findings = analyze_source(other, ASYNC_PATH, [AsyncBlockingChecker()])
    assert len(findings) == 1  # async-blocking is NOT in the list


def test_suppression_spaced_bracket_is_rule_scoped_not_ignore_all():
    """Regression: `ignore [rule]` used to fail the bracket parse and
    silently degrade to the bare suppress-EVERYTHING form — the
    dangerous direction. It must scope to the listed rules."""
    source = src(
        """
        import time

        async def handler(request):
            time.sleep(1)  # foremast: ignore [jit-hygiene]
        """
    )
    findings = analyze_source(source, ASYNC_PATH, [AsyncBlockingChecker()])
    assert len(findings) == 1  # NOT suppressed: the list names jit only
    scoped = src(
        """
        import time

        async def handler(request):
            time.sleep(1)  # foremast: ignore [async-blocking]
        """
    )
    assert analyze_source(scoped, ASYNC_PATH, [AsyncBlockingChecker()]) == []


# ---------------------------------------------------------------------------
# baseline round-trip
# ---------------------------------------------------------------------------


def test_baseline_round_trip_and_staleness(tmp_path):
    findings = analyze_source(ASYNC_BAD, ASYNC_PATH, [AsyncBlockingChecker()])
    assert findings
    path = str(tmp_path / "baseline.json")
    Baseline.from_findings(findings).save(path)
    loaded = Baseline.load(path)
    new, grandfathered = loaded.split(findings)
    assert new == [] and len(grandfathered) == len(findings)
    assert loaded.stale(findings) == []
    # a paid-off finding shows as stale; a brand-new one is not masked
    subset = findings[1:]
    assert len(loaded.stale(subset)) == 1
    extra = Finding(
        rule="async-blocking", path=ASYNC_PATH, line=99, message="novel"
    )
    new, _ = loaded.split([*findings, extra])
    assert new == [extra]


def test_baseline_fingerprint_is_line_independent():
    a = Finding(rule="r", path="p.py", line=10, message="m")
    b = Finding(rule="r", path="p.py", line=99, message="m")
    assert a.fingerprint() == b.fingerprint()
    assert a.fingerprint() != Finding(
        rule="r", path="p.py", line=10, message="other"
    ).fingerprint()


def test_missing_baseline_means_empty():
    assert Baseline.load("/nonexistent/baseline.json").entries == []


# ---------------------------------------------------------------------------
# the gate: the tree itself is clean (tier-1)
# ---------------------------------------------------------------------------


def test_tree_clean_against_committed_baseline():
    """`python -m foremast_tpu.analysis` exits 0 on this tree: every
    per-module checker over package + benchmarks + tests, the
    whole-program concurrency rules, the three generated-artifact
    contracts, and the committed (empty-or-shrinking) baseline."""
    from foremast_tpu.analysis.__main__ import program_findings
    from foremast_tpu.analysis.metrics_contract import (
        check_metrics_docs,
        check_registry_coverage,
    )

    root = repo_root()
    modules = collect_modules(root)
    findings = analyze_modules(modules, all_checkers())
    findings.extend(check_env_docs(root))
    findings.extend(check_metrics_docs(root))
    findings.extend(check_registry_coverage(modules))
    findings.extend(program_findings(root, modules))
    baseline = Baseline.load(os.path.join(root, "analysis_baseline.json"))
    new, _ = baseline.split(findings)
    assert new == [], "\n" + "\n".join(f.render() for f in new)


def test_runner_exit_codes(tmp_path, capsys):
    from foremast_tpu.analysis.__main__ import main

    bad = tmp_path / "fixture_bad.py"
    bad.write_text(ASYNC_BAD)
    assert main([str(bad)]) == 1
    out = capsys.readouterr().out
    assert "async-blocking" in out and "new finding" in out

    clean = tmp_path / "fixture_clean.py"
    clean.write_text(ASYNC_CLEAN)
    assert main([str(clean)]) == 0
    assert "clean" in capsys.readouterr().out


def test_runner_folds_in_metrics_lint():
    from foremast_tpu.analysis.__main__ import metrics_lint_findings

    assert metrics_lint_findings() == []


@pytest.mark.slow
def test_runner_cli_subprocess_gate():
    """The exact command `make check` runs, end to end."""
    import subprocess
    import sys

    proc = subprocess.run(
        [sys.executable, "-m", "foremast_tpu.analysis"],
        capture_output=True,
        text=True,
        cwd=repo_root(),
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
        timeout=300,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_lock_order_resolves_pep604_optional_annotations():
    """ISSUE 11: an optional typed dependency (`x: "X | None" = None`,
    the idiom for optionally-mounted planes like the handoff manager)
    still types the attribute it is assigned to — the nesting edge
    through it must land in the static graph, not only in the runtime
    witness."""
    from foremast_tpu.analysis.lock_order import build_graph

    g = build_graph(
        _program(
            {
                "foremast_tpu/fix/opt.py": """
                    import threading

                    class Plane:
                        def __init__(self):
                            self._lock = threading.Lock()

                        def probe(self):
                            with self._lock:
                                return True

                    class Seat:
                        def __init__(self, plane: "Plane | None" = None):
                            self._lock = threading.Lock()
                            self.plane = plane

                        def work(self):
                            with self._lock:
                                if self.plane is not None:
                                    self.plane.probe()
                """,
            }
        )
    )
    edges = {(e["from"], e["to"]) for e in g["edges"]}
    assert ("Seat._lock", "Plane._lock") in edges


# ---------------------------------------------------------------------------
# device-flow (ISSUE 17 tentpole)
# ---------------------------------------------------------------------------


def _device_flow_findings(sources):
    from foremast_tpu.analysis.blocking_under_lock import apply_suppressions
    from foremast_tpu.analysis.device_flow import check_device_flow

    prog = _program(sources)
    return apply_suppressions(check_device_flow(prog), prog.modules)


DEVICE_FLOW_SRC = {
    "foremast_tpu/engine/devfix.py": """
        import numpy as np

        def sweep(judge, tasks):
            res = judge.judge_columnar(tasks)
            total = float(res[0])
            rows = np.asarray(res[1])
            width = res[0].shape[0]
            return total, rows, width

        def drain(buf):
            return buf.item()

        def helper_sink(judge, tasks):
            res = judge.judge_columnar(tasks)
            return drain(res[0])

        # The fixture's designated decode stage: gathers the columnar
        # result once; everything it hands on is host.
        # foremast: device-boundary
        def decode(res):
            return [float(v) for v in res[0]]

        def caller(judge, tasks):
            res = judge.judge_columnar(tasks)
            out = decode(res)
            return sum(out)
    """,
}


def test_device_flow_flags_sinks_interprocedurally():
    """Sinks fire on dispatch-root taint in the SAME function and in a
    HELPER the tainted value is passed to; `.shape` metadata reads stay
    clean."""
    findings = _device_flow_findings(DEVICE_FLOW_SRC)
    assert findings and all(f.rule == "device-flow" for f in findings)
    msgs = "\n".join(f.message for f in findings)
    assert "`float()`" in msgs and "in `sweep`" in msgs
    assert "`np.asarray()`" in msgs
    assert "`.item()`" in msgs and "in `drain`" in msgs  # via helper_sink
    # exactly: float + asarray in sweep, .item in drain — the `.shape`
    # read and everything in decode/caller is clean
    assert len(findings) == 3


def test_device_flow_boundary_neither_flags_nor_pushes_taint():
    """A `# foremast: device-boundary` def is the sanctioned decode:
    sinks inside it are the design, and neither its return value nor
    the values it hands onward carry taint into callers."""
    findings = _device_flow_findings(DEVICE_FLOW_SRC)
    msgs = "\n".join(f.message for f in findings)
    assert "in `decode`" not in msgs
    assert "in `caller`" not in msgs


def test_device_flow_sink_scope_excludes_host_only_modules():
    """The same source outside engine//jobs//parallel/ (here: ingest/)
    is host-side plumbing — no findings."""
    src_text = DEVICE_FLOW_SRC["foremast_tpu/engine/devfix.py"]
    findings = _device_flow_findings(
        {"foremast_tpu/ingest/devfix.py": src_text}
    )
    assert findings == []


# ---------------------------------------------------------------------------
# recompile-hazard (ISSUE 17 tentpole)
# ---------------------------------------------------------------------------


RECOMPILE_SRC = {
    "foremast_tpu/engine/recfix.py": """
        import jax
        import numpy as np
        from functools import partial

        from foremast_tpu.engine.padding import bucket_length

        WIDTH = 16

        @partial(jax.jit, static_argnames=("width",))
        def kernel(values, width=8):
            return values * width

        def bad_static(xs, arr):
            return kernel(arr, width=len(xs))

        def good_static(arr, cfg):
            return kernel(arr, width=cfg.width) + kernel(arr, width=WIDTH)

        def bad_shape(vals, judge):
            buf = np.zeros((4, len(vals)))
            return judge.judge_columnar(buf)

        def good_shape(vals, judge):
            buf = np.zeros((4, bucket_length(len(vals))))
            return judge.judge_columnar(buf)

        def bad_percall(values):
            scaled = jax.jit(lambda v: v * 2.0)
            return scaled(values)

        class Holder:
            def __init__(self):
                self._scale = jax.jit(lambda v: v + 1.0)
    """,
}


def test_recompile_hazard_catches_each_violation_class():
    from foremast_tpu.analysis.recompile_hazard import check_recompile_hazard

    findings = check_recompile_hazard(_program(RECOMPILE_SRC))
    assert findings and all(f.rule == "recompile-hazard" for f in findings)
    msgs = "\n".join(f.message for f in findings)
    assert "unbounded static: `width`" in msgs            # bad_static
    assert "unbucketed trailing dimension" in msgs        # bad_shape
    assert "per-call `jax.jit` inside `bad_percall`" in msgs
    # good_static (config attr + module const), good_shape (bucketed
    # trailing axis) and the __init__ cache-per-instance idiom are clean
    assert len(findings) == 3


def test_recompile_hazard_clean_on_tree():
    """The real tree's jit call sites are calibrated clean: every
    shape-bearing arg flows through the pow2/bucket helpers and every
    static comes from a bounded domain."""
    from foremast_tpu.analysis.blocking_under_lock import apply_suppressions
    from foremast_tpu.analysis.interproc import Program
    from foremast_tpu.analysis.recompile_hazard import check_recompile_hazard

    pkg = [
        m for m in collect_modules(repo_root())
        if m.relpath.startswith("foremast_tpu/")
    ]
    prog = Program(pkg)
    assert apply_suppressions(check_recompile_hazard(prog), pkg) == []


# ---------------------------------------------------------------------------
# sharding-contract (ISSUE 17 tentpole)
# ---------------------------------------------------------------------------


def _sharding_findings(sources):
    from foremast_tpu.analysis.blocking_under_lock import apply_suppressions
    from foremast_tpu.analysis.sharding_contract import check_sharding_contract

    prog = _program(sources)
    return apply_suppressions(check_sharding_contract(prog), prog.modules)


def test_sharding_contract_placement_outside_hooks():
    findings = _sharding_findings(
        {
            "foremast_tpu/jobs/shardfix.py": """
                import jax.numpy as jnp

                def build(values):
                    return jnp.asarray(values)

                def _place(values):
                    return jnp.asarray(values)

                def build_suppressed(values):
                    # bench-only constructor (fixture)
                    # foremast: ignore[sharding-contract]
                    return jnp.asarray(values)
            """
        }
    )
    assert len(findings) == 1
    assert findings[0].rule == "sharding-contract"
    assert "`jnp.asarray` in warm-path code (`build`)" in findings[0].message


def test_sharding_contract_arena_needs_sharded_annotation():
    findings = _sharding_findings(
        {
            "foremast_tpu/parallel/arenafix.py": """
                class Router:
                    def spread(self):
                        return self._arena_budget + 1

                    # Reads the shard-agnostic budget only (fixture).
                    # foremast: sharded-arena
                    def budget(self):
                        return self._arena_budget
            """
        }
    )
    assert len(findings) == 1
    assert "arena reference `_arena_budget` in sharded code (`spread`)" in (
        findings[0].message
    )


# ---------------------------------------------------------------------------
# status-machine (ISSUE 17 tentpole)
# ---------------------------------------------------------------------------


STATUS_MODELS_FIX = """
    STATUS_INITIAL = "initial"
    STATUS_INPROGRESS = "preprocess_inprogress"
    STATUS_COMPLETED = "preprocess_completed"
    STATUS_HEALTHY = "completed_health"
    STATUS_FAILED = "failed"

    TERMINAL_STATUSES = frozenset({STATUS_HEALTHY, STATUS_FAILED})
    INPROGRESS_STATUSES = frozenset({STATUS_INPROGRESS})
    CLAIMABLE_STATUSES = frozenset({STATUS_INITIAL, STATUS_COMPLETED})
"""


def test_status_machine_write_legality_and_dynamic_writes(tmp_path):
    from foremast_tpu.analysis.status_machine import (
        build_graph,
        check_status_machine,
        write_graph,
    )

    prog = _program(
        {
            "foremast_tpu/jobs/modelsfix.py": STATUS_MODELS_FIX,
            "foremast_tpu/jobs/workerfix.py": """
                from foremast_tpu.jobs.modelsfix import (
                    STATUS_HEALTHY,
                    STATUS_INPROGRESS,
                )

                class Worker:
                    def judge(self, doc):
                        if doc.status == STATUS_INPROGRESS:
                            doc.status = STATUS_HEALTHY

                    def rewind(self, doc):
                        if doc.status == STATUS_HEALTHY:
                            doc.status = STATUS_INPROGRESS

                    def dynamic(self, doc, value):
                        doc.status = value

                    def alien(self, doc):
                        doc.status = "totally_new"
            """,
        }
    )
    write_graph(str(tmp_path), build_graph(prog))
    findings = check_status_machine(str(tmp_path), prog)
    msgs = "\n".join(f.message for f in findings)
    # `judge` (in-progress -> terminal) is legal and NOT flagged
    assert "`Worker.judge`" not in msgs
    assert "illegal status transition" in msgs and "`Worker.rewind`" in msgs
    assert "dynamic status write in `Worker.dynamic`" in msgs
    assert "unknown status `totally_new`" in msgs
    assert len(findings) == 3


def test_status_machine_claim_path_protection(tmp_path):
    """A claim whose span settles through a try/finally release edge is
    compliant; a bare claim with no protected exception edge is the
    stranded-docs finding — at the span owner, once."""
    from foremast_tpu.analysis.status_machine import (
        build_graph,
        check_status_machine,
        write_graph,
    )

    prog = _program(
        {
            "foremast_tpu/jobs/modelsfix.py": STATUS_MODELS_FIX,
            "foremast_tpu/jobs/claimfix.py": """
                from foremast_tpu.jobs.modelsfix import (
                    STATUS_COMPLETED,
                    STATUS_HEALTHY,
                )

                class Safe:
                    def cycle(self):
                        docs = self.store.claim("w", 600, 8)
                        try:
                            for d in docs:
                                d.status = STATUS_HEALTHY
                        finally:
                            self.release(docs)

                    def release(self, docs):
                        for d in docs:
                            d.status = STATUS_COMPLETED

                class Leaky:
                    def cycle(self):
                        docs = self.store.claim("w", 600, 8)
                        for d in docs:
                            d.status = STATUS_HEALTHY

                    def outer(self):
                        self.cycle()
            """,
        }
    )
    write_graph(str(tmp_path), build_graph(prog))
    findings = check_status_machine(str(tmp_path), prog)
    claim = [f for f in findings if "claim path" in f.message]
    # one finding, at the frame that owns the claim-to-settle span —
    # not repeated at `outer`, which cannot fix it
    assert len(claim) == 1
    assert "`Leaky.cycle`" in claim[0].message


def test_statusgraph_artifact_roundtrip_and_staleness(tmp_path):
    import json

    from foremast_tpu.analysis.status_machine import (
        GRAPH_NAME,
        build_graph,
        check_status_machine,
        load_graph,
        write_graph,
    )

    prog = _program(
        {
            "foremast_tpu/jobs/modelsfix.py": STATUS_MODELS_FIX,
            "foremast_tpu/jobs/workerfix.py": """
                from foremast_tpu.jobs.modelsfix import (
                    STATUS_HEALTHY,
                    STATUS_INPROGRESS,
                )

                class Worker:
                    def judge(self, doc):
                        if doc.status == STATUS_INPROGRESS:
                            doc.status = STATUS_HEALTHY
            """,
        }
    )
    g = build_graph(prog)
    root = str(tmp_path)
    # missing artifact is a finding
    missing = check_status_machine(root, prog)
    assert any("missing" in f.message for f in missing)
    # committed + in sync: clean
    write_graph(root, g)
    assert load_graph(root) == g
    assert check_status_machine(root, prog) == []
    # drift (a transition disappears from the committed file) fires
    stale = dict(g)
    stale["transitions"] = g["transitions"][1:]
    with open(tmp_path / GRAPH_NAME, "w") as f:
        json.dump(stale, f)
    findings = check_status_machine(root, prog)
    assert any("stale" in f.message for f in findings)


def test_tree_statusgraph_committed_in_sync():
    """Acceptance: analysis_statusgraph.json is committed and matches
    the graph computed from jobs/models.py + the write sites."""
    from foremast_tpu.analysis.interproc import Program
    from foremast_tpu.analysis.status_machine import (
        _normalize,
        build_graph,
        load_graph,
    )

    root = repo_root()
    pkg = [
        m for m in collect_modules(root)
        if m.relpath.startswith("foremast_tpu/")
    ]
    committed = load_graph(root)
    assert committed is not None, "run `make statusgraph` and commit"
    assert _normalize(committed) == _normalize(build_graph(Program(pkg)))
    # the machine's core contract is present in the committed artifact
    pairs = {(e["from"], e["to"], e["via"]) for e in committed["transitions"]}
    assert ("preprocess_inprogress", "preprocess_completed", "release") in pairs
    assert any(s["terminal"] for s in committed["statuses"])


# ---------------------------------------------------------------------------
# recompile witness (ISSUE 17: the runtime half)
# ---------------------------------------------------------------------------


def test_recompile_witness_phase_attribution_and_assert_zero():
    from foremast_tpu.analysis.recompile_witness import (
        COMPILE_EVENT,
        RecompileWitness,
    )

    wit = RecompileWitness()
    wit._installed = True  # count without touching a jax backend
    wit._on_event(COMPILE_EVENT, 0.01)          # outside any phase
    with wit.phase("cold"):
        wit._on_event(COMPILE_EVENT, 0.01)
        wit._on_event(COMPILE_EVENT + "/sub", 0.01)
        wit._on_event("/jax/unrelated", 0.01)   # filtered out
    with wit.phase("warm"):
        pass
    assert wit.count() == 3 and wit.count("cold") == 2
    assert wit.count("warm") == 0
    assert wit.snapshot() == {"total": 3, "cold": 2}
    wit.assert_zero("warm")
    # the doctored negative: a compile landing in the warm phase trips
    # the in-run gate with the rule citation
    with wit.phase("warm"):
        wit._on_event(COMPILE_EVENT, 0.01)
    with pytest.raises(AssertionError, match="recompile-hazard"):
        wit.assert_zero("warm")
    # a dead witness stops counting even if unregistration failed
    wit._installed = False
    wit._on_event(COMPILE_EVENT, 0.01)
    assert wit.count() == 4


def test_recompile_witness_env_gate():
    from foremast_tpu.analysis import recompile_witness as rw

    assert rw.install_from_env(env={}) is None
    assert rw.install_from_env(env={"FOREMAST_RECOMPILE_WITNESS": "0"}) is None
    wit = rw.install_from_env(env={"FOREMAST_RECOMPILE_WITNESS": "1"})
    try:
        assert wit is not None and rw.current() is wit
    finally:
        rw.uninstall()
    assert rw.current() is None


@pytest.mark.slow
def test_warm_judge_pass_zero_recompiles_witnessed():
    """Tier-1 pin of the zero-warm-recompile contract on the REAL
    dispatch path: a warm worker tick at unchanged shapes runs entirely
    from the dispatch cache — and the doctored arm (a genuinely new
    trailing shape in the warm phase) proves the witness observes, so a
    zero is a measurement, not a dead listener."""
    import time as _time

    jax = pytest.importorskip("jax")
    import jax.numpy as jnp

    from benchmarks.worker_bench import build_mixed_fleet
    from foremast_tpu.analysis.recompile_witness import RecompileWitness
    from foremast_tpu.config import BrainConfig
    from foremast_tpu.jobs.worker import BrainWorker

    n, hist, cur = 16, 128, 30
    now = float(int(_time.time()))
    store, source, _w = build_mixed_fleet(n, hist, cur, now)
    cfg = BrainConfig(
        algorithm="moving_average_all",
        season_steps=24,
        max_cache_size=4 * n + 64,
    )
    worker = BrainWorker(
        store, source, config=cfg, claim_limit=n, worker_id="wit-fix"
    )
    wit = RecompileWitness().install()
    try:
        with wit.phase("cold"):
            assert worker.tick(now=now + 150) == n
        # first warm tick owns the pipelined warm path's one-time
        # compiles (same attribution the benches use)
        with wit.phase("pipeline_warmup"):
            assert worker.tick(now=now + 160) == n
        with wit.phase("warm"):
            for k in range(2):
                assert worker.tick(now=now + 170 + 10 * k) == n
        wit.assert_zero("warm")
        assert wit.count("cold") > 0  # the cold pass really compiled

        # doctored negative: an unbucketed shape inside a "warm" phase
        @jax.jit
        def _leak(v):
            return (v * 2.0).sum()

        with wit.phase("doctored"):
            _leak(jnp.ones((3, 7))).block_until_ready()
        with pytest.raises(AssertionError, match="dispatch cache"):
            wit.assert_zero("doctored")
    finally:
        wit.uninstall()
        worker.close()
