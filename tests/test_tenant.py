"""Multi-tenant QoS plane (ISSUE 20): resolution, envelopes,
weighted-fair scheduling, targeted backpressure, bounded-cardinality
attribution — and the parity pin that with zero or one tenant every
seam is byte-identical to the untenanted build."""

import json
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from foremast_tpu.ingest import RingStore, canonical_series
from foremast_tpu.jobs.models import Document
from foremast_tpu.metrics.promql import prometheus_url
from foremast_tpu.reactive import DirtySet
from foremast_tpu.tenant import (
    DEFAULT_TENANT,
    OTHER_TENANT,
    DeficitRoundRobin,
    TenantAccounting,
    TenantCollector,
    TenantRegistry,
    TenantSpec,
    accounting_for,
    set_tenancy,
    tenancy_from_env,
)


@pytest.fixture(autouse=True)
def _no_global_tenancy():
    """Every test starts and ends untenanted — the process-global
    registry must never leak between tests (workers, rings and dirty
    sets read it at construction)."""
    set_tenancy(None)
    yield
    set_tenancy(None)


def _reg(**spec_fields) -> TenantRegistry:
    return TenantRegistry(
        {
            "whale": TenantSpec(name="whale", **spec_fields),
            "quiet": TenantSpec(name="quiet"),
        }
    )


def _series(tenant: str, i: int) -> str:
    return canonical_series(
        f'up{{app="app{i}",namespace="t",tenant="{tenant}"}}'
    )


def _doc(s: int, tenant: str) -> Document:
    expr = f'latency{{app="app{s}",namespace="t",tenant="{tenant}"}}'
    url = prometheus_url(
        {"endpoint": "http://p/api/v1/", "query": expr,
         "start": 0, "end": 600, "step": 60}
    )
    return Document(
        id=f"job-{s}",
        app_name=f"app{s}",
        historical_config=f"latency== {url}",
        current_config=f"latency== {url}",
    )


# ---------------------------------------------------------------------------
# resolution + envelope config
# ---------------------------------------------------------------------------


def test_resolution_series_doc_and_key():
    reg = _reg()
    assert reg.tenant_of_series(_series("whale", 1)) == "whale"
    assert reg.tenant_of_series('up{app="a"}') == DEFAULT_TENANT
    assert reg.tenant_of_doc(_doc(3, "quiet")) == "quiet"
    assert reg.tenant_of_doc(Document(id="d", app_name="a")) == (
        DEFAULT_TENANT
    )
    # arena fit keys embed the URL-ENCODED selector
    url = prometheus_url(
        {"endpoint": "http://p/api/v1/",
         "query": 'up{app="a",tenant="whale"}',
         "start": 0, "end": 600, "step": 60}
    )
    assert reg.tenant_of_key(f"app|up|{url}") == "whale"


def test_custom_label_env():
    reg = TenantRegistry(
        {"a": TenantSpec(name="a"), "b": TenantSpec(name="b")},
        label="team",
    )
    assert reg.tenant_of_series('up{app="x",team="a"}') == "a"
    assert reg.tenant_of_series('up{app="x",tenant="a"}') == (
        DEFAULT_TENANT
    )


def test_tenancy_from_env_inline_path_and_errors(tmp_path):
    assert tenancy_from_env({}) is None
    spec = {"acme": {"weight": 4, "ring_bytes": 1024}, "default": {}}
    reg = tenancy_from_env({"FOREMAST_TENANTS": json.dumps(spec)})
    assert reg.weight("acme") == 4.0
    assert reg.spec("acme").ring_bytes == 1024
    assert reg.fair  # two tenants
    p = tmp_path / "tenants.json"
    p.write_text(json.dumps({"tenants": spec}))
    reg2 = tenancy_from_env({"FOREMAST_TENANTS": f"@{p}"})
    assert reg2.weight("acme") == 4.0
    single = tenancy_from_env(
        {"FOREMAST_TENANTS": json.dumps({"only": {"weight": 2}})}
    )
    assert single is not None and not single.fair
    with pytest.raises(ValueError):
        tenancy_from_env({"FOREMAST_TENANTS": "{not json"})
    with pytest.raises(ValueError):
        tenancy_from_env(
            {"FOREMAST_TENANTS": json.dumps({"x": {"bogus_field": 1}})}
        )


# ---------------------------------------------------------------------------
# bounded-cardinality attribution (the BrainGauges-style cap)
# ---------------------------------------------------------------------------


def test_tenant_label_cardinality_cap_and_lint_clean():
    reg = TenantRegistry(
        {"a": TenantSpec(name="a"), "b": TenantSpec(name="b")},
        label_max=3,
    )
    # configured tenants + default always keep their own label value
    assert reg.metric_tenant("a") == "a"
    assert reg.metric_tenant(DEFAULT_TENANT) == DEFAULT_TENANT
    # unconfigured values claim slots up to the cap...
    for i in range(3):
        assert reg.metric_tenant(f"u{i}") == f"u{i}"
    # ...then fold into `other`, counted once per dropped name
    assert reg.metric_tenant("u3") == OTHER_TENANT
    assert reg.metric_tenant("u4") == OTHER_TENANT
    assert reg.metric_tenant("u3") == OTHER_TENANT  # counted ONCE
    assert reg.dropped_label_values == 2
    # a slot claimed before the cap stays claimed
    assert reg.metric_tenant("u1") == "u1"
    # the capped exposition is lint-clean: every foremast_tenant_*
    # family carries exactly the documented {tenant} label set
    from prometheus_client import CollectorRegistry

    from foremast_tpu.observe.metrics_lint import lint_registry

    acct = TenantAccounting(reg)
    for t in ("a", "u0", "u9", "u10"):
        acct.count_shed(reg.metric_tenant(t))
        acct.add_ring_bytes(reg.metric_tenant(t), 64)
    registry = CollectorRegistry()
    registry.register(TenantCollector(acct))
    assert lint_registry(registry) == []
    snap = acct.snapshot()
    assert OTHER_TENANT in snap
    assert snap[OTHER_TENANT]["shed"] == 2  # u9 + u10 folded


def test_accounting_ring_bytes_clamped():
    acct = TenantAccounting(_reg())
    acct.add_ring_bytes("whale", 100)
    acct.add_ring_bytes("whale", -500)
    assert acct.snapshot()["whale"]["ring_bytes"] == 0


# ---------------------------------------------------------------------------
# weighted-fair scheduling: DRR, dirty-set drain, sweep pool
# ---------------------------------------------------------------------------


def test_drr_weighted_split():
    drr = DeficitRoundRobin({"a": 4.0, "b": 1.0})
    order = drr.pick({"a": 100, "b": 100}, 10)
    assert order.count("a") == 8 and order.count("b") == 2


def test_drr_empty_tenant_forfeits():
    drr = DeficitRoundRobin({"a": 1.0, "b": 1.0})
    order = drr.pick({"a": 10}, 4)
    assert order == ["a"] * 4
    # b arriving later starts fresh — no hoarded credit from rounds it
    # had nothing queued
    order = drr.pick({"a": 10, "b": 10}, 4)
    assert order.count("b") == 2


def test_fair_drain_no_starvation_past_one_slice():
    """The starvation pin: a whale marking 100 series BEFORE a quiet
    tenant's single arrival cannot push that arrival past one drain
    boundary — the first take() already serves the quiet tenant."""
    reg = _reg()
    dirty = DirtySet(max_keys=1024, tenancy=reg)
    now = time.time()
    for i in range(100):
        dirty.mark_series(_series("whale", i), now=now)
    dirty.mark_series(_series("quiet", 0), now=now + 0.001)
    first = [rk for rk, _ in dirty.take(8)]
    assert "app0" in first, first  # the quiet arrival made slice one
    # within a tenant the order stays oldest-first
    whale_part = [rk for rk in first if rk != "app0"]
    assert whale_part == sorted(
        whale_part, key=lambda rk: int(rk[3:])
    ), first


def test_fifo_drain_untenanted_and_single_tenant():
    """<=1 tenant: take() is the exact pre-ISSUE-20 FIFO pop."""
    for tenancy in (
        None,
        TenantRegistry({"only": TenantSpec(name="only")}),
    ):
        dirty = DirtySet(max_keys=64, tenancy=tenancy)
        now = time.time()
        for i in range(10):
            dirty.mark(f"rk{i}", now + i)
        assert [rk for rk, _ in dirty.take(4)] == [
            "rk0", "rk1", "rk2", "rk3",
        ]
        assert dirty.debug_state()["tenant_fair"] is False


def test_sweep_pool_fair_slice_order():
    """PR-15 slice boundaries are the preemption points: the sweep
    pool's take() interleaves tenants by deficit-weighted order, so a
    whale's 40 queued docs cannot fill slice one while a quiet
    tenant's docs wait."""
    from foremast_tpu.jobs.worker import _SweepPool

    reg = _reg()
    docs = [_doc(s, "whale") for s in range(40)]
    docs += [_doc(100 + s, "quiet") for s in range(4)]
    pool = _SweepPool(docs, tenancy=reg)
    first = [d.id for d in pool.take(8)]
    assert any(d.startswith("job-10") for d in first), first
    # untenanted pool keeps strict FIFO
    pool2 = _SweepPool(docs, tenancy=None)
    assert [d.id for d in pool2.take(8)] == [
        f"job-{s}" for s in range(8)
    ]
    # drain() leaves no queue residue
    pool.drain()
    assert pool.take(4) == []


# ---------------------------------------------------------------------------
# resource isolation: ring envelopes + arena envelopes
# ---------------------------------------------------------------------------


def test_ring_envelope_evicts_whale_not_quiet():
    reg = _reg(ring_bytes=8192)
    ring = RingStore(budget_bytes=1 << 20, shards=2, tenancy=reg)
    now = 1_000_000.0
    ts = np.arange(0, 600, 60, dtype=np.int64)
    vs = np.ones(len(ts), np.float32)
    for i in range(4):
        ring.push(_series("quiet", i), ts, vs, now=now)
    for i in range(200):
        ring.push(_series("whale", i), ts, vs, now=now)
    acct = accounting_for(reg).snapshot()
    assert acct["whale"]["evictions"] > 0
    assert acct.get("quiet", {}).get("evictions", 0) == 0
    # the whale stayed inside its envelope; the quiet series survived
    assert acct["whale"]["ring_bytes"] <= 8192
    for i in range(4):
        assert (
            ring.query(_series("quiet", i), 0.0, 600.0, now=now)
            is not None
        )


def test_ring_untenanted_parity():
    """Same pushes, no registry: byte-identical residency + stats to a
    single-tenant registry (the parity pin at the ring seam)."""
    def build(tenancy):
        ring = RingStore(budget_bytes=4096, shards=2, tenancy=tenancy)
        ts = np.arange(0, 600, 60, dtype=np.int64)
        vs = np.ones(len(ts), np.float32)
        for i in range(40):
            ring.push(_series("x", i), ts, vs, now=1e6)
        return ring.stats()

    single = TenantRegistry({"only": TenantSpec(name="only")})
    assert build(None) == build(single)


def test_arena_envelope_same_tenant_recycle():
    """An over-envelope tenant recycles its OWN least-recent rows; the
    quiet tenant's rows never move and every eviction is charged to
    the whale."""
    from foremast_tpu.engine.arena import StateArena

    def key(t, i):
        url = prometheus_url(
            {"endpoint": "http://p/api/v1/",
             "query": f'up{{app="a{i}",tenant="{t}"}}',
             "start": 0, "end": 600, "step": 60}
        )
        return f"a{i}|up|{url}"

    reg = _reg(arena_rows=4)
    set_tenancy(reg)
    arena = StateArena(4, max_bytes=1 << 16)
    assert arena._qos is not None
    arena.assign([key("quiet", i) for i in range(6)], [])
    for rnd in range(4):
        arena.assign(
            [key("whale", rnd * 8 + i) for i in range(8)], []
        )
        arena.assign([key("quiet", i) for i in range(6)], [])
    assert arena._qos.rows["quiet"] == 6
    for i in range(6):
        assert key("quiet", i) in arena.rows
    acct = accounting_for(reg).snapshot()
    assert acct["whale"]["evictions"] > 0
    assert acct.get("quiet", {}).get("evictions", 0) == 0


def test_arena_untenanted_and_single_tenant_parity():
    from foremast_tpu.engine.arena import StateArena

    seq = [
        [f"k{j}-{i}" for i in range(8)] for j in range(3)
    ]

    def rows(tenancy):
        set_tenancy(tenancy)
        arena = StateArena(4, max_bytes=1 << 14)
        out = [arena.assign(ks, [])[0].tolist() for ks in seq]
        assert arena._qos is None
        return out

    single = TenantRegistry({"only": TenantSpec(name="only")})
    assert rows(None) == rows(single)


# ---------------------------------------------------------------------------
# receiver fairness: 429 + Retry-After target the flooding tenant
# ---------------------------------------------------------------------------


def _post(port, payload):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/api/v1/write",
        data=json.dumps(payload).encode(),
        method="POST",
        headers={"Content-Type": "application/json"},
    )
    try:
        with urllib.request.urlopen(req, timeout=10) as resp:
            resp.read()
            return resp.status, dict(resp.headers)
    except urllib.error.HTTPError as e:
        e.read()
        return e.code, dict(e.headers)


def test_receiver_sheds_flooding_tenant_only():
    from foremast_tpu.ingest import start_ingest_server, stop_ingest_server

    reg = _reg(ingest_bytes_per_s=1024)  # whale burst = 2 KiB
    ring = RingStore(budget_bytes=1 << 20, shards=2, tenancy=reg)
    srv, _ = start_ingest_server(
        0, ring, host="127.0.0.1", tenancy=reg
    )
    port = srv.server_address[1]
    try:
        ts = list(range(0, 60 * 40, 60))

        def payload(tenant, i):
            return {
                "timeseries": [
                    {
                        "alias": _series(tenant, i),
                        "times": ts,
                        "values": [1.0] * len(ts),
                    }
                ]
            }

        whale_codes = []
        retry_after = None
        for i in range(8):  # ~25 KB total vs a 2 KiB burst
            code, hdrs = _post(port, payload("whale", i))
            whale_codes.append(code)
            if code == 429:
                retry_after = hdrs.get("Retry-After")
        assert 429 in whale_codes, whale_codes
        assert retry_after is not None and 1 <= int(retry_after) <= 60
        # the quiet tenant pushes through the SAME socket, unshed
        code, _ = _post(port, payload("quiet", 0))
        assert code == 200
        acct = accounting_for(reg).snapshot()
        assert acct["whale"]["shed"] == whale_codes.count(429)
        assert acct.get("quiet", {}).get("shed", 0) == 0
        # attribution is visible on the wire: /debug/state tenants
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/debug/state", timeout=10
        ) as resp:
            state = json.load(resp)
        assert state["tenants"]["accounting"]["whale"]["shed"] > 0
        assert "ingest_buckets" in state["tenants"]
    finally:
        stop_ingest_server(srv)


# ---------------------------------------------------------------------------
# verdict-latency attribution: the bounded tenant label on the SLO family
# ---------------------------------------------------------------------------


def test_verdict_latency_carries_tenant_label():
    from prometheus_client import CollectorRegistry

    from foremast_tpu.observe.gauges import WorkerMetrics

    registry = CollectorRegistry()
    metrics = WorkerMetrics(registry=registry)
    metrics.verdict_latency.labels(path="micro", tenant="acme").observe(
        0.2
    )
    sample_labels = [
        s.labels
        for m in registry.collect()
        if m.name == "foremast_verdict_latency_seconds"
        for s in m.samples
    ]
    assert all("tenant" in lb for lb in sample_labels)
    assert any(lb.get("tenant") == "acme" for lb in sample_labels)


def test_worker_registers_tenant_collector_on_metrics_registry():
    """A tenanted worker's scrape registry exports the four
    foremast_tenant_* families (the ledger the receiver shares), and a
    second worker on the same registry is a no-op, not a crash."""
    from prometheus_client import CollectorRegistry

    from foremast_tpu.config import BrainConfig
    from foremast_tpu.jobs.store import InMemoryStore
    from foremast_tpu.jobs.worker import BrainWorker
    from foremast_tpu.metrics.source import MetricSource
    from foremast_tpu.observe.gauges import WorkerMetrics

    set_tenancy(_reg())
    registry = CollectorRegistry()
    metrics = WorkerMetrics(registry=registry)
    src = MetricSource()
    w = BrainWorker(InMemoryStore(), src, BrainConfig(), metrics=metrics)
    BrainWorker(InMemoryStore(), src, BrainConfig(), metrics=metrics)
    w._tenant_acct.count_shed("whale")
    names = {m.name for m in registry.collect()}
    assert {
        "foremast_tenant_shed",
        "foremast_tenant_evictions",
        "foremast_tenant_claims",
        "foremast_tenant_ring_bytes",
    } <= names
    shed = [
        s
        for m in registry.collect()
        if m.name == "foremast_tenant_shed"
        for s in m.samples
        if s.labels.get("tenant") == "whale"
    ]
    assert shed and shed[0].value == 1

    # untenanted worker: no tenant families on a fresh registry
    set_tenancy(None)
    bare = CollectorRegistry()
    BrainWorker(
        InMemoryStore(), src, BrainConfig(),
        metrics=WorkerMetrics(registry=bare),
    )
    assert not any(
        m.name.startswith("foremast_tenant_") for m in bare.collect()
    )
