"""Slow-path chunk-pipeline coverage (jobs/pipeline.py + worker rework):
serial-vs-pipelined write equivalence on a mixed warm/cold claim set,
fetch-failure isolation mid-pipeline, clean exception drain, depth-1
degradation for `concurrent_fetch = False` sources, and the persistent
fetch-pool satellites."""

import threading
import time

import numpy as np
import pytest

from benchmarks.worker_bench import _add_service, build_fleet
from foremast_tpu.config import BrainConfig
from foremast_tpu.jobs import BrainWorker
from foremast_tpu.jobs.models import (
    STATUS_PREPROCESS_COMPLETED,
    STATUS_PREPROCESS_FAILED,
    STATUS_PREPROCESS_INPROGRESS,
)

NOW = 1_760_000_000.0
HIST_LEN = 256
CUR_LEN = 30


def _mk(services, chunk_docs=2, depth=2, algorithm="moving_average_all",
        hook=None, seed=0):
    """Worker over a worker_bench fleet, slow path forced (the fast
    path would otherwise consume the warm subset) and the source
    declaring blocking fetches so the pipeline may engage."""
    store, source = build_fleet(services, HIST_LEN, CUR_LEN, NOW, seed=seed)
    # ArraySource is in-memory (concurrent_fetch=False); pose as a
    # blocking source so the worker pools fetches + engages the pipeline
    source.concurrent_fetch = True
    cfg = BrainConfig(algorithm=algorithm, season_steps=24,
                      max_cache_size=4 * services + 64)
    worker = BrainWorker(
        store, source, config=cfg, claim_limit=2 * services,
        worker_id="pipe-w", on_verdict=hook,
    )
    worker.cold_chunk_docs = chunk_docs
    worker.pipeline_depth = depth
    worker._fast_tick = lambda docs, now: (0, docs)  # force slow path
    return worker, store, source


def _grow_fleet(store, source, sids, seed=42):
    """Add fresh (cold) services to an existing fleet, deterministically
    (same seed => identical series across two fleets)."""
    rng = np.random.default_rng(seed)
    t_now = int(NOW)
    ht = t_now - 86_400 * 7 + 60 * np.arange(HIST_LEN, dtype=np.int64)
    ct = ht[-1] + 60 + 60 * np.arange(CUR_LEN, dtype=np.int64)
    end_time = time.strftime(
        "%Y-%m-%dT%H:%M:%SZ", time.gmtime(t_now + 3600)
    )
    for sid in sids:
        _add_service(store, source, sid, ht, ct, HIST_LEN, CUR_LEN,
                     end_time, rng)


def _statuses(store):
    return {
        d.id: (d.status, d.reason, d.anomaly_info)
        for d in store._docs.values()
    }


def _record_writes(store):
    """Ordered (doc id, status) log of every store write."""
    writes = []
    orig_update, orig_many = store.update, store.update_many

    def _u(doc):
        writes.append((doc.id, doc.status))
        return orig_update(doc)

    def _um(docs):
        writes.extend((d.id, d.status) for d in docs)
        return orig_many(docs)

    store.update, store.update_many = _u, _um
    return writes


def test_pipelined_equals_serial_on_mixed_warm_cold_claims():
    """Tick 1 warms 6 services' fits; 4 cold services join; tick 2's
    claim set is then mixed warm/cold across 5 chunks. The pipelined
    worker must produce the identical statuses, anomaly payloads,
    ordered store-write sequence, verdicts, and fit-cache key set as
    the serial (depth-1) worker."""
    verdicts_a, verdicts_b = [], []
    hook_a = lambda doc, vs: verdicts_a.append(
        (doc.id, [(v.alias, v.verdict) for v in vs])
    )
    hook_b = lambda doc, vs: verdicts_b.append(
        (doc.id, [(v.alias, v.verdict) for v in vs])
    )
    a, a_store, a_src = _mk(6, chunk_docs=2, depth=2, hook=hook_a)
    b, b_store, b_src = _mk(6, chunk_docs=2, depth=1, hook=hook_b)

    assert a.tick(now=NOW + 150) == 6
    assert b.tick(now=NOW + 150) == 6
    assert _statuses(a_store) == _statuses(b_store)

    # cold newcomers (identical on both fleets), plus a current-window
    # spike on a warm doc so anomaly payloads cross the pipeline too
    _grow_fleet(a_store, a_src, ["n0", "n1", "n2", "n3"])
    _grow_fleet(b_store, b_src, ["n0", "n1", "n2", "n3"])
    for src in (a_src, b_src):
        url = next(u for u in src.data if "cur" in u and "latency:app2" in u)
        ct, cv = src.data[url]
        spiked = cv.copy()
        spiked[-3:] = 40.0
        src.data[url] = (ct, spiked)

    writes_a = _record_writes(a_store)
    writes_b = _record_writes(b_store)
    assert a.tick(now=NOW + 200) == 10
    assert b.tick(now=NOW + 200) == 10

    assert a._last_pipeline["pipelined"] is True
    assert a._last_pipeline["chunks"] == 5
    assert b._last_pipeline["pipelined"] is False
    assert _statuses(a_store) == _statuses(b_store)
    assert writes_a == writes_b  # same docs, same statuses, same ORDER
    assert verdicts_a == verdicts_b
    keys_a = sorted(map(str, a._fit_cache._d.keys()))
    keys_b = sorted(map(str, b._fit_cache._d.keys()))
    assert keys_a == keys_b and keys_a
    a.close()
    b.close()


def test_fetch_failure_marks_only_its_doc_mid_pipeline():
    """A fetch blowing up for one doc in a middle chunk must mark ONLY
    that doc preprocess_failed; every other doc (including later
    chunks, already prefetching) judges normally."""
    worker, store, source = _mk(8, chunk_docs=2, depth=2)
    orig_fetch = source.fetch

    def fetch(url):
        if "latency:app5" in url and "cur" in url:
            raise RuntimeError("boom")
        return orig_fetch(url)

    source.fetch = fetch
    assert worker.tick(now=NOW + 150) == 8
    assert worker._last_pipeline["pipelined"] is True
    sts = {d.id: d.status for d in store._docs.values()}
    assert sts.pop("job-5") == STATUS_PREPROCESS_FAILED
    assert store.get("job-5").reason == "metric fetch failed"
    assert all(s == STATUS_PREPROCESS_COMPLETED for s in sts.values())
    worker.close()


def test_judge_exception_drains_cleanly_and_persists_prior_chunks():
    """A judge failure on chunk 3 must: write every chunk judged before
    it (the writer drains its queue), leave later docs claimed-but-
    unjudged, join the writer thread, and leave the worker usable."""
    worker, store, source = _mk(8, chunk_docs=2, depth=2)
    orig_judge = worker.judge.judge
    calls = []

    def judge(tasks):
        calls.append(len(tasks))
        if len(calls) == 3:
            raise RuntimeError("device on fire")
        return orig_judge(tasks)

    worker.judge.judge = judge
    with pytest.raises(RuntimeError, match="device on fire"):
        worker.tick(now=NOW + 150)

    sts = {d.id: d.status for d in store._docs.values()}
    for sid in (0, 1, 2, 3):  # chunks 1-2: judged AND persisted
        assert sts[f"job-{sid}"] == STATUS_PREPROCESS_COMPLETED
    for sid in (4, 5, 6, 7):  # failing chunk onward: never judged
        assert sts[f"job-{sid}"] == STATUS_PREPROCESS_INPROGRESS
    assert len(calls) == 3  # feeding stopped at the failing chunk
    # the abort-path snapshot is surfaced and marked as such
    assert worker._last_pipeline["completed"] is False
    # clean drain: the per-tick writer thread is gone
    assert not [
        t for t in threading.enumerate() if t.name == "foremast-writeback"
    ]
    # the worker survives: chunks 1-2's docs are claimable again and a
    # fresh tick (judge healthy now) processes them through the same
    # pipeline machinery
    assert worker.tick(now=NOW + 200) == 4
    worker.close()


def test_fetch_failures_persist_even_when_judge_crashes():
    """The serial loop persisted a chunk's preprocess_failed markings
    BEFORE judging; the pipeline must not lose them when the judge
    dies on that same chunk — the writer persists the failures first,
    then re-raises the judge error on the tick thread."""
    worker, store, source = _mk(4, chunk_docs=2, depth=2)
    orig_fetch = source.fetch

    def fetch(url):
        if "latency:app2" in url and "cur" in url:  # doc in chunk 2
            raise RuntimeError("boom")
        return orig_fetch(url)

    source.fetch = fetch
    orig_judge = worker.judge.judge
    calls = []

    def judge(tasks):
        calls.append(len(tasks))
        if len(calls) == 2:  # chunk 2 — the one with the failed fetch
            raise RuntimeError("device on fire")
        return orig_judge(tasks)

    worker.judge.judge = judge
    with pytest.raises(RuntimeError, match="device on fire"):
        worker.tick(now=NOW + 150)
    sts = {d.id: d.status for d in store._docs.values()}
    assert sts["job-2"] == STATUS_PREPROCESS_FAILED  # not lost
    assert sts["job-0"] == sts["job-1"] == STATUS_PREPROCESS_COMPLETED
    worker.close()


def test_concurrent_fetch_false_degrades_to_depth_1():
    """Pod-mode LeaderSource (and in-memory sources) declare
    concurrent_fetch=False: fetch ORDER is load-bearing, so the
    pipeline must run the serial loop and never spawn pool threads."""
    worker, store, source = _mk(6, chunk_docs=2, depth=4)
    source.concurrent_fetch = False
    assert worker.tick(now=NOW + 150) == 6
    stats = worker._last_pipeline
    assert stats["pipelined"] is False
    assert stats["chunks"] == 3
    assert worker._fetch_pool is None and worker._prefetch_pool is None
    assert all(
        d.status == STATUS_PREPROCESS_COMPLETED
        for d in store._docs.values()
    )


def test_persistent_fetch_pool_reused_across_ticks(monkeypatch):
    """One pool per worker (FOREMAST_FETCH_WORKERS), not one per chunk
    per tick; FOREMAST_PIPELINE_DEPTH is read at construction; close()
    shuts both pools down and stays idempotent."""
    monkeypatch.setenv("FOREMAST_FETCH_WORKERS", "3")
    monkeypatch.setenv("FOREMAST_PIPELINE_DEPTH", "3")
    store, source = build_fleet(4, HIST_LEN, CUR_LEN, NOW)
    source.concurrent_fetch = True
    worker = BrainWorker(
        store, source, config=BrainConfig(algorithm="moving_average_all",
                                          season_steps=24),
        claim_limit=4, worker_id="pool-w",
    )
    assert worker.fetch_workers == 3
    assert worker.pipeline_depth == 3
    worker.cold_chunk_docs = 2
    worker._fast_tick = lambda docs, now: (0, docs)
    assert worker.tick(now=NOW + 150) == 4
    pool = worker._fetch_pool
    assert pool is not None and pool._max_workers == 3
    assert worker._prefetch_pool is not None
    assert worker.tick(now=NOW + 160) == 4
    assert worker._fetch_pool is pool  # reused, not rebuilt
    worker.close()
    assert worker._fetch_pool is None and worker._prefetch_pool is None
    worker.close()  # idempotent


# -- ChunkPipeline unit-level drain semantics ---------------------------


def _pipe(fetch, judge, write, depth=2):
    from concurrent.futures import ThreadPoolExecutor

    from foremast_tpu.jobs.pipeline import ChunkPipeline

    pool = ThreadPoolExecutor(max_workers=max(1, depth - 1))
    return ChunkPipeline(fetch, judge, write, depth=depth,
                         prefetch_pool=pool), pool


def test_pipeline_write_error_propagates_and_stops_feeding():
    written = []

    def write(chunk, result):
        if result == 2:
            raise ValueError("store down")
        written.append(result)

    pipe, pool = _pipe(lambda c: c, lambda c, p: p, write)
    with pytest.raises(ValueError, match="store down"):
        pipe.run([1, 2, 3, 4, 5])
    pool.shutdown(wait=True)
    # FIFO writer: chunk 1 landed, chunk 2 failed, later chunks drain
    # unwritten — fail fast exactly where the serial loop would stop
    assert written == [1]


def test_pipeline_fetch_error_surfaces_after_draining_writes():
    written = []

    def fetch(chunk):
        if chunk == 3:
            raise RuntimeError("fetch exploded")
        return chunk

    pipe, pool = _pipe(fetch, lambda c, p: p, lambda c, r: written.append(r))
    with pytest.raises(RuntimeError, match="fetch exploded"):
        pipe.run([1, 2, 3, 4])
    pool.shutdown(wait=True)
    assert written == [1, 2]  # everything judged before the failure


def test_pipeline_stage_error_writes_partial_and_aborts():
    """StageError from the judge: feeding stops immediately (no later
    chunk touches the broken judge), the carried partial result still
    rides the writer queue, and the wrapped error propagates."""
    from foremast_tpu.jobs.pipeline import StageError

    written, judged = [], []

    def judge(chunk, payload):
        judged.append(chunk)
        if chunk == 2:
            raise StageError(RuntimeError("dead"), ("partial", chunk))
        return payload

    pipe, pool = _pipe(lambda c: c, judge,
                       lambda c, r: written.append(r), depth=2)
    with pytest.raises(RuntimeError, match="dead"):
        pipe.run([1, 2, 3, 4])
    pool.shutdown(wait=True)
    assert judged == [1, 2]
    assert written == [1, ("partial", 2)]


def test_pipeline_stats_account_stages():
    pipe, pool = _pipe(lambda c: c, lambda c, p: p, lambda c, r: None,
                       depth=3)
    stats = pipe.run([1, 2, 3, 4])
    pool.shutdown(wait=True)
    assert stats.pipelined is True
    assert stats.chunks == 4
    assert stats.wall_seconds > 0
    d = stats.as_dict()
    assert d["depth"] == 3
    assert 0.0 <= d["overlap_ratio"] < 1.0
    # serial fallback: single chunk
    stats1 = pipe.run([1])
    assert stats1.pipelined is False
