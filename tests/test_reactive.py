"""Reactive-plane coverage (ISSUE 12): the dirty-series set, the
receiver's arrival-clock contract, ingest-triggered micro-ticks
(tick-path status parity, mesh ownership, brownout degradation, the
push→verdict latency histogram), and the streaming K8s watch against
the fake kube server's real chunked watch endpoint (resume, 410
re-list, stalls, torn disconnects).
"""

import threading
import time
import urllib.request
import json as _json

import numpy as np
import pytest

from foremast_tpu.config import BrainConfig
from foremast_tpu.ingest import (
    RingSource,
    RingStore,
    canonical_series,
    start_ingest_server,
    stop_ingest_server,
)
from foremast_tpu.jobs.models import (
    STATUS_COMPLETED_UNHEALTH,
    STATUS_PREPROCESS_COMPLETED,
    Document,
)
from foremast_tpu.jobs.store import InMemoryStore
from foremast_tpu.jobs.worker import BrainWorker
from foremast_tpu.metrics.promql import prometheus_url
from foremast_tpu.reactive import DirtySet
from tests.fake_kube_server import FakeKubeServer

NOW = 1_760_000_000.0
HIST_LEN = 256
CUR_LEN = 30


# ---------------------------------------------------------------------------
# DirtySet semantics
# ---------------------------------------------------------------------------


def test_dirty_mark_coalesces_to_earliest_and_takes_oldest_first():
    d = DirtySet(max_keys=16)
    d.mark("b", 2.0)
    d.mark("a", 5.0)
    d.mark("a", 3.0)  # coalesce keeps the EARLIEST arrival
    d.mark("a", 9.0)  # later arrival never advances the stamp
    assert len(d) == 2
    assert d.take(1) == [("b", 2.0)]  # oldest-marked first
    assert d.take(8) == [("a", 3.0)]
    assert len(d) == 0
    c = d.counts()
    assert c["marked"] == 2 and c["coalesced"] == 2


def test_dirty_bounded_drop_oldest_with_counter_never_a_leak():
    d = DirtySet(max_keys=3)
    for i in range(10):
        d.mark(f"k{i}", float(i))
    assert len(d) == 3
    assert d.counts()["dropped"] == 7
    # the survivors are the NEWEST marks (oldest dropped)
    assert [k for k, _ in d.take_all()] == ["k7", "k8", "k9"]


def test_dirty_route_key_extraction_and_ownership_filter():
    owned = []
    d = DirtySet(owns=lambda key: key not in owned)
    # selector carrying the route label -> the app value is the key
    assert d.mark_series('up{app="svc1",ns="x"}', now=1.0)
    assert d.take_all() == [("svc1", 1.0)]
    # label-less series -> the whole canonical key routes
    assert d.mark_series("sum(rate(x[5m]))", now=2.0)
    assert d.take_all() == [("sum(rate(x[5m]))", 2.0)]
    # foreign (ownership predicate rejects): counted, never marked
    owned.append('up{app="svc2"}')
    assert not d.mark_series('up{app="svc2"}', now=3.0)
    assert len(d) == 0
    assert d.counts()["foreign"] == 1


def test_dirty_requeue_preserves_original_stamp():
    d = DirtySet()
    d.mark("app", 10.0)
    (k, stamp), = d.take(1)
    d.mark(k, stamp, requeue=True)
    assert d.take_all() == [("app", 10.0)]
    c = d.counts()
    assert c["requeued"] == 1 and c["marked"] == 1


def test_dirty_requeue_drains_before_fresher_marks():
    """A requeued arrival carries the OLDEST running SLO clock — it
    must re-enter at the FRONT of the drain order, not behind marks
    that arrived while its micro-tick was failing (priority
    inversion would inflate exactly the p99 the histogram bounds)."""
    d = DirtySet()
    d.mark("old", 1.0)
    (k, stamp), = d.take(1)
    d.mark("fresh", 50.0)
    d.mark(k, stamp, requeue=True)
    assert d.take(1) == [("old", 1.0)]
    assert d.take_all() == [("fresh", 50.0)]


def test_reactive_knob_parsing_tolerates_malformed_env(monkeypatch):
    """A templated manifest leaving a knob empty or garbled must not
    kill worker startup: warn-and-default, cli._env_int's policy."""
    from foremast_tpu.reactive.dirty import (
        microtick_docs_from_env,
        microtick_seconds_from_env,
    )

    monkeypatch.setenv("FOREMAST_MICROTICK_SECONDS", "")
    monkeypatch.setenv("FOREMAST_MICROTICK_DOCS", "nope")
    monkeypatch.setenv("FOREMAST_MICROTICK_DIRTY_MAX", "1e4")
    assert microtick_seconds_from_env() == 0.0
    assert microtick_docs_from_env() == 256
    assert DirtySet.from_env().max_keys == 8192


# ---------------------------------------------------------------------------
# receiver arrival clock (satellite: SLO immune to pusher clock skew)
# ---------------------------------------------------------------------------


def _post(url: str, body: dict) -> dict:
    req = urllib.request.Request(
        url,
        data=_json.dumps(body).encode(),
        method="POST",
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=5) as resp:
        return _json.loads(resp.read())


def test_receiver_stamps_arrival_with_its_own_clock_not_the_pushers():
    ring = RingStore(shards=1)
    dirty = DirtySet()
    srv, _ = start_ingest_server(0, ring, host="127.0.0.1", dirty=dirty)
    try:
        port = srv.server_address[1]
        # sample timestamps DECADES in the past — a skewed/replaying
        # pusher; the dirty stamp must be this process's wall clock
        before = time.time()
        out = _post(
            f"http://127.0.0.1:{port}/api/v1/write",
            {
                "timeseries": [
                    {
                        "alias": 'm{app="skewed"}',
                        "times": [1_000_000_000, 1_000_000_060],
                        "values": [1.0, 2.0],
                    }
                ]
            },
        )
        assert out["accepted_samples"] == 2
        (key, stamp), = dirty.take_all()
        assert key == "skewed"
        assert before - 1.0 <= stamp <= time.time() + 1.0
        # a re-push marks again (a last-write-wins revision of an
        # existing stamp is exactly the spike-correction case that
        # must re-judge)
        out = _post(
            f"http://127.0.0.1:{port}/api/v1/write",
            {
                "timeseries": [
                    {
                        "alias": 'm{app="skewed"}',
                        "times": [1_000_000_000, 1_000_000_060],
                        "values": [1.0, 9.0],
                    }
                ]
            },
        )
        assert out["accepted_samples"] == 2
        assert len(dirty) == 1
    finally:
        stop_ingest_server(srv)


# ---------------------------------------------------------------------------
# micro-ticks
# ---------------------------------------------------------------------------


def _build_push_fleet(services: int):
    """Pure-push fleet: docs in an InMemoryStore, histories + currents
    resident in a ring (continuous strategy, no baselines)."""
    rng = np.random.default_rng(0)
    store = InMemoryStore()
    ring = RingStore(shards=2)
    t_now = int(NOW)
    ht = t_now - 86_400 * 7 + 60 * np.arange(HIST_LEN, dtype=np.int64)
    ct = ht[-1] + 60 + 60 * np.arange(CUR_LEN, dtype=np.int64)
    end_time = time.strftime(
        "%Y-%m-%dT%H:%M:%SZ", time.gmtime(t_now + 3600)
    )
    keys = []
    for s in range(services):
        expr = f'lat{{namespace="ns",app="app{s}"}}'
        key = canonical_series(expr)
        keys.append(key)
        hv = rng.normal(1.0, 0.1, HIST_LEN).astype(np.float32)
        cv = np.ones(CUR_LEN, np.float32)
        ring.push(
            key,
            np.concatenate([ht, ct]),
            np.concatenate([hv, cv]),
            start=float(ht[0]),
            now=NOW,
        )
        cur_url = prometheus_url(
            {"endpoint": "http://p/api/v1/", "query": expr,
             "start": int(ct[0]), "end": int(ct[-1]), "step": 60}
        )
        hist_url = prometheus_url(
            {"endpoint": "http://p/api/v1/", "query": expr,
             "start": int(ht[0]), "end": int(ht[-1]), "step": 60}
        )
        store.create(
            Document(
                id=f"job-{s}",
                app_name=f"app{s}",
                end_time=end_time,
                current_config=f"latency== {cur_url}",
                historical_config=f"latency== {hist_url}",
                strategy="continuous",
            )
        )
    return store, ring, keys, ht, ct


def _mk_worker(store, ring, services, dirty=None, metrics=None, mesh=None):
    cfg = BrainConfig(
        algorithm="moving_average_all", season_steps=24,
        max_cache_size=services + 16,
    )
    return BrainWorker(
        store,
        RingSource(ring, fallback=None),
        config=cfg,
        claim_limit=max(services, 4),
        worker_id="reactive-w",
        dirty=dirty,
        metrics=metrics,
        mesh=mesh,
    )


def _statuses(store):
    return {
        d.id: (d.status, d.reason, d.anomaly_info)
        for d in store._docs.values()
    }


def test_micro_tick_claims_only_dirty_docs():
    store, ring, keys, ht, ct = _build_push_fleet(3)
    dirty = DirtySet()
    w = _mk_worker(store, ring, 3, dirty=dirty)
    assert w.micro_tick(now=NOW + 150) == 0  # nothing dirty, no claim
    dirty.mark_series(keys[1], now=NOW)
    assert w.micro_tick(now=NOW + 150) == 1
    sts = {d.id: d.status for d in store._docs.values()}
    assert sts["job-1"] == STATUS_PREPROCESS_COMPLETED
    assert sts["job-0"] == "initial" and sts["job-2"] == "initial"
    assert len(dirty) == 0


def test_micro_tick_status_byte_identical_to_full_tick():
    """THE tick-path parity pin (acceptance): the same doc judged by a
    micro-tick and by a full tick produces byte-identical statuses,
    reasons and anomaly payloads — cold first judgment, warm re-check,
    and an anomaly-flagging re-check after a spiking push."""
    store_a, ring_a, keys_a, ht, ct = _build_push_fleet(3)
    store_b, ring_b, keys_b, _, _ = _build_push_fleet(3)
    wa = _mk_worker(store_a, ring_a, 3)  # tick-paced
    db = DirtySet()
    wb = _mk_worker(store_b, ring_b, 3, dirty=db)  # reactive

    # cold first judgment
    assert wa.tick(now=NOW + 150) == 3
    for k in keys_b:
        db.mark_series(k, now=NOW)
    assert wb.micro_tick(now=NOW + 150) == 3
    assert _statuses(store_a) == _statuses(store_b)

    # warm re-check after a spiking push on app1 (both rings)
    spike = np.full(3, 40.0, np.float32)
    for ring, keys in ((ring_a, keys_a), (ring_b, keys_b)):
        ring.push(keys[1], ct[-3:], spike, now=NOW)
    assert wa.tick(now=NOW + 300) == 3
    db.mark_series(keys_b[1], now=NOW)
    assert wb.micro_tick(now=NOW + 300) == 1
    a = _statuses(store_a)
    assert a["job-1"] == _statuses(store_b)["job-1"]
    assert a["job-1"][0] == STATUS_COMPLETED_UNHEALTH


class _StubMesh:
    """Just enough MeshNode surface for the worker: a claim filter
    that rejects a fixed app set."""

    handoff = None
    draining = False

    def __init__(self, rejected_apps):
        self.rejected = set(rejected_apps)

    def on_tick(self):
        pass

    def claim_filter(self, doc) -> bool:
        return doc.app_name not in self.rejected

    def debug_state(self):
        return {"stub": True}


def test_micro_tick_composes_with_mesh_partition_filter():
    """Dirty routing respects partition ownership: a dirty key whose
    doc the mesh filter rejects is never claimed (and its arrival is
    dropped as unattributed, not leaked)."""
    store, ring, keys, ht, ct = _build_push_fleet(2)
    dirty = DirtySet()
    w = _mk_worker(
        store, ring, 2, dirty=dirty, mesh=_StubMesh({"app0"})
    )
    dirty.mark_series(keys[0], now=NOW)
    dirty.mark_series(keys[1], now=NOW)
    assert w.micro_tick(now=NOW + 150) == 1
    sts = {d.id: d.status for d in store._docs.values()}
    assert sts["job-1"] == STATUS_PREPROCESS_COMPLETED
    assert sts["job-0"] == "initial"
    assert dirty.counts()["unattributed"] == 1


class _BrownoutStore(InMemoryStore):
    """First N claims fail transiently (a store brownout)."""

    def __init__(self, fail_claims: int = 1):
        super().__init__()
        self.fail_claims = fail_claims

    def claim(self, *a, **kw):
        if self.fail_claims > 0:
            self.fail_claims -= 1
            raise ConnectionError("injected store brownout")
        return super().claim(*a, **kw)


def test_micro_tick_claim_brownout_requeues_arrivals_unspent():
    """A store brownout mid-micro-tick must not lose arrivals: the
    pending keys go back to the dirty set with their ORIGINAL stamps
    (the SLO clock keeps running), and the next cycle judges them."""
    store, ring, keys, ht, ct = _build_push_fleet(1)
    docs = list(store._docs.values())
    brown = _BrownoutStore(fail_claims=1)
    for d in docs:
        brown.create(d)
    dirty = DirtySet()
    w = _mk_worker(brown, ring, 1, dirty=dirty)
    dirty.mark_series(keys[0], now=NOW)
    assert w.micro_tick(now=NOW + 150) == 0  # degraded to empty tick
    assert dirty.counts()["requeued"] == 1
    (key, stamp), = dirty.take_all()
    assert key == "app0" and stamp == NOW  # original stamp preserved
    dirty.mark(key, stamp, requeue=True)
    assert w.micro_tick(now=NOW + 150) == 1  # store healed: judged


class _FlakySource:
    """Delegates to a RingSource but fails the first fetch batch
    transiently (dependency outage during a micro-tick)."""

    def __init__(self, inner, fail_fetches: int):
        self.inner = inner
        self.fail_fetches = fail_fetches
        self.concurrent_fetch = False

    def fetch(self, url):
        if self.fail_fetches > 0:
            self.fail_fetches -= 1
            raise ConnectionError("injected fetch outage")
        return self.inner.fetch(url)

    def __getattr__(self, name):
        # hist_columns / hist_coverage / ingest_debug_state pass through
        return getattr(self.inner, name)


def test_micro_tick_fetch_outage_releases_docs_and_requeues_arrival():
    """Satellite pin: a dependency outage during a micro-tick RELEASES
    the dirty docs un-judged — status back to preprocess_completed,
    claimable by the next sweep — and the arrival returns to the dirty
    set with its original stamp."""
    store, ring, keys, ht, ct = _build_push_fleet(1)
    dirty = DirtySet()
    cfg = BrainConfig(
        algorithm="moving_average_all", season_steps=24, max_cache_size=16
    )
    flaky = _FlakySource(RingSource(ring, fallback=None), fail_fetches=1)
    w = BrainWorker(
        store, flaky, config=cfg, claim_limit=4,
        worker_id="flaky-w", dirty=dirty,
    )
    dirty.mark_series(keys[0], now=NOW)
    w.micro_tick(now=NOW + 150)
    # released un-judged: claimable (preprocess_completed), no verdict
    doc = store._docs["job-0"]
    assert doc.status == STATUS_PREPROCESS_COMPLETED
    assert doc.anomaly_info is None
    assert w._degrade.stats.docs_snapshot().get("fetch_released") == 1
    # the arrival survived with its original stamp
    (key, stamp), = dirty.take_all()
    assert key == "app0" and stamp == NOW
    # next micro-tick (dependency healed) judges it for real
    dirty.mark(key, stamp, requeue=True)
    assert w.micro_tick(now=NOW + 150) == 1


def _hist_samples(registry, name, labels):
    for metric in registry.collect():
        for s in metric.samples:
            if s.name == name and all(
                s.labels.get(k) == v for k, v in labels.items()
            ):
                return s.value
    return None


def test_verdict_latency_histogram_micro_and_sweep_paths():
    from prometheus_client import CollectorRegistry

    from foremast_tpu.observe.gauges import WorkerMetrics

    registry = CollectorRegistry()
    metrics = WorkerMetrics(registry=registry)
    store, ring, keys, ht, ct = _build_push_fleet(2)
    dirty = DirtySet()
    w = _mk_worker(store, ring, 2, dirty=dirty, metrics=metrics)
    # arrival ~1.2 s ago on the REAL wall clock (the observation side
    # runs on time.time(); the judgment 'now' stays the fleet's clock)
    dirty.mark("app0", time.time() - 1.2)
    assert w.micro_tick(now=NOW + 150) == 1
    n_micro = _hist_samples(
        registry, "foremast_verdict_latency_seconds_count",
        {"path": "micro"},
    )
    s_micro = _hist_samples(
        registry, "foremast_verdict_latency_seconds_sum",
        {"path": "micro"},
    )
    assert n_micro == 1 and 1.0 <= s_micro <= 30.0
    # a FULL tick drains whatever the micro-ticks missed: path="sweep"
    dirty.mark("app1", time.time() - 0.5)
    assert w.tick(now=NOW + 150) >= 1
    assert (
        _hist_samples(
            registry, "foremast_verdict_latency_seconds_count",
            {"path": "sweep"},
        )
        == 1
    )
    assert _hist_samples(
        registry, "foremast_microtick_docs_total", {}
    ) == 1


# ---------------------------------------------------------------------------
# streaming watch against the fake kube server
# ---------------------------------------------------------------------------


def _dep(name, ns="ns", labels=None):
    return {
        "metadata": {
            "name": name,
            "namespace": ns,
            "uid": f"uid-{name}",
            **({"labels": labels} if labels else {}),
        }
    }


def _informer(srv, events):
    from foremast_tpu.reactive.watchstream import StreamingInformer
    from foremast_tpu.watch.kubeapi import HttpKube

    kube = HttpKube(base_url=srv.url, token="t")
    return StreamingInformer(
        kube,
        lambda e, d, old: events.append((e, d["metadata"]["name"])),
    )


def test_watch_stream_dispatches_on_arrival():
    events = []
    with FakeKubeServer() as srv:
        srv.state.put("deployments", "ns", _dep("d1"))
        inf = _informer(srv, events)
        inf.resync()
        assert events == [("add", "d1")]

        def later():
            time.sleep(0.15)
            srv.state.put("deployments", "ns", _dep("d2"))

        t = threading.Thread(target=later)
        t.start()
        t0 = time.monotonic()
        seen_at = None
        # the event must arrive well inside the window, not at its end
        assert inf.consume(1.0, stall_margin=1.0) >= 1
        t.join()
        assert ("add", "d2") in events
        # a subsequent update dispatches too, with the previous object
        srv.state.put("deployments", "ns", _dep("d2", labels={"v": "2"}))
        assert inf.consume(1.0, stall_margin=1.0) >= 1
        assert events[-1] == ("update", "d2")
        assert inf.counts["events"] >= 2


def test_watch_stream_resume_after_torn_disconnect_no_loss():
    events = []
    with FakeKubeServer() as srv:
        inf = _informer(srv, events)
        inf.resync()
        srv.state.put("deployments", "ns", _dep("d1"))
        srv.state.put("deployments", "ns", _dep("d2"))
        # first event streams whole, second tears mid-JSON-line
        srv.state.add_watch_fault(disconnect=True, after_events=1)
        inf.consume(1.0, stall_margin=0.5)
        assert events == [("add", "d1")]
        # resume from the last APPLIED rv: d2 arrives exactly once
        inf.consume(1.0, stall_margin=0.5)
        assert events == [("add", "d1"), ("add", "d2")]


def test_watch_stream_410_gone_relists_and_recovers():
    events = []
    with FakeKubeServer() as srv:
        srv.state.put("deployments", "ns", _dep("d1"))
        inf = _informer(srv, events)
        inf.resync()
        # changes land while the stream is down, then the resume rv
        # expires: consume must re-list and DIFF (no loss, no dup)
        srv.state.put("deployments", "ns", _dep("d2"))
        srv.state.add_watch_fault(gone=True)
        inf.consume(0.5, stall_margin=0.5)
        assert inf.counts["restart_gone"] == 1
        assert events == [("add", "d1"), ("add", "d2")]
        # the informer is live again: new events stream normally
        srv.state.put("deployments", "ns", _dep("d3"))
        inf.consume(0.5, stall_margin=0.5)
        assert ("add", "d3") in events


def test_watch_stream_natural_compaction_answers_410():
    events = []
    with FakeKubeServer() as srv:
        srv.state.watch_cap = 4
        inf = _informer(srv, events)
        inf.resync()  # rv = 0-ish baseline
        for i in range(12):  # blow past the event window
            srv.state.put("deployments", "ns", _dep(f"d{i}"))
        inf.consume(0.5, stall_margin=0.5)
        # the stale resume point got 410; the re-list recovered ALL
        # twelve deployments exactly once each
        assert inf.counts["restart_gone"] == 1
        adds = sorted(n for e, n in events if e == "add")
        assert adds == sorted(f"d{i}" for i in range(12))


def test_watch_stream_gone_with_failed_relist_recovers_next_window():
    """410 whose recovery re-list ALSO fails (apiserver still down at
    that instant) must not park the stream until the 30 s repair
    sweep: the next consume() retries the list and detection resumes
    the moment the server does."""
    events = []
    with FakeKubeServer() as srv:
        inf = _informer(srv, events)
        inf.resync()
        srv.state.put("deployments", "ns", _dep("d1"))
        # the 410 fires, then the recovery re-list fails once
        srv.state.add_watch_fault(gone=True)
        real_list = inf.kube.list_deployments_rv
        failed = []

        def flaky_list(ns=None):
            if not failed:
                failed.append(1)
                raise ConnectionError("injected list outage")
            return real_list(ns)

        inf.kube.list_deployments_rv = flaky_list
        inf.consume(0.5, stall_margin=0.5)
        assert inf.counts["restart_gone"] == 1
        assert events == []  # recovery list failed; nothing delivered
        # server healed: the NEXT window re-lists and delivers
        inf.consume(0.5, stall_margin=0.5)
        assert events == [("add", "d1")]


def test_watch_stream_midstream_error_event_counts_error_restart():
    """A non-410 mid-stream ERROR event (etcd leader change, internal
    server failure) is an ERROR restart, never a benign clean end —
    the runbook keys on foremast_watch_stream_restarts{reason}."""
    events = []
    with FakeKubeServer() as srv:
        inf = _informer(srv, events)
        inf.resync()
        srv.state.put("deployments", "ns", _dep("d1"))
        srv.state.add_watch_fault(error_code=500)
        inf.consume(0.5, stall_margin=0.5)
        assert inf.counts["restart_error"] == 1
        assert inf.counts["restart_end"] == 0
        inf.consume(0.5, stall_margin=0.5)
        assert events == [("add", "d1")]


def test_watch_stream_midstream_410_event_relists():
    """The apiserver's OTHER 410 shape — a 200 stream that opens and
    immediately writes the ERROR/code-410 event — takes the same
    re-list recovery as an answered 410."""
    events = []
    with FakeKubeServer() as srv:
        srv.state.put("deployments", "ns", _dep("d1"))
        inf = _informer(srv, events)
        inf.resync()
        srv.state.put("deployments", "ns", _dep("d2"))
        srv.state.add_watch_fault(error_code=410)
        inf.consume(0.5, stall_margin=0.5)
        assert inf.counts["restart_gone"] == 1
        assert events == [("add", "d1"), ("add", "d2")]


def test_watch_stream_stall_detected_and_recovered():
    events = []
    with FakeKubeServer() as srv:
        inf = _informer(srv, events)
        inf.resync()
        srv.state.put("deployments", "ns", _dep("d1"))
        srv.state.add_watch_fault(stall_seconds=5.0, after_events=0)
        t0 = time.monotonic()
        inf.consume(1.0, stall_margin=0.5)
        # the stall margin fired well before the 5 s injected stall
        assert time.monotonic() - t0 < 4.0
        assert inf.counts["restart_stall"] == 1
        assert events == []
        inf.consume(1.0, stall_margin=0.5)
        assert events == [("add", "d1")]


def test_watch_answered_4xx_never_opens_the_kube_breaker():
    """A config error on the watch path (RBAC 403 on every reconnect)
    must not open the SHARED kube breaker and short-circuit the whole
    controller: an answered non-transient status is proof the endpoint
    is alive (_req's policy); a transport failure still counts."""
    import urllib.error

    from foremast_tpu.chaos.breaker import CircuitBreaker
    from foremast_tpu.watch.kubeapi import HttpKube

    with FakeKubeServer() as srv:
        breaker = CircuitBreaker("kube", failure_threshold=2)
        kube = HttpKube(base_url=srv.url, token="t", breaker=breaker)
        for _ in range(4):
            srv.state.add_fault(
                path="deployments", method="GET", status=403
            )
            with pytest.raises(urllib.error.HTTPError):
                list(
                    kube.watch_deployments(
                        resource_version="1", timeout_seconds=1,
                        stall_margin=0.5,
                    )
                )
        assert breaker.state == "closed"
    # transport failures DO count: the server is gone now
    for _ in range(2):
        with pytest.raises(OSError):
            list(
                kube.watch_deployments(
                    resource_version="1", timeout_seconds=1,
                    stall_margin=0.5,
                )
            )
    assert breaker.state == "open"


def test_watch_plane_selects_streaming_informer():
    from foremast_tpu.reactive.watchstream import StreamingInformer
    from foremast_tpu.watch.kubeapi import HttpKube, InMemoryKube
    from foremast_tpu.watch.plane import WatchPlane

    with FakeKubeServer() as srv:
        plane = WatchPlane(
            HttpKube(base_url=srv.url, token="t"), stream=True
        )
        assert plane.stream
        assert isinstance(plane.informer, StreamingInformer)
        state = plane.debug_state()
        assert state["watch_stream"] is True and "stream" in state
    # InMemoryKube cannot stream: the poll informer stays, silently
    plane = WatchPlane(InMemoryKube(), stream=True)
    assert not plane.stream


def test_watch_plane_run_stream_dispatches_and_stops():
    """One run_stream pass against the real fake server: a deployment
    applied mid-run reaches the handler without waiting for a resync,
    and the stop callable exits the loop."""
    from foremast_tpu.watch.kubeapi import HttpKube
    from foremast_tpu.watch.plane import WatchPlane

    events = []
    with FakeKubeServer() as srv:
        plane = WatchPlane(
            HttpKube(base_url=srv.url, token="t"), stream=True
        )
        # observe the raw informer events (barrelman needs namespace
        # annotations + CRDs; the dispatch path is what this pins)
        plane.informer.handler = lambda e, d, old: events.append(
            (e, d["metadata"]["name"])
        )
        rounds = []

        def stop():
            rounds.append(1)
            if len(rounds) == 2:
                srv.state.put("deployments", "ns", _dep("live"))
            return len(rounds) > 3

        plane.run_stream(stop)
        assert ("add", "live") in events
