"""Columnar fast-tick coverage (ADVICE r4): engagement, slow-path parity,
band-mode fidelity, and per-key admission revalidation under churn.

The fast path (`jobs/worker.py _fast_tick` + `judge.judge_columnar`) is
the default production route for every warm re-check tick, so these tests
pin (a) that it actually engages on settled query_range-style URLs,
(b) that its verdicts/anomaly_info match the object path bit for bit for
both the deployed default and a gap-sensitive seasonal algorithm, and
(c) that hooks receive the same band shape on warm ticks as cold ones.
"""

import numpy as np
import pytest

from benchmarks.worker_bench import build_fleet, build_mixed_fleet
from foremast_tpu.config import BrainConfig
from foremast_tpu.jobs import (
    BrainWorker,
    STATUS_COMPLETED_UNHEALTH,
    STATUS_PREPROCESS_COMPLETED,
)

NOW = 1_760_000_000.0
HIST_LEN = 512
CUR_LEN = 30


def _mk_worker(services, algorithm, season, band_mode="last", hook=None,
               seed=0, baseline_frac=0.0):
    if baseline_frac > 0:
        store, source, _ = build_mixed_fleet(
            services, HIST_LEN, CUR_LEN, NOW, seed=seed,
            baseline_frac=baseline_frac,
        )
    else:
        store, source = build_fleet(
            services, HIST_LEN, CUR_LEN, NOW, seed=seed
        )
    cfg = BrainConfig(algorithm=algorithm, season_steps=season,
                      max_cache_size=4 * services + 64)
    worker = BrainWorker(
        store, source, config=cfg, claim_limit=services,
        worker_id="fast-w", band_mode=band_mode, on_verdict=hook,
    )
    return worker, store, source


def _count_columnar(worker):
    """Wrap the univariate judge's judge_columnar with a call counter."""
    calls = []
    orig = worker._uni.judge_columnar

    def counting(*a, **kw):
        calls.append(1)
        return orig(*a, **kw)

    worker._uni.judge_columnar = counting
    return calls


def _statuses(store):
    return {
        d.id: (d.status, d.reason, d.anomaly_info)
        for d in store._docs.values()
    }


def _force_slow(worker):
    worker._fast_tick = lambda docs, now: (0, docs)


@pytest.mark.parametrize(
    "algorithm,season",
    [("moving_average_all", 24), ("auto_univariate", 24)],
    ids=["deployed-default", "gap-sensitive-seasonal"],
)
def test_fast_path_engages_and_matches_slow_path(algorithm, season):
    """Two ticks: tick 1 is cold (object path fits + caches), tick 2 must
    take the columnar path and produce the SAME statuses and anomaly_info
    the object path would (ADVICE r4 medium: zero fast-path coverage)."""
    services = 6
    fast_w, fast_store, fast_src = _mk_worker(services, algorithm, season)
    slow_w, slow_store, slow_src = _mk_worker(services, algorithm, season)
    _force_slow(slow_w)
    calls = _count_columnar(fast_w)

    assert fast_w.tick(now=NOW + 150) == services
    assert slow_w.tick(now=NOW + 150) == services
    assert not calls, "cold tick must not take the fast path"
    assert _statuses(fast_store) == _statuses(slow_store)

    # spike one service's current window before the re-check tick so the
    # fast path must carry anomaly pairs through to anomaly_info
    for src in (fast_src, slow_src):
        url = next(u for u in src.data if "cur" in u and "latency:app3" in u)
        ct, cv = src.data[url]
        spiked = cv.copy()
        spiked[-3:] = 40.0
        src.data[url] = (ct, spiked)

    assert fast_w.tick(now=NOW + 200) == services
    assert slow_w.tick(now=NOW + 200) == services
    assert calls, "warm re-check tick must take the columnar fast path"
    fast_s, slow_s = _statuses(fast_store), _statuses(slow_store)
    assert fast_s == slow_s
    spiked_status = fast_s["job-3"]
    assert spiked_status[0] == STATUS_COMPLETED_UNHEALTH
    # anomaly_info carries per-alias flat [t, v, ...] pairs
    assert "latency" in spiked_status[2]["values"]
    healthy = [v for k, v in fast_s.items() if k != "job-3"]
    assert all(s[0] == STATUS_PREPROCESS_COMPLETED for s in healthy)


def test_fast_path_full_band_mode_keeps_band_shape():
    """band_mode="full" + an on_verdict hook must see the whole [Tc] band
    on BOTH cold and warm ticks (ADVICE r4 medium: the fast path silently
    truncated warm bands to length 1)."""
    band_lens = []

    def hook(doc, verdicts):
        band_lens.append([len(v.upper) for v in verdicts])

    worker, store, _ = _mk_worker(
        3, "moving_average_all", 24, band_mode="full", hook=hook
    )
    calls = _count_columnar(worker)
    worker.tick(now=NOW + 150)
    worker.tick(now=NOW + 200)
    assert calls, "warm tick should engage the fast path"
    assert band_lens, "hook never ran"
    for lens in band_lens:
        assert all(n == CUR_LEN for n in lens), band_lens


def test_fast_path_last_band_mode_is_length_one_on_warm():
    """Default band_mode="last": hooks get a length-1 band (documented
    contract — `upper[-1]` consumers) on every tick."""
    band_lens = []

    def hook(doc, verdicts):
        band_lens.append([len(v.upper) for v in verdicts])

    worker, _, _ = _mk_worker(
        3, "moving_average_all", 24, band_mode="last", hook=hook
    )
    worker.tick(now=NOW + 150)
    worker.tick(now=NOW + 200)
    assert all(n == 1 for lens in band_lens for n in lens)


def test_admission_revalidates_per_key_not_wholesale():
    """A fit-cache version bump (churn: one cold fit somewhere) must NOT
    force a full admission re-walk: entries whose fit objects are
    unchanged revalidate by identity and stay admitted; an entry whose
    fit was replaced under the same key is re-admitted with the new
    object (VERDICT r4 ask #4)."""
    services = 4
    worker, store, src = _mk_worker(services, "moving_average_all", 24)
    worker.tick(now=NOW + 150)
    worker.tick(now=NOW + 160)
    admit = worker._admit
    assert len(admit) == services
    token0 = {k: v[3] for k, v in admit.items()}

    # unrelated churn: bump the fit-cache version without touching any
    # admitted entry — every doc must stay admitted via revalidation
    worker._fit_cache.put(("x", 1, "unrelated"), (0.0, 0.0, np.zeros(1,
                          np.float32), 0, 1.0, 1))
    calls = _count_columnar(worker)
    worker.tick(now=NOW + 170)
    assert calls
    assert len(admit) == services
    assert all(admit[k][3] != token0[k] for k in admit)  # restamped

    # same-key refit: replace job-0's latency entry object; only that
    # doc's admission row may change, and it must pick up the NEW object
    key = next(
        k for k, v in worker._fit_cache._d.items()
        if "app0" in str(k) and "latency" in str(k)
    )
    old = worker._fit_cache.peek(key)
    replacement = tuple(old)  # equal value, different identity
    worker._fit_cache.put(key, replacement)
    rows_before = {k: v[1] for k, v in admit.items()}
    worker.tick(now=NOW + 180)
    assert any(r[3] is replacement for r in admit["job-0"][1])
    for k in admit:
        if k != "job-0":
            assert admit[k][1] is rows_before[k]  # untouched rowsinfo


@pytest.mark.parametrize(
    "algorithm", ["moving_average_all", "auto_univariate"],
    ids=["ma-moments-shortcut", "seasonal-reconstruct"],
)
def test_cold_fit_bf16_upload_matches_f32(monkeypatch, algorithm):
    """Cold fits upload anchor+bf16 deltas (FOREMAST_BF16_DELTA, default
    on): the deployed default via the moments shortcut, every other
    algorithm via in-program reconstruction. Verdicts, reasons, and
    anomaly_info must match the f32 fit path on both the cold tick and
    the warm re-check tick that scores from the cached state."""
    services = 5
    a_w, a_store, a_src = _mk_worker(services, algorithm, 24)
    b_w, b_store, b_src = _mk_worker(services, algorithm, 24)

    for src in (a_src, b_src):
        url = next(u for u in src.data if "cur" in u and "latency:app2" in u)
        ct, cv = src.data[url]
        spiked = cv.copy()
        spiked[-2:] = 40.0
        src.data[url] = (ct, spiked)

    assert a_w.tick(now=NOW + 150) == services  # bf16 fit upload (default)
    monkeypatch.setenv("FOREMAST_BF16_DELTA", "0")
    assert b_w.tick(now=NOW + 150) == services  # f32 fit upload
    monkeypatch.delenv("FOREMAST_BF16_DELTA")
    assert _statuses(a_store) == _statuses(b_store)
    assert _statuses(a_store)["job-2"][0] == STATUS_COMPLETED_UNHEALTH

    # the spiked doc is terminal; the warm tick re-checks the rest
    assert a_w.tick(now=NOW + 200) == services - 1
    monkeypatch.setenv("FOREMAST_BF16_DELTA", "0")
    assert b_w.tick(now=NOW + 200) == services - 1
    assert _statuses(a_store) == _statuses(b_store)


# -- canary columnar bucket (ISSUE 14) --------------------------------------


def _hook_recorder(records):
    def hook(doc, verdicts):
        for v in verdicts:
            records.append(
                (
                    doc.id,
                    v.alias,
                    int(v.verdict),
                    tuple(v.anomaly_pairs),
                    np.asarray(v.upper, np.float32).tobytes(),
                    np.asarray(v.lower, np.float32).tobytes(),
                    round(float(v.p_value), 7),
                    bool(v.dist_differs),
                )
            )

    return hook


@pytest.mark.parametrize(
    "algorithm,season",
    [("moving_average_all", 24), ("auto_univariate", 24)],
    ids=["deployed-default", "gap-sensitive-seasonal"],
)
def test_canary_fast_path_engages_and_matches_object_path(algorithm, season):
    """Baseline-carrying (canary) docs must ride the columnar fast tick
    as their own bucket (ISSUE 14) and produce statuses, anomaly_info,
    AND hook verdicts (bands + pairwise p/differs) byte-identical to
    the object path — including a doc whose BASELINE distribution
    shifted (dist_differs=True lowers the threshold in-program)."""
    services = 6
    fast_rec, slow_rec = [], []
    fast_w, fast_store, fast_src = _mk_worker(
        services, algorithm, season, baseline_frac=0.5,
        hook=_hook_recorder(fast_rec), band_mode="full",
    )
    slow_w, slow_store, slow_src = _mk_worker(
        services, algorithm, season, baseline_frac=0.5,
        hook=_hook_recorder(slow_rec), band_mode="full",
    )
    _force_slow(slow_w)
    calls = _count_columnar(fast_w)

    assert fast_w.tick(now=NOW + 150) == services
    assert slow_w.tick(now=NOW + 150) == services
    assert not calls, "cold tick must not take the fast path"
    assert _statuses(fast_store) == _statuses(slow_store)

    # spike one canary doc's current window, and SHIFT another canary
    # doc's baseline distribution (the rank tests must reject and lower
    # the threshold identically on both paths)
    for src in (fast_src, slow_src):
        url = next(
            u for u in src.data
            if u.startswith("http://prom/cur") and "latency:app1&" in u
        )
        ct, cv = src.data[url]
        spiked = cv.copy()
        spiked[-3:] = 40.0
        src.data[url] = (ct, spiked)
        burl = next(
            u for u in src.data
            if u.startswith("http://prom/base") and "latency:app0&" in u
        )
        bt, bv = src.data[burl]
        src.data[burl] = (bt, (bv + 0.5).astype(np.float32))

    fast_rec.clear()
    slow_rec.clear()
    assert fast_w.tick(now=NOW + 200) == services
    assert slow_w.tick(now=NOW + 200) == services
    assert calls, "warm re-check tick must take the columnar fast path"
    assert fast_w._fast_kinds["baseline"] > 0, fast_w._fast_kinds
    fast_s, slow_s = _statuses(fast_store), _statuses(slow_store)
    assert fast_s == slow_s
    assert fast_s["job-1"][0] == STATUS_COMPLETED_UNHEALTH
    assert sorted(fast_rec) == sorted(slow_rec)
    # the shifted-baseline doc's hook verdicts must carry the REAL
    # device pairwise outcome, not the baseline-less constants
    differs = [r for r in fast_rec if r[0] == "job-0" and r[7]]
    assert differs, "shifted baseline never rejected same-distribution"
    assert all(r[6] < 0.05 for r in differs)


def test_canary_columnar_opt_out(monkeypatch):
    """FOREMAST_CANARY_COLUMNAR=0 keeps baseline-carrying docs on the
    object path (the pre-round-16 routing) with identical judgments."""
    monkeypatch.setenv("FOREMAST_CANARY_COLUMNAR", "0")
    off_w, off_store, _ = _mk_worker(
        4, "moving_average_all", 24, baseline_frac=1.0
    )
    assert not off_w._canary_fast
    monkeypatch.delenv("FOREMAST_CANARY_COLUMNAR")
    on_w, on_store, _ = _mk_worker(
        4, "moving_average_all", 24, baseline_frac=1.0
    )
    for w in (off_w, on_w):
        assert w.tick(now=NOW + 150) == 4
        assert w.tick(now=NOW + 200) == 4
    assert off_w._fast_kinds["baseline"] == 0
    assert on_w._fast_kinds["baseline"] == 4
    assert _statuses(off_store) == _statuses(on_store)


def test_canary_doc_with_partial_baseline_aliases():
    """A canary doc where only SOME aliases carry baselines: the
    baseline-less aliases judge with the hardwired (p=1, False) inside
    the pairwise-active program (all-masked baseline rows), matching
    the object path bit for bit."""
    services = 3
    fast_rec, slow_rec = [], []
    fast_w, fast_store, fast_src = _mk_worker(
        services, "moving_average_all", 24, baseline_frac=1.0,
        hook=_hook_recorder(fast_rec),
    )
    slow_w, slow_store, slow_src = _mk_worker(
        services, "moving_average_all", 24, baseline_frac=1.0,
        hook=_hook_recorder(slow_rec),
    )
    _force_slow(slow_w)
    # strip ONE alias's baseline from one doc on both fleets: the doc
    # stays canary-shaped but carries a baseline-less row
    for store in (fast_store, slow_store):
        doc = store._docs["job-2"]
        parts = doc.baseline_config.split(" ||")
        doc.baseline_config = " ||".join(parts[1:])
    assert fast_w.tick(now=NOW + 150) == services
    assert slow_w.tick(now=NOW + 150) == services
    fast_rec.clear()
    slow_rec.clear()
    assert fast_w.tick(now=NOW + 200) == services
    assert slow_w.tick(now=NOW + 200) == services
    assert fast_w._fast_kinds["baseline"] == services
    assert _statuses(fast_store) == _statuses(slow_store)
    assert sorted(fast_rec) == sorted(slow_rec)
    # the stripped alias reports the baseline-less constants
    stripped = [r for r in fast_rec if r[0] == "job-2" and r[1] == "latency"]
    assert stripped and all(r[6] == 1.0 and not r[7] for r in stripped)
