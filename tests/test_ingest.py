"""Ingest-plane coverage (ISSUE 5): ring wrap-around, budget eviction,
staleness, shard-lock concurrency, the remote-write receiver, and the
end-to-end contract — a worker tick judged entirely from pushed samples
with zero Prometheus calls, cold-miss fallback + next-tick warmness,
and pull/push judgment parity on the same samples.
"""

import json
import threading
import time
import urllib.request

import numpy as np
import pytest

from foremast_tpu.config import BrainConfig
from foremast_tpu.ingest import (
    RingSource,
    RingStore,
    SeriesRing,
    canonical_series,
    parse_push,
    resolve_query_range,
    start_ingest_server,
)
from foremast_tpu.jobs.models import (
    STATUS_COMPLETED_UNHEALTH,
    STATUS_PREPROCESS_COMPLETED,
    Document,
)
from foremast_tpu.jobs.store import InMemoryStore
from foremast_tpu.jobs.worker import BrainWorker
from foremast_tpu.metrics.promql import prometheus_url
from foremast_tpu.metrics.source import MetricSource, PrometheusSource

NOW = 1_760_000_000.0
HIST_LEN = 256
CUR_LEN = 30


# ---------------------------------------------------------------------------
# wire format
# ---------------------------------------------------------------------------


def test_canonical_series_is_label_order_independent():
    a = canonical_series('m{b="2",a="1"}')
    b = canonical_series('m{a="1",b="2"}')
    assert a == b == 'm{a="1",b="2"}'
    # non-selector expressions pass through verbatim
    assert canonical_series("sum(rate(x[5m]))") == "sum(rate(x[5m]))"
    assert canonical_series("plain_name") == "plain_name"


def test_resolve_query_range_shapes():
    url = prometheus_url(
        {
            "endpoint": "http://p/api/v1/",
            "query": 'm{b="2",a="1"}',
            "start": 100,
            "end": 200,
            "step": 60,
        }
    )
    key, t0, t1, step = resolve_query_range(url)
    assert key == 'm{a="1",b="2"}'
    assert (t0, t1, step) == (100.0, 200.0, 60.0)
    # wavefront `&&` encoding (wavefronthelper.go shape)
    key, t0, t1, _ = resolve_query_range("ts(cpu)&&100&&m&&200")
    assert key == "ts(cpu)" and t0 == 100.0 and t1 == 200.0
    # no recognizable query => key None (source bypasses the ring)
    assert resolve_query_range("http://p/other?x=1")[0] is None


def test_parse_push_labels_and_alias_forms():
    entries = parse_push(
        {
            "timeseries": [
                {
                    "labels": {"__name__": "m", "app": "a"},
                    "samples": [[60, 1.5], [120, 2.5]],
                    "start": 0,
                },
                {"alias": 'q{b="2",a="1"}', "times": [60], "values": [9]},
            ]
        }
    )
    assert entries[0][0] == 'm{app="a"}'
    assert entries[0][1].tolist() == [60, 120]
    assert entries[0][3] == 0.0
    assert entries[1][0] == 'q{a="1",b="2"}'
    from foremast_tpu.ingest.wire import WireError

    with pytest.raises(WireError):
        parse_push({"timeseries": [{"samples": [[1, 2]]}]})  # no identity
    with pytest.raises(WireError):
        parse_push({"nope": []})


# ---------------------------------------------------------------------------
# ring + shards
# ---------------------------------------------------------------------------


def test_ring_wraparound_keeps_newest_and_advances_coverage():
    r = SeriesRing(capacity=4, max_points=8)
    r.append(np.arange(20, dtype=np.int64), np.arange(20, dtype=np.float32),
             start=0.0)
    assert len(r) == 8
    t, v = r.window(None, None)
    assert t.tolist() == list(range(12, 20))
    assert v.tolist() == [float(x) for x in range(12, 20)]
    # overwrite dropped samples 0..11: the ring is no longer
    # authoritative back to 0, so coverage must have advanced
    assert r.covered_from == 12.0
    # windows slice inclusively on both bounds
    t, _ = r.window(13, 15)
    assert t.tolist() == [13, 14, 15]


def test_ring_merge_sorts_and_dedups_last_wins():
    r = SeriesRing()
    r.append([10, 5, 5, 20], [1.0, 2.0, 3.0, 4.0])
    t, v = r.window(None, None)
    assert t.tolist() == [5, 10, 20]
    assert v.tolist() == [3.0, 1.0, 4.0]  # last write wins per timestamp
    # a later overlapping push revises in place (remote-write semantics)
    r.append([10], [7.0])
    t, v = r.window(None, None)
    assert v.tolist() == [3.0, 7.0, 4.0]


def test_store_eviction_under_budget_is_lru():
    # one shard so LRU order is observable; budget fits ~2 min-capacity
    # rings (256 pts * 12 B = 3072 B each)
    s = RingStore(budget_bytes=2 * 3072, shards=1, max_points=256)
    for name in ("a", "b", "c"):
        s.push(name, np.arange(10, dtype=np.int64), np.zeros(10, np.float32),
               start=0.0, now=100.0)
    st = s.stats()
    assert st["evictions"] == 1 and st["series"] == 2
    # "a" (oldest) was evicted; refresh "b" by QUERY then push "d": the
    # eviction victim must be "c", not the just-queried "b"
    assert s.query("a", 0, 9, now=100.0)[0] == "miss"
    assert s.query("b", 0, 9, now=100.0)[0] == "hit"
    s.push("d", np.arange(10, dtype=np.int64), np.zeros(10, np.float32),
           start=0.0, now=100.0)
    assert s.query("b", 0, 9, now=100.0)[0] == "hit"
    assert s.query("c", 0, 9, now=100.0)[0] == "miss"
    assert s.stats()["bytes"] <= s.budget_bytes


def test_staleness_and_coverage_cutoffs():
    s = RingStore(shards=1, stale_seconds=300.0)
    s.push("m", [1000, 1060, 1120], [1, 2, 3], start=1000.0, now=1180.0)
    # live window whose head is beyond the newest sample by > cutoff
    assert s.query("m", 1000, 2000, now=2000.0)[0] == "stale"
    # inside the cutoff: served
    assert s.query("m", 1000, 1400, now=1400.0)[0] == "hit"
    # a query reaching back before the coverage watermark cannot be
    # proven empty by the ring => uncovered, falls to the pull path
    assert s.query("m", 0, 1120, now=1180.0)[0] == "uncovered"


def test_parse_push_malformed_shapes_are_wire_errors():
    """Every malformed-payload shape must surface as WireError (the
    receiver's 400), never an uncaught TypeError/KeyError/
    AttributeError that kills the handler thread."""
    from foremast_tpu.ingest.wire import WireError

    bad = [
        # non-numeric start
        {"timeseries": [{"labels": {"__name__": "x"},
                         "samples": [[1, 2]], "start": [1, 2]}]},
        # labels as a list of lists instead of objects
        {"timeseries": [{"labels": [["__name__", "x"]],
                         "samples": [[1, 2]]}]},
        # samples as objects
        {"timeseries": [{"alias": "x", "samples": [{"t": 1}]}]},
        # nested (2-d) times/values
        {"timeseries": [{"alias": "x", "times": [[1], [2]],
                         "values": [[1], [2]]}]},
    ]
    for payload in bad:
        with pytest.raises(WireError):
            parse_push(payload)


def test_parse_push_rejects_label_entries_missing_name_or_value():
    """A proto-JSON label entry with a typoed/missing field must be a
    400, not a silently-coined `None` label no query can resolve."""
    from foremast_tpu.ingest.wire import WireError

    with pytest.raises(WireError):
        parse_push(
            {
                "timeseries": [
                    {
                        "labels": [
                            {"name": "__name__", "value": "m"},
                            {"value": "x"},  # missing `name`
                        ],
                        "samples": [[100, 1.5]],
                    }
                ]
            }
        )


def test_empty_backfill_without_start_bound_still_warms():
    """A query URL with no usable `start` over a genuinely-empty series:
    the empty fallback answer must still record coverage (point
    coverage at the head), so the next tick is a zero-HTTP empty hit
    instead of one HTTP round trip per tick forever."""
    feed = WindowedSource()
    feed.data["m"] = (np.zeros(0, np.int64), np.zeros(0, np.float32))
    ring = RingStore(shards=1, stale_seconds=300.0)
    source = RingSource(ring, fallback=feed, clock=lambda: 1200.0)
    url = "http://p/api/v1/query_range?query=m&end=1100&step=60"
    for _ in range(3):
        ts, _vs = source.fetch(url)
        assert len(ts) == 0
    assert len(feed.calls) == 1


def test_window_entirely_past_coverage_falls_back():
    """A query window with ZERO overlap with the covered interval must
    not be served as an empty hit — the pull path may hold real samples
    there (pusher died, then the doc's window slid past coverage)."""
    s = RingStore(shards=1, stale_seconds=300.0)
    s.push("m", [0, 60, 100], [1, 2, 3], start=0.0, now=100.0)
    assert s.query("m", 200, 300, now=300.0)[0] == "stale"


def test_unsorted_push_batch_records_full_coverage():
    """Coverage bounds come from min/max, not first/last: a retried
    out-of-order batch must not collapse the covered window and push
    the series onto the fallback forever."""
    s = RingStore(shards=1, stale_seconds=300.0)
    s.push("m", [180, 60, 120], [3.0, 1.0, 2.0], now=200.0)
    status, ts, vs = s.query("m", 60, 180, now=200.0)
    assert status == "hit"
    assert ts.tolist() == [60, 120, 180]
    assert vs.tolist() == [1.0, 2.0, 3.0]


def test_wavefront_step_units_resolve():
    assert resolve_query_range("ts(cpu)&&100&&h&&4000")[3] == 3600.0
    assert resolve_query_range("ts(cpu)&&100&&s&&200")[3] == 1.0


def test_disjoint_backfills_do_not_claim_the_gap():
    """Disjoint coverage spans COEXIST (ISSUE 10: a historical
    backfill must stay authoritative next to the live push stream so
    the second cold doc of the same app never re-fetches) — but a
    window is only ever served out of ONE span, so the gap between
    them still degrades to the pull path instead of serving a silently
    truncated slice."""
    s = RingStore(shards=1, stale_seconds=300.0)
    now = 700_000.0
    # live current slice [699000, 699600]
    cur_t = np.arange(699_000, 699_660, 60, dtype=np.int64)
    s.push("m", cur_t, np.ones(len(cur_t), np.float32),
           start=699_000.0, end=699_600.0, now=now, record_lag=False)
    # disjoint OLD historical slice [0, 600]: its own span now, not a
    # dropped authority claim (the round-5..8 behavior this pins out)
    old_t = np.arange(0, 660, 60, dtype=np.int64)
    s.push("m", old_t, np.ones(len(old_t), np.float32),
           start=0.0, end=600.0, now=now, record_lag=False)
    assert s.query("m", 699_000, 699_600, now=now)[0] == "hit"
    # the historical window itself is a HIT — the whole point
    assert s.query("m", 0, 600, now=now)[0] == "hit"
    # only samples inside the covering span come back: the live slice
    # never leaks into a historical read
    _, ts, _ = s.query("m", 0, 600, now=now)
    assert ts.tolist() == old_t[old_t <= 600].tolist()
    # a window reaching past the historical span's head by more than
    # the staleness slack (into the uncovered gap): still degraded
    assert s.query("m", 60, 90_000, now=now)[0] == "stale"
    # a window starting inside the gap, past the historical span's
    # head: degraded too (classified stale, same as the
    # single-interval code did for a window past the coverage head)
    assert s.query("m", 5_000, 90_000, now=now)[0] == "stale"
    # a window starting BEFORE any span's reach minus slack... the gap
    # start case where no span covers t0 at all
    assert s.query("m2", 0, 600, now=now)[0] == "miss"


def test_empty_backfill_serves_empty_hits():
    """A fallback that answers 'no data in [t0, t1]' is authoritative
    for that emptiness: the next fetch is an empty HIT (parity with the
    pull path, zero HTTP), not a perpetual miss."""
    feed = WindowedSource()
    feed.data["m"] = (np.zeros(0, np.int64), np.zeros(0, np.float32))
    ring = RingStore(shards=1, stale_seconds=300.0)
    source = RingSource(ring, fallback=feed, clock=lambda: 1200.0)
    url = "http://p/api/v1/query_range?query=m&start=1000&end=1100&step=60"
    ts, _ = source.fetch(url)
    assert len(ts) == 0 and len(feed.calls) == 1
    ts2, _ = source.fetch(url)
    assert len(ts2) == 0 and len(feed.calls) == 1  # served from coverage


def test_empty_coverage_survives_later_live_pushes():
    """A provably-empty backfilled range must stay authoritative when a
    live push later lands after it: coverage clamps only past samples
    DROPPED by overwrite, never merely to the oldest sample."""
    s = RingStore(shards=1, stale_seconds=300.0)
    s.push("m", [], [], start=1000.0, end=1100.0, now=1100.0,
           record_lag=False)
    s.push("m", [1160, 1220], [1.0, 2.0], now=1230.0)  # abuts in slack
    status, ts, _ = s.query("m", 1000, 1220, now=1230.0)
    assert status == "hit"
    assert ts.tolist() == [1160, 1220]


def test_series_key_escapes_quotes_no_collision():
    from foremast_tpu.ingest import series_key

    honest = series_key({"__name__": "m", "a": "1", "b": "2"})
    crafted = series_key({"__name__": "m", "a": '1",b="2'})
    assert honest != crafted
    assert crafted == 'm{a="1\\",b=\\"2"}'
    # and the honest key round-trips through the query-side canonicalizer
    assert canonical_series(honest) == honest


def test_backfill_does_not_report_receiver_lag():
    s = RingStore(shards=1)
    from foremast_tpu.ingest import backfill

    old = np.arange(0, 600, 60, dtype=np.int64)
    backfill(s, "m", (old, np.ones(len(old), np.float32)), start=0.0,
             end=600.0, now=700_000.0)
    assert s.stats()["receiver_lag_seconds"] is None
    s.push("m", [700_000], [1.0], now=700_030.0)
    assert s.stats()["receiver_lag_seconds"] == 30.0


def test_ring_source_concurrent_fetch_follows_fallback():
    ring = RingStore(shards=1)
    assert RingSource(ring, fallback=None).concurrent_fetch is False
    assert RingSource(ring, fallback=WindowedSource()).concurrent_fetch is False
    assert (
        RingSource(
            ring, fallback=PrometheusSource(session=_NoHTTPSession())
        ).concurrent_fetch
        is True
    )


def test_shard_lock_concurrency_smoke():
    s = RingStore(shards=4, max_points=512)
    n_threads, pushes = 8, 50
    errors = []

    def worker(i):
        try:
            for k in range(pushes):
                t0 = 60 * k
                s.push(
                    f"series-{i % 4}",
                    [t0, t0 + 30],
                    [float(i), float(k)],
                    start=0.0,
                    now=float(t0 + 30),
                )
                s.query(f"series-{i % 4}", 0, t0 + 30, now=float(t0 + 30))
        except Exception as e:  # noqa: BLE001 - the test IS the guard
            errors.append(e)

    threads = [
        threading.Thread(target=worker, args=(i,)) for i in range(n_threads)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors
    st = s.stats()
    assert st["samples"] == n_threads * pushes * 2
    assert st["series"] == 4


# ---------------------------------------------------------------------------
# receiver
# ---------------------------------------------------------------------------


def test_receiver_push_roundtrip_and_rejection():
    store = RingStore(shards=2)
    srv, _ = start_ingest_server(0, store, host="127.0.0.1")
    try:
        port = srv.server_address[1]
        body = json.dumps(
            {
                "timeseries": [
                    {
                        "labels": {"__name__": "m", "app": "a"},
                        "samples": [[60, 1.5], [120, 2.5]],
                        "start": 0,
                    }
                ]
            }
        ).encode()
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/api/v1/write", data=body, method="POST"
        )
        resp = urllib.request.urlopen(req)
        assert json.loads(resp.read())["accepted_samples"] == 2
        assert store.query('m{app="a"}', 0, 120, now=150.0)[0] == "hit"
        # malformed payload => 400 with the reason, nothing stored
        bad = urllib.request.Request(
            f"http://127.0.0.1:{port}/api/v1/write",
            data=b'{"timeseries": [{"samples": [[1, 2]]}]}',
            method="POST",
        )
        with pytest.raises(urllib.error.HTTPError) as exc_info:
            urllib.request.urlopen(bad)
        assert exc_info.value.code == 400
        state = json.loads(
            urllib.request.urlopen(
                f"http://127.0.0.1:{port}/debug/state"
            ).read()
        )
        assert state["series"] == 1 and state["samples"] == 2
        assert state["receiver_lag_seconds"] is not None
    finally:
        srv.shutdown()


def test_receiver_rejects_oversized_body_with_413():
    """ISSUE 6 hardening: a push whose Content-Length exceeds
    FOREMAST_INGEST_MAX_BODY_BYTES answers 413 WITHOUT buffering or
    parsing the payload; nothing lands in the ring and the receiver
    keeps serving normal pushes afterwards."""
    store = RingStore(shards=1)
    srv, _ = start_ingest_server(
        0, store, host="127.0.0.1", max_body_bytes=256
    )
    try:
        port = srv.server_address[1]
        big = json.dumps(
            {
                "timeseries": [
                    {
                        "alias": "big_series",
                        "times": list(range(60, 60 * 200, 60)),
                        "values": [1.0] * 199,
                    }
                ]
            }
        ).encode()
        assert len(big) > 256
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/api/v1/write", data=big, method="POST"
        )
        with pytest.raises(urllib.error.HTTPError) as exc_info:
            urllib.request.urlopen(req)
        assert exc_info.value.code == 413
        assert b"cap" in exc_info.value.read()
        assert store.stats()["series"] == 0
        # the cap is per request, not a latch: a small push still lands
        ok = urllib.request.Request(
            f"http://127.0.0.1:{port}/api/v1/write",
            data=json.dumps(
                {"timeseries": [{"alias": "s", "times": [60], "values": [1.0]}]}
            ).encode(),
            method="POST",
        )
        assert json.loads(urllib.request.urlopen(ok).read())[
            "accepted_samples"
        ] == 1
    finally:
        srv.shutdown()


def test_receiver_graceful_drain_on_close():
    """ISSUE 6 hardening: stop_ingest_server stops accepting, drains
    in-flight handlers, and frees the port — a mid-shutdown push gets a
    connection error, never a wedged thread holding worker close."""
    import socket

    from foremast_tpu.ingest import stop_ingest_server

    store = RingStore(shards=1)
    srv, thread = start_ingest_server(0, store, host="127.0.0.1")
    port = srv.server_address[1]
    # handler threads must be daemons (the pre-ISSUE-6 wedge: a
    # non-daemon handler blocked on a half-sent body held process exit)
    assert srv.daemon_threads is True
    assert stop_ingest_server(srv, drain_seconds=5.0) is True
    thread.join(timeout=5.0)
    assert not thread.is_alive()
    # the listen socket is closed: new pushes fail fast instead of
    # queueing against a dead receiver
    with pytest.raises((ConnectionError, urllib.error.URLError, OSError)):
        urllib.request.urlopen(
            urllib.request.Request(
                f"http://127.0.0.1:{port}/api/v1/write",
                data=b"{}",
                method="POST",
            ),
            timeout=2.0,
        )
    # ... and the port is immediately rebindable (SO_REUSEADDR + closed)
    s = socket.socket()
    try:
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        s.bind(("127.0.0.1", port))
    finally:
        s.close()


# ---------------------------------------------------------------------------
# end-to-end: worker ticks from the ring
# ---------------------------------------------------------------------------


class WindowedSource(MetricSource):
    """What a real Prometheus returns for these URLs: the sample-set
    slice [start, end] — so pull and push paths judge the same bytes."""

    concurrent_fetch = False

    def __init__(self):
        self.data: dict[str, tuple[np.ndarray, np.ndarray]] = {}
        self.calls: list[str] = []

    def fetch(self, url: str):
        key, t0, t1, _ = resolve_query_range(url)
        self.calls.append(url)
        t, v = self.data[key]
        lo = 0 if t0 is None else int(np.searchsorted(t, t0, side="left"))
        hi = len(t) if t1 is None else int(np.searchsorted(t, t1, side="right"))
        return t[lo:hi].copy(), v[lo:hi].copy()


class _NoHTTPSession:
    """Injected into the fallback PrometheusSource: any GET is a test
    failure — the warm tick must be zero-HTTP."""

    def get(self, url, timeout=None):
        raise AssertionError(f"HTTP fetch attempted: {url}")


def _build_fleet(services: int):
    """One doc per service, reference continuous-strategy shape: current
    and historical windows are the SAME series (app_query) at different
    ranges, like metricsquery.go builds them."""
    rng = np.random.default_rng(0)
    store = InMemoryStore()
    feed = WindowedSource()
    t_now = int(NOW)
    ht = t_now - 86_400 * 7 + 60 * np.arange(HIST_LEN, dtype=np.int64)
    ct = ht[-1] + 60 + 60 * np.arange(CUR_LEN, dtype=np.int64)
    end_time = time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime(t_now + 3600))
    endpoint = "http://prom/api/v1/"
    for s in range(services):
        expr = f'namespace_app_per_pod:latency{{namespace="ns",app="app{s}"}}'
        hv = rng.normal(1.0, 0.1, HIST_LEN).astype(np.float32)
        cv = (1.0 + 0.05 * np.sin(np.arange(CUR_LEN) / 3.0)).astype(
            np.float32
        )
        feed.data[canonical_series(expr)] = (
            np.concatenate([ht, ct]),
            np.concatenate([hv, cv]),
        )
        cur_url = prometheus_url(
            {"endpoint": endpoint, "query": expr, "start": int(ct[0]),
             "end": int(ct[-1]), "step": 60}
        )
        hist_url = prometheus_url(
            {"endpoint": endpoint, "query": expr, "start": int(ht[0]),
             "end": int(ht[-1]), "step": 60}
        )
        store.create(
            Document(
                id=f"job-{s}",
                app_name=f"app{s}",
                end_time=end_time,
                current_config=f"latency== {cur_url}",
                historical_config=f"latency== {hist_url}",
                strategy="continuous",
            )
        )
    return store, feed, ht, ct


def _mk_worker(store, source, services):
    cfg = BrainConfig(
        algorithm="moving_average_all",
        season_steps=24,
        max_cache_size=services + 16,
    )
    return BrainWorker(
        store, source, config=cfg, claim_limit=max(services, 4),
        worker_id="ingest-w",
    )


def _statuses(store):
    return {
        d.id: (d.status, d.reason, d.anomaly_info)
        for d in store._docs.values()
    }


def _push_feed(ring, feed, start):
    for key, (t, v) in feed.data.items():
        ring.push(key, t, v, start=float(start), now=NOW)


def test_worker_tick_judges_entirely_from_pushed_samples():
    """Warm-ring fleet tick with a fail-on-HTTP fallback: every window
    — historical fits included — comes from pushed samples, and the
    judgments match a pull-path worker on the same bytes exactly."""
    services = 5
    store_pull, feed, ht, ct = _build_fleet(services)
    store_push, _, _, _ = _build_fleet(services)
    ring = RingStore(shards=4)
    _push_feed(ring, feed, start=ht[0])
    fallback = PrometheusSource(session=_NoHTTPSession(), retries=0)
    push_w = _mk_worker(store_push, RingSource(ring, fallback=fallback),
                        services)
    pull_w = _mk_worker(store_pull, feed, services)

    assert push_w.tick(now=NOW + 150) == services
    assert pull_w.tick(now=NOW + 150) == services
    assert _statuses(store_push) == _statuses(store_pull)
    assert all(
        st[0] == STATUS_PREPROCESS_COMPLETED
        for st in _statuses(store_push).values()
    )
    stats = ring.stats()
    assert stats["hits"] >= 2 * services  # cur + hist per doc
    assert stats["misses"] == 0 and stats["stale"] == 0

    # spike one service via a revising push (last-write-wins merge) and
    # mirror it in the pull feed: warm re-check ticks must stay
    # byte-identical AND flag the anomaly on both paths
    key = canonical_series(
        'namespace_app_per_pod:latency{namespace="ns",app="app2"}'
    )
    t, v = feed.data[key]
    spiked = v.copy()
    spiked[-3:] = 40.0
    feed.data[key] = (t, spiked)
    ring.push(key, ct[-3:], spiked[-3:], now=NOW)
    assert push_w.tick(now=NOW + 300) == services
    assert pull_w.tick(now=NOW + 300) == services
    push_s = _statuses(store_push)
    assert push_s == _statuses(store_pull)
    assert push_s["job-2"][0] == STATUS_COMPLETED_UNHEALTH
    assert "latency" in json_values(push_s["job-2"][2])


def json_values(anomaly_info):
    return (anomaly_info or {}).get("values", {})


def test_cold_miss_falls_back_then_next_tick_is_warm():
    services = 4
    store, feed, ht, ct = _build_fleet(services)
    ring = RingStore(shards=2)
    source = RingSource(ring, fallback=feed)
    worker = _mk_worker(store, source, services)

    # tick 1: ring empty => every window misses, the fallback serves,
    # and each miss both subscribes the series and backfills the ring
    assert worker.tick(now=NOW + 150) == services
    calls_cold = len(feed.calls)
    assert calls_cold >= 2 * services
    assert len(source.book) == services  # one series per doc (shared expr)
    assert ring.stats()["series"] == services

    # tick 2: current windows come from the backfilled ring — ZERO new
    # fallback fetches (histories are settled + fit-cached, so the warm
    # path refetches only current)
    assert worker.tick(now=NOW + 300) == services
    assert len(feed.calls) == calls_cold
    st = ring.stats()
    assert st["hits"] >= services
    state = source.ingest_debug_state()
    assert state["subscriptions"]["total"] == services
    assert state["fallback"] == "WindowedSource"


def test_stale_ring_degrades_to_fallback():
    """A dead pusher must not freeze verdicts: a window whose head is
    past the newest pushed sample by more than the cutoff is re-fetched
    through the fallback (and the fresh result re-warms the ring)."""
    feed = WindowedSource()
    t = np.arange(0, 6000, 60, dtype=np.int64)
    feed.data["m"] = (t, np.ones(len(t), np.float32))
    ring = RingStore(shards=1, stale_seconds=300.0)
    # pusher died at t=1200
    ring.push("m", t[t <= 1200], np.ones(int((1200 / 60) + 1), np.float32),
              start=0.0, now=1200.0)
    source = RingSource(ring, fallback=feed, clock=lambda: 6000.0)
    url = "http://p/api/v1/query_range?query=m&start=0&end=5940&step=60"
    ts, vs = source.fetch(url)
    assert len(feed.calls) == 1
    assert ts.tolist() == t.tolist()
    # backfill refreshed the ring: the same fetch now hits
    ts2, _ = source.fetch(url)
    assert len(feed.calls) == 1
    assert ts2.tolist() == t.tolist()


def test_worker_debug_state_has_ingest_section():
    services = 2
    store, feed, ht, ct = _build_fleet(services)
    ring = RingStore(shards=2)
    _push_feed(ring, feed, start=ht[0])
    worker = _mk_worker(store, RingSource(ring, fallback=feed), services)
    worker.tick(now=NOW + 150)
    state = worker.debug_state()
    ing = state["ingest"]
    assert ing is not None
    assert ing["series"] == services
    assert ing["bytes"] > 0
    assert ing["hit_ratio"] == 1.0
    assert "subscriptions" in ing
    # pure-pull workers report None (the section stays enumerable)
    pull_worker = _mk_worker(store, feed, services)
    assert pull_worker.debug_state()["ingest"] is None


# ---------------------------------------------------------------------------
# ring-first cold start, short-history admission, refinement (ISSUE 10)
# ---------------------------------------------------------------------------


from foremast_tpu.engine import HEALTHY, UNKNOWN  # noqa: E402


def test_historical_backfill_sticks_second_cold_fit_zero_http():
    """Satellite: a cold-miss fallback fetch of a HISTORICAL range
    backfills the ring write-through AND its authority survives later
    disjoint live pushes (multi-interval coverage) — so the second
    cold fit against the same series never re-fetches over HTTP."""
    store, feed, ht, ct = _build_fleet(1)
    ring = RingStore(shards=1)
    source = RingSource(ring, fallback=feed)
    worker = _mk_worker(store, source, 2)
    assert worker.tick(now=NOW + 150) == 1
    hist_marker = f"start={int(ht[0])}"
    assert sum(1 for u in feed.calls if hist_marker in u) == 1
    # a live push lands ~7 days after the historical span — far past
    # the staleness slack. Rounds 5-8 DROPPED the backfill's coverage
    # here, re-paying the historical fetch for every later cold fit.
    key = canonical_series(
        'namespace_app_per_pod:latency{namespace="ns",app="app0"}'
    )
    ring.push(key, np.asarray([int(NOW)], np.int64),
              np.ones(1, np.float32), now=NOW)
    # second cold fit of the same series: a new doc whose alias (and
    # thus fit key) differs, same historical range
    docs = list(store._docs.values())
    proto = docs[0]
    store.create(
        Document(
            id="job-b",
            app_name="app0",
            end_time=proto.end_time,
            current_config=proto.current_config.replace(
                "latency== ", "latencyb== "
            ),
            historical_config=proto.historical_config.replace(
                "latency== ", "latencyb== "
            ),
            strategy="continuous",
        )
    )
    assert worker.tick(now=NOW + 300) == 2
    # STILL exactly one historical HTTP fetch: doc B's cold fit read
    # resident ring columns
    assert sum(1 for u in feed.calls if hist_marker in u) == 1
    assert worker.debug_state()["cold_start"]["hist_reads"]["ring_full"] >= 1


def test_hist_cache_bypassed_and_shrunk_with_ring_source():
    """Satellite: with a ring-backed source the worker's host-side
    history cache is bypassed (the ring owns those bytes) and shrunk;
    the decision is exposed on /debug/state."""
    store, feed, ht, ct = _build_fleet(1)
    ring = RingStore(shards=1)
    _push_feed(ring, feed, start=ht[0])
    worker = _mk_worker(store, RingSource(ring, fallback=feed), 1)
    assert worker.tick(now=NOW + 150) == 1
    cs = worker.debug_state()["cold_start"]
    assert cs["hist_bypass"] is True
    assert cs["hist_cache_cap"] < 256  # shrunk from HIST_CACHE_ENTRIES
    assert cs["hist_reads"]["ring_full"] >= 1
    assert cs["hist_reads"]["http"] == 0
    # the bypassed cache holds NOTHING for ring-served ranges
    assert len(worker._hist_cache) == 0
    # pull worker: no bypass, full-size cache
    pull = _mk_worker(store, feed, 1)
    cs = pull.debug_state()["cold_start"]
    assert cs["hist_bypass"] is False
    assert cs["hist_cache_cap"] == 256


def _newcomer_fleet(push0, push_end, t1, floor, services=1, stale=300.0):
    """Docs requesting a 7-day history ending at `t1`, with only
    [push0, push_end] actually pushed (a newcomer's short life) —
    pure-push mode, no fallback."""
    store = InMemoryStore()
    ring = RingStore(shards=1, stale_seconds=stale)
    t0 = t1 - 7 * 86_400
    cur_t1 = push_end - 60
    cur_t0 = cur_t1 - 28 * 60
    endpoint = "http://prom/api/v1/"
    end_time = time.strftime(
        "%Y-%m-%dT%H:%M:%SZ", time.gmtime(int(NOW) + 3600)
    )
    rng = np.random.default_rng(7)
    for s in range(services):
        expr = f'namespace_app_per_pod:latency{{namespace="ns",app="new{s}"}}'
        key = canonical_series(expr)
        pt = np.arange(int(push0), int(push_end) + 60, 60, dtype=np.int64)
        pv = rng.normal(1.0, 0.1, len(pt)).astype(np.float32)
        ring.push(key, pt, pv, now=NOW)
        cur_url = prometheus_url(
            {"endpoint": endpoint, "query": expr, "start": int(cur_t0),
             "end": int(cur_t1), "step": 60}
        )
        hist_url = prometheus_url(
            {"endpoint": endpoint, "query": expr, "start": int(t0),
             "end": int(t1), "step": 60}
        )
        store.create(
            Document(
                id=f"new-{s}",
                app_name=f"new{s}",
                end_time=end_time,
                current_config=f"latency== {cur_url}",
                historical_config=f"latency== {hist_url}",
                strategy="continuous",
            )
        )
    source = RingSource(ring, fallback=None, admit_floor=floor)
    return store, ring, source


def test_short_history_admission_first_tick_verdict():
    """Tentpole (b): a newcomer with enough fresh coverage gets a
    verdict-capable PROVISIONAL fit in its first tick (previously:
    pure-push UNKNOWN until the full window filled)."""
    base = int(NOW)
    t1 = base - 1000
    store, ring, source = _newcomer_fleet(
        push0=base - 8200, push_end=base - 1200, t1=t1, floor=3600.0
    )
    verdicts = []
    worker = BrainWorker(
        store, source, config=BrainConfig(algorithm="moving_average_all"),
        claim_limit=4, worker_id="newcomer-w",
        on_verdict=lambda d, vs: verdicts.extend(vs),
    )
    assert worker.tick(now=NOW + 150) == 1
    assert verdicts and all(v.verdict == HEALTHY for v in verdicts)
    assert len(worker._refine_book) == 1
    cs = worker.debug_state()["cold_start"]
    assert cs["hist_reads"]["ring_partial"] == 1
    assert cs["refine"]["pending"] == 1

    # below the floor: the same newcomer shape degrades to UNKNOWN
    # (pure-push), never to a fragile fit
    store2, _, source2 = _newcomer_fleet(
        push0=base - 8200, push_end=base - 1200, t1=t1, floor=30_000.0
    )
    verdicts2 = []
    w2 = BrainWorker(
        store2, source2, config=BrainConfig(algorithm="moving_average_all"),
        claim_limit=4, worker_id="newcomer-w2",
        on_verdict=lambda d, vs: verdicts2.extend(vs),
    )
    assert w2.tick(now=NOW + 150) == 1
    assert verdicts2 and all(v.verdict == UNKNOWN for v in verdicts2)
    assert len(w2._refine_book) == 0
    # pure-push (no fallback): the unservable read is labeled
    # "unserved", never "http" — no pull path exists to blame
    reads2 = w2.debug_state()["cold_start"]["hist_reads"]
    assert reads2["unserved"] == 1 and reads2["http"] == 0
    # and repeats STAY "unserved": a gap-sensitive fit re-reads the hist
    # URL on every re-claim (an empty history stores no gap anchors), and
    # the empty pure-push result must not be memoized into _hist_cache —
    # the dashboard would show the doc's history as served-from-"cache"
    # (a SERVED history, per the family help text) while it sits UNKNOWN
    store3, _, source3 = _newcomer_fleet(
        push0=base - 8200, push_end=base - 1200, t1=t1, floor=30_000.0
    )
    w3 = BrainWorker(
        store3, source3, config=BrainConfig(algorithm="phase_means"),
        claim_limit=4, worker_id="newcomer-w3",
    )
    assert w3.tick(now=NOW + 150) == 1
    assert w3.tick(now=NOW + 250) == 1
    reads3 = w3.debug_state()["cold_start"]["hist_reads"]
    assert reads3["unserved"] == 2 and reads3["cache"] == 0


def test_refinement_converges_to_from_scratch_fit():
    """Tentpole (c) + band parity: growth-paced refits upgrade a
    provisional fit as ring coverage grows, the record finalizes when
    the window closes, and the refined fit is BYTE-IDENTICAL to a
    from-scratch fit on the same (final) columns."""
    base = int(NOW)
    t1 = base - 1000
    push0 = base - 8200
    store, ring, source = _newcomer_fleet(
        push0=push0, push_end=base - 1200, t1=t1, floor=3600.0
    )
    worker = BrainWorker(
        store, source, config=BrainConfig(algorithm="moving_average_all"),
        claim_limit=4, worker_id="refine-w",
    )
    assert worker.tick(now=NOW + 150) == 1  # provisional fit admitted
    book = worker._refine_book
    assert len(book) == 1
    n0 = next(iter(book._recs.values()))["points"]

    # backward bulk-load (a pusher catching up on history): coverage
    # grows 4x inside the window but the window head stays uncovered
    key = canonical_series(
        'namespace_app_per_pod:latency{namespace="ns",app="new0"}'
    )
    rng = np.random.default_rng(8)
    old_t = np.arange(base - 30_000, push0, 60, dtype=np.int64)
    ring.push(key, old_t, rng.normal(1.0, 0.1, len(old_t)).astype(np.float32),
              now=NOW)
    # all-warm steady tick -> refinement pass: growth is due, the fit
    # is invalidated (still provisional)
    assert worker.tick(now=NOW + 160) == 1
    assert book.debug_state()["refit"] == 1
    # next tick refits from the larger window on the slow path
    assert worker.tick(now=NOW + 170) == 1
    assert len(book) == 1
    n1 = next(iter(book._recs.values()))["points"]
    assert n1 > n0

    # the window head fills in: coverage now closes the window
    tail_t = np.arange(base - 1200 + 60, t1 + 120, 60, dtype=np.int64)
    ring.push(key, tail_t,
              rng.normal(1.0, 0.1, len(tail_t)).astype(np.float32), now=NOW)
    assert worker.tick(now=NOW + 180) == 1  # steady -> terminal refit queued
    assert book.debug_state()["finalized"] == 1
    assert len(book) == 0
    assert worker.tick(now=NOW + 190) == 1  # the terminal refit lands

    # band parity: a FRESH worker fitting from scratch off the same
    # ring produces byte-identical terminal state
    fresh_store, _, _ = _newcomer_fleet(
        push0=push0, push_end=base - 1200, t1=t1, floor=3600.0
    )
    fresh = BrainWorker(
        fresh_store, source, config=BrainConfig(algorithm="moving_average_all"),
        claim_limit=4, worker_id="scratch-w",
    )
    assert fresh.tick(now=NOW + 190) == 1
    keys = [k for k in worker._fit_cache._d if k[2] and "new0" in str(k[2])]
    assert keys
    for k in keys:
        a = worker._fit_cache.peek(k)
        b = fresh._fit_cache.peek(k)
        assert b is not None, f"fresh worker missing fit {k}"
        for ai, bi in zip(a, b):
            assert np.array_equal(np.asarray(ai), np.asarray(bi)), k


def test_joint_invalidation_without_fast_admission_pops_by_app():
    """A joint doc's provisional fit must be invalidated even when the
    doc never warmed into the fast-path admission cache (columnar off,
    or refinement firing before the doc's second claim): the joint
    judge's slow-path LSTM cache key carries no history content, so
    without the by-app pop the short-history fit would be served
    forever while the refine book reported the doc finalized."""
    ring = RingStore(shards=1, stale_seconds=300.0)
    worker = BrainWorker(
        InMemoryStore(), RingSource(ring, fallback=None),
        config=BrainConfig(algorithm="lstm"),
        claim_limit=1, worker_id="joint-inv-w",
    )
    mvj = worker._mvj
    assert mvj is not None
    kept_fit = ("lstm", "other", ("a",), 1, 16, 4)
    kept_meta = ("jmeta", "lstm", "other", ("a",), ("h",))
    mvj.cache.put(("lstm", "appx", ("a", "b"), 2, 16, 4), {"w": 1})
    mvj.cache.put(kept_fit, {"w": 2})
    mvj.joint_meta.put(("jmeta", "lstm", "appx", ("a", "b"), ("h",)), (1,))
    mvj.joint_meta.put(kept_meta, (2,))
    worker._refine_book.note_joint("doc-1", "appx", ("u1", "u2"), 40)
    (bkey, rec), = worker._refine_book.take(1)
    assert "doc-1" not in worker._jadmit  # never fast-path-admitted
    worker._invalidate_provisional(bkey, rec)
    assert mvj.cache.peek(("lstm", "appx", ("a", "b"), 2, 16, 4)) is None
    assert mvj.joint_meta.peek(
        ("jmeta", "lstm", "appx", ("a", "b"), ("h",))
    ) is None
    # sibling apps untouched
    assert mvj.cache.peek(kept_fit) is not None
    assert mvj.joint_meta.peek(kept_meta) is not None


def test_fallback_cold_fit_counts_miss_once():
    """An unservable hist read falls straight through to fetch(): the
    hist_columns leg must not bump the fetch counters or record the
    subscription — fetch() does both for the SAME lookup, and counting
    twice skews every miss-rate dashboard and the hit_ratio
    denominator."""
    ring = RingStore(shards=1, stale_seconds=300.0)
    feed = WindowedSource()
    expr = 'namespace_app_per_pod:latency{namespace="ns",app="mc"}'
    key = canonical_series(expr)
    t = np.arange(0, 6000, 60, dtype=np.int64)
    feed.data[key] = (t, np.ones(len(t), dtype=np.float32))
    src = RingSource(ring, fallback=feed, clock=lambda: 6000.0)
    url = prometheus_url(
        {"endpoint": "http://prom", "query": expr, "start": 0,
         "end": 3000, "step": 60}
    )
    assert src.hist_columns(url, now=6000.0) is None
    src.fetch(url)
    stats = ring.stats()
    assert stats["misses"] == 1, stats
    assert stats["uncovered"] == 0 and stats["stale"] == 0
    assert len(feed.calls) == 1
    # the subscription was recorded exactly once (by fetch())
    snap = src.book.snapshot()
    assert snap["total"] == 1
    assert snap["recent"][key]["misses"] == 1


def test_refine_book_survives_restart(tmp_path):
    """Finding pinned: the PR-7 fit journals restore a provisional FIT
    warm, so the restored doc takes the fast path and nothing ever
    re-notes it — the refine book must persist alongside the fits or
    the short-history bands are served forever with refinement
    reporting nothing pending."""
    base = int(NOW)
    t1 = base - 1000
    store, ring, source = _newcomer_fleet(
        push0=base - 8200, push_end=base - 1200, t1=t1, floor=3600.0
    )
    worker = BrainWorker(
        store, source, config=BrainConfig(algorithm="moving_average_all"),
        claim_limit=4, worker_id="persist-w",
    )
    worker.enable_fit_persistence(str(tmp_path))
    assert worker.tick(now=NOW + 150) == 1
    assert len(worker._refine_book) == 1
    rec0 = next(iter(worker._refine_book._recs.values()))
    worker.close()

    w2 = BrainWorker(
        store, source, config=BrainConfig(algorithm="moving_average_all"),
        claim_limit=4, worker_id="persist-w2",
    )
    restored = w2.enable_fit_persistence(str(tmp_path))
    assert restored["refine"] == 1
    assert len(w2._refine_book) == 1
    assert next(iter(w2._refine_book._recs.values())) == rec0
    w2.close()


def test_refinement_settles_without_growth():
    """A provisional record whose window closes with no new in-window
    data settles WITHOUT a terminal refit — counted "settled", never
    "finalized" (foremast_refine_docs{result=finalized} counts actual
    refits paid, not bookkeeping)."""
    base = int(NOW)
    t1 = base - 1000
    store, ring, source = _newcomer_fleet(
        push0=base - 8200, push_end=base - 1200, t1=t1, floor=3600.0
    )
    worker = BrainWorker(
        store, source, config=BrainConfig(algorithm="moving_average_all"),
        claim_limit=4, worker_id="settle-w",
    )
    assert worker.tick(now=NOW + 150) == 1
    book = worker._refine_book
    assert len(book) == 1
    fits_before = dict(worker._fit_cache._d)
    # close the window's coverage from OUTSIDE it: one sample just past
    # the window head (within the merge slack) extends the live span
    # beyond t1 without adding any in-window points
    key = canonical_series(
        'namespace_app_per_pod:latency{namespace="ns",app="new0"}'
    )
    ring.push(key, np.array([t1 + 60], dtype=np.int64),
              np.array([1.0], dtype=np.float32), now=NOW)
    assert worker.tick(now=NOW + 160) == 1  # steady tick -> refinement
    st = book.debug_state()
    assert st["settled"] == 1 and st["finalized"] == 0, st
    assert len(book) == 0
    # no invalidation was paid: the admitted fit entries are untouched
    assert dict(worker._fit_cache._d) == fits_before


def test_refinement_record_survives_transient_ring_loss():
    """A refinement pass firing while the ring transiently cannot serve
    a provisional fit's series (mesh-rebalance eviction, budget
    pressure, a pusher pause) must KEEP the record: the short-history
    fit is still warm in the fit cache, so no cold claim will ever
    re-note it — dropping here would park the fit at its admitted
    history forever once the series comes back."""
    base = int(NOW)
    t1 = base - 1000
    store, ring, source = _newcomer_fleet(
        push0=base - 8200, push_end=base - 1200, t1=t1, floor=3600.0
    )
    worker = BrainWorker(
        store, source, config=BrainConfig(algorithm="moving_average_all"),
        claim_limit=4, worker_id="loss-w",
    )
    assert worker.tick(now=NOW + 150) == 1
    book = worker._refine_book
    assert len(book) == 1
    # the ring loses the series (rebalance eviction / budget pressure)
    assert ring.evict_unowned(lambda k: False) == 1
    assert worker._refine_provisional(NOW + 160) == 0
    st = book.debug_state()
    assert st["pending"] == 1 and st["dropped"] == 0, st
    # the series comes back and closes the window: the SAME record pays
    # its terminal refit
    key = canonical_series(
        'namespace_app_per_pod:latency{namespace="ns",app="new0"}'
    )
    rng = np.random.default_rng(9)
    t = np.arange(base - 8200, t1 + 120, 60, dtype=np.int64)
    ring.push(key, t, rng.normal(1.0, 0.1, len(t)).astype(np.float32),
              now=NOW)
    assert worker._refine_provisional(NOW + 170) == 1
    st = book.debug_state()
    assert st["finalized"] == 1 and st["dropped"] == 0, st
    assert len(book) == 0


def test_partial_admission_is_pure_push_only():
    """With a fallback configured, an uncovered window start must keep
    degrading to the fallback — it may hold the full history the ring
    lost — instead of silently pinning the doc to the ring's short
    slice forever."""
    feed = WindowedSource()
    t_full = np.arange(0, 60_000, 60, dtype=np.int64)
    feed.data["m"] = (t_full, np.ones(len(t_full), np.float32))
    ring = RingStore(shards=1, stale_seconds=300.0)
    # ring holds only a recent live span (well past any floor)
    live = t_full[t_full >= 50_000]
    ring.push("m", live, np.ones(len(live), np.float32), now=60_000.0)
    url = "http://p/api/v1/query_range?query=m&start=0&end=59940&step=60"
    # pure push: the same ring state serves the partial slice
    pure = RingSource(ring, fallback=None, clock=lambda: 60_000.0,
                      admit_floor=600.0)
    res = pure.hist_columns(url)
    assert res is not None and res[0] == "partial"
    # hybrid: the floor is inert — degrade to the fallback, which has
    # the full history and backfills it (resident from then on)
    hybrid = RingSource(ring, fallback=feed, clock=lambda: 60_000.0,
                        admit_floor=600.0)
    assert hybrid.hist_columns(url) is None
    ts, _ = hybrid.fetch(url)
    assert len(feed.calls) == 1
    assert len(ts) == len(t_full)  # the FULL history, not the slice
    # ... and the backfill made even the ring-first read FULL
    res = hybrid.hist_columns(url)
    assert res is not None and res[0] == "full"
