"""Worker mesh (ISSUE 6): partitioning, membership, routing, claims.

The mesh's correctness story has four independently testable legs:

  1. the consistent-hash ring is deterministic, reasonably balanced,
     and moves ONLY the dead member's keys on a membership change;
  2. membership leases: join/renew/expiry/leave against the real store
     API, with injectable clocks (no sleeps);
  3. route keys co-locate an application's documents with its pushed
     series, and the receiver answers foreign-series pushes with the
     owner's advertised address (accepting the samples regardless);
  4. the claim filter partitions a shared store: N workers claim
     disjoint subsets whose union is the fleet — on the in-memory
     store AND through the ES store's search+CAS path.

The worker-level kill/rebalance scenario lives in test_pod_failure.py;
the multi-process version runs in benchmarks/scaleout_bench.py.
"""

from __future__ import annotations

import json
import urllib.request

import numpy as np

from foremast_tpu.jobs.models import Document
from foremast_tpu.jobs.store import InMemoryStore
from foremast_tpu.mesh import (
    MESH_APP,
    HashRing,
    Membership,
    MeshNode,
    MeshRouter,
    RoutingPusher,
    doc_route_key,
    live_members,
    series_route_key,
)

# ---------------------------------------------------------------------------
# partition: the hash ring
# ---------------------------------------------------------------------------


def test_ring_deterministic_and_total():
    r1 = HashRing(["w0", "w1", "w2"])
    r2 = HashRing(["w2", "w0", "w1"])  # construction order must not matter
    for i in range(500):
        key = f"app{i}"
        assert r1.owner(key) == r2.owner(key)
        assert r1.owner(key) in ("w0", "w1", "w2")
    assert HashRing([]).owner("x") is None
    assert HashRing(["solo"]).owner("anything") == "solo"


def test_ring_balance_and_minimal_movement():
    members = [f"w{i}" for i in range(4)]
    ring = HashRing(members, replicas=64)
    keys = [f"app{i}" for i in range(8000)]
    owners = {k: ring.owner(k) for k in keys}
    counts = {m: sum(1 for o in owners.values() if o == m) for m in members}
    # 64 virtual nodes keep the spread reasonable at 4 members
    assert min(counts.values()) > 0.5 * (8000 / 4), counts
    assert max(counts.values()) < 1.6 * (8000 / 4), counts
    # kill w3: ONLY its keys move, and they land on survivors
    healed = HashRing(members[:3], replicas=64)
    for k in keys:
        if owners[k] != "w3":
            assert healed.owner(k) == owners[k], k
        else:
            assert healed.owner(k) in ("w0", "w1", "w2")


def test_ring_capacity_weighting():
    ring = HashRing({"big": 4, "small": 1}, replicas=64)
    keys = [f"app{i}" for i in range(4000)]
    big = sum(1 for k in keys if ring.owner(k) == "big")
    assert big > 2400, big  # ~4/5 of the keyspace, with slack


# ---------------------------------------------------------------------------
# membership: leases in the store
# ---------------------------------------------------------------------------


def _clock(box):
    return lambda: box[0]


def test_membership_join_renew_expire_leave():
    store = InMemoryStore()
    t = [1000.0]
    a = Membership(store, "w-a", lease_seconds=10.0, clock=_clock(t))
    b = Membership(store, "w-b", lease_seconds=10.0, clock=_clock(t))
    a.join()
    b.join()
    assert [m.worker_id for m in live_members(store, now=t[0])] == [
        "w-a", "w-b",
    ]
    # member docs are invisible to the claim path
    assert store.claim("claimer", 90.0, limit=10) == []

    # a renews, b goes silent: at t+11 only a is live
    t[0] = 1006.0
    assert a.renew() is True  # past lease/3
    assert a.renew() is False  # rate-limited
    t[0] = 1011.0
    assert [m.worker_id for m in live_members(store, now=t[0])] == ["w-a"]

    # b's next renew resurrects it (a restart re-taking its seat)
    b.renew()
    assert len(live_members(store, now=t[0])) == 2

    # a clean leave disappears immediately, fresh lease or not
    a.leave()
    assert [m.worker_id for m in live_members(store, now=t[0])] == ["w-b"]


def test_membership_lease_tolerates_reader_clock_skew():
    """ISSUE 9 satellite: a reader whose clock runs FAST must not
    declare a healthy renewing peer dead. membership.py reads leases by
    the READER's clock; a renewing member's record is at most lease/3
    stale, so the pinned tolerance is skew < 2/3 × lease
    (`CLOCK_SKEW_TOLERANCE_FRACTION`), with lease/2 the documented ops
    guidance. This pins both sides of the bound."""
    from foremast_tpu.mesh.membership import CLOCK_SKEW_TOLERANCE_FRACTION

    lease = 12.0
    store = InMemoryStore()
    t = [1000.0]
    member = Membership(store, "w-m", lease_seconds=lease, clock=_clock(t))
    member.join()
    # the member keeps renewing on its own cadence (every lease/3)
    for step in range(12):
        t[0] = 1000.0 + (step + 1) * (lease / 3.0)
        member.renew()
        # worst-case record staleness right before the NEXT renewal:
        real_now = t[0] + lease / 3.0 - 0.01
        # documented guidance (lease/2): always safe
        assert [
            m.worker_id
            for m in live_members(store, now=real_now + lease / 2.0)
        ] == ["w-m"], f"lease/2-skewed reader killed a healthy peer @{step}"
        # the pinned bound: any skew strictly under 2/3·lease is safe
        safe_skew = CLOCK_SKEW_TOLERANCE_FRACTION * lease - 0.05
        assert [
            m.worker_id
            for m in live_members(store, now=real_now + safe_skew)
        ] == ["w-m"]
    # and the bound is TIGHT: past 2/3·lease a fast reader CAN misjudge
    # a peer observed at its stalest (why ops guidance stays at lease/2)
    stale_now = t[0] + lease / 3.0 - 0.01
    over_skew = CLOCK_SKEW_TOLERANCE_FRACTION * lease + 0.1
    assert live_members(store, now=stale_now + over_skew) == []


def test_membership_slow_reader_only_delays_death_detection():
    """A reader running SLOW never falsely kills anyone — it just sees
    a dead peer as alive for up to the skew longer."""
    store = InMemoryStore()
    t = [1000.0]
    m = Membership(store, "w-dead", lease_seconds=10.0, clock=_clock(t))
    m.join()
    # peer dies at t=1000; a true-clock reader drops it at 1010.x
    assert live_members(store, now=1011.0) == []
    # a reader 5s slow still sees it until its own clock passes the
    # lease — delayed detection, never a false kill
    assert [r.worker_id for r in live_members(store, now=1006.0)] == [
        "w-dead"
    ]


def test_membership_record_carries_addresses():
    store = InMemoryStore()
    m = Membership(
        store, "w-x", lease_seconds=5.0,
        ingest_address="10.0.0.7:9009", observe_port=8001, capacity=2,
    )
    m.join()
    (rec,) = live_members(store)
    assert rec.ingest_address == "10.0.0.7:9009"
    assert rec.observe_port == 8001
    assert rec.capacity == 2


def test_membership_corrupt_record_is_dead_not_fatal():
    store = InMemoryStore()
    Membership(store, "w-ok", lease_seconds=60.0).join()
    store.create(
        Document(
            id="mesh::garbage",
            app_name=MESH_APP,
            status="mesh_member",
            current_config="{not json",
        )
    )
    assert [m.worker_id for m in live_members(store)] == ["w-ok"]


# ---------------------------------------------------------------------------
# routing: docs and series share an owner
# ---------------------------------------------------------------------------


def test_route_keys_colocate_doc_and_series():
    doc = Document(id="j1", app_name="checkout")
    assert doc_route_key(doc) == "checkout"
    # any label order, any matcher spacing — one canonical route key
    assert series_route_key('errors{app="checkout",ns="prod"}') == "checkout"
    assert series_route_key('errors{ns="prod", app="checkout"}') == "checkout"
    # no routing label: the whole canonical key is the identity
    assert (
        series_route_key('errors{ns="prod"}')
        == series_route_key('errors{ ns="prod" }')
    )
    # label named *app* only — a suffix like myapp must not match
    assert series_route_key('m{myapp="x"}') == 'm{myapp="x"}'


def _mesh_pair(store):
    t = [0.0]
    nodes = []
    for wid in ("w-a", "w-b"):
        mem = Membership(store, wid, lease_seconds=30.0, clock=_clock(t))
        router = MeshRouter(mem, refresh_seconds=0.0, clock=_clock(t))
        node = MeshNode(mem, router, clock=_clock(t))
        node.start()
        nodes.append(node)
    for node in nodes:
        node.router.refresh(force=True)  # both see both
    return nodes, t


def test_router_ownership_is_a_partition():
    store = InMemoryStore()
    (a, b), _ = _mesh_pair(store)
    docs = [Document(id=f"j{i}", app_name=f"app{i}") for i in range(300)]
    owned_a = {d.id for d in docs if a.claim_filter(d)}
    owned_b = {d.id for d in docs if b.claim_filter(d)}
    assert owned_a.isdisjoint(owned_b)
    assert len(owned_a) + len(owned_b) == 300
    assert owned_a and owned_b
    # series follow their app's documents
    for d in docs[:50]:
        key = f'latency{{app="{d.app_name}"}}'
        assert (a.router.owns_series(key)) == (d.id in owned_a)
    assert a.claim_counts["owned"] == len(owned_a)
    assert a.claim_counts["skipped"] == 300 - len(owned_a)


def test_router_sole_member_owns_everything():
    store = InMemoryStore()
    mem = Membership(store, "only", lease_seconds=30.0)
    router = MeshRouter(mem, refresh_seconds=0.0)
    node = MeshNode(mem, router)
    node.start()
    assert node.claim_filter(Document(id="x", app_name="anything"))
    assert router.redirect_hint('m{app="anything"}') is None


def test_rebalance_on_member_death_moves_only_orphans():
    store = InMemoryStore()
    (a, b), t = _mesh_pair(store)
    docs = [Document(id=f"j{i}", app_name=f"app{i}") for i in range(300)]
    before_a = {d.id for d in docs if a.router.owns_doc(d)}
    base = a.router.counters["rebalances"]
    # b dies: lease expires, a's next refresh heals the ring
    t[0] = 31.0
    a.membership.renew(force=True)
    assert a.router.refresh(force=True) is True
    assert a.router.counters["rebalances"] == base + 1
    after_a = {d.id for d in docs if a.router.owns_doc(d)}
    assert after_a == {d.id for d in docs}  # sole survivor owns all
    assert before_a <= after_a


# ---------------------------------------------------------------------------
# claims against shared stores
# ---------------------------------------------------------------------------


def _fleet(store, n):
    for i in range(n):
        store.create(
            Document(
                id=f"j{i}", app_name=f"app{i}",
                current_config="m== http://x", strategy="continuous",
            )
        )


def test_inmemory_claims_partition_the_fleet():
    store = InMemoryStore()
    (a, b), _ = _mesh_pair(store)
    _fleet(store, 60)
    got_a = store.claim("w-a", 90.0, limit=100, claim_filter=a.claim_filter)
    got_b = store.claim("w-b", 90.0, limit=100, claim_filter=b.claim_filter)
    ids_a = {d.id for d in got_a}
    ids_b = {d.id for d in got_b}
    assert ids_a.isdisjoint(ids_b)
    assert len(ids_a) + len(ids_b) == 60
    # filtered docs were NOT parked in-progress: a second owner claim
    # of the other partition still finds them claimable
    assert store.claim("w-a", 90.0, limit=100, claim_filter=a.claim_filter) == []


def test_es_store_claim_filter_between_search_and_cas():
    """The ES path applies the partition filter client-side between the
    claimability search and the bulk CAS: only owned docs are CASed,
    foreign hits stay untouched (status unchanged, seq_no unchanged)."""
    from test_es_store import FakeES

    from foremast_tpu.jobs.store import ElasticsearchStore

    fake = FakeES()
    store = ElasticsearchStore("http://fake:9200", session=fake)
    store.ensure_index()
    _fleet(store, 20)
    (a, b), _ = _mesh_pair(store)
    got_a = store.claim("w-a", 90.0, limit=50, claim_filter=a.claim_filter)
    ids_a = {d.id for d in got_a}
    assert ids_a and len(ids_a) < 20
    for doc_id, rec in fake.docs.items():
        if not doc_id.startswith("j"):
            continue
        status = rec["_source"]["status"]
        if doc_id in ids_a:
            assert status == "preprocess_inprogress"
        else:
            assert status == "initial"
    got_b = store.claim("w-b", 90.0, limit=50, claim_filter=b.claim_filter)
    assert {d.id for d in got_b} == {
        f"j{i}" for i in range(20)
    } - ids_a


def test_es_store_list_app_finds_members_past_the_open_page():
    from test_es_store import FakeES

    from foremast_tpu.jobs.store import ElasticsearchStore

    fake = FakeES()
    store = ElasticsearchStore("http://fake:9200", session=fake)
    store.ensure_index()
    _fleet(store, 5)
    Membership(store, "w-es", lease_seconds=30.0).join()
    docs = store.list_app(MESH_APP)
    assert [d.id for d in docs] == ["mesh::w-es"]
    assert live_members(store)[0].worker_id == "w-es"


# ---------------------------------------------------------------------------
# routed ingest: receiver hints + pusher convergence
# ---------------------------------------------------------------------------


def test_receiver_redirect_hint_accepts_and_points_at_owner():
    from foremast_tpu.ingest import RingStore, start_ingest_server

    store = InMemoryStore()
    (a, b), _ = _mesh_pair(store)
    # advertise addresses so hints can carry them
    a.membership.ingest_address = "127.0.0.1:7001"
    b.membership.ingest_address = "127.0.0.1:7002"
    a.membership.renew(force=True)
    b.membership.renew(force=True)
    a.router.refresh(force=True)
    b.router.refresh(force=True)

    # find one app owned by b
    foreign_app = next(
        f"app{i}"
        for i in range(100)
        if not a.router.owns_doc(Document(id="x", app_name=f"app{i}"))
    )
    ring = RingStore(shards=1)
    srv, _ = start_ingest_server(
        0, ring, host="127.0.0.1", router=a.router
    )
    try:
        port = srv.server_address[1]
        body = json.dumps(
            {
                "timeseries": [
                    {
                        "alias": f'm{{app="{foreign_app}"}}',
                        "times": [60, 120],
                        "values": [1.0, 2.0],
                    }
                ]
            }
        ).encode()
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/api/v1/write", data=body, method="POST"
        )
        out = json.loads(urllib.request.urlopen(req).read())
        # accepted (lossless during convergence) AND hinted at the owner
        assert out["accepted_samples"] == 2
        assert out["redirects"] == {
            f'm{{app="{foreign_app}"}}': "127.0.0.1:7002"
        }
        assert ring.stats()["series"] == 1
        assert a.router.counters["redirect_hints"] == 1
    finally:
        srv.shutdown()


def test_routing_pusher_converges_in_one_cycle():
    from foremast_tpu.ingest import RingStore, start_ingest_server

    store = InMemoryStore()
    (a, b), _ = _mesh_pair(store)
    rings = {}
    servers = []
    try:
        for node in (a, b):
            ring = RingStore(shards=1)
            srv, _ = start_ingest_server(
                0, ring, host="127.0.0.1", router=node.router
            )
            addr = f"127.0.0.1:{srv.server_address[1]}"
            node.membership.ingest_address = addr
            node.membership.renew(force=True)
            rings[node.worker_id] = ring
            servers.append(srv)
        a.router.refresh(force=True)
        b.router.refresh(force=True)

        series = [
            (
                f'm{{app="app{i}"}}',
                [60, 120],
                np.asarray([1.0, 2.0], np.float32),
                None,
            )
            for i in range(40)
        ]
        pusher = RoutingPusher([a.membership.ingest_address])
        first = pusher.push_cycle(series)
        assert first["redirects"] > 0  # b's share got hints
        second = pusher.push_cycle(series)
        assert second["redirects"] == 0  # converged
        # every series now resides on its OWNER's ring
        for key, *_ in series:
            owner = a.router.owner_of_series(key)
            assert rings[owner].query(key, 0, 120, now=150.0)[0] == "hit"
    finally:
        for srv in servers:
            srv.shutdown()


def test_routing_pusher_retries_through_receiver_restart():
    """ISSUE 7 satellite: a receiver down for restart costs the pusher
    RETRIES (jittered backoff), not samples — the POST succeeds on a
    later attempt within the same cycle, and nothing is buffered or
    dropped."""
    from foremast_tpu.ingest import RingStore, start_ingest_server

    ring = RingStore(shards=1)
    srv, _ = start_ingest_server(0, ring, host="127.0.0.1")
    try:
        addr = f"127.0.0.1:{srv.server_address[1]}"
        slept = []
        pusher = RoutingPusher(
            [addr], retries=3, backoff_seconds=0.1,
            sleep=slept.append,  # injected: no real waiting in tests
        )
        orig_post = pusher._post
        fails = [2]  # receiver "restarting" for the first 2 attempts

        def flaky(address, entries):
            if fails[0] > 0:
                fails[0] -= 1
                raise OSError("connection refused (restarting)")
            return orig_post(address, entries)

        pusher._post = flaky
        out = pusher.push_cycle(
            [('m{app="a"}', [60, 120], [1.0, 2.0], None)]
        )
        assert out["accepted"] == 2 and out["errors"] == 0
        assert out["buffered"] == 0 and out["dropped"] == 0
        assert pusher.counters["retries"] == 2
        # backoff grew and was jittered within [0.5, 1.5] of the base
        assert len(slept) == 2
        assert 0.05 <= slept[0] <= 0.15 and 0.1 <= slept[1] <= 0.3
        assert ring.stats()["series"] == 1
    finally:
        srv.shutdown()


def test_routing_pusher_buffers_and_flushes_across_outage():
    """A receiver down PAST the retry budget buffers the cycle's series
    (no samples lost that the cap allows keeping) and re-sends them at
    the front of the next cycle once the receiver is back."""
    from foremast_tpu.ingest import RingStore, start_ingest_server

    ring = RingStore(shards=1)
    srv, _ = start_ingest_server(0, ring, host="127.0.0.1")
    try:
        addr = f"127.0.0.1:{srv.server_address[1]}"
        pusher = RoutingPusher(
            [addr], retries=1, backoff_seconds=0.0, sleep=lambda s: None
        )
        down = [True]
        orig_post = pusher._post

        def gated(address, entries):
            if down[0]:
                raise OSError("connection refused")
            return orig_post(address, entries)

        pusher._post = gated
        out = pusher.push_cycle(
            [('m{app="a"}', [60], [1.0], None),
             ('m{app="b"}', [60], [2.0], None)]
        )
        assert out["errors"] == 1 and out["buffered"] == 2
        assert ring.stats()["series"] == 0  # receiver never saw them
        down[0] = False  # receiver restarted
        out2 = pusher.push_cycle([('m{app="c"}', [60], [3.0], None)])
        assert out2["errors"] == 0 and out2["buffered"] == 0
        assert out2["accepted"] == 3  # backlog + the new series
        assert pusher.counters["resent_series"] == 2
        assert ring.stats()["series"] == 3
    finally:
        srv.shutdown()


def test_routing_pusher_rejected_batch_is_dropped_not_buffered():
    """An HTTP error status is the receiver ANSWERING (400 malformed /
    413 over cap) — a permanent verdict on the batch. It must not burn
    retries and must NOT be buffered: re-merging a poisoned batch into
    later cycles would get every subsequent healthy series rejected
    alongside it."""
    from foremast_tpu.ingest import RingStore, start_ingest_server

    ring = RingStore(shards=1)
    srv, _ = start_ingest_server(0, ring, host="127.0.0.1")
    try:
        addr = f"127.0.0.1:{srv.server_address[1]}"
        slept = []
        pusher = RoutingPusher(
            [addr], retries=3, backoff_seconds=0.1, sleep=slept.append
        )
        # values that json-encode fine but fail the receiver's codec
        # (times/values length mismatch) => a real 400 over the wire
        bad = [('m{app="bad"}', [60, 120], [1.0], None)]
        out = pusher.push_cycle(bad)
        assert out["errors"] == 1 and out["rejected"] == 1
        assert out["buffered"] == 0 and pusher.buffered == 0
        assert slept == []  # no retry backoff burned on a verdict
        assert pusher.counters["rejected_series"] == 1
        # the next cycle is clean: nothing poisoned it
        out2 = pusher.push_cycle([('m{app="ok"}', [60], [1.0], None)])
        assert out2["accepted"] == 1 and out2["errors"] == 0
        assert ring.stats()["series"] == 1
    finally:
        srv.shutdown()


def test_routing_pusher_transient_status_retries_like_transport():
    """429/5xx are a proxy answering for a pod that is down (or an
    overloaded receiver) — the same transient class PrometheusSource
    retries. They must retry with backoff and eventually land, never
    count as a permanent rejection."""
    import io
    import urllib.error

    from foremast_tpu.ingest import RingStore, start_ingest_server

    ring = RingStore(shards=1)
    srv, _ = start_ingest_server(0, ring, host="127.0.0.1")
    try:
        addr = f"127.0.0.1:{srv.server_address[1]}"
        slept = []
        pusher = RoutingPusher(
            [addr], retries=3, backoff_seconds=0.1, sleep=slept.append
        )
        orig_post = pusher._post
        fails = [2]

        def proxied(address, entries):
            if fails[0] > 0:
                fails[0] -= 1
                raise urllib.error.HTTPError(
                    f"http://{address}", 503, "pod restarting", None,
                    io.BytesIO(b""),
                )
            return orig_post(address, entries)

        pusher._post = proxied
        out = pusher.push_cycle([('m{app="a"}', [60], [1.0], None)])
        assert out["accepted"] == 1 and out["errors"] == 0
        assert out["rejected"] == 0 and out["buffered"] == 0
        assert pusher.counters["retries"] == 2 and len(slept) == 2
        assert pusher.counters["rejected_series"] == 0
    finally:
        srv.shutdown()


def test_routing_pusher_buffer_cap_drops_oldest_with_counter():
    """The outage buffer is byte-capped: past it the OLDEST series drop
    (newest samples are what restart recovery needs) and the drop is
    counted, never silent."""
    pusher = RoutingPusher(
        ["127.0.0.1:1"], retries=0, backoff_seconds=0.0,
        sleep=lambda s: None, buffer_bytes=300,
    )

    def dead(address, entries):
        raise OSError("connection refused")

    pusher._post = dead
    for i in range(6):
        pusher.push_cycle([(f'm{{app="a{i}"}}', [60, 120], [1.0, 2.0], None)])
    assert pusher.counters["dropped_series"] > 0
    assert pusher.buffered < 6
    kept = {key for _, key, _ in pusher._buffer}
    assert f'm{{app="a5"}}' in kept  # newest kept
    assert f'm{{app="a0"}}' not in kept  # oldest dropped
    assert (
        pusher.counters["buffered_series"]
        == pusher.counters["dropped_series"]
        + pusher.counters["resent_series"]
        + pusher.buffered
    )


# ---------------------------------------------------------------------------
# worker integration: debug state + observe port auto-increment
# ---------------------------------------------------------------------------


def test_worker_debug_state_has_mesh_section():
    from foremast_tpu.config import BrainConfig
    from foremast_tpu.jobs.worker import BrainWorker
    from foremast_tpu.metrics.source import StaticSource

    store = InMemoryStore()
    (a, _b), _ = _mesh_pair(store)
    worker = BrainWorker(
        store,
        StaticSource({}),
        config=BrainConfig(),
        worker_id="w-a",
        mesh=a,
    )
    worker.tick(now=1000.0)
    state = worker.debug_state()
    assert state["mesh"]["live_members"] == 2
    assert {m["worker_id"] for m in state["mesh"]["members"]} == {
        "w-a", "w-b",
    }
    assert state["mesh"]["claim_docs"]["owned"] >= 0
    worker.close()


def test_observe_server_auto_increments_busy_port():
    import socket
    import urllib.request as _rq

    from foremast_tpu.observe.spans import start_observe_server

    blocker = socket.socket()
    blocker.bind(("127.0.0.1", 0))
    blocker.listen(1)
    port = blocker.getsockname()[1]
    try:
        srv, _ = start_observe_server(
            port, state_fn=lambda: {"ok": 1}, host="127.0.0.1",
            max_port_tries=8,
        )
        try:
            actual = srv.server_address[1]
            assert port < actual <= port + 7
            state = json.loads(
                _rq.urlopen(
                    f"http://127.0.0.1:{actual}/debug/state"
                ).read()
            )
            assert state == {"ok": 1}
        finally:
            srv.shutdown()
    finally:
        blocker.close()
