"""Worker mesh (ISSUE 6): partitioning, membership, routing, claims.

The mesh's correctness story has four independently testable legs:

  1. the consistent-hash ring is deterministic, reasonably balanced,
     and moves ONLY the dead member's keys on a membership change;
  2. membership leases: join/renew/expiry/leave against the real store
     API, with injectable clocks (no sleeps);
  3. route keys co-locate an application's documents with its pushed
     series, and the receiver answers foreign-series pushes with the
     owner's advertised address (accepting the samples regardless);
  4. the claim filter partitions a shared store: N workers claim
     disjoint subsets whose union is the fleet — on the in-memory
     store AND through the ES store's search+CAS path.

The worker-level kill/rebalance scenario lives in test_pod_failure.py;
the multi-process version runs in benchmarks/scaleout_bench.py.
"""

from __future__ import annotations

import json
import urllib.request

import numpy as np
import pytest

from foremast_tpu.jobs.models import Document
from foremast_tpu.jobs.store import InMemoryStore
from foremast_tpu.mesh import (
    MESH_APP,
    HashRing,
    Membership,
    MeshNode,
    MeshRouter,
    RoutingPusher,
    doc_route_key,
    live_members,
    series_route_key,
)



@pytest.fixture(scope="module", autouse=True)
def lock_witness():
    """ISSUE 11: the runtime lock witness rides this module — the
    handoff suite exercises the transfer plane's lock nesting (handoff
    manager lock under receiver handler threads racing the tick-side
    sender) and at teardown every OBSERVED acquisition edge must exist
    in the committed static lock graph (`make lockgraph` on a miss)."""
    from foremast_tpu.analysis import witness

    wit = witness.install()
    yield wit
    graph = witness.load_graph()
    witness.uninstall()
    assert graph is not None, "analysis_lockgraph.json missing from repo root"
    missing = wit.unobserved_edges(graph)
    assert not missing, (
        "runtime lock-acquisition edges missing from the static graph "
        f"(run `make lockgraph` and review): {missing}"
    )


# ---------------------------------------------------------------------------
# partition: the hash ring
# ---------------------------------------------------------------------------


def test_ring_deterministic_and_total():
    r1 = HashRing(["w0", "w1", "w2"])
    r2 = HashRing(["w2", "w0", "w1"])  # construction order must not matter
    for i in range(500):
        key = f"app{i}"
        assert r1.owner(key) == r2.owner(key)
        assert r1.owner(key) in ("w0", "w1", "w2")
    assert HashRing([]).owner("x") is None
    assert HashRing(["solo"]).owner("anything") == "solo"


def test_ring_balance_and_minimal_movement():
    members = [f"w{i}" for i in range(4)]
    ring = HashRing(members, replicas=64)
    keys = [f"app{i}" for i in range(8000)]
    owners = {k: ring.owner(k) for k in keys}
    counts = {m: sum(1 for o in owners.values() if o == m) for m in members}
    # 64 virtual nodes keep the spread reasonable at 4 members
    assert min(counts.values()) > 0.5 * (8000 / 4), counts
    assert max(counts.values()) < 1.6 * (8000 / 4), counts
    # kill w3: ONLY its keys move, and they land on survivors
    healed = HashRing(members[:3], replicas=64)
    for k in keys:
        if owners[k] != "w3":
            assert healed.owner(k) == owners[k], k
        else:
            assert healed.owner(k) in ("w0", "w1", "w2")


def test_ring_capacity_weighting():
    ring = HashRing({"big": 4, "small": 1}, replicas=64)
    keys = [f"app{i}" for i in range(4000)]
    big = sum(1 for k in keys if ring.owner(k) == "big")
    assert big > 2400, big  # ~4/5 of the keyspace, with slack


# ---------------------------------------------------------------------------
# membership: leases in the store
# ---------------------------------------------------------------------------


def _clock(box):
    return lambda: box[0]


def test_membership_join_renew_expire_leave():
    store = InMemoryStore()
    t = [1000.0]
    a = Membership(store, "w-a", lease_seconds=10.0, clock=_clock(t))
    b = Membership(store, "w-b", lease_seconds=10.0, clock=_clock(t))
    a.join()
    b.join()
    assert [m.worker_id for m in live_members(store, now=t[0])] == [
        "w-a", "w-b",
    ]
    # member docs are invisible to the claim path
    assert store.claim("claimer", 90.0, limit=10) == []

    # a renews, b goes silent: at t+11 only a is live
    t[0] = 1006.0
    assert a.renew() is True  # past lease/3
    assert a.renew() is False  # rate-limited
    t[0] = 1011.0
    assert [m.worker_id for m in live_members(store, now=t[0])] == ["w-a"]

    # b's next renew resurrects it (a restart re-taking its seat)
    b.renew()
    assert len(live_members(store, now=t[0])) == 2

    # a clean leave disappears immediately, fresh lease or not
    a.leave()
    assert [m.worker_id for m in live_members(store, now=t[0])] == ["w-b"]


def test_membership_lease_tolerates_reader_clock_skew():
    """ISSUE 9 satellite: a reader whose clock runs FAST must not
    declare a healthy renewing peer dead. membership.py reads leases by
    the READER's clock; a renewing member's record is at most lease/3
    stale, so the pinned tolerance is skew < 2/3 × lease
    (`CLOCK_SKEW_TOLERANCE_FRACTION`), with lease/2 the documented ops
    guidance. This pins both sides of the bound."""
    from foremast_tpu.mesh.membership import CLOCK_SKEW_TOLERANCE_FRACTION

    lease = 12.0
    store = InMemoryStore()
    t = [1000.0]
    member = Membership(store, "w-m", lease_seconds=lease, clock=_clock(t))
    member.join()
    # the member keeps renewing on its own cadence (every lease/3)
    for step in range(12):
        t[0] = 1000.0 + (step + 1) * (lease / 3.0)
        member.renew()
        # worst-case record staleness right before the NEXT renewal:
        real_now = t[0] + lease / 3.0 - 0.01
        # documented guidance (lease/2): always safe
        assert [
            m.worker_id
            for m in live_members(store, now=real_now + lease / 2.0)
        ] == ["w-m"], f"lease/2-skewed reader killed a healthy peer @{step}"
        # the pinned bound: any skew strictly under 2/3·lease is safe
        safe_skew = CLOCK_SKEW_TOLERANCE_FRACTION * lease - 0.05
        assert [
            m.worker_id
            for m in live_members(store, now=real_now + safe_skew)
        ] == ["w-m"]
    # and the bound is TIGHT: past 2/3·lease a fast reader CAN misjudge
    # a peer observed at its stalest (why ops guidance stays at lease/2)
    stale_now = t[0] + lease / 3.0 - 0.01
    over_skew = CLOCK_SKEW_TOLERANCE_FRACTION * lease + 0.1
    assert live_members(store, now=stale_now + over_skew) == []


def test_membership_slow_reader_only_delays_death_detection():
    """A reader running SLOW never falsely kills anyone — it just sees
    a dead peer as alive for up to the skew longer."""
    store = InMemoryStore()
    t = [1000.0]
    m = Membership(store, "w-dead", lease_seconds=10.0, clock=_clock(t))
    m.join()
    # peer dies at t=1000; a true-clock reader drops it at 1010.x
    assert live_members(store, now=1011.0) == []
    # a reader 5s slow still sees it until its own clock passes the
    # lease — delayed detection, never a false kill
    assert [r.worker_id for r in live_members(store, now=1006.0)] == [
        "w-dead"
    ]


def test_membership_record_carries_addresses():
    store = InMemoryStore()
    m = Membership(
        store, "w-x", lease_seconds=5.0,
        ingest_address="10.0.0.7:9009", observe_port=8001, capacity=2,
    )
    m.join()
    (rec,) = live_members(store)
    assert rec.ingest_address == "10.0.0.7:9009"
    assert rec.observe_port == 8001
    assert rec.capacity == 2


def test_membership_corrupt_record_is_dead_not_fatal():
    store = InMemoryStore()
    Membership(store, "w-ok", lease_seconds=60.0).join()
    store.create(
        Document(
            id="mesh::garbage",
            app_name=MESH_APP,
            status="mesh_member",
            current_config="{not json",
        )
    )
    assert [m.worker_id for m in live_members(store)] == ["w-ok"]


# ---------------------------------------------------------------------------
# routing: docs and series share an owner
# ---------------------------------------------------------------------------


def test_route_keys_colocate_doc_and_series():
    doc = Document(id="j1", app_name="checkout")
    assert doc_route_key(doc) == "checkout"
    # any label order, any matcher spacing — one canonical route key
    assert series_route_key('errors{app="checkout",ns="prod"}') == "checkout"
    assert series_route_key('errors{ns="prod", app="checkout"}') == "checkout"
    # no routing label: the whole canonical key is the identity
    assert (
        series_route_key('errors{ns="prod"}')
        == series_route_key('errors{ ns="prod" }')
    )
    # label named *app* only — a suffix like myapp must not match
    assert series_route_key('m{myapp="x"}') == 'm{myapp="x"}'


def _mesh_pair(store):
    t = [0.0]
    nodes = []
    for wid in ("w-a", "w-b"):
        mem = Membership(store, wid, lease_seconds=30.0, clock=_clock(t))
        router = MeshRouter(mem, refresh_seconds=0.0, clock=_clock(t))
        node = MeshNode(mem, router, clock=_clock(t))
        node.start()
        nodes.append(node)
    for node in nodes:
        node.router.refresh(force=True)  # both see both
    return nodes, t


def test_router_ownership_is_a_partition():
    store = InMemoryStore()
    (a, b), _ = _mesh_pair(store)
    docs = [Document(id=f"j{i}", app_name=f"app{i}") for i in range(300)]
    owned_a = {d.id for d in docs if a.claim_filter(d)}
    owned_b = {d.id for d in docs if b.claim_filter(d)}
    assert owned_a.isdisjoint(owned_b)
    assert len(owned_a) + len(owned_b) == 300
    assert owned_a and owned_b
    # series follow their app's documents
    for d in docs[:50]:
        key = f'latency{{app="{d.app_name}"}}'
        assert (a.router.owns_series(key)) == (d.id in owned_a)
    assert a.claim_counts["owned"] == len(owned_a)
    assert a.claim_counts["skipped"] == 300 - len(owned_a)


def test_router_sole_member_owns_everything():
    store = InMemoryStore()
    mem = Membership(store, "only", lease_seconds=30.0)
    router = MeshRouter(mem, refresh_seconds=0.0)
    node = MeshNode(mem, router)
    node.start()
    assert node.claim_filter(Document(id="x", app_name="anything"))
    assert router.redirect_hint('m{app="anything"}') is None


def test_rebalance_on_member_death_moves_only_orphans():
    store = InMemoryStore()
    (a, b), t = _mesh_pair(store)
    docs = [Document(id=f"j{i}", app_name=f"app{i}") for i in range(300)]
    before_a = {d.id for d in docs if a.router.owns_doc(d)}
    base = a.router.counters["rebalances"]
    # b dies: lease expires, a's next refresh heals the ring
    t[0] = 31.0
    a.membership.renew(force=True)
    assert a.router.refresh(force=True) is True
    assert a.router.counters["rebalances"] == base + 1
    after_a = {d.id for d in docs if a.router.owns_doc(d)}
    assert after_a == {d.id for d in docs}  # sole survivor owns all
    assert before_a <= after_a


# ---------------------------------------------------------------------------
# claims against shared stores
# ---------------------------------------------------------------------------


def _fleet(store, n):
    for i in range(n):
        store.create(
            Document(
                id=f"j{i}", app_name=f"app{i}",
                current_config="m== http://x", strategy="continuous",
            )
        )


def test_inmemory_claims_partition_the_fleet():
    store = InMemoryStore()
    (a, b), _ = _mesh_pair(store)
    _fleet(store, 60)
    got_a = store.claim("w-a", 90.0, limit=100, claim_filter=a.claim_filter)
    got_b = store.claim("w-b", 90.0, limit=100, claim_filter=b.claim_filter)
    ids_a = {d.id for d in got_a}
    ids_b = {d.id for d in got_b}
    assert ids_a.isdisjoint(ids_b)
    assert len(ids_a) + len(ids_b) == 60
    # filtered docs were NOT parked in-progress: a second owner claim
    # of the other partition still finds them claimable
    assert store.claim("w-a", 90.0, limit=100, claim_filter=a.claim_filter) == []


def test_es_store_claim_filter_between_search_and_cas():
    """The ES path applies the partition filter client-side between the
    claimability search and the bulk CAS: only owned docs are CASed,
    foreign hits stay untouched (status unchanged, seq_no unchanged)."""
    from test_es_store import FakeES

    from foremast_tpu.jobs.store import ElasticsearchStore

    fake = FakeES()
    store = ElasticsearchStore("http://fake:9200", session=fake)
    store.ensure_index()
    _fleet(store, 20)
    (a, b), _ = _mesh_pair(store)
    got_a = store.claim("w-a", 90.0, limit=50, claim_filter=a.claim_filter)
    ids_a = {d.id for d in got_a}
    assert ids_a and len(ids_a) < 20
    for doc_id, rec in fake.docs.items():
        if not doc_id.startswith("j"):
            continue
        status = rec["_source"]["status"]
        if doc_id in ids_a:
            assert status == "preprocess_inprogress"
        else:
            assert status == "initial"
    got_b = store.claim("w-b", 90.0, limit=50, claim_filter=b.claim_filter)
    assert {d.id for d in got_b} == {
        f"j{i}" for i in range(20)
    } - ids_a


def test_es_store_list_app_finds_members_past_the_open_page():
    from test_es_store import FakeES

    from foremast_tpu.jobs.store import ElasticsearchStore

    fake = FakeES()
    store = ElasticsearchStore("http://fake:9200", session=fake)
    store.ensure_index()
    _fleet(store, 5)
    Membership(store, "w-es", lease_seconds=30.0).join()
    docs = store.list_app(MESH_APP)
    assert [d.id for d in docs] == ["mesh::w-es"]
    assert live_members(store)[0].worker_id == "w-es"


# ---------------------------------------------------------------------------
# routed ingest: receiver hints + pusher convergence
# ---------------------------------------------------------------------------


def test_receiver_redirect_hint_accepts_and_points_at_owner():
    from foremast_tpu.ingest import RingStore, start_ingest_server

    store = InMemoryStore()
    (a, b), _ = _mesh_pair(store)
    # advertise addresses so hints can carry them
    a.membership.ingest_address = "127.0.0.1:7001"
    b.membership.ingest_address = "127.0.0.1:7002"
    a.membership.renew(force=True)
    b.membership.renew(force=True)
    a.router.refresh(force=True)
    b.router.refresh(force=True)

    # find one app owned by b
    foreign_app = next(
        f"app{i}"
        for i in range(100)
        if not a.router.owns_doc(Document(id="x", app_name=f"app{i}"))
    )
    ring = RingStore(shards=1)
    srv, _ = start_ingest_server(
        0, ring, host="127.0.0.1", router=a.router
    )
    try:
        port = srv.server_address[1]
        body = json.dumps(
            {
                "timeseries": [
                    {
                        "alias": f'm{{app="{foreign_app}"}}',
                        "times": [60, 120],
                        "values": [1.0, 2.0],
                    }
                ]
            }
        ).encode()
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/api/v1/write", data=body, method="POST"
        )
        out = json.loads(urllib.request.urlopen(req).read())
        # accepted (lossless during convergence) AND hinted at the owner
        assert out["accepted_samples"] == 2
        assert out["redirects"] == {
            f'm{{app="{foreign_app}"}}': "127.0.0.1:7002"
        }
        assert ring.stats()["series"] == 1
        assert a.router.counters["redirect_hints"] == 1
    finally:
        srv.shutdown()


def test_routing_pusher_converges_in_one_cycle():
    from foremast_tpu.ingest import RingStore, start_ingest_server

    store = InMemoryStore()
    (a, b), _ = _mesh_pair(store)
    rings = {}
    servers = []
    try:
        for node in (a, b):
            ring = RingStore(shards=1)
            srv, _ = start_ingest_server(
                0, ring, host="127.0.0.1", router=node.router
            )
            addr = f"127.0.0.1:{srv.server_address[1]}"
            node.membership.ingest_address = addr
            node.membership.renew(force=True)
            rings[node.worker_id] = ring
            servers.append(srv)
        a.router.refresh(force=True)
        b.router.refresh(force=True)

        series = [
            (
                f'm{{app="app{i}"}}',
                [60, 120],
                np.asarray([1.0, 2.0], np.float32),
                None,
            )
            for i in range(40)
        ]
        pusher = RoutingPusher([a.membership.ingest_address])
        first = pusher.push_cycle(series)
        assert first["redirects"] > 0  # b's share got hints
        second = pusher.push_cycle(series)
        assert second["redirects"] == 0  # converged
        # every series now resides on its OWNER's ring
        for key, *_ in series:
            owner = a.router.owner_of_series(key)
            assert rings[owner].query(key, 0, 120, now=150.0)[0] == "hit"
    finally:
        for srv in servers:
            srv.shutdown()


def test_routing_pusher_retries_through_receiver_restart():
    """ISSUE 7 satellite: a receiver down for restart costs the pusher
    RETRIES (jittered backoff), not samples — the POST succeeds on a
    later attempt within the same cycle, and nothing is buffered or
    dropped."""
    from foremast_tpu.ingest import RingStore, start_ingest_server

    ring = RingStore(shards=1)
    srv, _ = start_ingest_server(0, ring, host="127.0.0.1")
    try:
        addr = f"127.0.0.1:{srv.server_address[1]}"
        slept = []
        pusher = RoutingPusher(
            [addr], retries=3, backoff_seconds=0.1,
            sleep=slept.append,  # injected: no real waiting in tests
        )
        orig_post = pusher._post
        fails = [2]  # receiver "restarting" for the first 2 attempts

        def flaky(address, entries):
            if fails[0] > 0:
                fails[0] -= 1
                raise OSError("connection refused (restarting)")
            return orig_post(address, entries)

        pusher._post = flaky
        out = pusher.push_cycle(
            [('m{app="a"}', [60, 120], [1.0, 2.0], None)]
        )
        assert out["accepted"] == 2 and out["errors"] == 0
        assert out["buffered"] == 0 and out["dropped"] == 0
        assert pusher.counters["retries"] == 2
        # backoff grew and was jittered within [0.5, 1.5] of the base
        assert len(slept) == 2
        assert 0.05 <= slept[0] <= 0.15 and 0.1 <= slept[1] <= 0.3
        assert ring.stats()["series"] == 1
    finally:
        srv.shutdown()


def test_routing_pusher_buffers_and_flushes_across_outage():
    """A receiver down PAST the retry budget buffers the cycle's series
    (no samples lost that the cap allows keeping) and re-sends them at
    the front of the next cycle once the receiver is back."""
    from foremast_tpu.ingest import RingStore, start_ingest_server

    ring = RingStore(shards=1)
    srv, _ = start_ingest_server(0, ring, host="127.0.0.1")
    try:
        addr = f"127.0.0.1:{srv.server_address[1]}"
        pusher = RoutingPusher(
            [addr], retries=1, backoff_seconds=0.0, sleep=lambda s: None
        )
        down = [True]
        orig_post = pusher._post

        def gated(address, entries):
            if down[0]:
                raise OSError("connection refused")
            return orig_post(address, entries)

        pusher._post = gated
        out = pusher.push_cycle(
            [('m{app="a"}', [60], [1.0], None),
             ('m{app="b"}', [60], [2.0], None)]
        )
        assert out["errors"] == 1 and out["buffered"] == 2
        assert ring.stats()["series"] == 0  # receiver never saw them
        down[0] = False  # receiver restarted
        out2 = pusher.push_cycle([('m{app="c"}', [60], [3.0], None)])
        assert out2["errors"] == 0 and out2["buffered"] == 0
        assert out2["accepted"] == 3  # backlog + the new series
        assert pusher.counters["resent_series"] == 2
        assert ring.stats()["series"] == 3
    finally:
        srv.shutdown()


def test_routing_pusher_rejected_batch_is_dropped_not_buffered():
    """An HTTP error status is the receiver ANSWERING (400 malformed /
    413 over cap) — a permanent verdict on the batch. It must not burn
    retries and must NOT be buffered: re-merging a poisoned batch into
    later cycles would get every subsequent healthy series rejected
    alongside it."""
    from foremast_tpu.ingest import RingStore, start_ingest_server

    ring = RingStore(shards=1)
    srv, _ = start_ingest_server(0, ring, host="127.0.0.1")
    try:
        addr = f"127.0.0.1:{srv.server_address[1]}"
        slept = []
        pusher = RoutingPusher(
            [addr], retries=3, backoff_seconds=0.1, sleep=slept.append
        )
        # values that json-encode fine but fail the receiver's codec
        # (times/values length mismatch) => a real 400 over the wire
        bad = [('m{app="bad"}', [60, 120], [1.0], None)]
        out = pusher.push_cycle(bad)
        assert out["errors"] == 1 and out["rejected"] == 1
        assert out["buffered"] == 0 and pusher.buffered == 0
        assert slept == []  # no retry backoff burned on a verdict
        assert pusher.counters["rejected_series"] == 1
        # the next cycle is clean: nothing poisoned it
        out2 = pusher.push_cycle([('m{app="ok"}', [60], [1.0], None)])
        assert out2["accepted"] == 1 and out2["errors"] == 0
        assert ring.stats()["series"] == 1
    finally:
        srv.shutdown()


def test_routing_pusher_transient_status_retries_like_transport():
    """429/5xx are a proxy answering for a pod that is down (or an
    overloaded receiver) — the same transient class PrometheusSource
    retries. They must retry with backoff and eventually land, never
    count as a permanent rejection."""
    import io
    import urllib.error

    from foremast_tpu.ingest import RingStore, start_ingest_server

    ring = RingStore(shards=1)
    srv, _ = start_ingest_server(0, ring, host="127.0.0.1")
    try:
        addr = f"127.0.0.1:{srv.server_address[1]}"
        slept = []
        pusher = RoutingPusher(
            [addr], retries=3, backoff_seconds=0.1, sleep=slept.append
        )
        orig_post = pusher._post
        fails = [2]

        def proxied(address, entries):
            if fails[0] > 0:
                fails[0] -= 1
                raise urllib.error.HTTPError(
                    f"http://{address}", 503, "pod restarting", None,
                    io.BytesIO(b""),
                )
            return orig_post(address, entries)

        pusher._post = proxied
        out = pusher.push_cycle([('m{app="a"}', [60], [1.0], None)])
        assert out["accepted"] == 1 and out["errors"] == 0
        assert out["rejected"] == 0 and out["buffered"] == 0
        assert pusher.counters["retries"] == 2 and len(slept) == 2
        assert pusher.counters["rejected_series"] == 0
    finally:
        srv.shutdown()


def test_routing_pusher_buffer_cap_drops_oldest_with_counter():
    """The outage buffer is byte-capped: past it the OLDEST series drop
    (newest samples are what restart recovery needs) and the drop is
    counted, never silent."""
    pusher = RoutingPusher(
        ["127.0.0.1:1"], retries=0, backoff_seconds=0.0,
        sleep=lambda s: None, buffer_bytes=300,
    )

    def dead(address, entries):
        raise OSError("connection refused")

    pusher._post = dead
    for i in range(6):
        pusher.push_cycle([(f'm{{app="a{i}"}}', [60, 120], [1.0, 2.0], None)])
    assert pusher.counters["dropped_series"] > 0
    assert pusher.buffered < 6
    kept = {key for _, key, _ in pusher._buffer}
    assert f'm{{app="a5"}}' in kept  # newest kept
    assert f'm{{app="a0"}}' not in kept  # oldest dropped
    assert (
        pusher.counters["buffered_series"]
        == pusher.counters["dropped_series"]
        + pusher.counters["resent_series"]
        + pusher.buffered
    )


# ---------------------------------------------------------------------------
# worker integration: debug state + observe port auto-increment
# ---------------------------------------------------------------------------


def test_worker_debug_state_has_mesh_section():
    from foremast_tpu.config import BrainConfig
    from foremast_tpu.jobs.worker import BrainWorker
    from foremast_tpu.metrics.source import StaticSource

    store = InMemoryStore()
    (a, _b), _ = _mesh_pair(store)
    worker = BrainWorker(
        store,
        StaticSource({}),
        config=BrainConfig(),
        worker_id="w-a",
        mesh=a,
    )
    worker.tick(now=1000.0)
    state = worker.debug_state()
    assert state["mesh"]["live_members"] == 2
    assert {m["worker_id"] for m in state["mesh"]["members"]} == {
        "w-a", "w-b",
    }
    assert state["mesh"]["claim_docs"]["owned"] >= 0
    worker.close()


def test_observe_server_auto_increments_busy_port():
    import socket
    import urllib.request as _rq

    from foremast_tpu.observe.spans import start_observe_server

    blocker = socket.socket()
    blocker.bind(("127.0.0.1", 0))
    blocker.listen(1)
    port = blocker.getsockname()[1]
    try:
        srv, _ = start_observe_server(
            port, state_fn=lambda: {"ok": 1}, host="127.0.0.1",
            max_port_tries=8,
        )
        try:
            actual = srv.server_address[1]
            assert port < actual <= port + 7
            state = json.loads(
                _rq.urlopen(
                    f"http://127.0.0.1:{actual}/debug/state"
                ).read()
            )
            assert state == {"ok": 1}
        finally:
            srv.shutdown()
    finally:
        blocker.close()


# ---------------------------------------------------------------------------
# planned elasticity (ISSUE 11): lifecycle states, two rings, handoff
# ---------------------------------------------------------------------------


def test_member_state_roundtrip_and_forward_compat():
    """`state` rides the member record; a record from a build that
    predates states (or carries a state this build does not know) reads
    as `active` — old readers keep claiming/routing to new members,
    degrading planned handoff to cold refit, never to wrong ownership."""
    from foremast_tpu.mesh import STATE_DRAINING
    from foremast_tpu.mesh.membership import MemberRecord

    rec = MemberRecord(
        worker_id="w-d", renewed_at=5.0, state=STATE_DRAINING
    )
    back = MemberRecord.from_payload(rec.to_payload())
    assert back.state == STATE_DRAINING
    # pre-states payload (no "state" field at all)
    legacy = json.loads(rec.to_payload())
    del legacy["state"]
    assert MemberRecord.from_payload(json.dumps(legacy)).state == "active"
    # a NEWER build's unknown state
    future = json.loads(rec.to_payload())
    future["state"] = "hibernating"
    assert MemberRecord.from_payload(json.dumps(future)).state == "active"


def _mesh_trio_with_states(store, states):
    """Three members with the given lifecycle states, all views fresh."""
    from foremast_tpu.mesh import MeshRouter, Membership

    t = [0.0]
    nodes = {}
    for wid, state in states.items():
        mem = Membership(
            store, wid, lease_seconds=30.0, clock=_clock(t), state=state
        )
        mem.join()
        nodes[wid] = MeshRouter(mem, refresh_seconds=0.0, clock=_clock(t))
    for router in nodes.values():
        router.refresh(force=True)
    return nodes, t


def test_two_rings_fence_joiner_and_retire_drainer():
    """The CLAIM ring (active+draining) answers 'who judges NOW'; the
    TARGET ring (active+joining) answers 'who owns after the change'.
    A joiner is fenced from claims but receives hints/moves; a drainer
    keeps judging but hints/moves point past it."""
    from foremast_tpu.mesh import STATE_ACTIVE, STATE_DRAINING, STATE_JOINING

    store = InMemoryStore()
    routers, _ = _mesh_trio_with_states(
        store,
        {"w-a": STATE_ACTIVE, "w-j": STATE_JOINING, "w-d": STATE_DRAINING},
    )
    ra = routers["w-a"]
    docs = [Document(id=f"j{i}", app_name=f"app{i}") for i in range(400)]
    claim_owners = {d.id: ra._ring.owner(doc_route_key(d)) for d in docs}
    target_owners = {
        d.id: ra._target_ring.owner(doc_route_key(d)) for d in docs
    }
    # the joiner judges NOTHING yet; the drainer judges to the end
    assert "w-j" not in claim_owners.values()
    assert "w-d" in claim_owners.values()
    # the post-change world has no drainer and a claiming joiner
    assert "w-d" not in target_owners.values()
    assert "w-j" in target_owners.values()
    # transfer_target: only keys this member holds NOW that the change
    # moves elsewhere, and never to itself
    moved = {
        d.app_name: ra.transfer_target(doc_route_key(d))
        for d in docs
        if ra.transfer_target(doc_route_key(d)) is not None
    }
    assert set(moved.values()) <= {"w-j"}  # w-a only hands to the joiner
    for app in moved:
        assert claim_owners[f"j{app[3:]}"] == "w-a"


def test_redirect_hint_routes_to_target_ring_owner():
    """During a planned change pushers are hinted at the POST-change
    owner, so the new member's ring is warm the moment it claims."""
    from foremast_tpu.mesh import STATE_ACTIVE, STATE_JOINING

    store = InMemoryStore()
    routers, _ = _mesh_trio_with_states(
        store, {"w-a": STATE_ACTIVE, "w-j": STATE_JOINING}
    )
    # give the joiner an advertised receiver
    ra = routers["w-a"]
    rj = routers["w-j"]
    rj.membership.ingest_address = "127.0.0.1:7777"
    rj.membership.renew(force=True)
    ra.refresh(force=True)
    hinted = 0
    for i in range(200):
        key = f'm{{app="app{i}"}}'
        hint = ra.redirect_hint(key)
        if hint is not None:
            assert hint == "127.0.0.1:7777"
            # the claim ring says w-a still owns it (joiner fenced)
            assert ra._target_ring.owner(f"app{i}") == "w-j"
            hinted += 1
    assert hinted > 0  # the joiner's share of the keyspace gets hints


def _framed(*recs):
    import io
    import pickle

    from foremast_tpu.ingest.snapshot import append_record

    buf = io.BytesIO()
    for r in recs:
        append_record(buf, pickle.dumps(r, protocol=pickle.HIGHEST_PROTOCOL))
    return buf.getvalue()


def _handoff_worker(store, wid, t, state="active", deadline=20.0):
    """One full elastic seat: ring + fit cache + handoff + receiver +
    MeshNode, everything on the injected clock `t`."""
    from foremast_tpu.ingest import RingStore, start_ingest_server
    from foremast_tpu.mesh import HandoffManager, MeshRouter, Membership
    from foremast_tpu.models.cache import ModelCache

    ring = RingStore(budget_bytes=1 << 20, shards=2)
    handoff = HandoffManager(
        ring_store=ring, deadline_seconds=deadline, clock=_clock(t),
        sleep=lambda s: None,
    )
    fits = ModelCache(256)
    handoff.register_caches({"fits": fits})
    mem = Membership(
        store, wid, lease_seconds=60.0, clock=_clock(t), state=state
    )
    router = MeshRouter(mem, refresh_seconds=0.0, clock=_clock(t))
    srv, _ = start_ingest_server(0, ring, host="127.0.0.1", handoff=handoff)
    mem.ingest_address = "127.0.0.1:%d" % srv.server_address[1]
    node = MeshNode(mem, router, ring_store=ring, handoff=handoff,
                    clock=_clock(t))
    node.fits = fits
    node.srv = srv
    node.ring = ring
    return node


def _seed_state(node, apps, t0=6000):
    """Resident series + a fit per app on `node`'s seat."""
    for app in apps:
        ts = np.arange(t0, t0 + 60 * 32, 60, np.int64)
        node.ring.push(
            f'm{{app="{app}"}}', ts, np.ones(32, np.float32),
            start=float(t0 - 600), record_lag=False,
        )
        node.fits.put(("ma", 0, f"{app}|m0|http://x"), {"app": app})


def test_join_fenced_handoff_moves_state():
    """Scale-up end to end over the real receiver endpoint: the joiner
    registers fenced, the active owner streams it the moving ring
    series + fits, the joiner activates with the state RESIDENT — the
    planned move costs zero cold refits by construction."""
    from foremast_tpu.mesh import STATE_ACTIVE, STATE_JOINING

    store = InMemoryStore()
    t = [100.0]
    w1 = _handoff_worker(store, "w1", t)
    try:
        w1.start()
        w1.on_tick()
        assert w1.state == STATE_ACTIVE
        apps = [f"app{i}" for i in range(24)]
        _seed_state(w1, apps)

        w2 = _handoff_worker(store, "w2", t)
        try:
            w2.start()
            assert w2.state == STATE_JOINING
            # fenced: w2 claims nothing while joining
            assert not any(
                w2.claim_filter(Document(id=f"j{a}", app_name=a))
                for a in apps
            )
            w1.on_tick()  # w1 notices the joiner and streams (async)
            assert w1.wait_handoff_streams(10)
            w2.on_tick()  # w2 sees w1's done marker and activates
            assert w2.state == STATE_ACTIVE
            sent = w1.handoff.counters_snapshot()
            got = w2.handoff.counters_snapshot()
            assert sent["send"]["ok"] == 1 and sent["send"]["failed"] == 0
            assert got["receive"]["ok"] >= 1
            # every app the new ring hands to w2 arrived with its state
            w1.router.refresh(force=True)
            moved = [a for a in apps if w2.router.owns_series(f'm{{app="{a}"}}')]
            assert moved, "the joiner owns nothing (grow the app count)"
            assert sent["series_sent"] == len(moved)
            assert sent["fits_sent"] == len(moved)
            for a in moved:
                key = f'm{{app="{a}"}}'
                assert w2.ring.query(key, 6000, 6000 + 60 * 31,
                                     now=t[0] + 6000 + 60 * 32)[0] == "hit"
                assert w2.fits.peek(("ma", 0, f"{a}|m0|http://x")) is not None
            # and w1 kept what it still owns
            kept = [a for a in apps if a not in moved]
            for a in kept[:5]:
                assert w1.fits.peek(("ma", 0, f"{a}|m0|http://x")) is not None
        finally:
            w2.srv.shutdown()
    finally:
        w1.srv.shutdown()


def test_drain_streams_state_then_leaves():
    """Planned scale-down: drain() publishes `draining`, streams every
    owned series + fit to the post-drain owners, then leaves — the
    survivors inherit a partition whose state is already resident."""
    from foremast_tpu.mesh import STATE_ACTIVE

    store = InMemoryStore()
    t = [100.0]
    w1 = _handoff_worker(store, "w1", t)
    w2 = _handoff_worker(store, "w2", t)
    try:
        w1.start()
        w2.start()  # fences behind w1; the (empty) handoff completes it
        w1.on_tick()
        assert w1.wait_handoff_streams(10)
        w2.on_tick()
        w1.router.refresh(force=True)
        assert w1.state == STATE_ACTIVE and w2.state == STATE_ACTIVE
        apps = [f"app{i}" for i in range(24)]
        w2_apps = [a for a in apps if w2.router.owns_series(f'm{{app="{a}"}}')]
        assert w2_apps, "w2 owns nothing (grow the app count)"
        _seed_state(w2, w2_apps)

        out = w2.drain()
        assert out["targets"] == {"w1": "ok"}
        # w2 is gone; w1's next refresh heals and it owns everything
        assert w1.router.refresh(force=True) is True
        for a in w2_apps:
            key = f'm{{app="{a}"}}'
            assert w1.router.owns_series(key)
            assert w1.ring.query(key, 6000, 6000 + 60 * 31,
                                 now=t[0] + 6000 + 60 * 32)[0] == "hit"
            assert w1.fits.peek(("ma", 0, f"{a}|m0|http://x")) is not None
        recv = w1.handoff.counters_snapshot()
        assert recv["series_received"] == len(w2_apps)
        assert recv["fits_received"] == len(w2_apps)
    finally:
        w1.srv.shutdown()
        w2.srv.shutdown()


def test_drain_enumerates_state_once_for_all_targets():
    """A drain with N survivors takes ONE pass over the resident ring
    (consistent per-shard copies are not free on the shutdown path) and
    buckets records by target — not one full enumeration per target."""
    from foremast_tpu.mesh import STATE_ACTIVE

    store = InMemoryStore()
    t = [100.0]
    workers = {w: _handoff_worker(store, w, t) for w in ("w1", "w2", "w3")}
    try:
        for w in workers.values():
            w.start()
        for _ in range(3):
            for w in workers.values():
                w.on_tick()
                assert w.wait_handoff_streams(10)
        assert all(w.state == STATE_ACTIVE for w in workers.values())
        w3 = workers["w3"]
        apps = [f"app{i}" for i in range(32)]
        w3_apps = [a for a in apps if w3.router.owns_series(f'm{{app="{a}"}}')]
        assert w3_apps, "w3 owns nothing (grow the app count)"
        _seed_state(w3, w3_apps)

        calls = [0]
        orig = w3.ring.shard_state

        def counting_shard_state(i):
            calls[0] += 1
            return orig(i)

        w3.ring.shard_state = counting_shard_state
        out = w3.drain()
        assert set(out["targets"]) == {"w1", "w2"}
        assert all(r == "ok" for r in out["targets"].values())
        assert calls[0] == w3.ring.shard_count  # one pass, not per-target
        # and the bucketing still lands every series on its new owner
        for w in ("w1", "w2"):
            workers[w].router.refresh(force=True)
        for a in w3_apps:
            key = f'm{{app="{a}"}}'
            owner = next(
                workers[w]
                for w in ("w1", "w2")
                if workers[w].router.owns_series(key)
            )
            assert owner.ring.query(key, 6000, 6000 + 60 * 31,
                                    now=t[0] + 6000 + 60 * 32)[0] == "hit"
    finally:
        for w in workers.values():
            w.srv.shutdown()


def test_stream_drain_keeps_the_seat_until_drain_leaves():
    """The cli streams the drain on a side thread while the loop keeps
    ticking: `stream_drain()` publishes `draining` and moves the state
    but the member KEEPS its claim-ring seat (it judges its partition
    to the end); the later `drain()` only leaves — it must not stream
    a second time."""
    from foremast_tpu.mesh import STATE_ACTIVE, STATE_DRAINING

    store = InMemoryStore()
    t = [100.0]
    w1 = _handoff_worker(store, "w1", t)
    w2 = _handoff_worker(store, "w2", t)
    try:
        w1.start()
        w2.start()
        w1.on_tick()
        assert w1.wait_handoff_streams(10)
        w2.on_tick()
        w1.router.refresh(force=True)
        assert w1.state == STATE_ACTIVE and w2.state == STATE_ACTIVE
        apps = [f"app{i}" for i in range(24)]
        w2_apps = [a for a in apps if w2.router.owns_series(f'm{{app="{a}"}}')]
        assert w2_apps, "w2 owns nothing (grow the app count)"
        _seed_state(w2, w2_apps)

        out = w2.stream_drain()
        assert out["targets"] == {"w1": "ok"}
        # state moved, but the drainer still holds its claim-ring seat:
        # peers see it (draining) and it still claims its partition
        assert w2.state == STATE_DRAINING
        w1.router.refresh(force=True)
        peers = {m.worker_id: m.state for m in w1.router.members()}
        assert peers.get("w2") == STATE_DRAINING
        assert all(
            w2.claim_filter(Document(id=f"j{a}", app_name=a))
            for a in w2_apps
        )
        recv_after_stream = w1.handoff.counters_snapshot()

        out2 = w2.drain()  # the finally-block half: leave, no re-stream
        assert out2 == out
        assert w1.router.refresh(force=True) is True
        assert [m.worker_id for m in w1.router.members()] == ["w1"]
        recv_after_drain = w1.handoff.counters_snapshot()
        assert recv_after_drain == recv_after_stream
        assert w2.handoff.counters_snapshot()["send"]["ok"] == 1
    finally:
        w1.srv.shutdown()
        w2.srv.shutdown()


def test_drain_streams_joiner_slice_too():
    """Scale-down overlapping scale-up: the target ring may hand part
    of the draining member's partition straight to a still-fenced
    joiner, and a draining member's tick no longer serves joiners —
    the drain stream itself must target the joiner, or that slice
    silently drops to a cold refit."""
    from foremast_tpu.mesh import STATE_ACTIVE, STATE_JOINING

    store = InMemoryStore()
    t = [100.0]
    w1 = _handoff_worker(store, "w1", t)
    w2 = _handoff_worker(store, "w2", t)
    w3 = None
    try:
        w1.start()
        w2.start()
        w1.on_tick()
        assert w1.wait_handoff_streams(10)
        w2.on_tick()
        assert w2.state == STATE_ACTIVE
        apps = [f"app{i}" for i in range(32)]
        w2_apps = [a for a in apps if w2.router.owns_series(f'm{{app="{a}"}}')]
        assert w2_apps, "w2 owns nothing (grow the app count)"
        _seed_state(w2, w2_apps)

        # w3 registers fenced at the same moment w2 drains
        w3 = _handoff_worker(store, "w3", t)
        w3.start()
        assert w3.state == STATE_JOINING
        w2.router.refresh(force=True)
        out = w2.drain()
        assert set(out["targets"]) == {"w1", "w3"}
        assert all(r == "ok" for r in out["targets"].values())
        # every one of w2's series is resident on its target-ring owner
        for w in (w1, w3):
            w.router.refresh(force=True)
        to_w3 = [
            a
            for a in w2_apps
            if w3.router.target_owner_of_route(a) == "w3"
        ]
        assert to_w3, "no slice moved w2 -> w3 (grow the app count)"
        for a in to_w3:
            key = f'm{{app="{a}"}}'
            assert w3.ring.query(key, 6000, 6000 + 60 * 31,
                                 now=t[0] + 6000 + 60 * 32)[0] == "hit"
        # the drainer's done marker counts toward w3's fence, and w3
        # activates owning its slice WARM once w1's stream lands too
        w1.on_tick()
        assert w1.wait_handoff_streams(10)
        w3.on_tick()
        assert w3.state == STATE_ACTIVE
    finally:
        w1.srv.shutdown()
        w2.srv.shutdown()
        if w3 is not None:
            w3.srv.shutdown()


def test_autoscale_cooldown_absorbs_transient_streaks():
    """Observations inside the cooldown window must not bank toward
    the next verdict: a scale-up's own rebalance transient breaches
    occupancy all through the cooldown, and a streak built from it
    would fire the moment the window expires — a verdict re-earns
    breach_ticks FRESH breaches after cooldown."""
    from foremast_tpu.mesh import AutoscaleConfig, AutoscaleDriver

    t = [0.0]
    d = AutoscaleDriver(
        AutoscaleConfig(breach_ticks=3, cooldown_seconds=60.0),
        clock=lambda: t[0],
    )
    assert d.observe(0.95, members=2) == "hold"
    assert d.observe(0.95, members=2) == "hold"
    assert d.observe(0.95, members=2) == "scale_up"
    # the handoff transient inflates occupancy for the whole cooldown
    for _ in range(10):
        t[0] += 5.0
        assert d.observe(0.95, members=3) == "hold"
    t[0] = 61.0  # cooldown expired
    assert d.observe(0.95, members=3) == "hold"  # streak 1, not 11
    assert d.observe(0.95, members=3) == "hold"
    assert d.observe(0.95, members=3) == "scale_up"  # genuinely sustained


def test_handoff_rejected_send_counted_once():
    """A hard-4xx transfer (version-mismatched receiver) is ONE
    outcome: `send{rejected}` — the abandon path must not also count
    it `send{failed}`, or dashboards summing outcomes see two
    transfers where one happened."""
    import urllib.error

    from foremast_tpu.mesh import HandoffManager
    from foremast_tpu.mesh.membership import MemberRecord

    h = HandoffManager(sleep=lambda s: None)

    def rejecting_post(address, body):
        raise urllib.error.HTTPError(
            f"http://{address}", 400, "version mismatch", {}, None
        )

    h._post = rejecting_post
    ok = h.send_to(
        MemberRecord(worker_id="w-j", ingest_address="old:1"), None, "w-s"
    )
    assert ok is False
    c = h.counters_snapshot()
    assert c["send"]["rejected"] == 1
    assert c["send"]["failed"] == 0 and c["send"]["ok"] == 0


def test_restart_retaking_live_seat_does_not_fence():
    """A SIGKILLed worker re-taking its persisted mesh seat (PR-7 warm
    restart: lease still live, ring never moved) must come up ACTIVE —
    fencing would evict it from the claim ring and hand its partition
    to peers COLD, exactly the refit wall the warm restart avoids."""
    from foremast_tpu.mesh import STATE_ACTIVE

    store = InMemoryStore()
    t = [100.0]
    w1 = _handoff_worker(store, "w1", t)
    w2 = _handoff_worker(store, "w2", t)
    try:
        w1.start()
        w2.start()
        # w2 "dies" (no leave — the lease stays live) and restarts with
        # the same persisted identity
        w2b = _handoff_worker(store, "w2", t)
        try:
            w2b.start()
            assert w2b.state == STATE_ACTIVE  # no fence, no refit wall
            assert not w2b.handoff.join_pending()
        finally:
            w2b.srv.shutdown()
    finally:
        w1.srv.shutdown()
        w2.srv.shutdown()


def test_bootstrap_solo_member_never_fences():
    """The first member of a fresh fleet has nobody to hand off from —
    it must come up claiming, not parked on a deadline."""
    from foremast_tpu.mesh import STATE_ACTIVE

    store = InMemoryStore()
    t = [100.0]
    w1 = _handoff_worker(store, "w1", t)
    try:
        w1.start()
        assert w1.state == STATE_ACTIVE
        assert not w1.handoff.join_pending()
    finally:
        w1.srv.shutdown()


# ---------------------------------------------------------------------------
# handoff torn-state matrix (ISSUE 11 satellite): every damage shape
# degrades per-record to cold refit, with counters — never a crash
# ---------------------------------------------------------------------------


def _receiver_manager():
    from foremast_tpu.ingest import RingStore
    from foremast_tpu.mesh import HandoffManager
    from foremast_tpu.models.cache import ModelCache

    ring = RingStore(budget_bytes=1 << 20, shards=1)
    h = HandoffManager(ring_store=ring, deadline_seconds=10.0)
    fits = ModelCache(64)
    h.register_caches({"fits": fits})
    return h, ring, fits


def _series_rec(app, t0=6000, n=16):
    ts = np.arange(t0, t0 + 60 * n, 60, np.int64)
    return (
        "series", f'm{{app="{app}"}}', ts, np.ones(n, np.float32),
        [[float(t0 - 600), float(t0 + 60 * (n - 1))]],
    )


def _fit_rec(app):
    return ("fit", "fits", ("ma", 0, f"{app}|m0|http://x"), {"app": app})


def test_handoff_truncated_stream_keeps_healthy_prefix():
    """A transfer torn mid-stream (sender died, connection cut) applies
    everything before the tear — PR-7 per-record semantics — counts it
    `torn`, and the rest cold-refits."""
    from foremast_tpu.mesh.handoff import HANDOFF_VERSION

    h, ring, fits = _receiver_manager()
    body = _framed(
        ("hello", HANDOFF_VERSION, "w-s"),
        _series_rec("appA"),
        _fit_rec("appA"),
        _series_rec("appB"),
        ("done", "w-s", 2, 1),
    )
    code, out = h.apply_transfer(body[:-10])  # tear inside the tail
    assert code == 200
    assert out["torn"] is True and out["done"] is False
    assert out["applied_series"] >= 1
    assert ring.query('m{app="appA"}', 6000, 6000 + 60 * 15,
                      now=7000.0)[0] == "hit"
    c = h.counters_snapshot()
    assert c["receive"]["torn"] == 1
    # the tear never marked the sender done: a fenced joiner would
    # keep waiting (then deadline out), not trust half a transfer
    assert "w-s" not in h.debug_state()["done_from"]


def test_handoff_version_mismatch_rejected_whole_batch():
    """A sender from a different build must not guess at our format:
    the whole batch is rejected with the permanent 400 verdict and
    NOTHING is applied."""
    h, ring, fits = _receiver_manager()
    code, out = h.apply_transfer(
        _framed(("hello", 99, "w-s"), _series_rec("appA"))
    )
    assert code == 400
    assert ring.stats()["series"] == 0
    assert h.counters_snapshot()["receive"]["rejected"] == 1


def test_handoff_garbage_and_empty_bodies_rejected():
    h, ring, fits = _receiver_manager()
    for raw in (b"", b"not-a-frame-at-all"):
        code, _ = h.apply_transfer(raw)
        assert code == 400
    assert h.counters_snapshot()["receive"]["rejected"] == 2
    assert ring.stats()["series"] == 0


def test_handoff_undecodable_record_keeps_prefix():
    """A frame whose crc passes but whose pickle is garbage (a sender
    bug, not wire damage) degrades exactly like a tear: prefix kept."""
    import io

    from foremast_tpu.ingest.snapshot import append_record
    from foremast_tpu.mesh.handoff import HANDOFF_VERSION

    buf = io.BytesIO()
    import pickle

    for rec in (("hello", HANDOFF_VERSION, "w-s"), _series_rec("appA")):
        append_record(
            buf, pickle.dumps(rec, protocol=pickle.HIGHEST_PROTOCOL)
        )
    append_record(buf, b"\x80\x04 this is not a pickle")
    h, ring, fits = _receiver_manager()
    code, out = h.apply_transfer(buf.getvalue())
    assert code == 200 and out["torn"] is True
    assert out["applied_series"] == 1


def test_handoff_duplicate_delivery_replays_clean():
    """Every record kind is idempotent (ring pushes merge last-write-
    wins, fit puts overwrite equal state, done markers are a set): a
    retried/duplicated batch changes nothing and is COUNTED."""
    from foremast_tpu.mesh.handoff import HANDOFF_VERSION

    h, ring, fits = _receiver_manager()
    body = _framed(
        ("hello", HANDOFF_VERSION, "w-s"),
        _series_rec("appA"),
        _fit_rec("appA"),
        ("done", "w-s", 1, 1),
    )
    code1, out1 = h.apply_transfer(body)
    stats1 = ring.stats()
    code2, out2 = h.apply_transfer(body)
    assert code1 == code2 == 200
    assert out2["done"] is True
    assert ring.stats()["series"] == stats1["series"] == 1
    assert ring.query('m{app="appA"}', 6000, 6000 + 60 * 15,
                      now=7000.0)[0] == "hit"
    c = h.counters_snapshot()
    assert c["receive"]["ok"] == 1 and c["receive"]["duplicate"] == 1
    assert h.debug_state()["done_from"] == ["w-s"]


def test_handoff_mid_transfer_receiver_death_degrades_sender():
    """The receiver dying mid-transfer (some batches landed, then
    connection refused) is a FAILED send: counted, abandoned after
    retries — the receiver cold-refits what never arrived, and the
    sender's tick is never wedged."""
    from foremast_tpu.ingest import RingStore
    from foremast_tpu.mesh import HandoffManager, STATE_JOINING
    from foremast_tpu.mesh.membership import MemberRecord
    from foremast_tpu.models.cache import ModelCache

    store = InMemoryStore()
    routers, _ = _mesh_trio_with_states(
        store, {"w-s": "active", "w-j": STATE_JOINING}
    )
    ring = RingStore(budget_bytes=1 << 20, shards=1)
    fits = ModelCache(64)
    # tiny batch size: every record is its own POST
    h = HandoffManager(
        ring_store=ring, batch_bytes=64, retries=1,
        sleep=lambda s: None,
    )
    h.register_caches({"fits": fits})
    for i in range(8):
        ts = np.arange(6000, 6000 + 60 * 16, 60, np.int64)
        ring.push(f'm{{app="app{i}"}}', ts, np.ones(16, np.float32),
                  record_lag=False)
    receiver, _, _2 = _receiver_manager()
    calls = [0]

    def dying_post(address, body):
        calls[0] += 1
        if calls[0] > 2:
            raise ConnectionRefusedError("receiver died mid-transfer")
        receiver.apply_transfer(body)

    h._post = dying_post
    ok = h.send_to(
        MemberRecord(worker_id="w-j", ingest_address="dead:1"),
        routers["w-s"], "w-s",
    )
    assert ok is False
    c = h.counters_snapshot()
    assert c["send"]["failed"] == 1 and c["send"]["ok"] == 0
    # the prefix LANDED on the receiver (per-record durability) and a
    # duplicate replay of those records would still be clean
    assert receiver.counters_snapshot()["series_received"] >= 1
    # no done marker: the joiner's deadline owns the degradation
    assert receiver.debug_state()["done_from"] == []


def test_join_deadline_degrades_to_cold_refit_not_deadlock():
    """A joiner whose senders never finish (blackholed / torn / dead
    receiver) activates at the deadline — missing state cold-refits
    through the normal rebalance path; the fence is never a wedge."""
    from foremast_tpu.mesh import HandoffManager

    t = [1000.0]
    h = HandoffManager(deadline_seconds=30.0, clock=_clock(t))
    h.begin_join({"w-a", "w-b"})
    assert h.join_pending()
    # w-a's done arrives, w-b's never does
    code, _ = h.apply_transfer(
        _framed(("hello", 1, "w-a"), ("done", "w-a", 0, 0))
    )
    assert code == 200
    assert h.join_ready({"w-a", "w-b"}) is False
    t[0] = 1029.0
    assert h.join_ready({"w-a", "w-b"}) is False
    t[0] = 1031.0  # deadline passed
    assert h.join_ready({"w-a", "w-b"}) is True
    assert not h.join_pending()


def test_join_discounts_dead_senders():
    """An expected sender that died or left mid-join is discounted —
    waiting on a ghost would turn its crash into our deadlock."""
    from foremast_tpu.mesh import HandoffManager

    t = [1000.0]
    h = HandoffManager(deadline_seconds=1e9, clock=_clock(t))
    h.begin_join({"w-a", "w-b"})
    h.apply_transfer(_framed(("hello", 1, "w-a"), ("done", "w-a", 0, 0)))
    # w-b crashed: it is no longer live-active
    assert h.join_ready({"w-a"}) is True


def test_evict_unowned_never_races_a_transfer():
    """Series applied by a transfer are protected from the rebalance
    eviction pass until the claim ring catches up — TTL-bounded so an
    abandoned change cannot pin foreign state forever."""
    from foremast_tpu.ingest import RingStore
    from foremast_tpu.mesh import HandoffManager

    t = [1000.0]
    ring = RingStore(budget_bytes=1 << 20, shards=1)
    h = HandoffManager(
        ring_store=ring, deadline_seconds=10.0, clock=_clock(t)
    )
    code, _ = h.apply_transfer(
        _framed(("hello", 1, "w-s"), _series_rec("appX"))
    )
    assert code == 200
    key = 'm{app="appX"}'
    assert h.is_protected(key)
    # an eviction pass that believes we own nothing must keep it
    assert ring.evict_unowned(lambda k: h.is_protected(k)) == 0
    assert ring.stats()["series"] == 1
    # past the TTL (2x deadline) the protection lapses
    t[0] = 1021.0
    assert not h.is_protected(key)
    assert ring.evict_unowned(lambda k: h.is_protected(k)) == 1


# ---------------------------------------------------------------------------
# RoutingPusher elasticity (ISSUE 11 satellite): hints from NEW members
# survive transient failures; dead seeds rotate
# ---------------------------------------------------------------------------


def test_routing_pusher_new_member_hint_survives_one_failure():
    """One-cycle convergence after scale-up, pinned: a hint pointing at
    a just-joined member must survive that member failing ONE cycle (a
    thundering herd at a receiver still warming up looks exactly like
    that) — the old forget-on-first-failure path bounced the series
    back through a seed and re-converged from scratch every time."""
    pusher = RoutingPusher(
        ["127.0.0.1:1"], retries=0, backoff_seconds=0.0,
        sleep=lambda s: None,
    )
    new_addr = "127.0.0.1:2"
    pusher._route['m{app="a"}'] = new_addr  # the scale-up hint
    flaky = [1]

    def post(address, entries):
        assert address == new_addr, f"bounced to {address}"
        if flaky[0]:
            flaky[0] -= 1
            raise OSError("connection refused (receiver warming up)")
        return {
            "accepted_samples": sum(len(e["times"]) for e in entries),
            "redirects": {},
        }

    pusher._post = lambda a, e: post(a, e)
    out1 = pusher.push_cycle([('m{app="a"}', [60], [1.0], None)])
    assert out1["errors"] == 1 and out1["buffered"] == 1
    # the route is STILL the new member's — one failure is not death
    assert pusher._route['m{app="a"}'] == new_addr
    out2 = pusher.push_cycle([])
    assert out2["accepted"] == 1 and out2["errors"] == 0


def test_routing_pusher_forgets_dead_address_and_rotates_seed():
    """FORGET_AFTER_FAILURES consecutive failed cycles mark an address
    dead: its routes are forgotten (address-scoped) and a dead fallback
    seed rotates — after a planned drain the departed member's address
    may BE a seed, and pinning fallback to it would blackhole
    re-convergence."""
    dead, live = "127.0.0.1:1", "127.0.0.1:2"
    pusher = RoutingPusher(
        [dead, live], retries=0, backoff_seconds=0.0, sleep=lambda s: None,
    )
    relearned = "127.0.0.1:3"

    def post(address, entries):
        if address == dead:
            raise OSError("connection refused (drained member)")
        return {
            "accepted_samples": sum(len(e["times"]) for e in entries),
            "redirects": {},
        }

    pusher._post = lambda a, e: post(a, e)
    # a stale learned route at the (drained) address, and a fresh hint
    # onto another member
    pusher._route['m{app="x"}'] = dead
    pusher._route['m{app="a"}'] = relearned
    out1 = pusher.push_cycle(
        [('m{app="x"}', [60], [1.0], None),
         ('m{app="a"}', [60], [1.0], None)]
    )  # strike 1 on the dead address; the relearned batch lands
    assert out1["errors"] == 1 and out1["accepted"] == 1
    assert pusher._route['m{app="x"}'] == dead  # one failure ≠ death
    out2 = pusher.push_cycle([])  # backlog → dead again: strike 2
    assert out2["errors"] == 1
    # dead for real now: its routes forgotten — but ONLY its own (the
    # route re-learned onto another member is never clobbered)
    assert 'm{app="x"}' not in pusher._route
    assert pusher._route['m{app="a"}'] == relearned
    # the fallback seed rotated past the dead address: routeless series
    # (and the backlog) land on the LIVE seed
    out3 = pusher.push_cycle([('m{app="b"}', [60], [2.0], None)])
    assert out3["errors"] == 0 and out3["accepted"] == 2


def test_mesh_collector_states_and_handoff_families_lint_clean():
    """`foremast_mesh_members{state}` + the two handoff families pass
    the metrics contract, with stable zeros when no handoff is wired."""
    from prometheus_client import CollectorRegistry

    from foremast_tpu.mesh import STATE_JOINING
    from foremast_tpu.mesh.node import MeshCollector
    from foremast_tpu.observe.metrics_lint import lint_registry

    store = InMemoryStore()
    t = [100.0]
    w1 = _handoff_worker(store, "w1", t)
    try:
        w1.start()
        w1.on_tick()
        # a fenced joiner appears in the member gauge by state
        w2 = _handoff_worker(store, "w2", t)
        try:
            w2.start()
            w1.router.refresh(force=True)
            reg = CollectorRegistry()
            reg.register(MeshCollector(w1))
            assert lint_registry(reg) == []
            assert reg.get_sample_value(
                "foremast_mesh_members", {"state": "active"}
            ) == 1.0
            assert reg.get_sample_value(
                "foremast_mesh_members", {"state": STATE_JOINING}
            ) == 1.0
            assert reg.get_sample_value(
                "foremast_mesh_members", {"state": "draining"}
            ) == 0.0
            # handoff families exist with zero'd label sets pre-transfer
            assert reg.get_sample_value(
                "foremast_handoff_state_total",
                {"kind": "series", "direction": "sent"},
            ) == 0.0
            assert reg.get_sample_value(
                "foremast_handoff_transfers_total",
                {"role": "send", "result": "failed"},
            ) == 0.0
        finally:
            w2.srv.shutdown()
    finally:
        w1.srv.shutdown()


def test_transfer_endpoint_404_without_handoff_plane():
    """A receiver with no handoff manager answers the transfer path
    with 404 — a pre-elasticity worker is a hard (permanent) verdict
    for a sender, not a retry loop."""
    from foremast_tpu.ingest import RingStore, start_ingest_server
    from foremast_tpu.ingest.receiver import TRANSFER_PATH

    ring = RingStore(shards=1)
    srv, _ = start_ingest_server(0, ring, host="127.0.0.1")
    try:
        port = srv.server_address[1]
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}{TRANSFER_PATH}", data=b"x",
            method="POST",
        )
        try:
            urllib.request.urlopen(req, timeout=5)
            raise AssertionError("transfer accepted with no handoff plane")
        except urllib.error.HTTPError as e:
            assert e.code == 404
            e.close()
    finally:
        srv.shutdown()


def test_mesh_debug_state_carries_lifecycle_and_handoff():
    store = InMemoryStore()
    t = [100.0]
    w1 = _handoff_worker(store, "w1", t)
    try:
        w1.start()
        w1.on_tick()
        state = w1.debug_state()
        assert state["state"] == "active"
        assert state["members"][0]["state"] == "active"
        assert state["handoff"]["join_pending"] is False
        assert state["handoff"]["deadline_seconds"] == 20.0
    finally:
        w1.srv.shutdown()


def test_simultaneous_joiners_restream_on_target_change():
    """A second joiner appearing mid-join reshapes the first one's
    target-ring share — already-served joiners are RE-queued for a
    fresh (idempotent) stream, so the reshaped delta moves instead of
    cold-refitting."""
    from foremast_tpu.mesh import HandoffManager, STATE_ACTIVE, STATE_JOINING
    from foremast_tpu.mesh.membership import MemberRecord

    h = HandoffManager(deadline_seconds=10.0)

    def rec(wid, state):
        return MemberRecord(
            worker_id=wid, state=state, ingest_address=f"{wid}:1"
        )

    view1 = [rec("w1", STATE_ACTIVE), rec("w3", STATE_JOINING)]
    h.note_members(view1)
    assert [m.worker_id for m in h.pending_joiners(view1, "w1")] == ["w3"]
    h.mark_served("w3")
    h.note_members(view1)  # unchanged view: stays served
    assert h.pending_joiners(view1, "w1") == []
    # w4 appears while w3 is STILL joining: w3's share moved — re-serve
    view2 = view1 + [rec("w4", STATE_JOINING)]
    h.note_members(view2)
    assert [m.worker_id for m in h.pending_joiners(view2, "w1")] == [
        "w3", "w4",
    ]
