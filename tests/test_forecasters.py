"""Forecaster correctness: masked batched JAX vs plain-numpy references."""

import numpy as np
import pytest

import jax.numpy as jnp

from foremast_tpu.ops import (
    BOUND_BOTH,
    BOUND_UPPER,
    compute_bounds,
    detect_anomalies,
    double_exponential,
    ewma,
    fit_holt_winters,
    holt_winters,
    masked_mean,
    masked_std,
    moving_average,
    moving_average_all,
)
from foremast_tpu.ops.forecasters import horizon

RNG = np.random.default_rng(7)


def _mk(values_list, n=64):
    b = len(values_list)
    v = np.zeros((b, n), dtype=np.float32)
    m = np.zeros((b, n), dtype=bool)
    for i, vals in enumerate(values_list):
        v[i, : len(vals)] = vals
        m[i, : len(vals)] = True
    return jnp.asarray(v), jnp.asarray(m)


def test_masked_moments():
    x = RNG.normal(3, 2, 40).astype(np.float32)
    v, m = _mk([x])
    assert float(masked_mean(v, m)[0]) == pytest.approx(float(np.mean(x)), rel=1e-5)
    assert float(masked_std(v, m)[0]) == pytest.approx(float(np.std(x)), rel=1e-4)


def test_moving_average_all_is_global_mean_model():
    x = RNG.normal(5, 1, 30).astype(np.float32)
    y = RNG.normal(-2, 4, 50).astype(np.float32)
    v, m = _mk([x, y])
    fc = moving_average_all(v, m)
    np.testing.assert_allclose(
        np.asarray(fc.level), [np.mean(x), np.mean(y)], rtol=1e-5
    )
    np.testing.assert_allclose(
        np.asarray(fc.scale), [np.std(x), np.std(y)], rtol=1e-4
    )
    # prediction is flat at the mean, horizon too
    h = horizon(fc, 5)
    np.testing.assert_allclose(np.asarray(h[0]), np.full(5, np.mean(x)), rtol=1e-5)


def test_ewma_matches_sequential_reference():
    x = RNG.normal(0, 1, 45).astype(np.float32)
    alpha = 0.3
    v, m = _mk([x])
    fc = ewma(v, m, alpha=alpha)
    # sequential reference
    level = x[0]
    preds = [x[0]]
    for t in range(1, len(x)):
        preds.append(level)
        level = alpha * x[t] + (1 - alpha) * level
    np.testing.assert_allclose(
        np.asarray(fc.pred)[0, : len(x)], np.asarray(preds), rtol=1e-4, atol=1e-5
    )
    assert float(fc.level[0]) == pytest.approx(float(level), rel=1e-4)


def test_ewma_mask_skips_gaps():
    """A masked-out gap must not perturb the level (carry-through)."""
    x = RNG.normal(0, 1, 30).astype(np.float32)
    v_full, m_full = _mk([x], n=40)
    # same points with a hole punched mid-way: indices 10..14 invalid
    v_gap = np.asarray(v_full).copy()
    m_gap = np.asarray(m_full).copy()
    v_gap[0, 10:15] = 1e6  # garbage where invalid
    m_gap[0, 10:15] = False
    fc_gap = ewma(jnp.asarray(v_gap), jnp.asarray(m_gap), alpha=0.3)
    # reference: run sequentially on the surviving points
    kept = [x[i] for i in range(30) if not (10 <= i < 15)]
    level = kept[0]
    for t in range(1, len(kept)):
        level = 0.3 * kept[t] + 0.7 * level
    assert float(fc_gap.level[0]) == pytest.approx(level, rel=1e-4)


def test_double_exponential_tracks_linear_trend():
    t = np.arange(60, dtype=np.float32)
    x = 2.0 + 0.5 * t
    v, m = _mk([x], n=60)
    fc = double_exponential(v, m, alpha=0.5, beta=0.3)
    # on a clean line, the trend estimate converges to the true slope
    assert float(fc.trend[0]) == pytest.approx(0.5, abs=0.05)
    h = horizon(fc, 4)
    expected = x[-1] + 0.5 * np.arange(1, 5)
    np.testing.assert_allclose(np.asarray(h)[0], expected, rtol=0.05)


def test_holt_winters_learns_seasonality():
    m_len = 12
    t = np.arange(m_len * 20, dtype=np.float32)
    season = np.sin(2 * np.pi * t / m_len).astype(np.float32)
    x = 10.0 + season + RNG.normal(0, 0.05, len(t)).astype(np.float32)
    v, m = _mk([x], n=len(t))
    fc = holt_winters(v, m, season_length=m_len, alpha=0.3, beta=0.01, gamma=0.3)
    # residual scale must be close to noise level, far below seasonal amplitude
    assert float(fc.scale[0]) < 0.25
    # horizon continues the seasonal pattern
    h = np.asarray(horizon(fc, m_len))[0]
    expected = 10.0 + np.sin(2 * np.pi * (t[-1] + 1 + np.arange(m_len)) / m_len)
    np.testing.assert_allclose(h, expected, atol=0.5)


def test_fit_holt_winters_beats_default_on_noisy_seasonal():
    m_len = 8
    t = np.arange(m_len * 16, dtype=np.float32)
    x = (5 + 3 * np.cos(2 * np.pi * t / m_len) + RNG.normal(0, 0.1, len(t))).astype(
        np.float32
    )
    v, m = _mk([x, x], n=len(t))
    fit = fit_holt_winters(v, m, season_length=m_len)
    assert float(fit.scale[0]) < 0.6
    # batch consistency: identical series pick identical params/results
    np.testing.assert_allclose(np.asarray(fit.pred)[0], np.asarray(fit.pred)[1])


def test_holt_winters_per_series_params_match_scalar_runs():
    """alpha/beta/gamma may be [B] arrays (one smoothing set per series);
    each row must equal the scalar-parameter run of that row alone."""
    m_len = 12
    rng = np.random.default_rng(42)
    lens = [m_len * 10, m_len * 7 + 5, m_len - 3]
    rows = []
    for i, n in enumerate(lens):
        t = np.arange(n, dtype=np.float32)
        rows.append(
            (3.0 + i + 2 * np.sin(2 * np.pi * t / m_len)
             + 0.01 * t + rng.normal(0, 0.1, n)).astype(np.float32)
        )
    v, m = _mk(rows, n=max(lens))
    params = [(0.3, 0.05, 0.1), (0.7, 0.1, 0.1), (0.1, 0.01, 0.05)]
    batched = holt_winters(
        v, m, m_len,
        jnp.asarray([p[0] for p in params], jnp.float32),
        jnp.asarray([p[1] for p in params], jnp.float32),
        jnp.asarray([p[2] for p in params], jnp.float32),
    )
    for i, (a, b_, g) in enumerate(params):
        solo = holt_winters(v[i : i + 1], m[i : i + 1], m_len, a, b_, g)
        np.testing.assert_allclose(
            np.asarray(batched.pred)[i] * np.asarray(m)[i],
            np.asarray(solo.pred)[0] * np.asarray(m)[i],
            rtol=2e-4, atol=2e-4,
        )
        np.testing.assert_allclose(
            np.asarray(batched.level)[i], np.asarray(solo.level)[0],
            rtol=2e-4, atol=2e-4,
        )
        np.testing.assert_allclose(
            np.asarray(batched.season)[i], np.asarray(solo.season)[0],
            rtol=2e-4, atol=2e-4,
        )


def test_moving_average_rolling_window():
    x = np.arange(20, dtype=np.float32)
    v, m = _mk([x], n=20)
    fc = moving_average(v, m, window=4)
    # at t=10: mean of x[6..9] = 7.5
    assert float(fc.pred[0, 10]) == pytest.approx(7.5)
    # terminal level: mean of last 4 points
    assert float(fc.level[0]) == pytest.approx(np.mean(x[-4:]))


def test_bounds_and_detection_golden_trace(demo_traces):
    """moving_average_all on the normal trace must flag the 40.134/40.466
    spikes in the spike trace; at the cpu/memory-class threshold (5.0,
    reference `foremast-brain.yaml:56-73`) the normal trace stays clean.
    At the global default threshold 2.0 the spikes must still be flagged."""
    _, normal = demo_traces["normal"]
    _, spike = demo_traces["spike"]
    hist_v, hist_m = _mk([normal, normal], n=48)
    cur_v, cur_m = _mk([normal, spike], n=48)
    fc = moving_average_all(hist_v, hist_m)
    pred = jnp.broadcast_to(fc.level[:, None], cur_v.shape)
    upper, lower = compute_bounds(pred, fc.scale, threshold=5.0, min_lower_bound=0.0)
    flags = detect_anomalies(cur_v, cur_m, upper, lower, bound=BOUND_UPPER)
    n_anoms = np.asarray(jnp.sum(flags, axis=-1))
    assert n_anoms[0] == 0, "normal trace must be clean at threshold 5"
    assert n_anoms[1] == 2, "exactly the two 40.x spikes must be flagged"
    flagged_vals = np.asarray(cur_v)[1][np.asarray(flags)[1]]
    assert np.all(flagged_vals > 10)
    # global default threshold also catches the spikes
    upper2, lower2 = compute_bounds(pred, fc.scale, threshold=2.0)
    flags2 = detect_anomalies(cur_v, cur_m, upper2, lower2, bound=BOUND_UPPER)
    assert np.asarray(jnp.sum(flags2, axis=-1))[1] >= 2


def test_bound_selector_lower_and_both():
    hist = RNG.normal(10, 1, 40).astype(np.float32)
    cur = np.array([10.0, 2.0, 18.0], dtype=np.float32)
    hv, hm = _mk([hist], n=40)
    cv, cm = _mk([cur], n=40)
    fc = moving_average_all(hv, hm)
    pred = jnp.broadcast_to(fc.level[:, None], cv.shape)
    upper, lower = compute_bounds(pred, fc.scale, threshold=3.0)
    both = detect_anomalies(cv, cm, upper, lower, bound=BOUND_BOTH)
    up_only = detect_anomalies(cv, cm, upper, lower, bound=BOUND_UPPER)
    assert np.asarray(both)[0, :3].tolist() == [False, True, True]
    assert np.asarray(up_only)[0, :3].tolist() == [False, False, True]


def test_min_lower_bound_floors_lower():
    hist = RNG.normal(0.2, 0.5, 40).astype(np.float32)
    hv, hm = _mk([hist], n=40)
    fc = moving_average_all(hv, hm)
    pred = jnp.broadcast_to(fc.level[:, None], hv.shape)
    _, lower = compute_bounds(pred, fc.scale, threshold=5.0, min_lower_bound=0.0)
    assert float(jnp.min(lower)) >= 0.0


def test_holt_winters_horizon_phase_ignores_bucket_padding():
    """A 288-valid-point series packed into a 512 bucket must forecast the
    SAME seasonal continuation as the exact-length series: the horizon
    phase comes from the valid count, not the padded array length
    (regression: 512 % 24 = 8 used to shift the cycle)."""
    m_len = 24
    t = np.arange(288, dtype=np.float32)
    x = (5 + 2 * np.sin(2 * np.pi * t / m_len)).astype(np.float32)
    exact = holt_winters(*_mk([x], n=288), season_length=m_len)
    padded = holt_winters(*_mk([x], n=512), season_length=m_len)
    h_exact = np.asarray(horizon(exact, m_len))[0]
    h_padded = np.asarray(horizon(padded, m_len))[0]
    np.testing.assert_allclose(h_padded, h_exact, rtol=1e-5, atol=1e-5)
    # continuation actually follows the sine
    expected = 5 + 2 * np.sin(2 * np.pi * (288 + np.arange(m_len)) / m_len)
    np.testing.assert_allclose(h_padded, expected, atol=0.3)


def test_seasonal_horizon_phase_ignores_bucket_padding():
    from foremast_tpu.models.seasonal import fit_seasonal

    period = 24
    t = np.arange(288, dtype=np.float32)
    x = (5 + 2 * np.sin(2 * np.pi * t / period)).astype(np.float32)
    exact = fit_seasonal(*_mk([x], n=288), period=period, order=2)
    padded = fit_seasonal(*_mk([x], n=512), period=period, order=2)
    np.testing.assert_allclose(
        np.asarray(horizon(padded, period))[0],
        np.asarray(horizon(exact, period))[0],
        rtol=1e-3, atol=1e-3,
    )


def test_auto_univariate_routes_by_structure():
    """Flat series keep the global-mean model; seasonal and trending
    series route to the fitted Holt-Winters (VERDICT r1 item 6)."""
    from foremast_tpu.ops import fit_auto_univariate

    rng = np.random.default_rng(9)
    n = 24 * 14
    t = np.arange(n, dtype=np.float32)
    flat = 1.0 + rng.normal(0, 0.05, n).astype(np.float32)
    seasonal = (1 + 0.5 * np.sin(2 * np.pi * t / 24)
                + rng.normal(0, 0.05, n)).astype(np.float32)
    trend = (1 + 0.002 * t + rng.normal(0, 0.05, n)).astype(np.float32)
    v, m = _mk([flat, seasonal, trend], n=n)
    fc = fit_auto_univariate(v, m)
    # flat row == the moving_average_all model: zero trend+season, level=mean
    assert float(fc.trend[0]) == 0.0
    assert float(np.abs(np.asarray(fc.season)[0]).max()) == 0.0
    assert float(fc.level[0]) == pytest.approx(float(flat.mean()), rel=1e-4)
    # seasonal row carries a real seasonal buffer
    assert float(np.abs(np.asarray(fc.season)[1]).max()) > 0.2
    # trend row carries the slope
    assert float(fc.trend[2]) == pytest.approx(0.002, rel=0.5)
    # scales: structured rows near the noise level, flat row too
    assert all(float(s) < 0.12 for s in np.asarray(fc.scale))


def test_moving_average_all_robust_to_padding_and_empty():
    """Single-pass moments must not read padding: an extreme value in a
    MASKED slot 0 ('padding arbitrary where invalid') cannot poison the
    moments, and a zero-length time axis is unmeasurable, not a crash."""
    v = np.full((1, 8), 1.0, np.float32)
    v[0, 0] = 3e20  # masked-out garbage
    m = np.ones((1, 8), bool)
    m[0, 0] = False
    fc = moving_average_all(jnp.asarray(v), jnp.asarray(m))
    assert float(fc.level[0]) == pytest.approx(1.0)
    assert float(fc.scale[0]) == pytest.approx(0.0, abs=1e-5)
    empty = moving_average_all(jnp.zeros((2, 0)), jnp.zeros((2, 0), bool))
    assert empty.pred.shape == (2, 0)
    assert np.all(np.asarray(empty.scale) == 0.0)
    # all-invalid rows gate to zeros even next to huge garbage
    fc2 = moving_average_all(jnp.asarray(v), jnp.zeros((1, 8), bool))
    assert float(fc2.level[0]) == 0.0 and float(fc2.scale[0]) == 0.0


def test_holt_winters_rolled_matches_blocked_body():
    """The long-season rolled scan and the small-m unrolled-phases scan
    are the same recurrence: forcing the rolled body at m=24 reproduces
    `holt_winters` (which picks the blocked body there) bit-for-near-bit,
    including ragged tails and interior gaps."""
    from foremast_tpu.ops.forecasters import (
        _hw_rolled,
        holt_winters,
        masked_mean,
    )

    rng = np.random.default_rng(11)
    b, n, m = 8, 400, 24
    t = np.arange(n, dtype=np.float32)
    v = (5 + 2 * np.sin(2 * np.pi * t / m)[None, :]
         + rng.normal(0, 0.3, (b, n))).astype(np.float32)
    mk = np.ones((b, n), bool)
    mk[2, 350:] = False  # ragged tail
    mk[4, 100:140] = False  # interior gap
    vj, mj = jnp.asarray(v), jnp.asarray(mk)

    ref = holt_winters(vj, mj, m)  # m=24 <= _HW_UNROLL_MAX: blocked body
    fsm = mj & (jnp.arange(n)[None, :] < m)
    lvl = masked_mean(vj, fsm)
    seas0 = jnp.where(fsm[:, :m], vj[:, :m] - lvl[:, None], 0.0)
    a = jnp.float32(0.3)
    pred, level, trend, season = _hw_rolled(
        vj, mj, m, a, jnp.float32(0.05), jnp.float32(0.1), lvl, seas0
    )
    np.testing.assert_allclose(np.asarray(ref.pred), np.asarray(pred), atol=1e-4)
    np.testing.assert_allclose(np.asarray(ref.level), np.asarray(level), atol=1e-4)
    np.testing.assert_allclose(np.asarray(ref.season), np.asarray(season), atol=1e-4)


def test_holt_winters_long_season_compiles_and_tracks_daily_cycle():
    """m=1440 (daily at the 60 s step) takes the rolled path: the program
    must stay small enough to compile fast and the horizon must continue
    the cycle at the right phase."""
    rng = np.random.default_rng(12)
    b, n, m = 4, 4320, 1440  # 3 days
    t = np.arange(n, dtype=np.float64)
    cycle = 10 + 4 * np.sin(2 * np.pi * t / m)
    v = (cycle[None, :] + rng.normal(0, 0.2, (b, n))).astype(np.float32)
    fc = holt_winters(jnp.asarray(v), jnp.ones((b, n), bool), m)
    assert fc.season.shape == (b, m)
    h = np.asarray(horizon(fc, 120))
    expect = 10 + 4 * np.sin(2 * np.pi * (n + np.arange(120)) / m)
    # Per-phase HW state sees each phase only ~3x here, so its estimates
    # carry sampling noise (why the auto screen prefers the pooled
    # Fourier fit for long cycles) — but the PHASE must be right: error
    # stays well under the 4.0 amplitude a phase-blind model would eat.
    assert np.abs(h[0] - expect).max() < 2.0


def test_auto_univariate_daily_cycle_routes_to_pooled_seasonal():
    """At m=1440 the 7-day history holds only 7 cycles, so per-phase HW
    state is noisy; the auto screen must still produce a model whose
    horizon tracks the cycle (the pooled Fourier fit), and histories
    shorter than two cycles must keep the global-mean model outright."""
    from foremast_tpu.ops import fit_auto_univariate

    rng = np.random.default_rng(13)
    b, n, m = 2, 10_080, 1440
    t = np.arange(n, dtype=np.float64)
    cycle = 50 + 20 * np.sin(2 * np.pi * t / m)
    v = np.stack([
        cycle + rng.normal(0, 1.0, n),
        30 + rng.normal(0, 1.0, n),  # flat
    ]).astype(np.float32)
    fc = fit_auto_univariate(jnp.asarray(v), jnp.ones((b, n), bool), season_length=m)
    h = np.asarray(horizon(fc, 200))
    expect = 50 + 20 * np.sin(2 * np.pi * (n + np.arange(200)) / m)
    assert np.abs(h[0] - expect).max() < 2.0  # seasonal row tracks the cycle
    assert float(np.ptp(h[1])) < 0.1  # flat row keeps the mean model
    assert float(fc.scale[0]) < 1.5  # band ~ noise, not the 20-amp cycle

    # <2 cycles: unidentifiable -> global mean, [B, 1] zero season buffer
    short = fit_auto_univariate(
        jnp.asarray(v[:, : 2 * m - 1]), jnp.ones((b, 2 * m - 1), bool), season_length=m
    )
    assert short.season.shape == (b, 1)
    assert float(np.abs(np.asarray(short.trend)).max()) == 0.0


def test_fit_guards_apply_per_series_under_bucket_padding():
    """A series with <2 real cycles riding a long padded bucket must keep
    the global-mean model even though the batch's STATIC length passes
    the 2-cycle rule (code-review r3: bucket padding defeated the static
    guard and the grid fit memorized the partial cycle to a ~zero band)."""
    from foremast_tpu.models.seasonal import fit_seasonal

    rng = np.random.default_rng(21)
    m_len, n = 24, 256  # bucket: 256 >= 2*24 passes the static guard
    t = np.arange(n, dtype=np.float32)
    full = (5 + 2 * np.sin(2 * np.pi * t / m_len)
            + rng.normal(0, 0.1, n)).astype(np.float32)
    short = full.copy()  # identical signal, but only 40 valid points
    v = np.stack([full, short])
    mk = np.ones((2, n), bool)
    mk[1, 40:] = False  # 40 < 2*24: unidentifiable for THIS series

    for fit in (
        lambda a, b: fit_holt_winters(a, b, m_len),
        lambda a, b: fit_seasonal(a, b, period=m_len),
    ):
        fc = fit(jnp.asarray(v), jnp.asarray(mk))
        assert float(np.abs(np.asarray(fc.season)[0]).max()) > 0.5  # full row: real cycle
        assert float(np.abs(np.asarray(fc.season)[1]).max()) == 0.0  # short row: mean model
        assert float(fc.trend[1]) == 0.0
        mu = full[:40].mean()
        assert float(fc.level[1]) == pytest.approx(float(mu), rel=1e-3)
        # the short row's band must be the honest historical std, not a
        # memorized ~zero residual
        assert float(fc.scale[1]) == pytest.approx(float(full[:40].std()), rel=0.05)


def test_phase_means_pools_sharp_cycle_and_guards():
    """The pooled phase-means fit recovers ARBITRARY cycle shapes (a
    cron-style burst no low-order Fourier basis can express), applies
    the leave-one-out scale correction, and keeps the mean model below
    two cycles like every seasonal fit."""
    from foremast_tpu.ops import fit_phase_means

    rng = np.random.default_rng(23)
    b, n, m = 4, 4320, 1440  # 3 cycles
    t = np.arange(n)
    burst = 5.0 * ((t % m >= 100) & (t % m < 110))
    v = (10 + burst[None] + 0.002 * t[None]
         + rng.normal(0, 0.1, (b, n))).astype(np.float32)
    fc = fit_phase_means(jnp.asarray(v), jnp.ones((b, n), bool), m)
    h = np.asarray(horizon(fc, m))
    tt = n + np.arange(m)
    expect = 10 + 0.002 * tt + 5.0 * ((tt % m >= 100) & (tt % m < 110))
    assert np.abs(h[0] - expect).max() < 0.5  # burst carried at phase
    # LOO-corrected scale ~ noise * k/(k-1) at k=3, not deflated below it
    assert 0.08 < float(fc.scale[0]) < 0.25
    assert float(fc.trend[0]) == pytest.approx(0.002, rel=0.2)

    short = fit_phase_means(
        jnp.asarray(v[:, : 2 * m - 1]), jnp.ones((b, 2 * m - 1), bool), m
    )
    assert short.season.shape == (b, 1)  # mean-model fallback


def test_auto_z_gate_forces_phase_means_over_min_sse():
    """A series with BOTH a level shift (which hands the changepoint+
    Fourier fit the lower SSE) and a sparse cron burst must still route
    to the phase-means candidate: the z-gate exists because a phase-blind
    band false-flags every burst occurrence, so min-SSE must not override
    it (ADVICE r3 item 2)."""
    from foremast_tpu.ops import fit_auto_univariate

    rng = np.random.default_rng(31)
    n, m = 10_080, 1440
    t = np.arange(n)
    burst = 5.0 * ((t % m >= 100) & (t % m < 110))
    shift = 3.0 * (t >= n // 2)  # favors the hinge-knot seasonal fit's SSE
    v = (10 + shift + burst + rng.normal(0, 0.1, n)).astype(np.float32)[None]
    fc = fit_auto_univariate(jnp.asarray(v), jnp.ones((1, n), bool), season_length=m)
    h = np.asarray(horizon(fc, m))[0]
    ph = n % m  # horizon starts at this phase
    idx = (np.arange(m) + ph) % m
    in_burst = (idx >= 100) & (idx < 110)
    # the burst must be carried at its phase: a low-order Fourier fit
    # (or the mean model) would predict the baseline there and miss by ~5
    lift = h[in_burst].mean() - h[~in_burst].mean()
    assert lift > 3.0
