// foremast-tpu native runtime: window packing (the data-loader hot path).
//
// The engine's host side packs thousands of ragged (times, values) series
// per tick into fixed-shape [B, T] batches (mask-padded) before device
// transfer (SURVEY.md section 7.4: "host-side dispatcher that packs pending
// jobs into fixed-shape batches"). The reference has no native code (its
// brain is Python on a 100m-CPU sliver, foremast-brain.yaml:82-86); at this
// framework's throughput target (100k windows/sec) the per-series Python
// loop becomes the bottleneck, so the inner scatter runs here instead.
//
// ABI: plain C, consumed via ctypes (foremast_tpu/native.py). Inputs are
// per-series pointer tables plus a lengths array, so Python makes exactly
// one call per batch regardless of B and no staging copy is needed.

#include <algorithm>
#include <cstdint>
#include <cstring>
#include <thread>
#include <vector>

extern "C" {

// Pack ragged series into [B, T] values/times/mask.
//  values: float32*[B]   per-series value buffers (no staging copy —
//                        Python passes raw numpy pointers)
//  times:  int64*[B]     per-series timestamp buffers
//  lens:   int64[B]      per-series lengths
//  B, T:   batch and window length
//  out_values: float32[B*T]   caller-zeroed (np.zeros) — only the valid
//  out_times:  int32[B*T]     prefix is written here, so OS zero pages
//  out_mask:   uint8[B*T]     cover the padding without ever faulting the
//                             tail in (int32 times: f32 ulp at current
//                             epochs is 128 s — see windows.py)
// Series longer than T are truncated to their first T samples (same
// semantics as MetricWindows.from_ragged).
void fp_pack_windows(const float* const* values, const int64_t* const* times,
                     const int64_t* lens, int64_t B, int64_t T,
                     float* out_values, int32_t* out_times,
                     uint8_t* out_mask) {
  auto pack_range = [&](int64_t lo, int64_t hi) {
    for (int64_t i = lo; i < hi; ++i) {
      const int64_t n = std::min<int64_t>(lens[i], T);
      float* ov = out_values + i * T;
      int32_t* ot = out_times + i * T;
      uint8_t* om = out_mask + i * T;
      std::memcpy(ov, values[i], sizeof(float) * n);
      const int64_t* ts = times[i];
      for (int64_t j = 0; j < n; ++j) ot[j] = static_cast<int32_t>(ts[j]);
      std::memset(om, 1, n);
    }
  };

  // Parallelize across series for large batches; the per-series work is a
  // short memcpy, so only spin up threads when there is real volume.
  const int64_t kParallelThreshold = 1024;
  unsigned hw = std::thread::hardware_concurrency();
  if (B < kParallelThreshold || hw < 2) {
    pack_range(0, B);
    return;
  }
  const int64_t n_threads = std::min<int64_t>(hw, 8);
  std::vector<std::thread> workers;
  workers.reserve(n_threads);
  const int64_t chunk = (B + n_threads - 1) / n_threads;
  for (int64_t t = 0; t < n_threads; ++t) {
    const int64_t lo = t * chunk;
    const int64_t hi = std::min(lo + chunk, B);
    if (lo >= hi) break;
    workers.emplace_back(pack_range, lo, hi);
  }
  for (auto& w : workers) w.join();
}

// Encode anomaly (time, value) pairs for one window into the reference's
// flat [t1, v1, t2, v2, ...] wire form (Barrelman.go:605-615).
// values are double so float64 task inputs keep full precision (the
// Python fallback emits float64 — the wire forms must match bit-for-bit).
// Returns the number of pairs written; out must hold 2*n doubles.
int64_t fp_anomaly_pairs(const uint8_t* flags, const int64_t* times,
                         const double* values, int64_t n, double* out) {
  int64_t k = 0;
  for (int64_t i = 0; i < n; ++i) {
    if (flags[i]) {
      out[2 * k] = static_cast<double>(times[i]);
      out[2 * k + 1] = values[i];
      ++k;
    }
  }
  return k;
}

// ABI version tag so the Python side can detect stale builds.
int32_t fp_abi_version() { return 4; }

}  // extern "C"
