# Developer entry points (role parity with the reference's per-component
# Makefiles: test / build / docker-build).

PY ?= python

test:
	$(PY) -m pytest tests/ -q

bench:
	$(PY) bench.py

bench-suite:
	$(PY) -m benchmarks.suite

native:
	$(MAKE) -C native

deploy-render:
	$(PY) -m foremast_tpu.deploy deploy

metrics-lint:
	$(PY) -m foremast_tpu.observe.metrics_lint

docker-build:
	docker build -t foremast/foremast-tpu:0.1.0 .

clean:
	$(MAKE) -C native clean
	find . -name __pycache__ -type d -prune -exec rm -rf {} +

.PHONY: test bench bench-suite native deploy-render metrics-lint docker-build clean
