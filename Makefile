# Developer entry points (role parity with the reference's per-component
# Makefiles: test / build / docker-build).

PY ?= python

test:
	$(PY) -m pytest tests/ -q

# fast tier-1 slice (skips @slow): the test half of `make ci`
test-fast:
	$(PY) -m pytest tests/ -q -m 'not slow'

# the whole gate in one command: every static contract, then the fast
# tier-1 tests (docs/static-analysis.md, CONTRIBUTING.md)
ci: check test-fast

bench:
	$(PY) bench.py

bench-suite:
	$(PY) -m benchmarks.suite

bench-pipeline:
	$(PY) -m benchmarks.pipeline_bench

# mixed-fleet suite (ISSUE 4 + ISSUE 14): the 16,384-service / 15%-joint
# fleet, the canary-heavy fleet (50% baseline-carrying docs — columnar
# canary bucket vs the object-path baseline, >= 3x and >= 12.5k w/s/chip
# asserted in-run, statuses byte-identical across arms), the
# strategy x regime scenario-matrix F1 sweep (floors asserted in-run),
# and pusher fan-in shapes over the real ingest receiver
bench-mixed:
	$(PY) -m benchmarks.mixed_bench

# watch-plane scale: 10k DeploymentMonitors on InMemoryKube
bench-plane:
	$(PY) -m benchmarks.plane_bench

# push-based ingest plane (ISSUE 5): warm RingSource vs
# PrometheusSource-over-localhost on a 4k-doc fleet
bench-ingest:
	$(PY) -m benchmarks.ingest_bench

# worker-mesh scale-out (ISSUE 6): 1 vs 4 REAL worker processes
# sharding a 64k-service fleet over one HTTP store, with in-run
# exactly-once + kill/rebalance assertions
bench-scaleout:
	$(PY) -m benchmarks.scaleout_bench

# cold-start benchmark (ISSUE 10): ring-resident cold fits vs the
# pull-path baseline at the 16k daily-season shape, 10%-churn tick,
# short-history newcomer admission + background refinement — with
# in-run asserts: zero HTTP when the ring covers, byte-identical
# statuses vs pull, band parity, and (at full shape) the round-12
# bars (cold <= 120 s, churn <= 8 s, first verdict <= 10 s)
bench-cold:
	$(PY) -m benchmarks.cold_bench

# durable-restart crash harness (ISSUE 7): SIGKILL a worker mid-tick,
# restart it against the same FOREMAST_SNAPSHOT_DIR state, and assert
# in-run: next tick >= 90% fast-path, ZERO fallback fetches, no lost
# or duplicated verdicts (single-worker and 3-worker-mesh variants)
bench-restart:
	$(PY) -m benchmarks.restart_bench

# chaos soak (ISSUE 9): 3-worker mesh + receivers + fault-injected
# store/Prometheus under a scheduled FaultPlan (store brownout, prom
# blackhole, pusher flood, skewed clocks, worker crash) with in-run
# asserts: zero lost/duplicated verdicts, breakers re-close, recovery
# <= 2 ticks per fault, lock witness clean, memory bounded
bench-chaos:
	$(PY) -m benchmarks.chaos_bench

# reactive plane (ISSUE 12): event-driven detection latency — deploy
# PATCH -> first verdict through the fake kube server's real watch
# stream (<= 1 s bar), anomaly POST -> completed_unhealth through the
# real ingest receiver at the 16k fleet (p99 <= 2 s bar, pinned in
# BENCHMARKS.md), micro-vs-full tick-path status parity asserted in-run
bench-latency:
	$(PY) -m benchmarks.latency_bench

# elastic mesh (ISSUE 11): 2 -> 4 -> 2 workers under continuous load
# with in-run asserts: zero lost/duplicated verdicts, planned handoff
# inside 2 ticks with ZERO cold refits + ZERO fallback fetches, and a
# blackholed-transfer phase degrading to cold refit (never a wedge)
bench-elastic:
	$(PY) -m benchmarks.elastic_bench

# multi-tenant QoS plane (ISSUE 20): noisy-neighbor fleet (one whale
# tenant at 10x share flooding the real receiver) vs a solo-tenant
# control, with in-run asserts: quiet tenants' p99 verdict latency and
# F1 unchanged, every 429 + Retry-After lands on the whale, evictions
# charged to their causer, zero-vs-one-tenant byte parity on the
# sliced warm path, per-tenant ledger visible in /debug/state
bench-noisy:
	$(PY) -m benchmarks.noisy_bench

native:
	$(MAKE) -C native

deploy-render:
	$(PY) -m foremast_tpu.deploy deploy

# Unified static analysis (docs/static-analysis.md): the per-module
# rules (jit-hygiene, async-blocking, lock-discipline, env-contract,
# metrics-contract), the whole-program rules (lock-order,
# thread-escape, blocking-under-lock, device-flow, recompile-hazard,
# sharding-contract, status-machine), the generated-artifact gates
# (env table, metric families, lock graph, status graph) and the
# metric naming lint, gated against analysis_baseline.json.
check:
	$(PY) -m foremast_tpu.analysis

# legacy alias — the metrics lint now runs inside `make check`
metrics-lint: check

# regenerate the env-knob table in docs/operations.md from
# foremast_tpu/config.py's ENV_KNOBS registry
env-docs:
	$(PY) -m foremast_tpu.analysis --update-env-docs

# regenerate the metric-family index in docs/observability.md from
# observe/metrics_lint.py's registry (rule: metrics-contract)
metrics-docs:
	$(PY) -m foremast_tpu.analysis --update-metrics-docs

# recompute + commit the static lock-acquisition graph
# (analysis_lockgraph.json; rule: lock-order — `make check` fails when
# the committed artifact drifts from the computed graph)
lockgraph:
	$(PY) -m foremast_tpu.analysis --write-lockgraph

# recompute + commit the doc status transition graph
# (analysis_statusgraph.json; rule: status-machine — `make check` fails
# when the committed artifact drifts from the computed graph)
statusgraph:
	$(PY) -m foremast_tpu.analysis --write-statusgraph

docker-build:
	docker build -t foremast/foremast-tpu:0.1.0 .

clean:
	$(MAKE) -C native clean
	find . -name __pycache__ -type d -prune -exec rm -rf {} +

.PHONY: test test-fast ci bench bench-suite bench-pipeline bench-mixed bench-plane bench-ingest bench-scaleout bench-cold bench-restart bench-chaos bench-elastic bench-noisy native deploy-render check metrics-lint env-docs metrics-docs lockgraph statusgraph docker-build clean
