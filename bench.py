"""Headline benchmark: metric-windows scored per second, single chip.

BASELINE.md north star: 100k concurrent metric-windows/sec on a v5e-8 →
per-chip share 12,500 windows/sec (`vs_baseline` is measured/12,500). The
workload is BASELINE.md config 5 shaped: full pipeline per window —
pairwise rank tests (Mann-Whitney + Wilcoxon + Kruskal) on baseline vs
current, historical model fit over the 7-day window (10,080 points at the
60 s step, `metricsquery.go:75-77`), bounds, anomaly flags, verdict.

Prints ONE JSON line. Runs on whatever backend jax selects (the driver
provides the real TPU); BENCH_SMALL=1 shrinks shapes for CPU smoke runs.
"""

import json
import os
import time

import jax

from foremast_tpu.engine import scoring
from foremast_tpu.parallel.batch import throughput_batch

SMALL = os.environ.get("BENCH_SMALL") == "1"
# B: the whole pending population as ONE batch is the framework's design
# center (SURVEY.md §7.4); 32k windows ≈ an 8k-service × 4-metric tick and
# amortizes dispatch latency (measured 363k w/s at B=4k -> 1.37M at B=32k)
B = 512 if SMALL else 32768
HIST = 512 if SMALL else 10080  # 7-day window at 60 s step
CUR = 30  # 30-min current window
# Steady-state iteration count. The axon tunnel charges a ~100 ms fixed
# synchronization cost to every timed sequence (measured r3: per-iter
# wall time at ITERS 1/3/10/30/100 = 111/40/15/8.3/5.8 ms against a
# marginal per-iteration cost of ~4.8 ms) — a continuously-scoring
# engine pays that once, not per tick, so the headline measures the
# amortized steady state; the marginal decomposition lives in
# BENCHMARKS.md.
ITERS = 3 if SMALL else 100
PER_CHIP_BASELINE = 100_000 / 8  # north-star v5e-8 target, per chip


def main():
    batch = throughput_batch(B, HIST, CUR)
    batch = jax.device_put(batch)

    if os.environ.get("FOREMAST_BF16_DELTA", "1") == "1":
        # anchor-shifted bf16-delta history storage (BENCHMARKS.md
        # roofline note): history resides as f32 anchors + bf16 deltas,
        # halving the steady-state HBM read the headline is bound on.
        # Measured 2026-07-31: 10.94M w/s vs 5.60M f32 (1.95x), verdict/
        # flag parity and low-CV band geometry pinned by
        # tests/test_engine.py::test_bf16_delta_scorer_matches_f32...
        # Default ON for the steady-state headline; FOREMAST_BF16_DELTA=0
        # opts back into f32 storage.
        slim, anchor, delta = scoring.make_bf16_delta_batch(batch)
        anchor, delta, slim = jax.device_put((anchor, delta, slim))
        jax.block_until_ready(delta)

        def run(_):
            return scoring.score_bf16_delta(slim, anchor, delta)

    else:

        def run(b):
            return scoring.score(b)

    # compile + warm up
    res = run(batch)
    jax.block_until_ready(res.verdict)

    import contextlib
    import statistics

    from foremast_tpu.observe.profile import trace_scoring

    # Median of REPEATS timed loops: single-shot numbers over the driver
    # tunnel swing +-15% run to run. FOREMAST_PROFILE=<dir> dumps a
    # jax.profiler trace of the FIRST timed loop only (one loop is enough
    # to read, and repeats would triple the trace).
    REPEATS = 3
    times = []
    for rep in range(REPEATS):
        ctx = trace_scoring() if rep == 0 else contextlib.nullcontext()
        with ctx:
            t0 = time.perf_counter()
            for _ in range(ITERS):
                res = run(batch)
            jax.block_until_ready(res.verdict)
            times.append(time.perf_counter() - t0)

    windows_per_sec = B * ITERS / statistics.median(times)
    result = {
        "metric": "metric_windows_per_sec",
        "value": round(windows_per_sec, 1),
        "unit": "windows/s",
        "vs_baseline": round(windows_per_sec / PER_CHIP_BASELINE, 3),
    }
    print(json.dumps(result))
    from benchmarks.report import write_summary

    write_summary("engine", result)


if __name__ == "__main__":
    main()
