"""Fleet-scale END-TO-END worker benchmark: `BrainWorker.tick` measured
through claim -> fetch -> judge -> write-back.

BASELINE.md's north star is "100k concurrent metric-windows scored/sec"
— scored by the SYSTEM, not by a kernel. The suite's config 3r measures
the shipped judge; this module measures the whole worker loop the way
the reference's brain runs it (`docs/guides/design.md:35-43`): a fake
job store holding one document per service (4 metric aliases each, the
reference's 4-metric monitor shape) and an in-memory metric source, so
the measured time is claim CAS + config decode + window fetch + batch
pack + device scoring + verdict decode + ES-document write-back — every
host byte the production loop pays, minus only real network latency.

The re-check loop is the steady state being measured: every document's
endTime is in the future, so each tick re-judges the same fleet
(status `preprocess_completed` -> claimable again), exactly like the
reference brain re-checking until endTime (`design.md:43`).

Usage: python -m benchmarks.worker_bench [--services N] [--ticks K]
       [--algorithm A] [--season M] [--small]
Prints one JSON line per phase (cold, warm steady state).
"""

from __future__ import annotations

import argparse
import json
import time

import numpy as np

from foremast_tpu.config import BrainConfig
from foremast_tpu.jobs.models import (
    STATUS_PREPROCESS_COMPLETED,
    Document,
)
from foremast_tpu.jobs.store import InMemoryStore
from foremast_tpu.jobs.worker import BrainWorker
from foremast_tpu.metrics.source import MetricSource

ALIASES = ("latency", "error4xx", "error5xx", "tps")


class ArraySource(MetricSource):
    """Exact-match URL->series map: O(1) fetch, no parsing.

    ReplaySource's substring scan is O(routes) per fetch — fine for
    tests, quadratic at fleet scale. This source is the fake-Prometheus
    floor: the benchmark charges the worker for everything EXCEPT real
    HTTP latency."""

    concurrent_fetch = False

    def __init__(self):
        self.data: dict[str, tuple[np.ndarray, np.ndarray]] = {}

    def fetch(self, url: str):
        return self.data[url]


def _add_service(
    store, source, sid, ht, ct, hist_len, cur_len, end_time, rng,
    baseline=False,
):
    """Create one service's document + its 4 per-alias series. Returns
    (doc_id, urls) so churn can retire the service cleanly.

    `baseline=True` (ISSUE 14): the doc is CANARY-shaped — every alias
    also carries a baselineConfig URL serving a pre-deploy window of the
    same clean distribution (so the pairwise rank tests run every tick
    but don't reject: the healthy-canary steady state), exactly the
    reference's baseline-pods-vs-canary-pods headline query shape
    (metricsquery.go:111-116)."""
    cur_parts = []
    hist_parts = []
    base_parts = []
    urls = []
    for a in ALIASES:
        cur_url = f"http://prom/cur?q={a}:app{sid}&end={int(ct[0]) - 60}&step=60"
        hist_url = (
            f"http://prom/hist?q={a}:app{sid}"
            f"&end={ht[-1] + 60}&step=60"
        )
        # per-(service, alias) series so fits cannot alias each
        # other; current rides well inside the fitted band (+-0.5
        # sigma) so the fleet stays on the healthy re-check path —
        # Gaussian current tails would turn ~half the fleet
        # completed_unhealth (terminal) on the first tick
        hv = rng.normal(1.0, 0.1, hist_len).astype(np.float32)
        cv = (
            1.0
            + 0.05 * np.sin(np.arange(cur_len) / 3.0)
        ).astype(np.float32)
        source.data[cur_url] = (ct, cv)
        source.data[hist_url] = (ht, hv)
        urls.extend((cur_url, hist_url))
        cur_parts.append(f"{a}== {cur_url}")
        hist_parts.append(f"{a}== {hist_url}")
        if baseline:
            base_url = f"http://prom/base?q={a}:app{sid}&step=60"
            # the baseline pods' window: same signal family with its
            # own noise draw — same distribution, so the rank tests
            # hold (differs=False) and the canary stays healthy
            bv = (
                1.0
                + 0.05 * np.sin(np.arange(cur_len) / 3.0)
                + rng.normal(0, 0.01, cur_len)
            ).astype(np.float32)
            source.data[base_url] = (ct - 3600, bv)
            urls.append(base_url)
            base_parts.append(f"{a}== {base_url}")
    doc = Document(
        id=f"job-{sid}",
        app_name=f"app{sid}",
        end_time=end_time,
        current_config=" ||".join(cur_parts),
        historical_config=" ||".join(hist_parts),
        baseline_config=" ||".join(base_parts),
        strategy="canary" if baseline else "continuous",
    )
    store.create(doc)
    return doc.id, urls


def _add_joint_service(
    store, source, sid, ht, ct, f, end_time, rng
):
    """One service of f co-moving metrics (m0..m{f-1}) whose clean
    current windows continue the historical latent — under the `auto`
    selector the doc routes to the bivariate (f=2) or LSTM-hybrid
    (f>=3) detector, or the univariate fallback (f=1), and stays on the
    healthy re-check path."""
    from benchmarks.quality import draw_comoving

    r = np.random.default_rng(int(rng.integers(0, 2**31)))
    hist = draw_comoving(r, 1, f, len(ht), 0)[0]  # [f, hist_len]
    cur = draw_comoving(r, 1, f, len(ct), len(ht))[0]
    cur_parts = []
    hist_parts = []
    for m in range(f):
        cur_url = f"http://prom/cur?q=m{m}:app{sid}&step=60"
        hist_url = (
            f"http://prom/hist?q=m{m}:app{sid}&end={ht[-1] + 60}&step=60"
        )
        source.data[cur_url] = (ct, cur[m])
        source.data[hist_url] = (ht, hist[m])
        cur_parts.append(f"m{m}== {cur_url}")
        hist_parts.append(f"m{m}== {hist_url}")
    doc = Document(
        id=f"job-{sid}",
        app_name=f"app{sid}",
        end_time=end_time,
        current_config=" ||".join(cur_parts),
        historical_config=" ||".join(hist_parts),
        strategy="continuous",
    )
    store.create(doc)
    return doc.id


def build_fleet(
    services: int,
    hist_len: int,
    cur_len: int,
    now: float,
    seed: int = 0,
):
    """One document per service x 4 aliases, re-check steady state."""
    store, source, _ = build_mixed_fleet(
        services, hist_len, cur_len, now, joint_frac=0.0, seed=seed
    )
    return store, source


def build_mixed_fleet(
    services: int,
    hist_len: int,
    cur_len: int,
    now: float,
    joint_frac: float = 0.0,
    seed: int = 0,
    baseline_frac: float = 0.0,
):
    """One document per service, re-check steady state.

    joint_frac = 0: every service is the reference's 4-alias monitor
    shape, scored per alias by the configured univariate algorithm.
    joint_frac > 0 (the ISSUE 4 mixed-fleet condition, run under the
    `auto` selector): that fraction of services are JOINT docs —
    alternating 2-alias bivariate and 4-alias LSTM-hybrid — and the
    REST are single-alias docs (under `auto`, metric count IS the model
    selector, so a 4-alias doc is itself a joint doc; the univariate
    share of a mixed auto fleet is its single-metric services).
    baseline_frac > 0 (the ISSUE 14 canary-heavy condition, univariate
    fleets only): that fraction of services are CANARY docs — every
    alias carries a baselineConfig window, so the doc judges through
    the pairwise rank tests each tick. Returns (store, source,
    windows_by_doc)."""
    if joint_frac > 0 and baseline_frac > 0:
        raise ValueError("joint_frac and baseline_frac are separate modes")
    rng = np.random.default_rng(seed)
    store = InMemoryStore()
    source = ArraySource()
    t_now = int(now)
    ht = t_now - 86_400 * 7 + 60 * np.arange(hist_len, dtype=np.int64)
    ct = ht[-1] + 60 + 60 * np.arange(cur_len, dtype=np.int64)
    # endTime one hour out: every tick lands in the keep-re-checking
    # branch (STATUS_PREPROCESS_COMPLETED), the production steady state
    end_time = time.strftime(
        "%Y-%m-%dT%H:%M:%SZ", time.gmtime(t_now + 3600)
    )
    n_joint = int(round(services * joint_frac))
    n_canary = int(round(services * baseline_frac))
    windows_by_doc: dict[str, int] = {}
    for s in range(services):
        if joint_frac > 0 and s < n_joint:
            f = 2 if s % 2 == 0 else 4
            doc_id = _add_joint_service(
                store, source, str(s), ht, ct, f, end_time, rng
            )
            windows_by_doc[doc_id] = f
        elif joint_frac > 0:
            doc_id = _add_joint_service(
                store, source, str(s), ht, ct, 1, end_time, rng
            )
            windows_by_doc[doc_id] = 1
        else:
            doc_id, _ = _add_service(
                store, source, str(s), ht, ct, hist_len, cur_len,
                end_time, rng, baseline=s < n_canary,
            )
            windows_by_doc[doc_id] = len(ALIASES)
    return store, source, windows_by_doc


def run(
    services: int,
    ticks: int,
    algorithm: str,
    season: int,
    hist_len: int,
    cur_len: int,
    churn: float = 0.0,
    joint_frac: float = 0.0,
) -> dict:
    now = 1_760_000_000.0
    if joint_frac > 0 and churn > 0:
        raise ValueError("--churn and --joint-frac are separate modes")
    store, source, windows_by_doc = build_mixed_fleet(
        services, hist_len, cur_len, now, joint_frac=joint_frac
    )
    cfg = BrainConfig(
        algorithm=algorithm,
        season_steps=season,
        max_cache_size=4 * services + 64,
    )
    if joint_frac > 0:
        import dataclasses

        # joint detectors read the BASE threshold (their aliases match no
        # per-type rule); the quality scenarios calibrate them at 4 sigma
        # — at the deployed 2.0 default a clean fleet would page
        cfg = dataclasses.replace(
            cfg, anomaly=dataclasses.replace(cfg.anomaly, threshold=4.0)
        )
    worker = BrainWorker(
        store,
        source,
        config=cfg,
        claim_limit=services,
        worker_id="bench-worker",
    )
    windows = sum(windows_by_doc.values())

    from foremast_tpu.jobs.models import TERMINAL_STATUSES

    def open_count() -> int:
        with store._lock:
            return sum(
                1
                for d in store._docs.values()
                if d.status not in TERMINAL_STATUSES
            )

    # per-tick claimed WINDOW counts: mixed fleets carry 2/4 windows per
    # doc, so throughput must be measured in what was actually claimed
    claimed_windows: list[int] = []
    orig_claim = store.claim

    def _claim(worker_id, stuck, limit):
        docs = orig_claim(worker_id, stuck, limit)
        claimed_windows.append(
            sum(windows_by_doc.get(d.id, len(ALIASES)) for d in docs)
        )
        return docs

    store.claim = _claim

    # time-to-first-verdict: wrap the store's write path so the cold
    # tick's FIRST persisted judgment is timestamped (VERDICT r4 #7 —
    # progressive admission means a 16k-service cold tick should land
    # its first verdicts within one doc-chunk's work, not after the
    # whole fleet's fit)
    first_write = [None]
    orig_update, orig_many = store.update, store.update_many

    def _u(doc):
        if first_write[0] is None:
            first_write[0] = time.perf_counter()
        return orig_update(doc)

    def _um(docs):
        if first_write[0] is None and docs:
            first_write[0] = time.perf_counter()
        return orig_many(docs)

    store.update, store.update_many = _u, _um

    # Ticks start 150 s after job creation: the watcher builds each
    # historical range ending at deploy start (`metricsquery.go:65-72`),
    # so for the first ~2 min of a job's life the range is not yet
    # "settled" (HIST_SETTLED_SECONDS ingestion margin) and the worker
    # correctly refuses to cache series or fits. Production re-check
    # ticks — the steady state this measures — happen for the remaining
    # ~28 min of the job's 30-min window with settled histories.
    # cold: first tick pays fetch, pack, upload, fit, compile
    t0 = time.perf_counter()
    n = worker.tick(now=now + 150)
    cold_s = time.perf_counter() - t0
    first_verdict_s = (
        first_write[0] - t0 if first_write[0] is not None else cold_s
    )
    store.update, store.update_many = orig_update, orig_many
    assert n == services, f"claimed {n} != {services}"
    cold_windows = claimed_windows[0] if claimed_windows else windows

    # churn bookkeeping: retire the oldest live services, admit fresh
    # ones (new ids, new series) before each warm tick — the VERDICT r4
    # ask #4 regime where every tick mixes a few cold fits into the
    # warm fleet and bumps the fit-cache version
    rng = np.random.default_rng(1)
    t_now = int(now)
    ht = t_now - 86_400 * 7 + 60 * np.arange(hist_len, dtype=np.int64)
    ct = ht[-1] + 60 + 60 * np.arange(cur_len, dtype=np.int64)
    end_time = time.strftime(
        "%Y-%m-%dT%H:%M:%SZ", time.gmtime(t_now + 3600)
    )
    live = [str(s) for s in range(services)]
    url_map = {}  # sid -> urls (lazy: only churned-in services tracked)
    next_sid = services
    n_churn = max(1, int(services * churn)) if churn > 0 else 0

    def apply_churn():
        nonlocal next_sid
        for _ in range(n_churn):
            sid = live.pop(0)
            with store._lock:
                store._docs.pop(f"job-{sid}", None)
            for u in url_map.pop(sid, ()):
                source.data.pop(u, None)
            nsid = str(next_sid)
            next_sid += 1
            did, urls = _add_service(
                store, source, nsid, ht, ct, hist_len, cur_len,
                end_time, rng,
            )
            windows_by_doc[did] = len(ALIASES)
            url_map[nsid] = urls
            live.append(nsid)

    # warm steady state: same fleet re-checked (hist + fit caches hot);
    # under --churn, each tick also fits n_churn cold newcomers
    times = []
    warm_rates = []
    for k in range(ticks):
        if n_churn:
            apply_churn()
        expected = open_count()
        t0 = time.perf_counter()
        n = worker.tick(now=now + 160 + 10 * k)
        dt = time.perf_counter() - t0
        times.append(dt)
        warm_rates.append(claimed_windows[-1] / dt)
        assert n == expected, f"claimed {n} != {expected}"
    warm_s = float(np.median(times))
    out = {
        "services": services,
        "windows": windows,
        "algorithm": algorithm,
        "cold_tick_seconds": round(cold_s, 3),
        "cold_first_verdict_seconds": round(first_verdict_s, 3),
        "cold_windows_per_sec": round(cold_windows / cold_s, 1),
        "warm_tick_seconds": round(warm_s, 3),
        "warm_windows_per_sec": round(float(np.median(warm_rates)), 1),
        "warm_ticks_measured": ticks,
    }
    if n_churn:
        out["churn_per_tick"] = n_churn
        counters = worker._uni.device_state_counters()
        out["arena_fallbacks"] = counters.get("fallbacks", 0)
    if joint_frac > 0:
        n_joint = int(round(services * joint_frac))
        # per-kind columnar doc counts: bivariate/lstm > 0 is the
        # acceptance proof that joint docs rode the fast path
        out["joint_services"] = n_joint
        out["joint_fraction"] = joint_frac
        out["fast_path_docs"] = dict(worker._fast_kinds)
        out["joint_arena"] = worker._mvj.joint_state_counters()
        # clean fleets should stay open; terminal docs here are joint
        # false alarms (priced by the quality benchmark's clean-window
        # scenario) — reported, never hidden
        out["terminal_docs"] = services - open_count()
    return out


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--services", type=int, default=10_000)
    ap.add_argument("--ticks", type=int, default=3)
    ap.add_argument("--algorithm", default="moving_average_all")
    ap.add_argument("--season", type=int, default=24)
    ap.add_argument("--hist-len", type=int, default=10_080)
    ap.add_argument("--cur-len", type=int, default=30)
    ap.add_argument(
        "--churn",
        type=float,
        default=0.0,
        help="fraction of services retired + replaced before each warm "
        "tick (e.g. 0.1 = 10%% churn: that many cold fits per tick)",
    )
    ap.add_argument(
        "--joint-frac",
        type=float,
        default=0.0,
        help="fraction of services that are JOINT docs (alternating "
        "2-alias bivariate and 4-alias LSTM-hybrid) — the ISSUE 4 "
        "mixed-fleet mode; forces ML_ALGORITHM=auto semantics, so pair "
        "with --algorithm auto",
    )
    ap.add_argument(
        "--small", action="store_true", help="CPU smoke shapes (CI)"
    )
    ap.add_argument(
        "--profile",
        default=None,
        metavar="OUT.pstats",
        help="cProfile the warm ticks into OUT.pstats",
    )
    args = ap.parse_args(argv)
    if args.small:
        args.services = min(args.services, 128)
        args.hist_len = min(args.hist_len, 512)
    if args.joint_frac > 0:
        from foremast_tpu.engine.multivariate import MULTIVARIATE_ALGOS

        if args.algorithm not in MULTIVARIATE_ALGOS:
            args.algorithm = "auto"
    if args.profile:
        import cProfile

        # profile everything; cold-tick compile noise is excluded by
        # enabling only around the warm phase inside run() — simplest
        # honest alternative: profile a second run() whose compiles are
        # already cached in-process
        run(args.services, 1, args.algorithm, args.season,
            args.hist_len, args.cur_len)
        prof = cProfile.Profile()
        prof.enable()
        result = run(args.services, args.ticks, args.algorithm,
                     args.season, args.hist_len, args.cur_len,
                     churn=args.churn, joint_frac=args.joint_frac)
        prof.disable()
        prof.dump_stats(args.profile)
    else:
        result = run(args.services, args.ticks, args.algorithm,
                     args.season, args.hist_len, args.cur_len,
                     churn=args.churn, joint_frac=args.joint_frac)
    result["config"] = (
        "w-mixed-fleet-tick" if args.joint_frac > 0
        else "w-shipped-worker-tick"
    )
    result["metric"] = "warm_windows_per_sec"
    result["value"] = result["warm_windows_per_sec"]
    result["unit"] = "windows/s"
    print(json.dumps(result), flush=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
