"""Worker-mesh scale-out benchmark: N REAL processes sharding one fleet.

Every other benchmark measures one worker; this one measures the ISSUE 6
architecture end to end. A parent process serves the shared job store
over real HTTP (the production topology: independent workers against one
store) and spawns N worker subprocesses, each running the SHIPPED stack —
`BrainWorker` + `MeshNode` (membership lease in the store, consistent-hash
claim partition) + its own ingest receiver and ring shard fed through the
cold-miss backfill path. Metric data comes from `SynthSource`, a
deterministic in-process generator (every worker synthesizes identical
series from the URL alone), so the measured numbers are claim + partition
filter + fetch/ring + judge + write-back — everything except Prometheus
latency, same floor as worker_bench.

Phases (parent-orchestrated through the store server's /control plane):

  ready   all workers joined the mesh; the parent runs a ROUTED-PUSH
          cycle against the workers' receivers (`RoutingPusher`): cycle 1
          scatters blind and collects redirect hints, cycle 2 must land
          every series on its owner with zero redirects
  cold    one tick per worker (fits + ring backfill)
  prewarm one unmeasured warm round per worker (columnar program
          compiles + admission-cache build stay out of the steady-state
          window — the same discipline as every other bench here)
  warm    `--warm-ticks` measured ticks per worker; the parent wall-times
          the phase and ASSERTS exactly-once judgment: every fleet doc
          judged exactly `warm_ticks` times, all by one worker
  kill    (largest run only) one worker SIGKILLs itself mid-tick after
          its claim persisted; survivors keep ticking — the parent
          asserts every orphaned doc is re-judged by a survivor within
          2 ticks of that survivor seeing the membership drop
  stop

Single-host methodology: every worker in every run is pinned to
`nproc // max(workers)` cores (constant per-worker hardware — the
1 -> N comparison measures SCALE-OUT, not one process's XLA intra-op
threads absorbing the whole host), and the store runs in the parent
as a real HTTP service the way production ES would be a separate
system. Workers on real deployments bring their own hosts/chips, so
the single-host numbers here are the conservative floor.

Usage: python -m benchmarks.scaleout_bench [--services N] [--workers 1,4]
       [--warm-ticks K] [--small]
Prints one JSON line per worker count plus a summary line with the
1 -> max speedup.
"""

from __future__ import annotations

import argparse
import hashlib
import json
import os
import subprocess
import sys
import tempfile
import threading
import time
import urllib.parse

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

ALIAS_EXPR = 'synth_m{a}{{app="app{sid}"}}'
KILL_EXIT = 17


# ---------------------------------------------------------------------------
# deterministic metric source — identical series in every process
# ---------------------------------------------------------------------------


def synth_values(key: str, ts: np.ndarray) -> np.ndarray:
    """A healthy hour-period wave, phase-seeded by the series key: the
    band a moving-average fit draws around the history always contains
    the current window (same generator, same amplitude)."""
    h = int.from_bytes(
        hashlib.blake2b(key.encode(), digest_size=8).digest(), "big"
    )
    phase = (h % 4096) / 4096.0 * 2.0 * np.pi
    return (
        1.0 + 0.08 * np.sin(2.0 * np.pi * ts / 3600.0 + phase)
    ).astype(np.float32)


class SynthSource:
    """MetricSource synthesizing windows from the URL alone — the
    fake-Prometheus floor without a server (worker_bench.ArraySource
    needs the data pre-seeded; subprocesses cannot share that dict)."""

    concurrent_fetch = False

    def fetch(self, url: str):
        from foremast_tpu.ingest.wire import resolve_query_range

        key, t0, t1, step = resolve_query_range(url)
        if key is None or t0 is None or t1 is None:
            raise ValueError(f"unresolvable synth url {url!r}")
        ts = np.arange(int(t0), int(t1) + 1, int(step or 60), np.int64)
        return ts, synth_values(key, ts)


def build_fleet(store, services: int, aliases: int, hist_len: int,
                cur_len: int, now: int) -> None:
    """One continuous-strategy doc per service; series keys carry the
    app label, so documents and their pushed series hash to the same
    mesh member (mesh/routing.py route label)."""
    from foremast_tpu.jobs.models import Document

    cur_t1 = now - 60
    cur_t0 = cur_t1 - 60 * (cur_len - 1)
    hist_t1 = cur_t0 - 120  # settled AND disjoint from the current window
    hist_t0 = hist_t1 - 60 * (hist_len - 1)
    end_time = time.strftime(
        "%Y-%m-%dT%H:%M:%SZ", time.gmtime(now + 86_400)
    )
    for sid in range(services):
        cur_parts, hist_parts = [], []
        for a in range(aliases):
            expr = urllib.parse.quote(
                ALIAS_EXPR.format(a=a, sid=sid), safe=""
            )
            cur_parts.append(
                f"m{a}== http://synth/api/v1/query_range?query={expr}"
                f"&start={cur_t0}&end={cur_t1}&step=60"
            )
            hist_parts.append(
                f"m{a}== http://synth/api/v1/query_range?query={expr}"
                f"&start={hist_t0}&end={hist_t1}&step=60"
            )
        store.create(
            Document(
                id=f"job-{sid}",
                app_name=f"app{sid}",
                end_time=end_time,
                current_config=" ||".join(cur_parts),
                historical_config=" ||".join(hist_parts),
                strategy="continuous",
            )
        )


# ---------------------------------------------------------------------------
# the shared store, served over real HTTP
# ---------------------------------------------------------------------------


class _InjectedStoreFault(Exception):
    """A fault-hook hit: the HTTP handler answers `status` (not 500),
    so clients see the same wire behavior a browning-out ES would
    produce (503s on the write path classify as transient)."""

    def __init__(self, status: int, op: str):
        super().__init__(f"injected fault: HTTP {status} on {op!r}")
        self.status = status


class StoreServer:
    """InMemoryStore behind one JSON-RPC endpoint, with the mesh claim
    filter applied SERVER-SIDE through the real membership + ring code
    (the same ownership function the workers' own routers compute) and
    a judgment ledger the parent's exactly-once assertions read."""

    def __init__(self, replicas: int = 64):
        from foremast_tpu.jobs.store import InMemoryStore

        self.store = InMemoryStore()
        self.replicas = replicas
        self._lock = threading.Lock()
        # doc id -> [(worker, phase_tag, status, wall_seconds), ...]
        self.ledger: dict[str, list] = {}
        self.ticks: list[dict] = []
        self.barriers: dict[str, set] = {}
        self.phase = "ready"
        self._owner_cache: tuple | None = None  # (members_key, {app: owner})
        # per-worker ids already shipped in full: a re-claim of a doc a
        # worker has seen returns just the id (the config blobs are
        # immutable per id and the worker's meta cache already decoded
        # them) — the bench-protocol analog of ES `_source` filtering
        self.seen: dict[str, set] = {}
        self.op_seconds: dict[str, list] = {}  # op -> [count, seconds]
        self._srv = None
        # fault hooks (ISSUE 9 satellite): chaos tests drive a REAL
        # store server answering real error statuses per RPC op —
        # {"op": substr(""=all), "status": int, "latency": seconds,
        # "times": remaining fires (None=until removed)}; clear with
        # clear_faults(). Matching faults with a status short-circuit
        # the dispatch (the op never reaches the store).
        self.faults: list[dict] = []

    def add_fault(
        self,
        op: str = "",
        status: int = 503,
        latency: float = 0.0,
        times: int | None = None,
    ) -> None:
        with self._lock:
            self.faults.append(
                {"op": op, "status": status, "latency": latency,
                 "times": times}
            )

    def clear_faults(self) -> None:
        with self._lock:
            self.faults = []

    def _take_fault(self, op: str) -> dict | None:
        with self._lock:
            for f in self.faults:
                if f["op"] and f["op"] not in op:
                    continue
                if f["times"] is not None:
                    if f["times"] <= 0:
                        continue
                    f["times"] -= 1
                return dict(f)
        return None

    # -- mesh ownership, computed from the records IN the store --------

    def _claim_filter(self, worker_id: str):
        from foremast_tpu.mesh import (
            CLAIM_STATES,
            HashRing,
            doc_route_key,
            live_members,
        )

        # the CLAIM ring only (mesh/routing.py two-ring ownership): a
        # fenced `joining` member must not claim a doc the server side
        # still routes to the current owner, or the joiner judges COLD
        # mid-handoff — exactly the refit the fence exists to prevent
        members = [
            m for m in live_members(self.store) if m.state in CLAIM_STATES
        ]
        if not members:
            return None
        key = tuple((m.worker_id, m.capacity, m.state) for m in members)
        with self._lock:
            cached = self._owner_cache
            owners = cached[1] if cached and cached[0] == key else None
        if owners is None:
            owners = {}
            with self._lock:
                self._owner_cache = (key, owners)
        ring = HashRing(
            {m.worker_id: m.capacity for m in members},
            replicas=self.replicas,
        )

        def owns(doc) -> bool:
            rk = doc_route_key(doc)
            owner = owners.get(rk)
            if owner is None:
                owner = ring.owner(rk)
                owners[rk] = owner
            return owner == worker_id

        return owns

    def owner_map(self) -> dict[str, str]:
        """app -> owner under the CURRENT live membership (parent-side:
        orphan-set computation before a kill)."""
        from foremast_tpu.mesh import HashRing, doc_route_key, live_members
        from foremast_tpu.mesh.membership import CLAIM_STATES, MESH_APP

        members = live_members(self.store)
        ring = HashRing(
            {
                m.worker_id: m.capacity
                for m in members
                if m.state in CLAIM_STATES
            },
            replicas=self.replicas,
        )
        out = {}
        for doc in self.store.list_open():
            if doc.app_name == MESH_APP:
                continue
            out[doc.id] = ring.owner(doc_route_key(doc))
        return out

    def _record(self, doc_json: dict, worker: str, tag: str) -> None:
        from foremast_tpu.mesh.membership import MESH_APP

        if doc_json.get("appName") == MESH_APP:
            return
        status = doc_json.get("status", "")
        with self._lock:
            self.ledger.setdefault(doc_json["id"], []).append(
                (worker, tag, status, time.time())
            )

    # -- RPC ------------------------------------------------------------

    def _rpc(self, req: dict) -> dict:
        t0 = time.perf_counter()
        try:
            fault = self._take_fault(req["op"])
            if fault is not None:
                if fault["latency"]:
                    time.sleep(fault["latency"])
                if fault["status"]:
                    raise _InjectedStoreFault(fault["status"], req["op"])
            return self._dispatch(req)
        finally:
            dt = time.perf_counter() - t0
            with self._lock:
                agg = self.op_seconds.setdefault(req["op"], [0, 0.0])
                agg[0] += 1
                agg[1] += dt

    def _dispatch(self, req: dict) -> dict:
        from foremast_tpu.jobs.models import Document

        op = req["op"]
        if op == "create_many":
            for d in req["docs"]:
                self.store.create(Document.from_json(d))
            return {"ok": True}
        if op == "get":
            doc = self.store.get(req["id"])
            return {"doc": doc.to_json() if doc else None}
        if op == "claim":
            worker = req["workerId"]
            filt = self._claim_filter(worker) if req.get("mesh") else None
            docs = self.store.claim(
                worker, req["maxStuck"], req["limit"], claim_filter=filt,
            )
            seen = self.seen.setdefault(worker, set())
            new = [d.to_json() for d in docs if d.id not in seen]
            ids = [d.id for d in docs]
            seen.update(ids)
            return {"ids": ids, "new": new}
        if op == "update":
            doc = Document.from_json(req["doc"])
            self.store.update(doc)
            self._record(req["doc"], req.get("workerId", "?"), req.get("tag", ""))
            return {"ok": True}
        if op == "update_many":
            # partial-update rows [id, status, statusCode, reason,
            # anomalyInfo] — the bench-protocol analog of ES partial
            # updates: a warm write-back never re-ships the immutable
            # config blobs. One store lock for the whole batch: a
            # per-row get() would take and release it 16k times per
            # round per worker, serializing the mesh on lock churn.
            from foremast_tpu.jobs.store import now_rfc3339

            worker = req.get("workerId", "?")
            tag = req.get("tag", "")
            wall = time.time()
            entries = []
            stamp = now_rfc3339()
            with self.store._lock:
                docs = self.store._docs
                for doc_id, status, code, reason, anomaly in req["rows"]:
                    doc = docs.get(doc_id)
                    if doc is None:
                        continue
                    doc.status = status
                    doc.status_code = code
                    doc.reason = reason
                    doc.anomaly_info = anomaly
                    doc.modified_at = stamp
                    entries.append((doc_id, status))
            with self._lock:
                for doc_id, status in entries:
                    self.ledger.setdefault(doc_id, []).append(
                        (worker, tag, status, wall)
                    )
            return {"ok": True}
        if op == "list_app":
            return {
                "docs": [d.to_json() for d in self.store.list_app(req["app"])]
            }
        if op == "report_tick":
            with self._lock:
                self.ticks.append(req["tick"])
            return {"ok": True}
        if op == "barrier":
            with self._lock:
                self.barriers.setdefault(req["name"], set()).add(
                    req["workerId"]
                )
            return {"ok": True}
        if op == "phase":
            return {"phase": self.phase}
        raise ValueError(f"unknown op {op!r}")

    def barrier_count(self, name: str) -> int:
        with self._lock:
            return len(self.barriers.get(name, ()))

    def ledger_snapshot(self) -> dict[str, list]:
        with self._lock:
            return {k: list(v) for k, v in self.ledger.items()}

    def tick_reports(self) -> list[dict]:
        with self._lock:
            return list(self.ticks)

    # -- HTTP plumbing ---------------------------------------------------

    def start(self) -> str:
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        outer = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"  # keep-alive: one conn per worker

            def log_message(self, *a):
                pass

            def do_POST(self):
                n = int(self.headers.get("Content-Length") or 0)
                try:
                    body = outer._rpc(json.loads(self.rfile.read(n)))
                    code = 200
                except _InjectedStoreFault as e:
                    body, code = {"error": str(e)}, e.status
                except Exception as e:  # noqa: BLE001 — surface to the client
                    body, code = {"error": repr(e)}, 500
                payload = json.dumps(body, separators=(",", ":")).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(payload)))
                self.end_headers()
                self.wfile.write(payload)

        self._srv = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        self._srv.daemon_threads = True
        threading.Thread(
            target=self._srv.serve_forever, daemon=True
        ).start()
        return f"http://127.0.0.1:{self._srv.server_address[1]}"

    def stop(self):
        if self._srv is not None:
            self._srv.shutdown()


class HttpFleetStore:
    """Worker-side JobStore speaking the StoreServer protocol. The mesh
    claim filter travels as `mesh: true` — ownership is evaluated
    server-side from the same membership records with the same ring
    code, so the predicate callable never needs to cross the wire."""

    def __init__(self, base_url: str, worker_id: str, chaos=None, breaker=None):
        import requests

        from foremast_tpu.jobs.store import JobStore  # noqa: F401 — interface

        self.base = base_url
        self.worker_id = worker_id
        self.tag = ""  # phase tag stamped onto judgment writes
        self._s = requests.Session()
        if chaos is not None or breaker is not None:
            # the same one-choke-point seam ElasticsearchStore carries
            # (ISSUE 9): chaos benches drive the REAL degradation paths
            # through this client too
            from foremast_tpu.chaos import GuardedSession

            self._s = GuardedSession(self._s, chaos=chaos, breaker=breaker)
        # docs the server has shipped in full (slim re-claims return
        # ids only; the shared Document objects mirror InMemoryStore's
        # same-object semantics)
        self._docs: dict = {}

    def _rpc(self, **req) -> dict:
        r = self._s.post(self.base, json=req, timeout=120)
        r.raise_for_status()
        body = r.json()
        if "error" in body:
            raise RuntimeError(body["error"])
        return body

    def create(self, doc):
        got = self._rpc(op="get", id=doc.id)["doc"]
        if got is not None:
            from foremast_tpu.jobs.models import Document

            return Document.from_json(got), False
        self._rpc(op="create_many", docs=[doc.to_json()])
        return doc, True

    def get(self, doc_id):
        from foremast_tpu.jobs.models import Document

        got = self._rpc(op="get", id=doc_id)["doc"]
        return Document.from_json(got) if got else None

    def claim(self, worker_id, max_stuck_seconds, limit=64, claim_filter=None):
        from foremast_tpu.jobs.models import Document

        body = self._rpc(
            op="claim",
            workerId=worker_id,
            maxStuck=max_stuck_seconds,
            limit=limit,
            mesh=claim_filter is not None,
        )
        for d in body["new"]:
            doc = Document.from_json(d)
            self._docs[doc.id] = doc
        for i in body["ids"]:
            if i not in self._docs:
                # the server's `seen` set says this worker ID already
                # received the doc in full, but THIS process has not —
                # a restarted worker reusing its id (restart_bench).
                # Re-fetch once; the real ES store reships _source.
                got = self._rpc(op="get", id=i)["doc"]
                self._docs[i] = Document.from_json(got)
        return [self._docs[i] for i in body["ids"]]

    def update(self, doc):
        self._rpc(
            op="update", doc=doc.to_json(), workerId=self.worker_id,
            tag=self.tag,
        )
        self._docs[doc.id] = doc
        return doc

    def update_many(self, docs):
        if docs:
            self._rpc(
                op="update_many",
                rows=[
                    [
                        d.id, d.status, d.status_code, d.reason,
                        d.anomaly_info,
                    ]
                    for d in docs
                ],
                workerId=self.worker_id,
                tag=self.tag,
            )

    def list_app(self, app_name):
        from foremast_tpu.jobs.models import Document

        return [
            Document.from_json(d)
            for d in self._rpc(op="list_app", app=app_name)["docs"]
        ]

    def list_open(self):
        raise NotImplementedError("bench store: not needed")

    def count_open(self):
        raise NotImplementedError("bench store: not needed")

    def barrier(self, name):
        self._rpc(op="barrier", name=name, workerId=self.worker_id)

    def phase(self) -> str:
        return self._rpc(op="phase")["phase"]

    def report_tick(self, **tick):
        self._rpc(op="report_tick", tick=tick)


# ---------------------------------------------------------------------------
# the worker child (spawned as `-m benchmarks.scaleout_bench --child`)
# ---------------------------------------------------------------------------


class _SuicideSource:
    """Delegates until armed, then SIGKILLs this process on the 3rd
    fetch — mid-tick, after the claim persisted, before any verdict
    (the pod-failure test's worst case, at mesh scale)."""

    concurrent_fetch = False

    def __init__(self, inner):
        self.inner = inner
        self.armed = False
        self.calls = 0

    def fetch(self, url):
        if self.armed:
            self.calls += 1
            if self.calls >= 3:
                os._exit(KILL_EXIT)
        return self.inner.fetch(url)


def run_child(args) -> int:
    # Constant per-worker hardware, set BEFORE jax imports spawn its
    # thread pools: every worker in every run of one comparison is
    # pinned to the same number of cores, so 1 -> N measures SCALE-OUT
    # (N workers' worth of hardware doing N partitions) instead of N
    # oversubscribed XLA thread pools fighting over one host's cores —
    # without pinning, each worker's judge slows ~Nx and the comparison
    # measures the scheduler, not the mesh.
    if args.cpus:
        lo, _, hi = args.cpus.partition("-")
        try:
            os.sched_setaffinity(0, range(int(lo), int(hi) + 1))
        except (OSError, AttributeError):
            pass  # non-Linux: run unpinned

    # Device-mesh sharded-judge variant (ISSUE 13): shard each worker's
    # judge over an N-device local mesh. On real TPU hosts the devices
    # exist; on the CPU-host floor they are forced virtual devices (the
    # same stand-in tier-1 parity uses). MUST happen before jax imports.
    if args.device_mesh > 1:
        # JAX runtime controls, not foremast knobs (the conftest.py
        # precedent): read only to decide whether virtual devices must
        # stand in for real chips on a CPU host
        plat = os.environ.get("JAX_PLATFORMS", "")  # foremast: ignore[env-contract]
        flags = os.environ.get("XLA_FLAGS", "")  # foremast: ignore[env-contract]
        if (
            plat.startswith("cpu")
            and "xla_force_host_platform_device_count" not in flags
        ):
            os.environ["XLA_FLAGS"] = (
                flags
                + f" --xla_force_host_platform_device_count={args.device_mesh}"
            ).strip()
        os.environ["FOREMAST_DEVICE_MESH"] = str(args.device_mesh)
    else:
        # explicit OFF for the baseline runs: the pytest smoke inherits
        # an 8-virtual-device XLA_FLAGS from conftest, and "auto" would
        # silently shard the unsharded comparison arm
        os.environ["FOREMAST_DEVICE_MESH"] = "0"

    from foremast_tpu.config import BrainConfig
    from foremast_tpu.ingest import RingSource, RingStore, start_ingest_server
    from foremast_tpu.jobs.worker import BrainWorker
    from foremast_tpu.mesh import Membership, MeshNode, MeshRouter

    worker_id = f"w{args.index}"
    store = HttpFleetStore(args.store_url, worker_id)

    # the worker's own ingest shard: receiver + ring, warm current
    # windows served resident after the first backfill. The suicide
    # wrapper sits OUTSIDE the ring source — warm fetches are ring hits
    # that never reach the fallback, and the victim must die on the
    # fetches its judged tick actually makes.
    ring = RingStore(
        budget_bytes=args.ring_budget, shards=4,
        max_points=args.ring_points,
    )
    source = _SuicideSource(RingSource(ring, fallback=SynthSource()))
    membership = Membership(
        store, worker_id, lease_seconds=args.lease_seconds
    )
    router = MeshRouter(
        membership,
        replicas=args.replicas,
        refresh_seconds=min(1.0, args.lease_seconds / 4),
    )
    srv, _ = start_ingest_server(0, ring, host="127.0.0.1", router=router)
    address = f"127.0.0.1:{srv.server_address[1]}"
    membership.ingest_address = address
    node = MeshNode(membership, router, ring_store=ring)
    node.start()

    # Heartbeat thread: a cold tick at fleet scale runs far longer than
    # the bench's short lease, and a member whose lease lapses mid-tick
    # would hand its partition to a peer — double judgment by design
    # error, not by bug. Its OWN store client: requests.Session is not
    # thread-safe and the tick thread owns `store`. Dies with the
    # process, which is exactly what makes the kill phase's lease
    # expiry honest.
    hb_store = HttpFleetStore(args.store_url, worker_id)
    hb_membership = Membership(
        hb_store, worker_id, lease_seconds=args.lease_seconds,
        ingest_address=address,
    )
    hb_membership.join()
    hb_stop = threading.Event()

    def heartbeat():
        while not hb_stop.wait(args.lease_seconds / 3.0):
            hb_membership.renew(force=True)

    threading.Thread(target=heartbeat, daemon=True).start()

    cfg = BrainConfig(
        algorithm="moving_average_all",
        season_steps=24,
        max_stuck_seconds=args.max_stuck,
        max_cache_size=args.services * args.aliases + 64,
    )
    from prometheus_client import CollectorRegistry

    from foremast_tpu.observe.spans import Tracer

    tracer = Tracer(
        service=worker_id, registry=CollectorRegistry(), trace_dir=None
    )
    worker = BrainWorker(
        store, source, config=cfg, claim_limit=args.services,
        worker_id=worker_id, mesh=node, tracer=tracer,
    )

    def tick(tag: str) -> tuple[int, float]:
        store.tag = tag
        t0 = time.perf_counter()
        c0 = time.process_time()
        n = worker.tick()
        dt = time.perf_counter() - t0
        dm = worker._device_mesh_state()
        store.report_tick(
            worker=worker_id, tag=tag, docs=n, seconds=round(dt, 4),
            cpu_seconds=round(time.process_time() - c0, 4),
            members=len(router.members()),
            stages={
                k: round(v, 4)
                for k, v in tracer.last_stage_seconds.items()
            },
            # cumulative device-mesh counters (pad fraction, H2D place,
            # host gather) — the parent's roofline account reads the
            # final warm tick's values
            device_mesh=dm,
        )
        return n, dt

    cold_done = False
    prewarm_done = False
    warm_ticks = 0
    rebal_tick = 0
    arrived: set[str] = set()

    def arrive(name: str):
        if name not in arrived:
            arrived.add(name)
            store.barrier(name)

    store.barrier("ready")
    while True:
        phase = store.phase()
        if phase == "stop":
            break
        if phase == "cold" and not cold_done:
            n, _ = tick("cold")
            if n > 0:
                cold_done = True
                arrive("cold")
            continue
        if phase == "prewarm" and not prewarm_done:
            # one unmeasured warm round: first-warm costs (columnar
            # program compiles, admission-cache build) stay out of the
            # steady-state window, same discipline as every other bench
            n, _ = tick("prewarm")
            if n > 0:
                prewarm_done = True
                arrive("prewarm")
            continue
        if phase == "warm" and warm_ticks < args.warm_ticks:
            n, _ = tick(f"warm-{warm_ticks}")
            if n > 0:
                warm_ticks += 1
                if warm_ticks == args.warm_ticks:
                    arrive("warm")
            continue
        if phase == "kill":
            if args.victim:
                source.armed = True  # next tick dies after its claim
                tick("suicide")
                # unreachable past the claim (os._exit in fetch #3)
            else:
                # production-paced survivor loop: the ≤2-tick rebalance
                # bar is meaningless if an idle spin racks up hundreds
                # of empty "ticks" while the stuck window elapses
                _, dt = tick(f"rebal-{rebal_tick}")
                rebal_tick += 1
                time.sleep(max(0.0, 1.0 - dt))
            continue
        # holding between phases: keep the lease fresh AND the router
        # current (the ready-phase routed-push cycle needs every worker
        # to know the full membership before any tick runs)
        node.on_tick()
        time.sleep(0.05)
    hb_stop.set()
    node.close()
    worker.close()
    return 0


# ---------------------------------------------------------------------------
# the parent orchestration
# ---------------------------------------------------------------------------


def run_arena_check_child(args) -> int:
    """`--arena-child`: the ISSUE 19 sharded-arena capacity claims,
    demonstrated on real arenas under the sharded variant's exact
    device topology (forced virtual devices on CPU hosts, real chips
    on TPU). Three in-run asserts, one JSON verdict line for the
    parent:

      1. OOM-replicated-fits-sharded — a fleet whose row count blows
         the per-device budget hard-cap REFUSES on a replicated arena
         (assign -> None) and FITS a sharded arena under the identical
         per-device budget;
      2. linear capacity — aggregate sharded rows == devices x the
         replicated capacity the same budget buys;
      3. no cross-device gather leg — the compiled warm-tick program
         (`score_from_arena_sharded`, the real judgment jit) contains
         ZERO collectives: the roofline's gather leg is device-local.
    """
    n = args.device_mesh
    plat = os.environ.get("JAX_PLATFORMS", "")  # foremast: ignore[env-contract]
    flags = os.environ.get("XLA_FLAGS", "")  # foremast: ignore[env-contract]
    if (
        (not plat or plat.startswith("cpu"))
        and "xla_force_host_platform_device_count" not in flags
    ):
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={n}"
        ).strip()

    import re

    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from foremast_tpu.engine import arena as ar
    from foremast_tpu.engine import scoring
    from foremast_tpu.ops.windows import MetricWindows
    from foremast_tpu.parallel import mesh as meshlib

    mesh = meshlib.make_mesh(n_data=n)
    data_spec = NamedSharding(mesh, P(meshlib.DATA_AXIS))
    season = 16
    row_bytes = 20 + 4 * season
    per_device_rows = 64
    budget = per_device_rows * row_bytes
    fleet = n * per_device_rows
    keys = [f"svc{i}" for i in range(fleet)]
    ar.set_arena_budget(budget, budget)
    try:
        # replicated layout: every device must host the WHOLE fleet, so
        # the per-device budget hard-caps and admission refuses
        rep = ar.StateArena(
            season, sharding=NamedSharding(mesh, P()), shards=1
        )
        oom_replicated = rep.assign(keys, []) is None
        # the same budget DOES buy per_device_rows replicated rows...
        assert rep.assign(keys[:per_device_rows], []) is not None
        rep_cap = rep.cap

        # ...and the sharded layout turns that per-device budget into
        # devices x the rows: the whole fleet fits
        sha = ar.StateArena(season, sharding=data_spec, shards=n)
        res = sha.assign(keys, [])
        fits_sharded = res is not None
        assert oom_replicated, (
            "replicated arena admitted a fleet past its hard cap — "
            "the capacity comparison is broken"
        )
        assert fits_sharded, (
            "sharded arena refused a fleet that fits its aggregate "
            "capacity"
        )
        assert sha.cap == n * rep_cap, (sha.cap, n, rep_cap)

        rows_g, scat = res
        sha.scatter(
            rows_g,
            scat,
            [
                (1.0, 0.0, np.zeros(season, np.float32), 3, 1.0, 100)
                for _ in scat
            ],
        )

        # compile the REAL warm-tick judgment at the fleet shape and
        # prove the gather leg is device-local: zero collectives
        tc = 16
        local = jax.device_put(
            (np.asarray(rows_g) % sha.cap_s).astype(np.int32), data_spec
        )
        batch = scoring.ScoreBatch(
            historical=MetricWindows(
                values=jax.device_put(
                    np.zeros((fleet, 0), np.float32), data_spec
                ),
                mask=jax.device_put(np.zeros((fleet, 0), bool), data_spec),
                times=None,
            ),
            current=MetricWindows(
                values=jax.device_put(
                    np.ones((fleet, tc), np.float32), data_spec
                ),
                mask=jax.device_put(np.ones((fleet, tc), bool), data_spec),
                times=None,
            ),
            baseline=MetricWindows(
                values=jax.device_put(
                    np.zeros((fleet, tc), np.float32), data_spec
                ),
                mask=jax.device_put(np.zeros((fleet, tc), bool), data_spec),
                times=None,
            ),
            threshold=jax.device_put(
                np.full(fleet, 3.0, np.float32), data_spec
            ),
            bound=jax.device_put(np.zeros(fleet, np.int32), data_spec),
            min_lower_bound=jax.device_put(
                np.zeros(fleet, np.float32), data_spec
            ),
            min_points=jax.device_put(
                np.full(fleet, 10, np.int32), data_spec
            ),
        )
        hlo = (
            scoring.score_from_arena_sharded.lower(
                batch, *sha.state, local, mesh=mesh
            )
            .compile()
            .as_text()
        )
        collectives = sorted(
            set(
                re.findall(
                    r"all-gather|all-reduce-start|all-to-all"
                    r"|collective-permute",
                    hlo,
                )
            )
        )
        assert not collectives, (
            "warm sharded program grew a cross-device leg: "
            f"{collectives}"
        )
        print(
            json.dumps(
                {
                    "devices": n,
                    "per_device_row_budget": per_device_rows,
                    "fleet_rows": fleet,
                    "oom_replicated": oom_replicated,
                    "fits_sharded": fits_sharded,
                    "replicated_capacity_rows": rep_cap,
                    "sharded_capacity_rows": sha.cap,
                    "linear_scaling": sha.cap == n * rep_cap,
                    "warm_gather_collectives": collectives,
                }
            ),
            flush=True,
        )
    finally:
        ar.set_arena_budget(None, None)
    return 0


def run_arena_check(device_mesh: int, env: dict) -> dict:
    """Spawn the `--arena-child` capacity check and return its verdict
    (the child owns the forced-device topology; keeping it out of the
    parent keeps virtual devices away from the parent's jax)."""
    out = subprocess.run(
        [
            sys.executable, "-m", "benchmarks.scaleout_bench",
            "--arena-child", "--device-mesh", str(device_mesh),
        ],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=600,
    )
    assert out.returncode == 0, (
        f"arena capacity check failed:\n{out.stdout}\n{out.stderr}"
    )
    verdict = json.loads(out.stdout.strip().splitlines()[-1])
    assert verdict["oom_replicated"] and verdict["fits_sharded"], verdict
    assert verdict["linear_scaling"], verdict
    assert verdict["warm_gather_collectives"] == [], verdict
    return verdict


def _worker_log(i: int) -> str:
    try:
        with open(
            os.path.join(tempfile.gettempdir(), f"scaleout_w{i}.log")
        ) as fh:
            return fh.read()
    except OSError:
        return ""


def _wait(predicate, timeout: float, what: str):
    deadline = time.monotonic() + timeout
    while not predicate():
        if time.monotonic() > deadline:
            raise TimeoutError(f"timed out waiting for {what}")
        time.sleep(0.05)


def _routed_push_phase(server: StoreServer, services: int) -> dict:
    """Blind-scatter a sample of series at one receiver, learn the
    redirect hints, and show convergence on the second cycle."""
    from foremast_tpu.mesh import RoutingPusher, live_members

    members = live_members(server.store)
    addresses = [m.ingest_address for m in members if m.ingest_address]
    now = int(time.time())
    sample = min(512, services)
    series = []
    for sid in range(sample):
        key = ALIAS_EXPR.format(a=0, sid=sid)
        ts = np.arange(now - 300, now, 60, np.int64)
        series.append((key, ts.tolist(), synth_values(key, ts).tolist(), None))
    pusher = RoutingPusher(addresses)
    first = pusher.push_cycle(series)
    second = pusher.push_cycle(series)
    return {
        "series": sample,
        "receivers": len(addresses),
        "first_cycle_redirects": first["redirects"],
        "second_cycle_redirects": second["redirects"],
        "converged": second["redirects"] == 0,
    }


def run(
    services: int,
    aliases: int,
    hist_len: int,
    cur_len: int,
    warm_ticks: int,
    workers: int,
    kill: bool,
    cpus_per_worker: int = 0,
    lease_seconds: float = 2.0,
    max_stuck: float = 3.0,
    replicas: int = 128,
    timeout: float = 1800.0,
    device_mesh: int = 0,
) -> dict:
    kill = kill and workers > 1
    server = StoreServer(replicas=replicas)
    url = server.start()
    now = int(time.time())
    build_fleet(server.store, services, aliases, hist_len, cur_len, now)

    env = dict(os.environ)
    # children default to CPU, but an explicit parent platform (the TPU
    # tunnel run ROADMAP item 2 asks for: JAX_PLATFORMS=axon) passes
    # through — otherwise the sharded variant would silently benchmark
    # virtual CPU devices and record them as chip numbers
    env["JAX_PLATFORMS"] = (
        os.environ.get("JAX_PLATFORMS") or "cpu"  # foremast: ignore[env-contract]
    )
    env.pop("FOREMAST_INGEST", None)
    procs = []
    for i in range(workers):
        cmd = [
            sys.executable, "-m", "benchmarks.scaleout_bench", "--child",
            "--store-url", url, "--index", str(i),
            "--services", str(services), "--aliases", str(aliases),
            "--warm-ticks", str(warm_ticks),
            "--lease-seconds", str(lease_seconds),
            "--max-stuck", str(max_stuck),
            "--replicas", str(replicas),
            "--device-mesh", str(device_mesh),
        ]
        if cpus_per_worker:
            cmd += [
                "--cpus",
                f"{i * cpus_per_worker}-{(i + 1) * cpus_per_worker - 1}",
            ]
        if kill and i == workers - 1:
            cmd.append("--victim")
        # stdout/stderr stream to a per-worker file, NOT a pipe: nobody
        # drains a pipe until the end, so a chatty child (JAX_LOG_COMPILES
        # debugging, warning storms) would block on a full pipe buffer
        # mid-phase and read as a mysterious slowdown
        log_path = os.path.join(
            tempfile.gettempdir(), f"scaleout_w{i}.log"
        )
        log_fh = open(log_path, "w")
        procs.append(
            subprocess.Popen(
                cmd, cwd=REPO, env=env,
                stdout=log_fh, stderr=subprocess.STDOUT, text=True,
            )
        )
        log_fh.close()
    victim_id = f"w{workers - 1}" if kill else None
    try:
        _wait(
            lambda: server.barrier_count("ready") == workers,
            timeout, "workers to join",
        )
        # let every worker's router pick up the FULL membership (the
        # hold loop refreshes at sub-second cadence) before pushing
        time.sleep(1.5)
        routed = _routed_push_phase(server, services)

        server.phase = "cold"
        t0 = time.perf_counter()
        _wait(
            lambda: server.barrier_count("cold") == workers,
            timeout, "cold ticks",
        )
        cold_wall = time.perf_counter() - t0

        # orphan set BEFORE the kill, under the full ring
        owners = server.owner_map() if kill else {}

        server.phase = "prewarm"
        _wait(
            lambda: server.barrier_count("prewarm") == workers,
            timeout, "prewarm ticks",
        )

        server.phase = "warm"
        t0 = time.perf_counter()
        _wait(
            lambda: server.barrier_count("warm") == workers,
            timeout, "warm ticks",
        )
        warm_wall = time.perf_counter() - t0

        # exactly-once: every doc judged warm_ticks times, by ONE worker
        ledger = server.ledger_snapshot()
        double_judged = []
        for sid in range(services):
            entries = [
                e for e in ledger.get(f"job-{sid}", ())
                if e[1].startswith("warm")
            ]
            who = {e[0] for e in entries}
            if len(entries) != warm_ticks or len(who) != 1:
                double_judged.append((f"job-{sid}", entries))
        assert not double_judged, (
            f"{len(double_judged)} docs judged off-partition or re-judged: "
            f"{double_judged[:3]}"
        )

        rebalance = None
        if kill:
            orphans = {d for d, o in owners.items() if o == victim_id}
            assert orphans, "victim owned no documents?"
            server.phase = "kill"
            _wait(
                lambda: procs[-1].poll() is not None,
                timeout, "victim to die",
            )
            assert procs[-1].returncode == KILL_EXIT

            def orphans_rejudged():
                led = server.ledger_snapshot()
                return all(
                    any(
                        e[1].startswith("rebal") and e[0] != victim_id
                        for e in led.get(d, ())
                    )
                    for d in orphans
                )

            t0 = time.perf_counter()
            _wait(orphans_rejudged, timeout, "orphan takeover")
            heal_wall = time.perf_counter() - t0

            # ≤ 2 ticks: for each survivor, the tick index where its
            # membership view first dropped vs the tick that judged its
            # newly-owned orphans
            led = server.ledger_snapshot()
            reports = server.tick_reports()
            heal_tick = {}
            for r in reports:
                tag = r["tag"]
                if tag.startswith("rebal") and r["members"] < workers:
                    k = int(tag.split("-")[1])
                    w = r["worker"]
                    heal_tick[w] = min(heal_tick.get(w, k), k)
            worst = 0
            for d in orphans:
                for w, tag, _status, _wall in led.get(d, ()):
                    if tag.startswith("rebal") and w != victim_id:
                        k = int(tag.split("-")[1])
                        # claim authority is the SERVER's membership
                        # view, which can heal a refresh-interval ahead
                        # of the survivor's local router — an orphan
                        # judged before the local view caught up is lag
                        # 0, not negative
                        lag = max(0, k - heal_tick.get(w, k))
                        worst = max(worst, lag)
                        break
            assert worst <= 1, (
                f"rebalance took {worst + 1} ticks (> 2) after the ring "
                "healed"
            )
            rebalance = {
                "orphan_docs": len(orphans),
                "heal_wall_seconds": round(heal_wall, 3),
                "worst_ticks_after_heal": worst + 1,
                "lease_seconds": lease_seconds,
                "max_stuck_seconds": max_stuck,
            }

        server.phase = "stop"
        for i, p in enumerate(procs):
            try:
                p.wait(timeout=60)
            except subprocess.TimeoutExpired:
                p.kill()
                p.wait()
    except BaseException:
        for i, p in enumerate(procs):
            if p.poll() is None:
                p.kill()
        for i in range(workers):
            out = _worker_log(i)
            if out:
                sys.stderr.write(f"--- worker {i} output ---\n{out}\n")
        raise
    finally:
        server.stop()

    for i, p in enumerate(procs):
        if not (kill and i == workers - 1):
            assert p.returncode == 0, (
                f"worker {i} failed:\n{_worker_log(i)}"
            )

    windows = services * aliases
    # per-worker tick timings (diagnostics: where does a phase's wall
    # clock go — judge, store, or barrier skew)
    worker_ticks: dict = {}
    for r in server.tick_reports():
        worker_ticks.setdefault(r["worker"], {})[r["tag"]] = {
            "seconds": r["seconds"],
            **({"stages": r["stages"]} if r.get("stages") else {}),
        }
    # Roofline account for the sharded-judge variant (ISSUE 13): where
    # does a warm sharded tick's wall clock go — H2D placement, device
    # dispatch, host gather (which absorbs the deferred execution), or
    # host decode. Cumulative counters come from the FINAL warm tick's
    # device_mesh report; per-stage seconds sum over the warm ticks.
    roofline = None
    if device_mesh > 1:
        # per-worker cumulative counters: warm-phase deltas = last warm
        # report minus last prewarm report (cold/prewarm H2D must not
        # pollute the steady-state account)
        base: dict[str, dict] = {}
        final: dict[str, dict] = {}
        for r in server.tick_reports():
            if not r.get("device_mesh"):
                continue
            if r["tag"].startswith("warm"):
                final[r["worker"]] = r["device_mesh"]
            elif r["tag"] in ("cold", "prewarm"):
                # only PRE-warm snapshots form the baseline: kill runs
                # emit rebal-* reports AFTER the warm phase, and using
                # those as base would make every delta negative (and
                # the <2% pad assert vacuous)
                base[r["worker"]] = r["device_mesh"]
        assert final, "sharded variant produced no device_mesh reports"

        def delta(key):
            return sum(
                d[key] - base.get(w, {}).get(key, 0)
                for w, d in final.items()
            )

        stages: dict[str, float] = {}
        for r in server.tick_reports():
            if r["tag"].startswith("warm"):
                for k, v in (r.get("stages") or {}).items():
                    stages[k] = stages.get(k, 0.0) + v
        h2d_s = delta("place_seconds")
        h2d_b = delta("place_bytes")
        gat_s = delta("fetch_seconds")
        gat_b = delta("fetch_bytes")
        pad = delta("pad_rows_total")
        rows = delta("batch_rows_total")
        dms = list(final.values())
        roofline = {
            "devices_per_worker": dms[-1]["devices"],
            "h2d_seconds": round(h2d_s, 4),
            "h2d_mb_per_s": (
                round(h2d_b / h2d_s / 1e6, 1) if h2d_s else None
            ),
            "gather_seconds": round(gat_s, 4),
            "gather_mb_per_s": (
                round(gat_b / gat_s / 1e6, 1) if gat_s else None
            ),
            "dispatch_seconds": round(stages.get("score", 0.0), 4),
            "decode_seconds": round(stages.get("decode", 0.0), 4),
            "arena_assemble_seconds": round(
                stages.get("arena_assemble", 0.0), 4
            ),
            "padded_row_fraction": (
                round(pad / rows, 5) if rows else None
            ),
            "arena_layout": dms[-1].get("arena_layout"),
            "arena_capacity_rows": dms[-1].get("arena_capacity_rows"),
            "arena_replica_bytes": dms[-1]["arena_replica_bytes"],
            "arena_total_device_bytes": dms[-1][
                "arena_total_device_bytes"
            ],
        }
        if services >= 16384:
            # acceptance bar: padding must stay noise at fleet shapes
            assert roofline["padded_row_fraction"] < 0.02, roofline
    return {
        "workers": workers,
        "cpus_per_worker": cpus_per_worker or None,
        "device_mesh": device_mesh or None,
        "roofline": roofline,
        "worker_ticks": worker_ticks,
        "services": services,
        "aliases": aliases,
        "windows": windows,
        "warm_ticks": warm_ticks,
        "cold_wall_seconds": round(cold_wall, 3),
        "warm_wall_seconds": round(warm_wall, 3),
        "fleet_warm_windows_per_sec": round(
            windows * warm_ticks / warm_wall, 1
        ),
        "no_double_judgment": True,  # asserted above
        "routed_push": routed,
        "rebalance": rebalance,
    }


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--services", type=int, default=65536)
    ap.add_argument(
        "--aliases", type=int, default=4,
        help="metric aliases per document (4 = the reference's "
        "canonical monitor shape)",
    )
    ap.add_argument("--hist-len", type=int, default=256)
    ap.add_argument("--cur-len", type=int, default=30)
    ap.add_argument("--warm-ticks", type=int, default=3)
    ap.add_argument(
        "--workers", default="1,4",
        help="comma-separated worker counts to compare",
    )
    ap.add_argument(
        "--no-kill", action="store_true",
        help="skip the kill/rebalance phase",
    )
    ap.add_argument(
        "--small", action="store_true", help="CPU smoke shapes (CI)"
    )
    ap.add_argument(
        "--device-mesh", dest="device_mesh", type=int, default=0,
        help="shard every worker's judge over an N-device local mesh "
        "(ISSUE 13 sharded-judge variant; forces N virtual host "
        "devices on CPU platforms, spans real chips on TPU hosts). "
        "0 = single-device judges (the comparison baseline)",
    )
    ap.add_argument(
        "--cpus-per-worker", type=int, default=-1,
        help="cores pinned to EVERY worker in EVERY run (default: "
        "nproc // max worker count — constant per-worker hardware, so "
        "1 -> N measures scale-out, not scheduler contention; 0 "
        "disables pinning)",
    )
    # child-mode flags (internal)
    ap.add_argument("--child", action="store_true", help=argparse.SUPPRESS)
    ap.add_argument(
        "--arena-child", dest="arena_child", action="store_true",
        help=argparse.SUPPRESS,
    )
    ap.add_argument("--store-url", help=argparse.SUPPRESS)
    ap.add_argument("--index", type=int, default=0, help=argparse.SUPPRESS)
    ap.add_argument("--victim", action="store_true", help=argparse.SUPPRESS)
    ap.add_argument("--cpus", default="", help=argparse.SUPPRESS)
    ap.add_argument(
        "--lease-seconds", dest="lease_seconds", type=float, default=2.0,
        help=argparse.SUPPRESS,
    )
    ap.add_argument(
        "--max-stuck", dest="max_stuck", type=float, default=3.0,
        help=argparse.SUPPRESS,
    )
    ap.add_argument(
        "--replicas", type=int, default=128, help=argparse.SUPPRESS
    )
    ap.add_argument(
        "--ring-budget", type=int, default=256 * 1024 * 1024,
        help=argparse.SUPPRESS,
    )
    ap.add_argument(
        "--ring-points", type=int, default=64, help=argparse.SUPPRESS
    )
    args = ap.parse_args(argv)
    if args.arena_child:
        return run_arena_check_child(args)
    if args.child:
        return run_child(args)
    if args.small:
        args.services = min(args.services, 48)
        args.hist_len = min(args.hist_len, 128)
        args.warm_ticks = min(args.warm_ticks, 2)
        if args.workers == "1,4":
            args.workers = "1,2"
    worker_counts = sorted(
        {max(1, int(w)) for w in args.workers.split(",")}
    )
    cpus_per_worker = args.cpus_per_worker
    if cpus_per_worker < 0:
        cpus_per_worker = max(
            1, (os.cpu_count() or 8) // max(worker_counts)
        )
    arena_capacity = None
    if args.device_mesh > 1:
        # ISSUE 19 capacity claims, asserted in-run before the fleet
        # spins up: OOM-replicated-fits-sharded, linear aggregate
        # capacity, zero collectives in the compiled warm gather
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = (
            os.environ.get("JAX_PLATFORMS") or "cpu"  # foremast: ignore[env-contract]
        )
        arena_capacity = run_arena_check(args.device_mesh, env)
        print(json.dumps({"arena_capacity": arena_capacity}), flush=True)
    rows = []
    for i, w in enumerate(worker_counts):
        kill = (not args.no_kill) and i == len(worker_counts) - 1
        row = run(
            args.services, args.aliases, args.hist_len, args.cur_len,
            args.warm_ticks, w, kill, cpus_per_worker=cpus_per_worker,
            device_mesh=args.device_mesh,
        )
        rows.append(row)
        print(json.dumps(row), flush=True)
    base = rows[0]["fleet_warm_windows_per_sec"]
    peak = rows[-1]["fleet_warm_windows_per_sec"]
    summary = {
        "config": (
            "s-mesh-scaleout-sharded"
            if args.device_mesh > 1
            else "s-mesh-scaleout"
        ),
        "services": args.services,
        "windows": args.services * args.aliases,
        "device_mesh": args.device_mesh or None,
        "arena_capacity": arena_capacity,
        "roofline": rows[-1]["roofline"],
        "worker_counts": worker_counts,
        "fleet_warm_windows_per_sec": {
            str(r["workers"]): r["fleet_warm_windows_per_sec"] for r in rows
        },
        "no_double_judgment": all(r["no_double_judgment"] for r in rows),
        "routed_push_converged": all(
            r["routed_push"]["converged"] for r in rows
        ),
        "rebalance": rows[-1]["rebalance"],
        "metric": "fleet_throughput_speedup",
        "value": round(peak / base, 2) if base else None,
        "unit": f"x ({worker_counts[0]} -> {worker_counts[-1]} workers)",
    }
    # the ≥3x acceptance bar applies at benchmark shapes, not CI smoke
    if args.services >= 16384 and worker_counts[-1] >= 4:
        assert summary["value"] and summary["value"] >= 3.0, summary
    print(json.dumps(summary), flush=True)
    from benchmarks.report import write_summary

    write_summary(
        "scaleout_sharded" if args.device_mesh > 1 else "scaleout",
        summary,
        small=args.small,
    )
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
