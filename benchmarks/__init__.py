"""Benchmark harnesses (suite = throughput configs, quality = detector F1)."""


def prf1(tp: int, fp: int, fn: int) -> tuple[float, float, float]:
    """(precision, recall, f1); empty flag sets report 0, not undefined."""
    precision = tp / max(tp + fp, 1)
    recall = tp / max(tp + fn, 1)
    f1 = 2 * precision * recall / max(precision + recall, 1e-9)
    return precision, recall, f1
