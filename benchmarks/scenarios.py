"""Scenario-matrix workload generation (ISSUE 14): canary-shaped fleets.

The quality benchmark's `gen()` families probe detector behavior per
SIGNAL SHAPE; this module widens the workload generator into the matrix
the fleet bench sweeps — deployment STRATEGY x traffic REGIME — so the
headline canary claim is measured on canary-shaped fleets, not just the
baseline-less ones rounds 5-15 benchmarked:

  strategy — `canary` (a baseline window rides every judgment: the
             reference's baseline-pods-vs-canary-pods headline query,
             metricsquery.go:111-116), `rolling` (rollingUpdate — no
             baseline, bounded endTime), `continuous` (no baseline,
             open-ended re-check);
  regime   — `diurnal` (daily cycle), `spiky` (benign traffic bursts in
             the history — part of the distribution, not anomalies),
             `stair` (stair-step ramps: capacity changes / migrations),
             `outage` (outage-shaped GAPS in the history — the chaos
             plane's blackhole fault vocabulary re-used as a traffic
             shape: scrapes that never happened are masked-out samples,
             exactly what a PromQL range returns after an outage).

Each scenario draws B (history, current[, baseline]) window sets with
known injected anomaly points; `scenario_matrix()` scores them through
the SAME engine entry point the worker dispatches (`scoring.score`) and
returns point-level F1 per cell plus the canary cells' pairwise
false-reject rate (clean same-distribution baselines must not lower the
threshold). `FAN_IN_SHAPES` names the pusher fan-in dimension the
ingest-fed fleet variant in `benchmarks.mixed_bench` sweeps.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from benchmarks import prf1
from foremast_tpu.engine import scoring
from foremast_tpu.ops.windows import MetricWindows

STRATEGIES = ("canary", "rolling", "continuous")
REGIMES = ("diurnal", "spiky", "stair", "outage")
# pusher fan-in shapes for the ingest-fed fleet variant: how many
# concurrent pushers split the fleet's series (1 = one batching agent,
# 8 = per-node agents converging on one receiver)
FAN_IN_SHAPES = (1, 8)
# label SHAPES (ISSUE 15 satellite / ROADMAP item 4's remaining
# generator gap): how a fleet's series are labeled. `single` is the
# rounds-5..16 shape (one flat namespace); `multi_cluster` spreads the
# same apps over federated clusters (a `cluster` label on every
# series); `multi_tenant` adds a `tenant` label on top — the
# multi-team SaaS shape where one app name exists per tenant. Routing
# and ownership must be label-shape-INVARIANT: the mesh routes by the
# `app` label value alone, so a service's doc, fits, arena rows and
# pushed series co-locate on one worker no matter how many extra
# labels the selector carries (`label_shape_routing_cell` proves it).
LABEL_SHAPES = ("single", "multi_cluster", "multi_tenant")
# tenant-share REGIMES (ISSUE 20): how a multi-tenant fleet's series
# divide over tenants. `uniform` is the PR-15 shape (round-robin,
# every tenant equal); `noisy_neighbor` gives ONE tenant (`t0`, the
# whale) NOISY_FACTOR x every other tenant's share — the QoS plane's
# adversarial workload: without envelopes + weighted-fair draining the
# whale's backlog starves the quiet tenants' micro-ticks and its pushes
# evict their ring series
TENANT_REGIMES = ("uniform", "noisy_neighbor")
NOISY_FACTOR = 10
WHALE_TENANT = "t0"

PERIOD = 24
NOISE = 0.05
SPIKE_SIGMA = 8.0
STAIR_STEP = 0.4  # level jump per stair (a capacity migration)
SPIKY_BURST = 0.35  # benign burst height: tall, but part of the regime


def _regime_signal(regime: str, t: np.ndarray, th: int, rng) -> np.ndarray:
    """Deterministic base signal of one regime at time steps `t` [B, n]
    (broadcast over rows)."""
    if regime == "diurnal":
        return 1.0 + 0.5 * np.sin(2 * np.pi * t / PERIOD)
    if regime == "spiky":
        return np.ones_like(t, dtype=float)
    if regime == "stair":
        # stair-step ramps WITHIN the history (capacity changes /
        # traffic migrations at th/4, th/2, 3th/4), with the current
        # window continuing the last learned level — global-mean bands
        # mis-center across the steps; the auto screen's changepoint
        # trend localizes them. (A step AT the history/current boundary
        # is a genuine level-shift anomaly, not a regime — that case
        # belongs to the anomaly injection, not the signal.)
        return 1.0 + STAIR_STEP * np.minimum(
            np.floor(t / max(th // 4, 1)), 3.0
        )
    if regime == "outage":
        return np.ones_like(t, dtype=float)
    raise ValueError(regime)


def gen_scenario(
    strategy: str,
    regime: str,
    b: int,
    th: int,
    tc: int,
    seed: int = 0,
):
    """One scenario cell: (hist [B,Th], hist_mask, cur [B,Tc], truth
    [B,Tc] bool, base [B,Tc] | None).

    Injected anomalies are SPIKE_SIGMA-sigma points in the current
    window (two per row). The canary strategy's baseline is a clean
    same-distribution draw at the current phase — healthy canary, so
    the rank tests must hold (differs=False) while the band detection
    still catches the spikes. The spiky regime's history bursts and the
    outage regime's masked gaps are NOT anomalies: they are the regime.
    """
    rng = np.random.default_rng(
        seed + 1000 * STRATEGIES.index(strategy) + REGIMES.index(regime)
    )
    t_hist = np.arange(th)[None, :]
    t_cur = (th + np.arange(tc))[None, :]
    hist = _regime_signal(regime, t_hist, th, rng) + rng.normal(
        0, NOISE, (b, th)
    )
    cur = _regime_signal(regime, t_cur, th, rng) + rng.normal(
        0, NOISE, (b, tc)
    )
    hist_mask = np.ones((b, th), bool)
    if regime == "spiky":
        # benign bursts in the HISTORY (cron jobs, deploy traffic):
        # ~2% of samples sit SPIKY_BURST high — the fitted band must
        # absorb them (they widen sigma), not learn them as clean
        for i in range(b):
            k = max(th // 50, 2)
            idx = rng.choice(th, size=k, replace=False)
            hist[i, idx] += SPIKY_BURST
    elif regime == "outage":
        # outage-shaped gaps: two blackhole windows of ~5% of the
        # history each — masked samples, exactly a scrape outage's
        # PromQL shape (the chaos plane's fault vocabulary as data)
        gap = max(th // 20, 2)
        for i in range(b):
            for _ in range(2):
                g0 = int(rng.integers(0, th - gap))
                hist_mask[i, g0 : g0 + gap] = False
    truth = np.zeros((b, tc), bool)
    for i in range(b):
        idx = rng.choice(tc, size=2, replace=False)
        cur[i, idx] += SPIKE_SIGMA * NOISE
        truth[i, idx] = True
    base = None
    if strategy == "canary":
        # baseline pods: same signal family at the same phase, its own
        # noise draw — same distribution as a healthy canary's current
        base = _regime_signal(regime, t_cur, th, rng) + rng.normal(
            0, NOISE, (b, tc)
        )
        base = base.astype(np.float32)
    return (
        hist.astype(np.float32),
        hist_mask,
        cur.astype(np.float32),
        truth,
        base,
    )


def _batch(hist, hist_mask, cur, base):
    b, tc = cur.shape

    def win(v, m=None):
        return MetricWindows(
            values=jnp.asarray(v),
            mask=jnp.asarray(m) if m is not None else jnp.ones(v.shape, bool),
            times=jnp.zeros(v.shape, jnp.int32),
        )

    if base is None:
        baseline = MetricWindows(
            values=jnp.zeros_like(jnp.asarray(cur)),
            mask=jnp.zeros(cur.shape, bool),
            times=jnp.zeros(cur.shape, jnp.int32),
        )
    else:
        baseline = win(base)
    return scoring.ScoreBatch(
        historical=win(hist, hist_mask),
        current=win(cur),
        baseline=baseline,
        threshold=jnp.full((b,), 4.0, jnp.float32),
        bound=jnp.full((b,), 1, jnp.int32),
        min_lower_bound=jnp.zeros((b,), jnp.float32),
        min_points=jnp.full((b,), 10, jnp.int32),
    )


def score_scenario(
    strategy: str,
    regime: str,
    b: int,
    th: int,
    tc: int,
    seed: int = 0,
    algorithm: str = "auto_univariate",
):
    """(f1, precision, recall, differs_rate) for one matrix cell.

    differs_rate is the fraction of rows whose pairwise tests rejected
    same-distribution — on the clean baselines every cell draws it is
    the rank tests' false-reject rate (canary cells only; 0.0 where no
    baseline exists, the gates' hardwired outcome)."""
    hist, hist_mask, cur, truth, base = gen_scenario(
        strategy, regime, b, th, tc, seed
    )
    res = scoring.score(
        _batch(hist, hist_mask, cur, base),
        algorithm=algorithm,
        season_length=PERIOD,
    )
    flags = np.asarray(res.anomalies)
    tp = int((flags & truth).sum())
    fp = int((flags & ~truth).sum())
    fn = int((~flags & truth).sum())
    precision, recall, f1 = prf1(tp, fp, fn)
    differs_rate = float(np.asarray(res.dist_differs).mean())
    return f1, precision, recall, differs_rate


def scenario_labels(
    shape: str,
    s: int,
    clusters: int = 4,
    tenants: int = 8,
) -> dict[str, str]:
    """The label set of service index `s` under a label shape."""
    labels = {"namespace": "bench", "app": f"app{s}"}
    if shape == "single":
        return labels
    labels["cluster"] = f"c{s % clusters}"
    if shape == "multi_tenant":
        labels["tenant"] = f"t{s % tenants}"
        return labels
    if shape != "multi_cluster":
        raise ValueError(shape)
    return labels


def scenario_selector(
    shape: str,
    s: int,
    metric: str = "latency",
    clusters: int = 4,
    tenants: int = 8,
) -> str:
    """A PromQL selector for service `s` under a label shape (label
    order deliberately NON-canonical — cluster/tenant first — so the
    cell also proves canonicalization, not just extraction)."""
    labels = scenario_labels(shape, s, clusters, tenants)
    body = ",".join(
        f'{k}="{v}"' for k, v in reversed(sorted(labels.items()))
    )
    return f"{metric}{{{body}}}"


def tenant_fleet(
    regime: str,
    services: int,
    tenants: int = 4,
    factor: int = NOISY_FACTOR,
) -> list[str]:
    """Tenant name per service index under a tenant-share regime.

    `uniform` round-robins the fleet over `tenants` equal tenants;
    `noisy_neighbor` interleaves a weighted pattern in which the whale
    (WHALE_TENANT) owns `factor` slots per cycle and every other tenant
    one — so the whale's share of services (and of every per-series
    resource: pushes, ring bytes, dirty marks, claims) is `factor` x
    each neighbor's. Deterministic: the same index always maps to the
    same tenant, so control and treatment runs judge identical fleets.
    """
    if regime == "uniform":
        return [f"t{s % tenants}" for s in range(services)]
    if regime != "noisy_neighbor":
        raise ValueError(regime)
    pattern = [WHALE_TENANT] * factor + [
        f"t{i}" for i in range(1, tenants)
    ]
    return [pattern[s % len(pattern)] for s in range(services)]


def tenant_weighted_specs(
    tenants: int = 4,
    weight: float = 1.0,
    ring_bytes: int = 0,
    arena_rows: int = 0,
    ingest_bytes_per_s: int = 0,
) -> dict[str, dict]:
    """A FOREMAST_TENANTS-shaped spec map for a `tenants`-tenant fleet:
    EQUAL weights (the fairness claim under test is that weighted-fair
    draining protects quiet tenants from a whale's backlog, not that
    operators hand-tune the whale down) with optional uniform budget
    envelopes. json.dumps of the result is a valid FOREMAST_TENANTS
    value; benches feed it to TenantRegistry directly."""
    spec: dict[str, dict] = {}
    for i in range(tenants):
        s: dict = {"weight": weight}
        if ring_bytes:
            s["ring_bytes"] = int(ring_bytes)
        if arena_rows:
            s["arena_rows"] = int(arena_rows)
        if ingest_bytes_per_s:
            s["ingest_bytes_per_s"] = int(ingest_bytes_per_s)
        spec[f"t{i}"] = s
    return spec


def label_shape_routing_cell(
    shape: str,
    services: int = 256,
    workers: int = 4,
    route_label: str = "app",
) -> dict:
    """The routing/ownership proof for one label shape: every
    service's DOC route key and SERIES route key resolve to the same
    ring owner (doc↔series co-location — the invariant the mesh claim
    filter, the dirty set's ownership probe, and the receiver's
    accept-and-hint all assume), regardless of extra cluster/tenant
    labels; and ownership stays spread (no shape may collapse the
    fleet onto one member). Raises AssertionError on violation;
    returns the cell row for the bench table."""
    from foremast_tpu.ingest.wire import canonical_series
    from foremast_tpu.jobs.models import Document
    from foremast_tpu.mesh.partition import HashRing
    from foremast_tpu.mesh.routing import doc_route_key, series_route_key

    ring = HashRing([f"w{i}" for i in range(workers)])
    owners: dict[str, int] = {}
    for s in range(services):
        selector = scenario_selector(shape, s)
        key = canonical_series(selector)
        doc = Document(id=f"job-{s}", app_name=f"app{s}")
        rk_doc = doc_route_key(doc)
        rk_series = series_route_key(key, route_label)
        assert rk_doc == rk_series == f"app{s}", (
            shape, selector, rk_doc, rk_series,
        )
        owner = ring.owner(rk_doc)
        assert owner == ring.owner(rk_series), (shape, s)
        owners[owner] = owners.get(owner, 0) + 1
    # spread sanity: with blake2b points, 256 keys over 4 workers
    # cannot legally land on one member; a collapse means the label
    # shape leaked into the route key
    assert len(owners) == workers, owners
    return {
        "config": "q-label-shape-routing",
        "label_shape": shape,
        "services": services,
        "workers": workers,
        "owners": {k: owners[k] for k in sorted(owners)},
        "co_located": True,
    }


def scenario_matrix(b: int, th: int, tc: int, seed: int = 0) -> list[dict]:
    """The full strategy x regime sweep, one row dict per cell —
    `make bench-mixed` prints these and BENCHMARKS.md pins them
    (extends the `fleet_mix` table with the strategy dimension)."""
    rows = []
    for strategy in STRATEGIES:
        for regime in REGIMES:
            f1, precision, recall, differs = score_scenario(
                strategy, regime, b, th, tc, seed
            )
            rows.append(
                {
                    "scenario": f"{strategy}/{regime}",
                    "strategy": strategy,
                    "regime": regime,
                    "f1": round(f1, 3),
                    "precision": round(precision, 3),
                    "recall": round(recall, 3),
                    "pairwise_differs_rate": round(differs, 4),
                }
            )
    return rows
